"""ICI-sharded ANN search for the IVF and CAGRA indexes.

SURVEY.md §7 step 7 / §2.5: the reference leaves multi-GPU ANN to
downstream consumers (``docs/source/using_raft_comms.rst:5-7``); this
framework ships it in-tree. Two shardings, mirroring how the data
structures scale:

* **IVF-Flat: inverted lists sharded** across the mesh axis. Coarse
  probing runs against the replicated centers (tiny), each shard streams
  only its slice of the padded lists through the dense masked scan
  (:func:`raft_tpu.neighbors.ivf_flat.flat_scan_core`) — list ids in the
  padded layout are global dataset row ids, so per-shard top-k merge with
  one ``all_gather`` + k-way merge (``knn_merge_parts`` pattern).
* **CAGRA / IVF-PQ: queries sharded, index replicated** — graph beam
  search is latency-bound per query and the graph is compact, so
  replicated-index data parallelism is the first-order scaling knob (the
  reference's multi-GPU story for CAGRA is likewise index-replica
  sharding at the serving layer).

Everything runs under ``shard_map`` over a :func:`make_mesh` mesh and
works identically on real ICI or the virtual CPU test mesh.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import functools

from raft_tpu.core.errors import expects
from raft_tpu.neighbors import cagra as cagra_mod, ivf_flat as ivf_flat_mod, ivf_pq as ivf_pq_mod
from raft_tpu.ops.distance import DistanceType
from raft_tpu.ops.select_k import merge_parts
from raft_tpu.random.rng import as_key


@functools.lru_cache(maxsize=64)
def _ivf_flat_fn(mesh, axis, k, n_probes, metric, g, l_local):
    """Cached jitted shard_map program (rebuilding it per call would
    re-trace and recompile every search)."""

    def local(centers, ld, li, ln, q):
        rank = lax.axis_index(axis)
        qf = q
        if metric == DistanceType.CosineExpanded:
            qf = qf / jnp.maximum(jnp.linalg.norm(qf, axis=1, keepdims=True), 1e-12)
        probed = ivf_flat_mod.probe_mask(centers, qf, n_probes, metric)
        probed_local = lax.dynamic_slice_in_dim(probed, rank * l_local, l_local, axis=1)
        v, i = ivf_flat_mod.flat_scan_core(
            ld, li, ln, qf, probed_local, None,
            k=k, metric=metric, has_filter=False, chunk_lists=g,
        )
        all_v = jax.lax.all_gather(v, axis)
        all_i = jax.lax.all_gather(i, axis)
        nq = q.shape[0]
        cat_v = jnp.moveaxis(all_v, 0, 1).reshape(nq, -1)
        cat_i = jnp.moveaxis(all_i, 0, 1).reshape(nq, -1)
        select_min = metric != DistanceType.InnerProduct
        # invalid (-1) slots carry +/-inf values and lose the merge
        return merge_parts(cat_v, cat_i, k, select_min=select_min)

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def sharded_ivf_flat_search(
    mesh: Mesh,
    index: "ivf_flat_mod.IvfFlatIndex",
    queries,
    k: int,
    params: Optional["ivf_flat_mod.IvfFlatSearchParams"] = None,
    axis: str = "data",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-Flat search with lists sharded over ``mesh`` axis ``axis``.

    Returns replicated ``(distances [nq, k], indices [nq, k])`` drawn from
    the same probed candidate set as single-device scan search.
    """
    if params is None:
        params = ivf_flat_mod.IvfFlatSearchParams(**kwargs)
    queries = jnp.asarray(queries, jnp.float32)
    n_shards = mesh.shape[axis]
    L = index.n_lists
    expects(L % n_shards == 0, "n_lists %d not divisible by %d shards", L, n_shards)
    l_local = L // n_shards
    n_probes = min(params.n_probes, L)
    metric = index.metric
    g = ivf_flat_mod.scan_chunk_lists(l_local, index.max_list)

    fn = _ivf_flat_fn(mesh, axis, k, n_probes, metric, g, l_local)
    ln = index.list_norms
    if ln is None:
        ln = jnp.zeros(index.list_indices.shape, jnp.float32)
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    return fn(
        put(index.centers, P()),
        put(index.list_data, P(axis)),
        put(index.list_indices, P(axis)),
        put(ln, P(axis)),
        put(queries, P()),
    )


@functools.lru_cache(maxsize=64)
def _cagra_fn(mesh, axis, k, itopk, width, iters, n_init, size, metric, seed, use_vpq, init_sample):
    key = as_key(seed)

    def local(sqnorms, graph, q, *data_args):
        rank = lax.axis_index(axis)
        kb = jax.random.fold_in(key, rank)
        if init_sample > 0:
            init_ids = cagra_mod.strided_seed_ids(size, init_sample)
        else:
            init_ids = jax.random.randint(kb, (q.shape[0], n_init), 0, size, jnp.int32)
        if use_vpq:
            dataset, vpq_arrays = None, tuple(data_args)
        else:
            (dataset,), vpq_arrays = data_args, None
        return cagra_mod._cagra_search_impl(
            dataset, sqnorms, graph, q, init_ids, None, vpq_arrays,
            k=k, itopk=itopk, width=width, iters=iters,
            metric=metric, has_filter=False, use_vpq=use_vpq,
        )

    n_data = 4 if use_vpq else 1
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(axis)) + (P(),) * n_data,
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


def sharded_cagra_search(
    mesh: Mesh,
    index: "cagra_mod.CagraIndex",
    queries,
    k: int,
    params: Optional["cagra_mod.CagraSearchParams"] = None,
    axis: str = "data",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """CAGRA beam search with queries sharded over the mesh (replicated
    graph + dataset). Results come back query-sharded and are returned as
    one array."""
    if params is None:
        params = cagra_mod.CagraSearchParams(**kwargs)
    queries = jnp.asarray(queries, jnp.float32)
    nq = queries.shape[0]
    n_shards = mesh.shape[axis]
    expects(nq % n_shards == 0, "n_queries %d not divisible by %d shards", nq, n_shards)

    itopk, width, iters, n_init = cagra_mod.derive_search_config(params, k, index.size)
    use_vpq = index.dataset is None
    if use_vpq:
        expects(index.vpq is not None, "index has neither dataset nor vpq data")
    fn = _cagra_fn(
        mesh, axis, k, itopk, width, iters, n_init, index.size, index.metric,
        params.seed, use_vpq, params.init_sample,
    )
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    if use_vpq:
        return fn(
            put(index.vpq.sqnorms, P()),
            put(index.graph, P()),
            put(queries, P(axis)),
            put(index.vpq.vq_centers, P()),
            put(index.vpq.vq_labels, P()),
            put(index.vpq.pq_centers, P()),
            put(index.vpq.codes, P()),
        )
    return fn(
        put(index.sqnorms, P()),
        put(index.graph, P()),
        put(queries, P(axis)),
        put(index.dataset, P()),
    )


@functools.lru_cache(maxsize=64)
def _ivf_pq_fn(mesh, axis, k, n_probes, metric, per_cluster, g, bf16):
    def local(centers, centers_rot, rotation, pq_centers, codes, li, sqn, q):
        return ivf_pq_mod._ivf_pq_scan_impl(
            centers, centers_rot, rotation, pq_centers, codes, li, sqn, q, None,
            k=k, n_probes=n_probes, metric=metric,
            per_cluster=per_cluster, has_filter=False, chunk_lists=g, bf16=bf16,
        )

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P(), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


def sharded_ivf_pq_search(
    mesh: Mesh,
    index: "ivf_pq_mod.IvfPqIndex",
    queries,
    k: int,
    params: Optional["ivf_pq_mod.IvfPqSearchParams"] = None,
    axis: str = "data",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-PQ search with queries sharded over the mesh (replicated
    compressed index). The code footprint is ~pq_dim bytes/row, so a
    replica per chip covers far larger datasets than raw vectors would;
    query data-parallelism is the first-order ICI scaling knob."""
    if params is None:
        params = ivf_pq_mod.IvfPqSearchParams(**kwargs)
    queries = jnp.asarray(queries, jnp.float32)
    nq = queries.shape[0]
    n_shards = mesh.shape[axis]
    expects(nq % n_shards == 0, "n_queries %d not divisible by %d shards", nq, n_shards)
    n_probes = min(params.n_probes, index.n_lists)
    g = ivf_pq_mod.scan_chunk_lists(index.n_lists, index.max_list)
    per_cluster = index.codebook_kind == ivf_pq_mod.PER_CLUSTER
    bf16 = ivf_pq_mod.scan_bf16(params.lut_dtype)

    fn = _ivf_pq_fn(mesh, axis, k, n_probes, index.metric, per_cluster, g, bf16)
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    return fn(
        put(index.centers, P()),
        put(index.centers_rot, P()),
        put(index.rotation, P()),
        put(index.pq_centers, P()),
        put(index.codes, P()),
        put(index.list_indices, P()),
        put(index.rot_sqnorms, P()),
        put(queries, P(axis)),
    )
