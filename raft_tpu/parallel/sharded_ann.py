"""ICI-sharded ANN search for the IVF and CAGRA indexes.

SURVEY.md §7 step 7 / §2.5: the reference leaves multi-GPU ANN to
downstream consumers (``docs/source/using_raft_comms.rst:5-7``); this
framework ships it in-tree. Two shardings, mirroring how the data
structures scale:

* **IVF-Flat: inverted lists sharded** across the mesh axis. Coarse
  probing runs against the replicated centers (tiny), each shard streams
  only its slice of the padded lists through the dense masked scan
  (:func:`raft_tpu.neighbors.ivf_flat.flat_scan_core`) — list ids in the
  padded layout are global dataset row ids, so per-shard top-k merge with
  one ``all_gather`` + k-way merge (``knn_merge_parts`` pattern).
* **IVF-PQ: inverted lists sharded** (round 4): replicated coarse centers
  + quantizers (tiny), each shard decode-scans only ITS slice of the code
  lists (:func:`raft_tpu.neighbors.ivf_pq.pq_scan_core`), allgather +
  k-way merge — the compressed analog of the IVF-Flat sharding, and the
  path that takes DEEP-100M-class datasets past one chip's HBM.
  :func:`sharded_ivf_pq_build` is the matching distributed-build sketch
  (psum-Lloyd coarse centers + codebooks over row-sharded data).
* **CAGRA / IVF-PQ (small indexes): queries sharded, index replicated** —
  graph beam search is latency-bound per query and the graph is compact,
  so replicated-index data parallelism is the first-order scaling knob
  (the reference's multi-GPU story for CAGRA is likewise index-replica
  sharding at the serving layer).

Everything runs under ``shard_map`` over a :func:`make_mesh` mesh and
works identically on real ICI or the virtual CPU test mesh.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import functools

from raft_tpu.core.errors import expects
from raft_tpu.neighbors import cagra as cagra_mod, ivf_flat as ivf_flat_mod, ivf_pq as ivf_pq_mod
from raft_tpu.ops.distance import DistanceType
from raft_tpu.ops.pallas._guard import kernel_guard
from raft_tpu.ops.select_k import merge_parts, worst_value
from raft_tpu.parallel._compat import shard_map
from raft_tpu.random.rng import as_key
from raft_tpu.robust.fallback import FALLBACK_ERRORS, record_fallback


def _health_array(health, n_shards) -> jnp.ndarray:
    """Replicated per-shard health mask [n_shards] bool; ``None`` means
    all healthy (and callers then build the unmasked program)."""
    h = jnp.asarray(health, bool)
    expects(h.shape == (n_shards,), "health mask shape %s != (%d,)", h.shape, n_shards)
    return h


#: candidate-exchange engines for the lists-sharded searches
_MERGE_MODES = ("auto", "ring", "fused_ring", "gather")


def _resolve_merge_mode(merge_mode: str, n_shards: int, k=None) -> str:
    """``auto`` prefers the ring exchange whenever there is more than one
    shard (parity with gather is exact, wire bytes are ~0.4n× lower); a
    single shard has nothing to exchange and keeps the trivial path.
    ``fused_ring`` keeps the same wire schedule but folds the scan's
    candidate tile to the merge width inside the ring engine.

    This is the single merge-engine chokepoint: every sharded search
    (and the serving engine's sharded registrations, transitively)
    resolves ``auto`` here, through the planner's wire-model costing
    when enabled."""
    expects(merge_mode in _MERGE_MODES, "merge_mode %r (want one of %s)",
            merge_mode, _MERGE_MODES)
    if merge_mode == "auto":
        from raft_tpu import plan as _plan

        if _plan.is_enabled():
            return _plan.plan_merge_mode(n_shards, k).choice
        return "ring" if n_shards > 1 else "gather"
    if merge_mode == "fused_ring" and n_shards == 1:
        return "gather"
    return merge_mode


def _exchange_merge(v, i, k, select_min, axis, merge_mode):
    """Cross-shard candidate exchange + merge (runs inside ``shard_map``).

    ``ring`` streams each shard's surviving top-k around the ICI ring
    (:func:`raft_tpu.ops.pallas.ring_topk.ring_topk`), keeping wire bytes
    and peak memory O(k) per hop; ``fused_ring`` hands the scan's
    candidate tile (any width >= k) to
    :func:`~raft_tpu.ops.pallas.ring_topk.scan_ring_topk`, which runs the
    scan's final top-k fold inside the ring engine so the per-shard
    ``[nq, k]`` winners never round-trip through HBM before the exchange;
    ``gather`` materialises the full ``n_shards × k`` candidate set on
    every shard and is kept as the reference engine and both rings'
    fallback target. Ids are bit-identical across all three by the ring's
    (value, position) total-order contract.
    """
    if merge_mode == "fused_ring":
        from raft_tpu.ops.pallas.ring_topk import scan_ring_topk  # lazy: parallel <-> ops cycle

        return scan_ring_topk(v, i, k, select_min=select_min, axis=axis)
    if merge_mode == "ring":
        from raft_tpu.ops.pallas.ring_topk import ring_topk  # lazy: parallel <-> ops cycle

        return ring_topk(v, i, k, select_min=select_min, axis=axis)
    nq = v.shape[0]
    all_v = jax.lax.all_gather(v, axis)  # graft-lint: ignore[gather-merge] — reference engine + ring/fused_ring fallback target
    all_i = jax.lax.all_gather(i, axis)
    cat_v = jnp.moveaxis(all_v, 0, 1).reshape(nq, -1)
    cat_i = jnp.moveaxis(all_i, 0, 1).reshape(nq, -1)
    # invalid (-1) slots carry +/-inf values and lose the merge
    return merge_parts(cat_v, cat_i, k, select_min=select_min)


def _run_with_ring_fallback(build, args, mode):
    """Execute the resolved-engine program; a failing ring program
    (injected ``comms.ring_topk`` chaos, or a real lowering/runtime error
    on hardware) is re-run on the gather engine. Both rings are purely a
    transport — results are bit-identical — so falling back is always
    safe, including for explicitly requested ``merge_mode="ring"`` /
    ``"fused_ring"`` (unlike ``mode="fused"`` kernels, where the engine
    *is* the request). Fallbacks count under the existing
    ``fallbacks{algo}`` counter with the engine's own algo label.
    """
    if mode in ("ring", "fused_ring"):
        algo = "ring_topk" if mode == "ring" else "scan_ring_topk"
        try:
            with kernel_guard(algo):
                return build(mode)(*args)
        except FALLBACK_ERRORS as exc:
            record_fallback(algo, exc)
    return build("gather")(*args)


@functools.lru_cache(maxsize=64)
def _ivf_flat_fn(mesh, axis, k, n_probes, metric, g, l_local, masked=False,
                 merge_mode="gather"):
    """Cached jitted shard_map program (rebuilding it per call would
    re-trace and recompile every search). With ``masked=True`` the program
    takes an extra replicated ``healthy [n_shards]`` input and unhealthy
    shards' candidates are demoted to worst-value/-1 before the exchange,
    so the merge drops them (degraded-mode search; a demoted shard loses
    every ring fold the same way it loses the gathered merge)."""

    def local(centers, ld, li, ln, q, *rest):
        rank = lax.axis_index(axis)
        qf = q
        if metric == DistanceType.CosineExpanded:
            qf = qf / jnp.maximum(jnp.linalg.norm(qf, axis=1, keepdims=True), 1e-12)
        probed = ivf_flat_mod.probe_mask(centers, qf, n_probes, metric)
        probed_local = lax.dynamic_slice_in_dim(probed, rank * l_local, l_local, axis=1)
        v, i = ivf_flat_mod.flat_scan_core(
            ld, li, ln, qf, probed_local, None,
            k=k, metric=metric, has_filter=False, chunk_lists=g,
        )
        select_min = metric != DistanceType.InnerProduct
        if masked:
            (healthy,) = rest
            ok = healthy[rank]
            v = jnp.where(ok, v, worst_value(v.dtype, select_min))
            i = jnp.where(ok, i, -1)
        return _exchange_merge(v, i, k, select_min, axis, merge_mode)

    extra = (P(),) if masked else ()
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P()) + extra,
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def sharded_ivf_flat_search(
    mesh: Mesh,
    index: "ivf_flat_mod.IvfFlatIndex",
    queries,
    k: int,
    params: Optional["ivf_flat_mod.IvfFlatSearchParams"] = None,
    axis: str = "data",
    health=None,
    merge_mode: str = "auto",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-Flat search with lists sharded over ``mesh`` axis ``axis``.

    Returns replicated ``(distances [nq, k], indices [nq, k])`` drawn from
    the same probed candidate set as single-device scan search. With a
    per-shard boolean ``health`` mask, unhealthy shards are excluded from
    the merge (degraded-mode search; see :mod:`raft_tpu.robust.degrade`).
    ``merge_mode`` picks the cross-shard exchange: ``"ring"`` (in-VMEM
    ring top-k), ``"gather"`` (all-gather + merge reference), or
    ``"auto"`` (ring when sharded, with automatic gather fallback on
    kernel failure).
    """
    if params is None:
        params = ivf_flat_mod.IvfFlatSearchParams(**kwargs)
    queries = jnp.asarray(queries, jnp.float32)
    n_shards = mesh.shape[axis]
    L = index.n_lists
    expects(L % n_shards == 0, "n_lists %d not divisible by %d shards", L, n_shards)
    l_local = L // n_shards
    n_probes = min(params.n_probes, L)
    metric = index.metric
    g = ivf_flat_mod.scan_chunk_lists(l_local, index.max_list)

    masked = health is not None
    mode = _resolve_merge_mode(merge_mode, n_shards, k)
    ln = index.list_norms
    if ln is None:
        ln = jnp.zeros(index.list_indices.shape, jnp.float32)
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    args = [
        put(index.centers, P()),
        put(index.list_data, P(axis)),
        put(index.list_indices, P(axis)),
        put(ln, P(axis)),
        put(queries, P()),
    ]
    if masked:
        args.append(put(_health_array(health, n_shards), P()))
    build = lambda m: _ivf_flat_fn(
        mesh, axis, k, n_probes, metric, g, l_local, masked, m
    )
    return _run_with_ring_fallback(build, args, mode)


@functools.lru_cache(maxsize=64)
def _cagra_fn(mesh, axis, k, itopk, width, iters, n_init, size, metric, seed, use_vpq, init_sample):
    key = as_key(seed)

    def local(sqnorms, graph, q, *data_args):
        rank = lax.axis_index(axis)
        kb = jax.random.fold_in(key, rank)
        if init_sample > 0:
            init_ids = cagra_mod.strided_seed_ids(size, init_sample)
        else:
            init_ids = jax.random.randint(kb, (q.shape[0], n_init), 0, size, jnp.int32)
        if use_vpq:
            dataset, vpq_arrays = None, tuple(data_args)
        else:
            (dataset,), vpq_arrays = data_args, None
        return cagra_mod._cagra_search_impl(
            dataset, sqnorms, graph, q, init_ids, None, vpq_arrays,
            k=k, itopk=itopk, width=width, iters=iters,
            metric=metric, has_filter=False, use_vpq=use_vpq,
        )

    n_data = 4 if use_vpq else 1
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(axis)) + (P(),) * n_data,
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


def sharded_cagra_search(
    mesh: Mesh,
    index: "cagra_mod.CagraIndex",
    queries,
    k: int,
    params: Optional["cagra_mod.CagraSearchParams"] = None,
    axis: str = "data",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """CAGRA beam search with queries sharded over the mesh (replicated
    graph + dataset). Results come back query-sharded and are returned as
    one array."""
    if params is None:
        params = cagra_mod.CagraSearchParams(**kwargs)
    queries = jnp.asarray(queries, jnp.float32)
    nq = queries.shape[0]
    n_shards = mesh.shape[axis]
    expects(nq % n_shards == 0, "n_queries %d not divisible by %d shards", nq, n_shards)

    itopk, width, iters, n_init = cagra_mod.derive_search_config(params, k, index.size)
    use_vpq = index.dataset is None
    if use_vpq:
        expects(index.vpq is not None, "index has neither dataset nor vpq data")
    fn = _cagra_fn(
        mesh, axis, k, itopk, width, iters, n_init, index.size, index.metric,
        params.seed, use_vpq, params.init_sample,
    )
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    if use_vpq:
        return fn(
            put(index.vpq.sqnorms, P()),
            put(index.graph, P()),
            put(queries, P(axis)),
            put(index.vpq.vq_centers, P()),
            put(index.vpq.vq_labels, P()),
            put(index.vpq.pq_centers, P()),
            put(index.vpq.codes, P()),
        )
    return fn(
        put(index.sqnorms, P()),
        put(index.graph, P()),
        put(queries, P(axis)),
        put(index.dataset, P()),
    )


@functools.lru_cache(maxsize=64)
def _ivf_pq_lists_fn(mesh, axis, k, n_probes, metric, g, bf16, l_local, masked=False,
                     merge_mode="gather"):
    """Lists-sharded PQ search program: replicated centers/quantizers,
    per-shard decode scan over the local list slice, cross-shard exchange
    + merge (``merge_mode`` engine). ``masked=True`` adds the replicated
    per-shard health input (see :func:`_ivf_flat_fn`)."""

    def local(centers, centers_rot, rotation, pq_centers, codes, li, sqn, q, *rest):
        rank = lax.axis_index(axis)
        qf = q.astype(jnp.float32)
        q_dot_c = qf @ centers.T
        if metric == DistanceType.InnerProduct:
            coarse = -q_dot_c
        else:
            c_norm = jnp.sum(centers * centers, axis=1)
            coarse = c_norm[None, :] - 2.0 * q_dot_c
        nq = q.shape[0]
        n_lists = centers.shape[0]
        from raft_tpu.ops.select_k import select_k as _sk

        probed = jnp.zeros((nq, n_lists), bool)
        if n_probes < n_lists:
            _, probes = _sk(coarse, n_probes, select_min=True)
            probed = probed.at[jnp.arange(nq)[:, None], probes].set(True)
        else:
            probed = jnp.ones((nq, n_lists), bool)
        probed_l = lax.dynamic_slice_in_dim(probed, rank * l_local, l_local, axis=1)
        qdc_l = lax.dynamic_slice_in_dim(q_dot_c, rank * l_local, l_local, axis=1)
        q_rot = qf @ rotation.T
        v, i = ivf_pq_mod.pq_scan_core(
            pq_centers, codes, li, sqn, q_rot, qdc_l, probed_l, None,
            k=k, metric=metric, per_cluster=False, has_filter=False,
            chunk_lists=g, bf16=bf16,
        )
        select_min = metric != DistanceType.InnerProduct
        if masked:
            (healthy,) = rest
            ok = healthy[rank]
            v = jnp.where(ok, v, worst_value(v.dtype, select_min))
            i = jnp.where(ok, i, -1)
        return _exchange_merge(v, i, k, select_min, axis, merge_mode)

    extra = (P(),) if masked else ()
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(axis), P(axis), P(axis), P()) + extra,
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def sharded_ivf_pq_lists_search(
    mesh: Mesh,
    index: "ivf_pq_mod.IvfPqIndex",
    queries,
    k: int,
    params: Optional["ivf_pq_mod.IvfPqSearchParams"] = None,
    axis: str = "data",
    health=None,
    merge_mode: str = "auto",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-PQ search with the CODE LISTS sharded over ``mesh`` axis
    ``axis`` (replicated coarse centers + codebooks). Per-shard HBM holds
    ``1/n_shards`` of the codes — the scaling mode for datasets beyond one
    chip (SURVEY §7 step 7). Returns replicated ``(distances, indices)``
    from the same probed candidate set as single-device scan search.
    ``health`` (per-shard bools) excludes failed shards from the merge;
    ``merge_mode`` picks the exchange engine (see
    :func:`sharded_ivf_flat_search`)."""
    if params is None:
        params = ivf_pq_mod.IvfPqSearchParams(**kwargs)
    expects(
        index.codebook_kind == ivf_pq_mod.PER_SUBSPACE,
        "lists-sharded PQ needs per_subspace codebooks (per_cluster books would shard too)",
    )
    queries = jnp.asarray(queries, jnp.float32)
    n_shards = mesh.shape[axis]
    L = index.n_lists
    expects(L % n_shards == 0, "n_lists %d not divisible by %d shards", L, n_shards)
    l_local = L // n_shards
    n_probes = min(params.n_probes, L)
    g = ivf_pq_mod.scan_chunk_lists(l_local, index.max_list)
    bf16 = ivf_pq_mod.scan_bf16(params.lut_dtype)

    masked = health is not None
    mode = _resolve_merge_mode(merge_mode, n_shards, k)
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    args = [
        put(index.centers, P()),
        put(index.centers_rot, P()),
        put(index.rotation, P()),
        put(index.pq_centers, P()),
        put(index.codes_unpacked(), P(axis)),
        put(index.list_indices, P(axis)),
        put(index.rot_sqnorms, P(axis)),
        put(queries, P()),
    ]
    if masked:
        args.append(put(_health_array(health, n_shards), P()))
    build = lambda m: _ivf_pq_lists_fn(
        mesh, axis, k, n_probes, index.metric, g, bf16, l_local, masked, m
    )
    return _run_with_ring_fallback(build, args, mode)


#: cross-shard accumulator-exchange engines for the distributed builds
_COMM_MODES = ("auto", "full", "ca")


def _resolve_comm_mode(comm_mode: str, n_shards: int, n_rows=None,
                       d=None, ca_cap=None) -> str:
    """``auto`` prefers the communication-avoiding exchange whenever
    there is more than one shard (wire bytes per iteration drop to the
    changed-row fraction); a single shard pays no wire bytes either way
    and keeps the reference ``full`` exchange.

    With the planner enabled and the accumulator shape known
    (``n_rows``/``d``), ``auto`` is costed from the consolidated wire
    model instead — which also keeps ``full`` for degenerate shapes
    where the CA row cap cannot undercut the full exchange."""
    expects(comm_mode in _COMM_MODES, "comm_mode %r (want one of %s)",
            comm_mode, _COMM_MODES)
    if comm_mode == "auto":
        from raft_tpu import plan as _plan

        if _plan.is_enabled() and n_rows is not None and d is not None:
            return _plan.plan_comm_mode(n_rows, d, n_shards, ca_cap=ca_cap).choice
        return "ca" if n_shards > 1 else "full"
    return comm_mode


def _ca_cap(n_rows: int, ca_cap) -> int:
    """Exchanged-row budget for the CA accumulator exchange — the
    consolidated :func:`raft_tpu.parallel.wire_model.ca_exchange_cap`
    (kept as the builds' local name)."""
    from raft_tpu.parallel.wire_model import ca_exchange_cap

    return ca_exchange_cap(n_rows, ca_cap)


def _note_build_comms(phase: str, payload_bytes: float, axis: str,
                      verb: str = "allreduce", launches: int = 1) -> None:
    """Trace-time build-comms accounting: one ``comms.build.launches``
    tick per collective launch and the wire-model bytes
    (:func:`raft_tpu.parallel.comms.wire_bytes`) under
    ``comms.build.bytes``, both labelled with the build ``phase``. The
    build programs retrace per call, so per-iteration launches inside the
    Python training loop each fire once."""
    from raft_tpu import obs
    from raft_tpu.parallel._compat import axis_size
    from raft_tpu.parallel.comms import wire_bytes

    if not obs.is_enabled():
        return
    n = axis_size(axis)
    obs.inc("comms.build.launches", float(launches), phase=phase)
    obs.inc("comms.build.bytes", wire_bytes(verb, payload_bytes, n), phase=phase)


def _ca_exchange(rows_local, changed_local, gsums, cap, axis, phase):
    """Communication-avoiding accumulator exchange (runs inside
    ``shard_map``): allreduce the tiny per-row changed-count vector,
    pick the ``cap`` rows with the most global churn (``lax.top_k`` on a
    replicated input — every shard selects the same rows, ties broken by
    lowest index), allreduce ONLY those rows' fresh local partials, and
    patch them into the carried global accumulator.

    Exactness: a row whose assignments did not change on ANY shard has a
    bit-identical local partial this iteration (same rows, summed in the
    same order), so its carried psum value already equals a fresh
    full-width psum bit-for-bit. Whenever the global changed-row count
    fits under ``cap`` every iteration, the CA trajectory is therefore
    bit-identical to the ``full`` exchange (trivially so at
    ``cap=n_rows``); beyond the cap the least-churned rows lag one
    iteration — the bounded-drift regime covered by the recall-floor
    contract. Zero-change rows drafted to fill the cap re-psum to
    identical bits, so over-selection is harmless."""
    from raft_tpu.parallel.comms import allreduce

    gchanged = allreduce(changed_local, "sum", axis)
    _, sel = lax.top_k(gchanged, cap)
    block = allreduce(jnp.take(rows_local, sel, axis=0), "sum", axis)
    _note_build_comms(
        phase,
        changed_local.size * 4 + block.size * 4,
        axis,
        launches=2,
    )
    return gsums.at[sel].set(block)


def dist_lloyd_step(centers, x_local, n_lists, axis, cache=None, fuse_comms=True,
                    comm_mode="full", carry=None, ca_cap=None):
    """One distributed Lloyd iteration (runs inside ``shard_map``):
    Flash-KMeans blocked E step on the local rows (``cache`` from
    :func:`raft_tpu.cluster.kmeans.flash_norm_cache`, hoisted across
    iterations), then the centroid sums and counts are packed into ONE
    concatenated ``[n_lists, d+1]`` allreduce instead of two. psum is
    elementwise, so the packed reduction is bit-identical to the
    separate pair — the Lloyd trajectory is unchanged
    (``fuse_comms=False`` keeps the two-allreduce reference for the
    trajectory/byte-count tests).

    ``comm_mode="ca"`` is the communication-avoiding exchange: the step
    carries ``(prev_labels, packed_global_sums)`` across iterations and
    each iteration moves only the ``ca_cap`` most-churned lists' partial
    sums (plus a ``[n_lists]`` changed-count vector) over the wire — see
    :func:`_ca_exchange` for the bit-identical-under-cap contract and
    :func:`lloyd_wire_bytes_per_iter` for the byte model. In CA mode the
    step returns ``(centers, labels, carry)``; pass ``carry=None`` on
    the first iteration (which pays one full-width exchange to seed the
    carried accumulator)."""
    from raft_tpu.cluster.kmeans import flash_min_cluster_and_distance
    from raft_tpu.parallel.comms import allreduce

    lab, _ = flash_min_cluster_and_distance(
        x_local, centers, metric=DistanceType.L2Expanded, cache=cache
    )
    sums = jax.ops.segment_sum(x_local, lab, num_segments=n_lists)
    cnts = jax.ops.segment_sum(jnp.ones_like(lab, jnp.float32), lab, num_segments=n_lists)
    if comm_mode == "ca":
        local_rows = jnp.concatenate([sums, cnts[:, None]], axis=1)
        if carry is None:
            packed = allreduce(local_rows, "sum", axis)
            _note_build_comms("kmeans_full", local_rows.size * 4, axis)
        else:
            prev_lab, gsums = carry
            moved = (lab != prev_lab).astype(jnp.float32)
            changed = (
                jax.ops.segment_sum(moved, lab, num_segments=n_lists)
                + jax.ops.segment_sum(moved, prev_lab, num_segments=n_lists)
            )
            cap = _ca_cap(n_lists, ca_cap)
            packed = _ca_exchange(local_rows, changed, gsums, cap, axis, "kmeans_ca")
        gs, gc = packed[:, :-1], packed[:, -1]
        new = gs / jnp.maximum(gc[:, None], 1e-9)
        centers_out = jnp.where(gc[:, None] > 0, new, centers)
        return centers_out, lab, (lab, packed)
    if fuse_comms:
        packed = allreduce(jnp.concatenate([sums, cnts[:, None]], axis=1), "sum", axis)
        _note_build_comms("kmeans_full", packed.size * 4, axis)
        sums, cnts = packed[:, :-1], packed[:, -1]
    else:
        sums = allreduce(sums, "sum", axis)
        cnts = allreduce(cnts, "sum", axis)
        _note_build_comms("kmeans_full", sums.size * 4 + cnts.size * 4, axis,
                          launches=2)
    new = sums / jnp.maximum(cnts[:, None], 1e-9)
    return jnp.where(cnts[:, None] > 0, new, centers), lab


def dist_codebook_step(books, resid, ksub, axis, fuse_comms=True,
                       comm_mode="full", carry=None, ca_cap=None):
    """One distributed per-subspace codebook update (runs inside
    ``shard_map``): local assignment of residual sub-vectors, then the
    ``[pq_dim, ksub, pq_len]`` sums and ``[pq_dim, ksub]`` counts ride
    one concatenated allreduce (counts as an extra trailing column),
    matching :func:`dist_lloyd_step`'s comm fusion bit-for-bit.

    ``comm_mode="ca"`` flattens the accumulator to ``[pq_dim·ksub,
    pq_len+1]`` rows and applies the same carried changed-rows exchange
    as the Lloyd step (:func:`_ca_exchange`); returns ``(books, carry)``
    with ``carry=(codes, packed_rows)``."""
    from raft_tpu.parallel.comms import allreduce

    dots = jnp.einsum("npl,pkl->npk", resid, books, preferred_element_type=jnp.float32)
    cn = jnp.sum(books * books, axis=-1)[None, :, :]
    code = jnp.argmin(cn - 2.0 * dots, axis=-1)  # [nl, pq_dim]
    oh = jax.nn.one_hot(code, ksub, dtype=jnp.float32)  # [nl, pq_dim, ksub]
    sums = jnp.einsum("npk,npl->pkl", oh, resid)
    cnts = jnp.sum(oh, axis=0)  # [pq_dim, ksub]
    if comm_mode == "ca":
        pq_dim, _, pq_len = sums.shape
        local_rows = jnp.concatenate([sums, cnts[..., None]], axis=-1)
        local_rows = local_rows.reshape(pq_dim * ksub, pq_len + 1)
        if carry is None:
            packed = allreduce(local_rows, "sum", axis)
            _note_build_comms("pq_codebook_full", local_rows.size * 4, axis)
        else:
            prev_code, grows = carry
            moved = (code != prev_code).astype(jnp.float32)  # [nl, pq_dim]
            prev_oh = jax.nn.one_hot(prev_code, ksub, dtype=jnp.float32)
            changed = (
                jnp.einsum("np,npk->pk", moved, oh)
                + jnp.einsum("np,npk->pk", moved, prev_oh)
            ).reshape(pq_dim * ksub)
            cap = _ca_cap(pq_dim * ksub, ca_cap)
            packed = _ca_exchange(local_rows, changed, grows, cap, axis, "pq_codebook_ca")
        rows = packed.reshape(pq_dim, ksub, pq_len + 1)
        gs, gc = rows[..., :-1], rows[..., -1]
        new = gs / jnp.maximum(gc[..., None], 1e-9)
        return jnp.where(gc[..., None] > 0, new, books), (code, packed)
    if fuse_comms:
        packed = allreduce(jnp.concatenate([sums, cnts[..., None]], axis=-1), "sum", axis)
        _note_build_comms("pq_codebook_full", packed.size * 4, axis)
        sums, cnts = packed[..., :-1], packed[..., -1]
    else:
        sums = allreduce(sums, "sum", axis)
        cnts = allreduce(cnts, "sum", axis)
        _note_build_comms("pq_codebook_full", sums.size * 4 + cnts.size * 4, axis,
                          launches=2)
    new = sums / jnp.maximum(cnts[..., None], 1e-9)
    return jnp.where(cnts[..., None] > 0, new, books)


# The per-iteration build byte models moved to the consolidated
# raft_tpu.parallel.wire_model (the planner's comm terms price builds
# from the same table); re-exported at this original home, where the
# bench dist_build phase and tests import them from.
from raft_tpu.parallel.wire_model import (  # noqa: E402,F401  (re-export)
    codebook_wire_bytes_per_iter,
    lloyd_wire_bytes_per_iter,
)


def sharded_ivf_pq_build(
    mesh: Mesh,
    dataset,
    params: Optional["ivf_pq_mod.IvfPqIndexParams"] = None,
    axis: str = "data",
    fuse_comms: bool = True,
    comm_mode: str = "auto",
    ca_cap=None,
    ca_warmup: int = 2,
    **kwargs,
) -> "ivf_pq_mod.IvfPqIndex":
    """Distributed IVF-PQ build sketch (SURVEY §7 step 7): dataset rows
    sharded over the mesh, coarse centers and per-subspace codebooks
    trained with psum-Lloyd (local Flash-KMeans assign + summed center
    updates — the allreduce pattern of ``cluster/detail/kmeans_balanced.cuh``
    scaled out, with sums+counts fused into one allreduce per iteration),
    then every shard encodes its rows locally and the packed lists
    are assembled. The returned index is replicated (at DCN scale the
    final allgather would be skipped and the lists kept sharded for
    :func:`sharded_ivf_pq_lists_search`).

    ``comm_mode`` picks the per-iteration accumulator exchange:
    ``"full"`` is the reference fused allreduce, ``"ca"`` carries the
    global accumulator and moves only the most-churned rows each
    iteration (:func:`_ca_exchange`; bit-identical to ``full`` while the
    per-iteration churn fits under ``ca_cap``, recall-bounded beyond
    it), ``"auto"`` is CA whenever sharded. ``ca_warmup`` full-width
    Lloyd exchanges run before the capped exchange takes over —
    assignment churn is front-loaded (it decays geometrically once the
    centers coarse-settle), so paying full bytes for the first couple
    of iterations recovers nearly all of the full-mode recall while the
    steady-state per-iteration wire stays at the CA rate. Codebooks are
    seeded from a strided sample of EVERY shard's residuals (one
    init-only allgather) so the seed pool spans the global residual
    distribution — the rank-0-only seed this replaces left ~0.02 recall
    on the table vs the single-chip build whenever one shard's rows
    couldn't cover ``ksub`` distinct seeds."""
    if params is None:
        params = ivf_pq_mod.IvfPqIndexParams(**kwargs)
    dataset = jnp.asarray(dataset, jnp.float32)
    n, d = dataset.shape
    n_shards = mesh.shape[axis]
    expects(n % n_shards == 0, "rows %d not divisible by %d shards", n, n_shards)
    n_lists = min(params.n_lists, n)
    pq_dim = params.pq_dim or ivf_pq_mod._default_pq_dim(d)
    rot_dim = ((d + pq_dim - 1) // pq_dim) * pq_dim
    ksub = 1 << params.pq_bits
    mode = _resolve_comm_mode(comm_mode, n_shards, n_rows=n_lists, d=d,
                              ca_cap=ca_cap)

    key = as_key(params.seed)
    k_init, k_rot = jax.random.split(key)
    init_centers = dataset[jax.random.permutation(k_init, n)[:n_lists]]
    rotation = ivf_pq_mod._make_rotation(k_rot, rot_dim, d, params.force_random_rotation)

    def train(x_local, centers0):
        from raft_tpu.cluster.kmeans import flash_min_cluster_and_distance, flash_norm_cache
        from raft_tpu.parallel.comms import allgather

        # sample-side norms are iteration-invariant: hoist them out of
        # the Lloyd loop (the Flash-KMeans cache discipline)
        cache = flash_norm_cache(x_local, DistanceType.L2Expanded)
        centers = centers0
        if mode == "ca":
            carry = None
            for it in range(params.kmeans_n_iters):
                if it < ca_warmup - 1:
                    # warm-up: full-width while churn is still heavy
                    # (the first CA call re-seeds full-width anyway, so
                    # ca_warmup counts TOTAL full exchanges)
                    centers, _ = dist_lloyd_step(
                        centers, x_local, n_lists, axis, cache=cache,
                        fuse_comms=True,
                    )
                    continue
                centers, lab, carry = dist_lloyd_step(
                    centers, x_local, n_lists, axis, cache=cache,
                    comm_mode="ca", carry=carry, ca_cap=ca_cap,
                )
            # final labeling against the converged centers is comm-free
            lab, _ = flash_min_cluster_and_distance(
                x_local, centers, metric=DistanceType.L2Expanded, cache=cache
            )
        else:
            for _ in range(params.kmeans_n_iters):
                centers, _ = dist_lloyd_step(
                    centers, x_local, n_lists, axis, cache=cache, fuse_comms=fuse_comms
                )
            _, lab = dist_lloyd_step(
                centers, x_local, n_lists, axis, cache=cache, fuse_comms=fuse_comms
            )
        # per-subspace codebooks on local residuals, psum'd updates;
        # seeded from a stride-spread sample of EVERY shard's residuals
        # (real-data init — random gaussians collapse to few used
        # centers; a single shard's rows skew or under-fill the pool)
        resid = ((x_local - centers[lab]) @ rotation.T).reshape(x_local.shape[0], pq_dim, -1)
        nl_local = resid.shape[0]
        per = -(-ksub // n_shards)
        stride = max(1, nl_local // per)
        idx = jnp.minimum(jnp.arange(per) * stride, nl_local - 1)
        pool = allgather(resid[idx], axis)  # [n_shards, per, pq_dim, pq_len]
        _note_build_comms("seed", pool[0].size * 4, axis, verb="allgather")
        seed = jnp.swapaxes(pool, 0, 1).reshape(n_shards * per, pq_dim, -1)
        n_seed = min(ksub, n_shards * nl_local)
        books = jnp.transpose(seed[:n_seed], (1, 0, 2))
        if n_seed < ksub:
            reps = -(-ksub // n_seed)
            books = jnp.tile(books, (1, reps, 1))[:, :ksub, :]

        if mode == "ca":
            bcarry = None
            for _ in range(max(4, params.kmeans_n_iters)):
                books, bcarry = dist_codebook_step(
                    books, resid, ksub, axis,
                    comm_mode="ca", carry=bcarry, ca_cap=ca_cap,
                )
        else:
            for _ in range(max(4, params.kmeans_n_iters)):
                books = dist_codebook_step(books, resid, ksub, axis, fuse_comms=fuse_comms)
        return centers, books

    fn = jax.jit(
        shard_map(
            train,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    centers, books = fn(put(dataset, P(axis)), put(init_centers, P()))

    # local encode + pack (replicated assembly; kept sharded at DCN scale)
    from raft_tpu.neighbors import ivf_common

    cand = ivf_common.topk_labels(dataset, centers, k=8)
    max_list = ivf_common.choose_max_list(cand[:, 0], n, n_lists, params.list_cap_factor)
    slot = ivf_common.assign_slots(cand, n_lists=n_lists, max_list=max_list)
    final_labels = (slot // max_list).astype(jnp.int32)
    codes_rows = ivf_pq_mod._encode_all(
        dataset, final_labels, centers, rotation, books, pq_dim, False
    )
    codes, list_indices, list_sizes = ivf_common.scatter_rows(
        codes_rows, jnp.arange(n, dtype=jnp.int32), slot, n_lists=n_lists, max_list=max_list
    )
    centers_rot = centers @ rotation.T
    return ivf_pq_mod.IvfPqIndex(
        centers=centers,
        centers_rot=centers_rot,
        rotation=rotation,
        pq_centers=books,
        codes=codes,
        list_indices=list_indices,
        list_sizes=list_sizes,
        rot_sqnorms=ivf_pq_mod._sqnorms_for(codes, centers_rot, books, False),
        metric=ivf_pq_mod.resolve_metric(params.metric),
        codebook_kind=ivf_pq_mod.PER_SUBSPACE,
        pq_bits=params.pq_bits,
        size=n,
        list_cap_factor=params.list_cap_factor,
        center_rank=None,
    )


@functools.lru_cache(maxsize=64)
def _ivf_pq_fn(mesh, axis, k, n_probes, metric, per_cluster, g, bf16):
    def local(centers, centers_rot, rotation, pq_centers, codes, li, sqn, q):
        return ivf_pq_mod._ivf_pq_scan_impl(
            centers, centers_rot, rotation, pq_centers, codes, li, sqn, q, None,
            k=k, n_probes=n_probes, metric=metric,
            per_cluster=per_cluster, has_filter=False, chunk_lists=g, bf16=bf16,
        )

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P(), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


def sharded_ivf_pq_search(
    mesh: Mesh,
    index: "ivf_pq_mod.IvfPqIndex",
    queries,
    k: int,
    params: Optional["ivf_pq_mod.IvfPqSearchParams"] = None,
    axis: str = "data",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-PQ search with queries sharded over the mesh (replicated
    compressed index). The code footprint is ~pq_dim bytes/row, so a
    replica per chip covers far larger datasets than raw vectors would;
    query data-parallelism is the first-order ICI scaling knob."""
    if params is None:
        params = ivf_pq_mod.IvfPqSearchParams(**kwargs)
    queries = jnp.asarray(queries, jnp.float32)
    nq = queries.shape[0]
    n_shards = mesh.shape[axis]
    expects(nq % n_shards == 0, "n_queries %d not divisible by %d shards", nq, n_shards)
    n_probes = min(params.n_probes, index.n_lists)
    g = ivf_pq_mod.scan_chunk_lists(index.n_lists, index.max_list)
    per_cluster = index.codebook_kind == ivf_pq_mod.PER_CLUSTER
    bf16 = ivf_pq_mod.scan_bf16(params.lut_dtype)

    fn = _ivf_pq_fn(mesh, axis, k, n_probes, index.metric, per_cluster, g, bf16)
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    return fn(
        put(index.centers, P()),
        put(index.centers_rot, P()),
        put(index.rotation, P()),
        put(index.pq_centers, P()),
        put(index.codes_unpacked(), P()),
        put(index.list_indices, P()),
        put(index.rot_sqnorms, P()),
        put(queries, P(axis)),
    )
