"""Comms verb set — TPU-native analog of ``raft::comms::comms_t``.

The reference defines a virtual communicator interface (allreduce, bcast,
reduce, allgather(v), gather, reducescatter, barrier, comm_split, p2p
send/recv) implemented over NCCL/UCX/MPI (``core/comms.hpp:125``
``comms_iface``, ``:137-241``; ``comms/std_comms.hpp:70``), injected into the
resources handle and fetched by algorithms via ``resource::get_comms``.

On TPU the transport is the ICI/DCN fabric driven by XLA collectives; the
communicator object dissolves into a `jax.sharding.Mesh` plus `jax.lax`
collective ops that are only meaningful inside `shard_map`. This module
provides:

* mesh construction / installation on :class:`Resources` (the
  ``build_comms_nccl_only`` analog — no uniqueId dance: `jax.distributed`
  handles multi-host bootstrap),
* the typed verb set as thin wrappers over ``jax.lax`` collectives, usable
  inside ``shard_map`` bodies,
* ``comm_split`` as mesh-axis subsetting (the SUB_COMMUNICATOR slot,
  ``core/comms.hpp:274``).

Self-tests mirroring ``comms/comms_test.hpp:117-155`` live in
``tests/test_comms.py``.
"""
from __future__ import annotations

import functools
import inspect
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.parallel._compat import axis_size as _axis_size
from raft_tpu.robust import faults

DEFAULT_AXIS = "data"

_REDUCE_OPS = ("sum", "max", "min", "prod")


def _payload_bytes(x) -> float:
    """Per-rank payload size of a verb argument, from static shape/dtype
    metadata only — safe on tracers inside ``shard_map`` bodies."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(x):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            leaf = np.asarray(leaf)
            shape, dtype = leaf.shape, leaf.dtype
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return float(total)


# The per-verb wire model now lives in raft_tpu.parallel.wire_model so
# the planner, the build byte accounting, and these obs counters all
# price collectives from one table; re-exported here because this module
# is where the ``comms.{verb}.bytes`` counters apply it and where every
# pre-planner consumer imported it from.
from raft_tpu.parallel.wire_model import (  # noqa: F401  (re-export)
    WIRE_FACTORS as _WIRE_FACTORS,
    wire_bytes,
)


def _instrumented(verb: str):
    """Wrap a comms verb with obs counters + a trace-time span.

    Verbs execute while XLA is *tracing* a ``shard_map`` body, so there is
    no device work to sync on here — the span records trace-time only
    (flagged ``traced=True`` in its args) while the counters record call
    counts and per-rank bytes MOVED, i.e. the static input payload scaled
    by the verb's :data:`_WIRE_FACTORS` wire model (outside a named-axis
    trace, where the axis size is unknowable, the raw payload is counted).
    Composite verbs (``reduce`` → ``allreduce``, ``scatter`` → ``bcast``)
    also count their inner verb: that matches the collectives actually
    issued."""

    def deco(fn):
        sig = inspect.signature(fn)

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not obs.is_enabled():
                return fn(*a, **kw)
            bound = sig.bind(*a, **kw)
            bound.apply_defaults()
            x = bound.arguments.get("x")
            axis = str(bound.arguments.get("axis", DEFAULT_AXIS))
            nbytes = _payload_bytes(x) if x is not None else 4.0
            try:
                n = _axis_size(axis)
            except Exception:  # graft-lint: ignore[silent-except] — outside any axis trace
                n = None
            if n and n > 0:
                nbytes = _WIRE_FACTORS.get(verb, lambda p, _: p)(nbytes, n)
            obs.inc(f"comms.{verb}.calls", axis=axis)
            obs.inc(f"comms.{verb}.bytes", nbytes, axis=axis)
            with obs.span(f"comms.{verb}", bytes=nbytes, axis=axis, traced=True):
                return fn(*a, **kw)

        return wrapper

    return deco


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DEFAULT_AXIS,),
) -> Mesh:
    """Build a device mesh. Default: 1-D mesh over all local devices.

    The analog of communicator construction (``std_comms.hpp:70``); mesh
    axes are communicator "dimensions" and sub-communicators are axis
    subsets.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if shape is None:
        shape = (len(devices),)
    expects(
        int(np.prod(shape)) == len(devices),
        "mesh shape %s does not cover %d devices",
        shape,
        len(devices),
    )
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def init_comms(
    res: Optional[Resources] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DEFAULT_AXIS,),
) -> Mesh:
    """Create a mesh and install it on the resources handle — the analog of
    ``inject_comms_on_handle`` (``raft_dask/common/comms_utils.pyx:259``)."""
    res = ensure_resources(res)
    mesh = make_mesh(devices, shape, axis_names)
    res.mesh = mesh
    return mesh


# ---------------------------------------------------------------------------
# Verb set (valid inside shard_map bodies)
# ---------------------------------------------------------------------------


def comm_rank(axis: str = DEFAULT_AXIS) -> jax.Array:
    """This shard's rank along ``axis`` (``comms_t::get_rank``)."""
    return lax.axis_index(axis)


def comm_size(axis: str = DEFAULT_AXIS) -> int:
    """Number of shards along ``axis`` (``comms_t::get_size``)."""
    return _axis_size(axis)


@_instrumented("allreduce")
def allreduce(x, op: str = "sum", axis: str = DEFAULT_AXIS):
    """``comms_t::allreduce`` (``core/comms.hpp:297``)."""
    expects(op in _REDUCE_OPS, "unknown reduce op %s", op)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    # prod via log-domain would lose signs; use exp(sum(log|x|)) only for
    # positive inputs — instead do an allgather+reduce which is exact.
    # all_gather stacks a leading rank axis; reducing over it restores the
    # input shape, keeping prod consistent with sum/max/min.
    return jnp.prod(lax.all_gather(x, axis), axis=0)


@_instrumented("allgather")
def allgather(x, axis: str = DEFAULT_AXIS, tiled: bool = False):
    """``comms_t::allgather`` — concatenate per-rank blocks along axis 0
    (``core/comms.hpp:330``). With ``tiled=False`` a new leading rank axis is
    stacked; with ``tiled=True`` blocks are concatenated along axis 0."""
    # fault point fires at trace time (verbs run while shard_map traces);
    # an injected failure here aborts program construction, the collective
    # analog of a lost participant
    faults.fire("comms.all_gather", axis=str(axis))
    return lax.all_gather(x, axis, tiled=tiled)


@_instrumented("reducescatter")
def reducescatter(x, op: str = "sum", axis: str = DEFAULT_AXIS):
    """``comms_t::reducescatter`` (``core/comms.hpp:367``): elementwise
    reduce across ranks, then scatter equal chunks of axis 0."""
    expects(op == "sum", "reducescatter supports sum (psum_scatter)")
    return lax.psum_scatter(x, axis, tiled=True)


@_instrumented("bcast")
def bcast(x, root: int = 0, axis: str = DEFAULT_AXIS):
    """``comms_t::bcast`` (``core/comms.hpp:343``): every rank receives
    root's block."""
    gathered = lax.all_gather(x, axis)
    return jax.tree_util.tree_map(lambda g: g[root], gathered)


@_instrumented("reduce")
def reduce(x, root: int = 0, op: str = "sum", axis: str = DEFAULT_AXIS):
    """``comms_t::reduce``: reduction delivered to ``root``; other ranks get
    zeros (XLA collectives are symmetric, so we mask post-allreduce — same
    cost on ICI)."""
    full = allreduce(x, op=op, axis=axis)
    is_root = lax.axis_index(axis) == root
    return jax.tree_util.tree_map(lambda f: jnp.where(is_root, f, jnp.zeros_like(f)), full)


@_instrumented("ppermute")
def ppermute(x, perm: Sequence[tuple], axis: str = DEFAULT_AXIS):
    """Point-to-point ring/permutation send — the device p2p verb set
    (``comms_t::device_send/device_recv``) expressed as XLA's collective
    permute. ``perm`` is a list of (src, dst) pairs; ranks not named as a
    dst receive zeros."""
    return lax.ppermute(x, axis, perm)


@_instrumented("gather")
def gather(x, root: int = 0, axis: str = DEFAULT_AXIS):
    """``comms_t::gather`` (``core/comms.hpp:400``): root receives every
    rank's block stacked on a new leading axis; other ranks get zeros.
    XLA collectives are symmetric, so this is an all_gather + root mask —
    same ICI cost, and the mask keeps the verb's contract."""
    g = lax.all_gather(x, axis)
    is_root = lax.axis_index(axis) == root
    return jax.tree_util.tree_map(lambda a: jnp.where(is_root, a, jnp.zeros_like(a)), g)


@_instrumented("gatherv")
def gatherv(x, valid_n, root: int = 0, axis: str = DEFAULT_AXIS):
    """``comms_t::gatherv`` (``core/comms.hpp:417``): variable-size gather.
    XLA needs static shapes, so each rank contributes a padded block ``x
    [cap, ...]`` plus its true row count ``valid_n``; root receives
    ``(blocks [size, cap, ...], sizes [size])`` and other ranks zeros.
    Callers compact with the sizes (the raft recvcounts/displs analog)."""
    blocks = lax.all_gather(x, axis)
    sizes = lax.all_gather(jnp.asarray(valid_n, jnp.int32), axis)
    is_root = lax.axis_index(axis) == root
    mask = lambda a: jnp.where(is_root, a, jnp.zeros_like(a))  # noqa: E731
    return mask(blocks), mask(sizes)


@_instrumented("scatter")
def scatter(x, root: int = 0, axis: str = DEFAULT_AXIS):
    """Inverse of :func:`gather`: ``x [size, ...]`` on root (every rank
    passes the same-shaped buffer under SPMD); rank r receives block
    ``x_root[r]``. (The reference exposes this through raft-dask's
    scatter; the C++ iface covers it with device_send loops.)"""
    x_root = bcast(x, root=root, axis=axis)
    return jax.tree_util.tree_map(
        lambda a: lax.dynamic_index_in_dim(a, lax.axis_index(axis), 0, keepdims=False),
        x_root,
    )


@_instrumented("send_recv")
def send_recv(x, src: int, dst: int, axis: str = DEFAULT_AXIS):
    """One device p2p transfer (``comms_t::device_send``/``device_recv``
    pair, ``core/comms.hpp:506-540``): rank ``dst`` receives ``src``'s
    ``x``; every other rank (src included) gets zeros."""
    return lax.ppermute(x, axis, [(src, dst)])


@_instrumented("device_sendrecv")
def device_sendrecv(x, partner_of: Sequence[tuple], axis: str = DEFAULT_AXIS):
    """``comms_t::device_sendrecv`` (``core/comms.hpp:559``): simultaneous
    exchange — each (a, b) pair in ``partner_of`` ships a→b AND b→a in one
    collective permute."""
    perm = []
    for a, b in partner_of:
        perm.append((a, b))
        perm.append((b, a))
    return lax.ppermute(x, axis, perm)


@_instrumented("multicast_sendrecv")
def multicast_sendrecv(x, pairs: Sequence[tuple], axis: str = DEFAULT_AXIS):
    """``comms_t::device_multicast_sendrecv`` (``core/comms.hpp:580``):
    one source may feed several destinations — not a permutation, so XLA's
    ppermute cannot express it; an all_gather + per-rank source select
    does (one extra ICI hop vs NCCL's grouped sends)."""
    size = _axis_size(axis)
    src_of = np.full((size,), -1, np.int64)
    for s, d in pairs:
        src_of[d] = s
    g = lax.all_gather(x, axis)  # [size, ...]
    my_src = jnp.asarray(src_of, jnp.int32)[lax.axis_index(axis)]
    picked = jax.tree_util.tree_map(
        lambda a: lax.dynamic_index_in_dim(a, jnp.maximum(my_src, 0), 0, keepdims=False),
        g,
    )
    return jax.tree_util.tree_map(
        lambda a: jnp.where(my_src >= 0, a, jnp.zeros_like(a)), picked
    )


@_instrumented("barrier")
def barrier(axis: str = DEFAULT_AXIS):
    """``comms_t::barrier`` (``core/comms.hpp:389``): XLA programs are
    bulk-synchronous per collective, so a tiny psum is a true rendezvous.
    Returns a token array that must be consumed (data-dependence is what
    orders XLA programs)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


def comm_split(mesh: Mesh, axis: str) -> dict:
    """Split a multi-axis mesh into per-axis "sub-communicators"
    (``comms_t::comm_split``, ``core/comms.hpp:274``; SUB_COMMUNICATOR slot).

    In the mesh model a sub-communicator along ``axis`` is simply collectives
    over that axis name; this helper returns the axis metadata (name, size)
    callers use to target verbs at the sub-communicator.
    """
    expects(axis in mesh.axis_names, "axis %s not in mesh axes %s", axis, mesh.axis_names)
    return {"axis": axis, "size": mesh.shape[axis]}


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for replicated arrays on ``mesh``."""
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str = DEFAULT_AXIS) -> NamedSharding:
    """Sharding that splits axis 0 across ``axis``."""
    return NamedSharding(mesh, P(axis))
