"""Generation manifest: the single pointer that names the live index.

A mutable index directory holds *immutable* generation artifacts
(``gen-NNNNNNNN/`` snapshot dirs, per-generation WAL files) plus one
mutable file — ``MANIFEST.json`` — that names which generation is live.
Every artifact a manifest references is fully written and fsync'd
*before* the manifest swaps to it, and the swap itself is the v4
temp-fsync-rename idiom, so a crash at any instruction leaves the
directory loadable as either the old or the new generation — never a
hybrid. (This is the FusionANNS/LSM "publish by pointer flip"
discipline; compaction in :mod:`raft_tpu.mutable.compact` is its only
writer.)

The chaos seam ``manifest.swap`` (:mod:`raft_tpu.robust.faults`) fires
after the temp manifest is durable but before the rename: a kill there
must recover as the *old* generation, which ``tests/test_mutable.py``
verifies for every mutation kind.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

FILENAME = "MANIFEST.json"
FORMAT = 1


@dataclasses.dataclass(frozen=True)
class Manifest:
    """The live-generation pointer for one mutable index directory."""

    generation: int
    algo: str
    dim: int
    #: dir-relative path of the main-segment snapshot (None = empty main)
    main: Optional[str]
    #: dir-relative path of the raw-rows sidecar backing the main segment
    rows: Optional[str]
    #: dir-relative path of this generation's write-ahead log
    wal: str
    #: next auto-assigned global id as of this generation's compaction
    next_id: int = 0
    format: int = FORMAT

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "Manifest":
        doc = json.loads(text)
        if doc.get("format", 0) > FORMAT:
            raise ValueError(
                f"manifest format {doc.get('format')} is newer than supported {FORMAT}"
            )
        return Manifest(
            generation=int(doc["generation"]),
            algo=str(doc["algo"]),
            dim=int(doc["dim"]),
            main=doc.get("main"),
            rows=doc.get("rows"),
            wal=str(doc["wal"]),
            next_id=int(doc.get("next_id", 0)),
            format=int(doc.get("format", FORMAT)),
        )


def read(directory: str) -> Optional[Manifest]:
    """Load the live manifest, or None when the directory is fresh."""
    path = os.path.join(directory, FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return Manifest.from_json(f.read())


def swap(directory: str, manifest: Manifest) -> str:
    """Atomically publish ``manifest`` as the live generation.

    Temp-write + fsync + rename, with the ``manifest.swap`` fault seam
    between durability and visibility: everything the new manifest
    points at must already be on disk when this is called.
    """
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, FILENAME)
    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(manifest.to_json())
            f.flush()
            os.fsync(f.fileno())
        # chaos seam: a kill here leaves the old manifest live — the new
        # generation's files are orphans, not corruption
        from raft_tpu.robust import faults

        faults.fire("manifest.swap", generation=manifest.generation)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path
