"""raft_tpu.mutable — crash-consistent mutability over immutable indexes.

Segmented architecture (:mod:`~raft_tpu.mutable.segments`): a
generation-numbered main segment (any index type, tombstones masked
in-scan) plus a small brute-force delta segment for fresh rows; every
mutation is WAL-durable before it is visible
(:mod:`~raft_tpu.mutable.wal`); compaction rebuilds and atomically
publishes the next generation — foreground under the lock
(:mod:`~raft_tpu.mutable.compact`) or pinned-snapshot background with
catch-up replay (:mod:`~raft_tpu.mutable.maintenance`), both through
:mod:`~raft_tpu.mutable.manifest`. See ``docs/mutability.md``.
"""
from raft_tpu.mutable.compact import compact
from raft_tpu.mutable.maintenance import (
    CompactionPolicy,
    Compactor,
    compact_background,
)
from raft_tpu.mutable.manifest import Manifest
from raft_tpu.mutable.segments import MutableIndex, Snapshot
from raft_tpu.mutable.wal import WalRecord, WriteAheadLog, replay, segment_paths

__all__ = [
    "CompactionPolicy",
    "Compactor",
    "Manifest",
    "MutableIndex",
    "Snapshot",
    "WalRecord",
    "WriteAheadLog",
    "compact",
    "compact_background",
    "replay",
    "segment_paths",
]
