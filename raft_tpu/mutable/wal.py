"""Crash-consistent write-ahead log for mutable indexes.

Every mutation (insert/delete/upsert) is **durable before it is
visible**: the op is framed, CRC32-checksummed, appended, flushed, and
fsync'd to the generation's WAL *before* the in-memory delta segment or
tombstone bitset changes (the Faiss add-with-ids/remove story recast
for a process that can die at any instruction). A reader recovering
after a crash replays whatever prefix of the log survived: the frame
discipline is the same envelope idea as serialization v4
(:func:`raft_tpu.core.serialize.save_stream`) — length + CRC ahead of
the payload — applied per record, so a torn tail (partial header,
partial payload, or bit rot) truncates cleanly to the last whole
record instead of poisoning the whole log.

Frame layout (little-endian)::

    b"WALR" | u32 payload_len | u32 crc32(payload) | payload

Payload: ``op`` string, ``ids`` int64 array, has-vectors flag, and the
``vectors`` float array when the op carries rows, all via the
:mod:`raft_tpu.core.serialize` primitives.

The chaos seam ``wal.append`` (:mod:`raft_tpu.robust.faults`) fires
twice per append — ``stage="pre"`` before any byte is written (a crash
here loses the mutation entirely: pre-state on recovery) and
``stage="post"`` after the fsync (the mutation is durable but the
caller never saw it applied: post-state on recovery). Both outcomes
are legal; a *mixed* state is not, and ``tests/test_mutable.py`` kills
at both stages for every mutation kind to prove it.
"""
from __future__ import annotations

import dataclasses
import io
import os
import struct
import zlib
from typing import BinaryIO, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core import serialize as ser
from raft_tpu.core.errors import expects

_REC_MAGIC = b"WALR"
_HEADER = struct.Struct("<4sII")  # magic, payload_len, crc32

#: mutation kinds a WAL record may carry
OPS = ("insert", "delete", "upsert")


@dataclasses.dataclass(frozen=True)
class WalRecord:
    """One durable mutation: the op kind, the global ids it touches,
    and (for insert/upsert) the rows themselves."""

    op: str
    ids: np.ndarray  # int64[n]
    vectors: Optional[np.ndarray] = None  # float32[n, dim] for insert/upsert

    def encode(self) -> bytes:
        buf = io.BytesIO()
        ser.serialize_string(buf, self.op)
        ser.serialize_array(buf, np.asarray(self.ids, np.int64))
        ser.serialize_scalar(buf, int(self.vectors is not None), "uint32")
        if self.vectors is not None:
            ser.serialize_array(buf, np.asarray(self.vectors, np.float32))
        return buf.getvalue()

    @staticmethod
    def decode(payload: bytes) -> "WalRecord":
        buf = io.BytesIO(payload)
        op = ser.deserialize_string(buf)
        ids = np.asarray(ser.deserialize_array(buf))
        has_vecs = bool(ser.deserialize_scalar(buf, "uint32"))
        vectors = np.asarray(ser.deserialize_array(buf)) if has_vecs else None
        return WalRecord(op=op, ids=ids, vectors=vectors)


def replay(path: str) -> Tuple[List[WalRecord], int]:
    """Read the longest valid prefix of the log at ``path``.

    Returns ``(records, good_offset)`` where ``good_offset`` is the byte
    offset just past the last whole, CRC-clean frame. Anything beyond it
    is a torn tail (truncated header, truncated payload, magic or CRC
    damage) — counted in ``mutable.wal.torn_tail_bytes`` and meant to be
    truncated away by :meth:`WriteAheadLog.open`. A missing file is an
    empty log.
    """
    records: List[WalRecord] = []
    good = 0
    if not os.path.exists(path):
        return records, good
    with open(path, "rb") as f:
        data = f.read()
    n = len(data)
    while good < n:
        head = data[good : good + _HEADER.size]
        if len(head) < _HEADER.size:
            break
        magic, length, crc = _HEADER.unpack(head)
        if magic != _REC_MAGIC:
            break
        payload = data[good + _HEADER.size : good + _HEADER.size + length]
        if len(payload) < length or zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            records.append(WalRecord.decode(payload))
        except Exception:
            # a frame whose CRC passes but whose payload cannot decode is
            # still a torn/foreign tail — stop at the last good record
            break
        good += _HEADER.size + length
    torn = n - good
    if torn and obs.is_enabled():
        obs.inc("mutable.wal.torn_tail_bytes", float(torn))
    return records, good


class WriteAheadLog:
    """Append-only durable mutation log (one per index generation).

    Use :meth:`open` — it replays the valid prefix, truncates any torn
    tail, and positions the write cursor for appends.
    """

    def __init__(self, path: str, fh: BinaryIO, offset: int):
        self.path = path
        self._fh = fh
        self._offset = offset

    @classmethod
    def open(cls, path: str) -> Tuple["WriteAheadLog", List[WalRecord]]:
        """Open (creating if missing) the log at ``path``; returns the
        log plus the records recovered from its valid prefix."""
        records, good = replay(path)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # "a+b" creates when missing; reopen r+b to truncate a torn tail
        fh = open(path, "a+b")
        fh.seek(0, os.SEEK_END)
        if fh.tell() != good:
            fh.close()
            fh = open(path, "r+b")
            fh.truncate(good)
            fh.seek(good)
            fh.flush()
            os.fsync(fh.fileno())
        if obs.is_enabled() and records:
            obs.inc("mutable.wal.replayed", float(len(records)))
        return cls(path, fh, good), records

    @property
    def offset(self) -> int:
        return self._offset

    def append(self, record: WalRecord) -> int:
        """Make ``record`` durable (write + flush + fsync); returns the
        offset past the appended frame. The caller applies the mutation
        to the in-memory segments only after this returns."""
        expects(record.op in OPS, "unknown WAL op %r", record.op)
        payload = record.encode()
        frame = _HEADER.pack(_REC_MAGIC, len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
        # chaos seam: a crash before any byte lands loses the mutation
        # (pre-state on recovery) ...
        from raft_tpu.robust import faults

        faults.fire("wal.append", op=record.op, stage="pre")
        self._fh.seek(self._offset)
        self._fh.write(frame)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._offset += len(frame)
        # ... and a crash after the fsync leaves it durable but
        # unacknowledged (post-state on recovery)
        faults.fire("wal.append", op=record.op, stage="post")
        if obs.is_enabled():
            obs.inc("mutable.wal.records", op=record.op)
            obs.inc("mutable.wal.bytes", float(len(frame)))
        return self._offset

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # graft-lint: ignore[silent-except] — double-close on teardown is benign
            pass
