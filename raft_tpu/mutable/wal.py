"""Crash-consistent write-ahead log for mutable indexes.

Every mutation (insert/delete/upsert) is **durable before it is
visible**: the op is framed, CRC32-checksummed, appended, flushed, and
fsync'd to the generation's WAL *before* the in-memory delta segment or
tombstone bitset changes (the Faiss add-with-ids/remove story recast
for a process that can die at any instruction). A reader recovering
after a crash replays whatever prefix of the log survived: the frame
discipline is the same envelope idea as serialization v4
(:func:`raft_tpu.core.serialize.save_stream`) — length + CRC ahead of
the payload — applied per record, so a torn tail (partial header,
partial payload, or bit rot) truncates cleanly to the last whole
record instead of poisoning the whole log.

Frame layout (little-endian)::

    b"WALR" | u32 payload_len | u32 crc32(payload) | payload

Payload: ``op`` string, ``ids`` int64 array, has-vectors flag, and the
``vectors`` float array when the op carries rows, all via the
:mod:`raft_tpu.core.serialize` primitives.

The chaos seam ``wal.append`` (:mod:`raft_tpu.robust.faults`) fires
twice per append — ``stage="pre"`` before any byte is written (a crash
here loses the mutation entirely: pre-state on recovery) and
``stage="post"`` after the fsync (the mutation is durable but the
caller never saw it applied: post-state on recovery). Both outcomes
are legal; a *mixed* state is not, and ``tests/test_mutable.py`` kills
at both stages for every mutation kind to prove it.

Segment rotation (``max_bytes``): a long-lived generation would
otherwise grow one unbounded log file whose full replay cost every
reopen pays. When ``max_bytes`` is set, :meth:`WriteAheadLog.append`
rotates at a *frame boundary* — the active segment is sealed
(flush + fsync + close) and a fresh ``<path>.NNNNNN`` segment opens —
whenever the next frame would push the segment past the limit (a
single oversized frame still lands whole; frames are never split).
:meth:`WriteAheadLog.open` replays all segments in sequence order.
Only the *last* (active) segment may legally carry a torn tail —
sealed segments were fsync'd before rotation — so a tear found in a
sealed segment orphans every later segment (they were written after
the tear and are outside the longest-valid-prefix contract).
"""
from __future__ import annotations

import dataclasses
import io
import os
import struct
import zlib
from typing import BinaryIO, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core import serialize as ser
from raft_tpu.core.errors import expects

_REC_MAGIC = b"WALR"
_HEADER = struct.Struct("<4sII")  # magic, payload_len, crc32

#: mutation kinds a WAL record may carry
OPS = ("insert", "delete", "upsert")


@dataclasses.dataclass(frozen=True)
class WalRecord:
    """One durable mutation: the op kind, the global ids it touches,
    and (for insert/upsert) the rows themselves."""

    op: str
    ids: np.ndarray  # int64[n]
    vectors: Optional[np.ndarray] = None  # float32[n, dim] for insert/upsert

    def encode(self) -> bytes:
        buf = io.BytesIO()
        ser.serialize_string(buf, self.op)
        ser.serialize_array(buf, np.asarray(self.ids, np.int64))
        ser.serialize_scalar(buf, int(self.vectors is not None), "uint32")
        if self.vectors is not None:
            ser.serialize_array(buf, np.asarray(self.vectors, np.float32))
        return buf.getvalue()

    @staticmethod
    def decode(payload: bytes) -> "WalRecord":
        buf = io.BytesIO(payload)
        op = ser.deserialize_string(buf)
        ids = np.asarray(ser.deserialize_array(buf))
        has_vecs = bool(ser.deserialize_scalar(buf, "uint32"))
        vectors = np.asarray(ser.deserialize_array(buf)) if has_vecs else None
        return WalRecord(op=op, ids=ids, vectors=vectors)


def _segment_path(path: str, seq: int) -> str:
    """Segment ``seq`` of the log rooted at ``path``: the base file is
    segment 0 (backwards compatible with pre-rotation logs), rotations
    append ``.000001``, ``.000002``, ..."""
    return path if seq == 0 else f"{path}.{seq:06d}"


def _list_segments(path: str) -> List[Tuple[int, str]]:
    """All on-disk segments of the log at ``path`` in sequence order.
    Always includes segment 0 (even when the file does not exist yet) so
    callers have an active segment to create."""
    seqs = {0}
    parent = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path)
    if os.path.isdir(parent):
        for name in os.listdir(parent):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1 :]
                if suffix.isdigit():
                    seqs.add(int(suffix))
    return [(s, _segment_path(path, s)) for s in sorted(seqs)]


def segment_paths(path: str) -> List[str]:
    """Existing segment files of the log at ``path`` (for cleanup when a
    generation is superseded)."""
    return [sp for _, sp in _list_segments(path) if os.path.exists(sp)]


def replay(path: str, start: int = 0) -> Tuple[List[WalRecord], int]:
    """Read the longest valid prefix of the log at ``path``.

    Returns ``(records, good_offset)`` where ``good_offset`` is the byte
    offset just past the last whole, CRC-clean frame. Anything beyond it
    is a torn tail (truncated header, truncated payload, magic or bit
    damage) — counted in ``mutable.wal.torn_tail_bytes`` and meant to be
    truncated away by :meth:`WriteAheadLog.open`. A missing file is an
    empty log. ``start`` skips to a byte offset that must sit on a frame
    boundary (e.g. one recorded by :meth:`WriteAheadLog.position`) —
    background compaction uses it to read only the records that landed
    after its pin.
    """
    records: List[WalRecord] = []
    good = start
    if not os.path.exists(path):
        return records, good
    with open(path, "rb") as f:
        data = f.read()
    n = len(data)
    while good < n:
        head = data[good : good + _HEADER.size]
        if len(head) < _HEADER.size:
            break
        magic, length, crc = _HEADER.unpack(head)
        if magic != _REC_MAGIC:
            break
        payload = data[good + _HEADER.size : good + _HEADER.size + length]
        if len(payload) < length or zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            records.append(WalRecord.decode(payload))
        except Exception:
            # a frame whose CRC passes but whose payload cannot decode is
            # still a torn/foreign tail — stop at the last good record
            break
        good += _HEADER.size + length
    torn = max(n - good, 0)
    if torn and obs.is_enabled():
        obs.inc("mutable.wal.torn_tail_bytes", float(torn))
    return records, good


class WriteAheadLog:
    """Append-only durable mutation log (one per index generation).

    Use :meth:`open` — it replays the valid prefix, truncates any torn
    tail, and positions the write cursor for appends.
    """

    def __init__(
        self,
        path: str,
        fh: BinaryIO,
        offset: int,
        max_bytes: Optional[int] = None,
        seq: int = 0,
    ):
        self.path = path  # base path; the active segment is _segment_path(path, seq)
        self._fh = fh
        self._offset = offset  # write cursor within the active segment
        self._max_bytes = max_bytes
        self._seq = seq
        #: durable records in this log (recovered at open + appended
        #: since) — the leader side of replica staleness accounting
        self._records = 0

    @classmethod
    def open(
        cls, path: str, max_bytes: Optional[int] = None
    ) -> Tuple["WriteAheadLog", List[WalRecord]]:
        """Open (creating if missing) the log at ``path``; returns the
        log plus the records recovered from its valid prefix. Rotated
        segments replay in sequence order; only the last may carry a
        torn tail (it is truncated away) — a tear in a *sealed* segment
        stops recovery there and unlinks the later, orphaned segments.
        ``max_bytes`` arms size-triggered rotation for future appends."""
        segments = _list_segments(path)
        records: List[WalRecord] = []
        seq, seg_path, good = segments[0][0], segments[0][1], 0
        for i, (sq, sp) in enumerate(segments):
            recs, sp_good = replay(sp)
            records.extend(recs)
            seq, seg_path, good = sq, sp, sp_good
            size = os.path.getsize(sp) if os.path.exists(sp) else 0
            if sp_good != size and i != len(segments) - 1:
                for _, orphan in segments[i + 1 :]:
                    try:
                        os.unlink(orphan)
                    except OSError:  # graft-lint: ignore[silent-except] — orphan cleanup is advisory
                        pass
                break
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # "a+b" creates when missing; reopen r+b to truncate a torn tail
        fh = open(seg_path, "a+b")
        fh.seek(0, os.SEEK_END)
        if fh.tell() != good:
            fh.close()
            fh = open(seg_path, "r+b")
            fh.truncate(good)
            fh.seek(good)
            fh.flush()
            os.fsync(fh.fileno())
        if obs.is_enabled() and records:
            obs.inc("mutable.wal.replayed", float(len(records)))
        log = cls(path, fh, good, max_bytes=max_bytes, seq=seq)
        log._records = len(records)
        return log, records

    @property
    def offset(self) -> int:
        return self._offset

    @property
    def segment(self) -> int:
        """Sequence number of the active segment."""
        return self._seq

    def segment_paths(self) -> List[str]:
        """Existing segment files of this log, sequence order."""
        return segment_paths(self.path)

    def record_count(self) -> int:
        """Durable records in this log: the valid prefix recovered at
        :meth:`open` plus everything appended since. Replication reads
        this as the leader high-water mark when computing
        ``replica.staleness_records`` (``docs/replication.md``)."""
        return self._records

    def seal(self) -> bool:
        """Explicitly seal the active segment so its frames become
        shippable (:mod:`raft_tpu.replica.shipping` never reads the
        active segment — only sealed ones, which are immutable and end
        on a frame boundary). A no-op on an empty active segment:
        rotating then would mint empty sealed files. Returns True when
        a rotation actually happened. Counted in ``mutable.wal.seals``."""
        if self._offset == 0:
            return False
        self._rotate()
        if obs.is_enabled():
            obs.inc("mutable.wal.seals")
        return True

    def sealed_segments(self) -> List[Tuple[int, str]]:
        """The immutable ``(seq, path)`` segments of this log — every
        on-disk segment strictly before the active one. Sealed segments
        were flushed + fsync'd at rotation and are never written again,
        so a shipper may read them without racing :meth:`append`; a torn
        frame found in one is transport/storage damage, never an
        in-progress write."""
        return [
            (sq, sp)
            for sq, sp in _list_segments(self.path)
            if sq < self._seq and os.path.exists(sp)
        ]

    def position(self) -> Tuple[int, int]:
        """The durable high-water mark ``(segment, offset)`` — always a
        frame boundary. Background compaction records it at pin time;
        :meth:`read_from` later returns exactly the records appended
        after it."""
        return (self._seq, self._offset)

    def read_from(self, pos: Tuple[int, int]) -> List[WalRecord]:
        """Every record appended after ``pos`` (a :meth:`position`
        result): the tail of that segment plus all later segments, in
        order. The durable source of truth for compaction catch-up —
        what landed on disk is what replays, regardless of what any
        in-memory view saw."""
        seq0, off0 = pos
        records: List[WalRecord] = []
        for sq, sp in _list_segments(self.path):
            if sq < seq0:
                continue
            recs, _ = replay(sp, start=off0 if sq == seq0 else 0)
            records.extend(recs)
        return records

    def total_bytes(self) -> int:
        """Bytes on disk across all segments — the ``wal_bytes``
        auto-compaction trigger reads this."""
        total = 0
        for sp in self.segment_paths():
            try:
                total += os.path.getsize(sp)
            except OSError:  # graft-lint: ignore[silent-except] — raced unlink; size is advisory
                pass
        return total

    def _rotate(self) -> None:
        """Seal the active segment and start the next one. Called only
        at a frame boundary, so the sealed file ends on a whole record;
        the directory entry for the new segment is fsync'd so a crash
        right after rotation recovers the sealed prefix plus an empty
        (or torn-tail-truncated) active segment — never a gap."""
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._seq += 1
        nxt = _segment_path(self.path, self._seq)
        fh = open(nxt, "a+b")
        fh.seek(0, os.SEEK_END)
        dfd = os.open(os.path.dirname(os.path.abspath(nxt)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._fh = fh
        self._offset = fh.tell()
        if obs.is_enabled():
            obs.inc("mutable.wal.rotations")
            obs.set_gauge("mutable.wal.segments", float(self._seq + 1))

    def append(self, record: WalRecord) -> int:
        """Make ``record`` durable (write + flush + fsync); returns the
        offset past the appended frame within the active segment. The
        caller applies the mutation to the in-memory segments only
        after this returns."""
        expects(record.op in OPS, "unknown WAL op %r", record.op)
        payload = record.encode()
        frame = _HEADER.pack(_REC_MAGIC, len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
        # chaos seam: a crash before any byte lands loses the mutation
        # (pre-state on recovery) ...
        from raft_tpu.robust import faults

        faults.fire("wal.append", op=record.op, stage="pre")
        if (
            self._max_bytes is not None
            and self._offset > 0
            and self._offset + len(frame) > self._max_bytes
        ):
            self._rotate()
        self._fh.seek(self._offset)
        self._fh.write(frame)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._offset += len(frame)
        self._records += 1
        # ... and a crash after the fsync leaves it durable but
        # unacknowledged (post-state on recovery)
        faults.fire("wal.append", op=record.op, stage="post")
        if obs.is_enabled():
            obs.inc("mutable.wal.records", op=record.op)
            obs.inc("mutable.wal.bytes", float(len(frame)))
        return self._offset

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # graft-lint: ignore[silent-except] — double-close on teardown is benign
            pass
