"""Background maintenance: serve through rebuilds, never under them.

Foreground :func:`raft_tpu.mutable.compact.compact` holds the index
lock for the whole rebuild — correct, but a writer or fresh snapshot
queued behind it eats the entire build time (the ``p99_compact_ms``
column of the ``mutable_churn`` bench row). This module is the serving
mode: :func:`compact_background` rebuilds against a **pinned snapshot**
while writers and searchers proceed under the existing lock, and
re-enters the lock only twice, briefly:

1. **Pin** (lock held, microseconds): fire ``compact.pin``, copy the
   live rows, record the WAL high-water mark
   (:meth:`~raft_tpu.mutable.wal.WriteAheadLog.position`), and arm the
   in-memory mutation capture. From here on, every insert/delete/upsert
   lands in the *old* generation's WAL (durable) and the live delta as
   usual — nothing blocks.
2. **Rebuild** (no lock, the long part): build the new main segment
   over the pinned rows and write the new generation's artifacts
   through the atomic writers. Concurrent mutations accumulate behind
   the pin.
3. **Catch-up + flip** (lock held, proportional to the *backlog*, not
   the corpus): fire ``compact.replay``, read every record that landed
   after the pin — from the WAL for a durable index (the disk is the
   source of truth), from the capture list for ``directory=None`` —
   append them to the **new** generation's WAL (fsync'd, so they are
   durable in the new world *before* it becomes visible), fire
   ``compact.flip``, swap the manifest, switch the in-memory segments
   to the rebuilt main, and re-apply the backlog to the fresh delta.

Crash matrix (the chaos gate in ``tests/test_mutable.py``): a kill at
``compact.pin``, during the rebuild, at ``compact.replay``, at
``compact.flip``, or at the inner ``manifest.swap`` leaves the old
manifest live — cold recovery replays the old WAL, which contains every
mid-rebuild mutation, so the index recovers the exact pre-compaction
state *including* those mutations. Only after the rename lands is the
new generation visible, and it is complete by construction: pinned rows
+ replayed backlog. There is no crash point that yields a hybrid, and a
retried attempt reclaims the same generation number (stale catch-up WAL
segments from the dead attempt are cleared before the path goes live).

:class:`Compactor` runs this on a dedicated worker thread with the
seeded backoff of :mod:`raft_tpu.robust.retry`; the ``compact.worker``
seam injects worker-thread death, and :meth:`Compactor.tick` is the
watchdog that restarts a dead worker without losing the pending
request. :class:`CompactionPolicy` turns the existing counters (WAL
bytes, delta rows, tombstone fraction) into auto-compaction triggers;
``ServingEngine`` calls :meth:`Compactor.tick` from its step loop so a
churning index compacts itself without an operator call.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Optional

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.mutable import segments as seg
# NB: import names, not the module — the package __init__ rebinds the
# ``compact`` attribute to the function, shadowing the submodule
from raft_tpu.mutable.compact import (
    COMPACT_RETRY_POLICY,
    _cleanup_old_generation,
    _clear_stale_wal,
    _note_compaction,
    _publish,
    _switch_memory,
    _write_generation,
)
from raft_tpu.mutable.wal import WriteAheadLog
from raft_tpu.robust import faults
from raft_tpu.utils import lockcheck
from raft_tpu.robust.retry import RetryError, RetryPolicy, retry_call


def compact_background(
    mut: "seg.MutableIndex",
    res=None,
    _mid_rebuild: Optional[Callable[[], None]] = None,
) -> int:
    """One pin → rebuild-off-lock → catch-up+flip compaction of ``mut``
    on the calling thread. Returns the new generation number.

    ``_mid_rebuild`` is a test seam: a callable invoked after the new
    generation's artifacts are written but before the catch-up replay,
    i.e. the deterministic stand-in for "mutations arrive while the
    rebuild runs" that the chaos matrix and the bit-for-bit freshness
    gate drive. Production callers leave it ``None``.
    """
    t0 = time.perf_counter()
    with mut._compact_mutex:
        # -- phase 1: pin (brief lock) ---------------------------------
        with mut._lock:
            faults.fire("compact.pin", generation=mut.generation + 1)
            old_gen = mut.generation
            new_gen = old_gen + 1
            ids, vecs = mut.live_rows()
            old_wal_path = mut.wal.path if mut.wal is not None else None
            wal_pos = mut.wal.position() if mut.wal is not None else None
            mut._capture = []
        try:
            # -- phase 2: rebuild, no lock held ------------------------
            # writers and searchers proceed; their mutations go to the
            # old WAL (durable) and the live delta, and pile up behind
            # the pin for the catch-up below
            faults.fire("compact.merge", generation=new_gen, rows=len(ids))
            # only _compact_mutex is held here — declared may_block in
            # lock_order.toml (it serializes whole compactions by
            # design); writers/searchers contend on _lock, which is free
            index = (
                seg._build_main(mut.algo, vecs, mut.index_params, mut.metric)
                if len(ids)
                else None
            )
            rows_rel = main_rel = None
            if mut.directory is not None:
                rows_rel, main_rel = _write_generation(
                    mut, new_gen, ids, vecs, index
                )
            if _mid_rebuild is not None:
                _mid_rebuild()
            # -- phase 3: catch-up + flip (brief lock) -----------------
            with mut._lock:
                faults.fire("compact.replay", generation=new_gen)
                if mut.wal is not None:
                    # durable source of truth: exactly the frames that
                    # landed on disk after the pin
                    records = mut.wal.read_from(wal_pos)
                else:
                    records = list(mut._capture)
                # replay must not re-capture itself
                mut._capture = None
                new_wal = None
                if mut.directory is not None:
                    new_wal_path = os.path.join(
                        mut.directory, seg._wal_name(new_gen)
                    )
                    _clear_stale_wal(new_wal_path)
                    new_wal, _ = WriteAheadLog.open(
                        new_wal_path, max_bytes=mut.max_wal_bytes
                    )
                    for rec in records:
                        # durable in the new world before it is visible:
                        # a crash past the flip recovers these from the
                        # new WAL, a crash before it from the old one
                        new_wal.append(rec)
                faults.fire("compact.flip", generation=new_gen)
                if mut.directory is not None:
                    _publish(mut, new_gen, rows_rel, main_rel)
                pending_cleanup = _switch_memory(
                    mut, new_gen, ids, vecs, index, res=res,
                    old_wal_path=old_wal_path, new_wal=new_wal,
                )
                replayed = 0
                for rec in records:
                    mut._apply(rec)
                    replayed += len(rec.ids)
                mut._snap = None
                if obs.is_enabled():
                    obs.observe(
                        "mutable.compact.replayed_rows", float(replayed),
                        index=mut.name,
                    )
                _note_compaction(mut, "background", len(ids), t0)
            # the old generation is unreferenced once the flip landed;
            # delete it outside _lock so no writer queues behind rmtree
            if pending_cleanup is not None:
                _cleanup_old_generation(*pending_cleanup)
            return new_gen
        finally:
            # on success phase 3 already cleared it; on any failure the
            # index must stop capturing (and drop the backlog copy) —
            # under _lock, or a writer mid-append could capture into the
            # list an instant after this clears it
            with mut._lock:
                mut._capture = None


@dataclasses.dataclass(frozen=True)
class CompactionPolicy:
    """Auto-compaction triggers over the counters a
    :class:`~raft_tpu.mutable.segments.MutableIndex` already maintains.
    A threshold of ``None`` disables that trigger; the first one that
    trips names the reason. ``min_interval_s`` rate-limits back-to-back
    compactions regardless of triggers."""

    #: total on-disk WAL bytes (all segments) before a compaction
    wal_bytes: Optional[int] = None
    #: live delta-segment rows before a compaction
    delta_rows: Optional[int] = None
    #: dead/total fraction across both segments before a compaction
    tombstone_fraction: Optional[float] = None
    #: floor between *completed* compactions
    min_interval_s: float = 0.0

    def reason(self, mut: "seg.MutableIndex") -> Optional[str]:
        """The name of the first tripped trigger, or ``None``."""
        if self.delta_rows is not None and mut.delta_rows >= self.delta_rows:
            return "delta_rows"
        if (
            self.tombstone_fraction is not None
            and mut.tombstone_fraction >= self.tombstone_fraction
            and mut.tombstone_fraction > 0.0
        ):
            return "tombstone_fraction"
        if (
            self.wal_bytes is not None
            and mut.wal is not None
            and mut.wal.total_bytes() >= self.wal_bytes
        ):
            return "wal_bytes"
        return None


@lockcheck.guarded_fields
class Compactor:
    """Background compaction worker for one mutable index.

    A dedicated daemon thread waits for requests (explicit
    :meth:`request` or :class:`CompactionPolicy` triggers observed by
    :meth:`tick`) and runs :func:`compact_background` through the
    seeded retry machinery. The worker beats the
    ``mutable.maintenance.heartbeat`` gauge every loop; :meth:`tick` is
    also the watchdog — a worker killed mid-flight (the
    ``compact.worker`` chaos seam) is restarted with its request
    re-armed, so an injected thread death delays a compaction but never
    loses it.

    >>> comp = Compactor(mut, policy=CompactionPolicy(delta_rows=10_000))
    >>> comp.start()
    >>> ...                    # serve; call comp.tick() periodically
    >>> comp.stop()
    """

    def __init__(
        self,
        mut: "seg.MutableIndex",
        *,
        policy: Optional[CompactionPolicy] = None,
        retry_policy: Optional[RetryPolicy] = None,
        res=None,
        seed: int = 0,
        name: Optional[str] = None,
        poll_interval_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
    ):
        expects(poll_interval_s > 0, "poll_interval_s must be positive")
        self.mut = mut
        self.policy = policy
        self.name = name or mut.name
        self._retry_policy = (
            retry_policy if retry_policy is not None
            else COMPACT_RETRY_POLICY
        )
        self._res = res
        self._seed = int(seed)
        self._poll_interval_s = float(poll_interval_s)
        self._clock = clock
        # leaf lock (lock_order.toml: "compactor.state"): guards only
        # the pending/busy/thread flags, never held across — nor taken
        # under — the index locks; the lockcheck witness enforces that
        self._state_lock = lockcheck.tracked(
            threading.Lock(), "compactor.state"
        )
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending = False
        self._busy = False
        self._beats = 0
        #: completed / failed-after-retries compaction runs
        self.completed = 0
        self.failed = 0
        self.worker_restarts = 0
        #: the last run's terminal error (None after a success)
        self.last_error: Optional[BaseException] = None
        self._last_done_t: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start (or no-op if already running) the worker thread."""
        with self._state_lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"compactor-{self.name}", daemon=True
            )
            self._thread.start()

    def stop(self, wait: bool = True, timeout_s: float = 5.0) -> None:
        """Signal the worker to exit; with ``wait`` join it. A rebuild
        in flight completes (or fails) first — stop never tears a
        compaction."""
        self._stop.set()
        self._wake.set()
        t = self._thread
        if wait and t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- requests ----------------------------------------------------------

    def request(self, reason: str = "manual") -> bool:
        """Ask for one compaction (coalesced: a request while one is
        pending is a no-op). Returns True when newly armed."""
        with self._state_lock:
            if self._pending:
                return False
            self._pending = True
        obs.inc("mutable.compact.requested", index=self.name, reason=reason)
        self._wake.set()
        return True

    def busy(self) -> bool:
        """True while a request is pending or a rebuild is in flight."""
        with self._state_lock:
            return self._pending or self._busy

    def backlog(self) -> int:
        """Pending requests + in-flight rebuilds (0..2)."""
        with self._state_lock:
            return int(self._pending) + int(self._busy)

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block (polling, ticking the watchdog) until no work is
        pending or in flight; True on idle, False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.tick()
            if not self.busy():
                return True
            time.sleep(0.002)
        return False

    # -- the maintenance tick (watchdog + policy) --------------------------

    def tick(self) -> Optional[str]:
        """One maintenance heartbeat, called from the serving loop:
        restart a dead worker (re-arming its interrupted request),
        evaluate the auto-compaction policy, and publish the backlog
        gauge. Returns the policy trigger that fired, if any."""
        restart = False
        with self._state_lock:
            t = self._thread
            if t is not None and not t.is_alive() and not self._stop.is_set():
                # the worker died mid-flight (chaos injection or a bug
                # past the retry net): don't lose the request it held
                if self._busy:
                    self._busy = False
                    self._pending = True
                self._thread = None
                restart = True
        if restart:
            with self._state_lock:
                self.worker_restarts += 1
            obs.inc("mutable.maintenance.worker_restarts", index=self.name)
            # flight-recorder trigger: rides the same outside-lock spot
            # as the restart counter
            obs.recorder.note_worker_death(self.name)
            self.start()
        reason = None
        if self.policy is not None and not self.busy() and not self._stop.is_set():
            with self._state_lock:
                last_done = self._last_done_t
            interval_ok = (
                last_done is None
                or self.policy.min_interval_s <= 0
                or self._clock() - last_done >= self.policy.min_interval_s
            )
            if interval_ok:
                reason = self.policy.reason(self.mut)
                if reason is not None:
                    self.request(reason=reason)
        obs.set_gauge("mutable.compact.backlog", float(self.backlog()), index=self.name)
        return reason

    # -- the worker --------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._state_lock:
                self._beats += 1
                beats = self._beats
            obs.set_gauge(
                "mutable.maintenance.heartbeat", float(beats), index=self.name
            )
            self._wake.wait(self._poll_interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            with self._state_lock:
                pending = self._pending
                if pending:
                    self._pending = False
                    self._busy = True
            if not pending:
                continue
            # chaos seam: a raise here escapes the loop and kills the
            # worker thread while it owns the request — tick()'s
            # watchdog must restart it and re-arm the request
            faults.fire("compact.worker", index=self.name)
            try:
                self._run_one()
            finally:
                with self._state_lock:
                    self._busy = False

    def _run_one(self) -> None:
        attempts = {"n": 0}

        def _attempt():
            attempts["n"] += 1
            if attempts["n"] > 1:
                obs.inc("mutable.compact.retries", index=self.name, mode="background")
            return compact_background(self.mut, res=self._res)

        with self._state_lock:
            seed = self._seed + self.completed + self.failed
        try:
            retry_call(
                _attempt,
                policy=self._retry_policy,
                op="mutable.compact.background",
                seed=seed,
            )
            with self._state_lock:
                self.completed += 1
                self.last_error = None
                self._last_done_t = self._clock()
        except RetryError as e:
            with self._state_lock:
                self.failed += 1
                self.last_error = e.last
                self._last_done_t = self._clock()
            obs.inc(
                "mutable.compact.failed", index=self.name,
                error=type(e.last).__name__,
            )


__all__ = ["CompactionPolicy", "Compactor", "compact_background"]
