"""Segmented mutable index: main segment + delta segment + tombstones.

A production corpus is never static, but every raft_tpu index type is an
immutable XLA buffer built once. This module recasts the Faiss
add-with-ids/remove story for that constraint the LSM way — an index
becomes a generation-numbered **segment list**:

* the **main segment** is one ordinary immutable index (brute-force /
  IVF-Flat / IVF-PQ / CAGRA) over the rows that existed at the last
  compaction, plus a positional tombstone bitset
  (:class:`raft_tpu.core.bitset.Bitset`) passed *in-scan* as the index's
  ``prefilter`` — deletes mask candidates inside the kernels, before the
  k-way merge, so a dead row can never shadow a live one;
* the **delta segment** is a small append-only brute-force segment
  holding rows inserted since that compaction (served exactly), with its
  own live-mask; its row count is padded to a power of two so the
  jitted delta scan compiles ``log2`` programs, not one per insert;
* a **global id space** (int64, user-supplied or auto-assigned) maps
  onto (segment, position) so results from both segments merge into one
  best-first list.

Durability: every mutation is appended to the generation's write-ahead
log (:mod:`raft_tpu.mutable.wal`) — durable *then* visible — and
:func:`raft_tpu.mutable.compact.compact` folds delta + tombstones into
a rebuilt main segment published via an atomic manifest swap
(:mod:`raft_tpu.mutable.manifest`). :meth:`MutableIndex.snapshot`
returns an immutable, internally consistent :class:`Snapshot` that the
serving engine dispatches against, so queries in flight never observe a
half-applied mutation.
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.mutable import manifest as man
from raft_tpu.utils import lockcheck
from raft_tpu.mutable.wal import WalRecord, WriteAheadLog
from raft_tpu.ops.distance import DistanceType, is_min_close, resolve_metric

ALGOS = ("brute_force", "ivf_flat", "ivf_pq", "cagra")

#: delta-scan routing knobs accepted by ``delta_mode``
DELTA_MODES = ("auto", "exact", "fused")

#: The fused delta scan is lossless only while every merge bank holds a
#: single 128-lane group (see ``ops.pallas.ivf_scan._seg_compress``):
#: with the ``bank8`` merge that caps ONE kernel call at 8 * 128 padded
#: rows. Past that the delta is tiled into multiple 1024-row banks, each
#: scanned by its own (identically-shaped, so compiled-once) kernel call
#: inside the lossless window, and the per-bank top-k lists are k-way
#: merged on the accelerator by one stable sort — so routing through the
#: kernel keeps *bitwise* candidate parity with the exact XLA scan at
#: any banked size, rather than the approximate-top-k semantics the big
#: fused indexes accept.
_DELTA_FUSED_MAX_ROWS = 1024
_DELTA_FUSED_QT = 128
#: fused-route ceiling in banks: past 32 banks (32k padded rows) the
#: per-bank launch overhead beats the XLA scan and compaction is overdue
#: anyway — CompactionPolicy's delta-row trigger should have fired long
#: before.
_DELTA_FUSED_MAX_BANKS = 32

#: metrics whose fused-kernel epilogue matches brute-force exact
#: distances term-for-term (cosine divides by the norm product on the
#: XLA path but multiplies by rsqrt in-kernel — not bit-comparable)
_DELTA_FUSED_METRICS = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.InnerProduct,
    }
)

#: initial delta-buffer capacity (rows); grows by doubling
_DELTA_MIN_CAP = 64

#: serialized sidecar holding the main segment's raw rows + global ids
_ROWS_KIND = "mutable_rows"
_ROWS_VERSION = 1


def _po2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _build_main(algo: str, data: np.ndarray, index_params, metric):
    """Build one immutable main-segment index over ``data`` rows whose
    positional ids are 0..n-1 (each builder assigns ``arange(n)``)."""
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if algo == "brute_force":
        return brute_force.build(data, metric=metric)
    if algo == "ivf_flat":
        params = index_params or ivf_flat.IvfFlatIndexParams(metric=metric)
        return ivf_flat.build(data, params=params)
    if algo == "ivf_pq":
        params = index_params or ivf_pq.IvfPqIndexParams(metric=metric)
        return ivf_pq.build(data, params=params)
    if algo == "cagra":
        params = index_params or cagra.CagraIndexParams(metric=metric)
        return cagra.build(data, params=params)
    raise ValueError(f"unknown mutable algo {algo!r}")


def _search_main(algo: str, index, queries, k: int, params, prefilter, dataset, **kw):
    """Dispatch one main-segment search with the tombstone prefilter
    applied in-scan (every index type consumes a keep-``Bitset``)."""
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if algo == "brute_force":
        return brute_force.search(index, queries, k, prefilter=prefilter, **kw)
    if algo == "ivf_flat":
        return ivf_flat.search(index, queries, k, params, prefilter=prefilter, **kw)
    if algo == "ivf_pq":
        return ivf_pq.search(
            index, queries, k, params, prefilter=prefilter, dataset=dataset, **kw
        )
    if algo == "cagra":
        return cagra.search(index, queries, k, params, prefilter=prefilter, **kw)
    raise ValueError(f"unknown mutable algo {algo!r}")


def _delta_fused_eligible(metric, cap: int, k: int) -> bool:
    """True when the banked fused scan reproduces the exact scan
    bit-for-bit: a supported metric, the padded delta within the banked
    window (each 1024-row bank stays inside the lossless bank-merge
    width), and k within one extract width."""
    return (
        metric in _DELTA_FUSED_METRICS
        and cap <= _DELTA_FUSED_MAX_ROWS * _DELTA_FUSED_MAX_BANKS
        and k <= 128
    )


def _delta_route(mode: str, metric, cap: int, k: int) -> str:
    """Resolve ``delta_mode`` to the scan that actually runs."""
    expects(mode in DELTA_MODES, "delta_mode must be %s, got %r",
            "|".join(DELTA_MODES), mode)
    if mode == "exact":
        return "exact"
    eligible = _delta_fused_eligible(metric, cap, k)
    if mode == "fused":
        expects(
            eligible,
            "delta_mode='fused' needs an L2/IP metric, a delta of <= %d "
            "(padded) rows and k <= 128",
            _DELTA_FUSED_MAX_ROWS * _DELTA_FUSED_MAX_BANKS,
        )
        return "fused"
    import jax

    from raft_tpu import plan as _plan

    on_tpu = jax.default_backend() == "tpu"
    if _plan.is_enabled():
        return _plan.plan_delta_mode(eligible=eligible, on_tpu=on_tpu).choice
    return "fused" if eligible and on_tpu else "exact"


def _delta_fused_search(metric, delta_bf, delta_live, queries, k: int):
    """Delta scan through the fused Pallas probed-list kernel, treating
    each 1024-row tile of the padded delta buffer as ONE list that every
    query tile probes.

    Within the eligibility window (:func:`_delta_fused_eligible`) the
    kernel's lane-group compression is a pure reshuffle — no candidate
    is ever merged away — and its distance epilogue applies the same
    expanded-metric terms as :func:`raft_tpu.neighbors.brute_force.search`
    ``mode="exact"``, so ids match exactly and distances to float
    rounding (the parity gate in ``tests/test_mutable.py``). Dead and
    padding rows fold into the slot validity the same way the live
    bitset masks the exact scan.

    Past one bank (padded cap > 1024 — always a multiple of 1024, the
    cap grows by doubling) every bank is scanned by its own kernel call,
    each inside the lossless window, and the per-bank top-k lists are
    k-way merged by one stable sort on the kernel-space scores: the
    epilogue is a per-query monotone map, per-bank lists break ties by
    ascending slot, and banks concatenate in ascending-slot order — so
    the merged ids keep the exact scan's lowest-id-wins tie discipline.
    The bank count is published as the ``mutable.delta.banks`` gauge.
    """
    import jax

    from raft_tpu.ops.pallas.ivf_scan import fused_list_topk

    cap = int(delta_bf.size)
    qf = jnp.asarray(queries, jnp.float32)
    nq = qf.shape[0]
    qt = _DELTA_FUSED_QT
    n_qt = max(1, (nq + qt - 1) // qt)
    nq_pad = n_qt * qt
    if nq_pad != nq:
        qf = jnp.concatenate(
            [qf, jnp.broadcast_to(qf[:1], (nq_pad - nq, qf.shape[1]))]
        )
    mask = (
        jnp.asarray(delta_live.to_mask())
        if delta_live is not None
        else jnp.ones((cap,), bool)
    )
    positions = jnp.arange(cap, dtype=jnp.int32)
    tile_probes = jnp.zeros((n_qt, 1), jnp.int32)
    probe_valid = jnp.ones((n_qt, 1), jnp.int32)
    norms = delta_bf.norms
    interpret = jax.default_backend() != "tpu"

    bank_rows = _DELTA_FUSED_MAX_ROWS
    n_banks = max(1, (cap + bank_rows - 1) // bank_rows)

    bank_vals, bank_slots = [], []
    for b in range(n_banks):
        lo, hi = b * bank_rows, min((b + 1) * bank_rows, cap)
        list_indices = jnp.where(mask[lo:hi], positions[lo:hi], -1)[None, :]
        v, s = fused_list_topk(
            delta_bf.dataset[lo:hi][None].astype(jnp.float32),
            norms[lo:hi][None] if norms is not None else None,
            list_indices,
            qf,
            tile_probes,
            probe_valid,
            k=k,
            metric=metric,
            qt=qt,
            merge="bank8",
            interpret=interpret,
        )
        bank_vals.append(v)
        # Kernel slots are rows within the data it was handed — lift the
        # bank's rows back to global delta positions (invalid stays -1).
        bank_slots.append(jnp.where(s >= 0, s + lo, -1))
    if obs.is_enabled():
        obs.set_gauge("mutable.delta.banks", float(n_banks))
    if n_banks == 1:
        vals, slots = bank_vals[0], bank_slots[0]
    else:
        all_v = jnp.concatenate(bank_vals, axis=1)
        all_s = jnp.concatenate(bank_slots, axis=1)
        order = jnp.argsort(all_v, axis=1, stable=True)[:, :k]
        vals = jnp.take_along_axis(all_v, order, axis=1)
        slots = jnp.take_along_axis(all_s, order, axis=1)
    idx = jnp.where(slots >= 0, slots, -1)
    if metric == DistanceType.InnerProduct:
        out = -vals
    else:
        qn = jnp.sum(qf * qf, axis=1)
        out = jnp.maximum(qn[:, None] + vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)
    return out[:nq], idx[:nq]


def _save_rows(path: str, ids: np.ndarray, data: np.ndarray) -> str:
    """Atomic checksummed sidecar with the main segment's source rows
    (the rebuild input future compactions need — PQ codes are lossy)."""
    import io

    body = io.BytesIO()
    ser.serialize_array(body, np.asarray(ids, np.int64))
    ser.serialize_array(body, np.asarray(data, np.float32))
    payload = body.getvalue()
    return ser.atomic_write(
        path, lambda f: ser.save_stream(f, _ROWS_KIND, _ROWS_VERSION, payload)
    )


def _load_rows(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        _version, body = ser.load_stream(f, _ROWS_KIND)
        ids = np.asarray(ser.deserialize_array(body))
        data = np.asarray(ser.deserialize_array(body))
    return ids, data


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """An immutable, search-consistent view of a :class:`MutableIndex`.

    Everything a query needs is pinned here: the main segment and its
    tombstone bitset, the (padded) delta brute-force segment and its
    live bitset, and the position→global-id maps. Mutations after
    :meth:`MutableIndex.snapshot` returned never alter this object, so
    a serving batch dispatched against it is atomic with respect to
    writers.
    """

    generation: int
    version: int
    algo: str
    metric: DistanceType
    dim: int
    main_index: object  # built index or None when the main segment is empty
    main_ids: np.ndarray  # int64[n_main] position -> global id
    main_live: Optional[Bitset]  # None = no tombstones (fast path)
    n_main_live: int
    refine_dataset: object  # ivf_pq exact re-rank rows (device) or None
    delta_bf: object  # BruteForceIndex over the padded delta, or None
    delta_ids: np.ndarray  # int64[delta_cap] position -> global id (-1 pad)
    delta_live: Optional[Bitset]  # live bits over the padded delta rows
    n_delta_live: int
    search_params: object = None
    search_kwargs: Dict[str, object] = dataclasses.field(default_factory=dict)
    delta_mode: str = "auto"  # auto | exact | fused (see _delta_route)

    @property
    def size(self) -> int:
        """Live (visible) row count."""
        return self.n_main_live + self.n_delta_live

    @property
    def select_min(self) -> bool:
        return is_min_close(self.metric)

    def search(self, queries, k: int, params=None, **kw) -> Tuple[np.ndarray, np.ndarray]:
        """Best-first search over both segments with tombstones masked
        in-scan. Returns ``(distances f32 [m, k], ids int64 [m, k])``;
        unfilled slots get id -1 and the worst-sentinel distance.

        The main segment runs its native search (fused/XLA per its
        ``mode``) with the tombstone bitset as ``prefilter``; the delta
        segment runs an exact brute-force scan over its padded buffer
        with dead+padding rows masked; candidates merge k-way by
        distance on the host. With an empty delta and no tombstones the
        result is bit-for-bit the main index's own output (ids mapped
        to the global space).
        """
        queries = np.asarray(queries, np.float32)
        expects(queries.ndim == 2 and queries.shape[1] == self.dim, "bad query shape")
        expects(k >= 1, "k must be >= 1")
        m = queries.shape[0]
        params = params if params is not None else self.search_params
        kw = {**self.search_kwargs, **kw}
        worst = np.float32(np.inf if self.select_min else -np.inf)
        parts: List[Tuple[np.ndarray, np.ndarray]] = []

        if self.main_index is not None and len(self.main_ids):
            k_main = min(k, len(self.main_ids))
            d, p = _search_main(
                self.algo, self.main_index, queries, k_main, params,
                prefilter=self.main_live, dataset=self.refine_dataset, **kw
            )
            d = np.asarray(d, np.float32)
            p = np.asarray(p)
            ids = np.where(p >= 0, self.main_ids[np.clip(p, 0, None)], np.int64(-1))
            d = np.where(ids >= 0, d, worst)
            parts.append((d, ids))
            if self.delta_bf is None and k_main == k:
                return d, ids  # pure-main fast path: native ordering intact

        if self.delta_bf is not None:
            from raft_tpu.neighbors import brute_force

            k_delta = min(k, int(self.delta_bf.size))
            route = _delta_route(
                self.delta_mode, self.metric, int(self.delta_bf.size), k_delta
            )
            d = p = None
            if route == "fused":
                from raft_tpu.robust.fallback import FALLBACK_ERRORS

                try:
                    d, p = _delta_fused_search(
                        self.metric, self.delta_bf, self.delta_live, queries, k_delta
                    )
                except FALLBACK_ERRORS:
                    route = "exact"  # kernel failure degrades to the XLA scan
            if d is None:
                d, p = brute_force.search(
                    self.delta_bf, queries, k_delta,
                    prefilter=self.delta_live, mode="exact",
                )
            if obs.is_enabled():
                obs.inc("mutable.delta.scans", mode=route)
            d = np.asarray(d, np.float32)
            p = np.asarray(p)
            ids = np.where(p >= 0, self.delta_ids[np.clip(p, 0, None)], np.int64(-1))
            d = np.where(ids >= 0, d, worst)
            parts.append((d, ids))

        if not parts:
            return (
                np.full((m, k), worst, np.float32),
                np.full((m, k), -1, np.int64),
            )
        all_d = np.concatenate([d for d, _ in parts], axis=1)
        all_i = np.concatenate([i for _, i in parts], axis=1)
        # dead/unfilled slots already carry the worst sentinel, so one
        # stable argsort is the k-way merge (ties keep main-first order)
        key = all_d if self.select_min else -all_d
        order = np.argsort(key, axis=1, kind="stable")[:, :k]
        out_d = np.take_along_axis(all_d, order, axis=1)
        out_i = np.take_along_axis(all_i, order, axis=1)
        if out_d.shape[1] < k:
            pad = k - out_d.shape[1]
            out_d = np.pad(out_d, ((0, 0), (0, pad)), constant_values=worst)
            out_i = np.pad(out_i, ((0, 0), (0, pad)), constant_values=-1)
        return out_d, out_i


@lockcheck.guarded_fields
class MutableIndex:
    """A mutable, crash-consistent index over one immutable index type.

    >>> mut = MutableIndex.open("/data/wiki", "ivf_flat", dim=128)
    >>> ids = mut.insert(rows)               # durable-then-visible
    >>> mut.delete(ids[:10])                 # tombstoned in-scan
    >>> dist, gids = mut.search(queries, 10)
    >>> mut.compact()                        # fold delta+tombstones, new generation

    ``directory=None`` runs fully in memory (no WAL, no manifest) — the
    same visibility semantics without durability, for tests and
    benchmarks.
    """

    def __init__(
        self,
        algo: str,
        dim: int,
        *,
        directory: Optional[str] = None,
        index_params=None,
        search_params=None,
        metric=None,
        name: Optional[str] = None,
        max_wal_bytes: Optional[int] = None,
        delta_mode: str = "auto",
    ):
        expects(algo in ALGOS, "unknown mutable algo %r (want one of %s)",
                algo, ", ".join(ALGOS))
        expects(dim >= 1, "dim must be >= 1")
        expects(delta_mode in DELTA_MODES, "delta_mode must be %s, got %r",
                "|".join(DELTA_MODES), delta_mode)
        expects(max_wal_bytes is None or max_wal_bytes > 0,
                "max_wal_bytes must be positive when set")
        self.algo = algo
        self.dim = int(dim)
        self.directory = directory
        self.index_params = index_params
        self.search_params = search_params
        self.max_wal_bytes = max_wal_bytes
        self.delta_mode = delta_mode
        if metric is None:
            metric = getattr(index_params, "metric", DistanceType.L2Expanded)
        self.metric = resolve_metric(metric)
        self.name = name or (os.path.basename(directory) if directory else "mutable")
        self._lock = lockcheck.tracked(threading.RLock(), "mutable.lock")
        # lock ordering: _compact_mutex (if taken) strictly before _lock.
        # It serializes whole compactions (foreground or background) so
        # two rebuilds can never race a generation number, while writers
        # and searchers keep taking _lock alone. The full ordering
        # contract is machine-checked: tools/graft_lint/lock_order.toml
        # declares it, the lock-order lint derives it statically, and
        # the RAFT_TPU_LOCKCHECK witness asserts it at runtime.
        self._compact_mutex = lockcheck.tracked(
            threading.Lock(), "mutable.compact_mutex"
        )
        #: when a background compaction is between pin and flip, every
        #: applied mutation is also recorded here so the in-memory
        #: (directory=None) catch-up replay has a source of truth; the
        #: directory-backed path reads the WAL instead
        self._capture: Optional[List[WalRecord]] = None
        # main segment state
        self.main_index = None
        self.main_data = np.zeros((0, dim), np.float32)
        self.main_ids = np.zeros((0,), np.int64)
        self._main_live_mask = np.zeros((0,), bool)
        self._n_main_dead = 0
        self._refine_dataset = None
        # delta segment state (append-only buffer, doubling capacity)
        self._delta_data = np.zeros((_DELTA_MIN_CAP, dim), np.float32)
        self._delta_ids = np.full((_DELTA_MIN_CAP,), -1, np.int64)
        self._delta_live = np.zeros((_DELTA_MIN_CAP,), bool)
        self._n_delta = 0
        self._n_delta_dead = 0
        # id space + versions
        self._id_loc: Dict[int, Tuple[str, int]] = {}
        self.next_id = 0
        self.generation = 0
        self.version = 0  # mutation counter (any visible change bumps it)
        self.wal: Optional[WriteAheadLog] = None
        self._snap: Optional[Snapshot] = None
        self._delta_bf_cache: Tuple[int, object] = (-1, None)

    # -- construction ------------------------------------------------------

    @classmethod
    def open(
        cls,
        directory: str,
        algo: str,
        dim: int,
        *,
        index_params=None,
        search_params=None,
        metric=None,
        name: Optional[str] = None,
        max_wal_bytes: Optional[int] = None,
        delta_mode: str = "auto",
        res=None,
    ) -> "MutableIndex":
        """Open (or create) the mutable index at ``directory``.

        Recovery is manifest-then-WAL: the manifest names the live
        generation, its main-segment snapshot loads through the
        checksummed v4 path, and the generation's WAL replays on top —
        any valid prefix of a torn log recovers cleanly, so a crash at
        any point yields either the pre- or post-mutation state.
        ``max_wal_bytes`` arms size-triggered WAL segment rotation;
        ``delta_mode`` routes delta-segment scans (see
        :func:`_delta_route`).
        """
        self = cls(
            algo, dim, directory=directory, index_params=index_params,
            search_params=search_params, metric=metric, name=name,
            max_wal_bytes=max_wal_bytes, delta_mode=delta_mode,
        )
        m = man.read(directory)
        if m is None:
            m = man.Manifest(
                generation=0, algo=algo, dim=self.dim, main=None, rows=None,
                wal=_wal_name(0), next_id=0,
            )
            man.swap(directory, m)
        expects(m.algo == algo, "directory holds a %r index, not %r", m.algo, algo)
        expects(m.dim == self.dim, "directory holds dim=%d, not %d", m.dim, self.dim)
        self.generation = m.generation
        self.next_id = m.next_id
        if m.rows is not None:
            ids, data = _load_rows(os.path.join(directory, m.rows))
            self._install_main(ids, data, index=None, res=res)
            if m.main is not None:
                self.main_index = _load_main(
                    algo, os.path.join(directory, m.main), data, res=res
                )
        self.wal, records = WriteAheadLog.open(
            os.path.join(directory, m.wal), max_bytes=self.max_wal_bytes
        )
        for rec in records:
            self._apply(rec)
        self._note_obs()
        return self

    def _install_main(self, ids: np.ndarray, data: np.ndarray, index, res=None) -> None:
        """Replace the main segment (compaction/open): fresh tombstones,
        fresh id map for the main rows."""
        self.main_ids = np.asarray(ids, np.int64)
        self.main_data = np.asarray(data, np.float32)
        self.main_index = index
        self._main_live_mask = np.ones((len(ids),), bool)
        self._n_main_dead = 0
        self._refine_dataset = None
        if self.algo == "ivf_pq" and len(ids):
            # exact re-rank rows for the integrated refine path, pushed
            # to device once per generation
            self._refine_dataset = jnp.asarray(self.main_data)
        for pos, gid in enumerate(self.main_ids):
            self._id_loc[int(gid)] = ("m", pos)
        if len(ids):
            self.next_id = max(self.next_id, int(self.main_ids.max()) + 1)

    # -- introspection -----------------------------------------------------

    @property
    def size(self) -> int:
        """Visible (live) row count across both segments."""
        with self._lock:
            return (len(self.main_ids) - self._n_main_dead) + (
                self._n_delta - self._n_delta_dead
            )

    @property
    def delta_rows(self) -> int:
        with self._lock:
            return self._n_delta - self._n_delta_dead

    @property
    def tombstone_fraction(self) -> float:
        with self._lock:
            total = len(self.main_ids) + self._n_delta
            dead = self._n_main_dead + self._n_delta_dead
            return dead / total if total else 0.0

    def live_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """All live ``(ids, vectors)`` in stable segment order (main
        position order, then delta insertion order) — the exact input a
        from-scratch rebuild (or compaction) consumes."""
        with self._lock:
            mm = self._main_live_mask
            dm = self._delta_live[: self._n_delta]
            ids = np.concatenate([self.main_ids[mm], self._delta_ids[: self._n_delta][dm]])
            vecs = np.concatenate(
                [self.main_data[mm], self._delta_data[: self._n_delta][dm]], axis=0
            )
        return ids, vecs

    # -- mutations (durable then visible) ----------------------------------

    def insert(self, vectors, ids=None) -> np.ndarray:
        """Insert rows; returns their global ids (auto-assigned when
        ``ids`` is None). Fails on a live duplicate id — use
        :meth:`upsert` to replace."""
        vectors = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        expects(vectors.ndim == 2 and vectors.shape[1] == self.dim, "bad insert shape")
        with self._lock:
            if ids is None:
                ids = np.arange(self.next_id, self.next_id + len(vectors), dtype=np.int64)
            else:
                ids = np.asarray(ids, np.int64).reshape(-1)
                expects(len(ids) == len(vectors), "ids/vectors length mismatch")
                for gid in ids:
                    expects(int(gid) not in self._id_loc,
                            "id %d already live — use upsert()", int(gid))
            rec = WalRecord(op="insert", ids=ids, vectors=vectors)
            if self.wal is not None:
                self.wal.append(rec)
            self._apply(rec)
            self._note_obs()
        return ids

    def delete(self, ids) -> int:
        """Tombstone rows by global id; unknown ids are ignored. Returns
        the number of rows actually deleted."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            rec = WalRecord(op="delete", ids=ids)
            if self.wal is not None:
                self.wal.append(rec)
            n = self._apply(rec)
            self._note_obs()
        return n

    def upsert(self, ids, vectors) -> np.ndarray:
        """Replace-or-insert rows at explicit global ids (Faiss
        ``add_with_ids`` over existing ids): any live row with a given
        id is tombstoned and the new row becomes visible atomically."""
        vectors = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        ids = np.asarray(ids, np.int64).reshape(-1)
        expects(len(ids) == len(vectors), "ids/vectors length mismatch")
        expects(vectors.shape[1] == self.dim, "bad upsert shape")
        with self._lock:
            rec = WalRecord(op="upsert", ids=ids, vectors=vectors)
            if self.wal is not None:
                self.wal.append(rec)
            self._apply(rec)
            self._note_obs()
        return ids

    # -- application (shared by live mutation and WAL replay) --------------

    def _apply(self, rec: WalRecord) -> int:
        if self._capture is not None:
            # a background compaction pinned before this mutation: queue
            # it for the catch-up replay into the new generation
            self._capture.append(rec)
        if rec.op == "insert":
            self._apply_rows(rec.ids, rec.vectors, replace=False)
            if obs.is_enabled():
                obs.inc("mutable.inserts", float(len(rec.ids)), index=self.name)
            return len(rec.ids)
        if rec.op == "upsert":
            self._apply_rows(rec.ids, rec.vectors, replace=True)
            if obs.is_enabled():
                obs.inc("mutable.upserts", float(len(rec.ids)), index=self.name)
            return len(rec.ids)
        if rec.op == "delete":
            n = 0
            for gid in rec.ids:
                n += self._tombstone(int(gid))
            self.version += 1
            if obs.is_enabled():
                obs.inc("mutable.deletes", float(n), index=self.name)
            return n
        raise ValueError(f"unknown WAL op {rec.op!r}")

    def _tombstone(self, gid: int) -> int:
        loc = self._id_loc.pop(gid, None)
        if loc is None:
            return 0
        seg, pos = loc
        if seg == "m":
            self._main_live_mask[pos] = False
            self._n_main_dead += 1
        else:
            self._delta_live[pos] = False
            self._n_delta_dead += 1
        return 1

    def _apply_rows(self, ids: np.ndarray, vectors: np.ndarray, replace: bool) -> None:
        for gid, row in zip(ids, vectors):
            gid = int(gid)
            if replace:
                self._tombstone(gid)
            pos = self._n_delta
            if pos == len(self._delta_data):
                new_cap = max(_DELTA_MIN_CAP, 2 * len(self._delta_data))
                self._delta_data = np.concatenate(
                    [self._delta_data,
                     np.zeros((new_cap - len(self._delta_data), self.dim), np.float32)]
                )
                self._delta_ids = np.concatenate(
                    [self._delta_ids,
                     np.full((new_cap - len(self._delta_ids),), -1, np.int64)]
                )
                self._delta_live = np.concatenate(
                    [self._delta_live, np.zeros((new_cap - len(self._delta_live),), bool)]
                )
            self._delta_data[pos] = row
            self._delta_ids[pos] = gid
            self._delta_live[pos] = True
            self._id_loc[gid] = ("d", pos)
            self._n_delta += 1
            self.next_id = max(self.next_id, gid + 1)
        self.version += 1

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> Snapshot:
        """An immutable search-consistent view at this instant (cached
        until the next mutation or compaction)."""
        with self._lock:
            snap = self._snap
            if snap is not None and snap.generation == self.generation and snap.version == self.version:
                return snap
            main_live = None
            if self._n_main_dead and len(self.main_ids):
                main_live = Bitset.from_mask(jnp.asarray(self._main_live_mask))
            delta_bf, delta_live, delta_ids = None, None, self._delta_ids
            if self._n_delta - self._n_delta_dead > 0:
                delta_bf, delta_live, delta_ids = self._delta_segment()
            snap = Snapshot(
                generation=self.generation,
                version=self.version,
                algo=self.algo,
                metric=self.metric,
                dim=self.dim,
                main_index=self.main_index,
                main_ids=self.main_ids,
                main_live=main_live,
                n_main_live=len(self.main_ids) - self._n_main_dead,
                refine_dataset=self._refine_dataset,
                delta_bf=delta_bf,
                delta_ids=delta_ids,
                delta_live=delta_live,
                n_delta_live=self._n_delta - self._n_delta_dead,
                search_params=self.search_params,
                delta_mode=self.delta_mode,
            )
            self._snap = snap
            return snap

    def _delta_segment(self):
        """Brute-force view of the delta rows, padded to a power of two
        so the jitted scan sees at most log2 distinct shapes; padding
        and dead rows are masked by the live bitset."""
        from raft_tpu.neighbors import brute_force

        cap = _po2(max(self._n_delta, 1))
        key = (self.version, cap)
        cached_key, cached = self._delta_bf_cache
        if cached_key == key:
            return cached
        data = self._delta_data[:cap]
        ids = self._delta_ids[:cap]
        mask = np.zeros((cap,), bool)
        mask[: self._n_delta] = self._delta_live[: self._n_delta]
        bf = brute_force.build(data, metric=self.metric)
        out = (bf, Bitset.from_mask(jnp.asarray(mask)), ids.copy())
        self._delta_bf_cache = (key, out)
        return out

    def search(self, queries, k: int, params=None, **kw):
        """Convenience: :meth:`snapshot` then :meth:`Snapshot.search`."""
        return self.snapshot().search(queries, k, params=params, **kw)

    def compact(self, res=None) -> int:
        """Fold delta + tombstones into a rebuilt main segment and
        publish it as the next generation (see
        :func:`raft_tpu.mutable.compact.compact`)."""
        from raft_tpu.mutable.compact import compact

        return compact(self, res=res)

    def compact_background(self, res=None, _mid_rebuild=None) -> int:
        """One off-lock compaction on the calling thread: pin, rebuild
        without the lock, catch-up + flip under a brief lock (see
        :func:`raft_tpu.mutable.maintenance.compact_background`).
        Production callers want a :class:`~raft_tpu.mutable.maintenance.
        Compactor` worker instead."""
        from raft_tpu.mutable.maintenance import compact_background

        return compact_background(self, res=res, _mid_rebuild=_mid_rebuild)

    def close(self) -> None:
        with self._lock:
            if self.wal is not None:
                self.wal.close()
                self.wal = None

    # -- obs ---------------------------------------------------------------

    def _note_obs(self) -> None:
        if not obs.is_enabled():
            return
        obs.set_gauge("mutable.generation", float(self.generation), index=self.name)
        obs.set_gauge("mutable.delta_rows", float(self.delta_rows), index=self.name)
        obs.set_gauge("mutable.size", float(self.size), index=self.name)
        obs.set_gauge(
            "mutable.tombstone_fraction", float(self.tombstone_fraction), index=self.name
        )


def _wal_name(generation: int) -> str:
    return f"wal-{generation:08d}.log"


def _gen_dirname(generation: int) -> str:
    return f"gen-{generation:08d}"


def _load_main(algo: str, path: str, data: np.ndarray, res=None):
    """Load one main-segment snapshot through the per-algo checksummed
    loader (CAGRA snapshots may externalize the dataset — re-attach the
    sidecar rows)."""
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if algo == "brute_force":
        return brute_force.load_path(path, res=res)
    if algo == "ivf_flat":
        return ivf_flat.load_path(path, res=res)
    if algo == "ivf_pq":
        return ivf_pq.load_path(path, res=res)
    if algo == "cagra":
        return cagra.load_path(path, dataset=jnp.asarray(data), res=res)
    raise ValueError(f"unknown mutable algo {algo!r}")
