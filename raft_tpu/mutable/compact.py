"""Compaction: fold delta rows + tombstones into a rebuilt main segment.

Compaction takes every *live* row (main rows not tombstoned, plus delta
rows not tombstoned, in stable segment order), rebuilds the main-segment
index from scratch, and publishes the result as generation ``g+1``:

1. write ``gen-NNNNNNNN/rows.bin`` (raw rows + global ids, checksummed
   v4 envelope) and ``gen-NNNNNNNN/main.idx`` (the per-algo snapshot),
   both via the atomic temp-fsync-rename writer;
2. flip ``MANIFEST.json`` to the new generation with
   :func:`raft_tpu.mutable.manifest.swap` — the only mutable file;
3. switch the in-memory index over (empty delta, empty tombstones, a
   fresh per-generation WAL), then — after the index lock is released,
   so nobody queues behind filesystem work — best-effort delete the
   old generation's artifacts.

Crash matrix: a kill at the ``compact.merge`` seam (before any byte is
written) or anywhere during step 1 leaves the old manifest pointing at
the old, untouched generation — recovery sees the pre-compaction state
with its WAL intact. A kill at the ``manifest.swap`` seam leaves the new
generation's files on disk as orphans but the old manifest live — still
pre-state. Only once the rename lands is the new generation visible,
and then it is complete by construction. There is no crash point that
yields a hybrid.

The rebuild is deterministic (same rows in the same order through the
same seeded builder), so post-compaction search is bit-for-bit equal to
a from-scratch build over the live rows — the freshness acceptance
gate in ``tests/test_mutable.py``.

This module is the **foreground** mode: the whole fold runs under the
index lock, so writers and fresh snapshots queue behind the rebuild
(already-taken snapshots keep serving). That is the right call for an
operator console or a drained index; a serving system wants
:mod:`raft_tpu.mutable.maintenance`, which pins a snapshot, rebuilds
off-lock on a worker thread, and re-enters the lock only for the
catch-up replay + pointer flip. Both modes share the artifact writers
and the memory switch below, and both retry transient failures through
:mod:`raft_tpu.robust.retry` (the ``mutable.compact.retries`` counter);
the final failure re-raises the *underlying* error, so a chaos kill
surfaces as itself, not as a ``RetryError``.
"""
from __future__ import annotations

import os
import shutil
import time
from typing import Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.mutable import manifest as man
from raft_tpu.mutable import segments as seg
from raft_tpu.robust import faults
from raft_tpu.robust.retry import RetryError, RetryPolicy, retry_call

#: default backoff for compaction attempts: quick, bounded retries —
#: a compaction that keeps failing is reported, not looped forever
COMPACT_RETRY_POLICY = RetryPolicy(
    max_attempts=3, base_delay_s=0.01, multiplier=2.0, max_delay_s=0.25
)


def _save_main(algo: str, index, path: str) -> str:
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if algo == "brute_force":
        return brute_force.save_path(index, path)
    if algo == "ivf_flat":
        return ivf_flat.save_path(index, path)
    if algo == "ivf_pq":
        return ivf_pq.save_path(index, path)
    if algo == "cagra":
        # rows live in the sidecar; don't store the dataset twice
        return cagra.save_path(index, path, include_dataset=False)
    raise ValueError(f"unknown mutable algo {algo!r}")


def _write_generation(
    mut: "seg.MutableIndex", new_gen: int, ids: np.ndarray, vecs: np.ndarray, index
) -> Tuple[str, Optional[str]]:
    """Write generation ``new_gen``'s immutable artifacts (rows sidecar
    + per-algo main snapshot) through the atomic writers and return
    their manifest-relative paths. Touches nothing the live manifest
    references, so it is safe to run without the index lock."""
    gen_name = seg._gen_dirname(new_gen)
    gen_dir = os.path.join(mut.directory, gen_name)
    os.makedirs(gen_dir, exist_ok=True)
    rows_rel = os.path.join(gen_name, "rows.bin")
    seg._save_rows(os.path.join(mut.directory, rows_rel), ids, vecs)
    main_rel = None
    if index is not None:
        main_rel = os.path.join(gen_name, "main.idx")
        _save_main(mut.algo, index, os.path.join(mut.directory, main_rel))
    return rows_rel, main_rel


def _clear_stale_wal(path: str) -> None:
    """Unlink leftover WAL segments at a new generation's log path.
    Generation numbers are reused when a failed compaction retries, so
    a crashed earlier attempt may have left catch-up records here;
    replaying them on top of a freshly published generation would
    double-apply mutations. Must run *before* the manifest flip makes
    the path live."""
    from raft_tpu.mutable.wal import segment_paths

    for sp in segment_paths(path):
        try:
            os.unlink(sp)
        except OSError:  # graft-lint: ignore[silent-except] — path relinks below; open() would re-truncate
            pass


def _publish(mut: "seg.MutableIndex", new_gen: int, rows_rel, main_rel) -> None:
    """The atomic flip: swap ``MANIFEST.json`` to generation
    ``new_gen``. Before the rename recovery sees the old generation,
    after it the new — never a mixture."""
    man.swap(
        mut.directory,
        man.Manifest(
            generation=new_gen,
            algo=mut.algo,
            dim=mut.dim,
            main=main_rel,
            rows=rows_rel,
            wal=seg._wal_name(new_gen),
            next_id=mut.next_id,
        ),
    )


def _switch_memory(
    mut: "seg.MutableIndex",
    new_gen: int,
    ids: np.ndarray,
    vecs: np.ndarray,
    index,
    res=None,
    old_wal_path: Optional[str] = None,
    new_wal=None,
) -> Optional[Tuple[str, int, Optional[str]]]:
    """Install the just-published generation in memory: empty delta,
    empty tombstones, fresh id map, the new generation's WAL as the
    live log. Caller holds ``mut._lock``; the disk state is already
    durable, so this is pure pointer surgery — which is why the
    superseded generation is NOT deleted here. Deleting it is
    corpus-proportional filesystem work (rmtree + WAL unlinks) that
    once ran inside this critical section and stalled every writer and
    searcher behind it; instead the arguments for
    :func:`_cleanup_old_generation` are returned for the caller to run
    *after* releasing the lock (the artifacts are unreferenced the
    moment the manifest flip landed, so when exactly they disappear is
    irrelevant to correctness)."""
    mut._id_loc.clear()
    dim = mut.dim
    mut._delta_data = np.zeros((seg._DELTA_MIN_CAP, dim), np.float32)
    mut._delta_ids = np.full((seg._DELTA_MIN_CAP,), -1, np.int64)
    mut._delta_live = np.zeros((seg._DELTA_MIN_CAP,), bool)
    mut._n_delta = 0
    mut._n_delta_dead = 0
    mut._delta_bf_cache = (-1, None)
    mut._install_main(ids, vecs, index, res=res)
    mut.generation = new_gen
    mut.version += 1
    mut._snap = None
    if mut.directory is not None:
        if mut.wal is not None:
            mut.wal.close()
        if new_wal is not None:
            mut.wal = new_wal
        else:
            mut.wal, _ = seg.WriteAheadLog.open(
                os.path.join(mut.directory, seg._wal_name(new_gen)),
                max_bytes=mut.max_wal_bytes,
            )
        return (mut.directory, new_gen - 1, old_wal_path)
    return None


def _note_compaction(mut: "seg.MutableIndex", mode: str, rows: int, t0: float) -> None:
    if obs.is_enabled():
        obs.inc("mutable.compactions", index=mut.name, mode=mode)
        obs.observe(
            "mutable.compact.duration_ms", (time.perf_counter() - t0) * 1e3,
            index=mut.name,
        )
        obs.observe("mutable.compact.rows", float(rows), index=mut.name)
    mut._note_obs()


def _compact_once(mut: "seg.MutableIndex", res=None) -> int:
    """One synchronous compaction attempt, entirely under the index
    lock (writers and fresh snapshots wait it out)."""
    t0 = time.perf_counter()
    with mut._lock:
        old_gen = mut.generation
        new_gen = old_gen + 1
        ids, vecs = mut.live_rows()
        # chaos seam: a kill here (or anywhere before the manifest flip)
        # has written nothing the old manifest references — pre-state
        faults.fire("compact.merge", generation=new_gen, rows=len(ids))
        # Foreground mode *is* the documented blocking path: the rebuild
        # and artifact writes run with the lock held by design, and the
        # mutable_churn bench row measures exactly this cost. The
        # off-lock alternative is maintenance.compact_background.
        index = (
            seg._build_main(mut.algo, vecs, mut.index_params, mut.metric)  # graft-lint: ignore[blocking-under-lock] — foreground mode rebuilds under the lock by contract
            if len(ids)
            else None
        )
        old_wal_path = mut.wal.path if mut.wal is not None else None
        if mut.directory is not None:
            _clear_stale_wal(os.path.join(mut.directory, seg._wal_name(new_gen)))
            rows_rel, main_rel = _write_generation(  # graft-lint: ignore[blocking-under-lock] — foreground mode writes artifacts under the lock by contract
                mut, new_gen, ids, vecs, index
            )
            _publish(mut, new_gen, rows_rel, main_rel)
        # the new generation is durable and live on disk — switch memory
        pending_cleanup = _switch_memory(
            mut, new_gen, ids, vecs, index, res=res, old_wal_path=old_wal_path
        )
        _note_compaction(mut, "sync", len(ids), t0)
    # the superseded generation's artifacts are unreferenced once the
    # flip landed — delete them only after releasing the index lock
    if pending_cleanup is not None:
        _cleanup_old_generation(*pending_cleanup)
    return new_gen


def compact(
    mut: "seg.MutableIndex",
    res=None,
    *,
    retry_policy: Optional[RetryPolicy] = None,
    seed: int = 0,
) -> int:
    """Merge ``mut``'s delta + tombstones into a new main segment and
    publish it atomically. Returns the new generation number.

    Transient failures (an injected fault, a flaky filesystem) retry
    with the seeded backoff of :mod:`raft_tpu.robust.retry`, counted in
    ``mutable.compact.retries``; a failed attempt leaves only orphan
    artifacts the next attempt overwrites (and stale new-generation WAL
    segments it clears), so attempts are idempotent. When every attempt
    fails the *last underlying error* is re-raised — callers and chaos
    tests see the real failure, not a ``RetryError`` wrapper.
    """
    policy = retry_policy if retry_policy is not None else COMPACT_RETRY_POLICY
    state = {"attempts": 0}

    def _attempt():
        state["attempts"] += 1
        if state["attempts"] > 1:
            obs.inc("mutable.compact.retries", index=mut.name, mode="sync")
        return _compact_once(mut, res=res)

    # mutex before lock (the repo-wide compaction lock order): one
    # compaction at a time, foreground or background
    with mut._compact_mutex:
        try:
            return retry_call(_attempt, policy=policy, op="mutable.compact", seed=seed)
        except RetryError as e:
            raise e.last from e


def _cleanup_old_generation(directory: str, old_gen: int, old_wal_path) -> None:
    """Best-effort removal of the superseded generation's artifacts —
    they are unreferenced once the manifest flip landed, so a failure
    here only leaks disk (recovery ignores orphans)."""
    try:
        old_dir = os.path.join(directory, seg._gen_dirname(old_gen))
        if os.path.isdir(old_dir):
            shutil.rmtree(old_dir)
        if old_wal_path:
            from raft_tpu.mutable.wal import segment_paths

            # the base file plus every rotated .NNNNNN segment
            for sp in segment_paths(old_wal_path):
                os.unlink(sp)
    except OSError:  # graft-lint: ignore[silent-except] — orphan cleanup is advisory
        pass
