"""Compaction: fold delta rows + tombstones into a rebuilt main segment.

Compaction takes every *live* row (main rows not tombstoned, plus delta
rows not tombstoned, in stable segment order), rebuilds the main-segment
index from scratch, and publishes the result as generation ``g+1``:

1. write ``gen-NNNNNNNN/rows.bin`` (raw rows + global ids, checksummed
   v4 envelope) and ``gen-NNNNNNNN/main.idx`` (the per-algo snapshot),
   both via the atomic temp-fsync-rename writer;
2. flip ``MANIFEST.json`` to the new generation with
   :func:`raft_tpu.mutable.manifest.swap` — the only mutable file;
3. switch the in-memory index over (empty delta, empty tombstones, a
   fresh per-generation WAL) and best-effort delete the old
   generation's artifacts.

Crash matrix: a kill at the ``compact.merge`` seam (before any byte is
written) or anywhere during step 1 leaves the old manifest pointing at
the old, untouched generation — recovery sees the pre-compaction state
with its WAL intact. A kill at the ``manifest.swap`` seam leaves the new
generation's files on disk as orphans but the old manifest live — still
pre-state. Only once the rename lands is the new generation visible,
and then it is complete by construction. There is no crash point that
yields a hybrid.

The rebuild is deterministic (same rows in the same order through the
same seeded builder), so post-compaction search is bit-for-bit equal to
a from-scratch build over the live rows — the freshness acceptance
gate in ``tests/test_mutable.py``.

Compaction currently runs synchronously under the index lock (writers
and snapshot() block; already-taken snapshots keep serving). The p99
spike this causes under churn is measured by the ``mutable_churn``
bench row; moving the rebuild off-lock is future work.
"""
from __future__ import annotations

import os
import shutil
import time

from raft_tpu import obs
from raft_tpu.mutable import manifest as man
from raft_tpu.mutable import segments as seg
from raft_tpu.robust import faults


def _save_main(algo: str, index, path: str) -> str:
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if algo == "brute_force":
        return brute_force.save_path(index, path)
    if algo == "ivf_flat":
        return ivf_flat.save_path(index, path)
    if algo == "ivf_pq":
        return ivf_pq.save_path(index, path)
    if algo == "cagra":
        # rows live in the sidecar; don't store the dataset twice
        return cagra.save_path(index, path, include_dataset=False)
    raise ValueError(f"unknown mutable algo {algo!r}")


def compact(mut: "seg.MutableIndex", res=None) -> int:
    """Merge ``mut``'s delta + tombstones into a new main segment and
    publish it atomically. Returns the new generation number."""
    t0 = time.perf_counter()
    with mut._lock:
        old_gen = mut.generation
        new_gen = old_gen + 1
        ids, vecs = mut.live_rows()
        # chaos seam: a kill here (or anywhere before the manifest flip)
        # has written nothing the old manifest references — pre-state
        faults.fire("compact.merge", generation=new_gen, rows=len(ids))
        index = seg._build_main(mut.algo, vecs, mut.index_params, mut.metric) if len(ids) else None

        old_wal_path = mut.wal.path if mut.wal is not None else None
        if mut.directory is not None:
            gen_name = seg._gen_dirname(new_gen)
            gen_dir = os.path.join(mut.directory, gen_name)
            os.makedirs(gen_dir, exist_ok=True)
            rows_rel = os.path.join(gen_name, "rows.bin")
            seg._save_rows(os.path.join(mut.directory, rows_rel), ids, vecs)
            main_rel = None
            if index is not None:
                main_rel = os.path.join(gen_name, "main.idx")
                _save_main(mut.algo, index, os.path.join(mut.directory, main_rel))
            man.swap(
                mut.directory,
                man.Manifest(
                    generation=new_gen,
                    algo=mut.algo,
                    dim=mut.dim,
                    main=main_rel,
                    rows=rows_rel,
                    wal=seg._wal_name(new_gen),
                    next_id=mut.next_id,
                ),
            )

        # the new generation is durable and live on disk — switch memory
        mut._id_loc.clear()
        dim = mut.dim
        import numpy as np

        mut._delta_data = np.zeros((seg._DELTA_MIN_CAP, dim), np.float32)
        mut._delta_ids = np.full((seg._DELTA_MIN_CAP,), -1, np.int64)
        mut._delta_live = np.zeros((seg._DELTA_MIN_CAP,), bool)
        mut._n_delta = 0
        mut._n_delta_dead = 0
        mut._delta_bf_cache = (-1, None)
        mut._install_main(ids, vecs, index, res=res)
        mut.generation = new_gen
        mut.version += 1
        mut._snap = None

        if mut.directory is not None:
            if mut.wal is not None:
                mut.wal.close()
            mut.wal, _ = seg.WriteAheadLog.open(
                os.path.join(mut.directory, seg._wal_name(new_gen)),
                max_bytes=mut.max_wal_bytes,
            )
            _cleanup_old_generation(mut.directory, old_gen, old_wal_path)

        dur_ms = (time.perf_counter() - t0) * 1e3
        if obs.is_enabled():
            obs.inc("mutable.compactions", index=mut.name)
            obs.observe("mutable.compact.duration_ms", dur_ms, index=mut.name)
            obs.observe("mutable.compact.rows", float(len(ids)), index=mut.name)
        mut._note_obs()
        return new_gen


def _cleanup_old_generation(directory: str, old_gen: int, old_wal_path) -> None:
    """Best-effort removal of the superseded generation's artifacts —
    they are unreferenced once the manifest flip landed, so a failure
    here only leaks disk (recovery ignores orphans)."""
    try:
        old_dir = os.path.join(directory, seg._gen_dirname(old_gen))
        if os.path.isdir(old_dir):
            shutil.rmtree(old_dir)
        if old_wal_path:
            from raft_tpu.mutable.wal import segment_paths

            # the base file plus every rotated .NNNNNN segment
            for sp in segment_paths(old_wal_path):
                os.unlink(sp)
    except OSError:  # graft-lint: ignore[silent-except] — orphan cleanup is advisory
        pass
