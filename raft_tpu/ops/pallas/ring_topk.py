"""ICI ring top-k merge for sharded search — the communication-avoiding
replacement for the ``all_gather`` + k-way merge candidate exchange.

The gather path (``parallel/sharded_ann.py`` / ``sharded_knn.py``) ships
every shard's ``[nq, k]`` candidates to every chip and re-selects over the
``n_shards x k`` concatenation: per-rank wire traffic is
``8k(n-1)`` bytes/query and every chip materializes the full candidate
matrix in HBM. This module runs the same merge as a **ring
reduce-scatter + ring all-gather over query blocks** — the
communication-optimal schedule for an associative reduction:

* queries are split into ``n`` blocks; at reduce-scatter hop ``s`` chip
  ``r`` sends its running partial of block ``(r - s) mod n`` to its right
  neighbor and folds the block arriving from the left into its own
  partial — after ``n - 1`` hops chip ``r`` owns the *finished* top-k of
  block ``(r + 1) mod n``;
* an all-gather ring then replicates the finished blocks (values + ids
  only; the merge tie-break lane is no longer needed).

Per-rank wire is ``~20k(n-1)/n`` bytes/query (12 B/candidate while the
tie-break lane rides along, 8 B after) versus the gather path's
``8k(n-1)``: a ``0.4 n`` reduction — 3.2x at 8 chips — and peak memory
stays ``O(k)`` per query instead of ``O(n k)``.

**Bit-parity contract.** The gather path's merge is a stable
``lax.top_k`` over the shard-major concatenation, i.e. a sort by
``(value, concat position)``. Each candidate here carries its concat
position explicitly — ``pos = rank * k + slot`` (unique, total order) —
and every 2k -> k fold merges by ``(value, pos)``. A merge under a total
order is associative and schedule-independent, so the ring reproduces
the gather ids **bit-exactly** at every device count, hop order, and
degraded-health mask (demoted shards' candidates carry worst-value
sentinels and their true ``pos``, losing every fold exactly as they lose
the gather merge — a dead shard degenerates to a pass-through that
forwards its neighbor's buffer unchanged). Values are carried, never
recomputed, so distances are bit-identical too.

Two engines share that schedule:

* :func:`_ring_topk_xla` — ``lax.ppermute`` hops + a 2-key
  ``lax.sort`` fold. Runs everywhere (this is the engine the 8-device
  CPU test mesh exercises for parity) and is what ``merge_mode="ring"``
  means off-TPU.
* :func:`fused_ring_topk` — a Pallas kernel holding the per-block
  partials in VMEM and driving each hop with
  ``pltpu.make_async_remote_copy`` into the right neighbor's scratch,
  double-buffered send/recv slots with deferred send-semaphore waits so
  hop ``s``'s outgoing DMA drains while the hop-``s`` fold runs on the
  VPU. TPU-only: jax 0.4.x cannot interpret remote DMAs on CPU, so the
  dispatch gates on the real backend and the fold kernel is covered by
  an interpret-mode parity test instead
  (:func:`hop_merge` — the same rank-based placement proven bit-exact
  in ``cagra_search._rank_merge``, extended with the ``pos`` tie lane).

:func:`scan_ring_topk` (``merge_mode="fused_ring"``) is the scan-fused
variant of the same schedule: it takes the scan's full ``[nq,
k·refine_ratio]`` candidate tile and folds it to the merge width INSIDE
the ring engine (``_scan_ring_kernel`` stages the fold's winners
directly into the ring's VMEM state; the XLA mirror ``_scan_fold``
consumes the tile slice-wise), so the per-shard top-k never
round-trips through HBM between the scan and the exchange. Wire bytes
are unchanged vs ``ring_topk`` — only winners ride the ring.

Failure semantics: :func:`ring_topk` fires the ``comms.ring_topk``
fault point at trace time (the collective analog of a lost ring
participant — same placement as the ``comms.all_gather`` seam); callers
in ``parallel/`` catch :class:`~raft_tpu.core.errors.KernelFailure` /
runtime errors through ``_guard.kernel_guard`` and fall back to the
gather merge (warn-once, ``fallbacks{algo="ring_topk"}``). Per-ring obs:
``comms.ring.hops`` and ``comms.ring.bytes{direction}`` counters and a
traced ``ring_topk`` span.

VMEM residency of the fused kernel is modeled in
:func:`raft_tpu.ops.pallas.vmem_model.ring_topk_residency` and checked
by ``tools/graft_lint`` under the ``ring_topk`` bindings;
:func:`kernel_scratch_shapes` is asserted against the model in tests.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.parallel._compat import axis_size
from raft_tpu.robust import faults

#: Finite in-kernel "worst" value (see ``cagra_search.WORST``): the
#: rank-based fold places elements with masked one-hot sums, and
#: ``inf * 0`` would poison them with NaNs. The XLA engine keeps true
#: ``+/-inf`` sentinels (no masked arithmetic there).
WORST = 3.0e38

#: Sort-key pos for padding entries: must lose every tie against a real
#: candidate (real pos < n_shards * k << _PAD_POS).
_PAD_POS = jnp.iinfo(jnp.int32).max

#: Query-row chunk of the in-kernel fold — bounds the pairwise-rank
#: body intermediates to ~4 MiB at the serving shape (B=128, w=128).
_FOLD_ROWS = 32

#: Column chunk of the pairwise rank / one-hot placement passes
#: (``cagra_search._RANK_CHUNK``).
_RANK_CHUNK = 64

# The per-query merge wire model moved to the consolidated
# raft_tpu.parallel.wire_model (the planner prices ring-vs-gather from
# it); re-exported at this original home, where the engines' byte
# counters and every pre-planner consumer import it from.
from raft_tpu.parallel.wire_model import (  # noqa: F401  (re-export)
    AG_ENTRY_BYTES,
    RS_ENTRY_BYTES,
    wire_bytes_per_query,
)


# ---------------------------------------------------------------------------
# shared schedule helpers
# ---------------------------------------------------------------------------


def _pad_cols(key, pos, v, i, width: int, select_min: bool):
    """Right-pad the candidate lanes to ``width`` columns with losing
    sentinels (inf key, ``_PAD_POS`` tie-break, -1 id)."""
    pad = ((0, 0), (0, width - key.shape[1]))
    return (
        jnp.pad(key, pad, constant_values=jnp.inf),
        jnp.pad(pos, pad, constant_values=_PAD_POS),
        jnp.pad(v, pad, constant_values=jnp.inf if select_min else -jnp.inf),
        jnp.pad(i, pad, constant_values=-1),
    )


def _scan_fold(key, pos, v, i, k: int, select_min: bool):
    """Streaming local top-k: fold the ``[nq, kc]`` candidate tile into
    ``[nq, k]`` one ``k``-wide slice at a time through :func:`_fold`
    instead of one monolithic width-``kc`` sort. Bit-identical to the
    sort-truncate (the (key, pos) total order makes every fold schedule
    associative) — this is the XLA mirror of the fused kernel's in-VMEM
    scan fold, shaped so the wide tile is consumed slice-wise rather
    than re-materialized sorted."""
    kc = key.shape[1]
    acc = (key[:, :k], pos[:, :k], v[:, :k], i[:, :k])
    for c0 in range(k, kc, k):
        c1 = min(c0 + k, kc)
        sl = tuple(x[:, c0:c1] for x in (key, pos, v, i))
        if c1 - c0 < k:
            sl = _pad_cols(*sl, k, select_min)
        acc = _fold(acc, sl, k)
    return acc


def _prep(v, i, k: int, select_min: bool, axis: str, scan_fold: bool = False):
    """Normalize local candidates to the ring's working layout.

    Returns ``(key, pos, v, i, n, B, nq)`` where the first four are
    ``[n * B, w]`` with ``w = k``: the sort key (``v`` for min-select,
    ``-v`` for max), the global concat position tie-break, and the
    carried value/id payloads. Width is padded (losing sentinels) or
    truncated (a local 2-key top-k — entries past local rank ``k`` can
    never enter the global top-k; ``scan_fold=True`` folds slice-wise
    via :func:`_scan_fold`, bit-identically) to ``k``; query rows are
    padded to a multiple of the axis size."""
    n = axis_size(axis)
    r = lax.axis_index(axis)
    nq, kc = v.shape
    v = v.astype(jnp.float32)
    i = i.astype(jnp.int32)
    pos = (r * kc + lax.broadcasted_iota(jnp.int32, (nq, kc), 1)).astype(jnp.int32)
    key = v if select_min else -v
    if kc > k:
        if scan_fold:
            key, pos, v, i = _scan_fold(key, pos, v, i, k, select_min)
        else:
            key, pos, v, i = lax.sort((key, pos, v, i), dimension=1, num_keys=2)
            key, pos, v, i = key[:, :k], pos[:, :k], v[:, :k], i[:, :k]
    elif kc < k:
        pad = ((0, 0), (0, k - kc))
        key = jnp.pad(key, pad, constant_values=jnp.inf)
        v = jnp.pad(v, pad, constant_values=jnp.inf if select_min else -jnp.inf)
        pos = jnp.pad(pos, pad, constant_values=_PAD_POS)
        i = jnp.pad(i, pad, constant_values=-1)
    B = -(-nq // n)
    rpad = n * B - nq
    if rpad:
        pad = ((0, rpad), (0, 0))
        key = jnp.pad(key, pad, constant_values=jnp.inf)
        v = jnp.pad(v, pad, constant_values=jnp.inf if select_min else -jnp.inf)
        pos = jnp.pad(pos, pad, constant_values=_PAD_POS)
        i = jnp.pad(i, pad, constant_values=-1)
    return key, pos, v, i, n, B, nq


def _fold(a, b, w: int):
    """One 2w -> w merge under the (key, pos) total order. ``a``/``b``
    are (key, pos, val, id) tuples of ``[B, w]`` arrays; pos uniqueness
    makes the fold associative and schedule-independent — the parity
    contract with the gather path's stable ``top_k``."""
    cat = tuple(jnp.concatenate([x, y], axis=1) for x, y in zip(a, b))
    key, pos, v, i = lax.sort(cat, dimension=1, num_keys=2)
    return key[:, :w], pos[:, :w], v[:, :w], i[:, :w]


# ---------------------------------------------------------------------------
# XLA engine: ppermute hops (runs everywhere; the CPU-mesh parity engine)
# ---------------------------------------------------------------------------


def _ring_topk_xla(v, i, k: int, select_min: bool, axis: str, scan_fold: bool = False):
    key, pos, v, i, n, B, nq = _prep(v, i, k, select_min, axis, scan_fold=scan_fold)
    r = lax.axis_index(axis)
    state = tuple(x.reshape(n, B, k) for x in (key, pos, v, i))
    if n == 1:
        _, _, ov, oi = tuple(x[0] for x in state)
        return ov[:nq], oi[:nq]
    perm = [(j, (j + 1) % n) for j in range(n)]
    take = lambda t, b: tuple(  # noqa: E731
        lax.dynamic_index_in_dim(x, b, 0, keepdims=False) for x in t
    )
    put = lambda t, blk, b: tuple(  # noqa: E731
        lax.dynamic_update_index_in_dim(x, y, b, 0) for x, y in zip(t, blk)
    )
    # -- reduce-scatter: after hop s, my partial of block (r-s-1)%n has
    # folded in every rank <= me's candidates; after n-1 hops block
    # (r+1)%n is finished on rank r.
    for s in range(n - 1):
        send = take(state, (r - s) % n)
        recv = tuple(lax.ppermute(x, axis, perm) for x in send)
        b = (r - s - 1) % n
        state = put(state, _fold(take(state, b), recv, k), b)
    # -- all-gather of the finished blocks (key/pos lanes done their job)
    out_v, out_i = state[2], state[3]
    for s in range(n - 1):
        send = take((out_v, out_i), (r + 1 - s) % n)
        rv, ri = (lax.ppermute(x, axis, perm) for x in send)
        b = (r - s) % n
        out_v = lax.dynamic_update_index_in_dim(out_v, rv, b, 0)
        out_i = lax.dynamic_update_index_in_dim(out_i, ri, b, 0)
    return out_v.reshape(n * B, k)[:nq], out_i.reshape(n * B, k)[:nq]


# ---------------------------------------------------------------------------
# fused engine: Pallas async-remote-copy ring (real TPU ICI only)
# ---------------------------------------------------------------------------


def kernel_scratch_shapes(n: int, B: int, w: int):
    """The fused kernel's scratch declarations, exposed so tests can
    assert them against ``vmem_model.ring_topk_residency`` (the drift
    guard every fused kernel in this tree carries)."""
    return [
        pltpu.VMEM((n, B, w), jnp.float32),   # state_key
        pltpu.VMEM((n, B, w), jnp.int32),     # state_pos
        pltpu.VMEM((n, B, w), jnp.float32),   # state_val
        pltpu.VMEM((n, B, w), jnp.int32),     # state_id
        pltpu.VMEM((2, B, w), jnp.float32),   # send_key
        pltpu.VMEM((2, B, w), jnp.int32),     # send_pos
        pltpu.VMEM((2, B, w), jnp.float32),   # send_val
        pltpu.VMEM((2, B, w), jnp.int32),     # send_id
        pltpu.VMEM((2, B, w), jnp.float32),   # recv_key
        pltpu.VMEM((2, B, w), jnp.int32),     # recv_pos
        pltpu.VMEM((2, B, w), jnp.float32),   # recv_val
        pltpu.VMEM((2, B, w), jnp.int32),     # recv_id
        pltpu.SemaphoreType.DMA((2, 4)),      # send sems [slot, lane]
        pltpu.SemaphoreType.DMA((2, 4)),      # recv sems [slot, lane]
    ]


def scan_kernel_scratch_shapes(n: int, B: int, w: int, kc: int):
    """Scratch declarations of the scan-fused ring kernel — identical to
    :func:`kernel_scratch_shapes` (the scan fold reuses the state
    buffers as its accumulator target; only the *input* refs widen to
    ``kc`` columns). Exposed for the same vmem_model drift guard."""
    expects(kc % w == 0 and kc >= w,
            "scan width %d must be a positive multiple of merge width %d", kc, w)
    return kernel_scratch_shapes(n, B, w)


def _rank_merge_pos(uk, up, uv, ui, w: int):
    """Stable (key, pos)-ordered top-``w`` of the union ``[rows, 2w]``
    via pairwise ranks + one-hot placement — ``cagra_search._rank_merge``
    with the value tie broken by the unique concat position instead of
    the local column, which is what makes the fold order-independent.
    ``rank(i) = #{j : k_j < k_i or (k_j == k_i and p_j < p_i)}`` is a
    permutation of ``0..2w-1`` (pos unique); ranks ``< w`` survive."""
    rows, m = uk.shape
    parts = []
    for i0 in range(0, m, _RANK_CHUNK):
        i1 = min(i0 + _RANK_CHUNK, m)
        ki = uk[:, None, i0:i1]
        pi = up[:, None, i0:i1]
        less = (uk[:, :, None] < ki).astype(jnp.int32)
        tie = ((uk[:, :, None] == ki) & (up[:, :, None] < pi)).astype(jnp.int32)
        parts.append(jnp.sum(less + tie, axis=1))
    rank = jnp.concatenate(parts, axis=1)  # [rows, 2w]
    outs = [[] for _ in range(4)]
    for p0 in range(0, w, _RANK_CHUNK):
        p1 = min(p0 + _RANK_CHUNK, w)
        pidx = lax.broadcasted_iota(jnp.int32, (1, 1, p1 - p0), 2) + p0
        oh = rank[:, :, None] == pidx  # [rows, 2w, chunk]
        for o, u in zip(outs, (uk, up, uv, ui)):
            z = jnp.zeros((), u.dtype)
            o.append(jnp.sum(jnp.where(oh, u[:, :, None], z), axis=1))
    return tuple(jnp.concatenate(o, axis=1) for o in outs)


def _hop_merge_kernel(ak, ap, av, ai, bk, bp, bv, bi, ok, op, ov, oi):
    """Single-device fold kernel: merge two [qt, w] candidate tiles into
    the (key, pos)-ordered top-w. This is the exact fold the ring kernel
    runs per hop; factored out so interpret-mode tests can pin it
    against the XLA ``_fold`` bit-for-bit."""
    w = ak.shape[1]
    uk = jnp.concatenate([ak[:], bk[:]], axis=1)
    up = jnp.concatenate([ap[:], bp[:]], axis=1)
    uv = jnp.concatenate([av[:], bv[:]], axis=1)
    ui = jnp.concatenate([ai[:], bi[:]], axis=1)
    rk, rp, rv, ri = _rank_merge_pos(uk, up, uv, ui, w)
    ok[:], op[:], ov[:], oi[:] = rk, rp, rv, ri


@functools.partial(jax.jit, static_argnames=("qt", "interpret"))
def hop_merge(a, b, qt: int = _FOLD_ROWS, interpret: bool = True):
    """Run one 2w -> w fold through the Pallas kernel (grid over
    ``qt``-row tiles). ``a``/``b`` are (key, pos, val, id) tuples of
    ``[rows, w]`` arrays. Used by tests (interpret mode on CPU) to prove
    the in-kernel fold bit-matches the XLA fold; the ring kernel inlines
    the same ``_rank_merge_pos``."""
    rows, w = a[0].shape
    expects(rows % qt == 0, "fold rows %d not divisible by tile %d", rows, qt)
    grid = (rows // qt,)
    tile = lambda: pl.BlockSpec((qt, w), lambda g: (g, 0))  # noqa: E731
    dts = (jnp.float32, jnp.int32, jnp.float32, jnp.int32)
    return pl.pallas_call(
        _hop_merge_kernel,
        grid=grid,
        in_specs=[tile() for _ in range(8)],
        out_specs=tuple(tile() for _ in range(4)),
        out_shape=tuple(jax.ShapeDtypeStruct((rows, w), d) for d in dts),
        interpret=interpret,
    )(*a, *b)


def _ring_body(n: int, B: int, w: int, axis: str, ov, oi, state, send, recv,
               send_sem, recv_sem):
    """The shared ring schedule: reduce-scatter then all-gather, one
    ``make_async_remote_copy`` bundle per hop into the right neighbor's
    recv slot, fold on the VPU while the outgoing DMA drains (its
    send-semaphore wait is deferred until the slot is restaged two hops
    later — the double-buffer discipline of the guide's ring
    all-gather). ``state`` must already hold the staged ``[n, B, w]``
    per-block partials; :func:`_ring_kernel` stages a straight copy of
    the inputs, :func:`_scan_ring_kernel` stages the scan fold's
    winners."""
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, n)
    left = lax.rem(me + n - 1, n)

    # neighbor rendezvous: nobody DMAs into a peer still setting up
    # (staging touches only local state, never a recv slot, so running
    # it before the barrier is safe — peers cannot DMA until we signal)
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=(left,))
    pltpu.semaphore_signal(barrier, inc=1, device_id=(right,))
    pltpu.semaphore_wait(barrier, 2)

    def start_hop(slot, src_block, lanes):
        """Stage ``state[src_block]`` into the send slot and launch one
        remote copy per lane into the right neighbor's recv slot."""
        for ln in lanes:
            send[ln][slot] = pl.load(
                state[ln], (pl.ds(src_block, 1), slice(None), slice(None))
            )[0]
            pltpu.make_async_remote_copy(
                src_ref=send[ln].at[slot],
                dst_ref=recv[ln].at[slot],
                send_sem=send_sem.at[slot, ln],
                recv_sem=recv_sem.at[slot, ln],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ).start()

    lanes_rs = (0, 1, 2, 3)
    lanes_ag = (2, 3)  # finished blocks travel as (val, id) only

    # -- reduce-scatter hops ------------------------------------------------
    for s in range(n - 1):
        slot = s % 2
        if s >= 2:  # the slot's previous send must have drained
            for ln in lanes_rs:
                pltpu.semaphore_wait(send_sem[slot, ln], 1)
        start_hop(slot, lax.rem(me + n - s, n) if s else me, lanes_rs)
        for ln in lanes_rs:
            pltpu.semaphore_wait(recv_sem[slot, ln], 1)
        dst = lax.rem(me + n - s - 1 + n, n)
        cur = tuple(pl.load(st, (pl.ds(dst, 1), slice(None), slice(None)))[0] for st in state)
        got = tuple(rcv[slot] for rcv in recv)
        for q0 in range(0, B, _FOLD_ROWS):
            q1 = min(q0 + _FOLD_ROWS, B)
            uk = jnp.concatenate([cur[0][q0:q1], got[0][q0:q1]], axis=1)
            up = jnp.concatenate([cur[1][q0:q1], got[1][q0:q1]], axis=1)
            uv = jnp.concatenate([cur[2][q0:q1], got[2][q0:q1]], axis=1)
            ui = jnp.concatenate([cur[3][q0:q1], got[3][q0:q1]], axis=1)
            fk, fp, fv, fi = _rank_merge_pos(uk, up, uv, ui, w)
            for st, f in zip(state, (fk, fp, fv, fi)):
                pl.store(st, (pl.ds(dst, 1), pl.ds(q0, q1 - q0), slice(None)), f[None])
    for s in range(max(0, n - 3), n - 1):  # drain outstanding sends
        for ln in lanes_rs:
            pltpu.semaphore_wait(send_sem[s % 2, ln], 1)

    # rank r owns finished block (r+1)%n; write it to the output
    own = lax.rem(me + 1, n)
    for dst_ref, ln in ((ov, 2), (oi, 3)):
        blk = pl.load(state[ln], (pl.ds(own, 1), slice(None), slice(None)))[0]
        pl.store(dst_ref, (pl.ds(own * B, B), slice(None)), blk)

    # -- all-gather hops: forward the newest finished block rightward -------
    for s in range(n - 1):
        slot = s % 2
        if s >= 2:
            for ln in lanes_ag:
                pltpu.semaphore_wait(send_sem[slot, ln], 1)
        # the block being forwarded is already in the output; stage from
        # state (hop 0: own block) or from the previous hop's recv slot
        if s == 0:
            start_hop(slot, own, lanes_ag)
        else:
            for ln in lanes_ag:
                send[ln][slot] = recv[ln][1 - slot]
                pltpu.make_async_remote_copy(
                    src_ref=send[ln].at[slot],
                    dst_ref=recv[ln].at[slot],
                    send_sem=send_sem.at[slot, ln],
                    recv_sem=recv_sem.at[slot, ln],
                    device_id=(right,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                ).start()
        for ln in lanes_ag:
            pltpu.semaphore_wait(recv_sem[slot, ln], 1)
        dst = lax.rem(me + n - s, n)
        pl.store(ov, (pl.ds(dst * B, B), slice(None)), recv[2][slot])
        pl.store(oi, (pl.ds(dst * B, B), slice(None)), recv[3][slot])
    for s in range(max(0, n - 3), n - 1):
        for ln in lanes_ag:
            pltpu.semaphore_wait(send_sem[s % 2, ln], 1)


def _ring_kernel(
    n: int, B: int, w: int, axis: str,
    ink, inp, inv, ini, ov, oi,
    sk, sp, sv, si,          # state [n, B, w]
    tk, tp, tv, ti,          # send slots [2, B, w]
    rk, rp, rv, ri,          # recv slots [2, B, w]
    send_sem, recv_sem,
):
    """Width-``w`` inputs: stage a straight copy of each query block
    into the state buffers, then run the shared :func:`_ring_body`."""
    for b in range(n):
        sk[b], sp[b] = ink[b * B:(b + 1) * B], inp[b * B:(b + 1) * B]
        sv[b], si[b] = inv[b * B:(b + 1) * B], ini[b * B:(b + 1) * B]
    _ring_body(n, B, w, axis, ov, oi, (sk, sp, sv, si), (tk, tp, tv, ti),
               (rk, rp, rv, ri), send_sem, recv_sem)


def _scan_ring_kernel(
    n: int, B: int, w: int, kc: int, axis: str,
    ink, inp, inv, ini, ov, oi,
    sk, sp, sv, si,          # state [n, B, w]
    tk, tp, tv, ti,          # send slots [2, B, w]
    rk, rp, rv, ri,          # recv slots [2, B, w]
    send_sem, recv_sem,
):
    """Scan-fused staging: the inputs are the scan's FULL ``[n*B, kc]``
    candidate tile (``kc`` a multiple of ``w``; e.g. ``k·refine_ratio``
    wide). Each query block is folded ``w`` columns at a time through
    :func:`_rank_merge_pos` straight into the state buffers — the local
    top-``w`` never exists as an HBM array between the scan and the ring
    — and the shared :func:`_ring_body` takes over. Bit-identical to
    staging a pre-sorted top-``w``: every fold is under the (key, pos)
    total order."""
    state = (sk, sp, sv, si)
    ins = (ink, inp, inv, ini)
    for b in range(n):
        for q0 in range(0, B, _FOLD_ROWS):
            q1 = min(q0 + _FOLD_ROWS, B)
            acc = tuple(x[b * B + q0:b * B + q1, 0:w] for x in ins)
            for c0 in range(w, kc, w):
                sl = tuple(x[b * B + q0:b * B + q1, c0:c0 + w] for x in ins)
                uk = jnp.concatenate([acc[0], sl[0]], axis=1)
                up = jnp.concatenate([acc[1], sl[1]], axis=1)
                uv = jnp.concatenate([acc[2], sl[2]], axis=1)
                ui = jnp.concatenate([acc[3], sl[3]], axis=1)
                acc = _rank_merge_pos(uk, up, uv, ui, w)
            for st, f in zip(state, acc):
                pl.store(st, (pl.ds(b, 1), pl.ds(q0, q1 - q0), slice(None)), f[None])
    _ring_body(n, B, w, axis, ov, oi, state, (tk, tp, tv, ti),
               (rk, rp, rv, ri), send_sem, recv_sem)


def fused_ring_topk(v, i, k: int, select_min: bool, axis: str, collective_id: int = 7):
    """Pallas async-remote-copy ring (inside ``shard_map``). Same
    schedule and (key, pos) fold as :func:`_ring_topk_xla`; real-TPU
    only — remote DMAs have no CPU interpreter on this jax release."""
    key, pos, vv, ii, n, B, nq = _prep(v, i, k, select_min, axis)
    # in-kernel fold arithmetic needs finite sentinels (inf * 0 = NaN)
    key = jnp.clip(key, -WORST, WORST)
    vals = jnp.clip(vv, -WORST, WORST)
    if n == 1:
        return vv[:nq], ii[:nq]
    w = k
    dts = (jnp.float32, jnp.int32)
    out_v, out_i = pl.pallas_call(
        functools.partial(_ring_kernel, n, B, w, axis),
        out_shape=tuple(jax.ShapeDtypeStruct((n * B, w), d) for d in dts),
        scratch_shapes=kernel_scratch_shapes(n, B, w),
        compiler_params=pltpu.TPUCompilerParams(collective_id=collective_id),
    )(key, pos, vals, ii)
    # restore the inf sentinels the XLA/gather paths report
    worst = jnp.float32(WORST if select_min else -WORST)
    inf = jnp.float32(jnp.inf if select_min else -jnp.inf)
    out_v = jnp.where((out_v == worst) & (out_i < 0), inf, out_v)
    return out_v[:nq], out_i[:nq]


def fused_scan_ring_topk(v, i, k: int, select_min: bool, axis: str,
                         collective_id: int = 8):
    """Scan-fused Pallas ring (inside ``shard_map``): hands the scan's
    full ``[nq, kc]`` candidate tile to :func:`_scan_ring_kernel`, which
    folds it to the merge width in VMEM and runs the same ring as
    :func:`fused_ring_topk` (distinct ``collective_id`` — the two rings
    may coexist in one program). Real-TPU only, like the plain fused
    ring."""
    n = axis_size(axis)
    r = lax.axis_index(axis)
    nq, kc = v.shape
    if n == 1 or kc <= k:
        # nothing to fuse: no wide local tile (or no ring at all)
        return _ring_topk_xla(v, i, k, select_min, axis, scan_fold=True)
    vals = v.astype(jnp.float32)
    ids = i.astype(jnp.int32)
    pos = (r * kc + lax.broadcasted_iota(jnp.int32, (nq, kc), 1)).astype(jnp.int32)
    key = vals if select_min else -vals
    w = k
    kcp = -(-kc // w) * w
    if kcp > kc:
        key, pos, vals, ids = _pad_cols(key, pos, vals, ids, kcp, select_min)
    B = -(-nq // n)
    rpad = n * B - nq
    if rpad:
        pad = ((0, rpad), (0, 0))
        key = jnp.pad(key, pad, constant_values=jnp.inf)
        vals = jnp.pad(vals, pad, constant_values=jnp.inf if select_min else -jnp.inf)
        pos = jnp.pad(pos, pad, constant_values=_PAD_POS)
        ids = jnp.pad(ids, pad, constant_values=-1)
    # in-kernel fold arithmetic needs finite sentinels (inf * 0 = NaN)
    key = jnp.clip(key, -WORST, WORST)
    vals = jnp.clip(vals, -WORST, WORST)
    dts = (jnp.float32, jnp.int32)
    out_v, out_i = pl.pallas_call(
        functools.partial(_scan_ring_kernel, n, B, w, kcp, axis),
        out_shape=tuple(jax.ShapeDtypeStruct((n * B, w), d) for d in dts),
        scratch_shapes=scan_kernel_scratch_shapes(n, B, w, kcp),
        compiler_params=pltpu.TPUCompilerParams(collective_id=collective_id),
    )(key, pos, vals, ids)
    worst = jnp.float32(WORST if select_min else -WORST)
    inf = jnp.float32(jnp.inf if select_min else -jnp.inf)
    out_v = jnp.where((out_v == worst) & (out_i < 0), inf, out_v)
    return out_v[:nq], out_i[:nq]


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def ring_topk(
    v, i, k: int, *, select_min: bool = True, axis: str = "data",
    use_fused: bool | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Ring merge of per-shard candidates — call inside a ``shard_map``
    body where the gather path would ``all_gather`` + ``merge_parts``.

    ``v``/``i`` are the shard-local ``[nq, k_local]`` top-k (ids already
    global); returns replicated ``(vals [nq, k], ids [nq, k])``
    bit-identical to ``merge_parts`` over the shard-major concatenation.
    ``use_fused=None`` picks the Pallas remote-DMA kernel on real TPU
    and the ``ppermute`` engine elsewhere; failures escape to the
    caller's ``kernel_guard`` -> gather fallback.
    """
    n = axis_size(axis)
    # trace-time seam: the collective analog of a lost ring participant
    # (same placement as the comms.all_gather fault point)
    faults.fire("comms.ring_topk", axis=str(axis), n_shards=int(n))
    if use_fused is None:
        use_fused = jax.default_backend() == "tpu"
    if obs.is_enabled():
        hops = 2 * max(0, n - 1)
        B = -(-v.shape[0] // n)
        rs = (n - 1) * B * k * RS_ENTRY_BYTES
        ag = (n - 1) * B * k * AG_ENTRY_BYTES
        obs.inc("comms.ring.hops", hops, axis=str(axis))
        obs.inc("comms.ring.bytes", float(rs + ag), axis=str(axis), direction="send")
        obs.inc("comms.ring.bytes", float(rs + ag), axis=str(axis), direction="recv")
        with obs.span(
            "ring_topk", axis=str(axis), n_shards=int(n), k=int(k),
            engine="fused" if use_fused else "xla", traced=True,
        ):
            if use_fused:
                return fused_ring_topk(v, i, k, select_min, axis)
            return _ring_topk_xla(v, i, k, select_min, axis)
    if use_fused:
        return fused_ring_topk(v, i, k, select_min, axis)
    return _ring_topk_xla(v, i, k, select_min, axis)


def scan_ring_topk(
    v, i, k: int, *, select_min: bool = True, axis: str = "data",
    use_fused: bool | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Scan-fused ring merge: like :func:`ring_topk` but takes the
    scan's FULL ``[nq, k_candidates]`` tile (any width ≥ ``k``, ids
    already global) and runs the local top-``k`` fold inside the ring
    engine, so the per-shard winners never materialize in HBM between
    the scan and the exchange (``merge_mode="fused_ring"``).

    Same (value, position) total order as the gather path's stable merge
    over the shard-major width-``k_candidates`` concatenation — the
    global top-k is a subset of the per-shard top-k, so folding locally
    first is bit-exact. Wire bytes are identical to ``ring_topk``; obs
    counters land under the same ``comms.ring.*`` names and the shared
    ``ring_topk`` span (``engine="scan_fused"/"scan_xla"``). Failures
    escape to the caller's ``kernel_guard`` → gather fallback
    (``fallbacks{algo="scan_ring_topk"}``)."""
    n = axis_size(axis)
    # same seam as ring_topk (a lost participant kills either ring);
    # kind="scan" lets chaos drills target just the fused path
    faults.fire("comms.ring_topk", axis=str(axis), n_shards=int(n), kind="scan")
    if use_fused is None:
        use_fused = jax.default_backend() == "tpu"

    def run():
        if use_fused:
            return fused_scan_ring_topk(v, i, k, select_min, axis)
        return _ring_topk_xla(v, i, k, select_min, axis, scan_fold=True)

    if obs.is_enabled():
        hops = 2 * max(0, n - 1)
        B = -(-v.shape[0] // n)
        rs = (n - 1) * B * k * RS_ENTRY_BYTES
        ag = (n - 1) * B * k * AG_ENTRY_BYTES
        obs.inc("comms.ring.hops", hops, axis=str(axis))
        obs.inc("comms.ring.bytes", float(rs + ag), axis=str(axis), direction="send")
        obs.inc("comms.ring.bytes", float(rs + ag), axis=str(axis), direction="recv")
        with obs.span(
            "ring_topk", axis=str(axis), n_shards=int(n), k=int(k),
            engine="scan_fused" if use_fused else "scan_xla", traced=True,
        ):
            return run()
    return run()
