"""Typed failure boundary for the fused Pallas entry points.

Lowering/compile failures escape ``pallas_call`` as whatever
jax/jaxlib/mosaic type the toolchain produced that release; the serving
dispatch needs ONE type to key its fused→XLA fallback on. This guard
translates toolchain-originated exceptions into
:class:`raft_tpu.core.errors.KernelFailure` (chaining the original) while
letting library errors (``RaftError``) and plain caller bugs through
untouched.
"""
from __future__ import annotations

import contextlib

from raft_tpu.core.errors import KernelFailure, RaftError


@contextlib.contextmanager
def kernel_guard(name: str):
    """Translate jax/jaxlib-originated failures in the block into
    :class:`KernelFailure` (``__cause__`` keeps the original)."""
    try:
        yield
    except RaftError:
        raise
    except Exception as e:
        mod = type(e).__module__ or ""
        if mod.split(".")[0] in ("jax", "jaxlib", "mlir"):
            raise KernelFailure(f"{name}: {type(e).__name__}: {e}") from e
        raise
