"""Pallas fused probed-list scan for IVF-Flat search.

Reference analog: the fused interleaved-scan kernel
(``neighbors/detail/ivf_flat_interleaved_scan-inl.cuh:687``) — one CUDA
kernel that walks each query's probed lists, computes distances, and keeps
a per-query top-k, never materializing the full distance matrix.

TPU design
----------
The dense-scan XLA path (:func:`raft_tpu.neighbors.ivf_flat.flat_scan_core`)
streams the WHOLE padded index through the MXU and masks unprobed lists,
because XLA has no efficient data-dependent gather. That costs brute-force
FLOPs/bandwidth regardless of ``n_probes``. This kernel restores the IVF
work savings with three pieces:

1. **Scalar-prefetch DMA**: the grid is ``(query_tile, probe_slot)`` and the
   list-data block index map reads a prefetched probe table, so Mosaic's
   DMA engine streams exactly the probed ``[max_list, d]`` blocks from HBM
   into VMEM (double-buffered) — lists nobody probes are never touched.
2. **Tile-coherent queries**: probing is per query, DMA is per query-*tile*.
   Queries are sorted by the *spatial rank* of their nearest center (a
   PCA-bisection ordering of the coarse centroids, computed at build), so
   the ``QT`` queries of a tile probe nearly the same lists and the
   tile-union probe table stays small. Extra lists a tile scans beyond one
   query's own probes only *add* candidates (scored exactly), so per-query
   recall is >= the probe path's whenever the union fits the table; the
   table keeps the most-shared lists when it does not.
3. **In-kernel running top-k**: a VMEM accumulator merged per probe step,
   either exactly (``merge="exact"``: k rounds of min-extract over the full
   ``max_list`` width) or via a banked lane-group pre-compression
   (``merge="seg"``/``"seg1"``/``"seg4"``: per-(lane, bank) min over
   sublane groups first — the same PartialReduce idea as
   ``lax.approx_max_k``; ``seg`` = 2 banks, more banks = fewer
   same-lane collisions between candidates, slightly wider extract).

   ``merge="bank"``/``"bankN"`` goes one step further: the per-step
   compressed candidates are **min-merged elementwise** into a persistent
   ``[qt, N*128]`` (value, slot) buffer — 3 VPU selects per step — and the
   k-round extraction runs only every ``extract_every`` steps (0 = once at
   the end). The per-step cost drops from "compress + concat + k
   min-extract rounds" (the round-3 bottleneck: ~3-4x the matmul time) to
   "compress + 3 selects". The price is cross-step lane collisions: two
   candidates from different probe steps sharing a (lane, bank) slot keep
   only the better one. With N*128 slots and the true top-k spread
   uniformly over lanes, the expected loss is ~C(k,2)/(N*128) of one
   candidate per query (<0.5% recall@10 at N=8); ``extract_every`` bounds
   the collision window when that matters.

4. **Column-chunked scoring** (``col_chunk``): the [qt, m] score tile is
   computed in column slices so the f32 intermediate stays small enough to
   raise ``qt`` (bigger query tiles amortize the per-tile DMA of shared
   lists). Only supported with bank merge (slices merge into the
   persistent buffer; no per-slice extraction needed).

The kernel supports L2Expanded / L2SqrtExpanded / InnerProduct /
CosineExpanded, prefilters (folded into ``list_indices`` outside), and runs
in interpret mode on CPU for tests.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType
from raft_tpu.utils.math import cdiv

_SUPPORTED = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.InnerProduct,
        DistanceType.CosineExpanded,
    }
)


def supported_metric(metric: DistanceType) -> bool:
    return metric in _SUPPORTED


# ---------------------------------------------------------------------------
# spatial ordering of the coarse centers (build-time, host)
# ---------------------------------------------------------------------------


def spatial_center_rank(centers: np.ndarray, leaf: int = 8) -> np.ndarray:
    """PCA-bisection rank of the coarse centers: recursively split along
    the local principal direction at the median, so lists with nearby ranks
    are nearby in space. Sorting queries by ``rank[top1_center]`` makes
    query tiles probe-coherent (piece 2 of the kernel design). Host-side,
    one-time at build; O(n_lists * d * log n_lists)."""
    centers = np.asarray(centers, np.float64)
    n = centers.shape[0]
    rank = np.empty((n,), np.int32)
    pos = 0

    stack = [np.arange(n)]
    out = []
    while stack:
        idx = stack.pop()
        if len(idx) <= leaf:
            out.append(idx)
            continue
        x = centers[idx]
        x = x - x.mean(axis=0)
        # principal direction of the small [len, d] block via the d x d gram
        cov = x.T @ x
        # power iteration: cheap + deterministic, avoids full eigh cost
        v = np.ones((cov.shape[0],)) / np.sqrt(cov.shape[0])
        for _ in range(16):
            v = cov @ v
            v = v / max(np.linalg.norm(v), 1e-30)
        proj = x @ v
        order = np.argsort(proj, kind="stable")
        half = len(idx) // 2
        # push right first so left pops first -> in-order traversal
        stack.append(idx[order[half:]])
        stack.append(idx[order[:half]])
    for idx in out:
        rank[idx] = np.arange(pos, pos + len(idx), dtype=np.int32)
        pos += len(idx)
    return rank


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _extract_topk(cv, ci, k: int):
    """k rounds of (min, first-argmin, mask) over the candidate width.
    All VPU-friendly ops: compare/select/reduce — no gathers, no sorts."""
    cols = lax.broadcasted_iota(jnp.int32, cv.shape, 1)
    big_col = jnp.int32(2**30)
    vs, ids = [], []
    for _ in range(k):
        mv = jnp.min(cv, axis=1, keepdims=True)
        sel = jnp.min(jnp.where(cv == mv, cols, big_col), axis=1, keepdims=True)
        mid = jnp.sum(jnp.where(cols == sel, ci, 0), axis=1, keepdims=True)
        mid = jnp.where(jnp.isinf(mv), -1, mid)
        vs.append(mv)
        ids.append(mid)
        cv = jnp.where(cols == sel, jnp.inf, cv)
    return jnp.concatenate(vs, axis=1), jnp.concatenate(ids, axis=1)


def _seg_compress(score, base, qt: int, m: int, banks: int):
    """Lane-group pre-compression: [qt, m] -> [qt, banks * 128] keeping
    per-(lane, bank) minima over the sublane groups (the PartialReduce
    shape of ``lax.approx_max_k``), group ``g`` assigned to bank
    ``g % banks``. More banks -> fewer collisions between same-lane
    candidates (two true top-k rows of one list collide only when they
    share BOTH lane and bank parity), at linear extract-width cost.
    Tracks only the winning group index per lane — the full [qt, m] slot
    iota never materializes — and reconstructs
    ``slot = base + g * 128 + lane`` at the end."""
    mg = cdiv(m, 128)
    mpad = mg * 128
    if mpad != m:
        score = jnp.pad(score, ((0, 0), (0, mpad - m)), constant_values=jnp.inf)
    lane = lax.broadcasted_iota(jnp.int32, (qt, 128), 1)
    out_v, out_s = [], []
    for b in range(banks):
        groups = list(range(b, mg, banks))
        if not groups:
            continue
        best_v = score[:, groups[0] * 128 : (groups[0] + 1) * 128]
        best_g = jnp.full((qt, 128), groups[0], jnp.int32)
        for g in groups[1:]:
            v = score[:, g * 128 : (g + 1) * 128]
            take = v < best_v
            best_v = jnp.where(take, v, best_v)
            best_g = jnp.where(take, g, best_g)
        out_v.append(best_v)
        out_s.append(jnp.where(jnp.isinf(best_v), -1, base + best_g * 128 + lane))
    return jnp.concatenate(out_v, axis=1), jnp.concatenate(out_s, axis=1)


def _bank_count(merge: str) -> int:
    import re

    m = re.search(r"(\d+)$", merge)
    n = int(m.group(1)) if m else 0
    if merge.startswith("bank"):
        return n or 4
    if merge.startswith("seg"):
        return n or 2
    return 0


def _eff_banks(merge: str, m: int, col_chunk: int) -> int:
    """Banks clamped to the lane-group count of one compress call (a block
    slice narrower than banks*128 fills fewer banks)."""
    mc = col_chunk if col_chunk else m
    return max(1, min(_bank_count(merge), cdiv(mc, 128)))


def _make_kernel(*, k, metric, merge, qt, m, n_steps, precision, extract_every, col_chunk):
    bank_mode = merge.startswith("bank")
    banks = _eff_banks(merge, m, col_chunk) if bank_mode else _bank_count(merge)
    mc = col_chunk if (bank_mode and col_chunk) else m
    n_cc = m // mc

    def score_slice(q, ld_ref, ln_ref, li_ref, lo: int):
        """One [qt, mc] score slice: matmul + prepared epilogue."""
        y = ld_ref[0, lo : lo + mc, :]
        if y.dtype == jnp.bfloat16:
            # bf16 lists ride the native bf16 MXU path with f32 accum
            q = q.astype(jnp.bfloat16)
        else:
            y = y.astype(jnp.float32)  # int8 lists cast per block
        dot = lax.dot_general(
            q,
            y,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )  # [qt, mc]
        # ln_ref carries the PREPARED epilogue term (see the wrapper):
        # L2 -> norms with +inf folded in for invalid slots, IP -> a
        # 0/+inf penalty, cosine -> precomputed rsqrt norm scales — so
        # validity and normalization cost no extra [qt, m] passes
        ln = ln_ref[0, 0, lo : lo + mc]
        if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
            return ln[None, :] - 2.0 * dot
        if metric == DistanceType.InnerProduct:
            return ln[None, :] - dot
        # CosineExpanded; queries pre-normalized by the wrapper
        return jnp.where(
            (li_ref[0, 0, lo : lo + mc] >= 0)[None, :], -dot * ln[None, :], jnp.inf
        )

    def kernel(pr_ref, pv_ref, q_ref, ld_ref, ln_ref, li_ref, outv_ref, outi_ref,
               accv, acci, bankv=None, banki=None):
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            accv[...] = jnp.full((qt, k), jnp.inf, jnp.float32)
            acci[...] = jnp.full((qt, k), -1, jnp.int32)
            if bank_mode:
                bankv[...] = jnp.full((qt, banks * 128), jnp.inf, jnp.float32)
                banki[...] = jnp.full((qt, banks * 128), -1, jnp.int32)

        @pl.when(pv_ref[i, j] > 0)
        def _():
            q = q_ref[...]
            base = pr_ref[i, j] * m
            if bank_mode:
                # compress each column slice, min-merge into the bank buffer
                for cc in range(n_cc):
                    score = score_slice(q, ld_ref, ln_ref, li_ref, cc * mc)
                    if merge.startswith("bankraw"):  # perf probe: no compress
                        bankv[...] = score[:, : banks * 128]
                        banki[...] = jnp.full((qt, banks * 128), 1, jnp.int32)
                        continue
                    v, s = _seg_compress(score, base + cc * mc, qt, mc, banks)
                    if merge.startswith("bankover"):  # perf probe: no min-merge
                        bankv[...] = v
                        banki[...] = s
                    else:
                        take = v < bankv[...]
                        bankv[...] = jnp.where(take, v, bankv[...])
                        banki[...] = jnp.where(take, s, banki[...])
            else:
                score = score_slice(q, ld_ref, ln_ref, li_ref, 0)
                if merge.startswith("seg"):
                    score, slot = _seg_compress(score, base, qt, m, banks)
                else:
                    valid = jnp.isfinite(score)
                    slot = jnp.where(
                        valid, base + lax.broadcasted_iota(jnp.int32, (qt, m), 1), -1
                    )
                cv = jnp.concatenate([accv[...], score], axis=1)
                ci = jnp.concatenate([acci[...], slot], axis=1)
                nv, ni = _extract_topk(cv, ci, k)
                accv[...] = nv
                acci[...] = ni

        if bank_mode:
            # periodic + final extraction of the bank buffer into the top-k
            # accumulator; resetting bounds the cross-step collision window
            if extract_every and extract_every < n_steps:
                do_extract = ((j + 1) % extract_every == 0) | (j == n_steps - 1)
            else:
                do_extract = j == n_steps - 1

            @pl.when(do_extract)
            def _():
                cv = jnp.concatenate([accv[...], bankv[...]], axis=1)
                ci = jnp.concatenate([acci[...], banki[...]], axis=1)
                nv, ni = _extract_topk(cv, ci, k)
                accv[...] = nv
                acci[...] = ni
                bankv[...] = jnp.full((qt, banks * 128), jnp.inf, jnp.float32)
                banki[...] = jnp.full((qt, banks * 128), -1, jnp.int32)

        @pl.when(j == n_steps - 1)
        def _():
            outv_ref[...] = accv[...]
            outi_ref[...] = acci[...]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "metric", "qt", "merge", "precision", "extract_every", "col_chunk", "interpret"
    ),
)
def fused_list_topk(
    list_data,
    list_norms,
    list_indices,
    queries_sorted,
    tile_probes,
    probe_valid,
    *,
    k: int,
    metric: DistanceType,
    qt: int,
    merge: str = "seg",
    precision: str = "highest",
    extract_every: int = 0,
    col_chunk: int = 0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Run the fused probed-list scan.

    ``queries_sorted [nq_pad, d]`` (nq_pad % qt == 0, f32, tile-coherent
    order), ``tile_probes/probe_valid [nq_pad//qt, P]`` int32. Returns
    ``(scores [nq_pad, k] asc, slots [nq_pad, k])`` where slot =
    ``list_id * max_list + row`` (or -1).
    """
    n_lists, m, d = list_data.shape
    nq_pad = queries_sorted.shape[0]
    n_qt, n_steps = tile_probes.shape
    assert nq_pad == n_qt * qt
    if col_chunk:
        expects(merge.startswith("bank"), "col_chunk requires bank merge")
        expects(m % col_chunk == 0, "col_chunk %d must divide block rows %d", col_chunk, m)

    prec = dict(
        highest=lax.Precision.HIGHEST,
        default=lax.Precision.DEFAULT,
    )[precision]
    kernel = _make_kernel(
        k=k, metric=metric, merge=merge, qt=qt, m=m, n_steps=n_steps, precision=prec,
        extract_every=extract_every, col_chunk=col_chunk,
    )
    scratch_shapes = [
        pltpu.VMEM((qt, k), jnp.float32),
        pltpu.VMEM((qt, k), jnp.int32),
    ]
    if merge.startswith("bank"):
        w = _eff_banks(merge, m, col_chunk) * 128
        scratch_shapes += [
            pltpu.VMEM((qt, w), jnp.float32),
            pltpu.VMEM((qt, w), jnp.int32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_qt, n_steps),
        in_specs=[
            pl.BlockSpec((qt, d), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((1, m, d), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
            pl.BlockSpec((1, 1, m), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
            pl.BlockSpec((1, 1, m), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((qt, k), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((qt, k), lambda i, j, pr, pv: (i, 0)),
        ],
        scratch_shapes=scratch_shapes,
    )
    # prepare the per-slot epilogue term the kernel folds into the matmul
    # output (one pass here instead of one per (tile, probe) step inside):
    # L2 -> norm with +inf on invalid slots; IP -> 0/+inf penalty;
    # cosine -> rsqrt norm scale (validity handled via list_indices inside)
    valid = list_indices >= 0
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        raw = list_norms if list_norms is not None else jnp.zeros((n_lists, m), jnp.float32)
        ln = jnp.where(valid, raw, jnp.inf)
    elif metric == DistanceType.InnerProduct:
        ln = jnp.where(valid, 0.0, jnp.inf).astype(jnp.float32)
    else:
        raw = list_norms if list_norms is not None else jnp.zeros((n_lists, m), jnp.float32)
        ln = lax.rsqrt(jnp.maximum(raw, 1e-24))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nq_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((nq_pad, k), jnp.int32),
        ],
        interpret=interpret,
    )(
        tile_probes,
        probe_valid,
        queries_sorted.astype(jnp.float32),
        list_data,
        ln[:, None, :],
        list_indices[:, None, :],
    )


# ---------------------------------------------------------------------------
# shared probe-table construction (used by the IVF-Flat and IVF-PQ wrappers)
# ---------------------------------------------------------------------------


def build_tile_probe_tables(
    coarse, probed, center_rank, *, nq: int, qt: int, n_lists: int,
    group: int, n_probes: int, probe_factor: int
):
    """Tile-coherent query ordering + per-tile union probe tables.

    ``coarse [nq, n_lists]`` coarse scores (smaller = closer),
    ``probed [nq, n_lists]`` bool. Returns ``(order_pad [nq_pad],
    tile_probes [n_qt, P], probe_valid [n_qt, P])`` where probe units are
    ``group`` adjacent lists and invalid slots re-address the row's last
    valid unit (DMA-friendly ascending order)."""
    top1 = jnp.argmin(coarse, axis=1)
    order = jnp.argsort(center_rank[top1], stable=True).astype(jnp.int32)

    n_qt = cdiv(nq, qt)
    nq_pad = n_qt * qt
    order_pad = jnp.concatenate(
        [order, jnp.broadcast_to(order[:1], (nq_pad - nq,))]
    ) if nq_pad != nq else order
    row_real = (jnp.arange(nq_pad) < nq)[:, None]
    probed_sorted = probed[order_pad] & row_real

    expects(n_lists % group == 0, "n_lists %d not divisible by group %d", n_lists, group)
    n_units = n_lists // group
    probed_u = probed_sorted.reshape(nq_pad, n_units, group).any(axis=2)
    p = min(n_units, max(cdiv(probe_factor * n_probes, group), cdiv(n_probes, group)))
    counts = jnp.sum(probed_u.reshape(n_qt, qt, n_units).astype(jnp.int32), axis=1)
    cvals, tile_probes = lax.top_k(counts, p)
    probe_valid = (cvals > 0).astype(jnp.int32)
    # Ascending probe order per tile: the DMA engine pipelines far better
    # over monotonically increasing block indices (measured ~30% on v5e).
    # Invalid slots get the row's last valid id so their (skipped) steps
    # re-address an already-resident block instead of fetching a new one.
    sort_key = jnp.where(probe_valid > 0, tile_probes, n_units)
    probe_order = jnp.argsort(sort_key, axis=1)
    tile_probes = jnp.take_along_axis(tile_probes, probe_order, axis=1)
    probe_valid = jnp.take_along_axis(probe_valid, probe_order, axis=1)
    last_valid = jnp.max(jnp.where(probe_valid > 0, tile_probes, 0), axis=1, keepdims=True)
    tile_probes = jnp.where(probe_valid > 0, tile_probes, last_valid).astype(jnp.int32)
    return order_pad, tile_probes, probe_valid


# ---------------------------------------------------------------------------
# full search wrapper
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "k",
        "n_probes",
        "metric",
        "qt",
        "probe_factor",
        "group",
        "has_filter",
        "merge",
        "precision",
        "extract_every",
        "col_chunk",
        "interpret",
    ),
)
def ivf_flat_fused_search(
    centers,
    center_rank,
    list_data,
    list_indices,
    list_norms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    qt: int = 64,
    probe_factor: int = 4,
    group: int = 1,
    has_filter: bool = False,
    merge: str = "seg",
    precision: str = "highest",
    extract_every: int = 0,
    col_chunk: int = 0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-Flat search through the Pallas fused scan. Same candidate-set
    semantics as the probe path whenever each tile's probe union fits the
    ``probe_factor * n_probes`` table (extra tile-mates only add exactly
    scored candidates); distances/post-processing match
    :func:`raft_tpu.neighbors.ivf_flat.flat_scan_core`.

    ``group``: DMA unit in lists. Lists are stored in spatial order (build
    reorders them by PCA-bisection rank), so ``group`` adjacent lists form
    one probe-table entry and one ``[group * max_list, d]`` DMA block —
    bigger streams for the DMA engine and ``group``x the list coverage per
    table slot, at the cost of scoring a probed group's spatial neighbors
    too (usually probed anyway). Requires ``n_lists % group == 0``."""
    nq, d = queries.shape
    n_lists, m, _ = list_data.shape
    qf = queries.astype(jnp.float32)
    if metric == DistanceType.CosineExpanded:
        qf = qf / jnp.maximum(jnp.linalg.norm(qf, axis=1, keepdims=True), 1e-12)

    # ---- coarse scores, per-query probes, tile-coherent ordering ---------
    from raft_tpu.neighbors.ivf_common import probe_selection

    coarse, probed = probe_selection(centers, qf, n_probes, metric)
    order_pad, tile_probes, probe_valid = build_tile_probe_tables(
        coarse, probed, center_rank, nq=nq, qt=qt, n_lists=n_lists,
        group=group, n_probes=n_probes, probe_factor=probe_factor,
    )
    nq_pad = order_pad.shape[0]
    qs = qf[order_pad]

    # ---- prefilter folds into the per-slot validity ----------------------
    li_eff = list_indices
    if has_filter:
        ids = jnp.clip(list_indices, 0, None)
        word = filter_bits[ids // 32]
        bit = (word >> (ids % 32).astype(jnp.uint32)) & 1
        li_eff = jnp.where((bit == 1) & (list_indices >= 0), list_indices, -1)

    # The DMA/scoring unit is `group` adjacent lists: reshaping keeps the
    # flat slot order, so slots map straight back to list_indices.
    n_units = n_lists // group
    gm = group * m
    if col_chunk and not merge.startswith("bank"):
        col_chunk = 0  # chunked scoring only exists for the bank merge
    if col_chunk:
        # round down to a divisor of the block rows (0 disables chunking)
        cc = min(col_chunk, gm)
        while gm % cc:
            cc -= 1
        col_chunk = 0 if cc >= gm else cc
    vals, slots = fused_list_topk(
        list_data.reshape(n_units, gm, d),
        list_norms.reshape(n_units, gm) if list_norms is not None else None,
        li_eff.reshape(n_units, gm),
        qs,
        tile_probes,
        probe_valid,
        k=k,
        metric=metric,
        qt=qt,
        merge=merge,
        precision=precision,
        extract_every=extract_every,
        col_chunk=col_chunk,
        interpret=interpret,
    )

    # ---- postprocess (mirrors flat_scan_core's tail) ---------------------
    flat_ids = list_indices.reshape(-1)
    idx = jnp.where(slots >= 0, flat_ids[jnp.clip(slots, 0, None)], -1)
    if metric == DistanceType.InnerProduct:
        out = -vals
    elif metric == DistanceType.CosineExpanded:
        out = 1.0 + vals
        out = jnp.where(idx >= 0, out, jnp.inf)
    else:
        qn = jnp.sum(qs * qs, axis=1)
        out = jnp.maximum(qn[:, None] + vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)

    # ---- unsort ----------------------------------------------------------
    order = order_pad[:nq]
    dist = jnp.zeros((nq, k), jnp.float32).at[order].set(out[:nq])
    ind = jnp.full((nq, k), -1, jnp.int32).at[order].set(idx[:nq])
    return dist, ind
