"""HBM residency model and device/host placement planner.

Sibling of :mod:`raft_tpu.ops.pallas.vmem_model`, one level up the memory
hierarchy: where the VMEM model accounts for what one *grid step* keeps
live on-core, this module accounts for what a whole *index* keeps live in
device HBM — codes, coarse centroids, id maps, mutable delta banks, and
(optionally) the raw f32 vectors the refine re-rank reads.

The accounting drives :func:`plan_placement`: given every registered
index and an HBM budget, decide per component whether it lives on the
device or in host RAM. The rule mirrors the FusionANNS split (ROADMAP
item 2): components the *scan* touches every query (``required=True`` —
codes, centroids, ids, norms, graph) must be device-resident or the
registration is infeasible; the raw-vector slab the *refine* touches
only for ``k * refine_ratio`` winners per query (``required=False``) is
device-resident while budget remains and spills to the host tier
otherwise, where :mod:`raft_tpu.tiered` serves it via an overlapped
per-batch gather.

Estimates are exact for the dominant buffers (they are computed from the
same ``shape x itemsize`` arithmetic that allocates them — tests assert
model == ``arr.nbytes`` on built indexes) and deliberately omit
transient compile/workspace allocations, which the headroom fraction
absorbs.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterable, Optional, Sequence, Tuple

#: Per-device HBM on current TPU generations (v4: 32 GiB, v5e: 16 GiB).
#: A *budget*, not a limit — callers pass the slice of HBM the index
#: tier may plan for; the remainder belongs to XLA workspaces and the
#: serving engine's program cache.
HBM_DEFAULT_BUDGET_BYTES = 16 * 1024 * 1024 * 1024

#: Fraction of the stated budget the planner fills. The rest absorbs
#: what the model cannot see: fragmentation, donation copies, and the
#: compiler's scratch HBM.
HBM_HEADROOM = 0.9

#: Staging-slab model defaults. A spilled index gathers its refine rows
#: through the host tier's double-buffered staging
#: (``HostVectorStore._staging``): two host buffers of
#: ``[micro_batch, n_cand, dim]`` plus the one in-flight transfer slab
#: in device HBM. ``k * refine_ratio`` is not known at planning time, so
#: the planner charges this nominal candidate width (the serving
#: defaults: micro_batch 256, k 10 x refine_ratio ~6 rounded up).
STAGING_MICRO_BATCH = 256
STAGING_N_CAND = 64


@dataclasses.dataclass(frozen=True)
class HbmComponent:
    """One HBM-resident buffer of an index.

    ``required=True`` marks buffers the per-query *scan* reads (codes,
    centroids, ids): these cannot leave the device without losing the
    fused kernels. ``required=False`` marks the refine raw-vector slab,
    which :func:`plan_placement` may move to the host tier.

    ``replicated=True`` marks buffers every shard of a lists-sharded
    search keeps whole (coarse centroids, rotation, PQ codebook —
    everything ``sharded_ann`` device_puts with a replicated spec);
    :func:`plan_placement_sharded` charges them at full size per shard
    instead of ``1/n_shards``."""

    name: str
    shape: Tuple[int, ...]
    itemsize: int
    required: bool = True
    replicated: bool = False

    @property
    def nbytes(self) -> int:
        return int(math.prod(self.shape)) * self.itemsize

    def per_shard_bytes(self, n_shards: int) -> int:
        """Bytes this component costs on EACH shard of an
        ``n_shards``-way lists-sharded placement."""
        if self.replicated or n_shards <= 1:
            return self.nbytes
        return -(-self.nbytes // n_shards)  # ceil


def staging_footprint(
    dim: int,
    itemsize: int = 4,
    *,
    micro_batch: int = STAGING_MICRO_BATCH,
    n_cand: int = STAGING_N_CAND,
) -> Tuple[int, int]:
    """``(host_bytes, device_bytes)`` staging cost of ONE index whose
    raw slab lives on the host tier: two host buffers (double buffering
    — slab *i* stays valid for the in-flight refine while *i+1* fills)
    plus the one in-flight ``[micro_batch, n_cand, dim]`` transfer slab
    the refine jit holds in device HBM."""
    slab = int(micro_batch) * int(n_cand) * int(dim) * int(itemsize)
    return 2 * slab, slab


@dataclasses.dataclass(frozen=True)
class IndexResidency:
    """The model's full HBM accounting for one registered index."""

    index_id: str
    algo: str
    components: Tuple[HbmComponent, ...]

    @property
    def total_bytes(self) -> int:
        return sum(c.nbytes for c in self.components)

    @property
    def required_bytes(self) -> int:
        """Bytes that must stay device-resident for the scan to run."""
        return sum(c.nbytes for c in self.components if c.required)

    @property
    def optional_bytes(self) -> int:
        """Bytes eligible for the host tier (refine raw vectors)."""
        return sum(c.nbytes for c in self.components if not c.required)

    def by_name(self, name: str) -> HbmComponent:
        for c in self.components:
            if c.name == name:
                return c
        raise KeyError(name)

    def table(self) -> str:
        rows = [
            "%-14s %-18s %12d B  [%s]"
            % (c.name, "x".join(map(str, c.shape)), c.nbytes,
               "scan" if c.required else "refine")
            for c in self.components
        ]
        rows.append("total: %d B (%.2f GiB)" % (self.total_bytes, self.total_bytes / 2**30))
        return "\n".join(rows)


def _dataset_component(n_rows: int, dim: int, itemsize: int = 4) -> HbmComponent:
    return HbmComponent("raw_vectors", (n_rows, dim), itemsize, required=False)


def ivf_pq_residency(
    index_id: str,
    *,
    n_rows: int,
    dim: int,
    n_lists: int,
    pq_dim: int,
    pq_bits: int,
    ksub: int = 256,
    rot_dim: Optional[int] = None,
    max_list: Optional[int] = None,
    rabitq: bool = False,
    refine_rows: int = 0,
    refine_itemsize: int = 4,
) -> IndexResidency:
    """HBM residency of an IVF-PQ (or IVF-RaBitQ) index.

    ``refine_rows > 0`` adds the optional raw-vector slab the integrated
    refine path gathers from (``refine_rows`` is usually ``n_rows``)."""
    max_list = max_list or math.ceil(n_rows / max(n_lists, 1))
    rot = rot_dim or dim
    bpr = max(1, (pq_dim * pq_bits + 7) // 8)  # bytes per packed row
    comps = [
        HbmComponent("codes", (n_lists, max_list, bpr), 1),
        HbmComponent("centers", (n_lists, dim), 4, replicated=True),
        HbmComponent("ids", (n_lists, max_list), 4),
    ]
    if rabitq:
        # RaBitQ: 1 bit/dim codes already counted via bpr; per-row f32
        # correction factors replace the PQ codebook.
        comps.append(HbmComponent("corrections", (n_lists, max_list, 2), 4))
    else:
        comps.append(HbmComponent("codebook", (pq_dim, ksub, rot // max(pq_dim, 1)), 4,
                                  replicated=True))
        comps.append(HbmComponent("rotation", (rot, dim), 4, replicated=True))
    if refine_rows > 0:
        comps.append(_dataset_component(refine_rows, dim, refine_itemsize))
    return IndexResidency(index_id, "ivf_rabitq" if rabitq else "ivf_pq", tuple(comps))


def ivf_flat_residency(
    index_id: str,
    *,
    n_rows: int,
    dim: int,
    n_lists: int,
    itemsize: int = 4,
    max_list: Optional[int] = None,
    refine_rows: int = 0,
    refine_itemsize: int = 4,
) -> IndexResidency:
    """HBM residency of an IVF-Flat index (list-major padded storage)."""
    max_list = max_list or math.ceil(n_rows / max(n_lists, 1))
    comps = [
        HbmComponent("list_data", (n_lists, max_list, dim), itemsize),
        HbmComponent("centers", (n_lists, dim), 4, replicated=True),
        HbmComponent("ids", (n_lists, max_list), 4),
        HbmComponent("norms", (n_lists, max_list), 4),
    ]
    if refine_rows > 0:
        comps.append(_dataset_component(refine_rows, dim, refine_itemsize))
    return IndexResidency(index_id, "ivf_flat", tuple(comps))


def brute_force_residency(
    index_id: str,
    *,
    n_rows: int,
    dim: int,
    itemsize: int = 4,
    has_norms: bool = True,
    refine_rows: int = 0,
    refine_itemsize: int = 4,
) -> IndexResidency:
    """HBM residency of a brute-force index. With ``refine_rows`` the
    scan copy may be a narrow dtype (bf16) while the refine slab holds
    the f32 originals."""
    comps = [HbmComponent("dataset", (n_rows, dim), itemsize)]
    if has_norms:
        comps.append(HbmComponent("norms", (n_rows,), 4))
    if refine_rows > 0:
        comps.append(_dataset_component(refine_rows, dim, refine_itemsize))
    return IndexResidency(index_id, "brute_force", tuple(comps))


def cagra_residency(
    index_id: str,
    *,
    n_rows: int,
    dim: int,
    graph_degree: int,
    itemsize: int = 4,
) -> IndexResidency:
    """HBM residency of a CAGRA graph index (dataset + fixed-degree
    neighbor graph, both scanned every query)."""
    # sharded CAGRA shards queries, not the graph: both buffers are
    # replicated on every shard
    return IndexResidency(index_id, "cagra", (
        HbmComponent("dataset", (n_rows, dim), itemsize, replicated=True),
        HbmComponent("graph", (n_rows, graph_degree), 4, replicated=True),
    ))


def delta_bank_residency(
    index_id: str,
    *,
    cap: int,
    dim: int,
    bank_rows: int = 1024,
) -> IndexResidency:
    """HBM residency of a mutable index's delta segment: the po2-padded
    f32 brute-force rows plus per-bank norms (see
    :mod:`raft_tpu.mutable.segments` — past ``bank_rows`` the fused scan
    tiles the delta into ``ceil(cap / bank_rows)`` banks)."""
    banks = max(1, math.ceil(cap / bank_rows))
    return IndexResidency(index_id, "mutable_delta", (
        HbmComponent("delta_rows", (cap, dim), 4),
        HbmComponent("delta_norms", (cap,), 4),
        HbmComponent("delta_ids", (banks, min(cap, bank_rows)), 4),
    ))


def residency_for_index(index_id: str, algo: str, index, *,
                        refine_rows: int = 0) -> IndexResidency:
    """Model a *built* index object by reading its buffer shapes, so the
    estimate matches allocation exactly (tests assert component nbytes ==
    the live arrays' nbytes)."""
    if algo in ("ivf_pq", "ivf_rabitq"):
        # replicated flags follow the device_put specs of the lists-
        # sharded scan: centroids / rotation / codebook go up with P()
        # (every shard keeps them whole), codes / ids / norms with P(axis)
        comps = [
            HbmComponent("codes", tuple(index.codes.shape), index.codes.dtype.itemsize),
            HbmComponent("centers", tuple(index.centers.shape), index.centers.dtype.itemsize,
                         replicated=True),
            HbmComponent("centers_rot", tuple(index.centers_rot.shape),
                         index.centers_rot.dtype.itemsize, replicated=True),
            HbmComponent("rotation", tuple(index.rotation.shape), index.rotation.dtype.itemsize,
                         replicated=True),
            HbmComponent("codebook", tuple(index.pq_centers.shape),
                         index.pq_centers.dtype.itemsize, replicated=True),
            HbmComponent("ids", tuple(index.list_indices.shape), index.list_indices.dtype.itemsize),
            HbmComponent("sqnorms", tuple(index.rot_sqnorms.shape),
                         index.rot_sqnorms.dtype.itemsize),
        ]
        corr = getattr(index, "corrections", None)
        if corr is not None:
            comps.append(HbmComponent("corrections", tuple(corr.shape), corr.dtype.itemsize))
    elif algo == "ivf_flat":
        comps = [
            HbmComponent("list_data", tuple(index.list_data.shape), index.list_data.dtype.itemsize),
            HbmComponent("centers", tuple(index.centers.shape), index.centers.dtype.itemsize,
                         replicated=True),
            HbmComponent("ids", tuple(index.list_indices.shape), index.list_indices.dtype.itemsize),
            HbmComponent("norms", tuple(index.list_norms.shape), index.list_norms.dtype.itemsize),
        ]
    elif algo == "brute_force":
        comps = [HbmComponent("dataset", tuple(index.dataset.shape), index.dataset.dtype.itemsize)]
        if index.norms is not None:
            comps.append(HbmComponent("norms", tuple(index.norms.shape), index.norms.dtype.itemsize))
    elif algo == "cagra":
        comps = [
            HbmComponent("dataset", tuple(index.dataset.shape), index.dataset.dtype.itemsize),
            HbmComponent("graph", tuple(index.graph.shape), index.graph.dtype.itemsize),
        ]
    else:
        raise KeyError(f"no HBM residency model for algo {algo!r}")
    if refine_rows > 0:
        dim = comps[0].shape[-1] if algo in ("brute_force", "cagra") else (
            index.centers.shape[-1])
        comps.append(_dataset_component(refine_rows, dim))
    return IndexResidency(index_id, algo, tuple(comps))


@dataclasses.dataclass(frozen=True)
class Placement:
    """The planner's verdict for a set of indexes under one budget.

    ``tiers`` maps ``index_id -> {component_name -> "device" | "host"}``.
    ``feasible`` is False when even the required (scan) components
    overflow the budget — the caller must shard or shrink, there is no
    host tier for codes."""

    hbm_budget: int
    tiers: Dict[str, Dict[str, str]]
    device_bytes: int
    host_bytes: int
    feasible: bool
    #: double-buffered host staging slabs of spilled indexes (2x each)
    staging_host_bytes: int = 0
    #: in-flight gather transfer slabs of spilled indexes (1x each),
    #: included in ``device_bytes``
    staging_device_bytes: int = 0

    def tier(self, index_id: str, component: str) -> str:
        return self.tiers[index_id][component]

    def spilled(self, index_id: str) -> bool:
        """Does any component of ``index_id`` live off the device?"""
        return any(t != "device" for t in self.tiers[index_id].values())

    def table(self) -> str:
        rows = []
        for iid, comps in sorted(self.tiers.items()):
            for name, tier in comps.items():
                rows.append("%-20s %-14s -> %s" % (iid, name, tier))
        if self.staging_host_bytes or self.staging_device_bytes:
            rows.append(
                "staging: host %.2f MiB (2x double-buffer)  device %.2f MiB (transfer)"
                % (self.staging_host_bytes / 2**20, self.staging_device_bytes / 2**20)
            )
        rows.append(
            "device: %.2f GiB  host: %.2f GiB  budget: %.2f GiB%s"
            % (self.device_bytes / 2**30, self.host_bytes / 2**30,
               self.hbm_budget / 2**30, "" if self.feasible else "  INFEASIBLE")
        )
        return "\n".join(rows)


def plan_placement(
    indexes: Sequence[IndexResidency] | Iterable[IndexResidency],
    hbm_budget: int = HBM_DEFAULT_BUDGET_BYTES,
    *,
    headroom: float = HBM_HEADROOM,
) -> Placement:
    """Decide device- vs host-tier per component.

    Required components always plan to the device (the scan cannot run
    otherwise); if their sum exceeds ``hbm_budget * headroom`` the plan
    is marked infeasible. Optional components (refine raw vectors) are
    then admitted largest-first into the remaining budget — spilling the
    *biggest* slab first buys the most headroom per spilled index, so a
    mixed fleet keeps its small indexes fully resident.

    Every spilled index additionally charges its staging footprint
    (:func:`staging_footprint`): 2x host buffers into
    ``staging_host_bytes`` and the in-flight transfer slab into
    ``device_bytes`` / ``staging_device_bytes``. Admission is
    smallest-first, so spills form a suffix of the admission order and
    staging charges (which accrue only on spill) never retroactively
    evict an already-admitted slab; ``feasible`` stays a required-bytes
    criterion — staging is accounting the operator reads, not a reason
    to refuse a scan that fits.
    """
    indexes = list(indexes)
    cap = int(hbm_budget * headroom)
    tiers: Dict[str, Dict[str, str]] = {}
    device = 0
    for res in indexes:
        tiers[res.index_id] = {c.name: "device" for c in res.components if c.required}
        device += res.required_bytes
    feasible = device <= cap

    optional = sorted(
        ((c, res) for res in indexes for c in res.components if not c.required),
        key=lambda pair: pair[0].nbytes,
    )
    host = 0
    stage_host = stage_dev = 0
    staged = set()
    # smallest-first admission == largest-first spill
    for comp, res in optional:
        if feasible and device + comp.nbytes <= cap:
            tiers[res.index_id][comp.name] = "device"
            device += comp.nbytes
        else:
            tiers[res.index_id][comp.name] = "host"
            host += comp.nbytes
            if res.index_id not in staged:
                staged.add(res.index_id)
                sh, sd = staging_footprint(int(comp.shape[-1]), comp.itemsize)
                stage_host += sh
                stage_dev += sd
    return Placement(
        hbm_budget=int(hbm_budget), tiers=tiers,
        device_bytes=device + stage_dev, host_bytes=host, feasible=feasible,
        staging_host_bytes=stage_host, staging_device_bytes=stage_dev,
    )


@dataclasses.dataclass(frozen=True)
class ShardedPlacement:
    """Per-shard verdict of :func:`plan_placement_sharded`.

    All byte totals are PER SHARD. ``tiers`` maps ``index_id ->
    {component_name -> "device" | "host" | "disk"}``: device HBM, the
    shard host's RAM (an in-memory :class:`~raft_tpu.tiered.store.
    HostVectorStore`), or the shard host's disk (the mmap/SSD-backed
    store variant — read-ahead hints + the fetch-depth budget keep its
    p99 bounded on cold pages)."""

    n_shards: int
    hbm_budget_per_shard: int
    host_budget_per_shard: Optional[int]
    tiers: Dict[str, Dict[str, str]]
    device_bytes_per_shard: int
    host_bytes_per_shard: int
    disk_bytes_per_shard: int
    feasible: bool
    #: double-buffered host staging slabs of spilled indexes (2x each),
    #: charged against the host budget alongside RAM-tier slabs
    staging_host_bytes: int = 0
    #: in-flight gather transfer slabs (1x each), included in
    #: ``device_bytes_per_shard``
    staging_device_bytes: int = 0

    def tier(self, index_id: str, component: str) -> str:
        return self.tiers[index_id][component]

    def spilled(self, index_id: str) -> bool:
        """Does any component of ``index_id`` live off the device?"""
        return any(t != "device" for t in self.tiers[index_id].values())

    def table(self) -> str:
        rows = ["per-shard placement over %d shards:" % self.n_shards]
        for iid, comps in sorted(self.tiers.items()):
            for name, tier in comps.items():
                rows.append("%-20s %-14s -> %s" % (iid, name, tier))
        if self.staging_host_bytes or self.staging_device_bytes:
            rows.append(
                "staging/shard: host %.2f MiB (2x double-buffer)  device %.2f MiB (transfer)"
                % (self.staging_host_bytes / 2**20, self.staging_device_bytes / 2**20)
            )
        rows.append(
            "per shard — device: %.2f GiB  host: %.2f GiB  disk: %.2f GiB  hbm budget: %.2f GiB%s"
            % (self.device_bytes_per_shard / 2**30, self.host_bytes_per_shard / 2**30,
               self.disk_bytes_per_shard / 2**30, self.hbm_budget_per_shard / 2**30,
               "" if self.feasible else "  INFEASIBLE")
        )
        return "\n".join(rows)


def plan_placement_sharded(
    indexes: Sequence[IndexResidency] | Iterable[IndexResidency],
    n_shards: int,
    hbm_budget_per_shard: int = HBM_DEFAULT_BUDGET_BYTES,
    *,
    host_budget_per_shard: Optional[int] = None,
    headroom: float = HBM_HEADROOM,
    staging_micro_batch: int = STAGING_MICRO_BATCH,
    staging_n_cand: int = STAGING_N_CAND,
) -> ShardedPlacement:
    """Per-shard placement over the three-level hierarchy the pod-scale
    tier composition serves from: device HBM, the shard host's RAM, and
    the shard host's disk.

    Replicated components (coarse centroids, rotation, PQ codebook —
    see :attr:`HbmComponent.replicated`) cost their FULL size on every
    shard; everything else costs ``ceil(nbytes / n_shards)``. Required
    components must fit the per-shard device cap or the plan is
    infeasible (codes cannot leave HBM). Optional slabs admit
    smallest-first to the device; a spilled slab lands in host RAM
    while the per-shard host budget — charged with the 2x
    double-buffered staging slabs the spill brings — still holds, and
    on disk past it (the mmap/SSD-backed store; same gather, the OS
    pages rows in under read-ahead hints). ``host_budget_per_shard=None``
    means unconstrained host RAM: nothing plans to disk.
    """
    indexes = list(indexes)
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    cap = int(hbm_budget_per_shard * headroom)
    tiers: Dict[str, Dict[str, str]] = {}
    device = 0
    for res in indexes:
        tiers[res.index_id] = {c.name: "device" for c in res.components if c.required}
        device += sum(
            c.per_shard_bytes(n_shards) for c in res.components if c.required
        )
    feasible = device <= cap

    optional = sorted(
        ((c, res) for res in indexes for c in res.components if not c.required),
        key=lambda pair: pair[0].per_shard_bytes(n_shards),
    )
    host = disk = stage_host = stage_dev = 0
    staged = set()
    for comp, res in optional:
        b = comp.per_shard_bytes(n_shards)
        if feasible and device + b <= cap:
            tiers[res.index_id][comp.name] = "device"
            device += b
            continue
        # spilling: the index starts staging through the host no matter
        # which off-device tier the slab itself lands in
        sh, sd = staging_footprint(
            int(comp.shape[-1]), comp.itemsize,
            micro_batch=staging_micro_batch, n_cand=staging_n_cand,
        )
        charge_h = sh if res.index_id not in staged else 0
        if host_budget_per_shard is None or (
            host + b + stage_host + charge_h <= int(host_budget_per_shard)
        ):
            tiers[res.index_id][comp.name] = "host"
            host += b
        else:
            tiers[res.index_id][comp.name] = "disk"
            disk += b
        if res.index_id not in staged:
            staged.add(res.index_id)
            stage_host += sh
            stage_dev += sd
    return ShardedPlacement(
        n_shards=int(n_shards),
        hbm_budget_per_shard=int(hbm_budget_per_shard),
        host_budget_per_shard=(
            None if host_budget_per_shard is None else int(host_budget_per_shard)
        ),
        tiers=tiers,
        device_bytes_per_shard=device + stage_dev,
        host_bytes_per_shard=host,
        disk_bytes_per_shard=disk,
        feasible=feasible,
        staging_host_bytes=stage_host,
        staging_device_bytes=stage_dev,
    )
