"""Fused Pallas beam search for CAGRA — the TPU analog of the
reference's single-CTA kernel
(``detail/cagra/search_single_cta_kernel-inl.cuh:467``).

The XLA search loop (:mod:`raft_tpu.neighbors.cagra`) round-trips HBM
every iteration: a ``dataset[...]`` gather materializes the candidate
vectors, an einsum scores them, and a full ``select_k`` re-sorts the
beam — three dispatches per hop with no control over data movement.
This kernel keeps the whole traversal on-chip:

* the **beam buffer** — ``itopk`` slots of (distance, packed id|visited
  flag) per query — lives in VMEM across all iterations (the output
  tiles double as the loop state), like the reference's
  shared-memory ``itopk`` list;
* each iteration DMAs the ``search_width`` parents' **packed neighbor
  rows** straight from HBM into a ``[qt, width]``-deep VMEM buffer with
  one async copy per (query, parent) — all copies are issued up front
  and waited per query, so the scoring of query ``q`` overlaps the
  in-flight fetches of queries ``q+1..`` (the deep buffer is the
  multi-buffered pipeline; there is no XLA gather round trip);
* candidates are scored on the VPU as ``sum((q - v)^2)`` — one fused
  subtract/multiply/reduce per parent block, no MXU batching hazards —
  and merged with a **rank-based stable re-sort**: pairwise-comparison
  ranks place every union element into its sorted slot via one-hot
  accumulation, reproducing the XLA path's stable value sort, so the
  ``dedup="post"`` adjacent-id kill applies verbatim (equal ids carry
  bit-identical in-kernel distances, and stable ties keep the
  buffered/visited copy first — the visited *hashmap* of the reference
  stays a visited *flag lane*, ``hashmap.hpp`` analog).

Graph traversal is data-dependent, so the adjacency fetch cannot be a
scalar-prefetch ``index_map`` (those are fixed before the kernel runs,
``ivf_scan.py`` style); instead parent ids are staged VMEM -> SMEM each
iteration and drive guarded ``pltpu.make_async_copy`` slices of the
HBM-resident table.

**Packed neighbor table** (:func:`build_neighbor_table`): per node,
``deg`` neighbor vectors plus 3 id rows — base-256 digits of
``neighbor_id + 1`` in lanes ``0..deg-1`` (0 decodes to the -1 pad) —
giving ``[n, deg + 3, d]``. One contiguous ~5 KB DMA per parent fetches
vectors *and* ids; digits <= 255 are exact in bf16, so ids up to
``2^24 - 2`` survive the narrow dtype. The table costs ``deg x`` the
dataset in HBM (bf16 halves it) — the classic bandwidth-for-latency
trade, bought back by never touching the ``[n, d]`` dataset during
the loop.

VMEM residency is modeled in
:func:`raft_tpu.ops.pallas.vmem_model.cagra_search_residency` and
checked by ``tools/graft_lint`` under the ``cagra_search`` bindings.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.errors import expects
from raft_tpu.utils.math import cdiv

#: id rows appended to each node's vector rows: base-256 digits of
#: ``id + 1`` (lane j of row ``deg + t`` holds digit t of neighbor j).
ID_ROWS = 3

#: Largest node count the packed id encoding supports: three 8-bit
#: digits of ``id + 1``.
MAX_TABLE_IDS = (1 << 24) - 2

#: Finite in-kernel "worst" distance. The rank-merge places elements
#: with masked one-hot sums, and ``inf * 0`` would poison them with
#: NaNs; a finite sentinel keeps every lane arithmetic-safe. Mapped
#: back to the XLA path's ``worst_value`` outside the kernel.
WORST = 3.0e38

#: Column chunk of the pairwise rank / one-hot placement passes — bounds
#: the [qt, m, chunk] body intermediates to ~1 MiB at the bench shape.
_RANK_CHUNK = 64


def build_neighbor_table(dataset, graph, *, dtype=jnp.bfloat16, row_chunk: int = 65536):
    """Pack ``[n, deg + ID_ROWS, d]`` neighbor rows: node ``v``'s rows are
    its ``deg`` neighbors' vectors followed by 3 id-digit rows (base-256
    of ``id + 1`` in lanes ``0..deg-1``; lane 0-fill decodes to -1)."""
    n, d = dataset.shape
    deg = graph.shape[1]
    expects(deg <= d, "packed id rows need graph_degree (%d) <= dim (%d)", deg, d)
    expects(n <= MAX_TABLE_IDS, "packed ids support <= %d rows, got %d", MAX_TABLE_IDS, n)
    parts = []
    for s in range(0, n, row_chunk):
        g = jnp.asarray(graph[s : s + row_chunk], jnp.int32)
        c = g.shape[0]
        vecs = jnp.asarray(dataset)[jnp.clip(g, 0, None)].astype(dtype)  # [c, deg, d]
        gp1 = g + 1  # -1 pad -> 0
        digits = jnp.stack([gp1 & 255, (gp1 >> 8) & 255, (gp1 >> 16) & 255], axis=1)
        id_rows = jnp.zeros((c, ID_ROWS, d), dtype).at[:, :, :deg].set(digits.astype(dtype))
        parts.append(jnp.concatenate([vecs, id_rows], axis=1))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def _pick_positions(vals, width: int):
    """``width`` rounds of min-extract over ``[qt, itopk]`` (the
    ``pickup_next_parents`` analog, shared logic with the XLA path)."""
    cols = lax.broadcasted_iota(jnp.int32, vals.shape, 1)
    big = jnp.int32(2**30)
    poss, valids = [], []
    for _ in range(width):
        mv = jnp.min(vals, axis=1, keepdims=True)
        sel = jnp.min(jnp.where(vals == mv, cols, big), axis=1, keepdims=True)
        poss.append(sel)
        valids.append(mv < WORST)
        vals = jnp.where(cols == sel, WORST, vals)
    return jnp.concatenate(poss, axis=1), jnp.concatenate(valids, axis=1)


def _rank_merge(uv, uidf, itopk: int):
    """Stable value-sorted top-``itopk`` of the union ``[qt, m]`` via
    pairwise ranks + one-hot placement. ``rank(i) = #{j : v_j < v_i or
    (v_j == v_i and j < i)}`` is a permutation of ``0..m-1``; keeping
    ranks ``< itopk`` reproduces the XLA path's stable ``select_k``
    (beam entries precede candidates, so the visited copy of a
    duplicate wins the tie)."""
    qt, m = uv.shape
    jj = lax.broadcasted_iota(jnp.int32, (1, m, 1), 1)
    parts = []
    for i0 in range(0, m, _RANK_CHUNK):
        i1 = min(i0 + _RANK_CHUNK, m)
        vi = uv[:, None, i0:i1]
        ii = lax.broadcasted_iota(jnp.int32, (1, 1, i1 - i0), 2) + i0
        less = (uv[:, :, None] < vi).astype(jnp.int32)
        tie = ((uv[:, :, None] == vi) & (jj < ii)).astype(jnp.int32)
        parts.append(jnp.sum(less + tie, axis=1))
    rank = jnp.concatenate(parts, axis=1)  # [qt, m]
    nv_parts, ni_parts = [], []
    for p0 in range(0, itopk, _RANK_CHUNK):
        p1 = min(p0 + _RANK_CHUNK, itopk)
        pidx = lax.broadcasted_iota(jnp.int32, (1, 1, p1 - p0), 2) + p0
        oh = rank[:, :, None] == pidx  # [qt, m, chunk]
        nv_parts.append(jnp.sum(jnp.where(oh, uv[:, :, None], 0.0), axis=1))
        ni_parts.append(jnp.sum(jnp.where(oh, uidf[:, :, None], 0), axis=1))
    nv = jnp.concatenate(nv_parts, axis=1)
    nidf = jnp.concatenate(ni_parts, axis=1)
    return nv, jnp.where(nv >= WORST, -1, nidf)


def _beam_kernel(
    q_ref, iv_ref, ii_ref, table_ref, ov_ref, oi_ref,
    nbr, pv, cv, ci, ps, semp, semn,
    *, itopk: int, width: int, deg: int, d: int, qt: int, iters: int, ip: bool,
):
    # beam state = the output tiles, VMEM-resident across all iterations
    ov_ref[...] = iv_ref[...]
    oi_ref[...] = ii_ref[...]
    cols = lax.broadcasted_iota(jnp.int32, (qt, itopk), 1)

    def step(_, carry):
        beam_v = ov_ref[...]
        beam_idf = oi_ref[...]
        # -- pick parents: best `width` unvisited, valid slots ------------
        masked = jnp.where(((beam_idf & 1) == 1) | (beam_idf < 0), WORST, beam_v)
        ppos, pvalid = _pick_positions(masked, width)  # [qt, width]
        oh = ppos[:, :, None] == cols[:, None, :]  # [qt, width, itopk]
        ohv = oh & pvalid[:, :, None]
        pidf = jnp.sum(jnp.where(ohv, beam_idf[:, None, :], 0), axis=2)
        pv[...] = jnp.where(pvalid, pidf >> 1, -1)  # parent ids, -1 invalid
        # mark the picked slots visited before the merge sees them
        oi_ref[...] = jnp.where(jnp.any(ohv, axis=1), beam_idf | 1, beam_idf)

        # -- stage parent ids to SMEM, then issue every DMA up front ------
        stage = pltpu.make_async_copy(pv, ps, semp)
        stage.start()
        stage.wait()

        rows = deg + ID_ROWS

        def issue(j, c):
            qq, ww = j // width, j % width
            pid = ps[qq, ww]

            @pl.when(pid >= 0)
            def _():
                pltpu.make_async_copy(
                    table_ref.at[pid], nbr.at[qq, pl.ds(ww * rows, rows)],
                    semn.at[qq, ww],
                ).start()

            return c

        lax.fori_loop(0, qt * width, issue, 0)

        # -- score query q while later queries' fetches are in flight -----
        def score_q(qq, c):
            def waitw(ww, c2):
                pid = ps[qq, ww]

                @pl.when(pid >= 0)
                def _():
                    pltpu.make_async_copy(
                        table_ref.at[pid], nbr.at[qq, pl.ds(ww * rows, rows)],
                        semn.at[qq, ww],
                    ).wait()

                return c2

            lax.fori_loop(0, width, waitw, 0)
            blk = nbr[qq]  # [width * rows, d]: per parent, deg vec + 3 id rows
            vecs = jnp.concatenate(
                [blk[w * rows : w * rows + deg] for w in range(width)]
            ).astype(jnp.float32)  # [width * deg, d]
            qv = q_ref[qq]  # [d]
            if ip:
                dist = -jnp.sum(vecs * qv[None, :], axis=1)
            else:
                diff = vecs - qv[None, :]
                dist = jnp.sum(diff * diff, axis=1)
            # decode ids: base-256 digit rows, exact in the table dtype
            digits = [
                jnp.concatenate(
                    [blk[w * rows + deg + t : w * rows + deg + t + 1, :deg]
                     for w in range(width)]
                ).astype(jnp.float32)  # [width, deg]
                for t in range(ID_ROWS)
            ]
            cid = (digits[0] + 256.0 * digits[1] + 65536.0 * digits[2]).astype(
                jnp.int32
            ) - 1
            # a skipped (invalid-parent) DMA leaves stale lanes: mask them
            pm = jnp.broadcast_to((pv[qq] >= 0)[:, None], (width, deg))
            cid = jnp.where(pm, cid, -1).reshape(width * deg)
            cv[qq, :] = jnp.where(cid >= 0, dist, WORST)
            ci[qq, :] = cid
            return c

        lax.fori_loop(0, qt, score_q, 0)

        # -- merge + post-sort adjacent dedup (body_packed semantics) -----
        beam_idf = oi_ref[...]
        uv = jnp.concatenate([ov_ref[...], cv[...]], axis=1)
        uidf = jnp.concatenate([beam_idf, ci[...] * 2], axis=1)
        nv, nidf = _rank_merge(uv, uidf, itopk)
        ids_new = nidf >> 1
        prev = jnp.concatenate(
            [jnp.full((qt, 1), -2, jnp.int32), ids_new[:, :-1]], axis=1
        )
        dup = (ids_new == prev) & (ids_new >= 0)
        ov_ref[...] = jnp.where(dup, WORST, nv)
        oi_ref[...] = jnp.where(dup, -1, nidf)
        return carry

    lax.fori_loop(0, iters, step, 0)


def kernel_scratch_shapes(qt: int, width: int, deg: int, d: int, table_dtype=jnp.bfloat16):
    """The kernel's VMEM scratch declarations, in order — exposed so
    ``vmem_model.cagra_search_residency`` can be asserted against the
    literal shapes (the SMEM staging buffer and DMA semaphores are not
    VMEM and are appended separately at the call site)."""
    return [
        pltpu.VMEM((qt, width * (deg + 3), d), table_dtype),  # nbr rows
        pltpu.VMEM((qt, width), jnp.int32),  # parent ids
        pltpu.VMEM((qt, width * deg), jnp.float32),  # candidate dists
        pltpu.VMEM((qt, width * deg), jnp.int32),  # candidate ids
    ]


@functools.partial(
    jax.jit,
    static_argnames=("itopk", "width", "iters", "qt", "ip", "interpret"),
)
def cagra_fused_search(
    table,
    queries,
    init_v,
    init_idf,
    *,
    itopk: int,
    width: int,
    iters: int,
    qt: int = 32,
    ip: bool = False,
    interpret: bool = False,
):
    """Run the fused beam loop. ``queries [nq, d]`` f32, ``init_v``/
    ``init_idf [nq, itopk]`` the seeded beam (min-ordered distances —
    negate for InnerProduct — with :data:`WORST` in empty slots; ids
    packed ``id * 2 + flag``, -1 invalid). Returns the final beam
    ``(values [nq, itopk] f32, packed idf [nq, itopk] i32)``; the caller
    unpacks, runs the final unique-merge and metric epilogue."""
    nq, d = queries.shape
    rows = table.shape[1]
    deg = rows - ID_ROWS
    nqp = cdiv(nq, qt) * qt
    if nqp != nq:
        pad = nqp - nq
        queries = jnp.pad(queries, ((0, pad), (0, 0)))
        init_v = jnp.pad(init_v, ((0, pad), (0, 0)), constant_values=WORST)
        init_idf = jnp.pad(init_idf, ((0, pad), (0, 0)), constant_values=-1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nqp // qt,),
        in_specs=[
            pl.BlockSpec((qt, d), lambda i: (i, 0)),
            pl.BlockSpec((qt, itopk), lambda i: (i, 0)),
            pl.BlockSpec((qt, itopk), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),  # table stays in HBM
        ],
        out_specs=[
            pl.BlockSpec((qt, itopk), lambda i: (i, 0)),
            pl.BlockSpec((qt, itopk), lambda i: (i, 0)),
        ],
        scratch_shapes=[
            *kernel_scratch_shapes(qt, width, deg, d, table.dtype),
            pltpu.SMEM((qt, width), jnp.int32),  # scalar parent ids
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((qt, width)),
        ],
    )
    kern = functools.partial(
        _beam_kernel,
        itopk=itopk, width=width, deg=deg, d=d, qt=qt, iters=iters, ip=ip,
    )
    from raft_tpu.ops.pallas._guard import kernel_guard

    with kernel_guard("cagra_fused_search"):
        out_v, out_idf = pl.pallas_call(
            kern,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((nqp, itopk), jnp.float32),
                jax.ShapeDtypeStruct((nqp, itopk), jnp.int32),
            ],
            interpret=interpret,
        )(queries, init_v, init_idf, table)
    return out_v[:nq], out_idf[:nq]
