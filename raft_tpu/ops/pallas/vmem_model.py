"""VMEM residency model for the fused Pallas scan kernels.

The reference ships correctness tooling alongside its kernels
(compile-time template checks, sanitizer CI); this module is the TPU
analog for the resource axis: a byte-accurate model of what one grid
step of a fused scan keeps live in VMEM, used three ways —

* :mod:`raft_tpu.ops.pallas.pq_scan` derives its decode-chunk budget
  from the model's fixed residents instead of a hand-calibrated
  constant, so scratch-shape drift moves the cap instead of silently
  reintroducing Mosaic compile failures;
* tests assert the model against the kernel's actual scratch/BlockSpec
  shapes and against the measured 17.19 MiB residency of the 1M-row
  bench shape (m=1152, ksub=256) that motivated the cap;
* ``tools/graft_lint`` cross-checks the shapes it parses out of the
  kernel source against the same accounting.

Accounting rules (see ``docs/static_analysis.md`` for the rationale):

* every in/out tile contributes ``block_bytes x buffers`` where
  ``buffers = 2`` when the tile's block index varies along the
  *innermost* grid axis (the DMA pipeline double-buffers it) and 1 when
  it only changes at outer-axis boundaries, where the pipeline drains
  anyway;
* scratch buffers contribute their full size once — they persist across
  the whole grid;
* kernel-body intermediates contribute their peak: for the PQ decode
  that is one column chunk of the multi-hot ``S`` plus its f32
  byte-spread temps (:func:`decode_cell_bytes`) and the ``[qt, m]``
  f32 dot accumulator.

Sub-(8, 128) tiles are modeled at logical size; Mosaic's lane/sublane
padding of the k-sized accumulators is a second-order effect (<2% at
every supported shape).
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Optional, Tuple

#: Per-core VMEM on current TPU generations (v4/v5): 16 MiB. Mosaic
#: rejects kernels whose scoped allocation exceeds it.
VMEM_LIMIT_BYTES = 16 * 1024 * 1024

#: Fraction of VMEM the model lets a kernel plan for. The remainder
#: absorbs what the model cannot see: Mosaic spill slots, semaphores,
#: and compiler-scheduled copies. 0.75 reproduces (within 2%) the 8 MB
#: decode budget that was hand-calibrated against the 1M-row bench
#: shape before this model existed.
VMEM_HEADROOM = 0.75


def code_groups(code_mode: str, ksub: int, bpr: int) -> Tuple[int, int]:
    """(n_groups, gw): the PQ multi-hot column space is ``n_groups``
    groups of ``gw`` columns — one group per stored byte for u8/nib8/p4,
    one per CODE for the spanning b3/b5/b6/b7 bit layouts."""
    if code_mode in ("b3", "b5", "b6", "b7"):
        b = int(code_mode[1:])
        return bpr * 8 // b, ksub
    return bpr, (ksub if code_mode == "u8" else 32)


def decode_cell_bytes(code_mode: str) -> int:
    """Peak live bytes per (row, column) of one PQ decode chunk. u8/
    nib8/p4 hold the f32 byte-spread + the bf16 multi-hot (~6 B); the
    spanning bit layouts keep TWO f32 byte-spreads (low/high byte) plus
    f32 peel temps live at once (~14 B)."""
    return 14 if code_mode.startswith("b") and code_mode[1:].isdigit() else 6


def merge_banks(merge: str, m: int) -> int:
    """Bank count of the running top-k scratch for a ``bank<N>`` merge
    mode, clamped to the lane-group count of one compress call (mirrors
    ``ivf_scan._eff_banks`` at col_chunk=0)."""
    g = re.search(r"(\d+)$", merge)
    n = int(g.group(1)) if g else 0
    if merge.startswith("bank"):
        n = n or 4
    elif merge.startswith("seg"):
        n = n or 2
    return max(1, min(n, math.ceil(m / 128)))


@dataclasses.dataclass(frozen=True)
class Resident:
    """One VMEM-resident buffer of a kernel grid step.

    ``kind`` is ``"tile"`` (BlockSpec in/out block), ``"scratch"``
    (``pltpu.VMEM`` scratch), ``"body"`` (peak kernel-body
    intermediate), or ``"chunk"`` (the sizeable *scalable* body
    intermediate the budget is solved for)."""

    name: str
    shape: Tuple[int, ...]
    itemsize: int
    buffers: int = 1
    kind: str = "tile"

    @property
    def nbytes(self) -> int:
        return int(math.prod(self.shape)) * self.itemsize * self.buffers


@dataclasses.dataclass(frozen=True)
class KernelResidency:
    """The model's full accounting for one kernel configuration."""

    kernel: str
    residents: Tuple[Resident, ...]

    @property
    def total_bytes(self) -> int:
        return sum(r.nbytes for r in self.residents)

    @property
    def fixed_bytes(self) -> int:
        """Bytes that do not scale with the decode chunk size."""
        return sum(r.nbytes for r in self.residents if r.kind != "chunk")

    def by_name(self, name: str) -> Resident:
        for r in self.residents:
            if r.name == name:
                return r
        raise KeyError(name)

    def table(self) -> str:
        rows = [
            "%-14s %-18s x%d %10d B  [%s]"
            % (r.name, "x".join(map(str, r.shape)), r.buffers, r.nbytes, r.kind)
            for r in self.residents
        ]
        rows.append("total: %d B (%.2f MiB)" % (self.total_bytes, self.total_bytes / 2**20))
        return "\n".join(rows)


def pq_scan_residency(
    *,
    m: int,
    code_mode: str,
    ksub: int,
    bpr: int,
    qt: int = 128,
    k: int = 128,
    g_lists: int = 8,
    rot_dim: int = 128,
    merge: str = "bank8",
    decode_cols: int = 0,
) -> KernelResidency:
    """Model ``pq_scan.fused_pq_topk``'s VMEM residency for one grid
    step. Mirrors the kernel's grid spec exactly — the shapes here are
    asserted against the literal BlockSpec/scratch declarations in
    tests (``test_pq_fused.py``), so the two cannot drift apart
    silently.

    ``decode_cols=0`` omits the decode chunk (useful for computing the
    fixed residents the chunk budget is solved against); defaults for
    ``qt``/``k``/``g_lists``/``merge`` match ``IvfPqSearchParams``
    (``k=128`` is a conservative stand-in when the caller's k is
    unknown — the k-sized residents are <3% of the stack)."""
    n_groups, gw = code_groups(code_mode, ksub, bpr)
    K = n_groups * gw
    gm = g_lists * m
    banks = merge_banks(merge, m)
    residents = [
        # in tiles, in fused_pq_topk's in_specs order. Index maps that
        # reference the inner grid axis j (the probe step) are
        # double-buffered by the DMA pipeline; w/q_rot/outs only move
        # with the query-tile axis i.
        Resident("w_tile", (qt, K), 2),                      # bf16 LUT rows
        Resident("q_rot", (qt, rot_dim), 4),
        Resident("centers_rot", (1, g_lists, rot_dim), 4, buffers=2),
        Resident("codes", (1, gm, bpr), 1, buffers=2),
        Resident("ln", (1, 1, gm), 4, buffers=2),
        Resident("out_vals", (qt, k), 4),
        Resident("out_idx", (qt, k), 4),
        # scratch_shapes, in declaration order
        Resident("acc_vals", (qt, k), 4, kind="scratch"),
        Resident("acc_idx", (qt, k), 4, kind="scratch"),
        Resident("bank_vals", (qt, banks * 128), 4, kind="scratch"),
        Resident("bank_idx", (qt, banks * 128), 4, kind="scratch"),
        # peak kernel-body intermediates
        Resident("dot_acc", (qt, m), 4, kind="body"),
    ]
    if decode_cols:
        residents.append(
            Resident(
                "decode_chunk", (m, decode_cols), decode_cell_bytes(code_mode),
                kind="chunk",
            )
        )
    return KernelResidency("pq_scan.fused_pq_topk", tuple(residents))


def pq_decode_chunk_budget(
    *,
    m: int,
    code_mode: str,
    ksub: int,
    bpr: int,
    qt: int = 128,
    k: int = 128,
    g_lists: int = 8,
    rot_dim: int = 128,
    merge: str = "bank8",
    limit: int = VMEM_LIMIT_BYTES,
    headroom: float = VMEM_HEADROOM,
) -> int:
    """Bytes one PQ decode chunk may occupy: ``headroom x limit`` minus
    the kernel's fixed residents at this shape. Replaces the historical
    hand-calibrated 8 MB ``_DECODE_CHUNK_BUDGET`` — at the calibration
    shape (m=1152, ksub=256, k<=128) this derives ~7.85 MB, and unlike
    the constant it shrinks for longer lists / wider code rows whose
    fixed residents (dot accumulator, code DMA buffers) grow. May be
    <= 0: no chunk fits, the shape is fused-infeasible."""
    fixed = pq_scan_residency(
        m=m, code_mode=code_mode, ksub=ksub, bpr=bpr, qt=qt, k=k,
        g_lists=g_lists, rot_dim=rot_dim, merge=merge, decode_cols=0,
    ).fixed_bytes
    return int(limit * headroom) - fixed


#: Peak live bytes per (row, rot_dim-column) cell of one RaBitQ decode
#: chunk: the f32 byte-spread lanes, the f32 shift temp, and the f32
#: sign-bit plane live at once (3 x 4 B).
RABITQ_DECODE_CELL_BYTES = 12


def rabitq_scan_residency(
    *,
    m: int,
    bpr: int,
    qt: int = 128,
    k: int = 128,
    g_lists: int = 8,
    rot_dim: int = 128,
    merge: str = "bank8",
    decode_rows: int = 0,
) -> KernelResidency:
    """Model ``rabitq_scan.fused_rabitq_topk``'s VMEM residency for one
    grid step. Same accounting discipline as :func:`pq_scan_residency`
    (tests assert these shapes against the kernel's literal BlockSpec /
    scratch declarations); the LUT tile is replaced by the per-slot
    correction channel, and the scalable body intermediate is a ROW
    chunk of unpacked sign bits (``[rows, rot_dim]`` f32 planes,
    :data:`RABITQ_DECODE_CELL_BYTES`/cell) — the bit-dot accumulates
    into the same full ``[qt, m]`` body buffer pq_scan keeps.

    ``decode_rows=0`` omits the chunk (for computing the fixed
    residents the row budget is solved against)."""
    gm = g_lists * m
    banks = merge_banks(merge, m)
    residents = [
        # in tiles, in fused_rabitq_topk's in_specs order
        Resident("q_rot", (qt, rot_dim), 4),
        Resident("centers_rot", (1, g_lists, rot_dim), 4, buffers=2),
        Resident("codes", (1, gm, bpr), 1, buffers=2),
        Resident("ln", (1, 1, gm), 4, buffers=2),
        Resident("corr", (1, 1, gm), 4, buffers=2),
        Resident("out_vals", (qt, k), 4),
        Resident("out_idx", (qt, k), 4),
        # scratch_shapes, in declaration order
        Resident("acc_vals", (qt, k), 4, kind="scratch"),
        Resident("acc_idx", (qt, k), 4, kind="scratch"),
        Resident("bank_vals", (qt, banks * 128), 4, kind="scratch"),
        Resident("bank_idx", (qt, banks * 128), 4, kind="scratch"),
        # peak non-chunk body intermediates: the bit-dot accumulator, the
        # per-step coarse q.c tile, and the [bpr, rot_dim] byte-spread
        Resident("dot_acc", (qt, m), 4, kind="body"),
        Resident("qdc", (qt, g_lists), 4, kind="body"),
        Resident("spread", (bpr, rot_dim), 4, kind="body"),
    ]
    if decode_rows:
        residents.append(
            Resident(
                "decode_chunk", (decode_rows, rot_dim), RABITQ_DECODE_CELL_BYTES,
                kind="chunk",
            )
        )
    return KernelResidency("rabitq_scan.fused_rabitq_topk", tuple(residents))


def rabitq_decode_rows_budget(
    *,
    m: int,
    bpr: int,
    qt: int = 128,
    k: int = 128,
    g_lists: int = 8,
    rot_dim: int = 128,
    merge: str = "bank8",
    limit: int = VMEM_LIMIT_BYTES,
    headroom: float = VMEM_HEADROOM,
) -> int:
    """Bytes one RaBitQ decode row-chunk may occupy: ``headroom x
    limit`` minus the kernel's fixed residents at this shape. Per row
    the chunk costs ``RABITQ_DECODE_CELL_BYTES * rot_dim`` bytes of
    sign-bit planes; may be <= 0 when the shape is fused-infeasible."""
    fixed = rabitq_scan_residency(
        m=m, bpr=bpr, qt=qt, k=k, g_lists=g_lists, rot_dim=rot_dim,
        merge=merge, decode_rows=0,
    ).fixed_bytes
    return int(limit * headroom) - fixed


def cagra_search_residency(
    *,
    itopk: int = 160,
    width: int = 8,
    deg: int = 16,
    d: int = 128,
    qt: int = 32,
    table_itemsize: int = 2,
) -> KernelResidency:
    """Model ``cagra_search._beam_kernel``'s residency for one grid
    step (one ``qt``-query tile; the grid is 1-D over query tiles, so
    every tile moves with the innermost axis and double-buffers).
    Defaults are the 1M-row bench shape (itopk<=160, width 8, the
    bf16 packed table). The SMEM parent-id staging buffer
    (``[qt, width]`` i32) is not VMEM and is excluded.

    ``table_itemsize`` follows ``CagraSearchParams.fused_table_dtype``
    (2 = bf16 default, 4 = the float32 parity table)."""
    m = itopk + width * deg
    residents = [
        # in tiles (queries, init beam) + out tiles (final beam); the
        # out tiles double as the across-iteration beam state
        Resident("q_tile", (qt, d), 4, buffers=2),
        Resident("init_v", (qt, itopk), 4, buffers=2),
        Resident("init_idf", (qt, itopk), 4, buffers=2),
        Resident("out_v", (qt, itopk), 4, buffers=2),
        Resident("out_idf", (qt, itopk), 4, buffers=2),
        # scratch_shapes, in declaration order (table stays in HBM/ANY
        # and is streamed by explicit per-parent DMAs into nbr)
        Resident("nbr", (qt, width * (deg + 3), d), table_itemsize, kind="scratch"),
        Resident("parents", (qt, width), 4, kind="scratch"),
        Resident("cand_v", (qt, width * deg), 4, kind="scratch"),
        Resident("cand_id", (qt, width * deg), 4, kind="scratch"),
        # peak kernel-body intermediates: one pairwise rank/placement
        # column chunk (two i32 [qt, m, chunk] temps live at the peak)
        # and one parent block's f32 score diff
        Resident("rank_chunk", (qt, m, 64), 4, buffers=2, kind="body"),
        Resident("score_blk", (width * deg, d), 4, kind="body"),
    ]
    return KernelResidency("cagra_search._beam_kernel", tuple(residents))


def ring_topk_residency(
    *,
    n: int,
    B: int,
    w: int,
    fold_rows: int = 32,
    rank_chunk: int = 64,
) -> KernelResidency:
    """Model ``ring_topk._ring_kernel``'s residency. The kernel has no
    grid — the whole prepped candidate set ([n*B, w] per lane) sits in
    VMEM — so every in/out ref is single-buffered; the ring state
    ([n, B, w] per lane) plus the double-buffered send/recv DMA slots
    ([2, B, w] per lane) are scratch, asserted against
    ``ring_topk.kernel_scratch_shapes`` (DMA semaphores are not VMEM and
    are excluded); the body peak is one (key, pos) pairwise-rank chunk
    of the fold (two i32 ``[fold_rows, 2w, rank_chunk]`` temps live).

    ``n`` = ring size (devices), ``B`` = query-block rows per hop,
    ``w`` = merge width (k). At the serving shape (n=8, B=128, w=128)
    the total is ~5.6 MiB — comfortably inside the 0.75 x 16 MiB plan."""
    residents = [
        # in refs (prepped key/pos/val/id lanes), then out refs
        Resident("in_key", (n * B, w), 4),
        Resident("in_pos", (n * B, w), 4),
        Resident("in_val", (n * B, w), 4),
        Resident("in_id", (n * B, w), 4),
        Resident("out_v", (n * B, w), 4),
        Resident("out_i", (n * B, w), 4),
        # scratch_shapes, in declaration order (= kernel_scratch_shapes)
        Resident("state_key", (n, B, w), 4, kind="scratch"),
        Resident("state_pos", (n, B, w), 4, kind="scratch"),
        Resident("state_val", (n, B, w), 4, kind="scratch"),
        Resident("state_id", (n, B, w), 4, kind="scratch"),
        Resident("send_key", (2, B, w), 4, kind="scratch"),
        Resident("send_pos", (2, B, w), 4, kind="scratch"),
        Resident("send_val", (2, B, w), 4, kind="scratch"),
        Resident("send_id", (2, B, w), 4, kind="scratch"),
        Resident("recv_key", (2, B, w), 4, kind="scratch"),
        Resident("recv_pos", (2, B, w), 4, kind="scratch"),
        Resident("recv_val", (2, B, w), 4, kind="scratch"),
        Resident("recv_id", (2, B, w), 4, kind="scratch"),
        # peak body intermediate: less + tie of one rank chunk
        Resident("rank_chunk", (fold_rows, 2 * w, rank_chunk), 4, buffers=2,
                 kind="body"),
    ]
    return KernelResidency("ring_topk._ring_kernel", tuple(residents))


def scan_ring_topk_residency(
    *,
    n: int,
    B: int,
    w: int,
    kc: int,
    fold_rows: int = 32,
    rank_chunk: int = 64,
) -> KernelResidency:
    """Model ``ring_topk._scan_ring_kernel``'s residency — the
    scan-fused ring. Relative to :func:`ring_topk_residency` only the
    four INPUT refs widen to the scan's ``kc``-column candidate tile
    (``kc`` a multiple of ``w``, e.g. ``k·refine_ratio``); the staging
    fold writes straight into the same ring state, so scratch is
    byte-identical (asserted against
    ``ring_topk.scan_kernel_scratch_shapes``) and the body peak is the
    same pairwise-rank chunk — the staging fold and the per-hop fold
    share ``_rank_merge_pos`` at the same ``(fold_rows, 2w)`` union
    shape. At kc = 2k the lint binding (n=8, B=128, w=128, kc=256)
    totals exactly the 12 MiB (75% x 16 MiB) plan; kc = 4k (512, 16
    MiB) breaches it — wider scans must pre-fold toward 2k upstream or
    shrink the query block."""
    residents = [
        # in refs: the full scan candidate tile, kc wide
        Resident("in_key", (n * B, kc), 4),
        Resident("in_pos", (n * B, kc), 4),
        Resident("in_val", (n * B, kc), 4),
        Resident("in_id", (n * B, kc), 4),
        Resident("out_v", (n * B, w), 4),
        Resident("out_i", (n * B, w), 4),
        # scratch_shapes, in declaration order (= scan_kernel_scratch_shapes)
        Resident("state_key", (n, B, w), 4, kind="scratch"),
        Resident("state_pos", (n, B, w), 4, kind="scratch"),
        Resident("state_val", (n, B, w), 4, kind="scratch"),
        Resident("state_id", (n, B, w), 4, kind="scratch"),
        Resident("send_key", (2, B, w), 4, kind="scratch"),
        Resident("send_pos", (2, B, w), 4, kind="scratch"),
        Resident("send_val", (2, B, w), 4, kind="scratch"),
        Resident("send_id", (2, B, w), 4, kind="scratch"),
        Resident("recv_key", (2, B, w), 4, kind="scratch"),
        Resident("recv_pos", (2, B, w), 4, kind="scratch"),
        Resident("recv_val", (2, B, w), 4, kind="scratch"),
        Resident("recv_id", (2, B, w), 4, kind="scratch"),
        # peak body intermediate: less + tie of one rank chunk (shared
        # by the staging fold and the per-hop fold)
        Resident("rank_chunk", (fold_rows, 2 * w, rank_chunk), 4, buffers=2,
                 kind="body"),
    ]
    return KernelResidency("ring_topk._scan_ring_kernel", tuple(residents))


def ivf_scan_residency(
    *,
    m: int,
    d: int,
    qt: int = 128,
    k: int = 128,
    merge: str = "bank8",
    itemsize: int = 4,
) -> KernelResidency:
    """Model ``ivf_scan.fused_list_topk``'s residency (col_chunk=0):
    one query tile, one double-buffered list block + prepared epilogue
    and id rows, the top-k accumulator and bank scratch, and the
    ``[qt, m]`` f32 score block."""
    banks = merge_banks(merge, m)
    residents = [
        Resident("q_tile", (qt, d), 4),
        Resident("list_data", (1, m, d), itemsize, buffers=2),
        Resident("ln", (1, 1, m), 4, buffers=2),
        Resident("list_idx", (1, 1, m), 4, buffers=2),
        Resident("out_vals", (qt, k), 4),
        Resident("out_idx", (qt, k), 4),
        Resident("acc_vals", (qt, k), 4, kind="scratch"),
        Resident("acc_idx", (qt, k), 4, kind="scratch"),
        Resident("bank_vals", (qt, banks * 128), 4, kind="scratch"),
        Resident("bank_idx", (qt, banks * 128), 4, kind="scratch"),
        Resident("score", (qt, m), 4, kind="body"),
    ]
    return KernelResidency("ivf_scan.fused_list_topk", tuple(residents))
