"""Hand-written Pallas (Mosaic) TPU kernels for the hot search paths.

The XLA paths in :mod:`raft_tpu.neighbors` express everything as dense
masked matmuls because XLA cannot gather *only* the probed IVF lists
efficiently. Pallas can: a scalar-prefetch grid spec lets the block index
map read the probe table, so the DMA engine streams exactly the probed
lists from HBM into VMEM — the TPU answer to the reference's fused
interleaved-scan CUDA kernel (``ivf_flat_interleaved_scan-inl.cuh:687``),
with the reference's per-(query,probe) kernel grid replaced by a
(query-tile, probe-slot) grid over DMA'd list blocks.
"""
from raft_tpu.ops.pallas.ivf_scan import (
    fused_list_topk,
    ivf_flat_fused_search,
    spatial_center_rank,
)

__all__ = [
    "fused_list_topk",
    "ivf_flat_fused_search",
    "spatial_center_rank",
]
