"""Pallas fused probed-list scan for IVF-PQ search.

Reference analog: the shared-memory LUT similarity kernel
(``neighbors/detail/ivf_pq_compute_similarity-inl.cuh:252-457``) with its
fp8/half LUTs (``detail/ivf_pq_fp_8bit.cuh``) — one CUDA kernel per
(query, probe) that builds a per-subspace lookup table in shared memory
and accumulates ``sum_j LUT[j, code_j]`` over the probed list's codes.

TPU design
----------
TPUs have no fast per-lane gather, so the LUT lookup becomes a **multi-hot
matmul**: per query tile, the LUT ``W[q, (j, c)] = <q_sub[j], books[j, c]>``
is computed ONCE outside the kernel ([nq, K] bf16, K = pq_dim * ksub) and
the kernel scores a code block by expanding its codes to a multi-hot
``S [rows, K]`` (pq_dim ones per row, built with VPU compares) and taking
``W @ S^T`` on the MXU. With ksub <= 64 the decode FLOPs stay a small
multiple of the raw-vector scan's — and the DMA drops to the CODE bytes
(16-64 B/row instead of 256-512 B/row), which is the entire point of PQ:
on bandwidth-bound hardware the compressed index scans faster than raw
vectors and an order of magnitude beyond what fits in HBM raw.

Probe scheduling, tile-coherent query ordering, scalar-prefetch DMA of
only the probed code blocks, and the bank-merge running top-k are shared
with the IVF-Flat fused scan (:mod:`raft_tpu.ops.pallas.ivf_scan`).

Code layouts (``code_mode``):

* ``"u8"``  — one byte per sub-quantizer code, ``ksub = 2^pq_bits <= 64``.
* ``"nib8"`` — additive nibble pairs: byte j holds ``(hi, lo)`` indexing
  two 16-entry codebooks ``A[j], B[j]`` whose SUM quantizes subspace j
  (256 effective centers from 32 columns of W — 8-bit quality at 4-bit
  decode cost). The TPU-native substitute for the reference's fp8 LUTs.
* ``"p4"``  — packed 4-bit codes: byte b holds codes ``2b`` (low nibble)
  and ``2b+1`` (high nibble), ``ksub = 16``
  (``ivf_pq_types.hpp:129-164`` / ``detail/ivf_pq_codepacking.cuh``
  analog; here simple pairwise packing, not 16-byte interleave — TPU DMA
  wants plain contiguous bytes).

Supported metrics: L2Expanded / L2SqrtExpanded / InnerProduct (the
reference's PQ metric set).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType
from raft_tpu.ops.pallas import vmem_model
from raft_tpu.ops.pallas.ivf_scan import (
    _eff_banks,
    _extract_topk,
    _seg_compress,
    build_tile_probe_tables,
)

_SUPPORTED = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.InnerProduct,
    }
)


def supported_metric(metric: DistanceType) -> bool:
    return metric in _SUPPORTED


# (n_groups, gw) of the multi-hot column space — shared with the VMEM
# residency model so the decode-chunk budget and the kernel agree on the
# column layout by construction.
_code_groups = vmem_model.code_groups


def _multi_hot(cod, *, code_mode: str, ksub: int, m: int, bpr: int,
               g0: int = 0, ng: int = 0):
    """Expand a [m, bpr] uint8 code block to the multi-hot ``S [m, Kc]``
    bf16 the decode matmul consumes — the column chunk covering groups
    ``[g0, g0 + ng)`` of the full K-column space (``ng=0`` = all groups;
    chunking keeps S inside VMEM for 256-entry codebooks, where the full
    K = pq_dim * 256 would be tens of MB). Column order must match the W
    layout built in :func:`pq_lut`.

    Built entirely in 2D (Mosaic rejects collapsing a 3D one-hot's minor
    dims): a tiny "spread" matmul broadcasts byte j across its K-column
    group (code values <= 255 are exact in bf16/f32), nibbles are peeled
    arithmetically, and one lane-iota compare yields the one-hots.

    ``"b3"``/``"b5"``/``"b6"``/``"b7"`` (spanning little-endian bitstreams) use
    TWO spread matmuls — code j's low byte ``(j*b)//8`` and high byte one
    past it — then peel the value with power-of-two floor arithmetic
    (shifts <= 7 of bytes <= 255: every intermediate is an exact f32
    integer)."""
    n_groups, gw = _code_groups(code_mode, ksub, bpr)
    if not ng:
        ng = n_groups
    Kc = ng * gw
    # u8 -> f32 via i32 (Mosaic has no direct u8 -> float cast)
    codf = cod.astype(jnp.int32).astype(jnp.float32)  # [m, bpr]
    ej = lax.broadcasted_iota(jnp.int32, (bpr, Kc), 0)
    ec = lax.broadcasted_iota(jnp.int32, (bpr, Kc), 1)
    lane = lax.broadcasted_iota(jnp.int32, (m, Kc), 1)
    if code_mode in ("b3", "b5", "b6", "b7"):
        b = int(code_mode[1:])
        jb = (g0 + ec // ksub) * b  # code j's first global bit, per column
        s_lo = (ej == jb // 8).astype(jnp.float32)
        s_hi = (ej == jb // 8 + 1).astype(jnp.float32)  # all-zero col when
        #   the code ends inside its low byte OR at the row's last byte
        bl = lax.dot_general(
            codf, s_lo, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [m, Kc]
        bh = lax.dot_general(
            codf, s_hi, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        off = ((g0 + lane // ksub) * b) % 8
        lo_bits = jnp.minimum(8 - off, b)
        p_off = jnp.exp2(-off.astype(jnp.float32))
        p_lob = jnp.exp2(lo_bits.astype(jnp.float32))
        p_hib = jnp.exp2((b - lo_bits).astype(jnp.float32))
        t = jnp.floor(bl * p_off)  # low byte >> off
        v_lo = t - jnp.floor(t / p_lob) * p_lob  # ... & (2^lo_bits - 1)
        v_hi = (bh - jnp.floor(bh / p_hib) * p_hib) * p_lob
        sub = (lane % ksub).astype(jnp.float32)
        return (v_lo + v_hi == sub).astype(jnp.bfloat16)
    spread = (g0 + ec // gw == ej).astype(jnp.float32)  # [bpr, Kc] block-const
    byte_lane = lax.dot_general(
        codf, spread, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [m, Kc] — byte g0+j's value on each of its gw lanes
    if code_mode == "u8":
        sub = (lane % gw).astype(jnp.float32)
        return (byte_lane == sub).astype(jnp.bfloat16)
    sub16 = (lane % 16).astype(jnp.float32)
    hi = jnp.floor(byte_lane * 0.0625)  # byte >> 4, exact in f32
    lo = byte_lane - 16.0 * hi
    if code_mode == "nib8":
        # per byte: [A-one-hot (hi) | B-one-hot (lo)]
        val = jnp.where(lane % 32 < 16, hi, lo)
    else:  # p4: byte b = (code 2b in low nibble, code 2b+1 in high)
        val = jnp.where(lane % 32 < 16, lo, hi)
    return (val == sub16).astype(jnp.bfloat16)


# per-cell decode footprint — shared with the VMEM residency model
_decode_cell_bytes = vmem_model.decode_cell_bytes


def _decode_chunk_budget(*, m: int, code_mode: str, ksub: int, bpr: int,
                         **model_kwargs) -> int:
    """Bytes of scoped VMEM one decode chunk may use at this shape:
    ``VMEM_HEADROOM x VMEM_LIMIT`` minus the kernel's fixed residents
    (W tile, q_rot, bank/acc scratch, double-buffered code+epilogue
    DMA, dot accumulator) as accounted by
    :func:`raft_tpu.ops.pallas.vmem_model.pq_decode_chunk_budget`.
    Replaces the historical hand-calibrated 8 MB constant, which this
    derivation reproduces within 2% at its calibration shape
    (m=1152, ksub=256) while adapting to every other shape."""
    return vmem_model.pq_decode_chunk_budget(
        m=m, code_mode=code_mode, ksub=ksub, bpr=bpr, **model_kwargs
    )


def decode_feasible(*, m: int, code_mode: str, ksub: int, bpr: int,
                    **model_kwargs) -> bool:
    """Whether even a single-group decode chunk fits the derived VMEM
    budget — false for very long lists with wide codebooks (e.g.
    ksub=256 with max_list > ~3400), where the fused kernel cannot
    compile and callers must use the scan path instead."""
    _, gw = _code_groups(code_mode, ksub, bpr)
    budget = _decode_chunk_budget(
        m=m, code_mode=code_mode, ksub=ksub, bpr=bpr, **model_kwargs
    )
    return _decode_cell_bytes(code_mode) * m * gw <= budget


def vmem_decode_cols(requested: int, *, m: int, code_mode: str, ksub: int,
                     bpr: int, **model_kwargs) -> int:
    """Cap the decode column chunk so the kernel's scoped-VMEM stack fits
    the TPU's ~16 MB limit.

    A chunk materializes the multi-hot ``S [m, Kc]`` bf16 plus f32
    byte-spread intermediates (see :func:`_decode_cell_bytes`). Measured
    at the 1M-row bench shape (m=1152, ksub=256, Kc=2048) the kernel
    needs 17.19 MiB and the Mosaic compile dies at 16 MiB; capping the
    chunk to the per-shape budget :func:`_decode_chunk_budget` derives
    from the kernel's fixed residents keeps the whole stack inside the
    limit with margin. Chunks cover whole code groups, so the cap rounds
    down to a multiple of the group width. Raises when even one group
    cannot fit (use :func:`decode_feasible` to route such shapes to the
    scan path up front). ``model_kwargs`` (``qt``/``k``/``g_lists``/
    ``rot_dim``/``merge``) refine the resident accounting; omitted ones
    fall back to conservative defaults."""
    n_groups, gw = _code_groups(code_mode, ksub, bpr)
    K = n_groups * gw
    if not requested:
        requested = K
    expects(
        decode_feasible(m=m, code_mode=code_mode, ksub=ksub, bpr=bpr,
                        **model_kwargs),
        "fused PQ decode infeasible: one %d-column group over %d rows "
        "exceeds the VMEM chunk budget — use mode='scan' or more lists",
        gw, m,
    )
    budget = _decode_chunk_budget(
        m=m, code_mode=code_mode, ksub=ksub, bpr=bpr, **model_kwargs
    )
    cap = int(budget // (_decode_cell_bytes(code_mode) * max(m, 1)))
    cap = max(gw, (cap // gw) * gw)
    return min(requested, cap, K)


def _make_pq_kernel(*, k, metric, merge, qt, m, g_lists, n_steps, K,
                    code_mode, ksub, bpr, extract_every, decode_cols):
    banks = _eff_banks(merge, m, 0)
    n_groups, gw = _code_groups(code_mode, ksub, bpr)
    # decode in column chunks so S stays VMEM-resident even for 256-entry
    # codebooks (K = pq_dim * 256); a chunk covers whole groups
    chunk_groups = n_groups if not decode_cols else max(1, decode_cols // gw)
    chunk_groups = min(chunk_groups, n_groups)

    def kernel(pr_ref, pv_ref, w_ref, qrot_ref, crot_ref, cod_ref, ln_ref,
               outv_ref, outi_ref, accv, acci, bankv, banki):
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            accv[...] = jnp.full((qt, k), jnp.inf, jnp.float32)
            acci[...] = jnp.full((qt, k), -1, jnp.int32)
            bankv[...] = jnp.full((qt, banks * 128), jnp.inf, jnp.float32)
            banki[...] = jnp.full((qt, banks * 128), -1, jnp.int32)

        @pl.when(pv_ref[i, j] > 0)
        def _():
            w = w_ref[...]  # [qt, K] bf16
            base = pr_ref[i, j] * (g_lists * m)
            # coarse q.c term for the DMA'd lists (q_rot.c_rot == q.c under
            # the orthonormal rotation): one tiny [qt, G] matmul per step
            qdc = lax.dot_general(
                qrot_ref[...],
                crot_ref[0],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [qt, G]
            # one column chunk per list: the q.c coarse term is constant
            # within a list, so it folds into the chunk epilogue as a
            # scalar column instead of a [qt, m] pass
            for g in range(g_lists):
                cod = cod_ref[0, g * m : (g + 1) * m, :]  # [m, bpr] u8
                dot = jnp.zeros((qt, m), jnp.float32)
                for g0 in range(0, n_groups, chunk_groups):
                    ngc = min(chunk_groups, n_groups - g0)
                    s = _multi_hot(
                        cod, code_mode=code_mode, ksub=ksub, m=m, bpr=bpr,
                        g0=g0, ng=ngc,
                    )
                    dot = dot + lax.dot_general(
                        w[:, g0 * gw : (g0 + ngc) * gw],
                        s,
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )  # [qt, m]
                ln = ln_ref[0, 0, g * m : (g + 1) * m]  # prepared epilogue
                if metric == DistanceType.InnerProduct:
                    score = ln[None, :] - dot - qdc[:, g][:, None]
                else:
                    score = ln[None, :] - 2.0 * (dot + qdc[:, g][:, None])
                v, sl = _seg_compress(score, base + g * m, qt, m, banks)
                take = v < bankv[...]
                bankv[...] = jnp.where(take, v, bankv[...])
                banki[...] = jnp.where(take, sl, banki[...])

        if extract_every and extract_every < n_steps:
            do_extract = ((j + 1) % extract_every == 0) | (j == n_steps - 1)
        else:
            do_extract = j == n_steps - 1

        @pl.when(do_extract)
        def _():
            cv = jnp.concatenate([accv[...], bankv[...]], axis=1)
            ci = jnp.concatenate([acci[...], banki[...]], axis=1)
            nv, ni = _extract_topk(cv, ci, k)
            accv[...] = nv
            acci[...] = ni
            bankv[...] = jnp.full((qt, banks * 128), jnp.inf, jnp.float32)
            banki[...] = jnp.full((qt, banks * 128), -1, jnp.int32)

        @pl.when(j == n_steps - 1)
        def _():
            outv_ref[...] = accv[...]
            outi_ref[...] = acci[...]

    return kernel


def kernel_scratch_shapes(qt: int, k: int, banks: int):
    """The fused PQ kernel's scratch declarations: running top-k
    accumulator pair + bank-merge pair. Split out so tests can assert
    the VMEM residency model against the shapes the kernel actually
    allocates (``vmem_model.pq_scan_residency`` mirrors these)."""
    return [
        pltpu.VMEM((qt, k), jnp.float32),
        pltpu.VMEM((qt, k), jnp.int32),
        pltpu.VMEM((qt, banks * 128), jnp.float32),
        pltpu.VMEM((qt, banks * 128), jnp.int32),
    ]


def pq_lut(q_rot, books) -> jax.Array:
    """Per-query LUT ``W [nq, K]`` bf16: ``W[n, (j, c)] = <q_sub[n, j],
    books[j, c]>`` (the ``compute_similarity`` smem LUT, built once per
    query batch outside the kernel). ``books [pq_dim_eff, ksub_eff,
    pq_len]``; for nib8/p4 layouts the (j, c) flattening of ``books``
    must already match the kernel's multi-hot column order."""
    nq = q_rot.shape[0]
    pq_dim_eff, ksub_eff, pq_len = books.shape
    q_sub = q_rot.reshape(nq, pq_dim_eff, pq_len)
    w = jnp.einsum(
        "npl,pkl->npk", q_sub, books, preferred_element_type=jnp.float32
    )
    return w.reshape(nq, pq_dim_eff * ksub_eff).astype(jnp.bfloat16)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "metric", "qt", "merge", "code_mode", "ksub", "extract_every",
        "decode_cols", "interpret",
    ),
)
def fused_pq_topk(
    codes,        # [n_units, gm, bpr] u8
    ln,           # [n_units, 1, gm] f32 prepared epilogue (sqn/pen, +inf invalid)
    w,            # [nq_pad, K] bf16 per-query LUT rows (tile-sorted)
    q_rot,        # [nq_pad, rot_dim] f32 rotated queries (tile-sorted)
    centers_rot,  # [n_units, G, rot_dim] f32 rotated coarse centers
    tile_probes,
    probe_valid,
    *,
    k: int,
    metric: DistanceType,
    qt: int,
    merge: str = "bank8",
    code_mode: str = "u8",
    ksub: int = 16,
    extract_every: int = 0,
    decode_cols: int = 2048,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Run the fused probed-list PQ scan; returns ``(scores [nq_pad, k]
    asc, slots [nq_pad, k])`` with slot = unit * (G * max_list) + row."""
    n_units, gm, bpr = codes.shape
    nq_pad, K = w.shape
    rot_dim = q_rot.shape[1]
    n_qt, n_steps = tile_probes.shape
    g_lists = centers_rot.shape[1]
    m = gm // g_lists
    expects(nq_pad == n_qt * qt, "query rows %d != tiles*qt %d", nq_pad, n_qt * qt)
    expects(merge.startswith("bank"), "pq fused scan requires a bank merge mode")

    kernel = _make_pq_kernel(
        k=k, metric=metric, merge=merge, qt=qt, m=m, g_lists=g_lists,
        n_steps=n_steps, K=K, code_mode=code_mode, ksub=ksub, bpr=bpr,
        extract_every=extract_every, decode_cols=decode_cols,
    )
    banks = _eff_banks(merge, m, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_qt, n_steps),
        in_specs=[
            pl.BlockSpec((qt, K), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((qt, rot_dim), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((1, g_lists, rot_dim), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
            # codes rows are deliberately narrow (bpr = 16-64 B/row is
            # the whole point of PQ): the lane padding the linter sees
            # costs VMEM but the HBM DMA moves only the real code bytes
            pl.BlockSpec((1, gm, bpr), lambda i, j, pr, pv: (pr[i, j], 0, 0)),  # graft-lint: ignore[tile-align]
            pl.BlockSpec((1, 1, gm), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((qt, k), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((qt, k), lambda i, j, pr, pv: (i, 0)),
        ],
        scratch_shapes=kernel_scratch_shapes(qt, k, banks),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nq_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((nq_pad, k), jnp.int32),
        ],
        interpret=interpret,
    )(tile_probes, probe_valid, w, q_rot, centers_rot, codes, ln)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "qt", "probe_factor", "group",
        "has_filter", "merge", "code_mode", "ksub", "extract_every",
        "decode_cols", "interpret",
    ),
)
def ivf_pq_fused_search(
    centers,
    centers_rot,
    center_rank,
    rotation,
    books,        # [pq_dim_eff, ksub_eff, pq_len] f32, W column order
    codes,        # [n_lists, max_list, bpr] u8
    list_indices,
    rot_sqnorms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    qt: int = 128,
    probe_factor: int = 32,
    group: int = 8,
    has_filter: bool = False,
    merge: str = "bank8",
    code_mode: str = "u8",
    ksub: int = 16,
    extract_every: int = 0,
    decode_cols: int = 2048,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-PQ search through the Pallas fused scan. Candidate-set
    semantics match the probe path whenever the tile probe union fits the
    table (see :func:`ivf_scan.ivf_flat_fused_search`); scores are exact
    ADC distances of the (possibly additive-nibble) codebooks, so pairing
    with :func:`raft_tpu.neighbors.refine.refine` mirrors the reference's
    refinement ratio workflow."""
    nq, d = queries.shape
    n_lists, m, bpr = codes.shape
    qf = queries.astype(jnp.float32)

    from raft_tpu.neighbors.ivf_common import probe_selection

    coarse, probed = probe_selection(centers, qf, n_probes, metric)
    order_pad, tile_probes, probe_valid = build_tile_probe_tables(
        coarse, probed, center_rank, nq=nq, qt=qt, n_lists=n_lists,
        group=group, n_probes=n_probes, probe_factor=probe_factor,
    )
    nq_pad = order_pad.shape[0]
    qs = qf[order_pad]

    # per-query LUT, in tile order (the q.c coarse term is computed
    # in-kernel from q_rot x centers_rot — rotation-invariant)
    q_rot = qs @ rotation.T
    w = pq_lut(q_rot, books)
    n_units = n_lists // group
    rot_dim = rotation.shape[0]

    # prepared epilogue: sqn (+inf invalid) for L2, 0/+inf penalty for IP,
    # with the prefilter folded in
    valid = list_indices >= 0
    if has_filter:
        ids = jnp.clip(list_indices, 0, None)
        word = filter_bits[ids // 32]
        bit = (word >> (ids % 32).astype(jnp.uint32)) & 1
        valid = valid & (bit == 1)
    if metric == DistanceType.InnerProduct:
        ln = jnp.where(valid, 0.0, jnp.inf).astype(jnp.float32)
    else:
        ln = jnp.where(valid, rot_sqnorms, jnp.inf)

    from raft_tpu.ops.pallas._guard import kernel_guard

    gm = group * m
    with kernel_guard("ivf_pq_fused_search"):
        vals, slots = fused_pq_topk(
            codes.reshape(n_units, gm, bpr),
            ln.reshape(n_units, 1, gm),
            w,
            q_rot,
            centers_rot.reshape(n_units, group, rot_dim),
            tile_probes,
            probe_valid,
            k=k,
            metric=metric,
            qt=qt,
            merge=merge,
            code_mode=code_mode,
            ksub=ksub,
            extract_every=extract_every,
            decode_cols=decode_cols,
            interpret=interpret,
        )

    # postprocess (mirrors _ivf_pq_scan_impl's tail)
    flat_ids = list_indices.reshape(-1)
    idx = jnp.where(slots >= 0, flat_ids[jnp.clip(slots, 0, None)], -1)
    if metric == DistanceType.InnerProduct:
        out = -vals
    else:
        qn = jnp.sum(q_rot * q_rot, axis=1)
        out = jnp.maximum(qn[:, None] + vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)

    order = order_pad[:nq]
    dist = jnp.zeros((nq, k), jnp.float32).at[order].set(out[:nq])
    ind = jnp.full((nq, k), -1, jnp.int32).at[order].set(idx[:nq])
    return dist, ind
