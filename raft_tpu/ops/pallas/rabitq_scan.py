"""Pallas fused probed-list scan for IVF-RaBitQ search.

Reference analog: the bitwise IVF-RaBitQ scan of "GPU-Native Approximate
Nearest Neighbor Search with IVF-RaBitQ" (PAPERS.md) — one bit per
rotated-residual dimension plus two per-vector scalar corrections, scored
with the unbiased estimator and rescored through ``refine``.

TPU design
----------
The estimator needs one number per scanned row: the sign-bit dot
``b . q_rot``. On TPU that is a plain matmul against the unpacked bit
plane — no LUT, no per-lane gather. Per probed list the kernel

1. unpacks the ``[m, bpr]`` u8 codes to a ``[rows, D]`` f32 0/1 plane
   (byte-spread matmul + power-of-two floor peel, all exact in f32;
   row-chunked under the VMEM budget of
   :func:`raft_tpu.ops.pallas.vmem_model.rabitq_decode_rows_budget`),
2. takes ``dot = q_rot @ bits^T`` on the MXU ([qt, m] f32), and
3. applies the elementwise epilogue with the two prepared per-slot
   channels — ``ln`` (the center-dependent constant ``C1``, +inf for
   invalid/filtered slots) and ``corr`` (the estimator scale ``g``):

       score = ln - coef * (q . c_l) - g * (dot - sum(q_rot) / 2)

   (min-score convention; ``coef`` = 2 for L2, 1 for IP — the encode side
   in :mod:`raft_tpu.neighbors.ivf_pq` folds every other estimator term
   into ``ln``/``g`` so ONE kernel formula serves both metrics).

Versus the PQ fused scan the DMA per row is identical at d=128 (16 B)
but the decode matmul shrinks from ``pq_dim * ksub`` multi-hot columns
to D sign columns — the per-row FLOP drop the paper banks on.

Probe scheduling, tile-coherent query ordering, scalar-prefetch DMA of
only the probed code blocks, and the bank-merge running top-k are shared
with :mod:`raft_tpu.ops.pallas.ivf_scan` / :mod:`~.pq_scan`.

Supported metrics: L2Expanded / L2SqrtExpanded / InnerProduct.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType
from raft_tpu.ops.pallas import vmem_model
from raft_tpu.ops.pallas.ivf_scan import (
    _eff_banks,
    _extract_topk,
    _seg_compress,
    build_tile_probe_tables,
)

_SUPPORTED = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.InnerProduct,
    }
)


def supported_metric(metric: DistanceType) -> bool:
    return metric in _SUPPORTED


def _sign_bits(cod, *, rows: int, bpr: int, rot_dim: int):
    """Unpack a ``[rows, bpr]`` u8 code block to its ``[rows, rot_dim]``
    f32 0/1 sign plane (little-endian bit t of byte s = dimension
    ``s*8 + t``, matching ``ivf_pq.pack_codes_bits``). Built entirely in
    2D for Mosaic: a spread matmul broadcasts byte ``t // 8`` onto lane
    t (bytes <= 255 are exact in f32), then a power-of-two floor peel
    extracts bit ``t % 8`` (shifts <= 7 of exact integers — every
    intermediate is an exact f32 integer)."""
    # u8 -> f32 via i32 (Mosaic has no direct u8 -> float cast)
    codf = cod.astype(jnp.int32).astype(jnp.float32)  # [rows, bpr]
    ej = lax.broadcasted_iota(jnp.int32, (bpr, rot_dim), 0)
    et = lax.broadcasted_iota(jnp.int32, (bpr, rot_dim), 1)
    spread = (ej == et // 8).astype(jnp.float32)  # [bpr, rot_dim]
    byte_lane = lax.dot_general(
        codf, spread, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [rows, rot_dim] — dimension t's byte value on lane t
    tib = lax.broadcasted_iota(jnp.int32, (rows, rot_dim), 1) % 8
    t = jnp.floor(byte_lane * jnp.exp2(-tib.astype(jnp.float32)))  # >> t%8
    return t - 2.0 * jnp.floor(t * 0.5)  # ... & 1


def _decode_rows_budget(*, m: int, bpr: int, **model_kwargs) -> int:
    """Bytes of scoped VMEM one sign-plane row chunk may use at this
    shape (see :func:`vmem_model.rabitq_decode_rows_budget`)."""
    return vmem_model.rabitq_decode_rows_budget(m=m, bpr=bpr, **model_kwargs)


def vmem_decode_rows(
    *,
    m: int,
    bpr: int,
    qt: int = 128,
    k: int = 128,
    g_lists: int = 8,
    rot_dim: int = 128,
    merge: str = "bank8",
) -> int:
    """Row-chunk size for the in-kernel sign-bit unpack so the scoped
    VMEM stack fits the TPU's ~16 MB limit: the per-shape budget divided
    by :data:`vmem_model.RABITQ_DECODE_CELL_BYTES` per (row, dim) cell,
    rounded down to a multiple of 128 rows (sublane-friendly chunks).
    Returns ``m`` when the whole list fits in one chunk and 0 when not
    even a 128-row chunk fits (fused-infeasible — see
    :func:`rabitq_feasible`)."""
    budget = _decode_rows_budget(
        m=m, bpr=bpr, qt=qt, k=k, g_lists=g_lists, rot_dim=rot_dim,
        merge=merge,
    )
    per_row = vmem_model.RABITQ_DECODE_CELL_BYTES * rot_dim
    cap = max(0, budget) // per_row
    if cap >= m:
        return m
    return (cap // 128) * 128


def rabitq_feasible(
    *,
    m: int,
    bpr: int,
    qt: int = 128,
    k: int = 128,
    g_lists: int = 8,
    rot_dim: int = 128,
    merge: str = "bank8",
) -> bool:
    """Whether the fused rabitq kernel fits VMEM at this shape — false
    for very long lists (the full ``[qt, m]`` dot accumulator plus one
    row chunk exceed the budget), where callers must use the scan path
    instead."""
    return (
        vmem_decode_rows(
            m=m, bpr=bpr, qt=qt, k=k, g_lists=g_lists, rot_dim=rot_dim,
            merge=merge,
        )
        > 0
    )


def _make_rabitq_kernel(*, k, metric, merge, qt, m, g_lists, n_steps,
                        rot_dim, bpr, extract_every, decode_rows):
    banks = _eff_banks(merge, m, 0)
    chunk_rows = m if not decode_rows else min(decode_rows, m)

    def kernel(pr_ref, pv_ref, qrot_ref, crot_ref, cod_ref, ln_ref,
               corr_ref, outv_ref, outi_ref, accv, acci, bankv, banki):
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            accv[...] = jnp.full((qt, k), jnp.inf, jnp.float32)
            acci[...] = jnp.full((qt, k), -1, jnp.int32)
            bankv[...] = jnp.full((qt, banks * 128), jnp.inf, jnp.float32)
            banki[...] = jnp.full((qt, banks * 128), -1, jnp.int32)

        @pl.when(pv_ref[i, j] > 0)
        def _():
            qr = qrot_ref[...]  # [qt, rot_dim]
            sq = jnp.sum(qr, axis=1)  # [qt] — the estimator's sum(q_rot)
            base = pr_ref[i, j] * (g_lists * m)
            # coarse q.c term for the DMA'd lists (q_rot.c_rot == q.c under
            # the orthonormal rotation): one tiny [qt, G] matmul per step
            qdc = lax.dot_general(
                qr,
                crot_ref[0],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [qt, G]
            for g in range(g_lists):
                cod = cod_ref[0, g * m : (g + 1) * m, :]  # [m, bpr] u8
                # row-chunked sign unpack: only one [rows, rot_dim] bit
                # plane is live at a time; the dots concatenate back to
                # the full [qt, m] accumulator (static chunk bounds)
                parts = []
                for r0 in range(0, m, chunk_rows):
                    rc = min(chunk_rows, m - r0)
                    bits = _sign_bits(
                        cod[r0 : r0 + rc, :], rows=rc, bpr=bpr,
                        rot_dim=rot_dim,
                    )
                    parts.append(
                        lax.dot_general(
                            qr, bits,
                            dimension_numbers=(((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                    )  # [qt, rc]
                dot = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
                ln = ln_ref[0, 0, g * m : (g + 1) * m]  # prepared C1 (+inf invalid)
                gc = corr_ref[0, 0, g * m : (g + 1) * m]  # prepared g
                if metric == DistanceType.InnerProduct:
                    coef = 1.0
                else:
                    coef = 2.0
                score = (
                    ln[None, :]
                    - coef * qdc[:, g][:, None]
                    - gc[None, :] * (dot - 0.5 * sq[:, None])
                )
                v, sl = _seg_compress(score, base + g * m, qt, m, banks)
                take = v < bankv[...]
                bankv[...] = jnp.where(take, v, bankv[...])
                banki[...] = jnp.where(take, sl, banki[...])

        if extract_every and extract_every < n_steps:
            do_extract = ((j + 1) % extract_every == 0) | (j == n_steps - 1)
        else:
            do_extract = j == n_steps - 1

        @pl.when(do_extract)
        def _():
            cv = jnp.concatenate([accv[...], bankv[...]], axis=1)
            ci = jnp.concatenate([acci[...], banki[...]], axis=1)
            nv, ni = _extract_topk(cv, ci, k)
            accv[...] = nv
            acci[...] = ni
            bankv[...] = jnp.full((qt, banks * 128), jnp.inf, jnp.float32)
            banki[...] = jnp.full((qt, banks * 128), -1, jnp.int32)

        @pl.when(j == n_steps - 1)
        def _():
            outv_ref[...] = accv[...]
            outi_ref[...] = acci[...]

    return kernel


def kernel_scratch_shapes(qt: int, k: int, banks: int):
    """The fused rabitq kernel's scratch declarations: running top-k
    accumulator pair + bank-merge pair (identical to pq_scan's). Split
    out so tests can assert the VMEM residency model against the shapes
    the kernel actually allocates (``vmem_model.rabitq_scan_residency``
    mirrors these)."""
    return [
        pltpu.VMEM((qt, k), jnp.float32),
        pltpu.VMEM((qt, k), jnp.int32),
        pltpu.VMEM((qt, banks * 128), jnp.float32),
        pltpu.VMEM((qt, banks * 128), jnp.int32),
    ]


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "qt", "merge", "extract_every",
                     "decode_rows", "interpret"),
)
def fused_rabitq_topk(
    codes,        # [n_units, gm, bpr] u8 packed sign bits
    ln,           # [n_units, 1, gm] f32 prepared C1 (+inf invalid)
    corr,         # [n_units, 1, gm] f32 prepared g (0 at pad slots)
    q_rot,        # [nq_pad, rot_dim] f32 rotated queries (tile-sorted)
    centers_rot,  # [n_units, G, rot_dim] f32 rotated coarse centers
    tile_probes,
    probe_valid,
    *,
    k: int,
    metric: DistanceType,
    qt: int,
    merge: str = "bank8",
    extract_every: int = 0,
    decode_rows: int = 0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Run the fused probed-list rabitq scan; returns ``(scores [nq_pad,
    k] asc, slots [nq_pad, k])`` with slot = unit * (G * max_list) + row."""
    n_units, gm, bpr = codes.shape
    nq_pad, rot_dim = q_rot.shape
    n_qt, n_steps = tile_probes.shape
    g_lists = centers_rot.shape[1]
    m = gm // g_lists
    expects(nq_pad == n_qt * qt, "query rows %d != tiles*qt %d", nq_pad, n_qt * qt)
    expects(merge.startswith("bank"), "rabitq fused scan requires a bank merge mode")
    expects(bpr * 8 == rot_dim, "rabitq codes carry %d bits/row but rot_dim=%d",
            bpr * 8, rot_dim)

    kernel = _make_rabitq_kernel(
        k=k, metric=metric, merge=merge, qt=qt, m=m, g_lists=g_lists,
        n_steps=n_steps, rot_dim=rot_dim, bpr=bpr,
        extract_every=extract_every, decode_rows=decode_rows,
    )
    banks = _eff_banks(merge, m, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_qt, n_steps),
        in_specs=[
            pl.BlockSpec((qt, rot_dim), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((1, g_lists, rot_dim), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
            # codes rows are deliberately narrow (bpr = D/8 bytes/row is
            # the whole point of RaBitQ): the lane padding the linter
            # sees costs VMEM but the HBM DMA moves only real code bytes
            pl.BlockSpec((1, gm, bpr), lambda i, j, pr, pv: (pr[i, j], 0, 0)),  # graft-lint: ignore[tile-align]
            pl.BlockSpec((1, 1, gm), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
            pl.BlockSpec((1, 1, gm), lambda i, j, pr, pv: (pr[i, j], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((qt, k), lambda i, j, pr, pv: (i, 0)),
            pl.BlockSpec((qt, k), lambda i, j, pr, pv: (i, 0)),
        ],
        scratch_shapes=kernel_scratch_shapes(qt, k, banks),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nq_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((nq_pad, k), jnp.int32),
        ],
        interpret=interpret,
    )(tile_probes, probe_valid, q_rot, centers_rot, codes, ln, corr)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "qt", "probe_factor", "group",
        "has_filter", "merge", "extract_every", "decode_rows", "interpret",
    ),
)
def ivf_rabitq_fused_search(
    centers,
    centers_rot,
    center_rank,
    rotation,
    codes,        # [n_lists, max_list, bpr] u8 packed sign bits
    list_indices,
    rot_sqnorms,  # [n_lists, max_list] f32 — the estimator constant C1
    corrections,  # [n_lists, max_list] f32 — the estimator scale g
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    qt: int = 128,
    probe_factor: int = 32,
    group: int = 8,
    has_filter: bool = False,
    merge: str = "bank8",
    extract_every: int = 0,
    decode_rows: int = 0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """IVF-RaBitQ search through the Pallas fused scan. Candidate-set
    semantics match the probe path whenever the tile probe union fits the
    table (see :func:`ivf_scan.ivf_flat_fused_search`); scores are the
    unbiased rabitq estimates, so pairing with
    :func:`raft_tpu.neighbors.refine.refine` recovers exact-rank results
    the way the paper's rescoring pass does."""
    nq, d = queries.shape
    n_lists, m, bpr = codes.shape
    qf = queries.astype(jnp.float32)

    from raft_tpu.neighbors.ivf_common import probe_selection

    coarse, probed = probe_selection(centers, qf, n_probes, metric)
    order_pad, tile_probes, probe_valid = build_tile_probe_tables(
        coarse, probed, center_rank, nq=nq, qt=qt, n_lists=n_lists,
        group=group, n_probes=n_probes, probe_factor=probe_factor,
    )
    nq_pad = order_pad.shape[0]
    qs = qf[order_pad]
    q_rot = qs @ rotation.T
    n_units = n_lists // group
    rot_dim = rotation.shape[0]

    # prepared epilogue: the estimator constant C1 (stored in rot_sqnorms;
    # identically 0 for IP) with invalid/filtered slots pushed to +inf, and
    # the scale g (0 at pad slots, so inf - 0*dot stays inf, never NaN)
    valid = list_indices >= 0
    if has_filter:
        ids = jnp.clip(list_indices, 0, None)
        word = filter_bits[ids // 32]
        bit = (word >> (ids % 32).astype(jnp.uint32)) & 1
        valid = valid & (bit == 1)
    ln = jnp.where(valid, rot_sqnorms, jnp.inf)
    corr = jnp.where(valid, corrections, 0.0)

    from raft_tpu.ops.pallas._guard import kernel_guard

    gm = group * m
    with kernel_guard("ivf_rabitq_fused_search"):
        vals, slots = fused_rabitq_topk(
            codes.reshape(n_units, gm, bpr),
            ln.reshape(n_units, 1, gm),
            corr.reshape(n_units, 1, gm),
            q_rot,
            centers_rot.reshape(n_units, group, rot_dim),
            tile_probes,
            probe_valid,
            k=k,
            metric=metric,
            qt=qt,
            merge=merge,
            extract_every=extract_every,
            decode_rows=decode_rows,
            interpret=interpret,
        )

    # postprocess (mirrors rabitq_scan_core's tail: est = ||q||^2 + score
    # for L2, est = -score for IP)
    flat_ids = list_indices.reshape(-1)
    idx = jnp.where(slots >= 0, flat_ids[jnp.clip(slots, 0, None)], -1)
    if metric == DistanceType.InnerProduct:
        out = -vals
    else:
        qn = jnp.sum(q_rot * q_rot, axis=1)
        out = jnp.maximum(qn[:, None] + vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)

    order = order_pad[:nq]
    dist = jnp.zeros((nq, k), jnp.float32).at[order].set(out[:nq])
    ind = jnp.full((nq, k), -1, jnp.int32).at[order].set(idx[:nq])
    return dist, ind
