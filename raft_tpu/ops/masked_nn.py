"""Masked L2 1-nearest-neighbor — analog of
``raft::distance::masked_l2_nn`` (``distance/masked_nn.cuh:39``; params
struct ``masked_l2_nn_params`` at ``:67``).

The reference skips whole (x-tile, y-group) distance tiles when the
adjacency bit is off — a compute-skipping win for HDBSCAN-class consumers
(cross-component nearest neighbors). On the MXU, dense tiles beat
data-dependent skipping at these shapes, so the TPU form computes the
tiled fused distance+argmin (the :mod:`raft_tpu.ops.fused_1nn` engine)
and applies the group mask as an additive -inf/+inf epilogue that XLA
fuses into the matmul — the same *semantics* (only adjacent groups
compete) with dense scheduling.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import row_norms
from raft_tpu.utils.math import cdiv


@functools.partial(jax.jit, static_argnames=("sqrt", "tile"))
def _masked_l2_nn_impl(x, y, xn, yn, adj, group_ids, *, sqrt: bool, tile: int):
    m, d = x.shape
    n = y.shape[0]
    n_tiles = cdiv(n, tile)
    pad = n_tiles * tile - n
    yp = jnp.pad(y, ((0, pad), (0, 0))) if pad else y
    ynp = jnp.pad(yn, (0, pad)) if pad else yn
    gp = jnp.pad(group_ids, (0, pad), constant_values=0) if pad else group_ids
    validp = jnp.arange(n_tiles * tile) < n

    y_t = yp.reshape(n_tiles, tile, d)
    yn_t = ynp.reshape(n_tiles, tile)
    g_t = gp.reshape(n_tiles, tile)
    v_t = validp.reshape(n_tiles, tile)

    init = (jnp.full((m,), jnp.inf, jnp.float32), jnp.full((m,), -1, jnp.int32))

    def body(carry, inp):
        best_v, best_i = carry
        t, yt, ynt, gt, vt = inp
        dot = lax.dot_general(
            x, yt, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        dist = xn[:, None] + ynt[None, :] - 2.0 * dot
        dist = jnp.maximum(dist, 0.0)
        # additive mask: adj[i, group(j)] off or padded slot -> +inf
        allowed = adj[:, gt]  # [m, tile] via gather on the small group axis
        pen = jnp.where(vt[None, :] & allowed, 0.0, jnp.inf)
        dist = dist + pen
        tv = jnp.min(dist, axis=1)
        ti = jnp.argmin(dist, axis=1).astype(jnp.int32) + t * tile
        take = tv < best_v
        return (
            jnp.where(take, tv, best_v),
            jnp.where(take, ti, best_i),
        ), None

    (best_v, best_i), _ = lax.scan(
        body, init, (jnp.arange(n_tiles), y_t, yn_t, g_t, v_t)
    )
    best_i = jnp.where(jnp.isfinite(best_v), best_i, -1)
    if sqrt:
        best_v = jnp.sqrt(jnp.maximum(best_v, 0.0))
    best_v = jnp.where(best_i >= 0, best_v, jnp.inf)
    return best_v, best_i


def masked_l2_nn(
    x,
    y,
    adj,
    group_idxs,
    x_sqnorm: Optional[jax.Array] = None,
    y_sqnorm: Optional[jax.Array] = None,
    sqrt: bool = False,
    tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of ``x``, the (distance, index) of its nearest row of
    ``y`` among the *adjacent groups only*.

    Mirrors ``masked_l2_nn`` (``distance/masked_nn.cuh:39``): ``y`` rows
    are partitioned into contiguous groups whose END indices are
    ``group_idxs`` (``group_idxs[k]`` = one past the last row of group k,
    as in the reference), and ``adj [m, num_groups]`` says which groups
    each ``x`` row may connect to. Rows with no adjacent group return
    ``(inf, -1)`` (the reference's maxVal/-1 init).
    """
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    adj = jnp.asarray(adj, bool)
    group_idxs = jnp.asarray(group_idxs, jnp.int32)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1], "bad x/y shapes")
    m, n = x.shape[0], y.shape[0]
    num_groups = group_idxs.shape[0]
    expects(adj.shape == (m, num_groups), "adj must be [m, num_groups]")

    # group id per y row from the END indices: row j belongs to the first
    # group whose end exceeds j
    group_ids = jnp.searchsorted(group_idxs, jnp.arange(n, dtype=jnp.int32), side="right").astype(jnp.int32)
    group_ids = jnp.clip(group_ids, 0, num_groups - 1)

    xn = row_norms(x) if x_sqnorm is None else jnp.asarray(x_sqnorm, jnp.float32)
    yn = row_norms(y) if y_sqnorm is None else jnp.asarray(y_sqnorm, jnp.float32)
    return _masked_l2_nn_impl(
        x, y, xn, yn, adj, group_ids, sqrt=sqrt, tile=int(min(tile, max(n, 8)))
    )
