"""Kernel gram matrices — analog of ``raft::distance::kernels``
(``distance/detail/kernels/gram_matrix.cuh:52`` ``GramMatrixBase``,
``kernel_matrices.cuh`` ``PolynomialKernel``/``TanhKernel``/``RBFKernel``,
``kernel_factory.cuh`` dispatch on ``KernelParams``).

Every kernel is one MXU matmul (or the expanded-L2 matmul for RBF) plus a
fused elementwise epilogue — the natural TPU shape of the reference's
cuBLAS-gemm-plus-epilogue design.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, pairwise_distance


class KernelType(enum.IntEnum):
    """``KernelType`` enum (``kernel_factory.cuh``)."""

    LINEAR = 0
    POLYNOMIAL = 1
    RBF = 2
    TANH = 3


@dataclasses.dataclass
class KernelParams:
    """``KernelParams`` analog: (kernel, degree, gamma, coef0)."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def linear_kernel(x, y) -> jax.Array:
    """x @ y^T (``GramMatrixBase::linear``, ``gram_matrix.cuh``)."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    return x @ y.T


def polynomial_kernel(x, y, degree: int = 3, gamma: float = 1.0, coef0: float = 0.0) -> jax.Array:
    """(gamma x.y + coef0)^degree (``PolynomialKernel``,
    ``kernel_matrices.cuh:153``)."""
    return (gamma * linear_kernel(x, y) + coef0) ** degree


def tanh_kernel(x, y, gamma: float = 1.0, coef0: float = 0.0) -> jax.Array:
    """tanh(gamma x.y + coef0) (``TanhKernel``, ``kernel_matrices.cuh:329``)."""
    return jnp.tanh(gamma * linear_kernel(x, y) + coef0)


def rbf_kernel(x, y, gamma: float = 1.0) -> jax.Array:
    """exp(-gamma ||x - y||^2) (``RBFKernel``, ``kernel_matrices.cuh:497``;
    distances via the expanded-L2 matmul + ``rbf_fin_op.cuh`` epilogue)."""
    d2 = pairwise_distance(x, y, DistanceType.L2Expanded)
    return jnp.exp(-gamma * d2)


def gram_matrix(x, y: Optional[jax.Array] = None, params: Optional[KernelParams] = None, **kwargs) -> jax.Array:
    """Evaluate the gram matrix for ``params.kernel`` — the
    ``KernelFactory::create(params)`` + ``operator()`` path
    (``kernel_factory.cuh:30``). ``y=None`` means the symmetric gram of
    ``x`` with itself."""
    if params is None:
        params = KernelParams(**kwargs)
    y = x if y is None else y
    k = KernelType(params.kernel)
    if k == KernelType.LINEAR:
        return linear_kernel(x, y)
    if k == KernelType.POLYNOMIAL:
        return polynomial_kernel(x, y, params.degree, params.gamma, params.coef0)
    if k == KernelType.TANH:
        return tanh_kernel(x, y, params.gamma, params.coef0)
    expects(k == KernelType.RBF, "unknown kernel %s", k)
    return rbf_kernel(x, y, params.gamma)
