"""Primitive ops layer (L4 analog): pairwise distance, top-k selection,
fused distance+argmin.

See ``SURVEY.md`` §2.3 for the reference component map
(``/root/reference/cpp/include/raft/{distance,matrix}``).
"""
from raft_tpu.ops.distance import (
    DistanceType,
    is_min_close,
    pairwise_distance,
    resolve_metric,
    row_norms,
)
from raft_tpu.ops.fused_1nn import fused_l2_nn, min_cluster_and_distance
from raft_tpu.ops.kernels import (
    KernelParams,
    KernelType,
    gram_matrix,
    linear_kernel,
    polynomial_kernel,
    rbf_kernel,
    tanh_kernel,
)
from raft_tpu.ops.masked_nn import masked_l2_nn
from raft_tpu.ops.select_k import merge_parts, running_merge, select_k, worst_value

__all__ = [
    "KernelParams",
    "KernelType",
    "gram_matrix",
    "linear_kernel",
    "masked_l2_nn",
    "polynomial_kernel",
    "rbf_kernel",
    "tanh_kernel",
    "DistanceType",
    "is_min_close",
    "pairwise_distance",
    "resolve_metric",
    "row_norms",
    "fused_l2_nn",
    "min_cluster_and_distance",
    "merge_parts",
    "running_merge",
    "select_k",
    "worst_value",
]
