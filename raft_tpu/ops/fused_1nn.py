"""Fused distance + argmin — TPU-native analog of ``fusedL2NN``.

The reference fuses the 1-nearest-neighbor reduction into the distance
kernel's epilogue so the full [m, n] distance matrix is never materialized
(``distance/detail/fused_l2_nn.cuh:284`` ``fusedL2NNImpl``; public API
``distance/fused_l2_nn.cuh``). That matters just as much on TPU — HBM
bandwidth is the bottleneck — but the idiomatic formulation is different:
tile the *centroid/candidate* axis with ``lax.scan``, compute each
[m, tile] distance block as an MXU matmul, and fold a running
``(min_val, argmin)`` carry. Peak memory is O(m * tile) and XLA fuses the
min-reduction into the matmul epilogue.

Also provides ``min_cluster_and_distance`` (the k-means EM inner step,
``cluster/detail/kmeans.cuh:435`` ``minClusterAndDistanceCompute``).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, resolve_metric, row_norms
from raft_tpu.utils.math import cdiv


@functools.partial(jax.jit, static_argnames=("tile", "sqrt"))
def _fused_l2_nn_impl(x, y, x_sqnorm, y_sqnorm, *, tile: int, sqrt: bool):
    m, d = x.shape
    n = y.shape[0]
    n_tiles = cdiv(n, tile)
    n_pad = n_tiles * tile - n

    yp = jnp.pad(y, ((0, n_pad), (0, 0))) if n_pad else y
    ynp = jnp.pad(y_sqnorm, (0, n_pad), constant_values=jnp.inf) if n_pad else y_sqnorm
    y_tiles = yp.reshape(n_tiles, tile, d)
    yn_tiles = ynp.reshape(n_tiles, tile)

    init = (
        jnp.full((m,), jnp.inf, jnp.float32),
        jnp.zeros((m,), jnp.int32),
    )

    def body(carry, inputs):
        best_val, best_idx = carry
        t, (yt, ynt) = inputs
        dot = lax.dot_general(
            x, yt, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        d2 = x_sqnorm[:, None] + ynt[None, :] - 2.0 * dot
        d2 = jnp.maximum(d2, 0.0)
        # Padded columns carry inf norms -> inf distance -> never selected.
        d2 = jnp.where(ynt[None, :] == jnp.inf, jnp.inf, d2)
        tile_val = jnp.min(d2, axis=1)
        tile_arg = jnp.argmin(d2, axis=1).astype(jnp.int32) + t * tile
        # Tie-break toward the lower index, matching the reference's
        # KVPMinReduce (core/kvp.hpp) which keeps the first-seen minimum.
        take_new = tile_val < best_val
        best_val = jnp.where(take_new, tile_val, best_val)
        best_idx = jnp.where(take_new, tile_arg, best_idx)
        return (best_val, best_idx), None

    (best_val, best_idx), _ = lax.scan(
        body, init, (jnp.arange(n_tiles), (y_tiles, yn_tiles))
    )
    if sqrt:
        best_val = jnp.sqrt(best_val)
    return best_val, best_idx


def fused_l2_nn(
    x,
    y,
    x_sqnorm: Optional[jax.Array] = None,
    y_sqnorm: Optional[jax.Array] = None,
    sqrt: bool = False,
    tile: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of ``x`` [m, d], the (distance, index) of its nearest row
    in ``y`` [n, d] under (squared) L2 — without materializing [m, n].

    Analog of ``fusedL2NNMinReduce`` (``distance/fused_l2_nn.cuh:163``).
    Returns ``(min_dist [m] f32, argmin [m] i32)``.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2, "fused_l2_nn expects 2-D inputs")
    expects(x.shape[1] == y.shape[1], "feature dims differ")
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    xn = row_norms(xf) if x_sqnorm is None else x_sqnorm.astype(jnp.float32)
    yn = row_norms(yf) if y_sqnorm is None else y_sqnorm.astype(jnp.float32)
    tile = int(min(tile, max(128, y.shape[0])))
    return _fused_l2_nn_impl(xf, yf, xn, yn, tile=tile, sqrt=sqrt)


@functools.partial(jax.jit, static_argnames=("tile",))
def _fused_ip_nn_impl(x, y, *, tile: int):
    """Max-inner-product 1-NN: same tiled scan as the L2 path but carrying a
    running (max dot, argmax)."""
    m, d = x.shape
    n = y.shape[0]
    n_tiles = cdiv(n, tile)
    n_pad = n_tiles * tile - n
    yp = jnp.pad(y, ((0, n_pad), (0, 0))) if n_pad else y
    valid = jnp.arange(n_tiles * tile) < n
    y_tiles = yp.reshape(n_tiles, tile, d)
    v_tiles = valid.reshape(n_tiles, tile)

    init = (jnp.full((m,), -jnp.inf, jnp.float32), jnp.zeros((m,), jnp.int32))

    def body(carry, inputs):
        best_val, best_idx = carry
        t, (yt, vt) = inputs
        dot = lax.dot_general(
            x, yt, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        dot = jnp.where(vt[None, :], dot, -jnp.inf)
        tile_val = jnp.max(dot, axis=1)
        tile_arg = jnp.argmax(dot, axis=1).astype(jnp.int32) + t * tile
        take_new = tile_val > best_val
        return (
            jnp.where(take_new, tile_val, best_val),
            jnp.where(take_new, tile_arg, best_idx),
        ), None

    (best_val, best_idx), _ = lax.scan(
        body, init, (jnp.arange(n_tiles), (y_tiles, v_tiles))
    )
    return best_val, best_idx


def min_cluster_and_distance(
    x,
    centroids,
    metric=DistanceType.L2Expanded,
    tile: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """Per-sample nearest centroid (labels) + distance — the k-means EM inner
    step (``cluster/detail/kmeans.cuh:435``).

    * L2 variants: the fused L2 scan directly.
    * Cosine: rows are L2-normalized first — nearest-cosine == nearest-L2 on
      the unit sphere (1 - cos = ||x̂-ŷ||²/2), as the balanced-kmeans
      reference does (``cluster/detail/kmeans_balanced.cuh:83``
      predict_core) — and the distance is rescaled to 1 - cos so it matches
      :func:`pairwise_distance`'s cosine values.
    * InnerProduct: true max-inner-product (no normalization; centroid
      magnitude matters); returned "distance" is the raw dot product.
    """
    metric = resolve_metric(metric)
    x = jnp.asarray(x, jnp.float32)
    c = jnp.asarray(centroids, jnp.float32)
    tile_c = int(min(tile, max(128, c.shape[0])))
    if metric == DistanceType.InnerProduct:
        dot, idx = _fused_ip_nn_impl(x, c, tile=tile_c)
        return idx, dot
    if metric == DistanceType.CosineExpanded:
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        cn = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)
        d2, idx = fused_l2_nn(xn, cn, tile=tile_c)
        return idx, 0.5 * d2  # ||x̂-ĉ||²/2 == 1 - cos
    sqrt = metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded)
    dist, idx = fused_l2_nn(x, c, sqrt=sqrt, tile=tile_c)
    return idx, dist
