"""Pairwise distance engine — TPU-native analog of ``raft::distance``.

The reference implements pairwise distances as a tiled GEMM-like CUDA kernel
with per-metric accumulate/epilogue functors
(``distance/detail/pairwise_distance_base.cuh:69``,
``distance/detail/distance_ops/*.cuh``), dispatched over
``DistanceType`` (``distance/distance_types.hpp:23-68``,
``distance/distance-inl.cuh:239``).

The TPU design splits metrics into two families instead of one kernel:

* **Expanded (GEMM) metrics** — L2Expanded, Cosine, InnerProduct,
  Correlation, Hellinger, Jaccard, Dice, RusselRao: one MXU matmul
  (``x @ y.T`` with dtype-appropriate accumulation) plus a cheap vectorized
  epilogue using precomputed row statistics. This is exactly where the FLOPs
  belong on TPU; XLA fuses the epilogue into the matmul output.
* **Accumulation metrics** — L1, L2Unexpanded, Linf, Canberra, Lp,
  Hamming, KLDivergence, JensenShannon, BrayCurtis: no matmul form exists,
  so they are computed by scanning feature chunks with a per-metric
  elementwise combine + reduce, keeping peak memory at
  ``m*n*chunk`` instead of ``m*n*d`` (the analog of the reference's
  register-tiled accumulation loop).

All functions are jit-compatible with static shapes; the metric is a static
argument (trace-time dispatch, mirroring the reference's compile-time functor
dispatch at ``distance/detail/pairwise_matrix/dispatch-inl.cuh:58``).
"""
from __future__ import annotations

import enum
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects


class DistanceType(enum.IntEnum):
    """Metric enum; values match the reference ``DistanceType``
    (``distance/distance_types.hpp:23-68``)."""

    L2Expanded = 0
    L2SqrtExpanded = 1
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7
    Canberra = 8
    LpUnexpanded = 9
    CorrelationExpanded = 10
    JaccardExpanded = 11
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19
    Precomputed = 100


# Aliases accepted by the string API (mirrors pylibraft's
# ``pairwise_distance(..., metric="euclidean")`` surface,
# ``pylibraft/distance/pairwise_distance.pyx``).
_METRIC_ALIASES = {
    "euclidean": DistanceType.L2SqrtExpanded,
    "l2": DistanceType.L2SqrtExpanded,
    "sqeuclidean": DistanceType.L2Expanded,
    "l2_expanded": DistanceType.L2Expanded,
    "l2_unexpanded": DistanceType.L2Unexpanded,
    "cosine": DistanceType.CosineExpanded,
    "inner_product": DistanceType.InnerProduct,
    "dot": DistanceType.InnerProduct,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "chebyshev": DistanceType.Linf,
    "linf": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "minkowski": DistanceType.LpUnexpanded,
    "lp": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "kldivergence": DistanceType.KLDivergence,
    "russellrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
}

_EXPANDED = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.CosineExpanded,
        DistanceType.InnerProduct,
        DistanceType.CorrelationExpanded,
        DistanceType.JaccardExpanded,
        DistanceType.HellingerExpanded,
        DistanceType.RusselRaoExpanded,
        DistanceType.DiceExpanded,
    }
)


def resolve_metric(metric) -> DistanceType:
    """Resolve a ``DistanceType``, int, or string alias to the enum."""
    if isinstance(metric, DistanceType):
        return metric
    if isinstance(metric, str):
        key = metric.lower()
        expects(key in _METRIC_ALIASES, "unknown metric name %s", metric)
        return _METRIC_ALIASES[key]
    return DistanceType(metric)


def is_min_close(metric) -> bool:
    """Whether smaller distance means more similar
    (``distance/distance_types.hpp:72-85``)."""
    return resolve_metric(metric) != DistanceType.InnerProduct


def _acc_dtype(dtype) -> jnp.dtype:
    """Accumulation dtype for a given input dtype: integers accumulate in
    int32 (MXU int8 path), everything else in float32."""
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.dtype(jnp.int32)
    return jnp.dtype(jnp.float32)


def _dot(x: jax.Array, y: jax.Array) -> jax.Array:
    """``x @ y.T`` with accumulation in f32/i32 (MXU-friendly: bf16 and int8
    inputs keep their narrow storage type through the matmul)."""
    out = lax.dot_general(
        x,
        y,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_acc_dtype(x.dtype),
    )
    return out.astype(jnp.float32)


def row_norms(x: jax.Array, squared: bool = True) -> jax.Array:
    """Squared (or plain) L2 row norms in f32 — the precomputed-norms input
    of the reference's expanded-form epilogues (``distance/detail/
    distance_ops/l2_exp.cuh``)."""
    xf = x.astype(jnp.float32) if not jnp.issubdtype(x.dtype, jnp.integer) else x.astype(jnp.int32)
    sq = jnp.sum((xf * xf).astype(jnp.float32), axis=-1)
    return sq if squared else jnp.sqrt(sq)


# ---------------------------------------------------------------------------
# Expanded (matmul) family
# ---------------------------------------------------------------------------


def _expanded_distance(
    x: jax.Array,
    y: jax.Array,
    metric: DistanceType,
    x_sqnorm: Optional[jax.Array] = None,
    y_sqnorm: Optional[jax.Array] = None,
) -> jax.Array:
    """Matmul + epilogue path. ``x``: [m, d], ``y``: [n, d] → [m, n] f32.

    ``x_sqnorm``/``y_sqnorm`` allow index types to pass precomputed squared
    norms (the reference passes them into the epilogue the same way,
    ``neighbors/detail/knn_brute_force.cuh:126-181``).
    """
    m, d = x.shape
    if metric == DistanceType.HellingerExpanded:
        # dist = sqrt(1 - sum_k sqrt(x_k * y_k)); computed as an MXU matmul
        # of elementwise square roots (distance_ops/hellinger.cuh).
        xs = jnp.sqrt(x.astype(jnp.float32))
        ys = jnp.sqrt(y.astype(jnp.float32))
        acc = _dot(xs, ys)
        inner = 1.0 - acc
        # rectify negatives introduced by rounding before the sqrt
        return jnp.sqrt(jnp.maximum(inner, 0.0))

    dot = _dot(x, y)

    if metric == DistanceType.InnerProduct:
        return dot

    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        xn = row_norms(x) if x_sqnorm is None else x_sqnorm.astype(jnp.float32)
        yn = row_norms(y) if y_sqnorm is None else y_sqnorm.astype(jnp.float32)
        d2 = xn[:, None] + yn[None, :] - 2.0 * dot
        d2 = jnp.maximum(d2, 0.0)  # clamp fp cancellation (l2_exp.cuh epilogue)
        return jnp.sqrt(d2) if metric == DistanceType.L2SqrtExpanded else d2

    if metric == DistanceType.CosineExpanded:
        xn = row_norms(x, squared=False) if x_sqnorm is None else jnp.sqrt(x_sqnorm.astype(jnp.float32))
        yn = row_norms(y, squared=False) if y_sqnorm is None else jnp.sqrt(y_sqnorm.astype(jnp.float32))
        denom = xn[:, None] * yn[None, :]
        sim = dot / jnp.where(denom == 0.0, 1.0, denom)
        return 1.0 - sim

    if metric == DistanceType.CorrelationExpanded:
        # 1 - (k*dot - sx*sy) / sqrt((k*x2 - sx^2)(k*y2 - sy^2))
        # (distance_ops/correlation.cuh epilogue)
        xf = x.astype(jnp.float32)
        yf = y.astype(jnp.float32)
        sx = jnp.sum(xf, axis=1)
        sy = jnp.sum(yf, axis=1)
        x2 = row_norms(x)
        y2 = row_norms(y)
        numer = d * dot - sx[:, None] * sy[None, :]
        q = d * x2 - sx * sx
        r = d * y2 - sy * sy
        denom = jnp.sqrt(jnp.maximum(q[:, None] * r[None, :], 0.0))
        return 1.0 - numer / jnp.where(denom == 0.0, 1.0, denom)

    if metric == DistanceType.JaccardExpanded:
        # 1 - dot / (|x| + |y| - dot) with 0/0 -> 0 guard
        # (sparse/distance/detail/bin_distance.cuh jaccard functor)
        sx = jnp.sum(x.astype(jnp.float32), axis=1)
        sy = jnp.sum(y.astype(jnp.float32), axis=1)
        union = sx[:, None] + sy[None, :] - dot
        sim = jnp.where(union == 0.0, 0.0, dot / jnp.where(union == 0.0, 1.0, union))
        return 1.0 - sim

    if metric == DistanceType.DiceExpanded:
        # 1 - 2*dot / (|x| + |y|) (bin_distance.cuh dice functor)
        sx = jnp.sum(x.astype(jnp.float32), axis=1)
        sy = jnp.sum(y.astype(jnp.float32), axis=1)
        denom = sx[:, None] + sy[None, :]
        sim = jnp.where(denom == 0.0, 0.0, 2.0 * dot / jnp.where(denom == 0.0, 1.0, denom))
        return 1.0 - sim

    if metric == DistanceType.RusselRaoExpanded:
        # (k - dot) / k (distance_ops/russel_rao.cuh)
        return (d - dot) / d

    raise AssertionError(f"not an expanded metric: {metric}")


# ---------------------------------------------------------------------------
# Accumulation family
# ---------------------------------------------------------------------------


def _accum_step(xc: jax.Array, yc: jax.Array, metric: DistanceType, p: float):
    """Per-feature-chunk contribution, [m, 1, dc] vs [1, n, dc] → [m, n].

    The elementwise combine bodies mirror the reference's per-metric
    ``core()`` functors (``distance/detail/distance_ops/*.cuh``).
    """
    xb = xc[:, None, :]
    yb = yc[None, :, :]
    if metric == DistanceType.L1:
        return jnp.sum(jnp.abs(xb - yb), axis=-1)
    if metric in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        diff = xb - yb
        return jnp.sum(diff * diff, axis=-1)
    if metric == DistanceType.Linf:
        return jnp.max(jnp.abs(xb - yb), axis=-1)
    if metric == DistanceType.Canberra:
        diff = jnp.abs(xb - yb)
        add = jnp.abs(xb) + jnp.abs(yb)
        # 0/0 -> 0 (distance_ops/canberra.cuh)
        return jnp.sum(jnp.where(add == 0.0, 0.0, diff / jnp.where(add == 0.0, 1.0, add)), axis=-1)
    if metric == DistanceType.LpUnexpanded:
        return jnp.sum(jnp.abs(xb - yb) ** p, axis=-1)
    if metric == DistanceType.BrayCurtis:
        # sum |x-y| and sum |x+y| accumulated together; packed as complex
        # would be cute but two stacked channels are clearer.
        num = jnp.sum(jnp.abs(xb - yb), axis=-1)
        den = jnp.sum(jnp.abs(xb + yb), axis=-1)
        return jnp.stack([num, den], axis=0)
    if metric == DistanceType.HammingUnexpanded:
        return jnp.sum((xb != yb).astype(jnp.float32), axis=-1)
    if metric == DistanceType.KLDivergence:
        return jnp.sum(kl_term(xb, yb), axis=-1)
    if metric == DistanceType.JensenShannon:
        return jnp.sum(js_term(xb, yb), axis=-1)
    raise AssertionError(f"not an accumulation metric: {metric}")


def _accum_combine(acc, contrib, metric: DistanceType):
    if metric == DistanceType.Linf:
        return jnp.maximum(acc, contrib)
    return acc + contrib


def _accum_finalize(acc, metric: DistanceType, p: float, d: int):
    if metric == DistanceType.L2SqrtUnexpanded:
        return jnp.sqrt(acc)
    if metric == DistanceType.LpUnexpanded:
        return acc ** (1.0 / p)
    if metric == DistanceType.HammingUnexpanded:
        return acc / d
    if metric == DistanceType.JensenShannon:
        return jnp.sqrt(jnp.maximum(0.5 * acc, 0.0))
    if metric == DistanceType.BrayCurtis:
        num, den = acc[0], acc[1]
        return jnp.where(den == 0.0, 0.0, num / jnp.where(den == 0.0, 1.0, den))
    return acc


def _accum_distance(x: jax.Array, y: jax.Array, metric: DistanceType, p: float) -> jax.Array:
    """Feature-chunked accumulation engine for non-GEMM metrics.

    Scans ``d`` in chunks so peak temp memory is ``m*n*chunk`` (the analog of
    the reference's k-tiled accumulation in
    ``pairwise_distance_base.cuh:127``). Chunk size is chosen at trace time
    from static shapes.
    """
    m, d = x.shape
    n = y.shape[0]
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)

    # Keep the m*n*chunk broadcast temp under ~256 MiB of f32.
    budget_elems = (256 << 20) // 4
    chunk = max(1, min(d, budget_elems // max(1, m * n)))
    n_chunks = -(-d // chunk)
    if n_chunks <= 1:
        acc = _accum_step(xf, yf, metric, p)
        return _accum_finalize(acc, metric, p, d)

    pad = n_chunks * chunk - d
    if pad:
        # Pad features with zeros; for every accumulation metric a (0, 0)
        # feature pair contributes the identity (0 for sums, 0 for max).
        xf = jnp.pad(xf, ((0, 0), (0, pad)))
        yf = jnp.pad(yf, ((0, 0), (0, pad)))
    xcs = xf.reshape(m, n_chunks, chunk).transpose(1, 0, 2)
    ycs = yf.reshape(n, n_chunks, chunk).transpose(1, 0, 2)

    acc_shape = (2, m, n) if metric == DistanceType.BrayCurtis else (m, n)
    init = jnp.zeros(acc_shape, jnp.float32)

    def body(acc, chunks):
        xc, yc = chunks
        return _accum_combine(acc, _accum_step(xc, yc, metric, p), metric), None

    acc, _ = lax.scan(body, init, (xcs, ycs))
    return _accum_finalize(acc, metric, p, d)


def kl_term(a, b) -> jax.Array:
    """Elementwise ``a * (log a - log b)``, zero-guarded exactly as the
    reference's functor (``distance_ops/kl_divergence.cuh``): a==0 terms
    vanish, b==0 drops the log-b contribution. Shared by the dense
    accumulation engine and the sparse union path — keep the guards in
    exactly one place."""
    la = jnp.log(jnp.where(a == 0.0, 1.0, a))
    lb = jnp.where(b == 0.0, 0.0, jnp.log(jnp.where(b == 0.0, 1.0, b)))
    return a * (la - lb)


def js_term(a, b) -> jax.Array:
    """Elementwise Jensen-Shannon contribution ``-a*(log m - log a) -
    b*(log m - log b)`` with ``m = (a+b)/2``, zero-guarded
    (``distance_ops/jensen_shannon.cuh``). Finalize with
    ``sqrt(max(0.5 * sum, 0))``. Shared like :func:`kl_term`."""
    m = 0.5 * (a + b)
    lm = jnp.where(m == 0.0, 0.0, jnp.log(jnp.where(m == 0.0, 1.0, m)))
    la = jnp.log(jnp.where(a == 0.0, 1.0, a))
    lb = jnp.log(jnp.where(b == 0.0, 1.0, b))
    return -a * (lm - la) - b * (lm - lb)


def haversine_core(lat1, lon1, lat2, lon2) -> jax.Array:
    """Great-circle distance from broadcast-compatible (lat, lon in
    radians) components (``spatial/knn/detail/haversine_distance.cuh``).
    Shared by the pairwise engine and the ball-cover gathered path — keep
    the formula in exactly one place."""
    sin_0 = jnp.sin(0.5 * (lat1 - lat2))
    sin_1 = jnp.sin(0.5 * (lon1 - lon2))
    rdist = sin_0 * sin_0 + jnp.cos(lat1) * jnp.cos(lat2) * sin_1 * sin_1
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(rdist, 0.0, 1.0)))


def _haversine(x: jax.Array, y: jax.Array) -> jax.Array:
    """[m, n] pairwise haversine."""
    return haversine_core(x[:, 0:1], x[:, 1:2], y[None, :, 0], y[None, :, 1])


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("metric", "p"))
def _pairwise_impl(x, y, x_sqnorm, y_sqnorm, *, metric: DistanceType, p: float):
    if metric == DistanceType.Haversine:
        return _haversine(x.astype(jnp.float32), y.astype(jnp.float32))
    if metric in _EXPANDED:
        return _expanded_distance(x, y, metric, x_sqnorm, y_sqnorm)
    return _accum_distance(x, y, metric, p)


def pairwise_distance(
    x,
    y,
    metric=DistanceType.L2SqrtExpanded,
    metric_arg: float = 2.0,
    x_sqnorm: Optional[jax.Array] = None,
    y_sqnorm: Optional[jax.Array] = None,
) -> jax.Array:
    """Compute the full [m, n] pairwise distance matrix.

    Analog of ``raft::distance::pairwise_distance``
    (``distance/distance-inl.cuh:239``). ``metric`` may be a
    :class:`DistanceType`, its integer value, or a string alias
    ("euclidean", "cosine", ...). ``metric_arg`` is the Minkowski ``p``.
    """
    metric = resolve_metric(metric)
    expects(metric != DistanceType.Precomputed, "Precomputed is not a computable metric")
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2, "pairwise_distance expects 2-D inputs")
    expects(x.shape[1] == y.shape[1], "feature dims differ: %d vs %d", x.shape[1], y.shape[1])
    if metric == DistanceType.Haversine:
        expects(x.shape[1] == 2, "Haversine requires 2-D (lat, lon) points")
    return _pairwise_impl(x, y, x_sqnorm, y_sqnorm, metric=metric, p=float(metric_arg))
