"""Batched top-k selection — TPU-native analog of ``raft::matrix::select_k``.

The reference picks between a multi-pass radix kernel and warp-level bitonic
sorting networks via a shape heuristic
(``matrix/select_k.cuh:84``, ``matrix/detail/select_k-inl.cuh:47``
``choose_select_k_algorithm``; ``detail/select_radix.cuh``,
``detail/select_warpsort.cuh``). On TPU both specializations collapse into
XLA's ``lax.top_k`` (a sort-based lowering the compiler tiles onto the VPU);
what remains worth building natively is the *composition* machinery the
search paths need:

* min/max selection with an optional payload-index gather,
* ``merge_parts`` — the k-way merge of per-tile top-k results
  (``neighbors/detail/knn_merge_parts.cuh``), used by tiled brute force,
  sharded multi-chip search, and IVF probing,
* a running (streaming) merge used inside ``lax.scan`` loops,
* :func:`approx_select_k` — the TPU's second selection algorithm:
  ``lax.approx_max_k``'s PartialReduce op, which XLA **fuses into the
  producing matmul** so the [batch, n] score matrix is never materialized
  in HBM. This is the analog of the reference's radix/warpsort algorithm
  choice (``select_k-inl.cuh:42-78``): exact sort-based ``top_k`` when
  exactness is required, fused approximate selection (with a recall
  target) on the ANN hot paths where a recall threshold is the contract
  anyway. Measured on 1M×128 brute-force kNN, the fused path is ~100×
  faster than materialize-then-top_k.

All shapes static; jit-safe.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects


def select_k(
    values,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) entries per row.

    Parameters mirror ``matrix::select_k`` (``matrix/select_k.cuh:84``):
    ``values`` is [batch, n]; optional ``indices`` [batch, n] carries source
    ids (when absent, positional indices are returned).

    Returns ``(out_values [batch, k], out_indices [batch, k])`` sorted by
    rank (best first), matching the reference's ``sorted=true`` mode.
    """
    values = jnp.asarray(values)
    expects(values.ndim == 2, "select_k expects [batch, n] values, got ndim=%d", values.ndim)
    n = values.shape[1]
    expects(0 < k <= n, "k=%d out of range for n=%d columns", k, n)
    if select_min:
        vals, idx = lax.top_k(-values, k)
        vals = -vals
    else:
        vals, idx = lax.top_k(values, k)
    if indices is not None:
        idx = jnp.take_along_axis(jnp.asarray(indices), idx, axis=1)
    return vals, idx


def approx_select_k(
    values,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate per-row top-k via TPU PartialReduce
    (``lax.approx_min_k``/``approx_max_k``).

    Same contract as :func:`select_k` but each true top-k element is
    returned only with probability ``recall_target``; in exchange XLA
    fuses the selection into the producing matmul, never materializing
    ``values`` when it is a fusion temporary. Results are sorted
    best-first (``aggregate_to_topk=True``).
    """
    values = jnp.asarray(values)
    expects(values.ndim == 2, "approx_select_k expects [batch, n] values")
    n = values.shape[1]
    expects(0 < k <= n, "k=%d out of range for n=%d columns", k, n)
    if select_min:
        vals, idx = lax.approx_min_k(values, k, recall_target=recall_target)
    else:
        vals, idx = lax.approx_max_k(values, k, recall_target=recall_target)
    if indices is not None:
        idx = jnp.take_along_axis(jnp.asarray(indices), idx, axis=1)
    return vals, idx


def merge_parts(
    part_values: jax.Array,
    part_indices: jax.Array,
    k: int,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part top-k lists into a single top-k.

    Analog of ``knn_merge_parts`` (``neighbors/detail/knn_merge_parts.cuh``):
    inputs are [batch, n_parts * k_part] (concatenated per-part results, each
    already carrying *global* indices). A single re-selection over the short
    concatenated axis is optimal here — the merge width is tiny compared to
    the original n.
    """
    expects(
        part_values.shape == part_indices.shape,
        "merge_parts values/indices shape mismatch",
    )
    return select_k(part_values, k, select_min=select_min, indices=part_indices)


def running_merge(
    acc_values: jax.Array,
    acc_indices: jax.Array,
    new_values: jax.Array,
    new_indices: jax.Array,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Streaming top-k: merge a running [batch, k] result with a fresh
    [batch, t] candidate tile. Used as the scan carry in tiled brute-force
    search (the reference instead re-runs select_k over a temp buffer of
    tile results, ``knn_brute_force.cuh:222-246``)."""
    k = acc_values.shape[1]
    vals = jnp.concatenate([acc_values, new_values], axis=1)
    idx = jnp.concatenate([acc_indices, new_indices], axis=1)
    return select_k(vals, k, select_min=select_min, indices=idx)


def running_merge_unique(
    acc_values: jax.Array,
    acc_indices: jax.Array,
    new_values: jax.Array,
    new_indices: jax.Array,
    select_min: bool = True,
    acc_flags: Optional[jax.Array] = None,
    new_flags: Optional[jax.Array] = None,
):
    """:func:`running_merge` with per-row id deduplication.

    Graph-based searches (NN-descent local joins, CAGRA beam search) can
    propose the same candidate id through several paths; a plain merge would
    let one id occupy multiple top-k slots. Duplicates (same non-negative id
    within a row) are invalidated before selection — the analog of the CUDA
    visited-hashmap dedup (``detail/cagra/hashmap.hpp``), done as a sort +
    adjacent-compare, which is the TPU-shaped substitute for random-access
    hash probing. Assumes equal ids carry equal values (true when values are
    deterministic distances). Negative ids are treated as invalid padding.

    When ``acc_flags`` is given, a boolean flag lane (e.g. CAGRA's
    "visited", GNND's "already sampled") rides along through the merge; on a
    duplicate id the flagged (True) copy wins, and the return value gains a
    third element. Requires ids < 2^30 (int32 composite sort key).
    """
    k = acc_values.shape[1]
    vals = jnp.concatenate([acc_values, new_values], axis=1)
    ids = jnp.concatenate([acc_indices, new_indices], axis=1)
    worst = jnp.asarray(worst_value(vals.dtype, select_min), vals.dtype)
    vals = jnp.where(ids < 0, worst, vals)
    with_flags = acc_flags is not None
    if with_flags:
        if new_flags is None:
            new_flags = jnp.zeros(new_indices.shape, bool)
        flg = jnp.concatenate([acc_flags, new_flags], axis=1)
        # sort by (id, flagged-first) so the flagged copy survives dedup
        composite = ids * 2 + (1 - flg.astype(jnp.int32))
        composite = jnp.where(ids < 0, jnp.iinfo(jnp.int32).max, composite)
        order = jnp.argsort(composite, axis=1, stable=True)
        flg_s = jnp.take_along_axis(flg, order, axis=1)
    else:
        order = jnp.argsort(ids, axis=1)
    ids_s = jnp.take_along_axis(ids, order, axis=1)
    vals_s = jnp.take_along_axis(vals, order, axis=1)
    prev = jnp.concatenate([jnp.full_like(ids_s[:, :1], -2), ids_s[:, :-1]], axis=1)
    dup = (ids_s == prev) & (ids_s >= 0)
    vals_s = jnp.where(dup, worst, vals_s)
    out_v, pos = select_k(vals_s, k, select_min=select_min)
    out_i = jnp.take_along_axis(ids_s, pos, axis=1)
    # Slots that selected a sentinel (all-invalid row tails) report id -1.
    out_i = jnp.where(out_v == worst, -1, out_i)
    if with_flags:
        return out_v, out_i, jnp.take_along_axis(flg_s, pos, axis=1)
    return out_v, out_i


def worst_value(dtype, select_min: bool = True):
    """Sentinel used to pad candidate buffers (the reference uses
    ``upper_bound``/``lower_bound`` limits, ``select_warpsort.cuh``)."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        info = jnp.iinfo(dtype)
        return info.max if select_min else info.min
    return jnp.inf if select_min else -jnp.inf
