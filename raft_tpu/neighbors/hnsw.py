"""HNSW interop — analog of ``raft::neighbors::hnsw``
(``neighbors/hnsw.hpp:62`` ``from_cagra``, serializer
``neighbors/detail/cagra/cagra_serialize.cuh`` ``serialize_to_hnswlib``).

Writes a CAGRA index as a base-layer-only hnswlib file (bit-compatible
with the reference's writer, which hnswlib's ``loadIndex`` accepts with
``max_level=1`` and all points on level 0), and provides a CPU-light
reader + search so round-trips work without the hnswlib package.
"""
from __future__ import annotations

import dataclasses
import struct
from typing import BinaryIO, Tuple, Union

import numpy as np

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.neighbors.cagra import CagraIndex, CagraSearchParams, from_graph, search as cagra_search
from raft_tpu.ops.distance import DistanceType


def serialize_to_hnswlib(index: CagraIndex, stream: BinaryIO) -> None:
    """Write the hnswlib ``HierarchicalNSW`` file layout
    (``cagra_serialize.cuh`` serialize_to_hnswlib — same field order and
    widths: size_t header fields, per-element
    [link_count:int][links:IdxT*deg][data:T*dim][label:size_t], then one
    int 0 per element for the upper link lists)."""
    dataset = np.ascontiguousarray(np.asarray(index.dataset))
    graph = np.ascontiguousarray(np.asarray(index.graph, np.uint32))
    n, dim = dataset.shape
    deg = graph.shape[1]
    itemsize = dataset.dtype.itemsize

    size_data_per_element = deg * 4 + 4 + dim * itemsize + 8
    header = struct.pack(
        "<QQQQQQiiQQQdQ",
        0,  # offset_level_0
        n,  # max_element
        n,  # curr_element_count
        size_data_per_element,
        size_data_per_element - 8,  # label_offset
        deg * 4 + 4,  # offset_data
        1,  # max_level
        n // 2,  # entrypoint_node
        deg // 2,  # max_M
        deg,  # max_M0
        deg // 2,  # M
        0.42424242,  # mult (unused by the loader)
        500,  # efConstruction (unused)
    )
    stream.write(header)

    # vectorized per-element records via a structured dtype
    rec = np.dtype(
        [
            ("cnt", "<i4"),
            ("links", "<u4", (deg,)),
            ("data", dataset.dtype, (dim,)),
            ("label", "<u8"),
        ]
    )
    out = np.empty(n, rec)
    out["cnt"] = deg
    # -1 pads are not representable in hnswlib links; point them at self
    links = graph.astype(np.int64)
    rows = np.arange(n, dtype=np.int64)[:, None]
    links = np.where(np.asarray(index.graph) < 0, rows, links)
    out["links"] = links.astype(np.uint32)
    out["data"] = dataset
    out["label"] = np.arange(n, dtype=np.uint64)
    stream.write(out.tobytes())
    stream.write(np.zeros(n, "<i4").tobytes())


@dataclasses.dataclass
class HnswIndex:
    """Loaded base-layer hnsw graph (``hnsw::index`` analog,
    ``neighbors/detail/hnsw_types.hpp``)."""

    dataset: np.ndarray
    graph: np.ndarray
    entrypoint: int
    metric: DistanceType

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    def to_cagra(self) -> CagraIndex:
        return from_graph(self.dataset, self.graph, self.metric)


def from_cagra(index: CagraIndex) -> HnswIndex:
    """``hnsw::from_cagra`` (``neighbors/hnsw.hpp:62``): view the CAGRA
    graph as a base-layer hnsw index."""
    return HnswIndex(
        dataset=np.asarray(index.dataset),
        graph=np.asarray(index.graph),
        entrypoint=index.size // 2,
        metric=index.metric,
    )


def load_hnswlib(stream: BinaryIO, dtype=np.float32, metric=DistanceType.L2Expanded) -> HnswIndex:
    """Parse an hnswlib file written by :func:`serialize_to_hnswlib`
    (reader counterpart of ``hnsw_types.hpp``'s hnswlib loadIndex)."""
    head = stream.read(8 * 6)
    _, n, count, size_per, label_off, offset_data = struct.unpack("<QQQQQQ", head)
    max_level, entry = struct.unpack("<ii", stream.read(8))
    _max_m, max_m0, _m = struct.unpack("<QQQ", stream.read(24))
    _mult, _efc = struct.unpack("<dQ", stream.read(16))
    expects(max_level == 1, "only base-layer-only files supported")
    deg = (offset_data - 4) // 4
    itemsize = np.dtype(dtype).itemsize
    dim = (label_off - offset_data) // itemsize
    rec = np.dtype(
        [
            ("cnt", "<i4"),
            ("links", "<u4", (deg,)),
            ("data", np.dtype(dtype).newbyteorder("<"), (dim,)),
            ("label", "<u8"),
        ]
    )
    expects(rec.itemsize == size_per, "record size mismatch: corrupt file?")
    raw = np.frombuffer(stream.read(size_per * count), rec, count=count)
    # order rows by label (our writer emits them in order already)
    order = np.argsort(raw["label"])
    graph = raw["links"][order].astype(np.int32)
    data = np.ascontiguousarray(raw["data"][order])
    return HnswIndex(dataset=data, graph=graph, entrypoint=int(entry), metric=metric)


def search(
    index: HnswIndex, queries, k: int, ef: int = 64
) -> Tuple[np.ndarray, np.ndarray]:
    """Base-layer greedy search (the reference delegates to hnswlib's CPU
    searchKnn; here the same graph runs through the batched beam search —
    ``ef`` maps to ``itopk_size``).

    With :mod:`raft_tpu.obs` enabled the call is wrapped in an
    ``hnsw.search`` span (the nested ``cagra.search`` span shows the
    delegated traversal) with call/query counters."""
    if not obs.is_enabled():
        v, i = cagra_search(
            index.to_cagra(), queries, k, CagraSearchParams(itopk_size=max(ef, k))
        )
        return np.asarray(v), np.asarray(i)
    nq = int(np.shape(queries)[0]) if np.ndim(queries) == 2 else 1
    obs.inc("hnsw.search.calls", ef=str(ef))
    obs.inc("hnsw.search.queries", float(nq))
    with obs.span("hnsw.search", k=k, nq=nq, ef=ef) as sp:
        v, i = cagra_search(
            index.to_cagra(), queries, k, CagraSearchParams(itopk_size=max(ef, k))
        )
        sp.sync((v, i))
    return np.asarray(v), np.asarray(i)
