"""Shared IVF list machinery — analog of the reference's shared per-list
storage helpers (``neighbors/ivf_list.hpp``, ``ivf_list_types.hpp``,
``neighbors/ivf_flat_codepacker.hpp``), used by both IVF-Flat and IVF-PQ.

TPU-first layout: every list lives in ONE dense padded tensor
``[n_lists, max_list, ...]`` (the CUDA 32-row interleave dissolves into
sublane-padded dense tiles XLA can feed the MXU directly). The pieces here
solve the two problems that layout creates:

* **Capacity-capped assignment** (:func:`assign_slots`): one crowded
  cluster must not inflate ``max_list`` (and with it every list's stride).
  Rows overflowing their nearest list spill to their second-nearest and,
  in the rare case that is also full, to any free slot — bounding padding
  waste at ``cap_factor``× the mean list size.
* **On-device packing** (:func:`scatter_rows`): packing is sorts +
  scatters on the accelerator; the only host sync is one scalar (the
  ``max_list`` shape decision). Round 2 packed on host, which cost
  minutes of dataset transfer per build on tethered-TPU links.
"""
from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.ops.distance import DistanceType
from raft_tpu.utils.math import round_up


def coarse_scores(centers, qf, metric) -> jax.Array:
    """[nq, n_lists] coarse scores, smaller = better — the shared
    ``select_clusters`` ranking (``ivf_pq_search.cuh:67``) used by the
    probe mask, the fused Pallas path, and IVF-PQ. For cosine, ``qf`` must
    already be unit-normalized (centers trained on normalized data)."""
    q_dot_c = qf @ centers.T
    if metric == DistanceType.InnerProduct:
        return -q_dot_c
    c_norm = jnp.sum(centers * centers, axis=1)
    return c_norm[None, :] - 2.0 * q_dot_c


def probe_selection(centers, qf, n_probes: int, metric) -> Tuple[jax.Array, jax.Array]:
    """``(coarse [nq, n_lists], probed [nq, n_lists] bool)`` — the shared
    coarse ranking plus the per-query probe mask (``select_clusters``,
    ``ivf_flat_search-inl.cuh:145``). Single home for probe selection so
    the scan, probe, and fused paths cannot diverge."""
    from raft_tpu.ops.select_k import select_k

    nq = qf.shape[0]
    n_lists = centers.shape[0]
    coarse = coarse_scores(centers, qf, metric)
    if n_probes < n_lists:
        _, probes = select_k(coarse, n_probes, select_min=True)
        probed = jnp.zeros((nq, n_lists), bool).at[
            jnp.arange(nq)[:, None], probes
        ].set(True)
    else:
        probed = jnp.ones((nq, n_lists), bool)
    return coarse, probed


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_block(xb, centers, cn, *, k: int):
    score = 2.0 * (xb @ centers.T) - cn[None, :]  # max == nearest
    _, idx = lax.top_k(score, k)
    return idx.astype(jnp.int32)


def topk_labels(ds_f32: jax.Array, centers: jax.Array, k: int = 4, block: int = 131072):
    """Per-row k nearest center ids ``[n, k]`` — rankwise L2 via the norm
    trick, blocked so [block, n_lists] is the peak temporary."""
    n = ds_f32.shape[0]
    k = min(k, centers.shape[0])
    cn = jnp.sum(centers * centers, axis=1)
    outs = [_topk_block(ds_f32[s : s + block], centers, cn, k=k) for s in range(0, n, block)]
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


@functools.partial(jax.jit, static_argnames=("n_lists", "max_list"))
def assign_slots(cand_labels, *, n_lists: int, max_list: int) -> jax.Array:
    """Flat destination slot per row in the padded layout (list-major).

    ``cand_labels [n, c]`` ranks each row's candidate lists nearest-first.
    One pass per candidate column (nearest list while it has room, then the
    next candidate, ...), then a final pass dropping stragglers into any
    free slot. All static-shape sorts/scatters. Returns ``slot [n] int32``
    with every row placed (requires ``n <= n_lists * max_list``); the final
    list of a row is ``slot // max_list``.
    """
    n, n_cand = cand_labels.shape
    big = jnp.int32(n_lists)
    total = n_lists * max_list

    def rank_within(labels, active):
        """Stable rank of each active row within its label group."""
        key = jnp.where(active, labels, big)
        order = jnp.argsort(key)
        sl = key[order]
        first = jnp.concatenate([jnp.ones((1,), bool), sl[1:] != sl[:-1]])
        group_start = jnp.where(first, jnp.arange(n), 0)
        group_start = lax.associative_scan(jnp.maximum, group_start)
        rank_sorted = jnp.arange(n) - group_start
        return jnp.zeros((n,), jnp.int32).at[order].set(rank_sorted.astype(jnp.int32))

    slot = jnp.full((n,), total, jnp.int32)
    placed = jnp.zeros((n,), bool)
    used = jnp.zeros((n_lists,), jnp.int32)
    for c in range(n_cand):
        lc = cand_labels[:, c]
        rank = rank_within(lc, ~placed)
        fits = (~placed) & (used[lc] + rank < max_list)
        slot = jnp.where(fits, lc * max_list + used[lc] + rank, slot)
        used = used.at[jnp.where(fits, lc, n_lists)].add(1, mode="drop")
        placed = placed | fits

    # final pass: leftovers into any free slot (argsort puts free first)
    filled = (jnp.zeros((total + 1,), jnp.int32).at[slot].set(1, mode="drop"))[:total]
    free_slots = jnp.argsort(filled).astype(jnp.int32)
    rank3 = rank_within(jnp.zeros((n,), jnp.int32), ~placed)
    slot = jnp.where(~placed, free_slots[jnp.clip(rank3, 0, total - 1)], slot)
    return slot


def choose_max_list(l1, n: int, n_lists: int, cap_factor: float) -> int:
    """Pick the static ``max_list`` (ONE scalar device→host fetch)."""
    counts = jnp.zeros((n_lists,), jnp.int32).at[l1].add(1)
    max_count = int(jnp.max(counts))
    cap = max_count
    if cap_factor > 0:
        cap = min(cap, int(math.ceil(cap_factor * n / n_lists)))
    cap = max(cap, int(math.ceil(n / n_lists)))  # capacity for every row
    if cap >= 512:
        # Lane-align big lists: the fused Pallas scan compresses scores in
        # 128-lane groups, and a non-multiple max_list forces a full
        # score-matrix pad copy EVERY probe step (measured ~25% of step
        # time on v5e). +>=6% padding rows is cheap next to that.
        return round_up(cap, 128)
    return max(8, round_up(cap, 8))


def pack_rows(
    rows, ids, cand_labels, n_lists: int, cap_factor: float
) -> Tuple[jax.Array, jax.Array, jax.Array, int]:
    """assign_slots + scatter_rows with the max_list decision in between."""
    max_list = choose_max_list(cand_labels[:, 0], rows.shape[0], n_lists, cap_factor)
    slot = assign_slots(cand_labels, n_lists=n_lists, max_list=max_list)
    data, idx, sizes = scatter_rows(rows, ids, slot, n_lists=n_lists, max_list=max_list)
    return data, idx, sizes, max_list


@functools.partial(jax.jit, static_argnames=("n_lists", "max_list"))
def scatter_rows(rows, ids, slot, *, n_lists: int, max_list: int):
    """Scatter per-row payloads + ids into the padded layout. Returns
    ``(data [n_lists, max_list, d], indices [n_lists, max_list],
    sizes [n_lists])``."""
    d = rows.shape[1]
    total = n_lists * max_list
    flat_data = (jnp.zeros((total + 1, d), rows.dtype).at[slot].set(rows, mode="drop"))[:total]
    flat_ids = (jnp.full((total + 1,), -1, jnp.int32).at[slot].set(ids, mode="drop"))[:total]
    flat_ids = flat_ids.reshape(n_lists, max_list)
    sizes = jnp.sum((flat_ids >= 0).astype(jnp.int32), axis=1)
    return flat_data.reshape(n_lists, max_list, d), flat_ids, sizes
