"""Brute-force (exact) kNN index — analog of ``raft::neighbors::brute_force``.

The reference implements exact search as tiled pairwise distance + select_k
with a k>tile merge path (``neighbors/detail/knn_brute_force.cuh:60``
``tiled_brute_force_knn``, ``:326`` ``brute_force_knn_impl``) behind a
persistent index type holding the dataset and its precomputed norms
(``neighbors/brute_force_types.hpp:49``).

TPU design: the index is a pytree (dataset + f32 squared norms + static
metric), search is a single jitted function that ``lax.scan``s over dataset
tiles computing each [query_batch, tile] distance block on the MXU and
folding a running top-k carry (see :func:`raft_tpu.ops.select_k.running_merge`)
— so peak memory is O(batch * tile), never O(batch * n). Queries are batched
on the host like the reference's query iterator
(``knn_brute_force.cuh:440-480``). Prefiltering consumes
:class:`raft_tpu.core.Bitset` (``sample_filter_types.hpp:27`` analog).
"""
from __future__ import annotations

import dataclasses
import functools
import io
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    _EXPANDED,
    _accum_step,
    _expanded_distance,
    is_min_close,
    resolve_metric,
    row_norms,
)
from raft_tpu.ops.select_k import approx_select_k, running_merge, select_k, worst_value
from raft_tpu.utils.math import cdiv

_NORM_METRICS = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.CosineExpanded,
    }
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BruteForceIndex:
    """Persistent exact-kNN index (``brute_force_types.hpp:49`` analog)."""

    dataset: jax.Array  # [n_rows, dim]
    norms: Optional[jax.Array]  # [n_rows] f32 squared L2 norms, or None
    metric: DistanceType
    metric_arg: float

    def tree_flatten(self):
        return (self.dataset, self.norms), (self.metric, self.metric_arg)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(dataset=children[0], norms=children[1], metric=aux[0], metric_arg=aux[1])

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


def build(
    dataset,
    metric=DistanceType.L2SqrtExpanded,
    metric_arg: float = 2.0,
    res: Optional[Resources] = None,
) -> BruteForceIndex:
    """Build the index: store the dataset and precompute squared row norms
    for expanded metrics (``brute_force_knn_impl``'s norm precompute,
    ``knn_brute_force.cuh:352-370``)."""
    ensure_resources(res)
    metric = resolve_metric(metric)
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    norms = row_norms(dataset) if metric in _NORM_METRICS else None
    return BruteForceIndex(dataset=dataset, norms=norms, metric=metric, metric_arg=float(metric_arg))


def _tile_distances(q, q_sqnorm, y_tile, yn_tile, metric: DistanceType, p: float):
    """One [batch, tile] distance block. Expanded metrics ride the MXU with
    precomputed norms; accumulation metrics broadcast directly (the tile is
    small so m*tile*d temp is bounded by the tile size choice)."""
    if metric in _EXPANDED:
        return _expanded_distance(q, y_tile, metric, q_sqnorm, yn_tile)
    from raft_tpu.ops.distance import _accum_combine, _accum_finalize  # local: keep import surface small

    qf = q.astype(jnp.float32)
    yf = y_tile.astype(jnp.float32)
    acc = _accum_step(qf, yf, metric, p)
    return _accum_finalize(acc, metric, p, q.shape[1])


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "p", "tile", "select_min", "has_filter")
)
def _search_impl(
    dataset,
    norms,
    queries,
    filter_mask,
    *,
    k: int,
    metric: DistanceType,
    p: float,
    tile: int,
    select_min: bool,
    has_filter: bool,
):
    n, d = dataset.shape
    qb = queries.shape[0]
    n_tiles = cdiv(n, tile)
    pad = n_tiles * tile - n

    ds = jnp.pad(dataset, ((0, pad), (0, 0))) if pad else dataset
    ds_tiles = ds.reshape(n_tiles, tile, d)
    if norms is not None:
        nm = jnp.pad(norms, (0, pad)) if pad else norms
        nm_tiles = nm.reshape(n_tiles, tile)
    else:
        nm_tiles = jnp.zeros((n_tiles, tile), jnp.float32)
    if has_filter:
        fm = jnp.pad(filter_mask, (0, pad)) if pad else filter_mask
        fm_tiles = fm.reshape(n_tiles, tile)
    else:
        fm_tiles = jnp.ones((n_tiles, tile), bool)

    q_sqnorm = row_norms(queries) if metric in _NORM_METRICS else None
    worst = jnp.float32(worst_value(jnp.float32, select_min))

    init = (
        jnp.full((qb, k), worst, jnp.float32),
        jnp.full((qb, k), -1, jnp.int32),
    )

    def body(carry, inputs):
        acc_v, acc_i = carry
        t, yt, ynt, fmt = inputs
        dist = _tile_distances(queries, q_sqnorm, yt, ynt, metric, p).astype(jnp.float32)
        ids = t * tile + jnp.arange(tile, dtype=jnp.int32)
        valid = (ids < n) & fmt
        dist = jnp.where(valid[None, :], dist, worst)
        tile_ids = jnp.broadcast_to(ids[None, :], dist.shape)
        acc_v, acc_i = running_merge(acc_v, acc_i, dist, tile_ids, select_min=select_min)
        return (acc_v, acc_i), None

    (vals, idx), _ = lax.scan(
        body, init, (jnp.arange(n_tiles, dtype=jnp.int32), ds_tiles, nm_tiles, fm_tiles)
    )
    # Rows knocked out by the filter keep id -1 and the worst sentinel,
    # matching the reference's behavior of returning invalid ids when fewer
    # than k candidates pass the filter.
    return vals, idx


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "select_min", "has_filter", "recall_target"),
)
def _search_approx_impl(
    dataset,
    norms,
    queries_blocked,  # [n_blocks, block, d]
    filter_mask,
    *,
    k: int,
    metric: DistanceType,
    select_min: bool,
    has_filter: bool,
    recall_target: float,
):
    """Fused-scan fast path: per query block, one MXU matmul over the FULL
    dataset with the distance epilogue fused into an approximate top-k
    (PartialReduce). XLA never materializes the [block, n] distance matrix,
    so this runs at the matmul roofline — the TPU answer to the reference's
    tiled-GEMM + select_k pipeline (``knn_brute_force.cuh:60``). All query
    blocks ride one ``lax.scan`` inside one jit call: a single device
    dispatch regardless of n_queries."""
    worst = jnp.float32(worst_value(jnp.float32, select_min))

    def step(_, q):
        q_sqnorm = row_norms(q) if metric in _NORM_METRICS else None
        dist = _expanded_distance(q, dataset, metric, q_sqnorm, norms)
        if has_filter:
            dist = jnp.where(filter_mask[None, :], dist, worst)
        v, i = approx_select_k(
            dist, k, select_min=select_min, recall_target=recall_target
        )
        # slots that only found worst-sentinel values (fewer than k rows
        # pass the prefilter) return id -1, matching the exact path
        i = jnp.where(v == worst, -1, i.astype(jnp.int32))
        return None, (v, i)

    _, (vals, idx) = lax.scan(step, None, queries_blocked)
    return vals, idx


def search(
    index: BruteForceIndex,
    queries,
    k: int,
    prefilter: Optional[Bitset] = None,
    query_batch: int = 4096,
    dataset_tile: Optional[int] = None,
    mode: str = "exact",
    recall_target: float = 0.99,
    res: Optional[Resources] = None,
    dataset=None,
    refine_ratio: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """k-nearest-neighbor search.

    Analog of ``brute_force::search`` (``neighbors/brute_force-inl.cuh``).
    Returns ``(distances [n_queries, k] f32, indices [n_queries, k] i32)``,
    best-first. ``prefilter`` is a keep-bitset over dataset rows.

    ``mode="exact"`` (default) reproduces the reference's exact contract
    (tiled f32 scan + sort-based select). ``mode="approx"`` fuses the
    distance matmul with TPU approximate top-k (see
    :func:`raft_tpu.ops.select_k.approx_select_k`) — orders of magnitude
    faster on large n, returning each true neighbor with probability
    ``recall_target``; available for the expanded metrics
    (L2/IP/cosine).

    ``dataset`` + ``refine_ratio > 1`` adds the integrated refine (same
    contract as ivf_pq/ivf_flat): the scan keeps ``k * refine_ratio``
    candidates that an exact f32 re-rank against ``dataset`` — a device
    array or a tiered ``HostVectorStore`` — cuts back to ``k``. The
    natural pairing is ``mode="approx"`` (or a narrow-dtype index),
    where the re-rank recovers exactness the scan traded away.

    With :mod:`raft_tpu.obs` enabled the call is wrapped in a
    device-synced ``brute_force.search`` span with per-mode counters."""
    if dataset is not None and refine_ratio > 1:
        from raft_tpu.neighbors.refine import check_refine_dataset, refine

        check_refine_dataset(dataset, index.size, "brute_force")
        kk = min(k * refine_ratio, index.size)
        _, cand = search(
            index, queries, kk, prefilter=prefilter, query_batch=query_batch,
            dataset_tile=dataset_tile, mode=mode, recall_target=recall_target, res=res,
        )
        with obs.span("brute_force.search.refine", k=k, candidates=int(kk)) as sp:
            return sp.sync(
                refine(dataset, queries, cand, k, metric=index.metric,
                       metric_arg=index.metric_arg)
            )
    if not obs.is_enabled():
        return _search_dispatch(
            index, queries, k, prefilter, query_batch, dataset_tile, mode, recall_target, res
        )
    with obs.span("brute_force.search", k=k, nq=int(np.shape(queries)[0]), mode=mode) as sp:
        return sp.sync(
            _search_dispatch(
                index, queries, k, prefilter, query_batch, dataset_tile, mode, recall_target, res
            )
        )


def _search_dispatch(
    index: BruteForceIndex,
    queries,
    k: int,
    prefilter: Optional[Bitset],
    query_batch: int,
    dataset_tile: Optional[int],
    mode: str,
    recall_target: float,
    res: Optional[Resources],
) -> Tuple[jax.Array, jax.Array]:
    res = ensure_resources(res)
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2, "queries must be [n_queries, dim]")
    expects(queries.shape[1] == index.dim, "query dim %d != index dim %d", queries.shape[1], index.dim)
    n = index.size
    expects(0 < k <= n, "k=%d out of range for index of size %d", k, n)
    if prefilter is not None:
        expects(prefilter.size == n, "prefilter size %d != index size %d", prefilter.size, n)

    metric = index.metric
    select_min = is_min_close(metric)
    nq = queries.shape[0]
    if obs.is_enabled():
        obs.inc("brute_force.search.calls", mode=mode)
        obs.inc("brute_force.search.queries", float(nq))

    if mode == "approx":
        expects(
            metric in _EXPANDED,
            "approx mode needs a matmul-shaped (expanded) metric, got %s",
            metric,
        )
        filter_mask = prefilter.to_mask() if prefilter is not None else None
        block = min(query_batch, nq)
        n_blocks = cdiv(nq, block)
        pad = n_blocks * block - nq
        qp = jnp.pad(queries, ((0, pad), (0, 0))) if pad else queries
        with obs.span("brute_force.search.approx", nq=nq, k=k) as sp:
            v, i = sp.sync(
                _search_approx_impl(
                    index.dataset,
                    index.norms,
                    qp.reshape(n_blocks, block, index.dim),
                    filter_mask,
                    k=k,
                    metric=metric,
                    select_min=select_min,
                    has_filter=filter_mask is not None,
                    recall_target=recall_target,
                )
            )
        v = v.reshape(n_blocks * block, k)[:nq]
        i = i.reshape(n_blocks * block, k)[:nq]
        return v, i
    expects(mode == "exact", "mode must be 'exact' or 'approx', got %r", mode)

    if dataset_tile is None:
        # Size tiles so per-tile temporaries stay within the workspace budget
        # (workspace heuristic analog of knn_brute_force.cuh:73-90
        # faiss::chooseTileSize). Expanded metrics materialize [batch, tile];
        # accumulation metrics broadcast [batch, tile, d] inside
        # _tile_distances, so their budget divides by d as well.
        qb = min(query_batch, nq)
        per_elem = 8 if metric in _EXPANDED else 8 * index.dim
        dataset_tile = max(512, min(n, res.workspace_bytes // (per_elem * max(qb, 1))))
    dataset_tile = int(min(dataset_tile, n))

    filter_mask = prefilter.to_mask() if prefilter is not None else None

    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qchunk = queries[start : start + query_batch]
        # Pad the trailing batch so jit sees one batch shape (one compile).
        bpad = 0
        if qchunk.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qchunk.shape[0]
            qchunk = jnp.pad(qchunk, ((0, bpad), (0, 0)))
        with obs.span(
            "brute_force.search.exact_batch", nq=qchunk.shape[0], k=k, tile=dataset_tile
        ) as sp:
            v, i = sp.sync(
                _search_impl(
                    index.dataset,
                    index.norms,
                    qchunk,
                    filter_mask,
                    k=k,
                    metric=metric,
                    p=index.metric_arg,
                    tile=dataset_tile,
                    select_min=select_min,
                    has_filter=filter_mask is not None,
                )
            )
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


def knn(
    dataset,
    queries,
    k: int,
    metric=DistanceType.L2SqrtExpanded,
    metric_arg: float = 2.0,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot build+search convenience (``brute_force::knn``,
    ``neighbors/brute_force-inl.cuh:224``)."""
    idx = build(dataset, metric=metric, metric_arg=metric_arg, res=res)
    return search(idx, queries, k, res=res)


# -- serialization ----------------------------------------------------------

_KIND = "brute_force"
_VERSION = 1


def _write_body(index: BruteForceIndex, stream: BinaryIO) -> None:
    ser.serialize_scalar(stream, int(index.metric), "int32")
    ser.serialize_scalar(stream, float(index.metric_arg), "float64")
    ser.serialize_scalar(stream, int(index.norms is not None), "int32")
    ser.serialize_array(stream, index.dataset)
    if index.norms is not None:
        ser.serialize_array(stream, index.norms)


def save(index: BruteForceIndex, stream: BinaryIO) -> None:
    """Serialize (``neighbors/brute_force_serialize.cuh`` analog) in the
    checksummed v4 envelope."""
    body = io.BytesIO()
    _write_body(index, body)
    ser.save_stream(stream, _KIND, _VERSION, body.getvalue())


def load(stream: BinaryIO, res: Optional[Resources] = None) -> BruteForceIndex:
    ensure_resources(res)
    _version, body = ser.load_stream(stream, _KIND)
    metric = DistanceType(ser.deserialize_scalar(body, "int32"))
    metric_arg = float(ser.deserialize_scalar(body, "float64"))
    has_norms = bool(ser.deserialize_scalar(body, "int32"))
    dataset = ser.deserialize_array(body)
    norms = ser.deserialize_array(body) if has_norms else None
    return BruteForceIndex(dataset=dataset, norms=norms, metric=metric, metric_arg=metric_arg)


def save_path(index: BruteForceIndex, path: str) -> str:
    """Atomic (temp-then-rename) checksummed snapshot at ``path``."""
    return ser.atomic_write(path, lambda f: save(index, f))


def load_path(path: str, res: Optional[Resources] = None) -> BruteForceIndex:
    with open(path, "rb") as f:
        return load(f, res=res)


class BatchKQuery:
    """Lazy batched-k query iterator — analog of
    ``neighbors/detail/knn_brute_force_batch_k_query.cuh`` /
    ``neighbors/brute_force-inl.cuh`` ``batch_k_query``: page through a
    query's neighbors ``batch_size`` at a time, searching lazily with a
    growing k (and over-fetching ahead like the reference's 1.5x growth)
    so cheap "first page" consumers never pay for deep ks.

    >>> for batch in BatchKQuery(index, queries, batch_size=32):
    ...     ids, dists = batch.indices, batch.distances   # [nq, 32] each
    """

    class Batch:
        def __init__(self, distances, indices, offset):
            self.distances = distances
            self.indices = indices
            self.offset = offset

    def __init__(self, index: BruteForceIndex, queries, batch_size: int, mode: str = "exact"):
        expects(batch_size >= 1, "batch_size must be >= 1")
        self.index = index
        self.queries = jnp.asarray(queries)
        self.batch_size = int(batch_size)
        self.mode = mode
        self._k = 0  # neighbors fetched so far
        self._dists = None
        self._ids = None

    def _ensure(self, k: int) -> None:
        if k <= self._k:
            return
        # over-fetch 1.5x ahead (the reference grows the same way) but
        # never past the index size
        k_fetch = min(self.index.size, max(k, int(1.5 * k)))
        self._dists, self._ids = search(
            self.index, self.queries, k_fetch, mode=self.mode
        )
        self._k = k_fetch

    def batch(self, i: int) -> "BatchKQuery.Batch":
        """The i-th page of neighbors: ranks [i*bs, (i+1)*bs)."""
        lo = i * self.batch_size
        hi = min(lo + self.batch_size, self.index.size)
        expects(lo < self.index.size, "batch %d past index size", i)
        self._ensure(hi)
        return BatchKQuery.Batch(self._dists[:, lo:hi], self._ids[:, lo:hi], lo)

    def __iter__(self):
        i = 0
        while i * self.batch_size < self.index.size:
            yield self.batch(i)
            i += 1
