"""Candidate re-ranking with exact distances — analog of
``raft::neighbors::refine`` (``neighbors/refine-inl.cuh:70,92``).

Given approximate candidate lists (e.g. from IVF-PQ or CAGRA), recompute
exact distances between each query and its candidates and keep the best k.
On TPU this is a batched gather + one small einsum per query block — XLA
turns the [n_queries, n_candidates, dim] contraction into MXU work. The
whole body runs under one ``jit`` so the gather feeds the distance matmul
and the top-k inside a single device program (eager dispatch per op costs
several HBM round-trips plus, on tunneled dev chips, ~100 ms of host link
per hop — measured 3-4x end-to-end on the bench's refine rows).

Two gather tiers share one re-rank core (:func:`_exact_rerank`):

* device-resident ``dataset`` — the gather is ``dataset[ids]`` inside the
  jit (:func:`_refine_impl`), the original all-in-HBM path;
* host-resident ``dataset`` (a :class:`raft_tpu.tiered.HostVectorStore`)
  — the gather is an ``np.take`` on the host, the ``[batch, n_cand, dim]``
  slab is ``device_put`` and re-ranked by :func:`_refine_gathered_impl`.

Both paths run the identical f32 arithmetic on identical gathered values,
so tiered results are bit-identical to the all-resident ones (asserted in
``tests/test_tiered.py``).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.neighbors.brute_force import _tile_distances, _NORM_METRICS
from raft_tpu.ops.distance import DistanceType, is_min_close, resolve_metric, row_norms
from raft_tpu.ops.select_k import select_k, worst_value


def is_host_dataset(dataset) -> bool:
    """True for host-tier vector stores (duck-typed so this module never
    imports :mod:`raft_tpu.tiered`, which imports it)."""
    return getattr(dataset, "is_host_tier", False)


def check_refine_dataset(dataset, index_size: int, algo: str = "index") -> None:
    """Validate a refine ``dataset`` against the index it re-ranks for —
    *before* any scan runs, so a short dataset fails up front with a
    typed :class:`~raft_tpu.core.errors.LogicError` naming the index
    size instead of deep inside the candidate gather."""
    shape = np.shape(dataset) if not hasattr(dataset, "shape") else tuple(dataset.shape)
    expects(
        len(shape) == 2,
        "%s refine dataset must be [n_rows, dim], got shape %s", algo, shape,
    )
    rows = int(shape[0])
    expects(
        rows >= index_size,
        "%s refine dataset has %d rows but the index holds %d vectors — "
        "every stored id must be gatherable; pass the full build dataset "
        "(or a HostVectorStore over it)",
        algo, rows, index_size,
    )


def _exact_rerank(
    cand_vecs, queries, candidates, valid, *, k: int, metric: DistanceType, metric_arg: float
) -> Tuple[jax.Array, jax.Array]:
    """Shared re-rank core: exact per-candidate distances + top-k.

    ``cand_vecs`` [nq, n_cand, d] is the gathered candidate slab —
    whichever tier produced it, the arithmetic from here on is identical,
    which is what makes tiered and resident results bit-equal."""
    qf = queries.astype(jnp.float32)
    cf = cand_vecs.astype(jnp.float32)

    select_min = is_min_close(metric)
    worst = jnp.float32(worst_value(jnp.float32, select_min))

    # Per-query exact distance to each candidate, via the same per-metric
    # bodies as brute force (vmapped over the query axis).
    q_sqnorm = row_norms(qf) if metric in _NORM_METRICS else None

    def one_query(q, cands, qn):
        qn_arr = None if qn is None else qn[None]
        d = _tile_distances(
            q[None, :],
            qn_arr,
            cands,
            None if qn is None else row_norms(cands),
            metric,
            metric_arg,
        )
        return d[0]

    if q_sqnorm is None:
        dists = jax.vmap(lambda q, c: one_query(q, c, None))(qf, cf)
    else:
        dists = jax.vmap(lambda q, c, n: one_query(q, c, n))(qf, cf, q_sqnorm)

    dists = jnp.where(valid, dists.astype(jnp.float32), worst)
    vals, pos = select_k(dists, k, select_min=select_min)
    idx = jnp.take_along_axis(candidates, pos, axis=1)
    # Restore -1 for slots that selected an invalid (padded) candidate.
    idx = jnp.where(jnp.take_along_axis(valid, pos, axis=1), idx, -1)
    return vals, idx


@functools.partial(jax.jit, static_argnames=("k", "metric", "metric_arg"))
def _refine_impl(
    dataset, queries, candidates, *, k: int, metric: DistanceType, metric_arg: float
) -> Tuple[jax.Array, jax.Array]:
    valid = candidates >= 0
    safe_ids = jnp.where(valid, candidates, 0)
    cand_vecs = dataset[safe_ids]  # [nq, n_cand, d]
    return _exact_rerank(
        cand_vecs, queries, candidates, valid, k=k, metric=metric, metric_arg=metric_arg
    )


@functools.partial(jax.jit, static_argnames=("k", "metric", "metric_arg"))
def _refine_gathered_impl(
    cand_vecs, queries, candidates, *, k: int, metric: DistanceType, metric_arg: float
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank a pre-gathered slab (host-tier fetch): the gather already
    substituted row 0 for invalid slots exactly like :func:`_refine_impl`."""
    valid = candidates >= 0
    return _exact_rerank(
        cand_vecs, queries, candidates, valid, k=k, metric=metric, metric_arg=metric_arg
    )


def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric=DistanceType.L2SqrtExpanded,
    metric_arg: float = 2.0,
    query_batch: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` [n_queries, n_cand] (i32 ids into ``dataset``,
    -1 = invalid) down to the top ``k`` by exact distance.

    ``dataset`` may be a device array (all-in-HBM gather) or a
    :class:`raft_tpu.tiered.HostVectorStore` (host-tier ``np.take`` +
    ``device_put`` slab per batch); results are bit-identical.

    ``query_batch``: 0 = auto — cap the gathered [batch, n_cand, dim] f32
    temporary at ~1 GB (CAGRA's graph build refines the WHOLE dataset as
    queries; unbatched that would allocate n * n_cand * dim * 4 bytes).

    With :mod:`raft_tpu.obs` enabled the call is wrapped in a
    device-synced ``refine.refine`` span with call/query counters and a
    candidates-per-query histogram.

    Returns ``(distances [n_queries, k], indices [n_queries, k])``.
    """
    if not obs.is_enabled():
        return _refine_dispatch(
            dataset, queries, candidates, k, metric, metric_arg, query_batch
        )
    nq = int(np.shape(queries)[0])
    n_cand = int(np.shape(candidates)[1]) if np.ndim(candidates) == 2 else 0
    obs.inc("refine.refine.calls")
    obs.inc("refine.refine.queries", float(nq))
    obs.observe("refine.refine.candidates_per_query", float(n_cand))
    with obs.span("refine.refine", k=k, nq=nq, candidates=n_cand) as sp:
        return sp.sync(
            _refine_dispatch(
                dataset, queries, candidates, k, metric, metric_arg, query_batch
            )
        )


def _refine_dispatch(
    dataset,
    queries,
    candidates,
    k: int,
    metric,
    metric_arg: float,
    query_batch: int,
) -> Tuple[jax.Array, jax.Array]:
    metric = resolve_metric(metric)
    host_tier = is_host_dataset(dataset)
    if not host_tier:
        dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    candidates = jnp.asarray(candidates, jnp.int32)
    expects(candidates.ndim == 2, "candidates must be [n_queries, n_candidates]")
    expects(candidates.shape[0] == queries.shape[0], "queries/candidates row mismatch")
    n_cand = candidates.shape[1]
    expects(0 < k <= n_cand, "k=%d out of range for %d candidates", k, n_cand)

    nq = queries.shape[0]
    if query_batch <= 0:
        per_q = max(1, n_cand * dataset.shape[1] * 4)
        query_batch = max(256, (1 << 30) // per_q)

    def one_batch(q, c):
        if host_tier:
            slab = dataset.gather(np.asarray(c))
            return _refine_gathered_impl(
                slab, q, c, k=k, metric=metric, metric_arg=metric_arg
            )
        return _refine_impl(dataset, q, c, k=k, metric=metric, metric_arg=metric_arg)

    if nq > query_batch:
        out_v, out_i = [], []
        for s in range(0, nq, query_batch):
            cnt = min(query_batch, nq - s)
            if cnt < query_batch:  # pad the tail to keep one compiled shape
                q = jnp.pad(queries[s : s + cnt], ((0, query_batch - cnt), (0, 0)))
                c = jnp.pad(
                    candidates[s : s + cnt],
                    ((0, query_batch - cnt), (0, 0)),
                    constant_values=-1,
                )
            else:
                q, c = queries[s : s + cnt], candidates[s : s + cnt]
            v, i = one_batch(q, c)
            out_v.append(v[:cnt])
            out_i.append(i[:cnt])
        return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)

    return one_batch(queries, candidates)
