"""IVF-PQ index — analog of ``raft::neighbors::ivf_pq``.

Reference: params/index ``neighbors/ivf_pq_types.hpp:47-139,293``, build
``neighbors/detail/ivf_pq_build.cuh:1681`` (rotation ``:122``, residual
transforms ``:162-230``, codebook training ``train_per_subset`` /
``train_per_cluster``), search ``neighbors/detail/ivf_pq_search.cuh:588``
(coarse ``select_clusters:67``, LUT scan worker ``ivfpq_search_worker:252``,
similarity kernel ``detail/ivf_pq_compute_similarity-inl.cuh``).

TPU-first redesign:

* **Codebook training is a batched (vmapped) Lloyd**: all ``pq_dim``
  subspace codebooks share shapes, so one ``vmap`` trains them
  simultaneously as a single stack of MXU matmuls — replacing the
  reference's sequential per-subspace kernel loop (``train_per_subset``).
* **Codes are stored one byte per sub-quantizer** in a dense padded
  ``[n_lists, max_list, pq_dim]`` uint8 tensor (+ parallel id tensor), not
  the reference's bit-packed interleaved groups
  (``ivf_pq_types.hpp: list_data`` 16-byte chunk layout): TPU vector memory
  wants byte-aligned lanes, and XLA can tile a dense uint8 tensor directly.
  ``pq_bits < 8`` therefore saves codebook space but not code storage
  (documented trade-off).
* **Search LUT is built per (query, probe) with one einsum** and applied
  with a lane-wise gather; probes are processed by a ``lax.scan`` carrying a
  running top-k (same structure as IVF-Flat), instead of the CUDA
  shared-memory LUT kernel.
* fp8 LUTs (``detail/ivf_pq_fp_8bit.cuh``) are replaced by an optional
  bf16 LUT mode — the TPU-native reduced-precision path.
* **Fused Pallas search** (``mode="fused"``, round 4): scalar-prefetch DMA
  of only the probed code blocks + an in-kernel multi-hot-matmul LUT
  apply — the work-proportional fast path mirroring the reference's
  ``compute_similarity`` kernel. See :mod:`raft_tpu.ops.pallas.pq_scan`.
  Every ``per_subspace`` width is eligible: ``ksub <= 64`` decodes in a
  single multi-hot pass; ``ksub = 128/256`` (including the DEFAULT
  ``pq_bits=8`` kmeans config) via **column-chunked decode** (round 5) —
  the work-proportional answer to the LUT-cost problem the reference
  solves with fp8 LUTs. ``pq_kind="nibble"`` remains the cheap 8-bit
  point: **additive nibble codebooks** — each subspace quantized by the
  SUM of two 16-entry codebooks (A[hi] + B[lo], one byte per code) — 256
  effective centers at 32-column LUT cost (2-level residual quantization
  instead of low-precision table entries).
* ``pq_bits < 8`` codes are **bit-packed** whenever the row bitstream is
  byte-aligned: two per byte for 4-bit, spanning little-endian layouts
  for 5/6/7 (``ivf_pq_types.hpp:129-164`` /
  ``detail/ivf_pq_codepacking.cuh`` analog — plain contiguous bytes, not
  16-byte interleave: TPU DMA wants flat rows), cutting code storage and
  scan DMA to ``pq_bits/8`` of a byte per code.
* ``pq_kind="rabitq"`` (round 7): **RaBitQ binary quantization** ("GPU-
  Native Approximate Nearest Neighbor Search with IVF-RaBitQ",
  PAPERS.md) — each list residual is reduced to its D sign bits under a
  FORCED random rotation plus two per-vector f32 corrections, and scored
  with the unbiased bitwise estimator
  ``est = ||q-c||^2 + ||r||^2 - g*(b.q_rot - Σq_rot/2) + const(b, c)``
  where ``g = 4||r|| / (sqrt(D) * <o, x̄>)`` folds the estimator's
  normalization. One bit per dimension (16 bytes/row at d=128 — the same
  DMA footprint as the nibble config) but the scan's per-row decode is a
  single D-wide sign matmul instead of a ``pq_dim * ksub``-column
  multi-hot decode: ~4x cheaper per scanned row at equal bits. The
  center-dependent part of the estimator is folded into the per-slot
  constant channel (``rot_sqnorms`` stores it; ``corrections`` stores
  ``g``) so the fused kernel's bit matmul is query-only. Rescoring runs
  through the same integrated ``refine`` re-rank; see
  :mod:`raft_tpu.ops.pallas.rabitq_scan` for the fused Pallas path.

Supported metrics: L2Expanded, L2SqrtExpanded, InnerProduct.
"""
from __future__ import annotations

import dataclasses
import functools
import io
import warnings
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import BalancedKMeansParams
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.core.logging import logger
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.neighbors import ivf_common
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.ops.fused_1nn import min_cluster_and_distance
from raft_tpu.ops.select_k import running_merge, select_k, worst_value
from raft_tpu.random.rng import as_key
from raft_tpu.robust import fallback as _fallback, faults as _faults
from raft_tpu.utils.math import round_up

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
)

PER_SUBSPACE = "per_subspace"
PER_CLUSTER = "per_cluster"


def _default_pq_dim(dim: int) -> int:
    """Reference heuristic (``ivf_pq_types.hpp:588-601 calculate_pq_dim``):
    halve large dims, round down to a multiple of 32, else nearest pow2."""
    d = dim // 2 if dim >= 128 else dim
    r = (d // 32) * 32
    if r > 0:
        return r
    r = 1
    while r * 2 <= d:
        r *= 2
    return r


@dataclasses.dataclass
class IvfPqIndexParams:
    """``ivf_pq::index_params`` analog (``neighbors/ivf_pq_types.hpp:47``)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8
    pq_dim: int = 0  # 0 = auto (calculate_pq_dim)
    codebook_kind: str = PER_SUBSPACE
    force_random_rotation: bool = False
    seed: int = 0
    # Dense-layout list capacity cap (see ivf_common.assign_slots).
    # Default OFF for PQ: a spilled row's residual is taken against its
    # second-nearest center, which measurably degrades code quality —
    # unlike IVF-Flat, where spill only affects which probe finds the row.
    list_cap_factor: float = 0.0
    # "kmeans" = one 2^pq_bits-center codebook per subspace (reference
    # semantics). "nibble" = additive nibble pairs (requires pq_bits=8,
    # per_subspace): subspace j is quantized by A[j][hi] + B[j][lo] — 256
    # effective centers whose fused-scan LUT costs only 32 columns.
    # "rabitq" = 1-bit RaBitQ sign codes with per-vector correction
    # factors (pq_bits is forced to 1; pq_dim/codebook knobs are ignored;
    # the rotation is always random — the estimator's guarantees need it).
    # "auto" (default) = "rabitq" when pq_bits=1 is requested, else
    # "nibble" whenever representable (pq_bits=8 + per_subspace — i.e.
    # the out-of-box config), else "kmeans": the nibble+refine operating
    # point was the measured Pareto frontier (BENCH_r05: 15.7k QPS
    # @ 0.947 vs 4.6k @ 0.56 for kmeans-256); rabitq+refine beats it at
    # equal code bytes (BENCH_r06).
    pq_kind: str = "auto"


@dataclasses.dataclass
class IvfPqSearchParams:
    """``ivf_pq::search_params`` analog (``ivf_pq_types.hpp:120``).

    The ``fused_*`` knobs tune the Pallas fused scan (``mode="fused"``);
    they mirror :class:`raft_tpu.neighbors.ivf_flat.IvfFlatSearchParams`.

    The defaults sit on the measured Pareto frontier (BENCH_r05: nibble
    codes, ``n_probes=30``, 8x exact refine → ~15.7k QPS @ 0.947 on
    1M x 128): pass the raw ``dataset`` to :func:`search` and the default
    ``refine_ratio`` re-ranks ``k * refine_ratio`` PQ candidates with
    exact distances."""

    n_probes: int = 30
    # Exact re-rank depth: search keeps k * refine_ratio PQ candidates and
    # re-scores them against the raw dataset (refine.refine) when search()
    # is given ``dataset=``; without a dataset this knob is inert. 1 = off.
    refine_ratio: int = 8
    # LUT precision (the reference's ``lut_dtype``, ivf_pq_types.hpp:120).
    # None = auto: float32 on the scan/probe paths, bf16 on the fused
    # Pallas path (whose LUT matmul is MXU-bf16 by construction).
    # Explicitly requesting float32 makes ``mode="auto"`` route to the
    # scan path, which honors it; ``mode="fused"`` always computes the
    # LUT in bf16 regardless.
    lut_dtype: Optional[jnp.dtype] = None
    fused_qt: int = 128
    fused_probe_factor: int = 32
    fused_group: int = 8
    fused_merge: str = "bank8"
    fused_extract_every: int = 0
    # max multi-hot columns materialized per decode chunk (VMEM bound for
    # wide codebooks: K = pq_dim * ksub columns total); 0 = single pass.
    # Always further capped by a VMEM model of the kernel
    # (pq_scan.vmem_decode_cols) so long lists cannot blow the ~16 MB
    # scoped-VMEM stack.
    fused_decode_cols: int = 2048


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class IvfPqIndex:
    """Product-quantized inverted-file index (``ivf_pq_types.hpp:293``)."""

    centers: jax.Array  # [n_lists, d] f32 raw coarse centers
    centers_rot: jax.Array  # [n_lists, rot_dim] f32 rotated centers
    rotation: jax.Array  # [rot_dim, d] f32 orthonormal transform
    pq_centers: jax.Array  # per_subspace: [pq_dim, ksub, pq_len]
    #                         per_cluster:  [n_lists, ksub, pq_len]
    #   For additive nibble codebooks, the MATERIALIZED 256-entry sum grid
    #   pq_centers[j, hi*16+lo] = A[j, hi] + B[j, lo] — every XLA path
    #   (scan/probe/sqnorms/encode) works on it unchanged; the fused
    #   kernel re-derives A/B via nibble_books().
    codes: jax.Array  # [n_lists, max_list, pq_dim] uint8 (pq_dim/2 when packed)
    list_indices: jax.Array  # [n_lists, max_list] i32, -1 = empty
    list_sizes: jax.Array  # [n_lists] i32
    rot_sqnorms: jax.Array  # [n_lists, max_list] f32 ||c_rot + resid||^2
    #   rabitq: the per-slot additive constant of the distance estimator
    #   (center-dependent terms folded at build time; see _rabitq docs).
    metric: DistanceType
    codebook_kind: str
    pq_bits: int
    size: int
    list_cap_factor: float = 0.0  # build-time cap; honored by extend()
    additive: bool = False  # nibble-pair codebooks (pq_kind="nibble")
    packed: bool = False  # 4-bit codes packed two per byte
    center_rank: Optional[jax.Array] = None  # [n_lists] spatial rank (v3+)
    rabitq: bool = False  # 1-bit sign codes + corrections (pq_kind="rabitq")
    corrections: Optional[jax.Array] = None  # [n_lists, max_list] f32 rabitq g

    def tree_flatten(self):
        return (
            (
                self.centers,
                self.centers_rot,
                self.rotation,
                self.pq_centers,
                self.codes,
                self.list_indices,
                self.list_sizes,
                self.rot_sqnorms,
                self.center_rank,
                self.corrections,
            ),
            (
                self.metric, self.codebook_kind, self.pq_bits, self.size,
                self.list_cap_factor, self.additive, self.packed, self.rabitq,
            ),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(
            *children[:8],
            metric=aux[0],
            codebook_kind=aux[1],
            pq_bits=aux[2],
            size=aux[3],
            list_cap_factor=aux[4],
            additive=aux[5],
            packed=aux[6],
            center_rank=children[8],
            rabitq=aux[7],
            corrections=children[9],
        )

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.codes.shape[2] * 8 // self.pq_bits if self.packed else self.codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.pq_centers.shape[-1]

    @property
    def ksub(self) -> int:
        return self.pq_centers.shape[-2]

    @property
    def max_list(self) -> int:
        return self.codes.shape[1]

    def codes_unpacked(self) -> jax.Array:
        """[n_lists, max_list, pq_dim] u8 view for the XLA decode paths."""
        if not self.packed:
            return self.codes
        return unpack_codes_bits(self.codes, self.pq_bits, self.pq_dim)


# ---------------------------------------------------------------------------
# build helpers
# ---------------------------------------------------------------------------


def _make_rotation(key, rot_dim: int, dim: int, force: bool) -> jax.Array:
    """Orthonormal [rot_dim, dim] transform (``make_rotation_matrix``,
    ``ivf_pq_build.cuh:122``): identity when square and not forced, else the
    Q factor of a Gaussian matrix (rows are orthonormal)."""
    if not force and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    n = max(rot_dim, dim)
    g = jax.random.normal(key, (n, n), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q[:rot_dim, :dim]


@functools.partial(jax.jit, static_argnames=("k", "n_iters"))
def _batched_lloyd(X, mask, init, *, k: int, n_iters: int):
    """Vmapped masked Lloyd: ``X [B, n, d]``, 0/1 ``mask [B, n]``,
    ``init [B, k, d]`` → centers ``[B, k, d]``.

    The batched replacement for the reference's per-subspace /
    per-cluster sequential codebook loops (``train_per_subset``,
    ``train_per_cluster``, ``ivf_pq_build.cuh``): every subspace trains in
    the same stack of MXU ops.
    """

    def one(Xb, mb, cb):
        def body(_, centers):
            d2 = (
                jnp.sum(Xb * Xb, axis=1)[:, None]
                - 2.0 * Xb @ centers.T
                + jnp.sum(centers * centers, axis=1)[None, :]
            )
            labels = jnp.argmin(d2, axis=1)
            w = mb
            sums = jax.ops.segment_sum(Xb * w[:, None], labels, num_segments=k)
            counts = jax.ops.segment_sum(w, labels, num_segments=k)
            means = sums / jnp.maximum(counts[:, None], 1e-9)
            return jnp.where(counts[:, None] > 0, means, centers)

        return lax.fori_loop(0, n_iters, body, cb)

    return jax.vmap(one)(X, mask, init)


@functools.partial(jax.jit, static_argnames=("per_cluster",))
def _encode_chunk(resid_rot, labels, pq_centers, *, per_cluster: bool):
    """Encode rotated residuals ``[c, pq_dim, pq_len]`` to uint8 codes
    (``process_and_fill_codes`` analog): nearest sub-center per subspace via
    one batched matmul."""
    if per_cluster:
        pqc = pq_centers[labels]  # [c, ksub, pq_len]
        dots = jnp.einsum("npl,nkl->npk", resid_rot, pqc, preferred_element_type=jnp.float32)
        cn = jnp.sum(pqc * pqc, axis=-1)[:, None, :]  # [c, 1, ksub]
    else:
        dots = jnp.einsum("npl,pkl->npk", resid_rot, pq_centers, preferred_element_type=jnp.float32)
        cn = jnp.sum(pq_centers * pq_centers, axis=-1)[None, :, :]  # [1, pq_dim, ksub]
    # ||r - c||^2 = ||r||^2 - 2 r.c + ||c||^2 ; ||r||^2 constant in argmin
    d2 = cn - 2.0 * dots
    return jnp.argmin(d2, axis=-1).astype(jnp.uint8)


def _rotated_residuals(X, labels, centers, rotation, pq_dim: int):
    """R @ (x - c[label]) reshaped to [n, pq_dim, pq_len]."""
    resid = X - centers[labels]
    rr = resid @ rotation.T  # [n, rot_dim]
    return rr.reshape(X.shape[0], pq_dim, -1)


def pack_codes(codes) -> jax.Array:
    """Pack 4-bit codes pairwise: byte b = code[2b] | (code[2b+1] << 4).
    (``detail/ivf_pq_codepacking.cuh`` analog; contiguous pairs instead of
    the reference's 16-byte interleave — TPU DMA wants plain bytes.)"""
    lo = codes[..., 0::2]
    hi = codes[..., 1::2]
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_codes(packed) -> jax.Array:
    """Inverse of :func:`pack_codes`: [..., bpr] u8 -> [..., 2*bpr] u8."""
    lo = packed & jnp.uint8(15)
    hi = packed >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1], -1)


def pack_codes_bits(codes, bits: int) -> jax.Array:
    """Bit-pack ``bits``-wide codes as a little-endian bitstream per row:
    code j occupies global bits ``[j*bits, (j+1)*bits)``, bit t of byte s
    is global bit ``s*8 + t``. Requires ``pq_dim * bits % 8 == 0`` (the
    row bitstream is byte-aligned, so codes never span rows). For
    ``bits=4`` this is exactly :func:`pack_codes`'s pairwise layout.
    Spanning-width analog of the reference's per-width chunk packing
    (``ivf_pq_types.hpp:129-164``, ``detail/ivf_pq_codepacking.cuh``)."""
    if bits == 4:
        return pack_codes(codes)
    pq_dim = codes.shape[-1]
    expects(pq_dim * bits % 8 == 0, "pq_dim*bits must be byte-aligned to pack")
    bpr = pq_dim * bits // 8
    c = codes.astype(jnp.uint32)
    bit = (c[..., None] >> jnp.arange(bits, dtype=jnp.uint32)) & 1
    by = bit.reshape(*codes.shape[:-1], bpr, 8)
    w = jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32)
    return jnp.sum(by * w, axis=-1).astype(jnp.uint8)


def unpack_codes_bits(packed, bits: int, pq_dim: int) -> jax.Array:
    """Inverse of :func:`pack_codes_bits`."""
    if bits == 4:
        return unpack_codes(packed)
    p = packed.astype(jnp.uint32)
    bit = (p[..., None] >> jnp.arange(8, dtype=jnp.uint32)) & 1
    co = bit.reshape(*packed.shape[:-1], pq_dim, bits)
    w = jnp.uint32(1) << jnp.arange(bits, dtype=jnp.uint32)
    return jnp.sum(co * w, axis=-1).astype(jnp.uint8)


def nibble_books(pq_centers) -> jax.Array:
    """Derive the fused-scan nibble codebooks [pq_dim, 32, pq_len] from the
    materialized additive grid ``pq_centers[j, hi*16+lo] = A[hi] + B[lo]``:
    A'[hi] = grid[hi*16], B'[lo] = grid[lo] - grid[0] reproduces every sum
    exactly (A' absorbs B[0])."""
    pq_dim, ksub, pq_len = pq_centers.shape
    a = pq_centers[:, 0::16, :]  # [pq_dim, 16, pq_len] = A + B[0]
    b = pq_centers[:, 0:16, :] - pq_centers[:, 0:1, :]  # B - B[0]
    return jnp.concatenate([a, b], axis=1)  # hi-half then lo-half


def _train_nibble_books(t_resid, key, n_iters: int):
    """Additive nibble codebooks: A = 16-center Lloyd on the residuals,
    B = 16-center Lloyd on the second-level residuals, then alternating
    joint re-encode / re-fit. Returns the materialized 256-entry sum grid
    [pq_dim, 256, pq_len] (every non-fused path consumes that directly).

    A 2-level per-subspace residual quantizer: same decode cost as
    pq_bits=4 but 256 effective centers — the accuracy/FLOP point the
    reference reaches with fp8 LUTs (``detail/ivf_pq_fp_8bit.cuh``)."""
    pq_dim = t_resid.shape[1]
    nt = t_resid.shape[0]
    Xs = jnp.transpose(t_resid, (1, 0, 2))  # [pq_dim, nt, pq_len]
    mask = jnp.ones((pq_dim, nt), jnp.float32)
    ka, kb = jax.random.split(key)

    def seed_init(k, X):
        idx = jax.random.permutation(k, nt)[: min(16, nt)]
        init = X[:, idx, :]
        if init.shape[1] < 16:
            reps = -(-16 // init.shape[1])
            init = jnp.tile(init, (1, reps, 1))[:, :16, :]
        return init

    A = _batched_lloyd(Xs, mask, seed_init(ka, Xs), k=16, n_iters=n_iters)

    def assign(X, books):  # [pq_dim, nt, pq_len] x [pq_dim, 16, pq_len]
        d2 = (
            jnp.sum(books * books, axis=-1)[:, None, :]
            - 2.0 * jnp.einsum("pnl,pkl->pnk", X, books, preferred_element_type=jnp.float32)
        )
        return jnp.argmin(d2, axis=-1)  # [pq_dim, nt]

    hi = assign(Xs, A)
    R2 = Xs - jnp.take_along_axis(A, hi[:, :, None], axis=1)
    B = _batched_lloyd(R2, mask, seed_init(kb, R2), k=16, n_iters=n_iters)

    def refit(X, labels, k):
        def one(Xb, lb):
            sums = jax.ops.segment_sum(Xb, lb, num_segments=k)
            counts = jax.ops.segment_sum(jnp.ones_like(lb, jnp.float32), lb, num_segments=k)
            return sums / jnp.maximum(counts[:, None], 1e-9), counts

        return jax.vmap(one)(X, labels)

    for _ in range(2):  # coordinate descent on (A, B)
        lo = assign(Xs - jnp.take_along_axis(A, hi[:, :, None], axis=1), B)
        Anew, ca = refit(Xs - jnp.take_along_axis(B, lo[:, :, None], axis=1), hi, 16)
        A = jnp.where(ca[:, :, None] > 0, Anew, A)
        hi = assign(Xs - jnp.take_along_axis(B, lo[:, :, None], axis=1), A)
        Bnew, cb = refit(Xs - jnp.take_along_axis(A, hi[:, :, None], axis=1), lo, 16)
        B = jnp.where(cb[:, :, None] > 0, Bnew, B)

    # materialize the sum grid: grid[j, hi*16+lo] = A[j,hi] + B[j,lo]
    grid = A[:, :, None, :] + B[:, None, :, :]  # [pq_dim, 16, 16, pq_len]
    return grid.reshape(pq_dim, 256, -1)


@functools.partial(jax.jit, static_argnames=("per_cluster", "chunk_lists"))
def _decoded_sqnorms(codes, centers_rot, pq_centers, *, per_cluster: bool, chunk_lists: int):
    """Precompute ||c_rot[l] + decode(code)||^2 per slot [n_lists, max_list]
    — the constant term of the scan path's score epilogue (decoded once at
    build instead of on every search batch)."""
    n_lists, M, pq_dim = codes.shape
    ksub = pq_centers.shape[-2]
    rot_dim = centers_rot.shape[1]
    G = chunk_lists
    n_chunks = n_lists // G
    # f32 one-hot decode: build-time one-off, and the CPU backend has no
    # bf16 dot support
    books = pq_centers.astype(jnp.float32)

    def body(_, inp):
        cod, crot, bks = inp
        if per_cluster:
            onehot = (
                cod[:, :, :, None].astype(jnp.int32)
                == jnp.arange(ksub, dtype=jnp.int32)[None, None, None, :]
            ).astype(jnp.float32)
            resid = jnp.einsum(
                "gmjc,gcs->gmjs", onehot, bks, preferred_element_type=jnp.float32
            )
        else:
            onehot = (
                cod.reshape(G * M, pq_dim)[:, :, None].astype(jnp.int32)
                == jnp.arange(ksub, dtype=jnp.int32)[None, None, :]
            ).astype(jnp.float32)
            resid = jnp.einsum(
                "tjc,jcs->tjs", onehot, books, preferred_element_type=jnp.float32
            )
        dec = resid.reshape(G, M, rot_dim) + crot[:, None, :]
        return None, jnp.sum(dec * dec, axis=-1)

    crot_c = centers_rot.reshape(n_chunks, G, rot_dim)
    bks_c = (
        pq_centers.astype(jnp.float32).reshape(n_chunks, G, ksub, -1)
        if per_cluster
        else jnp.zeros((n_chunks, 1), jnp.float32)
    )
    _, sqn = lax.scan(body, None, (codes.reshape(n_chunks, G, M, pq_dim), crot_c, bks_c))
    return sqn.reshape(n_lists, M)


def _sqnorms_for(codes, centers_rot, pq_centers, per_cluster: bool):
    g = max(1, 262144 // max(codes.shape[1], 1))
    while codes.shape[0] % g:
        g -= 1
    return _decoded_sqnorms(
        codes, centers_rot, pq_centers, per_cluster=per_cluster, chunk_lists=g
    )


def _encode_all(ds_f32, labels, centers, rotation, pq_centers, pq_dim, per_cluster, chunk=65536):
    """Encode every row against its (final) list's center — fully on
    device, chunked so the [chunk, pq_dim, ksub] temporaries stay bounded."""
    outs = []
    n = ds_f32.shape[0]
    for s in range(0, n, chunk):
        lab = labels[s : s + chunk]
        rr = _rotated_residuals(ds_f32[s : s + chunk], lab, centers, rotation, pq_dim)
        outs.append(_encode_chunk(rr, lab, pq_centers, per_cluster=per_cluster))
    if not outs:
        return jnp.zeros((0, pq_dim), jnp.uint8)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


@functools.partial(jax.jit, static_argnames=("metric",))
def _rabitq_encode_chunk(X, labels, centers, rotation, centers_rot, *, metric):
    """RaBitQ-encode a chunk of rows against their lists' centers.

    Per row with rotated residual ``r = R(x - c_l)`` (``R`` orthonormal,
    ``D = rot_dim``), the stored code is the D sign bits ``b = [r > 0]``
    (the quantized direction is ``x̄ = (2b-1)/sqrt(D)``, a unit vector) and
    the RaBitQ estimator of ``<r/||r||, u>`` for any query-side ``u`` is
    ``<x̄, u> / <x̄, o>`` with ``o = r/||r||``. Expanding ``<x̄, u> =
    (2 b·u - Σu)/sqrt(D)`` and folding every center-dependent term at
    build time gives one per-slot affine form shared by both metrics:

        min-score      = C1 - coef·(q·c_l) - g·(b·q_rot - Σq_rot/2)
        L2   estimate  = ||q||² + min-score          (coef = 2)
        IP   estimate  = -min-score                  (coef = 1)

    with the two per-row scalars stored in the index:

        g_L2 = 4||r|| / (sqrt(D)·<x̄,o>)      g_IP = g_L2 / 2
        C1_L2 = ||c_rot||² + ||r||² + g_L2·(b·c_rot - Σc_rot/2)
        C1_IP = 0

    (``<x̄, o> = ||r||₁ / (sqrt(D)·||r||₂)``, computable from the residual
    alone.) Returns ``(packed_bits [c, D/8] u8, aux [c, 2] f32)`` with
    ``aux = [C1, g]``.
    """
    rr = (X - centers[labels]) @ rotation.T  # [c, D]
    D = rr.shape[1]
    r2 = jnp.sum(rr * rr, axis=1)
    r = jnp.sqrt(r2)
    sd = lax.rsqrt(jnp.float32(D))
    # <x̄, o> = sd * ||r||1 / ||r||2, in [sd, 1]; guard the zero residual.
    ood = sd * jnp.sum(jnp.abs(rr), axis=1) / jnp.maximum(r, 1e-30)
    g = jnp.where(r > 0, 4.0 * r * sd / jnp.maximum(ood, 1e-12), 0.0)
    if metric == DistanceType.InnerProduct:
        g = 0.5 * g
    signs = (rr > 0).astype(jnp.uint8)  # [c, D]
    crot = centers_rot[labels]  # [c, D]
    if metric == DistanceType.InnerProduct:
        # IP decomposes <x,q> = <c,q> + <r, q_rot>: no center term inside
        # the estimator argument, so the additive constant is zero.
        c1 = jnp.zeros_like(g)
    else:
        bdotc = jnp.sum(jnp.where(rr > 0, crot, 0.0), axis=1)
        c1 = jnp.sum(crot * crot, axis=1) + r2 + g * (bdotc - 0.5 * jnp.sum(crot, axis=1))
    return pack_codes_bits(signs, 1), jnp.stack([c1, g], axis=1)


def _rabitq_encode_all(ds_f32, labels, centers, rotation, centers_rot, metric, chunk=65536):
    """Chunked :func:`_rabitq_encode_chunk` over the full dataset."""
    n = ds_f32.shape[0]
    D = rotation.shape[0]
    codes, auxs = [], []
    for s in range(0, n, chunk):
        cod, aux = _rabitq_encode_chunk(
            ds_f32[s : s + chunk], labels[s : s + chunk], centers, rotation, centers_rot,
            metric=metric,
        )
        codes.append(cod)
        auxs.append(aux)
    if not codes:
        return jnp.zeros((0, D // 8), jnp.uint8), jnp.zeros((0, 2), jnp.float32)
    if len(codes) == 1:
        return codes[0], auxs[0]
    return jnp.concatenate(codes, axis=0), jnp.concatenate(auxs, axis=0)


def build(
    dataset,
    params: Optional[IvfPqIndexParams] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> IvfPqIndex:
    """Train the coarse quantizer + PQ codebooks and encode the dataset
    (``ivf_pq::build``, ``detail/ivf_pq_build.cuh:1681``)."""
    res = ensure_resources(res)
    if params is None:
        params = IvfPqIndexParams(**kwargs)
    metric = resolve_metric(params.metric)
    expects(metric in _SUPPORTED, "IVF-PQ does not support metric %s", metric)
    expects(params.codebook_kind in (PER_SUBSPACE, PER_CLUSTER), "bad codebook_kind")
    expects(
        params.pq_kind in ("auto", "kmeans", "nibble", "rabitq"),
        "pq_kind must be auto|kmeans|nibble|rabitq",
    )
    pq_kind = params.pq_kind
    if pq_kind == "auto":  # default: nibble whenever representable
        from raft_tpu import plan as _plan

        if _plan.is_enabled():
            pq_kind = _plan.plan_pq_kind(
                params.pq_bits,
                params.codebook_kind == PER_SUBSPACE,
                pq_dim=int(getattr(params, "pq_dim", 0) or 16),
            ).choice
        elif params.pq_bits == 1:
            pq_kind = "rabitq"
        else:
            pq_kind = (
                "nibble"
                if params.pq_bits == 8 and params.codebook_kind == PER_SUBSPACE
                else "kmeans"
            )
    nibble = pq_kind == "nibble"
    rabitq = pq_kind == "rabitq"
    if rabitq:
        # pq_bits is definitionally 1 (sign bit per rotated dimension);
        # accept the dataclass default (8) or an explicit 1, reject the
        # rest as probable configuration mistakes.
        expects(
            params.pq_bits in (1, 8),
            "pq_kind='rabitq' is 1 bit/dim; pq_bits=%d conflicts", params.pq_bits,
        )
    else:
        expects(3 <= params.pq_bits <= 8, "pq_bits must be in [3, 8], got %d", params.pq_bits)
    if nibble:
        expects(
            params.pq_bits == 8 and params.codebook_kind == PER_SUBSPACE,
            "pq_kind='nibble' requires pq_bits=8 and per_subspace codebooks",
        )
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    n, d = dataset.shape
    n_lists = min(params.n_lists, n)
    if rabitq:
        # one sign bit per rotated dimension; the rotation pads d up to a
        # byte-aligned D so rows pack to D/8 contiguous bytes.
        pq_dim = round_up(d, 8)
        rot_dim = pq_dim
        pq_len = 1
        ksub = 2
    else:
        pq_dim = params.pq_dim or _default_pq_dim(d)
        expects(pq_dim <= d, "pq_dim=%d larger than dim=%d", pq_dim, d)
        rot_dim = round_up(d, pq_dim)
        pq_len = rot_dim // pq_dim
        ksub = 1 << params.pq_bits

    key = as_key(params.seed)
    k_rot, k_cb = jax.random.split(key)

    ds_f32 = dataset.astype(jnp.float32)
    train_n = max(n_lists, int(n * params.kmeans_trainset_fraction))
    trainset = ds_f32
    if train_n < n:
        rng = np.random.default_rng(params.seed)
        trainset = ds_f32[jnp.asarray(rng.permutation(n)[:train_n])]

    # -- coarse quantizer (kmeans_balanced, as in the reference) ------------
    centers = kmeans_balanced.fit(
        trainset,
        BalancedKMeansParams(
            n_clusters=n_lists,
            n_iters=params.kmeans_n_iters,
            metric=DistanceType.L2Expanded,
            seed=params.seed,
        ),
    )
    # Physically order the lists by the PCA-bisection spatial rank of
    # their centers (same as IVF-Flat v3): the fused Pallas scan's
    # probe-coherent query tiles and group-granular DMA both assume
    # spatially nearby lists sit next to each other.
    from raft_tpu.ops.pallas.ivf_scan import spatial_center_rank

    srank = spatial_center_rank(np.asarray(centers))
    centers = jnp.asarray(np.asarray(centers)[np.argsort(srank)])
    center_rank = jnp.arange(n_lists, dtype=jnp.int32)

    # -- rotation + rotated centers ----------------------------------------
    # RaBitQ's estimator is only unbiased under a RANDOM rotation (the sign
    # quantizer needs the residual direction uniformly distributed on the
    # sphere), so rabitq always forces one.
    rotation = _make_rotation(k_rot, rot_dim, d, params.force_random_rotation or rabitq)
    centers_rot = centers @ rotation.T

    per_cluster = params.codebook_kind == PER_CLUSTER
    if rabitq:
        # No codebook to train: the "codebook" is the sign function.
        # pq_centers stays a [1, 1, 1] placeholder (pq_len/ksub properties
        # are meaningless for this kind and never consulted).
        pq_centers = jnp.zeros((1, 1, 1), jnp.float32)
        cand = ivf_common.topk_labels(ds_f32, centers, k=8)
        max_list = ivf_common.choose_max_list(cand[:, 0], n, n_lists, params.list_cap_factor)
        slot = ivf_common.assign_slots(cand, n_lists=n_lists, max_list=max_list)
        final_labels = (slot // max_list).astype(jnp.int32)
        codes_dev, aux_dev = _rabitq_encode_all(
            ds_f32, final_labels, centers, rotation, centers_rot, metric
        )
        codes, list_indices, list_sizes = ivf_common.scatter_rows(
            codes_dev, jnp.arange(n, dtype=jnp.int32), slot, n_lists=n_lists, max_list=max_list
        )
        aux, _, _ = ivf_common.scatter_rows(
            aux_dev, jnp.arange(n, dtype=jnp.int32), slot, n_lists=n_lists, max_list=max_list
        )
        return IvfPqIndex(
            centers=centers,
            centers_rot=centers_rot,
            rotation=rotation,
            pq_centers=pq_centers,
            codes=codes,
            list_indices=list_indices,
            list_sizes=list_sizes,
            rot_sqnorms=aux[..., 0],
            metric=metric,
            codebook_kind=params.codebook_kind,
            pq_bits=1,
            size=n,
            list_cap_factor=params.list_cap_factor,
            additive=False,
            packed=True,
            center_rank=center_rank,
            rabitq=True,
            corrections=aux[..., 1],
        )

    # -- codebook training on trainset residuals ---------------------------
    t_labels, _ = min_cluster_and_distance(trainset, centers, metric=DistanceType.L2Expanded)
    t_resid = _rotated_residuals(trainset, t_labels, centers, rotation, pq_dim)  # [nt, pq_dim, pq_len]
    nt = t_resid.shape[0]

    if nibble:
        pq_centers = _train_nibble_books(t_resid, k_cb, params.kmeans_n_iters)
    elif not per_cluster:
        # [pq_dim, nt, pq_len] stacks; one vmapped Lloyd trains all subspaces.
        Xs = jnp.transpose(t_resid, (1, 0, 2))
        mask = jnp.ones((pq_dim, nt), jnp.float32)
        init_idx = jax.random.permutation(k_cb, nt)[: min(ksub, nt)]
        init = Xs[:, init_idx, :]
        if init.shape[1] < ksub:  # degenerate tiny trainset: tile seeds
            reps = -(-ksub // init.shape[1])
            init = jnp.tile(init, (1, reps, 1))[:, :ksub, :]
        pq_centers = _batched_lloyd(Xs, mask, init, k=ksub, n_iters=params.kmeans_n_iters)
    else:
        # Pool each cluster's residual subvectors (all subspaces), pad to a
        # fixed per-cluster budget, and train all clusters in vmapped chunks.
        lab_np = np.asarray(t_labels)
        flat = np.asarray(t_resid).reshape(nt * pq_dim, pq_len)
        row_cluster = np.repeat(lab_np, pq_dim)
        order = np.argsort(row_cluster, kind="stable")
        counts = np.bincount(row_cluster, minlength=n_lists)
        budget = max(ksub, min(int(counts.max()) if n_lists else ksub, 4096))
        n_trunc = int((counts > budget).sum())
        if n_trunc:
            logger.info(
                "ivf_pq per-cluster codebooks: %d/%d clusters exceed the %d-row "
                "training budget; a seeded random subsample of each is used "
                "(lower kmeans_trainset_fraction or raise n_lists to avoid it)",
                n_trunc,
                n_lists,
                budget,
            )
        sub_rng = np.random.default_rng(params.seed + 0x5EED)
        Xc = np.zeros((n_lists, budget, pq_len), np.float32)
        Mc = np.zeros((n_lists, budget), np.float32)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        for c in range(n_lists):
            cnt = int(counts[c])
            take = min(cnt, budget)
            sel = order[starts[c] : starts[c] + cnt]
            if cnt > budget:
                # unbiased subsample instead of the first rows (which are
                # ordered by training-set position, not representative)
                sel = sel[sub_rng.choice(cnt, size=budget, replace=False)]
            rows = flat[sel]
            Xc[c, :take] = rows
            Mc[c, :take] = 1.0
            if take < ksub and take > 0:  # ensure >= ksub seed rows
                Xc[c, take:ksub] = rows[np.arange(ksub - take) % take]
        init = jnp.asarray(Xc[:, :ksub, :])
        chunk = max(1, 128 // max(1, budget // 1024))
        parts = []
        Xc_j, Mc_j = jnp.asarray(Xc), jnp.asarray(Mc)
        for s in range(0, n_lists, chunk):
            parts.append(
                _batched_lloyd(
                    Xc_j[s : s + chunk],
                    Mc_j[s : s + chunk],
                    init[s : s + chunk],
                    k=ksub,
                    n_iters=params.kmeans_n_iters,
                )
            )
        pq_centers = jnp.concatenate(parts, axis=0)

    # -- encode + pack the full dataset (on device) -------------------------
    # Capacity-capped assignment first (spilled rows encode against their
    # FINAL list's center so ADC distances stay consistent), then encode,
    # then one scatter into the padded layout. See ivf_common.py.
    cand = ivf_common.topk_labels(ds_f32, centers, k=8)
    max_list = ivf_common.choose_max_list(cand[:, 0], n, n_lists, params.list_cap_factor)
    slot = ivf_common.assign_slots(cand, n_lists=n_lists, max_list=max_list)
    final_labels = (slot // max_list).astype(jnp.int32)
    codes_dev = _encode_all(ds_f32, final_labels, centers, rotation, pq_centers, pq_dim, per_cluster)
    codes, list_indices, list_sizes = ivf_common.scatter_rows(
        codes_dev, jnp.arange(n, dtype=jnp.int32), slot, n_lists=n_lists, max_list=max_list
    )
    rot_sqnorms = _sqnorms_for(codes, centers_rot, pq_centers, per_cluster)
    # bit-pack sub-byte widths whenever the row bitstream is byte-aligned
    # (4: two per byte; 3/5/6/7: spanning little-endian — all decoded by
    # the fused kernel's generic b-mode). Reference:
    # ivf_pq_types.hpp:129-164.
    packed = not nibble and params.pq_bits < 8 and (pq_dim * params.pq_bits) % 8 == 0
    if packed:
        codes = pack_codes_bits(codes, params.pq_bits)

    return IvfPqIndex(
        centers=centers,
        centers_rot=centers_rot,
        rotation=rotation,
        pq_centers=pq_centers,
        codes=codes,
        list_indices=list_indices,
        list_sizes=list_sizes,
        rot_sqnorms=rot_sqnorms,
        metric=metric,
        codebook_kind=params.codebook_kind,
        pq_bits=params.pq_bits,
        size=n,
        list_cap_factor=params.list_cap_factor,
        additive=nibble,
        packed=packed,
        center_rank=center_rank,
    )


def extend(index: IvfPqIndex, new_vectors, new_ids=None) -> IvfPqIndex:
    """Encode new vectors with the existing quantizers and repack
    (``ivf_pq::extend``, ``detail/ivf_pq_build.cuh:1219``)."""
    new_vectors = jnp.asarray(new_vectors)
    expects(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim, "bad extend shape")
    n_new = new_vectors.shape[0]
    if new_ids is None:
        new_ids = np.arange(index.size, index.size + n_new, dtype=np.int32)
    else:
        new_ids = np.asarray(new_ids, np.int32)

    vec_f32 = new_vectors.astype(jnp.float32)
    per_cluster = index.codebook_kind == PER_CLUSTER
    n_lists = index.n_lists

    # Existing codes keep their list assignment (their residuals were
    # encoded against that center); compact them to the front on device.
    flat_ids = index.list_indices.reshape(-1)
    n_old = int(index.size)
    keep_order = jnp.argsort(flat_ids < 0)[:n_old]
    if index.rabitq:
        # sign-bit rows stay packed (one u8 row per vector); carry the
        # per-row [C1, g] estimator scalars alongside.
        old_codes = index.codes.reshape(-1, index.codes.shape[2])[keep_order]
        old_aux = jnp.stack(
            [index.rot_sqnorms.reshape(-1), index.corrections.reshape(-1)], axis=1
        )[keep_order]
    else:
        old_codes = index.codes_unpacked().reshape(-1, index.pq_dim)[keep_order]
    old_ids = flat_ids[keep_order]
    old_l1 = (keep_order // index.max_list).astype(jnp.int32)

    new_cand = ivf_common.topk_labels(vec_f32, index.centers, k=8)
    all_ids = jnp.concatenate([old_ids, new_ids])
    # old rows never spill past their current list (their codes are
    # residuals against that center): all their candidates are that list
    old_cand = jnp.broadcast_to(old_l1[:, None], (n_old, new_cand.shape[1]))
    cand = jnp.concatenate([old_cand, new_cand], axis=0)
    n_total = n_old + n_new
    # never shrink below the current stride so old rows keep their list
    max_list = max(
        ivf_common.choose_max_list(cand[:, 0], n_total, n_lists, index.list_cap_factor),
        index.max_list,
    )
    slot = ivf_common.assign_slots(cand, n_lists=n_lists, max_list=max_list)
    final_labels = (slot // max_list).astype(jnp.int32)
    if index.rabitq:
        new_codes, new_aux = _rabitq_encode_all(
            vec_f32,
            final_labels[n_old:],
            index.centers,
            index.rotation,
            index.centers_rot,
            index.metric,
        )
        all_codes = jnp.concatenate([old_codes, new_codes], axis=0)
        all_aux = jnp.concatenate([old_aux, new_aux], axis=0)
        codes, list_indices, list_sizes = ivf_common.scatter_rows(
            all_codes, all_ids, slot, n_lists=n_lists, max_list=max_list
        )
        aux, _, _ = ivf_common.scatter_rows(
            all_aux, all_ids, slot, n_lists=n_lists, max_list=max_list
        )
        return dataclasses.replace(
            index,
            codes=codes,
            list_indices=list_indices,
            list_sizes=list_sizes,
            rot_sqnorms=aux[..., 0],
            corrections=aux[..., 1],
            size=index.size + n_new,
        )
    new_codes = _encode_all(
        vec_f32,
        final_labels[n_old:],
        index.centers,
        index.rotation,
        index.pq_centers,
        index.pq_dim,
        per_cluster,
    )
    all_codes = jnp.concatenate([old_codes, new_codes], axis=0)
    codes, list_indices, list_sizes = ivf_common.scatter_rows(
        all_codes, all_ids, slot, n_lists=n_lists, max_list=max_list
    )
    sqn = _sqnorms_for(codes, index.centers_rot, index.pq_centers, per_cluster)
    return dataclasses.replace(
        index,
        codes=pack_codes_bits(codes, index.pq_bits) if index.packed else codes,
        list_indices=list_indices,
        list_sizes=list_sizes,
        rot_sqnorms=sqn,
        size=index.size + n_new,
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "k",
        "n_probes",
        "metric",
        "per_cluster",
        "has_filter",
        "chunk_lists",
        "bf16",
    ),
)
def _ivf_pq_scan_impl(
    centers,
    centers_rot,
    rotation,
    pq_centers,
    codes,
    list_indices,
    rot_sqnorms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    per_cluster: bool,
    has_filter: bool,
    chunk_lists: int,
    bf16: bool,
):
    """Dense decode-and-score scan — the TPU replacement for the reference's
    shared-memory LUT kernel (``detail/ivf_pq_compute_similarity-inl.cuh``).

    TPUs have no fast per-lane gather, so ADC's ``sum_j LUT[j, code_j]``
    (an XLA gather) runs ~1000x off the roofline. Instead each chunk of
    lists is **decoded on the fly with a one-hot MXU matmul**
    (``onehot(codes) @ codebook`` — FLOP-heavy but systolic-array-shaped),
    scored against the rotated queries with a second matmul, masked to the
    probed lists (elementwise, fused), and fed to the fused approximate
    top-k. Probe semantics are exactly the reference's — the same
    candidate set as the LUT kernel — only the *schedule* differs.
    Measured at SIFT-1M shapes this is ~1000x faster than the gather
    formulation on TPU v5e.
    """
    nq, d = queries.shape
    qf = queries.astype(jnp.float32)

    with obs.span("ivf_pq.search.coarse_probe", nq=nq, n_probes=n_probes) as sp:
        # coarse scores double as the probe selector AND the q.c_l term
        q_dot_c = qf @ centers.T  # [nq, n_lists]
        if metric == DistanceType.InnerProduct:
            coarse = -q_dot_c
        else:
            c_norm = jnp.sum(centers * centers, axis=1)
            coarse = c_norm[None, :] - 2.0 * q_dot_c
        n_lists = centers.shape[0]
        probed = jnp.zeros((nq, n_lists), bool)
        if n_probes < n_lists:
            _, probes = select_k(coarse, n_probes, select_min=True)
            probed = probed.at[jnp.arange(nq)[:, None], probes].set(True)
        else:
            probed = jnp.ones((nq, n_lists), bool)
        sp.sync(probed)

    q_rot = qf @ rotation.T  # [nq, rot_dim]
    with obs.span("ivf_pq.search.pq_scan", nq=nq, k=k) as sp:
        return sp.sync(
            pq_scan_core(
                pq_centers, codes, list_indices, rot_sqnorms, q_rot, q_dot_c,
                probed, filter_bits,
                k=k, metric=metric, per_cluster=per_cluster, has_filter=has_filter,
                chunk_lists=chunk_lists, bf16=bf16,
            )
        )


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "per_cluster", "has_filter", "chunk_lists", "bf16"),
)
def pq_scan_core(
    pq_centers,
    codes,
    list_indices,
    rot_sqnorms,
    q_rot,
    q_dot_c,
    probed,
    filter_bits,
    *,
    k: int,
    metric: DistanceType,
    per_cluster: bool,
    has_filter: bool,
    chunk_lists: int,
    bf16: bool,
):
    """Decode-and-score over a (possibly LOCAL slice of the) list set with
    a precomputed probe mask — the shardable core of the dense PQ scan,
    mirroring :func:`raft_tpu.neighbors.ivf_flat.flat_scan_core`:
    ``codes/list_indices/rot_sqnorms/q_dot_c/probed`` may all be sliced to
    a shard's lists (list_indices carry GLOBAL row ids, so per-shard
    results merge with one allgather + k-way merge)."""
    nq = q_rot.shape[0]
    n_lists, max_list, pq_dim = codes.shape
    ksub = pq_centers.shape[-2]
    rot_dim = q_rot.shape[1]

    cdtype = jnp.bfloat16 if bf16 else jnp.float32
    qc = q_rot.astype(cdtype)
    books = pq_centers.astype(cdtype)

    n_chunks = n_lists // chunk_lists
    G, M = chunk_lists, max_list
    codes_c = codes.reshape(n_chunks, G, M, pq_dim)
    ids_c = list_indices.reshape(n_chunks, G * M)
    sqn_c = rot_sqnorms.reshape(n_chunks, G * M)
    probed_c = probed.reshape(nq, n_chunks, G)
    # 2*q.c_l per (query, list): reuses the coarse matmul (q.c is metric-
    # invariant under the orthonormal rotation, so q_rot.c_rot == q.c)
    qdotc_c = jnp.moveaxis(q_dot_c.reshape(nq, n_chunks, G), 1, 0)
    if per_cluster:
        books_c = books.reshape(n_chunks, G, ksub, -1)

    init = (
        jnp.full((nq, k), -jnp.inf, jnp.float32),
        jnp.zeros((nq, k), jnp.int32),  # flat slot ids
    )

    def body(carry, inp):
        acc_v, acc_i = carry
        if per_cluster:
            cod, ids, sqn, pmask, qdc, bks, ci = inp
            onehot = (
                cod[:, :, :, None].astype(jnp.int32)
                == jnp.arange(ksub, dtype=jnp.int32)[None, None, None, :]
            ).astype(cdtype)  # [G, M, pq_dim, ksub]
            resid = jnp.einsum(
                "gmjc,gcs->gmjs", onehot, bks, preferred_element_type=cdtype
            )
        else:
            cod, ids, sqn, pmask, qdc, ci = inp
            codf = cod.reshape(G * M, pq_dim)
            onehot = (
                codf[:, :, None].astype(jnp.int32)
                == jnp.arange(ksub, dtype=jnp.int32)[None, None, :]
            ).astype(cdtype)
            resid = jnp.einsum(
                "tjc,jcs->tjs", onehot, books, preferred_element_type=cdtype
            )
        # score(q, x) for L2: 2 q_rot.(c_rot+r) - ||c_rot+r||^2
        #   = 2 q_rot.r  +  2 q.c_l  -  sqn   (sqn precomputed at build);
        # for IP: q_rot.r + q.c_l. The residual matmul is the einsum
        # output's only consumer, keeping the decode inside one fusion.
        # Masking is ADDITIVE on the small axes (a [G*M] pad penalty and an
        # [nq, G] probe penalty, broadcast into the epilogue) — a boolean
        # [nq, G*M] keep-mask defeats XLA's matmul fusion and costs ~10x.
        dots_r = (qc @ resid.reshape(G * M, rot_dim).T).astype(jnp.float32)
        pad_pen = jnp.where(ids >= 0, 0.0, -jnp.inf)  # [G*M]
        if has_filter:
            word = filter_bits[jnp.clip(ids, 0, None) // 32]
            bit = (word >> (jnp.clip(ids, 0, None) % 32).astype(jnp.uint32)) & 1
            pad_pen = jnp.where(bit == 1, pad_pen, -jnp.inf)
        if metric == DistanceType.InnerProduct:
            probe_pen = jnp.where(pmask, qdc, -jnp.inf)  # [nq, G]
            score = (
                dots_r
                + jnp.broadcast_to(probe_pen[:, :, None], (nq, G, M)).reshape(nq, G * M)
                + pad_pen[None, :]
            )
        else:
            probe_pen = jnp.where(pmask, 2.0 * qdc, -jnp.inf)
            score = (
                2.0 * dots_r
                - (sqn - pad_pen)[None, :]
                + jnp.broadcast_to(probe_pen[:, :, None], (nq, G, M)).reshape(nq, G * M)
            )
        # shortlist 2k per chunk (see _ivf_flat_scan_impl)
        kk = min(max(2 * k, 16), G * M)
        v, i = lax.approx_max_k(score, kk, recall_target=0.99)
        nv, ni = lax.top_k(jnp.concatenate([acc_v, v], axis=1), k)
        na = jnp.take_along_axis(
            jnp.concatenate([acc_i, i + ci * (G * M)], axis=1), ni, axis=1
        )
        return (nv, na), None

    xs = (codes_c, ids_c, sqn_c, jnp.moveaxis(probed_c, 1, 0), qdotc_c)
    if per_cluster:
        xs = xs + (books_c,)
    xs = xs + (jnp.arange(n_chunks, dtype=jnp.int32),)
    (vals, slots), _ = lax.scan(body, init, xs)

    idx = list_indices.reshape(-1)[slots.reshape(-1)].reshape(nq, k)
    idx = jnp.where(jnp.isfinite(vals), idx, -1)
    if metric == DistanceType.InnerProduct:
        out = vals
    else:
        qn = jnp.sum(q_rot * q_rot, axis=1)
        out = jnp.maximum(qn[:, None] - vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)
    return out, idx


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "per_cluster", "has_filter", "lut_dtype"),
)
def _ivf_pq_search_impl(
    centers,
    centers_rot,
    rotation,
    pq_centers,
    codes,
    list_indices,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    per_cluster: bool,
    has_filter: bool,
    lut_dtype,
):
    nq, d = queries.shape
    n_lists = centers.shape[0]
    pq_dim = codes.shape[2]
    qf = queries.astype(jnp.float32)

    # -- coarse: nearest centers (select_clusters, ivf_pq_search.cuh:67) ----
    q_dot_c = qf @ centers.T
    if metric == DistanceType.InnerProduct:
        coarse = -q_dot_c
    else:
        c_norm = jnp.sum(centers * centers, axis=1)
        coarse = c_norm[None, :] - 2.0 * q_dot_c
    _, probes = select_k(coarse, n_probes, select_min=True)  # [nq, n_probes]

    q_rot = qf @ rotation.T  # [nq, rot_dim]
    q_sub = q_rot.reshape(nq, pq_dim, -1)  # [nq, pq_dim, pq_len]

    select_min = metric != DistanceType.InnerProduct
    worst = jnp.float32(worst_value(jnp.float32, select_min))
    init = (
        jnp.full((nq, k), worst, jnp.float32),
        jnp.full((nq, k), -1, jnp.int32),
    )

    pqc_norm = jnp.sum(pq_centers * pq_centers, axis=-1)  # [pq_dim|n_lists, ksub]

    def body(carry, p):
        acc_v, acc_i = carry
        list_id = probes[:, p]  # [nq]
        codes_p = codes[list_id]  # [nq, max_list, pq_dim]
        ids_p = list_indices[list_id]  # [nq, max_list]

        # -- LUT build (compute_similarity kernel's smem LUT) ---------------
        if metric == DistanceType.InnerProduct:
            # score = q . c  +  sum_j q_sub[j] . pq_c[j, code_j]
            if per_cluster:
                pqc = pq_centers[list_id]  # [nq, ksub, pq_len]
                lut = jnp.einsum("npl,nkl->npk", q_sub, pqc, preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST)
            else:
                lut = jnp.einsum("npl,pkl->npk", q_sub, pq_centers, preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST)
            base = jnp.take_along_axis(q_dot_c, list_id[:, None], axis=1)[:, 0]
        else:
            # dist = sum_j || (q_rot - c_rot)[j] - pq_c[j, code_j] ||^2
            diff = q_sub - centers_rot[list_id].reshape(nq, pq_dim, -1)
            dn = jnp.sum(diff * diff, axis=-1)  # [nq, pq_dim]
            if per_cluster:
                pqc = pq_centers[list_id]
                dots = jnp.einsum("npl,nkl->npk", diff, pqc, preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST)
                cn = pqc_norm[list_id][:, None, :]  # [nq, 1, ksub]
            else:
                dots = jnp.einsum("npl,pkl->npk", diff, pq_centers, preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST)
                cn = pqc_norm[None, :, :]
            lut = dn[:, :, None] - 2.0 * dots + cn  # [nq, pq_dim, ksub]
            base = jnp.float32(0.0)

        if lut_dtype != "float32":
            lut = lut.astype(lut_dtype).astype(jnp.float32)

        # -- apply LUT to codes (the scan part of the similarity kernel) ----
        codes_t = jnp.transpose(codes_p, (0, 2, 1)).astype(jnp.int32)  # [nq, pq_dim, max_list]
        gathered = jnp.take_along_axis(lut, codes_t, axis=2)  # [nq, pq_dim, max_list]
        dist = jnp.sum(gathered, axis=1)  # [nq, max_list]
        if metric == DistanceType.InnerProduct:
            dist = dist + base[:, None]

        valid = ids_p >= 0
        if has_filter:
            word = filter_bits[jnp.clip(ids_p, 0, None) // 32]
            bit = (word >> (jnp.clip(ids_p, 0, None) % 32).astype(jnp.uint32)) & 1
            valid = valid & (bit == 1)
        dist = jnp.where(valid, dist, worst)
        ids_masked = jnp.where(valid, ids_p, -1)
        return running_merge(acc_v, acc_i, dist, ids_masked, select_min=select_min), None

    (vals, idx), _ = lax.scan(body, init, jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "has_filter", "chunk_lists"),
)
def _ivf_rabitq_scan_impl(
    centers,
    rotation,
    codes,
    corrections,
    list_indices,
    rot_sqnorms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    has_filter: bool,
    chunk_lists: int,
):
    """Dense RaBitQ scan: the same probe/schedule skeleton as
    :func:`_ivf_pq_scan_impl` with the one-hot decode matmul replaced by a
    single sign-bit matmul per chunk (see :func:`_rabitq_encode_chunk` for
    the estimator algebra)."""
    nq, d = queries.shape
    qf = queries.astype(jnp.float32)

    with obs.span("ivf_pq.search.coarse_probe", nq=nq, n_probes=n_probes) as sp:
        q_dot_c = qf @ centers.T  # [nq, n_lists]
        if metric == DistanceType.InnerProduct:
            coarse = -q_dot_c
        else:
            c_norm = jnp.sum(centers * centers, axis=1)
            coarse = c_norm[None, :] - 2.0 * q_dot_c
        n_lists = centers.shape[0]
        probed = jnp.zeros((nq, n_lists), bool)
        if n_probes < n_lists:
            _, probes = select_k(coarse, n_probes, select_min=True)
            probed = probed.at[jnp.arange(nq)[:, None], probes].set(True)
        else:
            probed = jnp.ones((nq, n_lists), bool)
        sp.sync(probed)

    q_rot = qf @ rotation.T  # [nq, rot_dim]
    with obs.span("ivf_pq.search.rabitq_xla", nq=nq, k=k) as sp:
        return sp.sync(
            rabitq_scan_core(
                codes, corrections, list_indices, rot_sqnorms, q_rot, q_dot_c,
                probed, filter_bits,
                k=k, metric=metric, has_filter=has_filter, chunk_lists=chunk_lists,
            )
        )


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "has_filter", "chunk_lists")
)
def rabitq_scan_core(
    codes,
    corrections,
    list_indices,
    rot_sqnorms,
    q_rot,
    q_dot_c,
    probed,
    filter_bits,
    *,
    k: int,
    metric: DistanceType,
    has_filter: bool,
    chunk_lists: int,
):
    """Shardable RaBitQ scan core (mirrors :func:`pq_scan_core`): per
    chunk, unpack the sign bits and evaluate the estimator as ONE
    [nq, rot_dim] x [rot_dim, G*M] matmul plus an elementwise epilogue.
    Keeps the maximize-score convention so the approx-top-k shortlist,
    pad/probe penalties, and the distance epilogue are shared with the PQ
    scan verbatim:

        mscore = coef*(q.c_l) + g*(b.q_rot - sum(q_rot)/2) - C1
        L2 out = max(||q||^2 - mscore, 0)      IP out = mscore
    """
    nq = q_rot.shape[0]
    n_lists, max_list, bpr = codes.shape
    D = q_rot.shape[1]

    sq = jnp.sum(q_rot, axis=1)  # [nq]
    coef = 1.0 if metric == DistanceType.InnerProduct else 2.0

    n_chunks = n_lists // chunk_lists
    G, M = chunk_lists, max_list
    codes_c = codes.reshape(n_chunks, G * M, bpr)
    ids_c = list_indices.reshape(n_chunks, G * M)
    c1_c = rot_sqnorms.reshape(n_chunks, G * M)
    g_c = corrections.reshape(n_chunks, G * M)
    probed_c = probed.reshape(nq, n_chunks, G)
    qdotc_c = jnp.moveaxis(q_dot_c.reshape(nq, n_chunks, G), 1, 0)

    init = (
        jnp.full((nq, k), -jnp.inf, jnp.float32),
        jnp.zeros((nq, k), jnp.int32),  # flat slot ids
    )

    def body(carry, inp):
        acc_v, acc_i = carry
        cod, ids, c1, gg, pmask, qdc, ci = inp
        # sign bits as f32 {0,1}: the bit dot is exact in f32 (each term is
        # a masked add of a query lane), matching the fused kernel's
        # arithmetic bit for bit.
        bits = unpack_codes_bits(cod, 1, D).astype(jnp.float32)  # [G*M, D]
        bq = jax.lax.dot_general(
            q_rot, bits,
            (((1,), (1,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # [nq, G*M]
        part = gg[None, :] * (bq - 0.5 * sq[:, None]) - c1[None, :]
        pad_pen = jnp.where(ids >= 0, 0.0, -jnp.inf)  # [G*M]
        if has_filter:
            word = filter_bits[jnp.clip(ids, 0, None) // 32]
            bit = (word >> (jnp.clip(ids, 0, None) % 32).astype(jnp.uint32)) & 1
            pad_pen = jnp.where(bit == 1, pad_pen, -jnp.inf)
        probe_pen = jnp.where(pmask, coef * qdc, -jnp.inf)  # [nq, G]
        score = (
            part
            + jnp.broadcast_to(probe_pen[:, :, None], (nq, G, M)).reshape(nq, G * M)
            + pad_pen[None, :]
        )
        kk = min(max(2 * k, 16), G * M)
        v, i = lax.approx_max_k(score, kk, recall_target=0.99)
        nv, ni = lax.top_k(jnp.concatenate([acc_v, v], axis=1), k)
        na = jnp.take_along_axis(
            jnp.concatenate([acc_i, i + ci * (G * M)], axis=1), ni, axis=1
        )
        return (nv, na), None

    xs = (
        codes_c, ids_c, c1_c, g_c, jnp.moveaxis(probed_c, 1, 0), qdotc_c,
        jnp.arange(n_chunks, dtype=jnp.int32),
    )
    (vals, slots), _ = lax.scan(body, init, xs)

    idx = list_indices.reshape(-1)[slots.reshape(-1)].reshape(nq, k)
    idx = jnp.where(jnp.isfinite(vals), idx, -1)
    if metric == DistanceType.InnerProduct:
        out = vals
    else:
        qn = jnp.sum(q_rot * q_rot, axis=1)
        out = jnp.maximum(qn[:, None] - vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)
    return out, idx


@functools.partial(
    jax.jit, static_argnames=("k", "n_probes", "metric", "has_filter")
)
def _ivf_rabitq_probe_impl(
    centers,
    rotation,
    codes,
    corrections,
    list_indices,
    rot_sqnorms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    has_filter: bool,
):
    """Probe-at-a-time RaBitQ estimator (memory-lean analog of
    :func:`_ivf_pq_search_impl`): gathers one list per query per step and
    evaluates the estimator with a per-query bit dot."""
    nq, d = queries.shape
    qf = queries.astype(jnp.float32)
    bpr = codes.shape[2]
    D = bpr * 8

    q_dot_c = qf @ centers.T
    if metric == DistanceType.InnerProduct:
        coarse = -q_dot_c
    else:
        c_norm = jnp.sum(centers * centers, axis=1)
        coarse = c_norm[None, :] - 2.0 * q_dot_c
    _, probes = select_k(coarse, n_probes, select_min=True)  # [nq, n_probes]

    q_rot = qf @ rotation.T  # [nq, D]
    sq = jnp.sum(q_rot, axis=1)  # [nq]
    qn = jnp.sum(q_rot * q_rot, axis=1)
    coef = 1.0 if metric == DistanceType.InnerProduct else 2.0

    select_min = metric != DistanceType.InnerProduct
    worst = jnp.float32(worst_value(jnp.float32, select_min))
    init = (
        jnp.full((nq, k), worst, jnp.float32),
        jnp.full((nq, k), -1, jnp.int32),
    )

    def body(carry, p):
        acc_v, acc_i = carry
        list_id = probes[:, p]  # [nq]
        cod = codes[list_id]  # [nq, max_list, bpr]
        ids_p = list_indices[list_id]  # [nq, max_list]
        c1 = rot_sqnorms[list_id]
        gg = corrections[list_id]
        qdc = jnp.take_along_axis(q_dot_c, list_id[:, None], axis=1)  # [nq, 1]

        bits = unpack_codes_bits(cod, 1, D).astype(jnp.float32)  # [nq, max_list, D]
        bq = jnp.einsum(
            "nd,nmd->nm", q_rot, bits,
            preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST,
        )
        mscore = coef * qdc + gg * (bq - 0.5 * sq[:, None]) - c1
        if metric == DistanceType.InnerProduct:
            dist = mscore
        else:
            dist = jnp.maximum(qn[:, None] - mscore, 0.0)

        valid = ids_p >= 0
        if has_filter:
            word = filter_bits[jnp.clip(ids_p, 0, None) // 32]
            bit = (word >> (jnp.clip(ids_p, 0, None) % 32).astype(jnp.uint32)) & 1
            valid = valid & (bit == 1)
        dist = jnp.where(valid, dist, worst)
        ids_masked = jnp.where(valid, ids_p, -1)
        return running_merge(acc_v, acc_i, dist, ids_masked, select_min=select_min), None

    (vals, idx), _ = lax.scan(body, init, jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


def scan_chunk_lists(n_lists: int, max_list: int) -> int:
    """Chunk size for the decode scan: ~256k rows (decode temporaries are
    [rows, pq_dim, ksub]-shaped, so PQ chunks stay smaller than the flat
    scan's), constrained to divide n_lists."""
    g = max(1, 262144 // max(max_list, 1))
    while n_lists % g:
        g -= 1
    return g


def scan_bf16(lut_dtype) -> bool:
    """Reduced-precision decode/score is a TPU-only mode (the CPU dot
    thunk has no bf16 support)."""
    return (
        lut_dtype is not None
        and jnp.dtype(lut_dtype) == jnp.dtype(jnp.bfloat16)
        and jax.default_backend() == "tpu"
    )



def _fused_code_layout(index) -> tuple:
    """(code_mode, ksub) the fused kernel would use for this index — the
    ONE mapping shared by the VMEM feasibility gate and the fused call
    (drift here would make auto-mode model feasibility with the wrong
    layout)."""
    if index.additive:
        return "nib8", 16
    if index.packed and index.pq_bits == 4:
        return "p4", 16
    if index.packed:
        return f"b{index.pq_bits}", index.ksub
    return "u8", index.ksub


def search(
    index: IvfPqIndex,
    queries,
    k: int,
    params: Optional[IvfPqSearchParams] = None,
    prefilter: Optional[Bitset] = None,
    query_batch: int = 1024,
    mode: str = "auto",
    res: Optional[Resources] = None,
    dataset=None,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """ADC search over probed lists (``ivf_pq::search``,
    ``detail/ivf_pq_search.cuh:588``). Returns best-first
    ``(distances [nq, k] f32, indices [nq, k] i32)``; unfilled slots get
    id -1. Distances are PQ approximations — pass the raw ``dataset`` and
    the default ``params.refine_ratio=8`` re-ranks ``k * refine_ratio``
    candidates with exact distances (:func:`raft_tpu.neighbors.refine`),
    the measured out-of-box Pareto point (~15.7k QPS @ 0.947 on 1M x 128).

    ``mode``: ``"fused"`` = the Pallas fused probed-list scan (DMAs only
    the probed CODE blocks — the work-proportional TPU fast path, see
    :mod:`raft_tpu.ops.pallas.pq_scan`; needs per_subspace codebooks and
    a supported metric; any ksub <= 256 including the default 8-bit
    config, wide books via column-chunked decode); ``"scan"`` =
    dense decode-and-score over list chunks (see
    :func:`_ivf_pq_scan_impl` — same probed candidate set, selected with
    the fused APPROXIMATE top-k so results can differ slightly from the
    deterministic probe path); ``"probe"`` = per-probe LUT gather (the
    literal analog of the reference's kernel schedule; better for
    single-digit query batches); ``"auto"`` picks fused on TPU when
    eligible for batches >= 128, else scan/probe by batch size.

    With observability on (:mod:`raft_tpu.obs`, ``RAFT_TPU_OBS=1``) the
    call records a sync-aware ``ivf_pq.search`` span with per-phase
    children (``coarse_probe`` / ``pq_scan`` / ``probe_scan`` /
    ``fused`` / ``refine``) plus counters for mode, n_probes, LUT dtype
    and refine candidates; disabled (the default) it costs one flag
    check."""
    if not obs.is_enabled():
        return _search_dispatch(
            index, queries, k, params, prefilter, query_batch, mode, res, dataset, **kwargs
        )
    with obs.span("ivf_pq.search", k=k, nq=int(np.shape(queries)[0])) as sp:
        return sp.sync(
            _search_dispatch(
                index, queries, k, params, prefilter, query_batch, mode, res, dataset, **kwargs
            )
        )


def _search_dispatch(
    index: IvfPqIndex,
    queries,
    k: int,
    params: Optional[IvfPqSearchParams],
    prefilter: Optional[Bitset],
    query_batch: int,
    mode: str,
    res: Optional[Resources],
    dataset,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """Mode routing + query batching behind :func:`search` (split out so
    the observability-off path costs a single flag check)."""
    ensure_resources(res)
    if params is None:
        params = IvfPqSearchParams(**kwargs)
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "bad query shape")
    expects(k >= 1, "k must be >= 1")
    if dataset is not None and params.refine_ratio > 1:
        from raft_tpu.neighbors.refine import check_refine_dataset, refine

        # Validate the dataset/index agreement BEFORE the scan runs: a
        # short dataset used to surface only as an out-of-bounds gather
        # deep inside refine's jit.
        check_refine_dataset(dataset, index.size, "ivf_pq")
        inner = dataclasses.replace(params, refine_ratio=1)
        kk = min(k * params.refine_ratio, index.size)
        _, cand = search(
            index, queries, kk, inner,
            prefilter=prefilter, query_batch=query_batch, mode=mode, res=res,
        )
        if obs.is_enabled():
            obs.observe("ivf_pq.search.refine_candidates_per_query", float(kk))
        with obs.span("ivf_pq.search.refine", k=k, candidates=int(kk)) as sp:
            return sp.sync(
                refine(dataset, queries, cand, k, metric=resolve_metric(index.metric))
            )
    if prefilter is not None:
        expects(prefilter.size >= index.size, "prefilter smaller than index")
    n_probes = min(params.n_probes, index.n_lists)
    nq = queries.shape[0]
    filter_bits = prefilter.bits if prefilter is not None else None

    if index.rabitq:
        return _rabitq_modes(
            index, queries, k, params, filter_bits, n_probes, query_batch, mode
        )

    # every per_subspace width is fused-eligible: ksub <= 64 decodes in one
    # multi-hot pass, 128/256 (the reference's DEFAULT pq_bits=8 config)
    # via column-chunked decode — the work-proportional answer to the LUT
    # cost the reference solves with fp8 LUTs (detail/ivf_pq_fp_8bit.cuh)
    fused_ok = (
        index.codebook_kind == PER_SUBSPACE
        and (index.additive or index.ksub <= 256)
        and index.metric in _SUPPORTED
    )
    if fused_ok:
        # very long lists with wide codebooks cannot fit even one decode
        # group in VMEM — auto must route them to the scan path
        from raft_tpu.ops.pallas.pq_scan import decode_feasible

        _cm, _ks = _fused_code_layout(index)
        fused_ok = decode_feasible(
            m=index.codes.shape[1], code_mode=_cm, ksub=_ks,
            bpr=index.codes.shape[2],
            qt=params.fused_qt, k=k, rot_dim=index.rotation.shape[0],
            merge=params.fused_merge,
        )
    # the fused kernel's LUT is bf16 by construction; an explicit float32
    # request is a precision demand auto must honor via the scan path
    wants_f32_lut = (
        params.lut_dtype is not None
        and jnp.dtype(params.lut_dtype) == jnp.dtype(jnp.float32)
    )
    requested_mode = mode
    if mode == "auto":
        from raft_tpu import plan as _plan

        on_tpu = jax.default_backend() == "tpu"
        if _plan.is_enabled():
            mode = _plan.plan_search_mode(
                "ivf_pq", nq, on_tpu=on_tpu, fused_ok=fused_ok,
                wants_f32_lut=wants_f32_lut,
            ).choice
        elif nq >= 128 and on_tpu and fused_ok and not wants_f32_lut:
            mode = "fused"
        else:
            mode = "scan" if nq >= 128 else "probe"
    expects(
        mode in ("scan", "probe", "fused"), "mode must be auto|scan|probe|fused, got %r", mode
    )
    if obs.is_enabled():
        lut = jnp.dtype(params.lut_dtype).name if params.lut_dtype is not None else "default"
        obs.inc("ivf_pq.search.calls", mode=mode, lut=lut)
        obs.inc("ivf_pq.search.queries", float(nq))
        obs.observe("ivf_pq.search.n_probes", float(n_probes))

    if mode == "fused":
        from raft_tpu.ops.pallas.pq_scan import ivf_pq_fused_search, vmem_decode_cols

        if wants_f32_lut:
            # auto routes f32-LUT requests to the scan path; an EXPLICIT
            # mode="fused" overrides that, so say so instead of silently
            # dropping the precision request (Python's warning registry
            # dedups this to once per process)
            warnings.warn(
                "mode='fused' computes the LUT in bf16 by construction; the "
                "explicit lut_dtype=float32 request is ignored (use "
                "mode='scan' or mode='auto' to honor it)",
                UserWarning,
                stacklevel=2,
            )
        expects(
            fused_ok,
            "fused mode needs per_subspace + (ksub<=256 | nibble) + a "
            "VMEM-feasible list length (long lists with wide codebooks "
            "must use mode='scan' or more n_lists)",
        )
        code_mode, ksub = _fused_code_layout(index)
        # nib8: additive nibble books, W columns = [A-hot | B-hot] per
        # byte; p4: W's natural [nq, pq_dim, 16] flattening is exactly
        # the kernel's per-byte [lo-hot | hi-hot] order; b3/5/6/7:
        # spanning bitstream peeled from (low, high) byte pairs, W in
        # natural j-major order
        books = nibble_books(index.pq_centers) if index.additive else index.pq_centers
        rank = index.center_rank
        group = params.fused_group
        if rank is None:
            # pre-v4 index: lists are in arbitrary k-means order — compute
            # a rank for tile coherence, single-list DMA units for safety
            from raft_tpu.neighbors.ivf_flat import _legacy_rank_cache

            rank = _legacy_rank_cache(index.centers)
            group = 1
        group = max(1, min(group, index.n_lists))
        while index.n_lists % group:
            group -= 1

        def run_fused(qc):
            return ivf_pq_fused_search(
                index.centers,
                index.centers_rot,
                rank,
                index.rotation,
                books,
                index.codes,
                index.list_indices,
                index.rot_sqnorms,
                qc,
                filter_bits,
                k=k,
                n_probes=n_probes,
                metric=index.metric,
                qt=params.fused_qt,
                probe_factor=params.fused_probe_factor,
                group=group,
                has_filter=filter_bits is not None,
                merge=params.fused_merge,
                code_mode=code_mode,
                ksub=ksub,
                extract_every=params.fused_extract_every,
                # VMEM-model cap: wide-codebook decode chunks must fit
                # the ~16 MB scoped-VMEM stack at any list length. The
                # budget is derived from the kernel's fixed residents at
                # THIS shape (vmem_model.pq_decode_chunk_budget), so the
                # exact qt/k/group/merge config sharpens the cap.
                decode_cols=vmem_decode_cols(
                    params.fused_decode_cols,
                    m=index.codes.shape[1],
                    code_mode=code_mode,
                    ksub=ksub,
                    bpr=index.codes.shape[2],
                    qt=params.fused_qt,
                    k=k,
                    g_lists=group,
                    rot_dim=index.rotation.shape[0],
                    merge=params.fused_merge,
                ),
                interpret=jax.default_backend() != "tpu",
            )

        from raft_tpu.neighbors.ivf_flat import _batched_search

        try:
            # host-level fault point: fires even when the jitted kernel
            # program below is cache-hit
            _faults.fire("pallas.pq_scan", nq=int(nq))
            with obs.span("ivf_pq.search.fused", nq=nq, k=k, n_probes=n_probes) as sp:
                # sync inside the try: runtime kernel failures surface at
                # block_until_ready and must reach the fallback handler
                return sp.sync(_batched_search(run_fused, queries, query_batch))
        except _fallback.FALLBACK_ERRORS as e:
            if requested_mode == "fused":
                raise  # the caller pinned the engine; do not mask
            _fallback.record_fallback("ivf_pq", e)
            mode = "scan"  # identical candidate set, decode-scan engine

    if mode == "scan":
        g = scan_chunk_lists(index.n_lists, index.max_list)
        codes_u = index.codes_unpacked()
        out_v, out_i = [], []
        for start in range(0, nq, query_batch):
            qc = queries[start : start + query_batch]
            bpad = 0
            if qc.shape[0] < query_batch and nq > query_batch:
                bpad = query_batch - qc.shape[0]
                qc = jnp.pad(qc, ((0, bpad), (0, 0)))
            v, i = _ivf_pq_scan_impl(
                index.centers,
                index.centers_rot,
                index.rotation,
                index.pq_centers,
                codes_u,
                index.list_indices,
                index.rot_sqnorms,
                qc.astype(jnp.float32),
                filter_bits,
                k=k,
                n_probes=n_probes,
                metric=index.metric,
                per_cluster=index.codebook_kind == PER_CLUSTER,
                has_filter=filter_bits is not None,
                chunk_lists=g,
                bf16=scan_bf16(params.lut_dtype),
            )
            if bpad:
                v, i = v[:-bpad], i[:-bpad]
            out_v.append(v)
            out_i.append(i)
        if len(out_v) == 1:
            return out_v[0], out_i[0]
        return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)

    # Probe mode gathers [qb, pq_dim, max_list] f32 LUT lanes per step; cap
    # the batch so that temporary stays under ~512 MB (an uncapped 1024-
    # query batch against 4k-row lists allocates gigabytes per probe and
    # can OOM the chip — the scan path is the right tool there).
    per_q = max(1, index.pq_dim * index.max_list * 4)
    query_batch = max(1, min(query_batch, (512 << 20) // per_q))

    codes_u = index.codes_unpacked()
    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qc = queries[start : start + query_batch]
        bpad = 0
        if qc.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qc.shape[0]
            qc = jnp.pad(qc, ((0, bpad), (0, 0)))
        # the per-probe LUT gather fuses coarse probing and the scan in one
        # jitted program — the span covers both phases
        with obs.span("ivf_pq.search.probe_scan", nq=qc.shape[0], k=k) as sp:
            v, i = sp.sync(
                _ivf_pq_search_impl(
                    index.centers,
                    index.centers_rot,
                    index.rotation,
                    index.pq_centers,
                    codes_u,
                    index.list_indices,
                    qc,
                    filter_bits,
                    k=k,
                    n_probes=n_probes,
                    metric=index.metric,
                    per_cluster=index.codebook_kind == PER_CLUSTER,
                    has_filter=filter_bits is not None,
                    lut_dtype=jnp.dtype(params.lut_dtype or jnp.float32).name,
                )
            )
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


def _rabitq_modes(
    index: IvfPqIndex,
    queries,
    k: int,
    params: IvfPqSearchParams,
    filter_bits,
    n_probes: int,
    query_batch: int,
    mode: str,
) -> Tuple[jax.Array, jax.Array]:
    """Mode routing for ``pq_kind="rabitq"`` — same fused/scan/probe
    trio as the PQ dispatch, backed by the rabitq estimator paths (the
    refine/prefilter/batching plumbing upstream is shared verbatim)."""
    from raft_tpu.ops.pallas.rabitq_scan import (
        ivf_rabitq_fused_search,
        rabitq_feasible,
        vmem_decode_rows,
    )

    nq = queries.shape[0]
    fused_ok = index.metric in _SUPPORTED and rabitq_feasible(
        m=index.max_list,
        bpr=index.codes.shape[2],
        qt=params.fused_qt,
        k=k,
        g_lists=params.fused_group,
        rot_dim=index.rot_dim,
        merge=params.fused_merge,
    )
    requested_mode = mode
    if mode == "auto":
        from raft_tpu import plan as _plan

        on_tpu = jax.default_backend() == "tpu"
        if _plan.is_enabled():
            mode = _plan.plan_search_mode(
                "ivf_pq", nq, on_tpu=on_tpu, fused_ok=fused_ok,
            ).choice
        elif nq >= 128 and on_tpu and fused_ok:
            mode = "fused"
        else:
            mode = "scan" if nq >= 128 else "probe"
    expects(
        mode in ("scan", "probe", "fused"), "mode must be auto|scan|probe|fused, got %r", mode
    )
    if obs.is_enabled():
        obs.inc("ivf_pq.search.calls", mode=mode, lut="rabitq")
        obs.inc("ivf_pq.search.queries", float(nq))
        obs.inc("ivf_pq.search.rabitq.queries", float(nq))
        obs.observe("ivf_pq.search.n_probes", float(n_probes))

    if mode == "fused":
        expects(
            fused_ok,
            "fused rabitq mode needs a supported metric and a VMEM-feasible "
            "list length (use mode='scan' or more n_lists)",
        )
        rank = index.center_rank
        group = params.fused_group
        if rank is None:
            from raft_tpu.neighbors.ivf_flat import _legacy_rank_cache

            rank = _legacy_rank_cache(index.centers)
            group = 1
        group = max(1, min(group, index.n_lists))
        while index.n_lists % group:
            group -= 1

        def run_fused(qc):
            return ivf_rabitq_fused_search(
                index.centers,
                index.centers_rot,
                rank,
                index.rotation,
                index.codes,
                index.list_indices,
                index.rot_sqnorms,
                index.corrections,
                qc,
                filter_bits,
                k=k,
                n_probes=n_probes,
                metric=index.metric,
                qt=params.fused_qt,
                probe_factor=params.fused_probe_factor,
                group=group,
                has_filter=filter_bits is not None,
                merge=params.fused_merge,
                extract_every=params.fused_extract_every,
                # VMEM-model cap on rows decoded per pass (the rabitq
                # analog of pq_scan's decode_cols chunking).
                decode_rows=vmem_decode_rows(
                    m=index.max_list,
                    bpr=index.codes.shape[2],
                    qt=params.fused_qt,
                    k=k,
                    g_lists=group,
                    rot_dim=index.rot_dim,
                    merge=params.fused_merge,
                ),
                interpret=jax.default_backend() != "tpu",
            )

        from raft_tpu.neighbors.ivf_flat import _batched_search

        try:
            # same host-level fault seam as the PQ fused path: the robust
            # layer's chaos hooks cover both kernels with one point
            _faults.fire("pallas.pq_scan", nq=int(nq))
            with obs.span("ivf_pq.search.rabitq_scan", nq=nq, k=k, n_probes=n_probes) as sp:
                return sp.sync(_batched_search(run_fused, queries, query_batch))
        except _fallback.FALLBACK_ERRORS as e:
            if requested_mode == "fused":
                raise  # the caller pinned the engine; do not mask
            _fallback.record_fallback("ivf_pq", e)
            mode = "scan"

    if mode == "scan":
        g = scan_chunk_lists(index.n_lists, index.max_list)
        out_v, out_i = [], []
        for start in range(0, nq, query_batch):
            qc = queries[start : start + query_batch]
            bpad = 0
            if qc.shape[0] < query_batch and nq > query_batch:
                bpad = query_batch - qc.shape[0]
                qc = jnp.pad(qc, ((0, bpad), (0, 0)))
            v, i = _ivf_rabitq_scan_impl(
                index.centers,
                index.rotation,
                index.codes,
                index.corrections,
                index.list_indices,
                index.rot_sqnorms,
                qc.astype(jnp.float32),
                filter_bits,
                k=k,
                n_probes=n_probes,
                metric=index.metric,
                has_filter=filter_bits is not None,
                chunk_lists=g,
            )
            if bpad:
                v, i = v[:-bpad], i[:-bpad]
            out_v.append(v)
            out_i.append(i)
        if len(out_v) == 1:
            return out_v[0], out_i[0]
        return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)

    # probe mode: the unpacked-bit temporary is [qb, max_list, D] f32 — cap
    # the batch the same way the PQ probe path caps its LUT gather.
    per_q = max(1, index.rot_dim * index.max_list * 4)
    query_batch = max(1, min(query_batch, (512 << 20) // per_q))
    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qc = queries[start : start + query_batch]
        bpad = 0
        if qc.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qc.shape[0]
            qc = jnp.pad(qc, ((0, bpad), (0, 0)))
        with obs.span("ivf_pq.search.probe_scan", nq=qc.shape[0], k=k) as sp:
            v, i = sp.sync(
                _ivf_rabitq_probe_impl(
                    index.centers,
                    index.rotation,
                    index.codes,
                    index.corrections,
                    index.list_indices,
                    index.rot_sqnorms,
                    qc.astype(jnp.float32),
                    filter_bits,
                    k=k,
                    n_probes=n_probes,
                    metric=index.metric,
                    has_filter=filter_bits is not None,
                )
            )
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


# ---------------------------------------------------------------------------
# serialization (neighbors/ivf_pq_serialize.cuh analog)
# ---------------------------------------------------------------------------

_KIND = "ivf_pq"
_VERSION = 4  # v4 adds the rabitq flag + corrections array


def _write_body(index: IvfPqIndex, stream: BinaryIO) -> None:
    ser.serialize_scalar(stream, int(index.metric), "int32")
    ser.serialize_scalar(stream, int(index.size), "int64")
    ser.serialize_scalar(stream, int(index.pq_bits), "int32")
    ser.serialize_scalar(stream, int(index.codebook_kind == PER_CLUSTER), "int32")
    ser.serialize_scalar(stream, float(index.list_cap_factor), "float64")
    ser.serialize_scalar(stream, int(index.additive), "int32")
    ser.serialize_scalar(stream, int(index.packed), "int32")
    ser.serialize_scalar(stream, int(index.center_rank is not None), "int32")
    ser.serialize_scalar(stream, int(index.rabitq), "int32")
    ser.serialize_array(stream, index.centers)
    ser.serialize_array(stream, index.centers_rot)
    ser.serialize_array(stream, index.rotation)
    ser.serialize_array(stream, index.pq_centers)
    ser.serialize_array(stream, index.codes)
    ser.serialize_array(stream, index.list_indices)
    ser.serialize_array(stream, index.list_sizes)
    ser.serialize_array(stream, index.rot_sqnorms)
    if index.rabitq:
        ser.serialize_array(stream, index.corrections)
    if index.center_rank is not None:
        ser.serialize_array(stream, index.center_rank)


def save(index: IvfPqIndex, stream: BinaryIO) -> None:
    body = io.BytesIO()
    _write_body(index, body)
    ser.save_stream(stream, _KIND, _VERSION, body.getvalue())


def load(stream: BinaryIO, res: Optional[Resources] = None) -> IvfPqIndex:
    ensure_resources(res)
    version, stream = ser.load_stream(stream, _KIND)
    metric = DistanceType(ser.deserialize_scalar(stream, "int32"))
    size = int(ser.deserialize_scalar(stream, "int64"))
    pq_bits = int(ser.deserialize_scalar(stream, "int32"))
    per_cluster = bool(ser.deserialize_scalar(stream, "int32"))
    cap_factor = float(ser.deserialize_scalar(stream, "float64")) if version >= 2 else 0.0
    additive = packed = False
    has_rank = rabitq = False
    if version >= 3:
        additive = bool(ser.deserialize_scalar(stream, "int32"))
        packed = bool(ser.deserialize_scalar(stream, "int32"))
        has_rank = bool(ser.deserialize_scalar(stream, "int32"))
    if version >= 4:
        rabitq = bool(ser.deserialize_scalar(stream, "int32"))
    centers = ser.deserialize_array(stream)
    centers_rot = ser.deserialize_array(stream)
    rotation = ser.deserialize_array(stream)
    pq_centers = ser.deserialize_array(stream)
    codes = ser.deserialize_array(stream)
    list_indices = ser.deserialize_array(stream)
    list_sizes = ser.deserialize_array(stream)
    if version >= 2:
        rot_sqnorms = ser.deserialize_array(stream)
    else:
        rot_sqnorms = _sqnorms_for(codes, centers_rot, pq_centers, per_cluster)
    corrections = ser.deserialize_array(stream) if rabitq else None
    center_rank = ser.deserialize_array(stream) if has_rank else None
    return IvfPqIndex(
        centers=centers,
        centers_rot=centers_rot,
        rotation=rotation,
        pq_centers=pq_centers,
        codes=codes,
        list_indices=list_indices,
        list_sizes=list_sizes,
        rot_sqnorms=rot_sqnorms,
        metric=metric,
        codebook_kind=PER_CLUSTER if per_cluster else PER_SUBSPACE,
        pq_bits=pq_bits,
        size=size,
        list_cap_factor=cap_factor,
        additive=additive,
        packed=packed,
        center_rank=center_rank,
        rabitq=rabitq,
        corrections=corrections,
    )


def save_path(index: IvfPqIndex, path: str) -> str:
    """Atomic (temp-then-rename) checksummed snapshot at ``path``."""
    return ser.atomic_write(path, lambda f: save(index, f))


def load_path(path: str, res: Optional[Resources] = None) -> IvfPqIndex:
    with open(path, "rb") as f:
        return load(f, res=res)
