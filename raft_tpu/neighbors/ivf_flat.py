"""IVF-Flat index — analog of ``raft::neighbors::ivf_flat``.

Reference: index layout ``neighbors/ivf_flat_types.hpp:44-164`` (per-list
interleaved groups of 32 rows x veclen chunks), build
``neighbors/detail/ivf_flat_build.cuh:382-460``, search
``neighbors/detail/ivf_flat_search-inl.cuh:271`` (coarse select at ``:145``),
fused scan+top-k kernel ``detail/ivf_flat_interleaved_scan-inl.cuh:687``.

TPU-first redesign (SURVEY.md §7 hard part (b) — ragged lists vs dense
tiles):

* Lists live in ONE dense padded tensor ``list_data [n_lists, max_list, d]``
  with parallel ``list_indices [n_lists, max_list]`` (-1 pads) and
  ``list_sizes [n_lists]`` — the CUDA 32-row interleave is replaced by
  sublane-padded dense tiles XLA can tile onto the MXU/VPU directly, and the
  gather of a probed list is one dynamic-slice.
* Coarse quantization = pairwise distance to centers + select_k, exactly the
  reference's ``select_clusters`` structure.
* Fine search ``lax.scan``s over the ``n_probes`` axis: each step gathers
  one probed list per query, computes the [batch, max_list] distance block
  (dot via einsum on the MXU; norms pre-stored), masks padded slots /
  filtered ids, and folds a running top-k — the interleaved_scan + fused
  top-k kernel expressed as scan + merge.
* Balanced k-means training keeps ``max_list`` close to the mean list size,
  bounding the padding waste the dense layout costs.

Supported metrics: L2Expanded, L2SqrtExpanded, InnerProduct, CosineExpanded
(the set the reference's IVF-Flat accepts).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import BalancedKMeansParams
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, is_min_close, resolve_metric, row_norms
from raft_tpu.ops.fused_1nn import min_cluster_and_distance
from raft_tpu.ops.select_k import running_merge, select_k, worst_value
from raft_tpu.utils.math import round_up

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
    DistanceType.CosineExpanded,
)


@dataclasses.dataclass
class IvfFlatIndexParams:
    """``ivf_flat::index_params`` analog (``neighbors/ivf_flat_types.hpp:44``)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    seed: int = 0


@dataclasses.dataclass
class IvfFlatSearchParams:
    """``ivf_flat::search_params`` analog (``ivf_flat_types.hpp:155``)."""

    n_probes: int = 20


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class IvfFlatIndex:
    """Dense-padded inverted-file index (``ivf_flat_types.hpp:129`` analog)."""

    centers: jax.Array  # [n_lists, d] f32
    list_data: jax.Array  # [n_lists, max_list, d] (dataset dtype)
    list_indices: jax.Array  # [n_lists, max_list] i32, -1 = empty slot
    list_sizes: jax.Array  # [n_lists] i32
    list_norms: Optional[jax.Array]  # [n_lists, max_list] f32 sq norms (L2/cos)
    metric: DistanceType
    size: int  # total indexed rows

    def tree_flatten(self):
        return (
            (self.centers, self.list_data, self.list_indices, self.list_sizes, self.list_norms),
            (self.metric, self.size),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0], size=aux[1])

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list(self) -> int:
        return self.list_data.shape[1]


def _pack_lists(dataset: jax.Array, labels: np.ndarray, n_lists: int, ids: np.ndarray):
    """Pack rows into the dense [n_lists, max_list, d] layout.

    Host-side packing at build time (the analog of the reference's
    ``build_index_kernel`` scatter, ``ivf_flat_build.cuh:116``); sizes are
    data-dependent so this is inherently a host decision point — one sync at
    build, zero at search.
    """
    n, d = dataset.shape
    counts = np.bincount(labels, minlength=n_lists)
    max_list = max(8, round_up(int(counts.max()), 8))

    order = np.argsort(labels, kind="stable")
    within = np.arange(n) - np.repeat(np.concatenate([[0], np.cumsum(counts)[:-1]]), counts)
    slots = labels[order] * max_list + within  # flat destination slot per row

    flat_data = np.zeros((n_lists * max_list, d), dtype=np.asarray(dataset).dtype)
    flat_ids = np.full((n_lists * max_list,), -1, np.int32)
    ds_np = np.asarray(dataset)
    flat_data[slots] = ds_np[order]
    flat_ids[slots] = ids[order]
    return (
        jnp.asarray(flat_data.reshape(n_lists, max_list, d)),
        jnp.asarray(flat_ids.reshape(n_lists, max_list)),
        jnp.asarray(counts.astype(np.int32)),
        max_list,
    )


def build(
    dataset,
    params: Optional[IvfFlatIndexParams] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> IvfFlatIndex:
    """Train centers with balanced k-means and pack the inverted lists
    (``ivf_flat::build``, ``detail/ivf_flat_build.cuh:382``)."""
    res = ensure_resources(res)
    if params is None:
        params = IvfFlatIndexParams(**kwargs)
    metric = resolve_metric(params.metric)
    expects(metric in _SUPPORTED, "IVF-Flat does not support metric %s", metric)
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    n, d = dataset.shape
    n_lists = min(params.n_lists, n)

    train_n = max(n_lists, int(n * params.kmeans_trainset_fraction))
    ds_f32 = dataset.astype(jnp.float32)
    trainset = ds_f32
    if train_n < n:
        rng = np.random.default_rng(params.seed)
        trainset = ds_f32[jnp.asarray(rng.permutation(n)[:train_n])]

    assign_data = ds_f32
    if metric == DistanceType.CosineExpanded:
        trainset = trainset / jnp.maximum(jnp.linalg.norm(trainset, axis=1, keepdims=True), 1e-12)
        assign_data = ds_f32 / jnp.maximum(jnp.linalg.norm(ds_f32, axis=1, keepdims=True), 1e-12)

    centers = kmeans_balanced.fit(
        trainset,
        BalancedKMeansParams(
            n_clusters=n_lists,
            n_iters=params.kmeans_n_iters,
            metric=DistanceType.L2Expanded,
            seed=params.seed,
        ),
    )
    labels, _ = min_cluster_and_distance(assign_data, centers, metric=DistanceType.L2Expanded)

    labels_np = np.asarray(labels)
    list_data, list_indices, list_sizes, _ = _pack_lists(
        dataset, labels_np, n_lists, np.arange(n, dtype=np.int32)
    )
    list_norms = None
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded, DistanceType.CosineExpanded):
        list_norms = row_norms(list_data.reshape(-1, d)).reshape(list_data.shape[:2])
    return IvfFlatIndex(
        centers=centers,
        list_data=list_data,
        list_indices=list_indices,
        list_sizes=list_sizes,
        list_norms=list_norms,
        metric=metric,
        size=n,
    )


def extend(index: IvfFlatIndex, new_vectors, new_ids=None) -> IvfFlatIndex:
    """Add vectors to an existing index (``ivf_flat::extend``,
    ``detail/ivf_flat_build.cuh:163``): assign to nearest centers and repack
    (centers are kept fixed, as in the reference)."""
    new_vectors = jnp.asarray(new_vectors)
    expects(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim, "bad extend shape")
    n_new = new_vectors.shape[0]
    if new_ids is None:
        new_ids = np.arange(index.size, index.size + n_new, dtype=np.int32)
    else:
        new_ids = np.asarray(new_ids, np.int32)

    vec_f32 = new_vectors.astype(jnp.float32)
    if index.metric == DistanceType.CosineExpanded:
        vec_f32 = vec_f32 / jnp.maximum(jnp.linalg.norm(vec_f32, axis=1, keepdims=True), 1e-12)
    labels, _ = min_cluster_and_distance(vec_f32, index.centers, metric=DistanceType.L2Expanded)

    # Collect existing rows (valid slots), concat, repack.
    d = index.dim
    old_mask = np.asarray(index.list_indices).reshape(-1) >= 0
    old_data = np.asarray(index.list_data).reshape(-1, d)[old_mask]
    old_ids = np.asarray(index.list_indices).reshape(-1)[old_mask]
    old_labels = np.repeat(np.arange(index.n_lists), index.max_list)[old_mask]

    all_data = np.concatenate([old_data, np.asarray(new_vectors)], axis=0)
    all_ids = np.concatenate([old_ids, new_ids])
    all_labels = np.concatenate([old_labels, np.asarray(labels)])

    list_data, list_indices, list_sizes, _ = _pack_lists(
        jnp.asarray(all_data), all_labels, index.n_lists, all_ids
    )
    list_norms = None
    if index.list_norms is not None:
        list_norms = row_norms(list_data.reshape(-1, d)).reshape(list_data.shape[:2])
    return IvfFlatIndex(
        centers=index.centers,
        list_data=list_data,
        list_indices=list_indices,
        list_sizes=list_sizes,
        list_norms=list_norms,
        metric=index.metric,
        size=index.size + n_new,
    )


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "has_filter"),
)
def _ivf_search_impl(
    centers,
    list_data,
    list_indices,
    list_norms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    has_filter: bool,
):
    nq, d = queries.shape
    n_lists, max_list = list_indices.shape
    qf = queries.astype(jnp.float32)
    if metric == DistanceType.CosineExpanded:
        qf = qf / jnp.maximum(jnp.linalg.norm(qf, axis=1, keepdims=True), 1e-12)

    # -- coarse: nearest centers (select_clusters, ivf_flat_search-inl.cuh:145)
    q_dot_c = qf @ centers.T  # [nq, n_lists] (MXU)
    if metric == DistanceType.InnerProduct:
        coarse = -q_dot_c
    else:
        c_norm = jnp.sum(centers * centers, axis=1)
        coarse = c_norm[None, :] - 2.0 * q_dot_c  # rankwise == L2 distance
    _, probes = select_k(coarse, n_probes, select_min=True)  # [nq, n_probes]

    q_sqnorm = jnp.sum(qf * qf, axis=1)
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.float32(worst_value(jnp.float32, select_min))

    init = (
        jnp.full((nq, k), worst, jnp.float32),
        jnp.full((nq, k), -1, jnp.int32),
    )

    def body(carry, p):
        acc_v, acc_i = carry
        list_id = probes[:, p]  # [nq]
        data_p = list_data[list_id]  # [nq, max_list, d] gather
        ids_p = list_indices[list_id]  # [nq, max_list]
        dots = jnp.einsum(
            "qd,qmd->qm",
            qf,
            data_p.astype(jnp.float32),
            preferred_element_type=jnp.float32,
            # full-precision passes: in-list ranking must match the exact
            # distances the reference computes (see cagra.py note on the
            # TPU default bf16 matmul)
            precision=lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            dist = dots
        elif metric == DistanceType.CosineExpanded:
            # qf is unit-normalized; stored rows are raw, so
            # 1 - cos = 1 - (q̂·x)/||x||.
            norms_p = list_norms[list_id]
            dist = 1.0 - dots * lax.rsqrt(jnp.maximum(norms_p, 1e-24))
        else:
            norms_p = list_norms[list_id]
            dist = q_sqnorm[:, None] + norms_p - 2.0 * dots
            dist = jnp.maximum(dist, 0.0)
        valid = ids_p >= 0
        if has_filter:
            word = filter_bits[jnp.clip(ids_p, 0, None) // 32]
            bit = (word >> (jnp.clip(ids_p, 0, None) % 32).astype(jnp.uint32)) & 1
            valid = valid & (bit == 1)
        dist = jnp.where(valid, dist, worst)
        ids_masked = jnp.where(valid, ids_p, -1)
        return running_merge(acc_v, acc_i, dist, ids_masked, select_min=select_min), None

    (vals, idx), _ = lax.scan(body, init, jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


def search(
    index: IvfFlatIndex,
    queries,
    k: int,
    params: Optional[IvfFlatSearchParams] = None,
    prefilter: Optional[Bitset] = None,
    query_batch: int = 1024,
    res: Optional[Resources] = None,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over probed lists (``ivf_flat::search``,
    ``detail/ivf_flat_search-inl.cuh:271``). Returns best-first
    ``(distances [nq, k] f32, indices [nq, k] i32)``; unfilled slots get
    id -1."""
    ensure_resources(res)
    if params is None:
        params = IvfFlatSearchParams(**kwargs)
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "bad query shape")
    expects(k >= 1, "k must be >= 1")
    if prefilter is not None:
        expects(prefilter.size >= index.size, "prefilter smaller than index")
    n_probes = min(params.n_probes, index.n_lists)
    nq = queries.shape[0]

    filter_bits = prefilter.bits if prefilter is not None else None

    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qc = queries[start : start + query_batch]
        bpad = 0
        if qc.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qc.shape[0]
            qc = jnp.pad(qc, ((0, bpad), (0, 0)))
        v, i = _ivf_search_impl(
            index.centers,
            index.list_data,
            index.list_indices,
            index.list_norms,
            qc,
            filter_bits,
            k=k,
            n_probes=n_probes,
            metric=index.metric,
            has_filter=filter_bits is not None,
        )
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


# -- serialization (neighbors/ivf_flat_serialize.cuh analog) ----------------

_KIND = "ivf_flat"
_VERSION = 1


def save(index: IvfFlatIndex, stream: BinaryIO) -> None:
    ser.dump_header(stream, _KIND, _VERSION)
    ser.serialize_scalar(stream, int(index.metric), "int32")
    ser.serialize_scalar(stream, int(index.size), "int64")
    ser.serialize_scalar(stream, int(index.list_norms is not None), "int32")
    ser.serialize_array(stream, index.centers)
    ser.serialize_array(stream, index.list_data)
    ser.serialize_array(stream, index.list_indices)
    ser.serialize_array(stream, index.list_sizes)
    if index.list_norms is not None:
        ser.serialize_array(stream, index.list_norms)


def load(stream: BinaryIO, res: Optional[Resources] = None) -> IvfFlatIndex:
    ensure_resources(res)
    ser.check_header(stream, _KIND)
    metric = DistanceType(ser.deserialize_scalar(stream, "int32"))
    size = int(ser.deserialize_scalar(stream, "int64"))
    has_norms = bool(ser.deserialize_scalar(stream, "int32"))
    centers = ser.deserialize_array(stream)
    list_data = ser.deserialize_array(stream)
    list_indices = ser.deserialize_array(stream)
    list_sizes = ser.deserialize_array(stream)
    list_norms = ser.deserialize_array(stream) if has_norms else None
    return IvfFlatIndex(
        centers=centers,
        list_data=list_data,
        list_indices=list_indices,
        list_sizes=list_sizes,
        list_norms=list_norms,
        metric=metric,
        size=size,
    )
