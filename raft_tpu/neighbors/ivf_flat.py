"""IVF-Flat index — analog of ``raft::neighbors::ivf_flat``.

Reference: index layout ``neighbors/ivf_flat_types.hpp:44-164`` (per-list
interleaved groups of 32 rows x veclen chunks), build
``neighbors/detail/ivf_flat_build.cuh:382-460``, search
``neighbors/detail/ivf_flat_search-inl.cuh:271`` (coarse select at ``:145``),
fused scan+top-k kernel ``detail/ivf_flat_interleaved_scan-inl.cuh:687``.

TPU-first redesign (SURVEY.md §7 hard part (b) — ragged lists vs dense
tiles):

* Lists live in ONE dense padded tensor ``list_data [n_lists, max_list, d]``
  with parallel ``list_indices [n_lists, max_list]`` (-1 pads) and
  ``list_sizes [n_lists]`` — the CUDA 32-row interleave is replaced by
  sublane-padded dense tiles XLA can tile onto the MXU/VPU directly, and the
  gather of a probed list is one dynamic-slice.
* Coarse quantization = pairwise distance to centers + select_k, exactly the
  reference's ``select_clusters`` structure.
* Fine search ``lax.scan``s over the ``n_probes`` axis: each step gathers
  one probed list per query, computes the [batch, max_list] distance block
  (dot via einsum on the MXU; norms pre-stored), masks padded slots /
  filtered ids, and folds a running top-k — the interleaved_scan + fused
  top-k kernel expressed as scan + merge.
* Balanced k-means training keeps ``max_list`` close to the mean list size,
  bounding the padding waste the dense layout costs.

Supported metrics: L2Expanded, L2SqrtExpanded, InnerProduct, CosineExpanded
(the set the reference's IVF-Flat accepts).
"""
from __future__ import annotations

import dataclasses
import functools
import io
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import BalancedKMeansParams
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.neighbors.ivf_common import pack_rows as _pack, topk_labels as _topk_labels
from raft_tpu.ops.distance import DistanceType, is_min_close, resolve_metric, row_norms
from raft_tpu.ops.fused_1nn import min_cluster_and_distance
from raft_tpu.ops.select_k import running_merge, select_k, worst_value

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
    DistanceType.CosineExpanded,
)


@dataclasses.dataclass
class IvfFlatIndexParams:
    """``ivf_flat::index_params`` analog (``neighbors/ivf_flat_types.hpp:44``)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    seed: int = 0
    # Dense-layout list capacity: lists are capped at
    # ``cap_factor * n / n_lists`` rows; overflow spills to the row's
    # next-nearest lists (see ``ivf_common.assign_slots``). 0 disables
    # capping (max_list = largest cluster, as the ragged reference layout).
    list_cap_factor: float = 2.0


@dataclasses.dataclass
class IvfFlatSearchParams:
    """``ivf_flat::search_params`` analog (``ivf_flat_types.hpp:155``).

    The ``fused_*`` knobs tune the Pallas fused scan (``mode="fused"``):
    query-tile height, tile probe-table size (``fused_probe_factor *
    n_probes`` lists per tile), top-k merge strategy (``"seg"``/``"seg1"``/``"seg4"``
    banked lane-group PartialReduce, ``"bank"``/``"bankN"`` persistent
    min-merge buffer with periodic extraction — the fast path — or
    ``"exact"``), and MXU precision for the distance matmul
    (``"highest"`` = f32-exact passes, ``"default"`` = fast)."""

    n_probes: int = 20
    # qt/probe_factor/group/merge = the measured 1M x 128 operating point
    # on TPU v5e (see docs/tpu_design.md); group rounds down to a divisor
    # of n_lists and the probe table caps at the unit count, so they
    # degrade gracefully on small indexes. precision stays "highest"
    # (f32-exact distances) by default — the bench trades it for speed
    # explicitly with "default". bank8 + col_chunk=1024 replaced seg4 in
    # round 4: per-step min-merge into a persistent 8x128-lane buffer with
    # one extraction per tile is both faster and slightly higher-recall
    # than per-step extraction at these shapes.
    fused_qt: int = 128
    fused_probe_factor: int = 32
    fused_group: int = 8  # lists per DMA block / probe-table entry
    fused_merge: str = "bank8"
    fused_precision: str = "highest"
    # bank-merge extras: extraction period (0 = once per tile) and score
    # column-chunk rows (0 = whole DMA block at once)
    fused_extract_every: int = 0
    fused_col_chunk: int = 1024
    # Exact re-rank depth: search keeps k * refine_ratio candidates and
    # re-scores them against the raw dataset (refine.refine) when search()
    # is given one — the escape hatch that recovers exactness when
    # list_data is stored in a narrow dtype (bf16/int8) or the scan ran
    # an approximate top-k. 1 = off (the all-resident default).
    refine_ratio: int = 1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class IvfFlatIndex:
    """Dense-padded inverted-file index (``ivf_flat_types.hpp:129`` analog)."""

    centers: jax.Array  # [n_lists, d] f32
    list_data: jax.Array  # [n_lists, max_list, d] (dataset dtype)
    list_indices: jax.Array  # [n_lists, max_list] i32, -1 = empty slot
    list_sizes: jax.Array  # [n_lists] i32
    list_norms: Optional[jax.Array]  # [n_lists, max_list] f32 sq norms (L2/cos)
    metric: DistanceType
    size: int  # total indexed rows
    list_cap_factor: float = 2.0  # build-time cap; honored by extend()
    # PCA-bisection spatial rank of the centers (see
    # raft_tpu.ops.pallas.spatial_center_rank); used by the fused Pallas
    # search path to form probe-coherent query tiles. Optional: computed at
    # build, regenerated on demand for indexes loaded from old files.
    center_rank: Optional[jax.Array] = None

    def tree_flatten(self):
        return (
            (
                self.centers,
                self.list_data,
                self.list_indices,
                self.list_sizes,
                self.list_norms,
                self.center_rank,
            ),
            (self.metric, self.size, self.list_cap_factor),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(
            *children[:5],
            metric=aux[0],
            size=aux[1],
            list_cap_factor=aux[2],
            center_rank=children[5],
        )

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list(self) -> int:
        return self.list_data.shape[1]


def build(
    dataset,
    params: Optional[IvfFlatIndexParams] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> IvfFlatIndex:
    """Train centers with balanced k-means and pack the inverted lists
    (``ivf_flat::build``, ``detail/ivf_flat_build.cuh:382``)."""
    res = ensure_resources(res)
    if params is None:
        params = IvfFlatIndexParams(**kwargs)
    metric = resolve_metric(params.metric)
    expects(metric in _SUPPORTED, "IVF-Flat does not support metric %s", metric)
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    n, d = dataset.shape
    n_lists = min(params.n_lists, n)

    train_n = max(n_lists, int(n * params.kmeans_trainset_fraction))
    ds_f32 = dataset.astype(jnp.float32)
    trainset = ds_f32
    if train_n < n:
        rng = np.random.default_rng(params.seed)
        trainset = ds_f32[jnp.asarray(rng.permutation(n)[:train_n])]

    assign_data = ds_f32
    if metric == DistanceType.CosineExpanded:
        trainset = trainset / jnp.maximum(jnp.linalg.norm(trainset, axis=1, keepdims=True), 1e-12)
        assign_data = ds_f32 / jnp.maximum(jnp.linalg.norm(ds_f32, axis=1, keepdims=True), 1e-12)

    centers = kmeans_balanced.fit(
        trainset,
        BalancedKMeansParams(
            n_clusters=n_lists,
            n_iters=params.kmeans_n_iters,
            metric=DistanceType.L2Expanded,
            seed=params.seed,
        ),
    )
    # Physically order the lists by the PCA-bisection spatial rank of their
    # centers, so spatially nearby lists get nearby indices. The fused
    # Pallas path depends on this: probe-coherent query tiles and
    # group-granular probe tables both assume neighbor lists sit next to
    # each other in the layout. (List order is meaningless to every other
    # path, so this is free.)
    from raft_tpu.ops.pallas import spatial_center_rank

    rank = spatial_center_rank(np.asarray(centers))
    centers = jnp.asarray(np.asarray(centers)[np.argsort(rank)])
    cand = _topk_labels(assign_data, centers, k=8)
    list_data, list_indices, list_sizes, _ = _pack(
        dataset, jnp.arange(n, dtype=jnp.int32), cand, n_lists, params.list_cap_factor
    )
    list_norms = None
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded, DistanceType.CosineExpanded):
        list_norms = row_norms(list_data.reshape(-1, d)).reshape(list_data.shape[:2])
    # lists are stored in spatial order, so the rank is the identity
    center_rank = jnp.arange(n_lists, dtype=jnp.int32)
    return IvfFlatIndex(
        centers=centers,
        list_data=list_data,
        list_indices=list_indices,
        list_sizes=list_sizes,
        list_norms=list_norms,
        metric=metric,
        size=n,
        list_cap_factor=params.list_cap_factor,
        center_rank=center_rank,
    )


def extend(
    index: IvfFlatIndex, new_vectors, new_ids=None, cap_factor: Optional[float] = None
) -> IvfFlatIndex:
    """Add vectors to an existing index (``ivf_flat::extend``,
    ``detail/ivf_flat_build.cuh:163``): assign to nearest centers and repack
    on device (centers are kept fixed, as in the reference). Unlike the
    round-2 implementation there is no device→host→device round trip — the
    valid rows are gathered, concatenated with the new ones, and
    re-scattered entirely on the accelerator. ``cap_factor=None`` uses the
    index's build-time ``list_cap_factor``."""
    if cap_factor is None:
        cap_factor = index.list_cap_factor
    new_vectors = jnp.asarray(new_vectors)
    expects(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim, "bad extend shape")
    n_new = new_vectors.shape[0]
    if new_ids is None:
        new_ids = jnp.arange(index.size, index.size + n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    d = index.dim
    # Compact existing valid rows to the front (on device): argsort on the
    # invalid flag keeps list order among valid rows.
    flat_ids = index.list_indices.reshape(-1)
    n_old = int(index.size)
    keep_order = jnp.argsort(flat_ids < 0)[:n_old]
    old_data = index.list_data.reshape(-1, d)[keep_order]
    old_ids = flat_ids[keep_order]

    all_data = jnp.concatenate([old_data, new_vectors.astype(index.list_data.dtype)], axis=0)
    all_ids = jnp.concatenate([old_ids, new_ids])
    assign = all_data.astype(jnp.float32)
    if index.metric == DistanceType.CosineExpanded:
        assign = assign / jnp.maximum(jnp.linalg.norm(assign, axis=1, keepdims=True), 1e-12)
    cand = _topk_labels(assign, index.centers, k=8)

    list_data, list_indices, list_sizes, _ = _pack(
        all_data, all_ids, cand, index.n_lists, cap_factor
    )
    list_norms = None
    if index.list_norms is not None:
        list_norms = row_norms(list_data.reshape(-1, d)).reshape(list_data.shape[:2])
    return IvfFlatIndex(
        centers=index.centers,
        list_data=list_data,
        list_indices=list_indices,
        list_sizes=list_sizes,
        list_norms=list_norms,
        metric=index.metric,
        size=index.size + n_new,
        list_cap_factor=cap_factor,
        center_rank=index.center_rank,
    )


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "has_filter", "chunk_lists"),
)
def _ivf_flat_scan_impl(
    centers,
    list_data,
    list_indices,
    list_norms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    has_filter: bool,
    chunk_lists: int,
):
    """Dense masked scan — the TPU answer to the reference's fused
    interleaved-scan kernel (``ivf_flat_interleaved_scan-inl.cuh:687``)
    for batched queries.

    Rather than gathering each query's probed lists (a per-(query,probe)
    HBM gather that runs far off the roofline on TPU), the whole padded
    index is streamed chunk-of-lists at a time through ONE dense MXU
    matmul per chunk; rows in lists a query did not probe are masked with
    an elementwise predicate that XLA fuses into the matmul epilogue, and
    the selection is the fused approximate top-k. The candidate set is
    exactly the probe path's. Wins whenever the query batch is large
    enough that most lists are probed by someone (the usual
    throughput-mode regime); ``search`` keeps the gather path for small
    batches."""
    qf = queries.astype(jnp.float32)
    if metric == DistanceType.CosineExpanded:
        qf = qf / jnp.maximum(jnp.linalg.norm(qf, axis=1, keepdims=True), 1e-12)
    probed = probe_mask(centers, qf, n_probes, metric)
    return flat_scan_core(
        list_data,
        list_indices,
        list_norms,
        qf,
        probed,
        filter_bits,
        k=k,
        metric=metric,
        has_filter=has_filter,
        chunk_lists=chunk_lists,
    )


def scan_chunk_lists(n_lists: int, max_list: int) -> int:
    """Chunk-of-lists size for the dense scan: ~512k rows per chunk (the
    measured fusion sweet spot), constrained to divide n_lists."""
    g = max(1, 524288 // max(max_list, 1))
    while n_lists % g:
        g -= 1
    return g


def probe_mask(centers, qf, n_probes: int, metric: DistanceType) -> jax.Array:
    """[nq, n_lists] bool — which lists each query probes (the coarse
    ``select_clusters`` step as a mask). For cosine, ``qf`` must already be
    unit-normalized."""
    from raft_tpu.neighbors.ivf_common import probe_selection

    return probe_selection(centers, qf, n_probes, metric)[1]


def flat_scan_core(
    list_data,
    list_indices,
    list_norms,
    qf,
    probed,
    filter_bits,
    *,
    k: int,
    metric: DistanceType,
    has_filter: bool,
    chunk_lists: int,
):
    """Masked dense scan over (a shard of) the padded lists. ``probed`` is
    [nq, n_lists_local]; ``list_indices`` carry global row ids, so per-shard
    results merge directly (used by ``parallel.sharded_ann``)."""
    nq = qf.shape[0]
    n_lists, max_list = list_indices.shape
    d = list_data.shape[-1]
    G, M = chunk_lists, max_list
    n_chunks = n_lists // G
    data_c = list_data.reshape(n_chunks, G * M, d)
    ids_c = list_indices.reshape(n_chunks, G * M)
    if list_norms is not None:
        norms_c = list_norms.reshape(n_chunks, G * M)
    else:
        norms_c = jnp.zeros((n_chunks, G * M), jnp.float32)
    probed_cm = jnp.moveaxis(probed.reshape(nq, n_chunks, G), 1, 0)

    init = (
        jnp.full((nq, k), -jnp.inf, jnp.float32),
        jnp.zeros((nq, k), jnp.int32),  # flat slots
    )

    def body(carry, inp):
        acc_v, acc_i = carry
        rows, ids, nrm, pmask, ci = inp
        dots = (qf @ rows.astype(jnp.float32).T).astype(jnp.float32)
        if metric == DistanceType.InnerProduct:
            score = dots
        elif metric == DistanceType.CosineExpanded:
            score = dots * lax.rsqrt(jnp.maximum(nrm, 1e-24))[None, :]
        else:
            score = 2.0 * dots - nrm[None, :]  # max == min L2
        # Masking is ADDITIVE on the small axes (a [G*M] pad penalty and an
        # [nq, G] probe penalty broadcast into the epilogue) — a boolean
        # [nq, G*M] keep-mask defeats XLA's matmul fusion and costs ~10x.
        pad_pen = jnp.where(ids >= 0, 0.0, -jnp.inf)  # [G*M]
        if has_filter:
            word = filter_bits[jnp.clip(ids, 0, None) // 32]
            bit = (word >> (jnp.clip(ids, 0, None) % 32).astype(jnp.uint32)) & 1
            pad_pen = jnp.where(bit == 1, pad_pen, -jnp.inf)
        probe_pen = jnp.where(pmask, 0.0, -jnp.inf)  # [nq, G]
        score = (
            score
            + pad_pen[None, :]
            + jnp.broadcast_to(probe_pen[:, :, None], (nq, G, M)).reshape(nq, G * M)
        )
        # shortlist 2k per chunk: each true top-k member lands in the
        # approximate top-2k with much higher probability than in the
        # top-k, lifting end-to-end recall toward the probe path's
        kk = min(max(2 * k, 16), G * M)
        v, i = lax.approx_max_k(score, kk, recall_target=0.99)
        nv, ni = lax.top_k(jnp.concatenate([acc_v, v], axis=1), k)
        na = jnp.take_along_axis(
            jnp.concatenate([acc_i, i + ci * (G * M)], axis=1), ni, axis=1
        )
        return (nv, na), None

    (vals, slots), _ = lax.scan(
        body,
        init,
        (data_c, ids_c, norms_c, probed_cm, jnp.arange(n_chunks, dtype=jnp.int32)),
    )

    idx = list_indices.reshape(-1)[slots.reshape(-1)].reshape(nq, k)
    idx = jnp.where(jnp.isfinite(vals), idx, -1)
    if metric == DistanceType.InnerProduct:
        out = vals
    elif metric == DistanceType.CosineExpanded:
        out = 1.0 - vals
        out = jnp.where(idx >= 0, out, jnp.inf)
    else:
        qn = jnp.sum(qf * qf, axis=1)
        out = jnp.maximum(qn[:, None] - vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out = jnp.sqrt(out)
        out = jnp.where(idx >= 0, out, jnp.inf)
    return out, idx


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "has_filter"),
)
def _ivf_search_impl(
    centers,
    list_data,
    list_indices,
    list_norms,
    queries,
    filter_bits,
    *,
    k: int,
    n_probes: int,
    metric: DistanceType,
    has_filter: bool,
):
    nq, d = queries.shape
    n_lists, max_list = list_indices.shape
    qf = queries.astype(jnp.float32)
    if metric == DistanceType.CosineExpanded:
        qf = qf / jnp.maximum(jnp.linalg.norm(qf, axis=1, keepdims=True), 1e-12)

    # -- coarse: nearest centers (select_clusters, ivf_flat_search-inl.cuh:145)
    q_dot_c = qf @ centers.T  # [nq, n_lists] (MXU)
    if metric == DistanceType.InnerProduct:
        coarse = -q_dot_c
    else:
        c_norm = jnp.sum(centers * centers, axis=1)
        coarse = c_norm[None, :] - 2.0 * q_dot_c  # rankwise == L2 distance
    _, probes = select_k(coarse, n_probes, select_min=True)  # [nq, n_probes]

    q_sqnorm = jnp.sum(qf * qf, axis=1)
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.float32(worst_value(jnp.float32, select_min))

    init = (
        jnp.full((nq, k), worst, jnp.float32),
        jnp.full((nq, k), -1, jnp.int32),
    )

    def body(carry, p):
        acc_v, acc_i = carry
        list_id = probes[:, p]  # [nq]
        data_p = list_data[list_id]  # [nq, max_list, d] gather
        ids_p = list_indices[list_id]  # [nq, max_list]
        dots = jnp.einsum(
            "qd,qmd->qm",
            qf,
            data_p.astype(jnp.float32),
            preferred_element_type=jnp.float32,
            # full-precision passes: in-list ranking must match the exact
            # distances the reference computes (see cagra.py note on the
            # TPU default bf16 matmul)
            precision=lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            dist = dots
        elif metric == DistanceType.CosineExpanded:
            # qf is unit-normalized; stored rows are raw, so
            # 1 - cos = 1 - (q̂·x)/||x||.
            norms_p = list_norms[list_id]
            dist = 1.0 - dots * lax.rsqrt(jnp.maximum(norms_p, 1e-24))
        else:
            norms_p = list_norms[list_id]
            dist = q_sqnorm[:, None] + norms_p - 2.0 * dots
            dist = jnp.maximum(dist, 0.0)
        valid = ids_p >= 0
        if has_filter:
            word = filter_bits[jnp.clip(ids_p, 0, None) // 32]
            bit = (word >> (jnp.clip(ids_p, 0, None) % 32).astype(jnp.uint32)) & 1
            valid = valid & (bit == 1)
        dist = jnp.where(valid, dist, worst)
        ids_masked = jnp.where(valid, ids_p, -1)
        return running_merge(acc_v, acc_i, dist, ids_masked, select_min=select_min), None

    (vals, idx), _ = lax.scan(body, init, jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


def _batched_search(run, queries, query_batch: int):
    """Shared query-batching: pad the tail batch, call ``run`` per batch,
    trim, concatenate. One home for the loop the fused/scan/probe modes
    all need."""
    nq = queries.shape[0]
    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qc = queries[start : start + query_batch]
        bpad = 0
        if qc.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qc.shape[0]
            qc = jnp.pad(qc, ((0, bpad), (0, 0)))
        v, i = run(qc)
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


# Rank cache for legacy (pre-v3) indexes, keyed on the identity of the
# centers array: computing the PCA-bisection rank is a host-side walk we
# don't want per search call, and caching ON the index object (as an
# attribute) is a mutation of user-owned state that doesn't survive
# serialization or pytree transforms. Weak refs let index arrays die;
# arrays that refuse weakrefs would otherwise pin themselves forever, so
# the cache is also capped (FIFO evict) — a long-running server loading
# many legacy indexes must not grow without bound.
_RANK_CACHE: dict = {}
_RANK_CACHE_MAX = 64


def _rank_cache_put(key, ref, value):
    _RANK_CACHE[key] = (ref, value)
    while len(_RANK_CACHE) > _RANK_CACHE_MAX:
        _RANK_CACHE.pop(next(iter(_RANK_CACHE)))


def _legacy_rank_cache(centers) -> jax.Array:
    import weakref

    key = id(centers)
    hit = _RANK_CACHE.get(key)
    if hit is not None and hit[0]() is centers:
        return hit[1]
    from raft_tpu.ops.pallas.ivf_scan import spatial_center_rank

    rank = jnp.asarray(spatial_center_rank(np.asarray(centers)))
    try:
        ref = weakref.ref(centers, lambda _: _RANK_CACHE.pop(key, None))
    except TypeError:  # some array types refuse weakrefs; FIFO cap evicts
        ref = lambda: centers  # noqa: E731
    _rank_cache_put(key, ref, rank)
    return rank


def _rank_is_identity(rank) -> bool:
    key = id(rank)
    hit = _RANK_CACHE.get(("ident", key))
    if hit is not None and hit[0]() is rank:
        return hit[1]
    import weakref

    r = np.asarray(rank)
    ident = bool((r == np.arange(r.shape[0], dtype=r.dtype)).all())
    try:
        ref = weakref.ref(rank, lambda _: _RANK_CACHE.pop(("ident", key), None))
    except TypeError:
        ref = lambda: rank  # noqa: E731
    _rank_cache_put(("ident", key), ref, ident)
    return ident


def search(
    index: IvfFlatIndex,
    queries,
    k: int,
    params: Optional[IvfFlatSearchParams] = None,
    prefilter: Optional[Bitset] = None,
    query_batch: int = 1024,
    mode: str = "auto",
    res: Optional[Resources] = None,
    dataset=None,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over probed lists (``ivf_flat::search``,
    ``detail/ivf_flat_search-inl.cuh:271``). Returns best-first
    ``(distances [nq, k] f32, indices [nq, k] i32)``; unfilled slots get
    id -1.

    ``mode``: ``"fused"`` = the Pallas fused probed-list scan (DMAs only
    the probed lists — the big-batch TPU fast path, see
    :mod:`raft_tpu.ops.pallas.ivf_scan`); ``"scan"`` = dense masked scan
    over list chunks (:func:`_ivf_flat_scan_impl`); ``"probe"`` = per-probe
    gather (latency path for small batches); ``"auto"`` picks fused on TPU
    for batches >= 128 (when the metric/dtype qualify and there is no
    prefilter fallback issue), else scan for batches >= 128, else probe.
    All draw from the same probed candidate set; fused/scan select with an
    approximate top-k (lane-group PartialReduce), so results can differ
    slightly from the deterministic probe path. Fused accepts
    ``params.fused_*`` tuning knobs and runs in interpret mode off-TPU."""
    ensure_resources(res)
    if params is None:
        params = IvfFlatSearchParams(**kwargs)
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "bad query shape")
    expects(k >= 1, "k must be >= 1")
    if dataset is not None and params.refine_ratio > 1:
        # Integrated refine (same contract as ivf_pq.search): scan for
        # k * refine_ratio candidates, then exact re-rank against the raw
        # dataset — a device array or a tiered HostVectorStore.
        from raft_tpu.neighbors.refine import check_refine_dataset, refine

        check_refine_dataset(dataset, index.size, "ivf_flat")
        inner = dataclasses.replace(params, refine_ratio=1)
        kk = min(k * params.refine_ratio, index.size)
        _, cand = search(
            index, queries, kk, inner,
            prefilter=prefilter, query_batch=query_batch, mode=mode, res=res,
        )
        if obs.is_enabled():
            obs.observe("ivf_flat.search.refine_candidates_per_query", float(kk))
        with obs.span("ivf_flat.search.refine", k=k, candidates=int(kk)) as sp:
            return sp.sync(
                refine(dataset, queries, cand, k, metric=resolve_metric(index.metric))
            )
    if prefilter is not None:
        expects(prefilter.size >= index.size, "prefilter smaller than index")
    n_probes = min(params.n_probes, index.n_lists)
    nq = queries.shape[0]

    filter_bits = prefilter.bits if prefilter is not None else None

    if mode == "auto":
        from raft_tpu import plan as _plan
        from raft_tpu.ops.pallas.ivf_scan import supported_metric

        on_tpu = jax.default_backend() == "tpu"
        if _plan.is_enabled():
            mode = _plan.plan_search_mode(
                "ivf_flat", nq, on_tpu=on_tpu,
                fused_ok=supported_metric(index.metric),
            ).choice
        elif nq >= 128 and on_tpu and supported_metric(index.metric):
            mode = "fused"
        else:
            mode = "scan" if nq >= 128 else "probe"
    expects(
        mode in ("scan", "probe", "fused"), "mode must be auto|scan|probe|fused, got %r", mode
    )
    if mode == "fused":
        from raft_tpu.ops.pallas.ivf_scan import (
            ivf_flat_fused_search,
            spatial_center_rank,
            supported_metric,
        )

        expects(supported_metric(index.metric), "fused mode: unsupported metric")
        rank = index.center_rank
        if rank is None:
            # legacy (pre-v3) index: compute once and cache OUTSIDE the
            # index (keyed on the centers array) — mutating a user-owned
            # index here would leak an unserializable side channel
            rank = _legacy_rank_cache(index.centers)
        # Lists are physically stored in spatial order only when the v3
        # build produced them: that build reorders list storage and leaves
        # center_rank == identity. A rank regenerated for a legacy file is
        # a genuine PCA-bisection permutation (never identity), so this
        # check is derived from the data — it survives serialization and
        # pytree round-trips, unlike an in-memory flag.
        legacy_order = not _rank_is_identity(rank)
        # Clamp the DMA group to the VMEM budget: two double-buffered list
        # blocks, plus the in-kernel f32 copy that int8/uint8 lists get
        # (f32 is identity, bf16 rides the MXU natively). Empirical limit:
        # 2 x 8 MB f32 blocks overflow the ~16 MB scoped budget, 2 x 4 MB
        # bf16 blocks fit with room.
        itemsize = index.list_data.dtype.itemsize
        cast_bytes = 4 if itemsize < 2 else 0
        per_group = index.max_list * index.dim * (2 * itemsize + cast_bytes)
        vmem_group_cap = max(1, (12 * 1024 * 1024) // max(1, per_group))
        group = max(1, min(params.fused_group, index.n_lists, vmem_group_cap))
        if legacy_order:
            # pre-v3 files store lists in arbitrary k-means order; grouping
            # assumes spatially adjacent lists, so fall back to single-list
            # DMA blocks rather than silently losing probe coverage
            group = 1
        while index.n_lists % group:
            group -= 1

        def run(qc):
            return ivf_flat_fused_search(
                index.centers,
                rank,
                index.list_data,
                index.list_indices,
                index.list_norms,
                qc,
                filter_bits,
                k=k,
                n_probes=n_probes,
                metric=index.metric,
                qt=params.fused_qt,
                probe_factor=params.fused_probe_factor,
                group=group,
                has_filter=filter_bits is not None,
                merge=params.fused_merge,
                precision=params.fused_precision,
                extract_every=params.fused_extract_every,
                col_chunk=params.fused_col_chunk,
                interpret=jax.default_backend() != "tpu",
            )

        return _batched_search(run, queries, query_batch)
    if mode == "scan":
        g = scan_chunk_lists(index.n_lists, index.max_list)

        def run_scan(qc):
            return _ivf_flat_scan_impl(
                index.centers,
                index.list_data,
                index.list_indices,
                index.list_norms,
                qc,
                filter_bits,
                k=k,
                n_probes=n_probes,
                metric=index.metric,
                has_filter=filter_bits is not None,
                chunk_lists=g,
            )

        return _batched_search(run_scan, queries, query_batch)

    def run_probe(qc):
        return _ivf_search_impl(
            index.centers,
            index.list_data,
            index.list_indices,
            index.list_norms,
            qc,
            filter_bits,
            k=k,
            n_probes=n_probes,
            metric=index.metric,
            has_filter=filter_bits is not None,
        )

    return _batched_search(run_probe, queries, query_batch)


# -- serialization (neighbors/ivf_flat_serialize.cuh analog) ----------------

_KIND = "ivf_flat"
_VERSION = 3


def _write_body(index: IvfFlatIndex, stream: BinaryIO) -> None:
    ser.serialize_scalar(stream, int(index.metric), "int32")
    ser.serialize_scalar(stream, int(index.size), "int64")
    ser.serialize_scalar(stream, float(index.list_cap_factor), "float64")
    ser.serialize_scalar(stream, int(index.list_norms is not None), "int32")
    ser.serialize_scalar(stream, int(index.center_rank is not None), "int32")
    ser.serialize_array(stream, index.centers)
    ser.serialize_array(stream, index.list_data)
    ser.serialize_array(stream, index.list_indices)
    ser.serialize_array(stream, index.list_sizes)
    if index.list_norms is not None:
        ser.serialize_array(stream, index.list_norms)
    if index.center_rank is not None:
        ser.serialize_array(stream, index.center_rank)


def save(index: IvfFlatIndex, stream: BinaryIO) -> None:
    body = io.BytesIO()
    _write_body(index, body)
    ser.save_stream(stream, _KIND, _VERSION, body.getvalue())


def load(stream: BinaryIO, res: Optional[Resources] = None) -> IvfFlatIndex:
    ensure_resources(res)
    version, stream = ser.load_stream(stream, _KIND)
    metric = DistanceType(ser.deserialize_scalar(stream, "int32"))
    size = int(ser.deserialize_scalar(stream, "int64"))
    cap_factor = float(ser.deserialize_scalar(stream, "float64")) if version >= 2 else 2.0
    has_norms = bool(ser.deserialize_scalar(stream, "int32"))
    has_rank = bool(ser.deserialize_scalar(stream, "int32")) if version >= 3 else False
    centers = ser.deserialize_array(stream)
    list_data = ser.deserialize_array(stream)
    list_indices = ser.deserialize_array(stream)
    list_sizes = ser.deserialize_array(stream)
    list_norms = ser.deserialize_array(stream) if has_norms else None
    center_rank = ser.deserialize_array(stream) if has_rank else None
    return IvfFlatIndex(
        centers=centers,
        list_data=list_data,
        list_indices=list_indices,
        list_sizes=list_sizes,
        list_norms=list_norms,
        metric=metric,
        size=size,
        list_cap_factor=cap_factor,
        center_rank=center_rank,
    )


def save_path(index: IvfFlatIndex, path: str) -> str:
    """Atomic (temp-then-rename) checksummed snapshot at ``path``."""
    return ser.atomic_write(path, lambda f: save(index, f))


def load_path(path: str, res: Optional[Resources] = None) -> IvfFlatIndex:
    with open(path, "rb") as f:
        return load(f, res=res)
