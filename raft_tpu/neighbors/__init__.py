"""ANN index layer (L5 analog): brute-force, IVF-Flat, IVF-PQ, CAGRA,
NN-descent, refine, filters.

See ``SURVEY.md`` §2.4 (``/root/reference/cpp/include/raft/neighbors``).
"""
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, nn_descent
from raft_tpu.neighbors.refine import refine

__all__ = ["brute_force", "cagra", "ivf_flat", "ivf_pq", "nn_descent", "refine"]
