"""ANN index layer (L5 analog): brute-force, IVF-Flat, IVF-PQ, CAGRA,
NN-descent, refine, filters.

See ``SURVEY.md`` §2.4 (``/root/reference/cpp/include/raft/neighbors``).
"""
from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    hnsw,
    ivf_flat,
    ivf_pq,
    nn_descent,
)
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors
from raft_tpu.neighbors.refine import refine

__all__ = [
    "ball_cover",
    "brute_force",
    "cagra",
    "eps_neighbors",
    "hnsw",
    "ivf_flat",
    "ivf_pq",
    "nn_descent",
    "refine",
]
