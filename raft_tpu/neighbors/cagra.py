"""CAGRA graph index — analog of ``raft::neighbors::cagra``.

Reference: build ``neighbors/detail/cagra/cagra_build.cuh:47,238,263``
(kNN graph via IVF-PQ search or NN-descent), 2-hop detour pruning
``detail/cagra/graph_core.cuh:130`` (``kern_prune``) + reverse-edge merge
(``graph_core.cuh:440-560``), search plan ``detail/cagra/search_plan.cuh:81``
and single-CTA greedy beam search
``detail/cagra/search_single_cta_kernel-inl.cuh:467``
(``pickup_next_parents:54``, bitonic topk ``:97,200``, visited hashmap
``detail/cagra/hashmap.hpp``). Index type ``neighbors/cagra_types.hpp:142``.

TPU-first redesign:

* **Pruning** is a dense batched computation: the detour count of edge
  A->B_rank_b — #{a < b : B ∈ G[G[A,a]]} — comes from a two-hop gather plus
  an equality-reduction scan over the higher-ranked neighbor axis; edges are
  then re-ranked by (detour_count, original rank) with one argsort. No
  atomics, no per-node kernels.
* **Reverse-edge merge** keeps the first ``degree/2`` forward edges
  protected and fills the tail with rank-limited reverse edges followed by
  the remaining forward edges, deduplicated with a sort-based keep-first
  compaction — the vectorized equivalent of the reference's shift-insert
  loop.
* **Search** is a fixed-iteration batched beam search under ``jit``: an
  ``itopk``-slot candidate buffer per query carries (distance, id, visited)
  — the visited *hashmap* becomes a visited *flag lane* merged by a
  sort-dedup (TPUs prefer sorted lanes over random scatter). Each step
  expands ``search_width`` best unvisited parents, gathers their fixed-
  degree adjacency rows, scores them with one MXU einsum, and re-selects
  the buffer. Data-dependent termination is replaced by a static iteration
  count (SURVEY.md §7 hard part (c)).

Supported metrics: L2Expanded, L2SqrtExpanded, InnerProduct.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.ops.select_k import running_merge_unique, select_k, worst_value
from raft_tpu.random.rng import as_key
from raft_tpu.utils.graph import reverse_edges

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
)

IVF_PQ = "ivf_pq"
NN_DESCENT = "nn_descent"


@dataclasses.dataclass
class CagraIndexParams:
    """``cagra::index_params`` analog (``neighbors/cagra_types.hpp:62``)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: str = NN_DESCENT
    metric: DistanceType = DistanceType.L2Expanded
    nn_descent_niter: int = 20
    seed: int = 0


@dataclasses.dataclass
class CagraSearchParams:
    """``cagra::search_params`` analog (``neighbors/cagra_types.hpp:85``)."""

    itopk_size: int = 64
    search_width: int = 1
    max_iterations: int = 0  # 0 = auto (search_plan.cuh:136 adjust)
    seed: int = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CagraIndex:
    """Fixed-degree graph + dataset (``cagra_types.hpp:142``)."""

    dataset: jax.Array  # [n, d]
    sqnorms: jax.Array  # [n] f32 (L2 metrics)
    graph: jax.Array  # [n, graph_degree] i32
    metric: DistanceType
    size: int

    def tree_flatten(self):
        return (self.dataset, self.sqnorms, self.graph), (self.metric, self.size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0], size=aux[1])

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


# ---------------------------------------------------------------------------
# graph optimization (prune + reverse merge)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("kout",))
def _detour_rerank_chunk(graph, chunk_ids, *, kout: int):
    """Detour counts for a chunk of nodes + re-rank (``kern_prune``,
    ``graph_core.cuh:130`` and the rank-ordered rebuild at ``:425-442``).

    For node A with ranked neighbors G[A]: detour(A, b) =
    #{a < b : G[A, b] ∈ G[G[A, a]]}. Edges are kept ordered by
    (detour count, original rank), truncated to ``kout``.
    """
    kin = graph.shape[1]
    rows = graph[chunk_ids]  # [c, kin]
    # rows may hold -1 padding (e.g. the IVF-PQ build path's short kNN
    # rows); a raw gather would wrap to the last node's adjacency and
    # pollute detour counts, so gather clipped and mask the contribution.
    rows_valid = rows >= 0  # [c, kin]
    two_hop = graph[jnp.maximum(rows, 0)]  # [c, kin, kin]

    def body(a, counts):
        # hit[c, b] = G[A, b] ∈ two_hop[A, a, :]
        hit = jnp.any(two_hop[:, a, :, None] == rows[:, None, :], axis=1)
        hit = hit & rows_valid[:, a][:, None]  # invalid rank-a edge: no 2-hop
        rank_mask = jnp.arange(kin) > a  # only edges ranked after a
        return counts + (hit & rank_mask[None, :]).astype(jnp.int32)

    counts = lax.fori_loop(0, kin, body, jnp.zeros(rows.shape, jnp.int32))
    # invalid (padded) edges sort last; order by (detour, rank) via one
    # composite-integer argsort
    counts = jnp.where(rows < 0, kin + 1, counts)
    key = counts * kin + jnp.arange(kin)[None, :]
    order = jnp.argsort(key, axis=1)
    return jnp.take_along_axis(rows, order[:, :kout], axis=1)


@functools.partial(jax.jit, static_argnames=("kout",))
def _merge_reverse(fwd, rev, *, kout: int):
    """Protected-head merge (``graph_core.cuh:525-555``): keep the first
    ``kout/2`` forward edges, fill the tail with reverse edges then the
    remaining forward edges, keep-first dedup, truncate to ``kout``."""
    n = fwd.shape[0]
    protected = kout // 2
    cand = jnp.concatenate([fwd[:, :protected], rev, fwd[:, protected:]], axis=1)
    m = cand.shape[1]
    # keep-first dedup: sort by (id, position); a sorted entry is a dup if
    # its predecessor holds the same id (an earlier position wins).
    pos = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32), cand.shape)
    # int32 composite requires n * (2*graph_degree) < 2^31; invalid ids all
    # tie at INT32_MAX (stable argsort keeps their relative order).
    composite = jnp.where(cand < 0, jnp.iinfo(jnp.int32).max, cand * m + pos)
    order = jnp.argsort(composite, axis=1, stable=True)
    ids_s = jnp.take_along_axis(cand, order, axis=1)
    pos_s = jnp.take_along_axis(pos, order, axis=1)
    prev = jnp.concatenate([jnp.full_like(ids_s[:, :1], -2), ids_s[:, :-1]], axis=1)
    dup = (ids_s == prev) | (ids_s < 0)
    # compact survivors back into original order, take first kout
    key2 = jnp.where(dup, m + pos_s, pos_s)
    order2 = jnp.argsort(key2, axis=1)
    merged = jnp.take_along_axis(ids_s, order2[:, :kout], axis=1)
    dup_k = jnp.take_along_axis(dup, order2[:, :kout], axis=1)
    return jnp.where(dup_k, -1, merged)


def optimize(knn_graph: jax.Array, graph_degree: int, node_chunk: int = 2048) -> jax.Array:
    """Prune an intermediate kNN graph to a fixed-degree CAGRA graph
    (``cagra::optimize``, ``cagra_build.cuh:263``)."""
    knn_graph = jnp.asarray(knn_graph, jnp.int32)
    n, kin = knn_graph.shape
    kout = min(graph_degree, kin)
    parts = []
    for s in range(0, n, node_chunk):
        ids = jnp.arange(s, min(s + node_chunk, n), dtype=jnp.int32)
        parts.append(_detour_rerank_chunk(knn_graph, ids, kout=kout))
    fwd = jnp.concatenate(parts, axis=0)
    # reverse lists ordered by forward rank: the reference's k-major
    # insertion order (kern_make_rev_graph, graph_core.cuh:480-500)
    rev = reverse_edges(fwd, n, kout, order_by_rank=True)
    return _merge_reverse(fwd, rev, kout=kout)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build(
    dataset,
    params: Optional[CagraIndexParams] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> CagraIndex:
    """Build the CAGRA index (``cagra::build``, ``cagra_build.cuh:293``):
    intermediate kNN graph via NN-descent or IVF-PQ+refine, then
    :func:`optimize`."""
    res = ensure_resources(res)
    if params is None:
        params = CagraIndexParams(**kwargs)
    metric = resolve_metric(params.metric)
    expects(metric in _SUPPORTED, "CAGRA does not support metric %s", metric)
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    n, d = dataset.shape
    kin = min(params.intermediate_graph_degree, n - 1)
    kout = min(params.graph_degree, kin)

    if params.build_algo == NN_DESCENT:
        from raft_tpu.neighbors import nn_descent

        out = nn_descent.build(
            dataset,
            nn_descent.NNDescentParams(
                graph_degree=kin,
                intermediate_graph_degree=min(max(kin + kin // 2, kin + 8), n - 1),
                max_iterations=params.nn_descent_niter,
                metric=metric,
                seed=params.seed,
            ),
        )
        knn_graph = out.graph
    else:
        expects(params.build_algo == IVF_PQ, "unknown build_algo %s", params.build_algo)
        from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
        from raft_tpu.neighbors.refine import refine as refine_fn

        # build_knn_graph via IVF-PQ search over the dataset itself + exact
        # re-rank (cagra_build.cuh:47-146)
        pq = ivf_pq_mod.build(
            dataset,
            ivf_pq_mod.IvfPqIndexParams(
                n_lists=max(1, min(1024, n // 128)), metric=metric, seed=params.seed
            ),
        )
        top = kin + 1
        _, cand = ivf_pq_mod.search(
            pq, dataset, min(2 * top, pq.size), n_probes=32, query_batch=4096
        )
        _, nbrs = refine_fn(dataset, dataset, cand, top, metric=metric)
        # drop self-edges, keep kin per row: stable argsort pushes the (at
        # most one) self-edge per row to the end — on device (shipping the
        # [n, kin] graph through the host link costs minutes at 1M rows)
        rows = jnp.arange(n, dtype=jnp.int32)[:, None]
        mask = nbrs != rows
        pos = jnp.argsort(~mask, axis=1, stable=True)[:, :kin]
        knn = jnp.take_along_axis(nbrs, pos, axis=1).astype(jnp.int32)
        knn_graph = jnp.where(jnp.take_along_axis(mask, pos, axis=1), knn, -1)

    graph = optimize(knn_graph, kout)
    data_f32 = dataset.astype(jnp.float32)
    sqnorms = jnp.sum(data_f32 * data_f32, axis=1)
    return CagraIndex(dataset=dataset, sqnorms=sqnorms, graph=graph, metric=metric, size=n)


def from_graph(dataset, graph, metric=DistanceType.L2Expanded) -> CagraIndex:
    """Assemble an index from a pre-built graph (``cagra::index`` ctor from
    existing dataset+graph views, ``cagra_types.hpp:253``)."""
    dataset = jnp.asarray(dataset)
    graph = jnp.asarray(graph, jnp.int32)
    expects(dataset.shape[0] == graph.shape[0], "dataset/graph row mismatch")
    data_f32 = dataset.astype(jnp.float32)
    return CagraIndex(
        dataset=dataset,
        sqnorms=jnp.sum(data_f32 * data_f32, axis=1),
        graph=graph,
        metric=resolve_metric(metric),
        size=dataset.shape[0],
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k", "itopk", "width", "iters", "metric", "has_filter"),
)
def _cagra_search_impl(
    dataset,
    sqnorms,
    graph,
    queries,
    init_ids,
    filter_bits,
    *,
    k: int,
    itopk: int,
    width: int,
    iters: int,
    metric: DistanceType,
    has_filter: bool,
):
    nq, d = queries.shape
    n, deg = graph.shape
    qf = queries.astype(jnp.float32)
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.asarray(worst_value(jnp.float32, select_min), jnp.float32)
    q_sqnorm = jnp.sum(qf * qf, axis=1)

    def score(cand):  # cand: [nq, c] ids, -1 invalid
        safe = jnp.clip(cand, 0, None)
        vecs = dataset[safe].astype(jnp.float32)  # [nq, c, d]
        # HIGHEST: single-pass bf16 MXU rounding visibly degrades beam
        # ranking (measured ~6 recall points on TPU); these matmuls are tiny
        # and HBM-bound, so full-precision passes cost ~nothing.
        dots = jnp.einsum(
            "qd,qcd->qc",
            qf,
            vecs,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )
        if select_min:
            dist = q_sqnorm[:, None] + sqnorms[safe] - 2.0 * dots
            dist = jnp.maximum(dist, 0.0)
        else:
            dist = dots
        invalid = cand < 0
        if has_filter:
            # filter at insertion (the reference's sample-filter hook inside
            # the search kernel): banned ids never occupy buffer slots, so
            # valid candidates keep competing even under selective filters
            word = filter_bits[jnp.clip(cand, 0, None) // 32]
            bit = (word >> (jnp.clip(cand, 0, None) % 32).astype(jnp.uint32)) & 1
            invalid = invalid | (bit == 0)
        return jnp.where(invalid, worst, dist)

    # -- init: random seed candidates (search_plan random init) -------------
    # The visited-flag lane through running_merge_unique is the sort-based
    # stand-in for the CUDA visited hashmap + bitonic merge
    # (search_single_cta_kernel-inl.cuh:97-200).
    init_d = score(init_ids)
    buf_v, buf_i, buf_f = running_merge_unique(
        jnp.full((nq, itopk), worst, jnp.float32),
        jnp.full((nq, itopk), -1, jnp.int32),
        init_d,
        init_ids,
        select_min=select_min,
        acc_flags=jnp.zeros((nq, itopk), bool),
    )

    def body(_, carry):
        buf_v, buf_i, buf_f = carry
        # pickup_next_parents (:54): best `width` unvisited entries
        masked = jnp.where(buf_f | (buf_i < 0), worst, buf_v)
        _, ppos = select_k(masked, width, select_min=select_min)
        parents = jnp.take_along_axis(buf_i, ppos, axis=1)  # [nq, width]
        pvalid = jnp.take_along_axis(masked, ppos, axis=1) != worst
        parents = jnp.where(pvalid, parents, -1)
        rows = jnp.arange(nq)[:, None]
        buf_f = buf_f.at[rows, ppos].set(True)
        # expand fixed-degree adjacency
        nbrs = graph[jnp.clip(parents, 0, None)]  # [nq, width, deg]
        nbrs = jnp.where(parents[:, :, None] >= 0, nbrs, -1).reshape(nq, width * deg)
        dist = score(nbrs)
        return running_merge_unique(
            buf_v, buf_i, dist, nbrs, select_min=select_min, acc_flags=buf_f
        )

    buf_v, buf_i, buf_f = lax.fori_loop(0, iters, body, (buf_v, buf_i, buf_f))

    vals, idx = buf_v[:, :k], buf_i[:, :k]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


def derive_search_config(params: "CagraSearchParams", k: int, size: int):
    """(itopk, width, iters, n_init) from search params — the
    ``search_plan.cuh:136`` adjust step, shared with the sharded path."""
    itopk = max(params.itopk_size, k)
    width = max(1, params.search_width)
    iters = params.max_iterations or max(10, itopk // max(1, width))
    return itopk, width, iters, min(itopk, size)


def search(
    index: CagraIndex,
    queries,
    k: int,
    params: Optional[CagraSearchParams] = None,
    prefilter: Optional[Bitset] = None,
    query_batch: int = 1024,
    res: Optional[Resources] = None,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy beam search over the graph (``cagra::search``,
    ``detail/cagra/cagra_search.cuh:249``). Returns best-first
    ``(distances [nq, k], indices [nq, k])``; unfilled slots get id -1."""
    ensure_resources(res)
    if params is None:
        params = CagraSearchParams(**kwargs)
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "bad query shape")
    expects(k >= 1, "k must be >= 1")
    # auto iteration count (search_plan.cuh:136 adjust_search_params)
    itopk, width, iters, n_init = derive_search_config(params, k, index.size)
    if prefilter is not None:
        expects(prefilter.size >= index.size, "prefilter smaller than index")
    filter_bits = prefilter.bits if prefilter is not None else None

    nq = queries.shape[0]
    key = as_key(params.seed)

    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qc = queries[start : start + query_batch]
        bpad = 0
        if qc.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qc.shape[0]
            qc = jnp.pad(qc, ((0, bpad), (0, 0)))
        key, kb = jax.random.split(key)
        init_ids = jax.random.randint(kb, (qc.shape[0], n_init), 0, index.size, jnp.int32)
        v, i = _cagra_search_impl(
            index.dataset,
            index.sqnorms,
            index.graph,
            qc,
            init_ids,
            filter_bits,
            k=k,
            itopk=itopk,
            width=width,
            iters=iters,
            metric=index.metric,
            has_filter=filter_bits is not None,
        )
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


# ---------------------------------------------------------------------------
# serialization (neighbors/cagra_serialize.cuh analog)
# ---------------------------------------------------------------------------

_KIND = "cagra"
_VERSION = 1


def save(index: CagraIndex, stream: BinaryIO, include_dataset: bool = True) -> None:
    ser.dump_header(stream, _KIND, _VERSION)
    ser.serialize_scalar(stream, int(index.metric), "int32")
    ser.serialize_scalar(stream, int(index.size), "int64")
    ser.serialize_scalar(stream, int(include_dataset), "int32")
    ser.serialize_array(stream, index.graph)
    if include_dataset:
        ser.serialize_array(stream, index.dataset)


def load(stream: BinaryIO, dataset=None, res: Optional[Resources] = None) -> CagraIndex:
    """Load an index; if it was saved without the dataset, one must be
    supplied (mirrors the reference's dataset-less serialize mode,
    ``cagra_serialize.cuh``)."""
    ensure_resources(res)
    ser.check_header(stream, _KIND)
    metric = DistanceType(ser.deserialize_scalar(stream, "int32"))
    size = int(ser.deserialize_scalar(stream, "int64"))
    has_ds = bool(ser.deserialize_scalar(stream, "int32"))
    graph = ser.deserialize_array(stream)
    if has_ds:
        data = ser.deserialize_array(stream)
    else:
        expects(dataset is not None, "index was saved without dataset; pass one")
        data = jnp.asarray(dataset)
    expects(data.shape[0] == size, "dataset rows != index size")
    return from_graph(data, graph, metric)
