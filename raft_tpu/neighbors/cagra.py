"""CAGRA graph index — analog of ``raft::neighbors::cagra``.

Reference: build ``neighbors/detail/cagra/cagra_build.cuh:47,238,263``
(kNN graph via IVF-PQ search or NN-descent), 2-hop detour pruning
``detail/cagra/graph_core.cuh:130`` (``kern_prune``) + reverse-edge merge
(``graph_core.cuh:440-560``), search plan ``detail/cagra/search_plan.cuh:81``
and single-CTA greedy beam search
``detail/cagra/search_single_cta_kernel-inl.cuh:467``
(``pickup_next_parents:54``, bitonic topk ``:97,200``, visited hashmap
``detail/cagra/hashmap.hpp``). Index type ``neighbors/cagra_types.hpp:142``.

TPU-first redesign:

* **Pruning** is a dense batched computation: the detour count of edge
  A->B_rank_b — #{a < b : B ∈ G[G[A,a]]} — comes from a two-hop gather plus
  an equality-reduction scan over the higher-ranked neighbor axis; edges are
  then re-ranked by (detour_count, original rank) with one argsort. No
  atomics, no per-node kernels.
* **Reverse-edge merge** keeps the first ``degree/2`` forward edges
  protected and fills the tail with rank-limited reverse edges followed by
  the remaining forward edges, deduplicated with a sort-based keep-first
  compaction — the vectorized equivalent of the reference's shift-insert
  loop.
* **Search** is a fixed-iteration batched beam search under ``jit``: an
  ``itopk``-slot candidate buffer per query carries (distance, id, visited)
  — the visited *hashmap* becomes a visited *flag lane* merged by a
  sort-dedup (TPUs prefer sorted lanes over random scatter). Each step
  expands ``search_width`` best unvisited parents, gathers their fixed-
  degree adjacency rows, scores them with one MXU einsum, and re-selects
  the buffer. Data-dependent termination is replaced by a static iteration
  count (SURVEY.md §7 hard part (c)).

Supported metrics: L2Expanded, L2SqrtExpanded, InnerProduct.
"""
from __future__ import annotations

import dataclasses
import functools
import io
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.ops.select_k import running_merge_unique, select_k, worst_value
from raft_tpu.random.rng import as_key
from raft_tpu.robust import fallback as _fallback, faults as _faults
from raft_tpu.utils.graph import reverse_edges

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
)

IVF_PQ = "ivf_pq"
NN_DESCENT = "nn_descent"


@dataclasses.dataclass
class CagraIndexParams:
    """``cagra::index_params`` analog (``neighbors/cagra_types.hpp:62``)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: str = NN_DESCENT
    metric: DistanceType = DistanceType.L2Expanded
    nn_descent_niter: int = 20
    seed: int = 0


@dataclasses.dataclass
class CagraSearchParams:
    """``cagra::search_params`` analog (``neighbors/cagra_types.hpp:85``).

    ``init_sample``: seed the beam from the best-scoring of this many
    evenly strided dataset rows (scored exactly with ONE [nq, S] MXU
    matmul) instead of purely random ids — the in-tree analog of the
    reference's optional seed points (``search_plan.cuh:100`` ``dev_seed``
    + ``num_random_samplings``). On clustered data random inits rarely
    land near the query's cluster and the pruned fixed-degree graph has
    few long-range edges to recover, so sampled seeding is the difference
    between ~0.2 and ~0.9 recall at 1M scale. 0 = legacy random init.

    ``seed`` only affects the legacy random init (``init_sample=0``): the
    default strided-sample path is deterministic and ignores it."""

    itopk_size: int = 64
    search_width: int = 1
    max_iterations: int = 0  # 0 = auto (search_plan.cuh:136 adjust)
    seed: int = 0
    init_sample: int = 4096
    # fused (Pallas) path knobs — see ops/pallas/cagra_search.py. ``qt``
    # is the per-grid-step query tile (VMEM-modeled at 32);
    # ``fused_table_dtype`` trades table HBM footprint for score
    # precision (bf16 halves the deg-x table; use float32 for
    # bit-faithful parity runs).
    fused_qt: int = 32
    fused_table_dtype: str = "bfloat16"
    # Candidate deduplication strategy per iteration:
    #   "sort" — id-sort + adjacent-compare + re-select (two sorts; the
    #            round-3 default, exact).
    #   "post" — single value-sort merge, then adjacent-id kill on the
    #            RESULT: duplicates of one id carry the same distance, so
    #            a stable value sort makes them adjacent, and the stable
    #            tie order guarantees the visited (buffered) copy
    #            survives. Half the sort work of "sort"; dup copies decay
    #            to dead slots instead of re-selectable ghosts. Default.
    #   "none" — no dedup. NOT recommended: unflagged duplicates of
    #            already-expanded nodes get re-picked as parents forever
    #            and the beam stalls (measured: recall 0.97 -> 0.39).
    # True/False are accepted as aliases of "sort"/"none".
    dedup: str = "post"


@dataclasses.dataclass
class VpqParams:
    """``vpq_params`` analog (``neighbors/dataset.hpp:210-235``): coarse
    vector quantization + product quantization of the residual."""

    vq_n_centers: int = 0  # 0 = auto (~sqrt(n))
    pq_dim: int = 0  # 0 = auto (dim / 4, min 1)
    pq_bits: int = 8
    kmeans_n_iters: int = 15
    seed: int = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class VpqDataset:
    """VQ+PQ compressed dataset (``vpq_dataset``,
    ``neighbors/dataset.hpp:236-259``): each row is a coarse VQ center
    plus PQ-coded residual, ~pq_dim bytes/row instead of 4*dim — the
    beyond-HBM story for large CAGRA datasets. Decoding during beam
    search is a one-hot MXU matmul (TPUs have no fast per-lane gather)."""

    vq_centers: jax.Array  # [vq_n, d] f32
    vq_labels: jax.Array  # [n] i32
    pq_centers: jax.Array  # [pq_dim, ksub, pq_len] f32
    codes: jax.Array  # [n, pq_dim] u8
    sqnorms: jax.Array  # [n] f32 — ||decoded row||^2, precomputed

    def tree_flatten(self):
        return (self.vq_centers, self.vq_labels, self.pq_centers, self.codes, self.sqnorms), ()

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def pq_dim(self) -> int:
        return self.codes.shape[1]

    @property
    def ksub(self) -> int:
        return self.pq_centers.shape[1]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CagraIndex:
    """Fixed-degree graph + dataset (``cagra_types.hpp:142``). The dataset
    is either raw rows or a :class:`VpqDataset` (``neighbors/dataset.hpp:37``
    strided vs ``:259`` vpq dataset variants)."""

    dataset: Optional[jax.Array]  # [n, d], or None when vpq is set
    sqnorms: Optional[jax.Array]  # [n] f32 (L2 metrics)
    graph: jax.Array  # [n, graph_degree] i32
    metric: DistanceType
    size: int
    vpq: Optional[VpqDataset] = None
    dim_hint: int = 0  # feature dim when dataset is compressed away

    def tree_flatten(self):
        return (self.dataset, self.sqnorms, self.graph, self.vpq), (
            self.metric,
            self.size,
            self.dim_hint,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(
            dataset=children[0],
            sqnorms=children[1],
            graph=children[2],
            vpq=children[3],
            metric=aux[0],
            size=aux[1],
            dim_hint=aux[2],
        )

    @property
    def dim(self) -> int:
        if self.dataset is not None:
            return self.dataset.shape[1]
        return self.dim_hint or self.vpq.vq_centers.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


# ---------------------------------------------------------------------------
# graph optimization (prune + reverse merge)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("kout",))
def _detour_rerank_chunk(graph, chunk_ids, *, kout: int):
    """Detour counts for a chunk of nodes + re-rank (``kern_prune``,
    ``graph_core.cuh:130`` and the rank-ordered rebuild at ``:425-442``).

    For node A with ranked neighbors G[A]: detour(A, b) =
    #{a < b : G[A, b] ∈ G[G[A, a]]}. Edges are kept ordered by
    (detour count, original rank), truncated to ``kout``.

    Membership is a SORTED two-hop adjacency + batched binary search
    (O(kin² log kin) per node instead of the O(kin³) equality scan —
    TPUs have no hash sets, but vmapped searchsorted vectorizes cleanly).
    """
    kin = graph.shape[1]
    rows = graph[chunk_ids]  # [c, kin]
    # rows may hold -1 padding (e.g. the IVF-PQ build path's short kNN
    # rows); a raw gather would wrap to the last node's adjacency and
    # pollute detour counts, so gather clipped and mask the contribution.
    rows_valid = rows >= 0  # [c, kin]
    two_hop = graph[jnp.maximum(rows, 0)]  # [c, kin, kin]
    th_sorted = jnp.sort(two_hop, axis=-1)

    def member(th_a, targets):  # th_a [kin] sorted, targets [kin]
        pos = jnp.clip(jnp.searchsorted(th_a, targets), 0, kin - 1)
        return th_a[pos] == targets

    # hit[c, a, b] = G[A, b] ∈ G[G[A, a]]
    hit = jax.vmap(jax.vmap(member, in_axes=(0, None)))(th_sorted, rows)
    hit = hit & rows_valid[:, :, None]  # invalid rank-a edge: no 2-hop
    a_lt_b = jnp.arange(kin)[:, None] < jnp.arange(kin)[None, :]
    counts = jnp.sum(hit & a_lt_b[None, :, :], axis=1).astype(jnp.int32)
    # invalid (padded) edges sort last; order by (detour, rank) via one
    # composite-integer argsort
    counts = jnp.where(rows < 0, kin + 1, counts)
    key = counts * kin + jnp.arange(kin)[None, :]
    order = jnp.argsort(key, axis=1)
    return jnp.take_along_axis(rows, order[:, :kout], axis=1)


@functools.partial(jax.jit, static_argnames=("kout",))
def _merge_reverse(fwd, rev, *, kout: int):
    """Protected-head merge (``graph_core.cuh:525-555``): keep the first
    ``kout/2`` forward edges, fill the tail with reverse edges then the
    remaining forward edges, keep-first dedup, truncate to ``kout``."""
    n = fwd.shape[0]
    protected = kout // 2
    cand = jnp.concatenate([fwd[:, :protected], rev, fwd[:, protected:]], axis=1)
    m = cand.shape[1]
    # keep-first dedup: sort by (id, position); a sorted entry is a dup if
    # its predecessor holds the same id (an earlier position wins).
    pos = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32), cand.shape)
    # int32 composite requires n * (2*graph_degree) < 2^31; invalid ids all
    # tie at INT32_MAX (stable argsort keeps their relative order).
    composite = jnp.where(cand < 0, jnp.iinfo(jnp.int32).max, cand * m + pos)
    order = jnp.argsort(composite, axis=1, stable=True)
    ids_s = jnp.take_along_axis(cand, order, axis=1)
    pos_s = jnp.take_along_axis(pos, order, axis=1)
    prev = jnp.concatenate([jnp.full_like(ids_s[:, :1], -2), ids_s[:, :-1]], axis=1)
    dup = (ids_s == prev) | (ids_s < 0)
    # compact survivors back into original order, take first kout
    key2 = jnp.where(dup, m + pos_s, pos_s)
    order2 = jnp.argsort(key2, axis=1)
    merged = jnp.take_along_axis(ids_s, order2[:, :kout], axis=1)
    dup_k = jnp.take_along_axis(dup, order2[:, :kout], axis=1)
    return jnp.where(dup_k, -1, merged)


def optimize(knn_graph: jax.Array, graph_degree: int, node_chunk: int = 2048) -> jax.Array:
    """Prune an intermediate kNN graph to a fixed-degree CAGRA graph
    (``cagra::optimize``, ``cagra_build.cuh:263``)."""
    knn_graph = jnp.asarray(knn_graph, jnp.int32)
    n, kin = knn_graph.shape
    kout = min(graph_degree, kin)
    parts = []
    for s in range(0, n, node_chunk):
        ids = jnp.arange(s, min(s + node_chunk, n), dtype=jnp.int32)
        parts.append(_detour_rerank_chunk(knn_graph, ids, kout=kout))
    fwd = jnp.concatenate(parts, axis=0)
    # reverse lists ordered by forward rank: the reference's k-major
    # insertion order (kern_make_rev_graph, graph_core.cuh:480-500)
    rev = reverse_edges(fwd, n, kout, order_by_rank=True)
    return _merge_reverse(fwd, rev, kout=kout)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build(
    dataset,
    params: Optional[CagraIndexParams] = None,
    res: Optional[Resources] = None,
    pq_index=None,
    **kwargs,
) -> CagraIndex:
    """Build the CAGRA index (``cagra::build``, ``cagra_build.cuh:293``):
    intermediate kNN graph via NN-descent or IVF-PQ+refine, then
    :func:`optimize`. ``pq_index``: an already-built
    :class:`~raft_tpu.neighbors.ivf_pq.IvfPqIndex` over this dataset to
    reuse for the ``build_algo="ivf_pq"`` path (skips the internal PQ
    build — callers that serve both indexes build once)."""
    res = ensure_resources(res)
    if params is None:
        params = CagraIndexParams(**kwargs)
    metric = resolve_metric(params.metric)
    expects(metric in _SUPPORTED, "CAGRA does not support metric %s", metric)
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    n, d = dataset.shape
    kin = min(params.intermediate_graph_degree, n - 1)
    kout = min(params.graph_degree, kin)

    if params.build_algo == NN_DESCENT:
        from raft_tpu.neighbors import nn_descent

        out = nn_descent.build(
            dataset,
            nn_descent.NNDescentParams(
                graph_degree=kin,
                intermediate_graph_degree=min(max(kin + kin // 2, kin + 8), n - 1),
                max_iterations=params.nn_descent_niter,
                metric=metric,
                seed=params.seed,
            ),
        )
        knn_graph = out.graph
    else:
        expects(params.build_algo == IVF_PQ, "unknown build_algo %s", params.build_algo)
        from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
        from raft_tpu.neighbors.refine import refine as refine_fn

        # build_knn_graph via IVF-PQ search over the dataset itself + exact
        # re-rank (cagra_build.cuh:47-146). Additive-nibble codebooks make
        # the index eligible for the fused Pallas scan, which is what
        # makes this path the fast 1M-scale default (vs ~16 min of
        # NN-descent local joins on the same hardware).
        import time as _time

        from raft_tpu.core.logging import logger

        t0 = _time.perf_counter()
        with obs.span("cagra.build.pq_build", n=n):
            if pq_index is not None:
                expects(pq_index.size == n, "pq_index covers %d rows, dataset has %d", pq_index.size, n)
                pq = pq_index
            else:
                pq = ivf_pq_mod.build(
                    dataset,
                    ivf_pq_mod.IvfPqIndexParams(
                        n_lists=max(1, min(1024, n // 128)),
                        metric=metric,
                        seed=params.seed,
                        # pq_dim 32 keeps the fused decode LUT small (K = 32*32
                        # columns); graph-build shortlists only need coarse
                        # ranking, the exact refine below restores order
                        pq_dim=32 if d >= 64 and d % 32 == 0 else 0,
                        pq_kind="nibble",
                        kmeans_n_iters=10,
                        kmeans_trainset_fraction=min(1.0, max(0.05, 100_000 / max(n, 1))),
                        list_cap_factor=1.1,
                    ),
                )
            jax.block_until_ready(pq.codes)
        t1 = _time.perf_counter()
        top = kin + 1
        with obs.span("cagra.build.self_search", n=n):
            _, cand = ivf_pq_mod.search(
                pq, dataset, min(2 * top, pq.size), n_probes=24, query_batch=4096
            )
            jax.block_until_ready(cand)
        t2 = _time.perf_counter()
        with obs.span("cagra.build.refine", n=n):
            _, nbrs = refine_fn(dataset, dataset, cand, top, metric=metric)
            jax.block_until_ready(nbrs)
        logger.info(
            "cagra ivf_pq graph build: pq_build %.1fs, self-search %.1fs, refine %.1fs",
            t1 - t0, t2 - t1, _time.perf_counter() - t2,
        )
        # drop self-edges, keep kin per row: stable argsort pushes the (at
        # most one) self-edge per row to the end — on device (shipping the
        # [n, kin] graph through the host link costs minutes at 1M rows)
        rows = jnp.arange(n, dtype=jnp.int32)[:, None]
        mask = nbrs != rows
        pos = jnp.argsort(~mask, axis=1, stable=True)[:, :kin]
        knn = jnp.take_along_axis(nbrs, pos, axis=1).astype(jnp.int32)
        knn_graph = jnp.where(jnp.take_along_axis(mask, pos, axis=1), knn, -1)

    graph = optimize(knn_graph, kout)
    data_f32 = dataset.astype(jnp.float32)
    sqnorms = jnp.sum(data_f32 * data_f32, axis=1)
    return CagraIndex(dataset=dataset, sqnorms=sqnorms, graph=graph, metric=metric, size=n)


def from_graph(dataset, graph, metric=DistanceType.L2Expanded) -> CagraIndex:
    """Assemble an index from a pre-built graph (``cagra::index`` ctor from
    existing dataset+graph views, ``cagra_types.hpp:253``)."""
    dataset = jnp.asarray(dataset)
    graph = jnp.asarray(graph, jnp.int32)
    expects(dataset.shape[0] == graph.shape[0], "dataset/graph row mismatch")
    data_f32 = dataset.astype(jnp.float32)
    return CagraIndex(
        dataset=dataset,
        sqnorms=jnp.sum(data_f32 * data_f32, axis=1),
        graph=graph,
        metric=resolve_metric(metric),
        size=dataset.shape[0],
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _pick_positions(vals, w: int, worst):
    """Positions of the ``w`` best entries per row via w rounds of
    min-extract — VPU compare/select passes instead of the full sort
    ``lax.top_k`` lowers to (the beam only needs 1-4 parents out of
    itopk, so a sort is ~10x overkill per iteration)."""
    cols = lax.broadcasted_iota(jnp.int32, vals.shape, 1)
    big = jnp.int32(2**30)
    poss, valids = [], []
    for _ in range(w):
        mv = jnp.min(vals, axis=1, keepdims=True)
        sel = jnp.min(jnp.where(vals == mv, cols, big), axis=1, keepdims=True)
        poss.append(sel)
        valids.append(mv != worst)
        vals = jnp.where(cols == sel, worst, vals)
    return jnp.concatenate(poss, axis=1), jnp.concatenate(valids, axis=1)


def _seed_select(qf, q_sqnorm, vecs, vsq, init_ids, *, itopk, select_min, worst,
                 filter_bits, has_filter):
    """Score the shared strided seed rows (one [nq, S] MXU matmul — the
    ``dev_seed`` analog) and select the initial ``itopk`` beam. Shared
    by the XLA and fused search paths so both start from an IDENTICAL
    beam: (values, ids) with ``worst``/-1 in unfilled slots."""
    s = init_ids.shape[0]
    dots = jnp.dot(
        qf, vecs.T, preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST
    )
    if select_min:
        sample_d = jnp.maximum(q_sqnorm[:, None] + vsq[None, :] - 2.0 * dots, 0.0)
    else:
        sample_d = dots
    if has_filter:
        word = filter_bits[init_ids // 32]
        bit = (word >> (init_ids % 32).astype(jnp.uint32)) & 1
        sample_d = jnp.where((bit == 1)[None, :], sample_d, worst)
    kk = min(itopk, s)
    v0, pos = select_k(sample_d, kk, select_min=select_min)
    i0 = jnp.where(v0 != worst, init_ids[pos], -1)
    if kk < itopk:
        v0 = jnp.pad(v0, ((0, 0), (0, itopk - kk)), constant_values=worst)
        i0 = jnp.pad(i0, ((0, 0), (0, itopk - kk)), constant_values=-1)
    return v0, i0


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "itopk", "width", "iters", "metric", "has_filter", "use_vpq", "dedup"
    ),
)
def _cagra_search_impl(
    dataset,
    sqnorms,
    graph,
    queries,
    init_ids,
    filter_bits,
    vpq_arrays=None,  # (vq_centers, vq_labels, pq_centers, codes) or None
    *,
    k: int,
    itopk: int,
    width: int,
    iters: int,
    metric: DistanceType,
    has_filter: bool,
    use_vpq: bool = False,
    dedup: str = "post",
):
    nq, d = queries.shape
    n, deg = graph.shape
    qf = queries.astype(jnp.float32)
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.asarray(worst_value(jnp.float32, select_min), jnp.float32)
    q_sqnorm = jnp.sum(qf * qf, axis=1)

    def gather_vecs(safe):
        if not use_vpq:
            return dataset[safe].astype(jnp.float32)  # [nq, c, d]
        # VPQ decode (dataset.hpp:259 vpq_dataset): coarse VQ center +
        # one-hot-matmul PQ residual — the TPU substitute for the CUDA
        # per-lane LUT gather
        vq_centers, vq_labels, pq_centers, codes = vpq_arrays
        ksub = pq_centers.shape[1]
        b, c = safe.shape  # b == nq for beam rows, 1 for the shared seed row
        base = vq_centers[vq_labels[safe]]  # [b, c, d]
        cod = codes[safe].astype(jnp.int32)  # [b, c, pq_dim]
        onehot = (
            cod[..., None] == jnp.arange(ksub, dtype=jnp.int32)
        ).astype(jnp.float32)
        resid = jnp.einsum(
            "qcjs,jst->qcjt", onehot, pq_centers, preferred_element_type=jnp.float32
        )
        return base + resid.reshape(b, c, d)

    def score(cand):  # cand: [nq, c] ids, -1 invalid
        safe = jnp.clip(cand, 0, None)
        vecs = gather_vecs(safe)
        # HIGHEST: single-pass bf16 MXU rounding visibly degrades beam
        # ranking (measured ~6 recall points on TPU); these matmuls are tiny
        # and HBM-bound, so full-precision passes cost ~nothing.
        dots = jnp.einsum(
            "qd,qcd->qc",
            qf,
            vecs,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )
        if select_min:
            dist = q_sqnorm[:, None] + sqnorms[safe] - 2.0 * dots
            dist = jnp.maximum(dist, 0.0)
        else:
            dist = dots
        invalid = cand < 0
        if has_filter:
            # filter at insertion (the reference's sample-filter hook inside
            # the search kernel): banned ids never occupy buffer slots, so
            # valid candidates keep competing even under selective filters
            word = filter_bits[jnp.clip(cand, 0, None) // 32]
            bit = (word >> (jnp.clip(cand, 0, None) % 32).astype(jnp.uint32)) & 1
            invalid = invalid | (bit == 0)
        return jnp.where(invalid, worst, dist)

    # -- init: seed candidates ----------------------------------------------
    # The visited-flag lane through running_merge_unique is the sort-based
    # stand-in for the CUDA visited hashmap + bitonic merge
    # (search_single_cta_kernel-inl.cuh:97-200).
    if init_ids.ndim == 1:
        # shared strided sample (dev_seed analog): all queries score the
        # same S rows, so the gather is [S, d] once and the scoring is one
        # MXU matmul — no [nq, S, d] blowup
        v0, i0 = _seed_select(
            qf, q_sqnorm, gather_vecs(init_ids[None, :])[0], sqnorms[init_ids],
            init_ids, itopk=itopk, select_min=select_min, worst=worst,
            filter_bits=filter_bits, has_filter=has_filter,
        )
        buf_v, buf_i, buf_f = v0, i0, jnp.zeros((nq, itopk), bool)
    else:
        init_d = score(init_ids)
        buf_v, buf_i, buf_f = running_merge_unique(
            jnp.full((nq, itopk), worst, jnp.float32),
            jnp.full((nq, itopk), -1, jnp.int32),
            init_d,
            init_ids,
            select_min=select_min,
            acc_flags=jnp.zeros((nq, itopk), bool),
        )

    def _expand_parents(masked, ids_at):
        """Shared pickup_next_parents (:54) → adjacency → score prologue:
        the best ``width`` unvisited buffer entries (width rounds of
        min-extract, not a full sort) parent a fixed-degree expansion.
        ``ids_at(ppos)`` reads parent ids from the carry's own
        representation; returns (ppos, rows, nbrs, dist)."""
        ppos, pvalid = _pick_positions(
            masked if select_min else -masked, width, jnp.inf
        )
        parents = jnp.where(pvalid, ids_at(ppos), -1)  # [nq, width]
        rows = jnp.arange(nq)[:, None]
        nbrs = graph[jnp.clip(parents, 0, None)]  # [nq, width, deg]
        nbrs = jnp.where(parents[:, :, None] >= 0, nbrs, -1).reshape(nq, width * deg)
        return ppos, rows, nbrs, score(nbrs)

    def body_sort(_, carry):
        buf_v, buf_i, buf_f = carry
        masked = jnp.where(buf_f | (buf_i < 0), worst, buf_v)
        ppos, rows, nbrs, dist = _expand_parents(
            masked, lambda p: jnp.take_along_axis(buf_i, p, axis=1)
        )
        buf_f = buf_f.at[rows, ppos].set(True)
        return running_merge_unique(
            buf_v, buf_i, dist, nbrs, select_min=select_min, acc_flags=buf_f
        )

    def body_packed(_, carry):
        # "post"/"none" fast path: the (id, visited) pair rides as ONE
        # int32 lane ``idf = id * 2 + flag`` through the value-sorted
        # merge — one take_along_axis instead of three per iteration
        # (measured ~20% of the per-iteration cost). id = -1 decodes
        # from both packings: -2 >> 1 == -1 (flag 0), -1 >> 1 == -1
        # (flag 1); requires ids < 2^30 like running_merge_unique.
        buf_v, buf_idf = carry
        masked = jnp.where(((buf_idf & 1) == 1) | (buf_idf < 0), worst, buf_v)
        ppos, rows, nbrs, dist = _expand_parents(
            masked, lambda p: jnp.take_along_axis(buf_idf >> 1, p, axis=1)
        )
        buf_idf = buf_idf.at[rows, ppos].set(
            jnp.take_along_axis(buf_idf, ppos, axis=1) | 1
        )
        # one value-sorted selection; "post" then kills adjacent duplicate
        # ids on the result (equal ids carry equal distances, and stable
        # tie order keeps the buffered/visited copy first)
        vals = jnp.concatenate([buf_v, jnp.where(nbrs < 0, worst, dist)], axis=1)
        idfs = jnp.concatenate([buf_idf, nbrs * 2], axis=1)
        out_v, pos = select_k(vals, itopk, select_min=select_min)
        out_idf = jnp.take_along_axis(idfs, pos, axis=1)
        out_idf = jnp.where(out_v == worst, -1, out_idf)
        if dedup == "post":
            out_i = out_idf >> 1
            prev = jnp.concatenate([jnp.full_like(out_i[:, :1], -2), out_i[:, :-1]], axis=1)
            dup = (out_i == prev) & (out_i >= 0)
            out_v = jnp.where(dup, worst, out_v)
            out_idf = jnp.where(dup, -1, out_idf)  # -1 = id -1, flagged: never parents
        return out_v, out_idf

    if dedup == "sort":
        buf_v, buf_i, buf_f = lax.fori_loop(0, iters, body_sort, (buf_v, buf_i, buf_f))
    else:
        buf_idf = buf_i * 2 + buf_f.astype(jnp.int32)
        buf_idf = jnp.where(buf_i < 0, -1, buf_idf)  # invalid slots stay non-parents
        buf_v, buf_idf = lax.fori_loop(0, iters, body_packed, (buf_v, buf_idf))
        buf_i = buf_idf >> 1
        buf_f = (buf_idf & 1) == 1
    if dedup in ("none", "post"):
        # one final sort-dedup so duplicate ids cannot occupy several of
        # the returned top-k slots. Needed for "post" too: the shared-seed
        # init scores via a [nq,s] dot while loop expansions use score()'s
        # einsum, and the two contractions can round differently — an
        # init-seeded node re-proposed during expansion then isn't
        # value-adjacent to its buffered copy, so the per-iteration
        # adjacent-id kill misses it
        buf_v, buf_i, buf_f = running_merge_unique(
            buf_v, buf_i,
            jnp.full((nq, 1), worst, jnp.float32), jnp.full((nq, 1), -1, jnp.int32),
            select_min=select_min, acc_flags=buf_f,
        )

    vals, idx = buf_v[:, :k], buf_i[:, :k]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


def strided_seed_ids(size: int, sample: int) -> jnp.ndarray:
    """``min(sample, size)`` DISTINCT evenly spaced seed ids:
    ``floor(i * size / sample)`` — covers the whole id range whatever the
    build order groups (a fixed integer stride either truncates coverage
    or collapses onto a subgroup when it divides ``size``). Shared by the
    local and sharded search paths (``dev_seed`` analog,
    ``search_plan.cuh:100``)."""
    s = min(sample, size)
    # host-side int64: jnp.arange(int64) silently downgrades to int32 when
    # jax_enable_x64 is off, and i * size overflows int32 at ~2k seeds on
    # a 1M-row index
    return jnp.asarray((np.arange(s, dtype=np.int64) * size) // s, jnp.int32)


def plan_search_params(
    nq: int, k: int, size: int, base: Optional["CagraSearchParams"] = None
) -> "CagraSearchParams":
    """Pick the search schedule from the query-batch shape — the
    ``search_plan.cuh:81-164`` plan-selection analog. The reference
    chooses among three kernel schedules (single-CTA for big batches,
    multi-CTA / multi-kernel to keep one GPU busy on few queries); on TPU
    a single fused batched schedule serves every shape, so the plan
    moves the latency/throughput trade through
    ``(search_width, init_sample)``:

    * **every default-width call** gets the wide (width-8) beam: the
      fixed per-iteration cost (buffer merge, flag bookkeeping, host
      dispatch) is batch-size independent, so cutting the auto iteration
      count ``~itopk/width`` by the width factor wins in every regime
      (measured: +40-50% QPS at equal itopk/recall at batch 1024,
      ``artifacts/tpu/cagra_width_sweep_*``).
    * **tiny batches** (the multi-CTA / multi-kernel regime) additionally
      seed from a larger strided sample (one cheap matmul) so fewer hops
      are needed while the chip is otherwise idle.

    Explicit non-default ``base`` values are respected — the plan only
    raises knobs the caller left at their defaults."""
    base = base or CagraSearchParams()
    width = base.search_width
    init = base.init_sample
    if width == CagraSearchParams.search_width:
        # Measured (artifacts/tpu/cagra_width_sweep_*): at equal itopk a
        # width-8 beam matches width-4 recall with ~40% more QPS — the
        # auto iteration count drops ~width-fold while the fixed per-
        # iteration cost (buffer merge, flag bookkeeping, host dispatch)
        # does not grow with width. That overhead is batch-size-
        # independent, so the wide beam wins in EVERY regime.
        width = 8
    if nq <= 32 and init == CagraSearchParams.init_sample:
        # multi-CTA/multi-kernel regime: seed from a larger strided
        # sample (one cheap matmul) so fewer hops are needed
        init = min(size, 4 * CagraSearchParams.init_sample)
    return dataclasses.replace(
        base, itopk_size=max(base.itopk_size, k), search_width=width, init_sample=init
    )


def derive_search_config(params: "CagraSearchParams", k: int, size: int):
    """(itopk, width, iters, n_init) from search params — the
    ``search_plan.cuh:136`` adjust step, shared with the sharded path."""
    itopk = max(params.itopk_size, k)
    width = max(1, params.search_width)
    # search_plan.cuh:138-144: 1 + min((itopk/width)*1.1, itopk/width + 10),
    # floored at the reference's min_iterations default
    ratio = itopk // max(1, width)
    iters = params.max_iterations or max(10, 1 + min(int(ratio * 1.1), ratio + 10))
    return itopk, width, iters, min(itopk, size)


def fused_eligible(
    index: CagraIndex,
    params: "CagraSearchParams",
    prefilter: Optional[Bitset] = None,
) -> bool:
    """Whether the Pallas fused beam kernel
    (:mod:`raft_tpu.ops.pallas.cagra_search`) can serve this search:
    raw (uncompressed) dataset, shared strided seeding, ``"post"``
    dedup semantics (the kernel's merge implements exactly those), no
    prefilter, ids within the packed base-256 encoding, and id rows
    that fit the vector lanes (``graph_degree <= dim``)."""
    from raft_tpu.ops.pallas.cagra_search import MAX_TABLE_IDS

    return (
        index.dataset is not None
        and prefilter is None
        and params.init_sample > 0
        and params.dedup == "post"
        and index.metric in _SUPPORTED
        and index.graph_degree <= index.dim
        and index.size <= MAX_TABLE_IDS
    )


def _fused_table(index: CagraIndex, dtype) -> jax.Array:
    """Build (once) and cache the packed ``[n, deg + 3, d]`` neighbor
    table on the index. Plain attribute, not a pytree leaf — transforms
    never see it, and a rebuilt index starts with a cold cache."""
    from raft_tpu.ops.pallas.cagra_search import build_neighbor_table

    dtype = jnp.dtype(dtype)
    cached = getattr(index, "_fused_table_cache", None)
    if cached is None or cached[0] != dtype:
        table = build_neighbor_table(index.dataset, index.graph, dtype=dtype)
        cached = (dtype, table)
        index._fused_table_cache = cached
    return cached[1]


@functools.partial(
    jax.jit,
    static_argnames=("k", "itopk", "width", "iters", "metric", "qt", "interpret"),
)
def _cagra_fused_impl(
    table,
    dataset,
    sqnorms,
    queries,
    init_ids,
    *,
    k: int,
    itopk: int,
    width: int,
    iters: int,
    metric: DistanceType,
    qt: int,
    interpret: bool,
):
    """Fused-path wrapper: identical seed beam to the XLA path (shared
    :func:`_seed_select`), the Pallas beam loop, then the same final
    unique-merge + metric epilogue as ``_cagra_search_impl``. The final
    merge also collapses the one dup class the in-kernel adjacent kill
    cannot see: a seed node rescored during expansion carries the
    kernel's arithmetic, not the init matmul's, so the two copies are
    not value-adjacent."""
    from raft_tpu.ops.pallas.cagra_search import WORST as KWORST
    from raft_tpu.ops.pallas.cagra_search import cagra_fused_search

    nq, _ = queries.shape
    qf = queries.astype(jnp.float32)
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.asarray(worst_value(jnp.float32, select_min), jnp.float32)
    q_sqnorm = jnp.sum(qf * qf, axis=1)
    v0, i0 = _seed_select(
        qf, q_sqnorm, dataset[init_ids].astype(jnp.float32), sqnorms[init_ids],
        init_ids, itopk=itopk, select_min=select_min, worst=worst,
        filter_bits=None, has_filter=False,
    )
    # kernel beam is min-ordered with a finite worst: negate IP dots,
    # map empty slots, pack (id, visited=0) into one lane
    kv0 = jnp.where(i0 < 0, KWORST, v0 if select_min else -v0)
    kidf0 = jnp.where(i0 < 0, -1, i0 * 2)
    bv, bidf = cagra_fused_search(
        table, qf, kv0, kidf0,
        itopk=itopk, width=width, iters=iters, qt=qt,
        ip=not select_min, interpret=interpret,
    )
    buf_i = bidf >> 1
    buf_f = (bidf & 1) == 1
    buf_v = jnp.where(buf_i < 0, worst, bv if select_min else -bv)
    buf_v, buf_i, buf_f = running_merge_unique(
        buf_v, buf_i,
        jnp.full((nq, 1), worst, jnp.float32), jnp.full((nq, 1), -1, jnp.int32),
        select_min=select_min, acc_flags=buf_f,
    )
    vals, idx = buf_v[:, :k], buf_i[:, :k]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.where(idx >= 0, jnp.sqrt(jnp.maximum(vals, 0.0)), vals)
    return vals, idx


def search(
    index: CagraIndex,
    queries,
    k: int,
    params: Optional[CagraSearchParams] = None,
    prefilter: Optional[Bitset] = None,
    query_batch: int = 1024,
    res: Optional[Resources] = None,
    mode: str = "auto",
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy beam search over the graph (``cagra::search``,
    ``detail/cagra/cagra_search.cuh:249``). Returns best-first
    ``(distances [nq, k], indices [nq, k])``; unfilled slots get id -1.

    ``mode``: ``"fused"`` = the Pallas DMA-fed beam kernel
    (:mod:`raft_tpu.ops.pallas.cagra_search`) — beam state VMEM-resident
    across iterations, parents' packed neighbor rows streamed HBM->VMEM;
    ``"xla"`` = the gather/einsum/select loop (the fallback and the
    recall oracle the fused path is tested against); ``"auto"`` picks
    fused on TPU when :func:`fused_eligible`, else xla.

    With observability on (:mod:`raft_tpu.obs`, ``RAFT_TPU_OBS=1``) the
    call records a sync-aware ``cagra.search`` span with per-batch
    children, the mode chosen (fused vs xla), iterations executed, and
    beam occupancy; disabled (the default) it costs one flag check."""
    if not obs.is_enabled():
        return _search_dispatch(
            index, queries, k, params, prefilter, query_batch, res, mode, **kwargs
        )
    with obs.span("cagra.search", k=k, nq=int(np.shape(queries)[0])) as sp:
        return sp.sync(
            _search_dispatch(
                index, queries, k, params, prefilter, query_batch, res, mode, **kwargs
            )
        )


def _search_dispatch(
    index: CagraIndex,
    queries,
    k: int,
    params: Optional[CagraSearchParams],
    prefilter: Optional[Bitset],
    query_batch: int,
    res: Optional[Resources],
    mode: str,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """Mode routing + query batching behind :func:`search` (split out so
    the observability-off path costs a single flag check)."""
    ensure_resources(res)
    if params is None:
        params = CagraSearchParams(**kwargs)
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "bad query shape")
    expects(k >= 1, "k must be >= 1")
    expects(
        params.dedup in ("sort", "post", "none", True, False),
        "dedup must be sort|post|none, got %r", params.dedup,
    )
    # auto iteration count (search_plan.cuh:136 adjust_search_params)
    itopk, width, iters, n_init = derive_search_config(params, k, index.size)
    if prefilter is not None:
        expects(prefilter.size >= index.size, "prefilter smaller than index")
    filter_bits = prefilter.bits if prefilter is not None else None

    requested_mode = mode
    if mode == "auto":
        from raft_tpu import plan as _plan

        on_tpu = jax.default_backend() == "tpu"
        if _plan.is_enabled():
            mode = _plan.plan_cagra_mode(
                queries.shape[0], on_tpu=on_tpu,
                fused_ok=fused_eligible(index, params, prefilter),
            ).choice
        else:
            mode = (
                "fused"
                if on_tpu and fused_eligible(index, params, prefilter)
                else "xla"
            )
    expects(mode in ("xla", "fused"), "mode must be auto|xla|fused, got %r", mode)
    if mode == "fused":
        expects(
            fused_eligible(index, params, prefilter),
            "fused mode needs a raw dataset, init_sample > 0, dedup='post', "
            "no prefilter, and graph_degree <= dim (use mode='xla')",
        )
    if obs.is_enabled():
        obs.inc("cagra.search.calls", mode=mode)
        obs.inc("cagra.search.queries", float(queries.shape[0]))
        obs.observe("cagra.search.iterations", float(iters))
        obs.set_gauge("cagra.search.itopk", float(itopk))
        obs.set_gauge("cagra.search.width", float(width))

    nq = queries.shape[0]
    key = as_key(params.seed)

    out_v, out_i = [], []
    for start in range(0, nq, query_batch):
        qc = queries[start : start + query_batch]
        bpad = 0
        if qc.shape[0] < query_batch and nq > query_batch:
            bpad = query_batch - qc.shape[0]
            qc = jnp.pad(qc, ((0, bpad), (0, 0)))
        if params.init_sample > 0:
            init_ids = strided_seed_ids(index.size, params.init_sample)
        else:
            key, kb = jax.random.split(key)
            init_ids = jax.random.randint(kb, (qc.shape[0], n_init), 0, index.size, jnp.int32)
        if mode == "fused":
            try:
                # host-level fault point: fires per batch even when the
                # jitted program below is cache-hit
                _faults.fire("pallas.cagra_search", nq=int(qc.shape[0]))
                table = _fused_table(index, params.fused_table_dtype)
                with obs.span(
                    "cagra.search.fused_batch", nq=qc.shape[0], iters=iters, width=width
                ) as sp:
                    v, i = sp.sync(
                        _cagra_fused_impl(
                            table,
                            index.dataset,
                            index.sqnorms,
                            qc,
                            init_ids,
                            k=k,
                            itopk=itopk,
                            width=width,
                            iters=iters,
                            metric=index.metric,
                            qt=max(8, min(params.fused_qt, -(-qc.shape[0] // 8) * 8)),
                            interpret=jax.default_backend() != "tpu",
                        )
                    )
                if bpad:
                    v, i = v[:-bpad], i[:-bpad]
                if obs.is_enabled():
                    obs.observe(
                        "cagra.search.beam_occupancy", float(jnp.mean(i >= 0)), mode="fused"
                    )
                out_v.append(v)
                out_i.append(i)
                continue
            except _fallback.FALLBACK_ERRORS as e:
                if requested_mode == "fused":
                    raise  # the caller pinned the engine; do not mask
                _fallback.record_fallback("cagra", e)
                mode = "xla"  # this batch and the rest take the XLA path
        use_vpq = index.dataset is None
        vpq_arrays = None
        sqnorms = index.sqnorms
        if use_vpq:
            expects(index.vpq is not None, "index has neither dataset nor vpq data")
            vpq_arrays = (
                index.vpq.vq_centers,
                index.vpq.vq_labels,
                index.vpq.pq_centers,
                index.vpq.codes,
            )
            sqnorms = index.vpq.sqnorms
        with obs.span(
            "cagra.search.xla_batch", nq=qc.shape[0], iters=iters, width=width
        ) as sp:
            v, i = sp.sync(
                _cagra_search_impl(
                    index.dataset,
                    sqnorms,
                    index.graph,
                    qc,
                    init_ids,
                    filter_bits,
                    vpq_arrays,
                    k=k,
                    itopk=itopk,
                    width=width,
                    iters=iters,
                    metric=index.metric,
                    has_filter=filter_bits is not None,
                    use_vpq=use_vpq,
                    dedup={True: "sort", False: "none"}.get(params.dedup, params.dedup),
                )
            )
        if bpad:
            v, i = v[:-bpad], i[:-bpad]
        if obs.is_enabled():
            obs.observe(
                "cagra.search.beam_occupancy", float(jnp.mean(i >= 0)), mode="xla"
            )
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, axis=0), jnp.concatenate(out_i, axis=0)


# ---------------------------------------------------------------------------
# VPQ compression (neighbors/dataset.hpp:210-259 vpq_dataset)
# ---------------------------------------------------------------------------


def _default_vpq_pq_dim(d: int) -> int:
    for cand in (d // 4, d // 2, d):
        if cand >= 1 and d % cand == 0:
            return cand
    return d


def compress(index: CagraIndex, params: Optional[VpqParams] = None, **kwargs) -> CagraIndex:
    """Replace the raw dataset with a VQ+PQ compressed one
    (``cagra::compress`` / ``vpq_build``, ``neighbors/dataset.hpp:210``):
    coarse VQ centers + per-subspace PQ codebooks over the VQ residuals.
    Search decodes candidates on the fly; memory drops from ``4*dim``
    to ``~pq_dim`` bytes per row."""
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_balanced import BalancedKMeansParams
    from raft_tpu.neighbors.ivf_pq import _batched_lloyd
    from raft_tpu.ops.fused_1nn import min_cluster_and_distance

    if params is None:
        params = VpqParams(**kwargs)
    expects(index.dataset is not None, "index already compressed")
    ds = index.dataset.astype(jnp.float32)
    n, d = ds.shape
    vq_n = params.vq_n_centers or max(8, min(1024, int(round(n ** 0.5))))
    pq_dim = params.pq_dim or _default_vpq_pq_dim(d)
    expects(d % pq_dim == 0, "dim %d must be divisible by pq_dim %d", d, pq_dim)
    pq_len = d // pq_dim
    ksub = 1 << params.pq_bits

    key, k_sub, k_init = jax.random.split(as_key(params.seed), 3)
    vq_centers = kmeans_balanced.fit(
        ds,
        BalancedKMeansParams(
            n_clusters=vq_n, n_iters=params.kmeans_n_iters, seed=params.seed
        ),
    )
    vq_labels, _ = min_cluster_and_distance(ds, vq_centers)
    resid = (ds - vq_centers[vq_labels]).reshape(n, pq_dim, pq_len)

    # per-subspace codebooks on (a subsample of) the residuals
    nt = min(n, ksub * 256)
    sub = jax.random.permutation(k_sub, n)[:nt]
    Xs = jnp.transpose(resid[sub], (1, 0, 2))  # [pq_dim, nt, pq_len]
    init_idx = jax.random.permutation(k_init, nt)[: min(ksub, nt)]
    init = Xs[:, init_idx, :]
    if init.shape[1] < ksub:
        reps = -(-ksub // init.shape[1])
        init = jnp.tile(init, (1, reps, 1))[:, :ksub, :]
    pq_centers = _batched_lloyd(
        Xs, jnp.ones((pq_dim, nt), jnp.float32), init, k=ksub, n_iters=params.kmeans_n_iters
    )

    # encode: nearest sub-center per subspace (chunked)
    cn = jnp.sum(pq_centers * pq_centers, axis=-1)  # [pq_dim, ksub]
    codes_parts = []
    sq_parts = []
    chunk = 131072
    for s in range(0, n, chunk):
        rr = resid[s : s + chunk]  # [c, pq_dim, pq_len]
        dots = jnp.einsum("cjl,jkl->cjk", rr, pq_centers, preferred_element_type=jnp.float32)
        code = jnp.argmax(2.0 * dots - cn[None, :, :], axis=-1).astype(jnp.uint8)
        codes_parts.append(code)
        # decoded sqnorm for the score epilogue
        dec = jnp.take_along_axis(
            pq_centers[None], code[:, :, None, None].astype(jnp.int32), axis=2
        )[:, :, 0, :].reshape(-1, d) + vq_centers[vq_labels[s : s + chunk]]
        sq_parts.append(jnp.sum(dec * dec, axis=1))
    codes = codes_parts[0] if len(codes_parts) == 1 else jnp.concatenate(codes_parts)
    sqnorms = sq_parts[0] if len(sq_parts) == 1 else jnp.concatenate(sq_parts)

    vpq = VpqDataset(
        vq_centers=vq_centers,
        vq_labels=vq_labels.astype(jnp.int32),
        pq_centers=pq_centers,
        codes=codes,
        sqnorms=sqnorms,
    )
    return dataclasses.replace(
        index, dataset=None, sqnorms=None, vpq=vpq, dim_hint=d
    )


# ---------------------------------------------------------------------------
# serialization (neighbors/cagra_serialize.cuh analog)
# ---------------------------------------------------------------------------

_KIND = "cagra"
_VERSION = 2


def _write_body(index: CagraIndex, stream: BinaryIO, include_dataset: bool = True) -> None:
    ser.serialize_scalar(stream, int(index.metric), "int32")
    ser.serialize_scalar(stream, int(index.size), "int64")
    has_raw = index.dataset is not None and include_dataset
    has_vpq = index.vpq is not None
    ser.serialize_scalar(stream, int(has_raw), "int32")
    ser.serialize_scalar(stream, int(has_vpq), "int32")
    ser.serialize_scalar(stream, int(index.dim), "int32")
    ser.serialize_array(stream, index.graph)
    if has_raw:
        ser.serialize_array(stream, index.dataset)
    if has_vpq:
        ser.serialize_array(stream, index.vpq.vq_centers)
        ser.serialize_array(stream, index.vpq.vq_labels)
        ser.serialize_array(stream, index.vpq.pq_centers)
        ser.serialize_array(stream, index.vpq.codes)
        ser.serialize_array(stream, index.vpq.sqnorms)


def save(index: CagraIndex, stream: BinaryIO, include_dataset: bool = True) -> None:
    body = io.BytesIO()
    _write_body(index, body, include_dataset=include_dataset)
    ser.save_stream(stream, _KIND, _VERSION, body.getvalue())


def load(stream: BinaryIO, dataset=None, res: Optional[Resources] = None) -> CagraIndex:
    """Load an index; if it was saved without the dataset, one must be
    supplied (mirrors the reference's dataset-less serialize mode,
    ``cagra_serialize.cuh``)."""
    ensure_resources(res)
    version, stream = ser.load_stream(stream, _KIND)
    metric = DistanceType(ser.deserialize_scalar(stream, "int32"))
    size = int(ser.deserialize_scalar(stream, "int64"))
    has_ds = bool(ser.deserialize_scalar(stream, "int32"))
    has_vpq = bool(ser.deserialize_scalar(stream, "int32")) if version >= 2 else False
    dim = int(ser.deserialize_scalar(stream, "int32")) if version >= 2 else 0
    graph = ser.deserialize_array(stream)
    vpq = None
    if has_ds:
        data = ser.deserialize_array(stream)
    if has_vpq:
        vpq = VpqDataset(
            vq_centers=ser.deserialize_array(stream),
            vq_labels=ser.deserialize_array(stream),
            pq_centers=ser.deserialize_array(stream),
            codes=ser.deserialize_array(stream),
            sqnorms=ser.deserialize_array(stream),
        )
    if not has_ds:
        if vpq is not None and dataset is None:
            return CagraIndex(
                dataset=None, sqnorms=None, graph=graph, metric=metric,
                size=size, vpq=vpq, dim_hint=dim,
            )
        expects(dataset is not None, "index was saved without dataset; pass one")
        data = jnp.asarray(dataset)
    expects(data.shape[0] == size, "dataset rows != index size")
    out = from_graph(data, graph, metric)
    return dataclasses.replace(out, vpq=vpq, dim_hint=dim)


def save_path(index: CagraIndex, path: str, include_dataset: bool = True) -> str:
    """Atomic (temp-then-rename) checksummed snapshot at ``path``."""
    return ser.atomic_write(path, lambda f: save(index, f, include_dataset=include_dataset))


def load_path(path: str, dataset=None, res: Optional[Resources] = None) -> CagraIndex:
    with open(path, "rb") as f:
        return load(f, dataset=dataset, res=res)
