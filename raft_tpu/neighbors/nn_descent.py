"""NN-descent (GNND) kNN-graph construction — analog of
``raft::neighbors::experimental::nn_descent``.

Reference: ``neighbors/detail/nn_descent.cuh:342`` (``class GNND``), the
per-iteration ``local_join`` (``:1191``), ``build`` (``:1215``), params in
``neighbors/nn_descent_types.hpp``.

TPU-first redesign of the local join. The CUDA version samples "new"/"old"
neighbor lists per node, plus reverse edges, and runs a warp-level join
kernel with bloom-filter sampling and shared-memory insertion sort. Here the
same neighborhood-expansion fixed point is reached with dense, static-shape
ops:

1. **Sample** a pool ``P(u)`` of ``max_samples`` forward neighbors per node
   (preferring not-yet-visited "new" entries, which are then marked old —
   GNND's new/old split) plus up to ``max_samples`` *reverse* neighbors,
   built by sorting the sampled edge list by destination and rank-limiting
   (the static-shape substitute for the CUDA scatter into ragged reverse
   lists).
2. **Expand**: candidates(u) = P(P(u)) — because ``a ∈ P(u)`` implies the
   hosts of ``a`` are exactly ``P(a)``, the pairwise local join over every
   pool collapses into one two-hop gather over the symmetrized sample graph.
3. **Score** candidates with one batched MXU matmul per node chunk and
   **merge** into the running top-k with id-dedup
   (:func:`raft_tpu.ops.select_k.running_merge_unique`).

Iteration stops when the fraction of changed graph entries drops below
``termination_threshold`` (GNND's update-rate test) — a host-side check at
build time only.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.ops.select_k import running_merge_unique, worst_value
from raft_tpu.random.rng import as_key
from raft_tpu.utils.graph import reverse_edges

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
    DistanceType.CosineExpanded,
)


@dataclasses.dataclass
class NNDescentParams:
    """``nn_descent::index_params`` analog (``nn_descent_types.hpp``)."""

    graph_degree: int = 64
    intermediate_graph_degree: int = 128
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    max_samples: int = 16  # pool size per direction per iteration
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0
    node_chunk: int = 4096  # rows scored per device step (memory knob)


@dataclasses.dataclass
class NNDescentOutput:
    """The built kNN graph (``nn_descent::index`` analog): best-first
    neighbor ids and distances per row."""

    graph: jax.Array  # [n, graph_degree] i32
    distances: jax.Array  # [n, graph_degree] f32
    metric: DistanceType


@functools.partial(jax.jit, static_argnames=("k", "select_min"))
def _score_and_merge(data, sqnorms, cand, acc_v, acc_i, acc_f, row0, *, k: int, select_min: bool):
    """Score a chunk of rows against their candidate ids and merge.

    ``cand``: [c, C] candidate ids (-1 invalid). One einsum puts the
    distance work on the MXU (the local join's distance computations,
    ``nn_descent.cuh:1191``). The "already sampled" flag lane rides through
    the merge (GNND's new/old bookkeeping); fresh candidates enter
    unsampled.
    """
    c, C = cand.shape
    rows = row0 + jnp.arange(c, dtype=jnp.int32)
    q = data[rows]  # [c, d]
    safe = jnp.clip(cand, 0, None)
    vecs = data[safe]  # [c, C, d]
    # HIGHEST precision: graph quality is sensitive to distance-rank errors
    # from the TPU's default single-pass bf16 matmul (see cagra.py).
    dots = jnp.einsum(
        "cd,cCd->cC",
        q,
        vecs,
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )
    if select_min:
        dist = sqnorms[rows][:, None] + sqnorms[safe] - 2.0 * dots
        dist = jnp.maximum(dist, 0.0)
    else:
        dist = dots
    worst = jnp.asarray(worst_value(jnp.float32, select_min), jnp.float32)
    invalid = (cand < 0) | (cand == rows[:, None])  # padding + self-loops
    dist = jnp.where(invalid, worst, dist)
    cand = jnp.where(invalid, -1, cand)
    return running_merge_unique(
        acc_v, acc_i, dist, cand, select_min=select_min, acc_flags=acc_f
    )


@functools.partial(jax.jit, static_argnames=("half",))
def _sample_pool(key, ids, sampled, *, half: int):
    """Sample ``half`` new (never-sampled) + ``half`` old neighbors per node
    via Gumbel top-k over the flag-partitioned lists (GNND's new/old
    sampling, ``nn_descent.cuh`` sample_graph); returns
    (pool [n, 2*half], updated flags with the drawn new entries marked
    sampled)."""
    n, k = ids.shape
    g = jax.random.gumbel(key, (n, k))
    valid = ids >= 0
    new_logit = jnp.where(valid & ~sampled, g, -jnp.inf)
    old_logit = jnp.where(valid & sampled, g, -jnp.inf)
    _, new_pos = lax.top_k(new_logit, half)
    _, old_pos = lax.top_k(old_logit, half)
    new_sel = jnp.take_along_axis(ids, new_pos, axis=1)
    old_sel = jnp.take_along_axis(ids, old_pos, axis=1)
    # Positions whose logit was -inf were invalid picks.
    new_sel = jnp.where(jnp.take_along_axis(new_logit, new_pos, axis=1) == -jnp.inf, -1, new_sel)
    old_sel = jnp.where(jnp.take_along_axis(old_logit, old_pos, axis=1) == -jnp.inf, -1, old_sel)
    # Mark the drawn new entries as sampled.
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    sampled = sampled.at[rows, new_pos].set(True)
    return jnp.concatenate([new_sel, old_sel], axis=1), sampled


def build(
    dataset,
    params: Optional[NNDescentParams] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> NNDescentOutput:
    """Build an approximate kNN graph (``nn_descent::build``,
    ``detail/nn_descent.cuh:1215``).

    With :mod:`raft_tpu.obs` enabled the build is wrapped in a
    device-synced ``nn_descent.build`` span with call/row counters and
    an iterations-to-convergence histogram."""
    if params is None:
        params = NNDescentParams(**kwargs)
    if not obs.is_enabled():
        return _build_impl(dataset, params, res)
    n = int(np.shape(dataset)[0])
    obs.inc("nn_descent.build.calls")
    obs.inc("nn_descent.build.rows", float(n))
    with obs.span(
        "nn_descent.build", n=n, graph_degree=params.graph_degree,
        intermediate=params.intermediate_graph_degree,
    ) as sp:
        out = _build_impl(dataset, params, res)
        sp.sync((out.graph, out.distances))
        return out


def _build_impl(
    dataset, params: NNDescentParams, res: Optional[Resources]
) -> NNDescentOutput:
    res = ensure_resources(res)
    metric = resolve_metric(params.metric)
    expects(metric in _SUPPORTED, "nn_descent does not support metric %s", metric)
    dataset = jnp.asarray(dataset)
    expects(dataset.ndim == 2, "dataset must be [n_rows, dim]")
    n, d = dataset.shape
    gd = params.graph_degree
    k = max(params.intermediate_graph_degree, gd)
    expects(gd >= 1, "graph_degree must be >= 1")
    expects(k < n, "graph degree %d must be < n_rows %d", k, n)

    data = dataset.astype(jnp.float32)
    if metric == DistanceType.CosineExpanded:
        # cosine ranking == L2 ranking on unit vectors; distances converted
        # at the end (1 - cos = L2^2 / 2 on the unit sphere).
        data = data / jnp.maximum(jnp.linalg.norm(data, axis=1, keepdims=True), 1e-12)
    select_min = metric != DistanceType.InnerProduct
    sqnorms = jnp.sum(data * data, axis=1)

    key = as_key(params.seed)
    key, k_init = jax.random.split(key)

    # -- random initial graph (GNND's random init) --------------------------
    init_ids = jax.random.randint(k_init, (n, k), 0, n, dtype=jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    init_ids = jnp.where(init_ids == rows, (init_ids + 1) % n, init_ids)

    worst = jnp.asarray(worst_value(jnp.float32, select_min), jnp.float32)
    chunk = max(256, params.node_chunk)

    def merge_candidates(acc_v, acc_i, acc_f, cand_of_chunk):
        out_v, out_i, out_f = [], [], []
        for s in range(0, n, chunk):
            c = cand_of_chunk(s)
            v, i, f = _score_and_merge(
                data, sqnorms, c,
                acc_v[s : s + chunk], acc_i[s : s + chunk], acc_f[s : s + chunk],
                jnp.int32(s), k=k, select_min=select_min,
            )
            out_v.append(v)
            out_i.append(i)
            out_f.append(f)
        return (
            jnp.concatenate(out_v, axis=0),
            jnp.concatenate(out_i, axis=0),
            jnp.concatenate(out_f, axis=0),
        )

    acc_v = jnp.full((n, k), worst, jnp.float32)
    acc_i = jnp.full((n, k), -1, jnp.int32)
    sampled = jnp.zeros((n, k), bool)  # everything new (never sampled)
    acc_v, acc_i, sampled = merge_candidates(
        acc_v, acc_i, sampled, lambda s: init_ids[s : s + chunk]
    )

    half = max(1, min(params.max_samples // 2, k))

    @functools.partial(jax.jit, static_argnames=())
    def _two_hop_chunk(sym, sym_c):
        # candidates(u) = P(P(u)) for one row chunk — expanding per chunk
        # keeps the [chunk, 4h, 4h] gather small (the full [n, 4h, 4h]
        # tensor is 4*n*half^2 ints and blows HBM at 1M rows)
        safe_c = jnp.clip(sym_c, 0, None)
        cand = jnp.where(sym_c[:, :, None] >= 0, sym[safe_c], -1)
        cand = cand.reshape(sym_c.shape[0], -1)
        return jnp.concatenate([cand, sym_c], axis=1)  # include one-hop too

    for it in range(params.max_iterations):
        key, k_sample = jax.random.split(key)
        pool, sampled = _sample_pool(k_sample, acc_i, sampled, half=half)
        rev = reverse_edges(pool, n, 2 * half)
        sym = jnp.concatenate([pool, rev], axis=1)  # [n, 4*half]

        prev_i = acc_i
        acc_v, acc_i, sampled = merge_candidates(
            acc_v, acc_i, sampled, lambda s: _two_hop_chunk(sym, sym[s : s + chunk])
        )

        # update rate = fraction of entries not present before (sorted lookup)
        prev_sorted = jnp.sort(prev_i, axis=1)
        pos = jax.vmap(lambda ps, ai: jnp.searchsorted(ps, ai))(prev_sorted, acc_i)
        found = jnp.take_along_axis(prev_sorted, jnp.clip(pos, 0, k - 1), axis=1) == acc_i
        new_mask = (~found) & (acc_i >= 0)
        update_rate = float(jnp.mean(new_mask.astype(jnp.float32)))
        if update_rate < params.termination_threshold:
            break

    if obs.is_enabled() and params.max_iterations > 0:
        obs.observe("nn_descent.build.iterations", float(it + 1))
    graph = acc_i[:, :gd]
    dists = acc_v[:, :gd]
    if metric == DistanceType.L2SqrtExpanded:
        dists = jnp.where(graph >= 0, jnp.sqrt(jnp.maximum(dists, 0.0)), dists)
    elif metric == DistanceType.CosineExpanded:
        dists = jnp.where(graph >= 0, 0.5 * dists, dists)
    return NNDescentOutput(graph=graph, distances=dists, metric=metric)
