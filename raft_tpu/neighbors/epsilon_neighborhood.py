"""Epsilon neighborhood — analog of
``raft::neighbors::epsilon_neighborhood``
(``neighbors/epsilon_neighborhood.cuh`` ``epsUnexpL2SqNeighborhood``).

One tiled distance pass producing a boolean adjacency + vertex degrees;
XLA fuses the compare into the distance epilogue.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, pairwise_distance, resolve_metric


def eps_neighbors(
    x, y, eps: float, metric=DistanceType.L2Expanded, block: int = 4096
) -> Tuple[jax.Array, jax.Array]:
    """Adjacency ``adj[i, j] = dist(x_i, y_j) < eps`` plus per-row degrees
    (``epsUnexpL2SqNeighborhood``'s (adj, vd) outputs; the reference fixes
    the metric to squared L2 — here any dense metric works, with ``eps``
    in that metric's units)."""
    metric = resolve_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1], "bad shapes")
    adj_parts = []
    for s in range(0, x.shape[0], block):
        d = pairwise_distance(x[s : s + block], y, metric)
        adj_parts.append(d < eps)
    adj = jnp.concatenate(adj_parts, axis=0)
    vd = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, vd
