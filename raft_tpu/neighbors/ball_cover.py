"""Random ball cover — analog of ``raft::neighbors::ball_cover``
(``neighbors/ball_cover-inl.cuh:112,259,314``; index type
``neighbors/ball_cover_types.hpp``), the 2-3D geospatial index for
haversine/euclidean metrics.

TPU-first note. The GPU RBC accelerates by *skipping* distance
computations via landmark triangle-inequality pruning — a win when each
skipped pair saves warp work. On the MXU, dense tiles are so much faster
than data-dependent branching that the pruned scan loses to a straight
tiled scan at RBC's 2-3D scale; accordingly:

* the index keeps the RBC *structure* — √n sampled landmarks, per-landmark
  grouped layout, landmark radii — for API parity and for the eps-query
  pruning mask, and
* ``knn_query`` is an exact tiled scan (distances via
  :func:`raft_tpu.ops.distance.pairwise_distance`, which includes
  haversine) rather than a translation of the CUDA registers-and-warps
  pruning loop; results are exact, matching the reference's guarantee.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, pairwise_distance, resolve_metric
from raft_tpu.ops.fused_1nn import min_cluster_and_distance
from raft_tpu.ops.select_k import running_merge, select_k, worst_value

_SUPPORTED = (
    DistanceType.Haversine,
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2SqrtUnexpanded,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BallCoverIndex:
    """``BallCoverIndex`` analog (``neighbors/ball_cover_types.hpp``)."""

    dataset: jax.Array  # [n, d] (d in {2, 3})
    landmarks: jax.Array  # [n_landmarks, d]
    assignments: jax.Array  # [n] landmark of each row
    landmark_dists: jax.Array  # [n] distance to own landmark
    radii: jax.Array  # [n_landmarks] max member distance
    metric: DistanceType

    def tree_flatten(self):
        return (
            (self.dataset, self.landmarks, self.assignments, self.landmark_dists, self.radii),
            (self.metric,),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0])

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]


def build(dataset, metric=DistanceType.Haversine, n_landmarks: Optional[int] = None, seed: int = 0) -> BallCoverIndex:
    """Sample √n landmarks and group points (``rbc_build``,
    ``ball_cover-inl.cuh:112``)."""
    metric = resolve_metric(metric)
    expects(metric in _SUPPORTED, "ball_cover supports haversine/euclidean, got %s", metric)
    dataset = jnp.asarray(dataset, jnp.float32)
    expects(dataset.ndim == 2 and dataset.shape[1] in (2, 3), "ball cover expects 2-3D points")
    if metric == DistanceType.Haversine:
        expects(dataset.shape[1] == 2, "haversine needs (lat, lon) pairs")
    n = dataset.shape[0]
    k = n_landmarks or max(1, int(math.sqrt(n)))
    rng = np.random.default_rng(seed)
    landmarks = dataset[jnp.asarray(rng.permutation(n)[:k])]
    d_lm = pairwise_distance(dataset, landmarks, metric)  # [n, k]
    assignments = jnp.argmin(d_lm, axis=1).astype(jnp.int32)
    dists = jnp.take_along_axis(d_lm, assignments[:, None], axis=1)[:, 0]
    radii = jax.ops.segment_max(dists, assignments, num_segments=k)
    return BallCoverIndex(
        dataset=dataset,
        landmarks=landmarks,
        assignments=assignments,
        landmark_dists=dists,
        radii=radii,
        metric=metric,
    )


def knn_query(
    index: BallCoverIndex, queries, k: int, block: int = 8192
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN (``rbc_knn_query``, ``ball_cover-inl.cuh:259``): tiled
    scan + running top-k merge."""
    queries = jnp.asarray(queries, jnp.float32)
    expects(queries.shape[1] == index.dataset.shape[1], "bad query shape")
    n = index.size
    expects(0 < k <= n, "k out of range")
    nq = queries.shape[0]
    worst = jnp.float32(worst_value(jnp.float32, True))
    acc_v = jnp.full((nq, k), worst, jnp.float32)
    acc_i = jnp.full((nq, k), -1, jnp.int32)
    for s in range(0, n, block):
        cnt = min(block, n - s)
        d = pairwise_distance(queries, index.dataset[s : s + cnt], index.metric)
        ids = s + jnp.arange(cnt, dtype=jnp.int32)[None, :].repeat(nq, axis=0)
        if cnt >= k:
            dv, di = select_k(d, k, select_min=True, indices=ids)
        else:
            dv, di = d, ids
        acc_v, acc_i = running_merge(acc_v, acc_i, dv, di, select_min=True)
    return acc_v, acc_i


def eps_query(
    index: BallCoverIndex, queries, eps: float
) -> Tuple[jax.Array, jax.Array]:
    """Exact eps-ball adjacency (``rbc_eps_nn_query``,
    ``ball_cover-inl.cuh:314``) with the RBC landmark prune: whole
    landmark groups whose triangle-inequality lower bound exceeds ``eps``
    are masked out before the point-level test.

    The reference restricts eps queries to metrics satisfying the triangle
    inequality (``ball_cover-inl.cuh:323`` asserts L2Sqrt*); squared L2
    does not satisfy it, so for ``L2Expanded`` indexes the bound is
    computed in sqrt space: prune when
    ``(sqrt(d_lm) - sqrt(radius))^2 > eps``."""
    queries = jnp.asarray(queries, jnp.float32)
    d_lm = pairwise_distance(queries, index.landmarks, index.metric)  # [nq, L]
    if index.metric == DistanceType.L2Expanded:
        lb = jnp.maximum(
            jnp.sqrt(jnp.maximum(d_lm, 0.0))
            - jnp.sqrt(jnp.maximum(index.radii, 0.0))[None, :],
            0.0,
        )
        group_ok = (lb * lb) <= eps  # [nq, L]
    else:
        group_ok = (d_lm - index.radii[None, :]) <= eps  # [nq, L]
    d = pairwise_distance(queries, index.dataset, index.metric)  # [nq, n]
    adj = (d < eps) & group_ok[:, index.assignments]
    vd = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, vd
