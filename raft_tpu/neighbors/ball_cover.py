"""Random ball cover — analog of ``raft::neighbors::ball_cover``
(``neighbors/ball_cover-inl.cuh:112,259,314``; index type
``neighbors/ball_cover_types.hpp``), the 2-3D geospatial index for
haversine/euclidean metrics.

TPU-first note. The GPU RBC accelerates by *skipping* distance
computations via landmark triangle-inequality pruning inside a
warp-level loop. TPUs can't branch per lane, but the same pruning maps
to the probed-group pattern the IVF indexes use:

* the index stores the RBC structure — √n sampled landmarks, members
  grouped per landmark in a padded ``[L, max_group]`` layout, landmark
  radii;
* ``knn_query(n_probes=p)`` scans groups in waves of the ``p``
  landmark-nearest groups per query (one gather + batched distance per
  wave), then applies the reference's **post-filtering rule**
  (``ball_cover-inl.cuh:259``): a wave stops the search only when the
  triangle-inequality lower bound ``d(q, lm_g) - radius_g`` of every
  unscanned group exceeds the current k-th distance — so results stay
  EXACT while clustered workloads touch a fraction of the points;
* ``n_probes=0`` (default) keeps the dense tiled scan, which wins when
  the data is small or uniform (MXU tiles beat gathers there).
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, pairwise_distance, resolve_metric
from raft_tpu.ops.select_k import running_merge, select_k, worst_value

_SUPPORTED = (
    DistanceType.Haversine,
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2SqrtUnexpanded,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BallCoverIndex:
    """``BallCoverIndex`` analog (``neighbors/ball_cover_types.hpp``)."""

    dataset: jax.Array  # [n, d] (d in {2, 3})
    landmarks: jax.Array  # [n_landmarks, d]
    assignments: jax.Array  # [n] landmark of each row
    landmark_dists: jax.Array  # [n] distance to own landmark
    radii: jax.Array  # [n_landmarks] max member distance
    group_rows: jax.Array  # [n_landmarks, max_group] i32 members, -1 pad
    metric: DistanceType

    def tree_flatten(self):
        return (
            (
                self.dataset,
                self.landmarks,
                self.assignments,
                self.landmark_dists,
                self.radii,
                self.group_rows,
            ),
            (self.metric,),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0])

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]


def build(dataset, metric=DistanceType.Haversine, n_landmarks: Optional[int] = None, seed: int = 0) -> BallCoverIndex:
    """Sample √n landmarks and group points (``rbc_build``,
    ``ball_cover-inl.cuh:112``)."""
    metric = resolve_metric(metric)
    expects(metric in _SUPPORTED, "ball_cover supports haversine/euclidean, got %s", metric)
    dataset = jnp.asarray(dataset, jnp.float32)
    expects(dataset.ndim == 2 and dataset.shape[1] in (2, 3), "ball cover expects 2-3D points")
    if metric == DistanceType.Haversine:
        expects(dataset.shape[1] == 2, "haversine needs (lat, lon) pairs")
    n = dataset.shape[0]
    k = n_landmarks or max(1, int(math.sqrt(n)))
    rng = np.random.default_rng(seed)
    landmarks = dataset[jnp.asarray(rng.permutation(n)[:k])]
    d_lm = pairwise_distance(dataset, landmarks, metric)  # [n, k]
    assignments = jnp.argmin(d_lm, axis=1).astype(jnp.int32)
    dists = jnp.take_along_axis(d_lm, assignments[:, None], axis=1)[:, 0]
    radii = jax.ops.segment_max(dists, assignments, num_segments=k)
    # padded per-landmark member lists (host-side: one stable sort)
    a_np = np.asarray(assignments)
    counts = np.bincount(a_np, minlength=k)
    mg = max(1, int(counts.max()))
    order = np.argsort(a_np, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])
    within = np.arange(n) - starts[a_np[order]]
    group_rows = np.full((k, mg), -1, np.int32)
    group_rows[a_np[order], within] = order.astype(np.int32)
    return BallCoverIndex(
        dataset=dataset,
        landmarks=landmarks,
        assignments=assignments,
        landmark_dists=dists,
        radii=radii,
        group_rows=jnp.asarray(group_rows),
        metric=metric,
    )


def _gathered_distance(q, pts, metric):
    """Distances between query n and its gathered candidates: ``q [nq, d]``
    vs ``pts [nq, c, d]`` -> ``[nq, c]``."""
    if metric == DistanceType.Haversine:
        from raft_tpu.ops.distance import haversine_core

        return haversine_core(q[:, 0:1], q[:, 1:2], pts[..., 0], pts[..., 1])
    diff = q[:, None, :] - pts
    d2 = jnp.sum(diff * diff, axis=-1)
    if metric == DistanceType.L2Expanded:
        return d2
    return jnp.sqrt(jnp.maximum(d2, 0.0))


def _triangle_lb(d_lm, radii, metric):
    """Per-(query, group) lower bound on the distance to any group member.
    Proper metrics: ``max(d(q, lm) - radius, 0)``. Squared L2 violates the
    triangle inequality, so the bound is formed in sqrt space and squared
    back (``ball_cover-inl.cuh:323`` restricts eps queries the same way)."""
    if metric == DistanceType.L2Expanded:
        s = jnp.sqrt(jnp.maximum(d_lm, 0.0)) - jnp.sqrt(jnp.maximum(radii, 0.0))[None, :]
        s = jnp.maximum(s, 0.0)
        return s * s
    return jnp.maximum(d_lm - radii[None, :], 0.0)


@functools.lru_cache(maxsize=None)
def _make_scan_wave(metric):
    @jax.jit
    def scan_wave(dataset, group_rows, queries, probe_ids, acc_v, acc_i):
        nq = queries.shape[0]
        rows = group_rows[probe_ids]  # [nq, p, mg]
        rows_flat = rows.reshape(nq, -1)
        valid = rows_flat >= 0
        pts = dataset[jnp.clip(rows_flat, 0, None)]  # [nq, c, d]
        worst = jnp.float32(worst_value(jnp.float32, True))
        d = jnp.where(valid, _gathered_distance(queries, pts, metric), worst)
        ids = jnp.where(valid, rows_flat, -1)
        k = acc_v.shape[1]
        if d.shape[1] > k:
            d, ids = select_k(d, k, select_min=True, indices=ids)
        return running_merge(acc_v, acc_i, d, ids, select_min=True)

    return scan_wave


def knn_query(
    index: BallCoverIndex, queries, k: int, block: int = 8192, n_probes: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN (``rbc_knn_query``, ``ball_cover-inl.cuh:259``).

    ``n_probes=0``: dense tiled scan + running top-k merge.
    ``n_probes=p``: landmark-pruned search — scan waves of the ``p``
    landmark-nearest groups per query, stopping as soon as the triangle
    inequality certifies no unscanned group can hold a closer point than
    the current k-th (the reference's post-filtering pass). Exact either
    way; the pruned path wins on clustered data where early waves already
    contain the true neighbors.

    The pruned path decides how many waves to run from DATA (the
    certificate), so it is a host-side loop of jitted waves — call it
    outside ``jax.jit`` (the dense path traces fine)."""
    queries = jnp.asarray(queries, jnp.float32)
    if n_probes > 0 and (
        isinstance(queries, jax.core.Tracer) or isinstance(index.dataset, jax.core.Tracer)
    ):
        raise TypeError(
            "ball_cover.knn_query(n_probes>0) runs a data-dependent host "
            "loop (the post-filter certificate) and cannot be traced under "
            "jax.jit; call it outside jit, or use n_probes=0 (dense scan)"
        )
    expects(queries.shape[1] == index.dataset.shape[1], "bad query shape")
    n = index.size
    expects(0 < k <= n, "k out of range")
    nq = queries.shape[0]
    worst = jnp.float32(worst_value(jnp.float32, True))
    if n_probes > 0:
        L = index.n_landmarks
        p = min(n_probes, L)
        d_lm = pairwise_distance(queries, index.landmarks, index.metric)  # [nq, L]
        lb = _triangle_lb(d_lm, index.radii, index.metric)
        order = jnp.argsort(d_lm, axis=1).astype(jnp.int32)  # nearest landmarks first
        lb_ord = jnp.take_along_axis(lb, order, axis=1)
        scan_wave = _make_scan_wave(index.metric)
        acc_v = jnp.full((nq, k), worst, jnp.float32)
        acc_i = jnp.full((nq, k), -1, jnp.int32)
        scanned = 0
        while scanned < L:
            probe_ids = order[:, scanned : scanned + p]
            acc_v, acc_i = scan_wave(
                index.dataset, index.group_rows, queries, probe_ids, acc_v, acc_i
            )
            scanned += int(probe_ids.shape[1])
            if scanned >= L:
                break
            # post-filter certificate: can any unscanned group beat the
            # current k-th distance for any query?
            beta = acc_v[:, k - 1]
            if not bool(jnp.any(lb_ord[:, scanned:] <= beta[:, None])):
                break
        return acc_v, acc_i
    acc_v = jnp.full((nq, k), worst, jnp.float32)
    acc_i = jnp.full((nq, k), -1, jnp.int32)
    for s in range(0, n, block):
        cnt = min(block, n - s)
        d = pairwise_distance(queries, index.dataset[s : s + cnt], index.metric)
        ids = s + jnp.arange(cnt, dtype=jnp.int32)[None, :].repeat(nq, axis=0)
        if cnt >= k:
            dv, di = select_k(d, k, select_min=True, indices=ids)
        else:
            dv, di = d, ids
        acc_v, acc_i = running_merge(acc_v, acc_i, dv, di, select_min=True)
    return acc_v, acc_i


def eps_query(
    index: BallCoverIndex, queries, eps: float
) -> Tuple[jax.Array, jax.Array]:
    """Exact eps-ball adjacency (``rbc_eps_nn_query``,
    ``ball_cover-inl.cuh:314``) with the RBC landmark prune: whole
    landmark groups whose triangle-inequality lower bound exceeds ``eps``
    are masked out before the point-level test.

    The reference restricts eps queries to metrics satisfying the triangle
    inequality (``ball_cover-inl.cuh:323`` asserts L2Sqrt*); squared L2
    does not satisfy it, so for ``L2Expanded`` indexes the bound is
    computed in sqrt space: prune when
    ``(sqrt(d_lm) - sqrt(radius))^2 > eps``."""
    queries = jnp.asarray(queries, jnp.float32)
    d_lm = pairwise_distance(queries, index.landmarks, index.metric)  # [nq, L]
    group_ok = _triangle_lb(d_lm, index.radii, index.metric) <= eps  # [nq, L]
    d = pairwise_distance(queries, index.dataset, index.metric)  # [nq, n]
    adj = (d < eps) & group_ok[:, index.assignments]
    vd = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, vd
