"""WAL shipping: leader seals segments, followers replay them.

The mutable-index replication story is log shipping, the oldest trick
in the replicated-database book, recast onto the repo's existing
crash-consistency machinery instead of a new wire protocol:

* the **leader** is an ordinary directory-backed
  :class:`~raft_tpu.mutable.MutableIndex`. Its WAL already frames every
  mutation with a CRC (``b"WALR" | len | crc32 | payload``) and rotates
  segments at frame boundaries; replication adds only an explicit
  :meth:`~raft_tpu.mutable.wal.WriteAheadLog.seal` — sealed segments
  are immutable, end on a whole record, and are therefore safe to read
  without racing ``append``;
* a :class:`Shipper` moves sealed bytes to one follower through a
  pluggable ``transport`` (default: read the segment file — replicas in
  one process or on one shared filesystem; a network hop slots in
  without touching the protocol). Every chunk crosses the ``wal.ship``
  chaos seam;
* the **follower** (:class:`Follower`) verifies every frame — magic,
  length, CRC, decode — *before* anything is applied, persists the
  verified bytes locally (its own crash story), and replays the records
  into an in-memory :class:`~raft_tpu.mutable.MutableIndex` via
  ``upsert``/``delete`` (an ``insert`` of a not-live id and an
  ``upsert`` of it are byte-identical in the delta, so replay is
  idempotent across restarts). A chunk with a damaged frame raises
  :class:`ShipRejected` at the exact clean-prefix offset: the shipper
  **re-requests from there** — a partial or corrupt record is never
  applied, matching the WAL's own longest-valid-prefix recovery;
* generations follow the **leader's manifest**: when compaction flips
  the leader to a new generation, :meth:`Follower.sync_generation`
  rebases — loads the new generation's main-segment artifacts from the
  leader directory, drops the old generation's shipped files, and
  resumes shipping the new WAL from zero. The follower's
  ``MANIFEST``-equivalent is ``FOLLOWER.json`` (generation, segment,
  offset, applied records), swapped with the same temp-fsync-rename
  idiom as everything else persisted in this repo.

**Bounded staleness**: a follower serves the leader's state as of the
last sealed-and-shipped record — records still in the leader's active
segment are the lag. :class:`Replication` (the per-index pipeline the
:class:`~raft_tpu.replica.group.ReplicaGroup` ticks) seals once the
active segment passes ``seal_bytes``, ships to every follower, and
publishes each lag as ``replica.staleness_records``; the router's
``max_staleness_records`` admission floor turns that gauge into a read
contract (``docs/replication.md``).
"""
from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Callable, List, Optional, Tuple

from raft_tpu import obs
from raft_tpu.core.errors import RaftError, expects
from raft_tpu.obs import recorder
from raft_tpu.mutable import manifest as man
from raft_tpu.mutable.segments import MutableIndex, _load_main, _load_rows
from raft_tpu.mutable.wal import _HEADER, _REC_MAGIC, WalRecord, WriteAheadLog
from raft_tpu.mutable.wal import replay as wal_replay
from raft_tpu.robust import faults

POSITION_FILE = "FOLLOWER.json"

#: default transfer chunk (bytes) — small enough that chaos tests see
#: multi-chunk segments, large enough to amortize the per-chunk fsync
DEFAULT_CHUNK_BYTES = 1 << 16


class ShipRejected(RaftError):
    """The follower refused a shipped chunk: a frame failed
    verification (magic/CRC/decode) or a sealed segment ended mid-frame.
    ``offset`` is the follower's clean-prefix high-water mark — the
    byte the shipper must re-request from."""

    def __init__(self, msg: str, *, segment: int, offset: int):
        super().__init__(msg)
        self.segment = int(segment)
        self.offset = int(offset)


class FencedError(RaftError):
    """A shipped chunk carried a stale fencing token: the sender's
    lease epoch is below the follower's fence. This is NOT a transport
    or verification failure — the bytes may be pristine — it is a
    *deposed leader* still shipping. Deliberately not a subclass of
    :class:`ShipRejected`: re-requesting the same bytes can never help,
    so the shipper must not retry; the error propagates to the tick,
    where it is counted and the stale pipeline stays parked."""

    def __init__(self, msg: str, *, epoch: int, fence_epoch: int):
        super().__init__(msg)
        self.epoch = int(epoch)
        self.fence_epoch = int(fence_epoch)


@dataclasses.dataclass(frozen=True)
class FollowerPosition:
    """A follower's durable replication cursor: which leader generation
    it mirrors, the sealed segment it is consuming, the verified byte
    offset within it, and the records applied this generation."""

    generation: int
    segment: int
    offset: int
    applied_records: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(doc: dict) -> "FollowerPosition":
        return FollowerPosition(
            generation=int(doc["generation"]),
            segment=int(doc["segment"]),
            offset=int(doc["offset"]),
            applied_records=int(doc["applied_records"]),
        )


def _read_file_chunk(path: str, offset: int, nbytes: int) -> bytes:
    """The default transport: the leader's segment file is directly
    readable (same process / shared filesystem)."""
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read(nbytes)


class Follower:
    """One bounded-staleness replica of a leader mutable index.

    Serves from an **in-memory** :class:`MutableIndex` (``.index`` —
    what a :class:`~raft_tpu.serve.engine.ServingEngine` registers)
    rebuilt from the leader's manifest artifacts and advanced by
    replaying shipped WAL frames. Its own ``directory`` holds the
    verified shipped bytes plus ``FOLLOWER.json``, so a killed follower
    restarts exactly where it stopped — :meth:`sync_generation` replays
    the local files and lands bit-identical to its pre-kill state.
    """

    def __init__(
        self,
        leader_dir: str,
        directory: str,
        *,
        algo: str,
        dim: int,
        index_params=None,
        search_params=None,
        metric=None,
        name: str = "follower",
        delta_mode: str = "auto",
    ):
        self.leader_dir = leader_dir
        self.directory = directory
        self.algo = algo
        self.dim = int(dim)
        self.index_params = index_params
        self.search_params = search_params
        self.metric = metric
        self.name = str(name)
        self.delta_mode = delta_mode
        os.makedirs(directory, exist_ok=True)
        self.index: Optional[MutableIndex] = None
        self.position = FollowerPosition(
            generation=-1, segment=0, offset=0, applied_records=0
        )
        #: fencing high-water mark: the highest lease epoch this
        #: follower has accepted a frame under (0 = unfenced — every
        #: non-control-plane pipeline ships at epoch 0 and is accepted).
        #: Single-owner like ``position`` (the shipping tick), so no lock.
        self.fence_epoch = 0
        self.sync_generation()

    def fence(self, epoch: int) -> None:
        """Raise the fencing floor: frames stamped with a lease epoch
        below ``epoch`` are rejected typed from now on (a deposed
        leader's ship can no longer advance this follower). Monotonic —
        fencing never lowers."""
        self.fence_epoch = max(self.fence_epoch, int(epoch))

    # -- generation management ---------------------------------------------

    def _seg_file(self, segment: int) -> str:
        """Local store of the verified bytes of leader segment
        ``segment`` for the current generation."""
        return os.path.join(
            self.directory,
            f"shipped-g{self.position.generation:08d}-{segment:06d}",
        )

    def sync_generation(self) -> bool:
        """Follow the leader's manifest: when its generation moved (or
        on first call / restart), rebuild the serving index from the
        generation's artifacts, drop shipped files from dead
        generations, and replay this generation's locally-persisted
        shipped frames. Durable local bytes outrank the persisted
        cursor — a crash between frame fsync and cursor swap recovers
        forward, and replay-by-upsert makes re-application idempotent.
        Returns True when a rebase happened."""
        m = man.read(self.leader_dir)
        expects(m is not None, "leader directory %r has no manifest", self.leader_dir)
        if self.index is not None and m.generation == self.position.generation:
            return False
        expects(m.algo == self.algo, "leader serves %r, follower built for %r",
                m.algo, self.algo)
        expects(m.dim == self.dim, "leader dim %d, follower dim %d", m.dim, self.dim)
        idx = MutableIndex(
            self.algo, self.dim,
            index_params=self.index_params, search_params=self.search_params,
            metric=self.metric, name=f"{self.name}-g{m.generation}",
            delta_mode=self.delta_mode,
        )
        idx.generation = m.generation
        idx.next_id = m.next_id
        if m.rows is not None:
            ids, data = _load_rows(os.path.join(self.leader_dir, m.rows))
            idx._install_main(ids, data, index=None)
            if m.main is not None:
                idx.main_index = _load_main(
                    self.algo, os.path.join(self.leader_dir, m.main), data
                )
        persisted = self._read_position()
        self.index = idx
        self.position = FollowerPosition(
            generation=m.generation, segment=0, offset=0, applied_records=0
        )
        for fname in sorted(os.listdir(self.directory)):
            if fname.startswith("shipped-") and not fname.startswith(
                f"shipped-g{m.generation:08d}-"
            ):
                os.unlink(os.path.join(self.directory, fname))
        self._replay_local()
        if persisted is not None and persisted.generation == m.generation:
            # the cursor may legitimately be ahead of local content in
            # exactly one way: advance_past persisted a segment bump
            # without writing bytes for the next segment yet
            if (persisted.segment, persisted.offset) > (
                self.position.segment, self.position.offset
            ):
                self.position = dataclasses.replace(
                    persisted,
                    applied_records=max(
                        persisted.applied_records, self.position.applied_records
                    ),
                )
        self._persist_position()
        if obs.is_enabled():
            obs.inc("replica.generation_syncs", follower=self.name)
        return True

    def _replay_local(self) -> None:
        """Rebuild replication state from the locally-persisted shipped
        frames of the current generation (restart path)."""
        gen = self.position.generation
        prefix = f"shipped-g{gen:08d}-"
        seqs: List[int] = []
        for fname in os.listdir(self.directory):
            if fname.startswith(prefix) and fname[len(prefix):].isdigit():
                seqs.append(int(fname[len(prefix):]))
        applied = 0
        seg, off = 0, 0
        for sq in sorted(seqs):
            records, good = wal_replay(
                os.path.join(self.directory, f"{prefix}{sq:06d}")
            )
            for rec in records:
                self._replay(rec)
            applied += len(records)
            seg, off = sq, good
        if seqs:
            self.position = FollowerPosition(
                generation=gen, segment=seg, offset=off, applied_records=applied
            )

    # -- the apply path ----------------------------------------------------

    def apply(self, segment: int, offset: int, data: bytes, *, epoch: int = 0) -> int:
        """Verify and apply one shipped chunk.

        Every frame is checked (magic, length, CRC, payload decode)
        before any of the chunk is applied; the verified clean prefix is
        fsync'd to the local segment file, replayed into the serving
        index, and the cursor swapped — in that order, so a kill at any
        instruction recovers to a state replay reconstructs. A chunk
        that merely *ends* mid-frame is normal chunking (the remainder
        re-ships next call); a damaged frame raises
        :class:`ShipRejected` at the clean-prefix offset AFTER the
        clean prefix was applied, so the shipper re-requests only the
        damaged bytes. Returns bytes consumed.

        ``epoch`` is the sender's fencing token (its lease epoch at
        ship time). A token below :attr:`fence_epoch` raises
        :class:`FencedError` before a single byte is considered — a
        deposed leader cannot corrupt a follower, however valid its
        frames. A token *above* the fence advances it: followers learn
        a new leadership regime from the frames themselves."""
        faults.fire("replica.apply", follower=self.name, segment=segment)
        epoch = int(epoch)
        if epoch < self.fence_epoch:
            obs.inc("replica.fenced_frames", follower=self.name)
            recorder.note_fenced(self.name, epoch, self.fence_epoch)
            raise FencedError(
                f"follower {self.name!r} fenced at epoch {self.fence_epoch} "
                f"rejected a frame stamped epoch {epoch} (deposed sender)",
                epoch=epoch, fence_epoch=self.fence_epoch,
            )
        if epoch > self.fence_epoch:
            self.fence_epoch = epoch
        pos = self.position
        expects(segment == pos.segment,
                "chunk for segment %d but follower is at segment %d",
                segment, pos.segment)
        expects(offset == pos.offset,
                "chunk at offset %d but follower is at offset %d",
                offset, pos.offset)
        records: List[WalRecord] = []
        good, n = 0, len(data)
        bad: Optional[str] = None
        while good < n:
            head = data[good : good + _HEADER.size]
            if len(head) < _HEADER.size:
                break  # chunk ends mid-header: benign, await more bytes
            magic, length, crc = _HEADER.unpack(head)
            if magic != _REC_MAGIC:
                bad = "magic"
                break
            payload = data[good + _HEADER.size : good + _HEADER.size + length]
            if len(payload) < length:
                break  # chunk ends mid-payload: benign
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                bad = "crc"
                break
            try:
                records.append(WalRecord.decode(payload))
            except Exception:
                bad = "decode"
                break
            good += _HEADER.size + length
        if good:
            with open(self._seg_file(segment), "ab") as f:
                f.write(data[:good])
                f.flush()
                os.fsync(f.fileno())
            for rec in records:
                self._replay(rec)
            self.position = dataclasses.replace(
                pos,
                offset=pos.offset + good,
                applied_records=pos.applied_records + len(records),
            )
            self._persist_position()
            if obs.is_enabled():
                obs.set_gauge(
                    "replica.applied_records",
                    float(self.position.applied_records), follower=self.name,
                )
        if bad is not None:
            obs.inc("replica.ship.rejected", follower=self.name, reason=bad)
            raise ShipRejected(
                f"follower {self.name!r} rejected segment {segment} at offset "
                f"{self.position.offset}: frame failed {bad} verification",
                segment=segment, offset=self.position.offset,
            )
        return good

    def advance_past(self, segment: int) -> None:
        """The shipper's signal that leader segment ``segment`` is fully
        consumed: move the cursor to the start of the next one."""
        pos = self.position
        expects(segment == pos.segment, "cannot advance past segment %d from %d",
                segment, pos.segment)
        self.position = dataclasses.replace(pos, segment=segment + 1, offset=0)
        self._persist_position()

    def _replay(self, rec: WalRecord) -> None:
        """One record into the serving index. ``insert`` replays as
        ``upsert``: identical bytes in the delta when the id is not
        live, and idempotent when a restart replays it twice."""
        if rec.op in ("insert", "upsert"):
            self.index.upsert(rec.ids, rec.vectors)
        else:
            self.index.delete(rec.ids)

    # -- cursor persistence ------------------------------------------------

    def _position_path(self) -> str:
        return os.path.join(self.directory, POSITION_FILE)

    def _read_position(self) -> Optional[FollowerPosition]:
        path = self._position_path()
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return FollowerPosition.from_dict(json.loads(f.read()))

    def _persist_position(self) -> None:
        path = self._position_path()
        tmp = path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(self.position.as_dict(), indent=2, sort_keys=True))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def snapshot(self):
        """The follower's current search view (delegates to the serving
        index) — what a reader at this replica sees."""
        return self.index.snapshot()


class Shipper:
    """Moves sealed WAL frames from one leader log to one follower.

    ``wal_source`` is the leader's :class:`WriteAheadLog` or a callable
    returning it — compaction replaces the leader's log object at every
    generation flip, so the pipeline passes ``lambda: leader.wal``.
    ``transport(path, offset, nbytes) -> bytes`` abstracts the byte
    transfer; a rejected chunk (CRC damage in flight) is **re-requested
    from the follower's clean-prefix offset** up to ``max_retries``
    times per segment before the error propagates to the tick.

    ``epoch_source`` is the control plane's fencing hook: a callable
    returning the sender's *current* lease epoch, read per chunk so the
    token is fresh at every seal→ship→apply hop. Without one, chunks
    ship at epoch 0 (the unfenced, pre-control-plane protocol).
    """

    def __init__(
        self,
        wal_source,
        follower: Follower,
        *,
        transport: Optional[Callable[[str, int, int], bytes]] = None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        max_retries: int = 2,
        epoch_source: Optional[Callable[[], int]] = None,
    ):
        self._wal_source = wal_source
        self.follower = follower
        self.transport = transport if transport is not None else _read_file_chunk
        self.chunk_bytes = int(chunk_bytes)
        self.max_retries = int(max_retries)
        self.epoch_source = epoch_source

    def _wal(self) -> WriteAheadLog:
        w = self._wal_source
        return w() if callable(w) else w

    def ship(self) -> int:
        """Ship every sealed frame the follower has not applied yet;
        returns the number of records the follower applied."""
        wal = self._wal()
        before = self.follower.position.applied_records
        for sq, sp in wal.sealed_segments():
            if sq < self.follower.position.segment:
                continue  # fully consumed in an earlier tick
            self._ship_segment(sq, sp)
        return self.follower.position.applied_records - before

    def _ship_segment(self, sq: int, sp: str) -> None:
        size = os.path.getsize(sp)
        rejections = 0
        chunk = self.chunk_bytes
        while self.follower.position.offset < size:
            pos = self.follower.position
            nbytes = min(chunk, size - pos.offset)
            faults.fire(
                "wal.ship",
                segment=sq, offset=pos.offset, nbytes=nbytes,
                follower=self.follower.name,
            )
            data = self.transport(sp, pos.offset, nbytes)
            if obs.is_enabled():
                obs.inc("replica.ship.bytes", float(len(data)),
                        follower=self.follower.name)
            epoch = int(self.epoch_source()) if self.epoch_source is not None else 0
            try:
                consumed = self.follower.apply(sq, pos.offset, data, epoch=epoch)
            except ShipRejected:
                rejections += 1
                if rejections > self.max_retries:
                    raise
                # re-request: the follower applied the clean prefix and
                # its cursor now sits exactly on the damaged byte
                continue
            if consumed == 0:
                if pos.offset + len(data) >= size:
                    # a sealed segment may never end mid-frame — this is
                    # storage/transport truncation, not chunking
                    rejections += 1
                    obs.inc("replica.ship.rejected",
                            follower=self.follower.name, reason="torn_tail")
                    if rejections > self.max_retries:
                        raise ShipRejected(
                            f"sealed segment {sq} of {sp!r} ends mid-frame at "
                            f"offset {pos.offset}",
                            segment=sq, offset=pos.offset,
                        )
                else:
                    # one frame larger than the chunk: widen and re-read
                    chunk *= 2
                continue
        self.follower.advance_past(sq)


class Replication:
    """The per-index replication pipeline: one leader, N followers,
    one :meth:`tick` the serving layer drives.

    Each tick: follow the leader's manifest generation, seal the
    leader's active segment once it passes ``seal_bytes``, ship sealed
    frames to every follower, and publish each follower's record lag
    (``replica.staleness_records``). :meth:`indexes` hands the group
    one serving handle per replica — the leader itself, then each
    follower's in-memory index."""

    def __init__(
        self,
        leader: MutableIndex,
        followers: List[Follower],
        *,
        seal_bytes: int = DEFAULT_CHUNK_BYTES,
        transports: Optional[List[Optional[Callable]]] = None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        max_retries: int = 2,
        epoch_source: Optional[Callable[[], int]] = None,
    ):
        expects(leader.directory is not None and leader.wal is not None,
                "replication needs a directory-backed (WAL-carrying) leader")
        expects(len(followers) >= 1, "replication needs at least one follower")
        expects(seal_bytes >= 1, "seal_bytes must be >= 1")
        self.leader = leader
        self.followers = list(followers)
        self.seal_bytes = int(seal_bytes)
        self._chunk_bytes = int(chunk_bytes)
        self._max_retries = int(max_retries)
        #: the fencing token source every shipper stamps chunks with —
        #: a :class:`~raft_tpu.replica.control.ControlPlane` points this
        #: at its lease epoch; None ships at epoch 0 (unfenced)
        self.epoch_source = epoch_source
        #: attached control plane (lease/election coordinator) — ticked
        #: first on every :meth:`tick` when present
        self.controller = None
        #: False while the leader is known dead and no successor has
        #: been elected yet: the pipeline parks (no seal, no ship)
        #: instead of pumping a corpse's WAL
        self.active = True
        self._handles_changed = False
        if transports is None:
            transports = [None] * len(self.followers)
        self._transports = list(transports)
        self.shippers = [self._mk_shipper(f, t)
                         for f, t in zip(self.followers, self._transports)]

    def _mk_shipper(self, f: Follower, t: Optional[Callable]) -> Shipper:
        return Shipper(
            lambda: self.leader.wal, f,
            transport=t, chunk_bytes=self._chunk_bytes,
            max_retries=self._max_retries, epoch_source=self._epoch,
        )

    def _epoch(self) -> int:
        src = self.epoch_source
        return int(src()) if src is not None else 0

    # -- control-plane reconfiguration --------------------------------------

    def replace(
        self,
        leader: MutableIndex,
        followers: List[Follower],
        *,
        transports: Optional[List[Optional[Callable]]] = None,
    ) -> None:
        """Swap in a whole new leader + follower set (what a promotion
        builds) and rebuild the shippers. Serving handles changed:
        :meth:`take_handles_changed` tells the replica group to
        re-register every engine."""
        expects(leader.directory is not None and leader.wal is not None,
                "replication needs a directory-backed (WAL-carrying) leader")
        expects(len(followers) >= 1, "replication needs at least one follower")
        if transports is None:
            transports = [None] * len(followers)
        self.leader = leader
        self.followers = list(followers)
        self._transports = list(transports)
        self.shippers = [self._mk_shipper(f, t)
                         for f, t in zip(self.followers, self._transports)]
        self.active = True
        self._handles_changed = True

    def add_follower(self, follower: Follower, transport: Optional[Callable] = None) -> None:
        """Grow the pipeline by one follower (replica scale-up)."""
        self.followers = self.followers + [follower]
        self._transports = self._transports + [transport]
        self.shippers = self.shippers + [self._mk_shipper(follower, transport)]
        self._handles_changed = True

    def remove_follower(self) -> Follower:
        """Retire the last follower (replica scale-down); the caller
        has already drained its replica."""
        expects(len(self.followers) >= 2,
                "cannot retire the last follower of a replication")
        f = self.followers[-1]
        self.followers = self.followers[:-1]
        self._transports = self._transports[:-1]
        self.shippers = self.shippers[:-1]
        self._handles_changed = True
        return f

    def take_handles_changed(self) -> bool:
        """True exactly once after a reconfiguration changed
        :meth:`indexes` — the group's cue to re-register engines."""
        changed, self._handles_changed = self._handles_changed, False
        return changed

    def tick(self) -> int:
        """One seal → ship → publish cycle; returns records applied
        across followers. A follower whose ship fails this tick keeps
        its clean prefix and retries next tick — the error (transport,
        verification, or a stale fencing token) is counted, never
        raised into the serving loop."""
        if self.controller is not None:
            self.controller.tick()
        if not self.active:
            return 0
        for f in self.followers:
            f.sync_generation()
        wal = self.leader.wal
        if wal is not None and wal.offset >= self.seal_bytes:
            wal.seal()
        applied = 0
        for f, sh in zip(self.followers, self.shippers):
            try:
                applied += sh.ship()
            except (ShipRejected, FencedError, OSError) as e:
                obs.inc("replica.ship.errors", follower=f.name,
                        kind=type(e).__name__)
        if obs.is_enabled():
            for i, f in enumerate(self.followers):
                obs.set_gauge("replica.staleness_records",
                              float(self.staleness(i)), follower=f.name)
        return applied

    def staleness(self, i: int) -> int:
        """Follower ``i``'s lag in WAL records behind the leader's
        durable high-water mark (a whole generation behind counts as
        the full log)."""
        f = self.followers[i]
        wal = self.leader.wal
        total = wal.record_count() if wal is not None else 0
        if f.position.generation != self.leader.generation:
            return total
        return max(total - f.position.applied_records, 0)

    def indexes(self) -> List[object]:
        """One serving handle per replica: the leader, then each
        follower's in-memory index (replica ``j+1`` serves follower
        ``j`` — the ordering :meth:`~raft_tpu.replica.group.
        ReplicaGroup.register_mutable_replicated` assumes)."""
        return [self.leader] + [f.index for f in self.followers]
