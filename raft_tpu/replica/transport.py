"""A real socket transport behind the ``Shipper.transport`` seam.

The shipping protocol (:mod:`raft_tpu.replica.shipping`) was designed
so "a network hop slots in without touching the protocol" — this module
is that hop. A :class:`SegmentServer` exports a leader's sealed WAL
segment files over length-framed TCP; a :class:`SocketTransport` is the
``transport(path, offset, nbytes) -> bytes`` callable a
:class:`~raft_tpu.replica.shipping.Shipper` plugs in.

**Framing** reuses the WAL's own record envelope — ``b"WALR" | u32 len
| u32 crc32 | payload`` (:data:`raft_tpu.mutable.wal._HEADER`) — for
both the request (a JSON ``{path, offset, nbytes}`` body) and the
response (one status byte + the segment bytes). The client verifies
the envelope CRC before returning, so *wire* damage is caught at the
transport and retried; *content* damage (a corrupted segment file, or
a chaos ``mangle`` hook below) passes the envelope intact and is
caught by the follower's per-frame verification — surfacing as the
existing :class:`~raft_tpu.replica.shipping.ShipRejected`
clean-prefix/re-request path, now exercised over a wire that can
actually drop, truncate, and reorder.

**Failure containment**: every fetch crosses the ``transport.read``
chaos seam, runs under a seeded-backoff :func:`~raft_tpu.robust.retry.
retry_call` (injectable ``sleep`` — virtual-clock tests assert the
schedule), and is gated by a per-peer :class:`~raft_tpu.robust.retry.
CircuitBreaker` so a dead peer costs one connection attempt per reset
window, not one per chunk. Terminal failures raise
:class:`TransportError` — an ``OSError`` subclass *by contract*:
``Replication.tick`` catches ``(ShipRejected, FencedError, OSError)``
and counts them, so a dead wire degrades to bounded staleness, never
into the serving loop. Socket timeouts bound every blocking call — a
slow peer is a typed timeout, never a hang.

The server's accept loop is one daemon thread, joined by
:meth:`SegmentServer.close`; requests are one-shot (one frame in, one
frame out, close), so the server holds no per-client state and needs
no lock. The client is lock-free by the same single-owner discipline
as the shipper that calls it.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Optional

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.mutable.wal import _HEADER, _REC_MAGIC
from raft_tpu.robust import faults
from raft_tpu.robust.retry import CircuitBreaker, RetryError, RetryPolicy, retry_call

#: response status bytes (first payload byte)
_ST_OK = b"\x00"
_ST_ERR = b"\x01"

#: cap on a single framed payload crossing the wire — a request is tiny
#: and a response is at most one ship chunk (chunk-widening doubles from
#: 64 KiB), so anything near this is a corrupt length field, not data
_MAX_FRAME = 1 << 28


class TransportError(OSError):
    """A segment fetch failed terminally (retries exhausted, breaker
    open, torn frame, or peer timeout). Subclasses :class:`OSError`
    so ``Replication.tick``'s existing catch contains it — a transport
    death is bounded staleness, not a serving error."""


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(_REC_MAGIC, len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise: a peer that hangs up mid-frame
    is a torn wire, typed — never silently short."""
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise TransportError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(conn: socket.socket) -> bytes:
    """One CRC-verified framed payload off the socket."""
    head = _recv_exact(conn, _HEADER.size)
    try:
        magic, length, crc = _HEADER.unpack(head)
    except struct.error as e:  # pragma: no cover - _recv_exact guarantees size
        raise TransportError(f"unreadable frame header: {e}")
    if magic != _REC_MAGIC:
        raise TransportError(f"bad frame magic {magic!r}")
    if length > _MAX_FRAME:
        raise TransportError(f"frame length {length} exceeds cap {_MAX_FRAME}")
    payload = _recv_exact(conn, length)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TransportError("frame CRC mismatch (damaged in flight)")
    return payload


class SegmentServer:
    """Serves chunk reads of files under ``root`` over TCP.

    One request per connection: a framed JSON ``{path, offset,
    nbytes}`` in, a framed ``status + bytes`` out. Paths are validated
    to resolve under ``root`` — the server never reads outside the
    leader directory it was built for.

    The chaos hooks exist for the transport's own test matrix:
    ``mangle`` rewrites the segment bytes *before* framing (content
    damage the client's envelope CRC cannot see — the follower's frame
    verification must catch it), ``truncate_wire`` cuts the response
    off mid-frame (a torn wire the client retries), and ``delay_s``
    stalls before replying (a slow peer the client times out on).
    """

    def __init__(self, root: str, *, host: str = "127.0.0.1"):
        self.root = os.path.realpath(root)
        #: test hooks (see class docstring); None/0 = healthy server
        self.mangle: Optional[Callable[[bytes], bytes]] = None
        self.truncate_wire: Optional[int] = None
        self.delay_s: float = 0.0
        self._sock = socket.create_server((host, 0))
        self._sock.settimeout(0.1)  # bounded accept wait → prompt close()
        self.host, self.port = self._sock.getsockname()[:2]
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name=f"segment-server:{self.port}", daemon=True
        )
        self._thread.start()

    def address(self):
        return self.host, self.port

    def close(self) -> None:
        """Stop accepting and join the accept loop."""
        self._stopped.set()
        self._thread.join(timeout=5.0)
        self._sock.close()

    # -- the accept loop ----------------------------------------------------

    def _serve(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed under us during shutdown
            try:
                with conn:
                    conn.settimeout(2.0)
                    self._handle(conn)
            except Exception as e:
                # a broken client must never kill the accept loop; count
                # it — the client side surfaces its own typed error
                # (label is the constant "server": ports are ephemeral
                # and would mint unbounded series)
                obs.inc(
                    "replica.transport.errors",
                    peer="server", kind=type(e).__name__,
                )

    def _handle(self, conn: socket.socket) -> None:
        req = json.loads(_read_frame(conn).decode("utf-8"))
        path = os.path.realpath(str(req["path"]))
        offset = int(req["offset"])
        nbytes = int(req["nbytes"])
        if path != self.root and not path.startswith(self.root + os.sep):
            conn.sendall(_frame(_ST_ERR + b"path outside served root"))
            return
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(nbytes)
        except OSError as e:
            conn.sendall(_frame(_ST_ERR + str(e).encode("utf-8")))
            return
        if self.mangle is not None:
            data = self.mangle(data)
        if self.delay_s > 0.0:
            time.sleep(self.delay_s)
        wire = _frame(_ST_OK + data)
        if self.truncate_wire is not None:
            wire = wire[: self.truncate_wire]
        conn.sendall(wire)


class SocketTransport:
    """The ``transport(path, offset, nbytes) -> bytes`` callable that
    fetches from a :class:`SegmentServer` peer.

    One fetch = chaos seam → breaker gate → retried framed
    request/response. ``policy``/``seed``/``sleep`` make the backoff
    schedule deterministic (tests assert it); ``timeout_s`` bounds
    every socket operation so a slow or silent peer is a typed error,
    never a hang.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        timeout_s: float = 2.0,
        policy: Optional[RetryPolicy] = None,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        breaker: Optional[CircuitBreaker] = None,
        name: Optional[str] = None,
    ):
        expects(timeout_s > 0.0, "timeout_s must be positive")
        self.host = str(host)
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.policy = policy if policy is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.01, retryable=(OSError,)
        )
        self.seed = int(seed)
        self.sleep = sleep
        self.name = name or f"transport:{self.host}:{self.port}"
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            self.name, failure_threshold=3, reset_timeout_s=0.25
        )
        self.fetches = 0

    def _fetch(self, path: str, offset: int, nbytes: int) -> bytes:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        ) as conn:
            conn.settimeout(self.timeout_s)
            body = json.dumps(
                {"path": path, "offset": int(offset), "nbytes": int(nbytes)}
            ).encode("utf-8")
            conn.sendall(_frame(body))
            payload = _read_frame(conn)
        if not payload or payload[:1] != _ST_OK:
            detail = payload[1:].decode("utf-8", "replace") if payload else "empty"
            raise TransportError(f"peer {self.name} refused read: {detail}")
        return payload[1:]

    def __call__(self, path: str, offset: int, nbytes: int) -> bytes:
        faults.fire("transport.read", peer=self.name, offset=int(offset),
                    nbytes=int(nbytes))
        if not self.breaker.allow():
            raise TransportError(
                f"breaker open for {self.name}: peer quarantined"
            )
        self.fetches += 1
        try:
            data = retry_call(
                self._fetch, path, offset, nbytes,
                policy=self.policy, op="transport.read",
                seed=self.seed, sleep=self.sleep,
            )
        except RetryError as e:
            self.breaker.record_failure()
            obs.inc("replica.transport.errors", peer=self.name,
                    kind=type(e.last).__name__ if e.last is not None else "unknown")
            raise TransportError(
                f"fetch from {self.name} failed terminally: {e}"
            ) from e
        self.breaker.record_success()
        if obs.is_enabled():
            obs.inc("replica.transport.bytes", float(len(data)), peer=self.name)
        return data
