"""Control plane: lease-based leader election, fencing, autoscaling.

The shipping pipeline (:mod:`raft_tpu.replica.shipping`) answers *how*
bytes move; this module answers the three questions a production
deployment asks on top (ROADMAP item 6):

* **who ingests** — a :class:`LeaseStore` holds one time-bounded lease
  with a monotonic **epoch counter**. The atomic primitive is
  filesystem CAS: a candidate writes the lease body to a private temp
  file (fsync'd), then ``os.link``\\ s it to ``lease-e{epoch}`` — link
  fails with ``FileExistsError`` when another candidate claimed that
  epoch first, so exactly one acquirer wins and the winning file is
  always complete (content precedes visibility, the repo's usual
  durable-then-visible discipline). Renewal rewrites the holder's own
  epoch file (temp + fsync + ``os.replace``); an *expired* lease is
  never renewable — a new regime requires a new epoch, which is what
  makes fencing sound.
* **what happens when the leader dies** — :class:`ControlPlane` binds
  one :class:`~raft_tpu.replica.shipping.Replication` to one lease.
  Every tick it renews inside the renew window; once the lease has
  expired (a dead leader stops renewing — that *is* the failure
  detector) it elects: the live follower with the **highest shipped
  cursor** ``(generation, applied_records, segment, offset)`` promotes.
  Promotion rebuilds a directory-backed leader from the winner's
  ``live_rows()``, rebases every other slot as a fresh follower of the
  new leader, and **fences** them at the new epoch. The epoch rides
  every seal→ship→apply hop (``Shipper.epoch_source`` →
  ``Follower.apply(epoch=...)``), so a deposed leader that keeps
  shipping gets a typed :class:`~raft_tpu.replica.shipping.FencedError`
  — never a corrupted follower.
* **how the fleet resizes** — :class:`Autoscaler` is the hysteresis
  state machine ``ReplicaGroup.maintenance_tick`` consults: SLO fast
  burn rate or queue depth above the up-thresholds for ``up_ticks``
  consecutive ticks grows the group (the group warms the new replica
  up *before* it takes traffic); both below the down-thresholds for
  ``down_ticks`` shrinks it (the group drains the retiring replica
  first). :meth:`Autoscaler.decide` only ever *advises* — acting
  (spawning engines, draining, registering) is the group's business,
  outside this module's lock.

Chaos seams: ``lease.acquire`` and ``lease.renew`` fire before any
store I/O, ``election.promote`` fires before the winning candidate's
CAS — a fault injected there models a coordinator dying mid-election
(the next tick simply re-runs it; the CAS makes double-promotion
impossible). Control-plane faults are **contained**: :meth:`ControlPlane.
tick` catches everything, counts it as ``replica.control.errors``, and
retries next tick — an election in progress is never a caller-visible
error.

Locking contract (``tools/graft_lint/lock_order.toml``):
``replica.lease`` guards only the store's last-observed-lease cache and
``replica.autoscaler`` only the hysteresis counters; both are edge-free
leaves — every fault seam, obs emission, and file operation runs with
the lock released. :class:`ControlPlane` itself takes no lock: it is
single-driver by contract (the maintenance tick — thread 0 in the
group's threaded mode, the stepping thread otherwise).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, List, Optional, Set

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.mutable.segments import MutableIndex
from raft_tpu.obs import recorder
from raft_tpu.replica.shipping import Follower, Replication
from raft_tpu.robust import faults
from raft_tpu.utils import lockcheck

_LEASE_PREFIX = "lease-e"


@dataclasses.dataclass(frozen=True)
class Lease:
    """One leadership grant: who holds it, under which fencing epoch,
    and until when (on the store's injectable clock)."""

    holder: str
    epoch: int
    expires_s: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(doc: dict) -> "Lease":
        return Lease(
            holder=str(doc["holder"]),
            epoch=int(doc["epoch"]),
            expires_s=float(doc["expires_s"]),
        )


@lockcheck.guarded_fields
class LeaseStore:
    """File-backed atomic-CAS lease with a monotonic epoch counter.

    One directory holds one lease history: ``lease-e{epoch:016d}``
    files, highest epoch current. :meth:`acquire` claims epoch
    ``current + 1`` via write-temp → fsync → ``os.link`` — the link is
    the CAS, so two racing candidates cannot both win an epoch and a
    visible lease file is always complete. :meth:`renew` extends the
    holder's own live lease in place (atomic ``os.replace``); an
    expired lease is *not* renewable — the holder must re-acquire,
    bumping the epoch, which is exactly what downstream fencing needs.

    ``clock`` is injectable (virtual-clock tests drive expiry
    deterministically). The ``replica.lease`` lock guards only the
    last-observed-lease cache; all file I/O and every chaos seam
    (``lease.acquire`` / ``lease.renew``) run with it released.
    """

    def __init__(
        self,
        directory: str,
        *,
        ttl_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        expects(ttl_s > 0.0, "lease ttl must be positive, got %r", ttl_s)
        self.directory = str(directory)
        self.ttl_s = float(ttl_s)
        self.clock = clock
        os.makedirs(self.directory, exist_ok=True)
        # guards _cached only (lock_order.toml [[guards]]); edge-free
        # leaf — nothing is called while it is held
        self._lock = lockcheck.tracked(threading.Lock(), "replica.lease")
        self._cached: Optional[Lease] = None

    # -- reading -----------------------------------------------------------

    def _path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"{_LEASE_PREFIX}{epoch:016d}")

    def current(self) -> Optional[Lease]:
        """The highest-epoch lease on disk (live or expired), or None
        when nothing was ever granted."""
        best = -1
        for fname in os.listdir(self.directory):
            if fname.startswith(_LEASE_PREFIX):
                tail = fname[len(_LEASE_PREFIX):]
                if tail.isdigit():
                    best = max(best, int(tail))
        if best < 0:
            return None
        with open(self._path(best), "r", encoding="utf-8") as f:
            lease = Lease.from_dict(json.loads(f.read()))
        with self._lock:
            self._cached = lease
        return lease

    def cached(self) -> Optional[Lease]:
        """The last lease this store observed (no I/O)."""
        with self._lock:
            return self._cached

    def epoch(self) -> int:
        """The current fencing epoch (0 before any grant)."""
        cur = self.current()
        return cur.epoch if cur is not None else 0

    def expired(self, lease: Optional[Lease] = None, now: Optional[float] = None) -> bool:
        if lease is None:
            lease = self.current()
        if lease is None:
            return True
        now = self.clock() if now is None else now
        return now >= lease.expires_s

    # -- the CAS -----------------------------------------------------------

    def acquire(self, holder: str, *, now: Optional[float] = None) -> Optional[Lease]:
        """Claim the lease under a fresh epoch. Succeeds only when no
        *live* lease is held by someone else AND this candidate wins
        the epoch CAS; returns None otherwise (caller retries on a
        later tick). A holder re-acquiring its own expired lease also
        bumps the epoch — any acquisition is a new regime."""
        faults.fire("lease.acquire", holder=holder)
        now = self.clock() if now is None else now
        cur = self.current()
        if cur is not None and now < cur.expires_s and cur.holder != holder:
            return None  # someone else's live lease governs
        epoch = (cur.epoch if cur is not None else 0) + 1
        lease = Lease(holder=str(holder), epoch=epoch, expires_s=now + self.ttl_s)
        tmp = os.path.join(
            self.directory, f".acquire-{os.getpid()}-{threading.get_ident()}"
        )
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(lease.as_dict(), indent=2, sort_keys=True))
            f.flush()
            os.fsync(f.fileno())
        try:
            # the CAS: link fails iff another candidate claimed this
            # epoch first — and a visible lease file is always complete
            os.link(tmp, self._path(epoch))
        except FileExistsError:
            return None
        finally:
            os.unlink(tmp)
        with self._lock:
            self._cached = lease
        if obs.is_enabled():
            obs.inc("replica.lease.acquired", holder=str(holder))
        return lease

    def renew(self, holder: str, *, now: Optional[float] = None) -> Optional[Lease]:
        """Extend the holder's *live* lease to ``now + ttl``. Returns
        None when the holder was deposed (someone else holds a higher
        epoch) or the lease already expired — expiry demands a fresh
        :meth:`acquire` so the epoch advances."""
        faults.fire("lease.renew", holder=holder)
        now = self.clock() if now is None else now
        cur = self.current()
        if cur is None or cur.holder != holder or now >= cur.expires_s:
            return None
        lease = Lease(holder=cur.holder, epoch=cur.epoch, expires_s=now + self.ttl_s)
        path = self._path(cur.epoch)
        tmp = path + f".renew{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(lease.as_dict(), indent=2, sort_keys=True))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        with self._lock:
            self._cached = lease
        return lease

    def release(self, holder: str, *, now: Optional[float] = None) -> bool:
        """Voluntarily end the holder's live lease (expires it *now*),
        letting a successor acquire without waiting out the ttl.
        Returns False when the holder no longer governs."""
        now = self.clock() if now is None else now
        cur = self.current()
        if cur is None or cur.holder != holder or now >= cur.expires_s:
            return False
        ended = Lease(holder=cur.holder, epoch=cur.epoch, expires_s=now)
        path = self._path(cur.epoch)
        tmp = path + f".release{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(ended.as_dict(), indent=2, sort_keys=True))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        with self._lock:
            self._cached = ended
        return True


class ControlPlane:
    """Leader election + fencing coordinator for one replication.

    Construction claims the bootstrap lease for the current leader and
    points the pipeline's ``epoch_source`` at :attr:`epoch`, so every
    shipped chunk carries the live fencing token from the first tick.
    :meth:`tick` (driven by ``Replication.tick``, i.e. the group's
    maintenance cadence) then:

    1. renews the leader's lease once inside the renew window
       (``renew_fraction * ttl`` before expiry);
    2. does nothing while a live lease governs — including a lease held
       by a leader whose *transport* is dead (the partition case: ingest
       pauses, followers serve bounded-stale reads, and election waits
       for honest expiry);
    3. on expiry, elects: the live follower with the highest shipped
       cursor wins, acquires the next epoch by CAS, and promotes.

    Promotion = rebuild a directory-backed leader from the winner's
    ``live_rows()`` under ``root_dir``, rebase every other slot as a
    fresh follower of it, fence everyone at the new epoch, and hand the
    new handle set to the pipeline (``Replication.replace``) — the
    replica group re-registers its engines on the next maintenance
    tick. The deposed leader's serving slot rejoins as a follower, so
    the replica count is conserved.

    Every failure inside a tick (including injected ``lease.*`` /
    ``election.promote`` faults) is contained: counted as
    ``replica.control.errors{kind}`` and retried next tick.
    """

    def __init__(
        self,
        replication: Replication,
        lease_store: LeaseStore,
        *,
        root_dir: str,
        name: str = "control",
        renew_fraction: float = 0.5,
        clock: Optional[Callable[[], float]] = None,
    ):
        expects(0.0 < renew_fraction <= 1.0,
                "renew_fraction must be in (0, 1], got %r", renew_fraction)
        self.replication = replication
        self.lease = lease_store
        self.root_dir = str(root_dir)
        self.name = str(name)
        self.renew_fraction = float(renew_fraction)
        self._clock = clock if clock is not None else lease_store.clock
        os.makedirs(self.root_dir, exist_ok=True)
        self.leader_name = replication.leader.name
        self._dead: Set[str] = set()
        self.elections = 0
        self._spawned = 0
        # bootstrap: the standing leader claims epoch 1 so fencing is
        # armed from the first shipped chunk
        lease = lease_store.acquire(self.leader_name)
        if lease is not None:
            self.epoch = lease.epoch
        else:
            cur = lease_store.current()
            self.epoch = cur.epoch if cur is not None else 0
        if obs.is_enabled():
            obs.set_gauge("replica.leader_epoch", float(self.epoch),
                          group=self.name)
        replication.epoch_source = self.current_epoch
        replication.controller = self

    def current_epoch(self) -> int:
        """The fencing token shippers stamp chunks with right now."""
        return self.epoch

    # -- failure detector inputs -------------------------------------------

    def kill_leader(self) -> None:
        """Declare the current leader dead (test/drill API — the
        in-process stand-in for a crashed ingest node): its renewals
        stop, the pipeline parks, and the lease's honest expiry starts
        the election clock."""
        self._dead.add(self.leader_name)
        self.replication.active = False

    def leader_alive(self) -> bool:
        return self.leader_name not in self._dead

    # -- the tick ----------------------------------------------------------

    def tick(self) -> None:
        """One renew-or-elect pass; every failure is contained and
        retried next tick (an election in progress must never become a
        caller-visible serving error)."""
        try:
            self._tick()
        except Exception as e:
            obs.inc("replica.control.errors", kind=type(e).__name__)

    def _tick(self) -> None:
        now = self._clock()
        cur = self.lease.current()
        if (
            cur is not None
            and cur.holder == self.leader_name
            and self.leader_alive()
            and now < cur.expires_s
        ):
            if cur.expires_s - now <= self.renew_fraction * self.lease.ttl_s:
                renewed = self.lease.renew(self.leader_name, now=now)
                if renewed is not None:
                    self.epoch = renewed.epoch
            return
        if cur is not None and now < cur.expires_s:
            # a live lease governs — even one held by a leader we cannot
            # reach (partition): wait out the ttl, never depose early
            return
        self._elect("expiry" if cur is not None else "bootstrap", now)

    def _cursor(self, f: Follower):
        p = f.position
        return (p.generation, p.applied_records, p.segment, p.offset)

    def _elect(self, reason: str, now: float) -> None:
        candidates = [
            (self._cursor(f), j)
            for j, f in enumerate(self.replication.followers)
            if f.name not in self._dead
        ]
        if not candidates:
            return  # nobody left to promote; keep ticking
        _, j = max(candidates)
        winner = self.replication.followers[j]
        # the coordinator-dies-mid-election seam: fires BEFORE the CAS,
        # so a retried election re-runs the whole decision — the CAS
        # (not this code path) is what makes double-promotion impossible
        faults.fire("election.promote", follower=winner.name, reason=reason)
        lease = self.lease.acquire(winner.name, now=now)
        if lease is None:
            return  # lost the CAS (or a live lease appeared); retry later
        self._promote(j, lease.epoch)
        self.leader_name = winner.name
        self.epoch = lease.epoch
        self.elections += 1
        obs.inc("replica.elections", reason=reason)
        if obs.is_enabled():
            obs.set_gauge("replica.leader_epoch", float(lease.epoch),
                          group=self.name)
        recorder.note_election(self.name, lease.epoch, winner.name, reason)

    def _follower_for(self, leader: MutableIndex, directory: str, name: str) -> Follower:
        f = Follower(
            leader.directory, directory,
            algo=leader.algo, dim=leader.dim,
            index_params=leader.index_params,
            search_params=leader.search_params,
            metric=leader.metric, name=name,
            delta_mode=leader.delta_mode,
        )
        f.fence(self.epoch)
        return f

    def _promote(self, j: int, epoch: int) -> None:
        """Winner ``j`` becomes the leader of a new directory-backed
        index seeded from its shipped state; every other slot (and the
        deposed leader's) rebases as a fresh follower, fenced at
        ``epoch``."""
        rep = self.replication
        winner = rep.followers[j]
        new_dir = os.path.join(self.root_dir, f"leader-e{epoch:06d}")
        leader = MutableIndex.open(
            new_dir, winner.algo, winner.dim,
            index_params=winner.index_params,
            search_params=winner.search_params,
            metric=winner.metric, name=winner.name,
            delta_mode=winner.delta_mode,
        )
        ids, vecs = winner.index.live_rows()
        if len(ids):
            leader.upsert(ids, vecs)
        # seal the seed records so the rebased followers catch up on
        # the very next ship, whatever seal_bytes says
        if leader.wal is not None:
            leader.wal.seal()
        self.epoch = epoch  # fence the rebased followers at the new regime
        new_followers: List[Follower] = []
        for f in rep.followers:
            if f is winner:
                continue
            new_followers.append(self._follower_for(
                leader,
                os.path.join(self.root_dir, f"{f.name}-e{epoch:06d}"),
                f.name,
            ))
        # the deposed leader's serving slot rejoins as a follower, so
        # the group's replica count is conserved across the election
        new_followers.append(self._follower_for(
            leader,
            os.path.join(self.root_dir, f"rejoin-e{epoch:06d}"),
            f"{self.leader_name}-rejoined",
        ))
        rep.replace(leader, new_followers)

    # -- autoscaling hooks --------------------------------------------------

    def add_follower(self) -> Follower:
        """Grow the pipeline by one follower of the current leader
        (replica scale-up); the caller registers its in-memory index on
        the new serving engine."""
        self._spawned += 1
        f = self._follower_for(
            self.replication.leader,
            os.path.join(
                self.root_dir,
                f"scale-f{self._spawned:04d}-e{self.epoch:06d}",
            ),
            f"{self.name}-scale{self._spawned}",
        )
        self.replication.add_follower(f)
        return f

    def remove_follower(self) -> Follower:
        """Retire the last follower (replica scale-down, already
        drained by the group)."""
        return self.replication.remove_follower()


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The autoscaler's thresholds and hysteresis.

    Scale **up** when the SLO fast burn rate reaches ``burn_up`` or
    queued rows per replica reach ``queue_up_rows``, sustained for
    ``up_ticks`` consecutive decisions; scale **down** when burn is at
    most ``burn_down`` *and* rows per replica at most
    ``queue_down_rows`` for ``down_ticks``. ``cooldown_s`` spaces
    consecutive scale actions so one incident cannot thrash the fleet.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    burn_up: float = 2.0
    queue_up_rows: int = 64
    burn_down: float = 0.5
    queue_down_rows: int = 4
    up_ticks: int = 2
    down_ticks: int = 4
    cooldown_s: float = 0.0


@lockcheck.guarded_fields
class Autoscaler:
    """Hysteresis state machine advising the replica group's size.

    :meth:`decide` is pure bookkeeping under the ``replica.autoscaler``
    lock (an edge-free leaf — no engine, obs, or fault call is ever
    made while it is held); acting on the advice — spawning, warming,
    draining, retiring — is the group's business, outside this lock.
    """

    def __init__(
        self,
        policy: AutoscalePolicy,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        expects(policy.min_replicas >= 1, "min_replicas must be >= 1")
        expects(policy.max_replicas >= policy.min_replicas,
                "max_replicas must be >= min_replicas")
        self.policy = policy
        self._clock = clock
        # guards the hysteresis counters only (lock_order.toml
        # [[guards]]); edge-free leaf
        self._lock = lockcheck.tracked(threading.Lock(), "replica.autoscaler")
        self._over = 0
        self._under = 0
        self._last_scale_t = -float("inf")

    def decide(
        self,
        *,
        burn: float,
        queue_rows: int,
        n_replicas: int,
        now: Optional[float] = None,
    ) -> int:
        """One sizing decision: +1 (grow), -1 (shrink), or 0 (hold)."""
        p = self.policy
        now = self._clock() if now is None else now
        per_replica = float(queue_rows) / max(int(n_replicas), 1)
        hot = burn >= p.burn_up or per_replica >= p.queue_up_rows
        cold = burn <= p.burn_down and per_replica <= p.queue_down_rows
        with self._lock:
            self._over = self._over + 1 if hot else 0
            self._under = self._under + 1 if cold else 0
            if now - self._last_scale_t < p.cooldown_s:
                return 0
            if self._over >= p.up_ticks and n_replicas < p.max_replicas:
                self._over = 0
                self._under = 0
                self._last_scale_t = now
                return 1
            if self._under >= p.down_ticks and n_replicas > p.min_replicas:
                self._over = 0
                self._under = 0
                self._last_scale_t = now
                return -1
        return 0
