"""Health-aware admission routing over a replica set.

The router is the per-request decision the :class:`~raft_tpu.replica.
group.ReplicaGroup` delegates to: given the instantaneous queue depths
of N replicas, pick the one to admit a request on. Three filters, then
a tie-break:

* **breaker** — each replica carries a :class:`~raft_tpu.robust.retry.
  CircuitBreaker` (the PR-4 per-shard health probe generalized to a
  stateful per-replica machine: closed → open on consecutive dispatch
  failures/timeouts → half-open probe). Only CLOSED replicas take new
  admissions; OPEN/HALF_OPEN replicas are quarantined until their probe
  (driven by the group's pump, not by caller traffic) closes them.
* **staleness floor** — a follower replica lagging the leader by more
  than ``max_staleness_records`` WAL records is excluded, so the
  bounded-staleness read contract (``docs/replication.md``) is enforced
  at admission, not discovered by the caller.
* **exclusion** — failover re-submission excludes the replica the
  request just failed on, closing the race window before the breaker
  has tripped.

Among the survivors, **least queue depth** wins (ties go to the lowest
replica id, which keeps routing deterministic under test). The router
holds no engine references — depths are passed in — so it is trivially
unit-testable and imposes no lock ordering on the serving path: its one
lock guards the staleness array only and is an edge-free leaf in
``tools/graft_lint/lock_order.toml``.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Set

from raft_tpu.core.errors import expects
from raft_tpu.robust.retry import CircuitBreaker
from raft_tpu.utils import lockcheck


@lockcheck.guarded_fields
class Router:
    """Least-queue-depth admission over breaker-healthy, fresh-enough
    replicas."""

    def __init__(
        self,
        n_replicas: int,
        *,
        failure_threshold: int = 3,
        reset_timeout_s: float = 0.25,
        max_staleness_records: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        expects(n_replicas >= 1, "need at least one replica, got %d", n_replicas)
        expects(
            max_staleness_records is None or max_staleness_records >= 0,
            "max_staleness_records must be >= 0 when set",
        )
        self.n_replicas = int(n_replicas)
        #: admission floor: a replica further behind the leader than
        #: this many WAL records takes no new requests (None = no floor)
        self.max_staleness_records = max_staleness_records
        self._failure_threshold = int(failure_threshold)
        self._reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._breakers = [
            self._mk_breaker(r) for r in range(self.n_replicas)
        ]
        # guards the staleness array and the draining set only; nothing
        # (locks, obs, faults, engines) is ever called while it is held
        # — an edge-free leaf
        self._lock = lockcheck.tracked(threading.Lock(), "replica.router")
        self._staleness = [0] * self.n_replicas
        self._draining: set = set()

    def _mk_breaker(self, r: int) -> CircuitBreaker:
        return CircuitBreaker(
            f"replica{r}",
            failure_threshold=self._failure_threshold,
            reset_timeout_s=self._reset_timeout_s,
            clock=self._clock,
        )

    # -- dynamic resize (autoscaler) ----------------------------------------

    def add_replica(self) -> int:
        """Grow by one replica (fresh breaker, zero staleness); returns
        its id. Lists are replaced whole so concurrent readers see
        either the old set or the new one, never a half-grown state."""
        rid = self.n_replicas
        self._breakers = self._breakers + [self._mk_breaker(rid)]
        with self._lock:
            self._staleness = self._staleness + [0]
        self.n_replicas = rid + 1
        return rid

    def remove_last(self) -> None:
        """Retire the highest-id replica (the group drained it first)."""
        expects(self.n_replicas >= 2, "cannot retire the last replica")
        rid = self.n_replicas - 1
        self.n_replicas = rid
        self._breakers = self._breakers[:-1]
        with self._lock:
            self._staleness = self._staleness[:-1]
            self._draining.discard(rid)

    def set_draining(self, replica: int, draining: bool = True) -> None:
        """Mark a replica draining: it finishes in-flight work but
        admits nothing new (the scale-down prelude)."""
        with self._lock:
            if draining:
                self._draining.add(int(replica))
            else:
                self._draining.discard(int(replica))

    def draining(self, replica: int) -> bool:
        with self._lock:
            return int(replica) in self._draining

    # -- health inputs -----------------------------------------------------

    def breaker(self, replica: int) -> CircuitBreaker:
        return self._breakers[replica]

    def set_staleness(self, replica: int, records: int) -> None:
        """Publish replica lag (WAL records behind the leader; the
        leader itself stays 0). Fed by the replication maintenance
        tick; an id beyond the current size (resize in flight) is
        dropped — the next tick republishes."""
        with self._lock:
            if replica < len(self._staleness):
                self._staleness[replica] = int(records)

    def staleness(self, replica: int) -> int:
        with self._lock:
            return self._staleness[replica] if replica < len(self._staleness) else 0

    # -- the routing decision ----------------------------------------------

    def admissible(self, replica: int) -> bool:
        """May NEW work be admitted on ``replica`` right now? (The
        half-open probe is the pump's business, not the caller's — see
        :meth:`~raft_tpu.robust.retry.CircuitBreaker.allow`.)"""
        breakers = self._breakers
        if replica >= len(breakers):
            return False  # resize in flight: not admissible until grown
        if breakers[replica].state != CircuitBreaker.CLOSED:
            return False
        with self._lock:
            if replica in self._draining:
                return False
            lag = self._staleness[replica]
        if self.max_staleness_records is None:
            return True
        return lag <= self.max_staleness_records

    def pick(self, depths: Sequence[int], exclude: Set[int] = frozenset()) -> Optional[int]:
        """The replica to admit one request on: least ``depths`` entry
        among admissible replicas not in ``exclude`` (lowest id breaks
        ties); ``None`` when no replica qualifies. ``depths`` may
        briefly disagree with ``n_replicas`` while the autoscaler is
        resizing — only the common prefix is considered."""
        best: Optional[int] = None
        best_depth = 0
        for r in range(min(self.n_replicas, len(depths))):
            if r in exclude or not self.admissible(r):
                continue
            d = int(depths[r])
            if best is None or d < best_depth:
                best, best_depth = r, d
        return best

    def states(self) -> List[str]:
        """Per-replica breaker state, for ``health()`` snapshots."""
        return [b.state for b in self._breakers]
