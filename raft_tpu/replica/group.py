"""Replicated serving: N engine-backed copies behind one futures API.

:class:`ReplicaGroup` owns N :class:`~raft_tpu.serve.engine.
ServingEngine` s, each holding its own copy of every registered index,
and presents the *same* submit/step/run_until_idle surface as a single
engine — callers cannot tell (and should not care) how many replicas
answer them. What the group adds on top:

* **health-routed admission** — every submit consults the
  :class:`~raft_tpu.replica.router.Router`: least-queue-depth replica
  among those whose :class:`~raft_tpu.robust.retry.CircuitBreaker` is
  closed and whose staleness is within the admission floor. A replica
  that keeps failing its pump is quarantined (breaker opens) and takes
  no new work until its half-open probe succeeds.
* **failover that re-queues** — a replica that dies mid-batch (pump
  raises through the ``replica.dispatch`` fault seam, or exceeds
  ``dispatch_timeout_s``) has its queue evacuated and every in-flight
  request **re-submitted on a healthy replica** under the same trace
  ID. The caller's future completes with a normal result; the only
  caller-visible artifact of a replica death is latency (and the
  ``serve.failovers`` counter). Requests that cannot immediately be
  placed are *parked* and retried every step — never errored, never
  dropped.
* **bounded-staleness follower serving** — mutable registrations ride
  :class:`~raft_tpu.replica.shipping.Replication` (leader WAL seal →
  ship → follower replay); the group's maintenance tick drives the
  seal/ship cycle and feeds each follower's record lag into the router
  so reads never land on a replica further behind than
  ``max_staleness_records``.

Drive modes: the default is the repo's synchronous discipline —
:meth:`step` pumps every replica on the caller's thread, so tests are
deterministic. :meth:`start` switches to one pump thread per replica
(what the ``replicated`` bench phase uses to demonstrate >1x scaling);
:meth:`stop` returns to synchronous mode.

Lock discipline: ``replica.group`` guards only the in-flight and
parked bookkeeping lists. It is an **edge-free leaf** in
``tools/graft_lint/lock_order.toml`` — no engine, obs, faults, or other
tracked-lock call ever happens while it is held; every method snapshots
under the lock and acts outside it.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

import numpy as np

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.replica.router import Router
from raft_tpu.robust import faults
from raft_tpu.serve.batcher import DeadlineExceeded, QueueFull, ServeFuture
from raft_tpu.serve.engine import ServingEngine
from raft_tpu.utils import lockcheck


@dataclasses.dataclass
class _Flight:
    """One caller request the group is responsible for: the caller's
    future (``gfut``), the engine-level future of its current placement
    (``efut``), and everything needed to re-submit it elsewhere."""

    gfut: ServeFuture
    efut: Optional[ServeFuture]
    replica: int
    index_id: str
    queries: np.ndarray
    k: int
    #: absolute deadline on the group clock (None = no deadline) — kept
    #: absolute so failover re-submission shrinks, never resets, it
    deadline_s: Optional[float]
    trace_id: str
    attempts: int = 1


@lockcheck.guarded_fields
class ReplicaGroup:
    """N replicas of a serving engine behind health-aware routing and
    re-queueing failover.

    >>> group = ReplicaGroup(n_replicas=2)
    >>> group.register("wiki", "cagra", index)   # shared immutable copy
    >>> fut = group.submit("wiki", rows, k=10)
    >>> group.run_until_idle()
    >>> res = fut.result()
    """

    def __init__(
        self,
        engines: Optional[Sequence[ServingEngine]] = None,
        *,
        n_replicas: int = 2,
        engine_factory: Optional[Callable[[int], ServingEngine]] = None,
        failure_threshold: int = 3,
        reset_timeout_s: float = 0.25,
        dispatch_timeout_s: Optional[float] = None,
        max_staleness_records: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
        name: str = "replicas",
        maintenance_interval_ms: float = 10.0,
    ):
        self._engine_factory = engine_factory or (
            lambda r: ServingEngine(clock=clock)
        )
        if engines is not None:
            self.engines: List[ServingEngine] = list(engines)
        else:
            self.engines = [self._engine_factory(r) for r in range(int(n_replicas))]
        expects(len(self.engines) >= 1, "a replica group needs >= 1 engine")
        self.name = str(name)
        self.n_replicas = len(self.engines)
        self._clock = clock if clock is not None else time.monotonic
        #: a pump (one engine.step) slower than this declares the
        #: replica failed even though it returned — the slow-replica
        #: analog of the engine's slow-shard policy (None = no bound)
        self.dispatch_timeout_s = dispatch_timeout_s
        self.router = Router(
            self.n_replicas,
            failure_threshold=failure_threshold,
            reset_timeout_s=reset_timeout_s,
            max_staleness_records=max_staleness_records,
            clock=self._clock,
        )
        self.maintenance_interval_ms = float(maintenance_interval_ms)
        self._last_maint = -float("inf")
        #: mutable replication pipelines by index_id (leader on replica
        #: 0, follower j on replica j+1) — see register_mutable_replicated
        self._replications: Dict[str, object] = {}
        # guards _flights/_parked ONLY; everything else (engines, obs,
        # faults, router breakers) is called with it released
        self._lock = lockcheck.tracked(threading.RLock(), "replica.group")
        self._flights: List[_Flight] = []
        self._parked: List[_Flight] = []
        #: how to rebuild each registration on a freshly provisioned
        #: replica (autoscale-up) or after a control-plane promotion
        #: swapped the serving handles — ("immutable", (algo, index,
        #: kwargs)) or ("replicated", kwargs), plus declared SLOs
        self._registrations: Dict[str, tuple] = {}
        self._slo_kwargs: Dict[str, dict] = {}
        # autoscaler state: owned by the maintenance driver (thread 0 in
        # threaded mode, the stepping thread otherwise) — single-owner,
        # like _threads
        self._autoscaler = None
        self._warm_k: Dict[str, int] = {}
        self._draining_rid: Optional[int] = None
        self._pump_interval_s = 0.0005
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # -- registration ------------------------------------------------------

    def register(self, index_id: str, algo: str, indexes, **kwargs) -> None:
        """Register an immutable index on every replica.

        ``indexes`` is either one index object (shared — immutable
        structures are safe to serve from N engines at once) or a
        sequence of ``n_replicas`` per-replica copies. ``kwargs`` pass
        through to each engine's :meth:`~raft_tpu.serve.engine.
        ServingEngine.register` unchanged."""
        per_replica = (
            list(indexes)
            if isinstance(indexes, (list, tuple))
            else [indexes] * self.n_replicas
        )
        expects(
            len(per_replica) == self.n_replicas,
            "need one index per replica (%d), got %d",
            self.n_replicas, len(per_replica),
        )
        for eng, idx in zip(self.engines, per_replica):
            eng.register(index_id, algo, idx, **kwargs)
        with self._lock:
            # immutable structures are safe to share: a scaled-up
            # replica re-registers the first copy
            self._registrations[index_id] = (
                "immutable", (algo, per_replica[0], dict(kwargs))
            )

    def register_mutable_replicated(self, index_id: str, replication, **kwargs) -> None:
        """Register a WAL-shipped mutable replication pipeline: the
        leader :class:`~raft_tpu.mutable.MutableIndex` serves from
        replica 0 and each :class:`~raft_tpu.replica.shipping.Follower`
        from the next replica. The group's maintenance tick drives
        ``replication.tick()`` (seal → ship → replay) and publishes each
        follower's record lag to the router, closing the
        bounded-staleness loop. Requires ``1 + len(followers) ==
        n_replicas``."""
        handles = replication.indexes()
        expects(
            len(handles) == self.n_replicas,
            "replication carries %d indexes (leader + followers) but the "
            "group has %d replicas",
            len(handles), self.n_replicas,
        )
        for eng, idx in zip(self.engines, handles):
            eng.register_mutable(index_id, idx, **kwargs)
        with self._lock:
            self._replications[index_id] = replication
            self._registrations[index_id] = ("replicated", dict(kwargs))

    def registered(self) -> List[str]:
        return self.engines[0].registered()

    # -- submission --------------------------------------------------------

    def submit(
        self,
        index_id: str,
        queries,
        k: int,
        deadline_ms: Optional[float] = None,
    ) -> ServeFuture:
        """Enqueue one request on the best replica and return a
        group-level future. Admission walks replicas in router order —
        a replica rejecting with :class:`QueueFull` (its queue, not the
        group's) falls through to the next; only when *every* admissible
        replica rejects does the caller see the typed rejection."""
        q = np.asarray(queries)
        if q.ndim == 1:
            q = q[None, :]
        now = self._clock()
        deadline_s = now + deadline_ms / 1e3 if deadline_ms is not None else None
        trace_id = obs.new_trace_id() if obs.is_enabled() else ""
        fl = _Flight(
            gfut=ServeFuture(),
            efut=None,
            replica=-1,
            index_id=index_id,
            queries=q,
            k=int(k),
            deadline_s=deadline_s,
            trace_id=trace_id,
        )
        placed, last_exc = self._place(fl, exclude=set())
        if not placed:
            raise last_exc if last_exc is not None else QueueFull(
                f"no admissible replica for {index_id!r} "
                f"({self.n_replicas} replicas, all open/stale)"
            )
        with self._lock:
            self._flights.append(fl)
        return fl.gfut

    def _place(self, fl: _Flight, exclude: Set[int]):
        """Try to land ``fl`` on an admissible replica; mutates
        ``fl.replica``/``fl.efut`` on success. Returns ``(placed,
        last_typed_rejection)``."""
        tried = set(exclude)
        last_exc: Optional[BaseException] = None
        while True:
            depths = [eng.queue_depth() for eng in self.engines]
            rid = self.router.pick(depths, exclude=tried)
            if rid is None:
                return False, last_exc
            now = self._clock()
            remaining_ms: Optional[float] = None
            if fl.deadline_s is not None:
                remaining_ms = max((fl.deadline_s - now) * 1e3, 0.0)
            try:
                # _Flight is single-owner: exactly one thread holds it at a
                # time (submitter until placed, then whichever pump harvests
                # it), with ownership handed off through _flights under
                # self._lock — its fields never need their own guard
                fl.efut = self.engines[rid].submit(  # graft-lint: ignore[guard-inference]
                    fl.index_id, fl.queries, fl.k,
                    deadline_ms=remaining_ms,
                    trace_id=fl.trace_id or None,
                )
            except (QueueFull, DeadlineExceeded) as e:
                last_exc = e
                tried.add(rid)
                continue
            fl.replica = rid  # graft-lint: ignore[guard-inference] — single-owner handoff, see above
            return True, None

    # -- the loop drivers --------------------------------------------------

    def step(self, force: bool = False) -> int:
        """Pump every replica once on the calling thread (a no-op
        returning 0 while :meth:`start` ed pump threads own the
        engines), retry parked failovers, and run rate-limited
        maintenance. Returns caller futures completed."""
        if self._threads:
            return 0
        done = 0
        for rid in range(self.n_replicas):
            done += self._pump_replica(rid, force)
        done += self._retry_parked()
        if self._maint_due():
            self.maintenance_tick()
        return done

    def _maint_due(self) -> bool:
        """Rate-limit gate for maintenance: check-and-advance
        ``_last_maint`` atomically so concurrent drivers can't both fire
        the same interval (the tick itself runs outside the lock)."""
        now = self._clock()
        with self._lock:
            if now - self._last_maint >= self.maintenance_interval_ms / 1e3:
                self._last_maint = now
                return True
        return False

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive :meth:`step` until no flight, parked request, or queued
        row remains; returns caller futures completed. With pump threads
        running this just waits for quiescence."""
        total = 0
        for _ in range(max_steps):
            if not self._busy():
                break
            if self._threads:
                time.sleep(0.0005)
            else:
                total += self.step(force=True)
        return total

    def _busy(self) -> bool:
        with self._lock:
            pending = bool(self._flights or self._parked)
        return pending or any(eng.queue_depth() for eng in self.engines)

    def queue_depth(self) -> int:
        """Queued query rows across all replicas plus parked failovers."""
        with self._lock:
            parked_rows = sum(int(fl.queries.shape[0]) for fl in self._parked)
        return sum(eng.queue_depth() for eng in self.engines) + parked_rows

    # -- the per-replica pump ----------------------------------------------

    def _pump_replica(self, rid: int, force: bool) -> int:
        """One ``engine.step`` for replica ``rid``, wrapped in the
        failure machinery: the ``replica.dispatch`` chaos seam fires
        first (a replica kill is a fault installed here), a raise or a
        too-slow pump fails the replica (breaker + evacuate + failover),
        and a clean pump harvests completed engine futures into the
        caller-facing ones."""
        breaker = self.router.breaker(rid)
        if breaker.state != breaker.CLOSED and not breaker.allow():
            return 0  # quarantined, and no probe is due yet
        err: Optional[BaseException] = None
        t0 = time.perf_counter()
        try:
            faults.fire("replica.dispatch", replica=rid, group=self.name)
            self.engines[rid].step(force=force)
        except Exception as e:
            err = e
        slow = (
            err is None
            and self.dispatch_timeout_s is not None
            and time.perf_counter() - t0 > self.dispatch_timeout_s
        )
        if err is not None or slow:
            self._fail_replica(rid, err, slow)
            return 0
        done = self._harvest(rid)
        breaker.record_success()
        return done

    def _harvest(self, rid: int) -> int:
        """Move completed engine futures on ``rid`` into their caller
        futures; dispatch failures become failovers."""
        with self._lock:
            mine = [fl for fl in self._flights if fl.replica == rid]
        done = 0
        failed: List[_Flight] = []
        for fl in mine:
            if fl.efut is None or not fl.efut.done():
                continue
            with self._lock:
                if fl in self._flights:
                    self._flights.remove(fl)
            exc = fl.efut.exception(timeout=0)
            if exc is None:
                fl.gfut.set_result(fl.efut.result(timeout=0))
                done += 1
            elif isinstance(exc, (QueueFull, DeadlineExceeded)):
                # the engine's own typed verdict (deadline expired in
                # queue) is the caller's verdict — failover can't help
                fl.gfut.set_exception(exc)
                done += 1
            else:
                failed.append(fl)
        if failed:
            self.router.breaker(rid).record_failure()
            for fl in failed:
                self._failover(fl)
        return done

    def _fail_replica(self, rid: int, err: Optional[BaseException], slow: bool) -> None:
        """Declare replica ``rid`` failed: trip its breaker one notch,
        evacuate its queue, and fail over every flight it held. Callers
        see none of this — their futures re-queue elsewhere."""
        kind = "slow" if slow else type(err).__name__
        obs.inc("replica.pump_failures", replica=str(rid), kind=kind)
        self.router.breaker(rid).record_failure()
        # abandon the engine-level futures: the flights below re-submit
        # on a healthy replica and complete their caller futures there
        self.engines[rid].evict_queued()
        with self._lock:
            mine = [fl for fl in self._flights if fl.replica == rid]
            for fl in mine:
                self._flights.remove(fl)
        for fl in mine:
            # a batch the engine completed before the pump died still
            # counts — deliver it rather than recompute
            if fl.efut is not None and fl.efut.done():
                exc = fl.efut.exception(timeout=0)
                if exc is None:
                    fl.gfut.set_result(fl.efut.result(timeout=0))
                    continue
                if isinstance(exc, (QueueFull, DeadlineExceeded)):
                    fl.gfut.set_exception(exc)
                    continue
            self._failover(fl)

    def _failover(self, fl: _Flight) -> None:
        """Re-queue one flight on a healthy replica (excluding the one
        it just failed on), parking it for retry when nowhere is
        admissible right now. The request's trace ID rides along, so
        the obs timeline shows one request crossing replicas."""
        obs.inc("serve.failovers", index_id=fl.index_id, replica=str(fl.replica))
        if fl.trace_id and obs.is_enabled():
            with obs.trace_scope((fl.trace_id,)):
                with obs.span(
                    "replica.failover",
                    index_id=fl.index_id, from_replica=fl.replica,
                    attempt=fl.attempts,
                ):
                    pass
        now = self._clock()
        if fl.deadline_s is not None and now > fl.deadline_s:
            fl.gfut.set_exception(DeadlineExceeded(
                f"request deadline expired during failover off replica "
                f"{fl.replica} (attempt {fl.attempts})"
            ))
            return
        failed_on = fl.replica
        fl.attempts += 1  # graft-lint: ignore[guard-inference] — single-owner handoff, see _place
        placed, _ = self._place(fl, exclude={failed_on})
        if placed:
            with self._lock:
                self._flights.append(fl)
        else:
            # nowhere to go *right now* (breakers open / queues full):
            # park — _retry_parked re-offers it every step until a
            # replica recovers or its deadline truly expires
            with self._lock:
                self._parked.append(fl)

    def _retry_parked(self) -> int:
        """Re-offer every parked flight; expired deadlines become typed
        rejections, the rest either land or park again."""
        with self._lock:
            if not self._parked:
                return 0
            parked, self._parked = self._parked, []
        done = 0
        for fl in parked:
            now = self._clock()
            if fl.deadline_s is not None and now > fl.deadline_s:
                fl.gfut.set_exception(DeadlineExceeded(
                    f"request deadline expired while parked for failover "
                    f"(attempt {fl.attempts})"
                ))
                done += 1
                continue
            placed, _ = self._place(fl, exclude=set())
            if placed:
                with self._lock:
                    self._flights.append(fl)
            else:
                with self._lock:
                    self._parked.append(fl)
        return done

    # -- maintenance, replication, health ----------------------------------

    def maintenance_tick(self) -> None:
        """Drive every replication pipeline one cycle (leader seal →
        ship sealed frames → follower replay — and, when a control
        plane is attached, its renew-or-elect pass), re-register
        engines when a promotion swapped the serving handles, publish
        follower lag to the router's admission floor, and run one
        autoscaler decision."""
        with self._lock:
            replications = list(self._replications.items())
        for index_id, replication in replications:
            replication.tick()
            take = getattr(replication, "take_handles_changed", None)
            if take is not None and take():
                self._reregister(index_id, replication)
            for j in range(len(replication.followers)):
                self.router.set_staleness(j + 1, replication.staleness(j))
        self._autoscale_step()

    def _reregister(self, index_id: str, replication) -> None:
        """A control-plane promotion (or resize) swapped the
        replication's serving handles: point every engine at the new
        ones. Same-length zip by construction — promotions conserve the
        replica count; a mid-resize mismatch self-heals next tick."""
        with self._lock:
            reg = self._registrations.get(index_id)
        kwargs = reg[1] if reg is not None and reg[0] == "replicated" else {}
        for eng, idx in zip(list(self.engines), replication.indexes()):
            eng.register_mutable(index_id, idx, **kwargs)

    # -- SLO-driven autoscaling --------------------------------------------

    def enable_autoscaler(
        self,
        policy,
        *,
        warm_k: Optional[Dict[str, int]] = None,
        autoscaler=None,
    ) -> None:
        """Arm SLO-driven fleet sizing: every maintenance tick feeds the
        worst fast-window burn rate (across replica 0's SLOs) and the
        group-wide queue depth into an :class:`~raft_tpu.replica.
        control.Autoscaler`, and acts on its advice — grow with a
        warmed-up replica, or drain-then-retire the highest one.

        ``policy`` is an :class:`~raft_tpu.replica.control.
        AutoscalePolicy` (ignored when a prebuilt ``autoscaler`` is
        passed). ``warm_k`` maps index ids to the ``k`` each new
        replica precompiles (:meth:`ServingEngine.warmup` →
        ``ProgramCache.warmup``) *before* it takes traffic."""
        if autoscaler is None:
            from raft_tpu.replica.control import Autoscaler

            autoscaler = Autoscaler(policy, clock=self._clock)
        self._warm_k = dict(warm_k or {})
        self._autoscaler = autoscaler

    def _autoscale_step(self) -> None:
        """One sizing decision per maintenance tick. A drain in
        progress preempts new decisions — the fleet finishes one
        resize before considering the next."""
        a = self._autoscaler
        if a is None:
            return
        if self._draining_rid is not None:
            self._drain_step()
            return
        eng0 = self.engines[0]
        burn = 0.0
        for iid in eng0.registered():
            b = eng0.slo_burn(iid)
            if b is not None:
                burn = max(burn, b)
        decision = a.decide(
            burn=burn, queue_rows=self.queue_depth(),
            n_replicas=self.n_replicas, now=self._clock(),
        )
        if decision > 0:
            self._scale_up()
        elif decision < 0 and self.n_replicas >= 2:
            self._begin_drain()

    def _provision_engine(self, rid: int):
        """Build a fresh engine carrying every current registration
        (replicated ones grow their pipeline by one follower via the
        control plane). Returns None when any registration cannot be
        reproduced — a partially registered replica must never join
        the routable set."""
        eng = self._engine_factory(rid)
        with self._lock:
            regs = dict(self._registrations)
            replications = dict(self._replications)
            slos = {k: dict(v) for k, v in self._slo_kwargs.items()}
        for index_id, (kind, payload) in regs.items():
            if kind == "replicated":
                replication = replications.get(index_id)
                controller = getattr(replication, "controller", None)
                if controller is None:
                    return None  # no control plane: cannot mint a follower
                follower = controller.add_follower()
                eng.register_mutable(index_id, follower.index, **payload)
                # consumed: this path registered the new handle itself
                replication.take_handles_changed()
            else:
                algo, idx, kwargs = payload
                eng.register(index_id, algo, idx, **kwargs)
        for index_id, kwargs in slos.items():
            eng.set_slo(index_id, **kwargs)
        return eng

    def _scale_up(self) -> None:
        rid = self.n_replicas
        eng = self._provision_engine(rid)
        if eng is None:
            return
        # warm BEFORE the replica is routable: precompile each declared
        # (index, k) so the first real request never pays an XLA compile
        for index_id, k in self._warm_k.items():
            try:
                eng.warmup(index_id, int(k), run=True)
            except Exception as e:
                obs.inc("replica.control.errors", kind=type(e).__name__)
        # publish the new follower's true lag before admission opens, so
        # the staleness floor keeps reads off it until it catches up
        lag = 0
        with self._lock:
            replications = list(self._replications.values())
        for replication in replications:
            n_f = len(replication.followers)
            if n_f:
                lag = max(lag, replication.staleness(n_f - 1))
        self.engines = self.engines + [eng]
        self.router.add_replica()
        self.router.set_staleness(rid, lag)
        self.n_replicas = len(self.engines)
        if self._threads:
            t = threading.Thread(
                target=self._pump_loop, args=(rid, self._pump_interval_s),
                name=f"{self.name}-pump{rid}", daemon=True,
            )
            self._threads.append(t)
            t.start()
        obs.inc("serve.autoscale", direction="up")
        obs.recorder.note_scale(self.name, "up", self.n_replicas)

    def _begin_drain(self) -> None:
        """Start retiring the highest replica: stop admitting onto it,
        keep pumping until its queue and flights empty (never replica 0
        — it serves every replication's leader)."""
        rid = self.n_replicas - 1
        if rid == 0:
            return
        self._draining_rid = rid
        self.router.set_draining(rid, True)

    def _drain_step(self) -> None:
        rid = self._draining_rid
        if rid is None:
            return
        with self._lock:
            busy = any(fl.replica == rid for fl in self._flights) or any(
                fl.replica == rid for fl in self._parked
            )
        if busy or self.engines[rid].queue_depth() > 0:
            return  # in-flight work still draining; decide again next tick
        self._retire(rid)

    def _retire(self, rid: int) -> None:
        eng = self.engines[rid]
        with self._lock:
            replications = list(self._replications.values())
        for replication in replications:
            controller = getattr(replication, "controller", None)
            if controller is not None and len(replication.followers) >= 2:
                controller.remove_follower()
                replication.take_handles_changed()  # handles only shrank
        # shrink the routable set first so the retiring pump thread
        # (which exits once rid >= n_replicas) can be joined
        self.n_replicas = rid
        if self._threads:
            t = self._threads.pop()
            t.join(timeout=5.0)
        self.engines = self.engines[:-1]
        self.router.remove_last()
        eng.shutdown(wait=True)
        self._draining_rid = None
        obs.inc("serve.autoscale", direction="down")
        obs.recorder.note_scale(self.name, "down", self.n_replicas)

    def health(self) -> Dict[str, object]:
        """Group health: per-replica breaker/queue/staleness plus the
        in-flight and parked counts. Each replica's full engine health
        snapshot rides under ``engine``; ``cluster`` is the aggregated
        one-line snapshot (worst breaker, max staleness, summed queue
        depth) dashboards and flight-recorder bundles consume."""
        with self._lock:
            in_flight = len(self._flights)
            parked = len(self._parked)
        states = self.router.states()
        replicas = []
        # snapshot the engine list and clip to the router's view so a
        # concurrent autoscale resize can't index past either side
        engines = list(self.engines)[: len(states)]
        for rid, eng in enumerate(engines):
            breaker = self.router.breaker(rid)
            replicas.append({
                "breaker": states[rid],
                "consecutive_failures": breaker.failures,
                "queue_rows": eng.queue_depth(),
                "staleness_records": self.router.staleness(rid),
                "draining": self.router.draining(rid),
                "engine": eng.health(),
            })
        severity = {"closed": 0, "half_open": 1, "open": 2}
        cluster = {
            "replicas": len(replicas),
            "worst_breaker": (
                max(states, key=lambda s: severity.get(s, 0))
                if states else "closed"
            ),
            "open_breakers": sum(1 for s in states if s == "open"),
            "max_staleness_records": max(
                (r["staleness_records"] for r in replicas), default=0
            ),
            "queue_rows": sum(r["queue_rows"] for r in replicas),
            "in_flight": in_flight,
            "parked": parked,
        }
        return {
            "name": self.name,
            "replicas": replicas,
            "cluster": cluster,
            "in_flight": in_flight,
            "parked": parked,
            "threaded": bool(self._threads),
        }

    def warmup(self, index_id: str, k: int, run: bool = True):
        """Precompile on every replica (deploy-time warmup)."""
        return [eng.warmup(index_id, k, run=run) for eng in self.engines]

    def set_slo(self, index_id: str, **kwargs):
        """Declare the same SLO on every replica; returns the trackers.
        Remembered, so an autoscaled replica gets the same objective."""
        with self._lock:
            self._slo_kwargs[index_id] = dict(kwargs)
        return [eng.set_slo(index_id, **kwargs) for eng in self.engines]

    def shutdown(self, wait: bool = True) -> None:
        self.stop()
        for eng in self.engines:
            eng.shutdown(wait=wait)

    # -- threaded pump mode ------------------------------------------------

    def start(self, interval_s: float = 0.0005) -> None:
        """Switch to one daemon pump thread per replica (true replica
        parallelism — what the ``replicated`` bench phase measures).
        Thread 0 additionally retries parked failovers and drives
        maintenance. :meth:`step` returns 0 while threads run."""
        if self._threads:
            return
        self._stop.clear()
        self._pump_interval_s = float(interval_s)
        for rid in range(self.n_replicas):
            t = threading.Thread(
                target=self._pump_loop, args=(rid, float(interval_s)),
                name=f"{self.name}-pump{rid}", daemon=True,
            )
            self._threads.append(t)
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        """Stop pump threads and return to synchronous :meth:`step`."""
        if not self._threads:
            return
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    def _pump_loop(self, rid: int, interval_s: float) -> None:
        # the loop also exits when its replica is retired (autoscale
        # scale-down shrinks n_replicas, then joins this thread)
        while not self._stop.is_set() and rid < self.n_replicas:
            try:
                self._pump_replica(rid, force=True)
                if rid == 0:
                    self._retry_parked()
                    if self._maint_due():
                        self.maintenance_tick()
            except Exception as e:
                # a pump loop must never die silently: count and keep
                # pumping — the breaker machinery handles the failure
                obs.inc("replica.pump_failures", replica=str(rid),
                        kind=type(e).__name__)
            if interval_s > 0.0:
                time.sleep(interval_s)
