"""Replicated serving: health-routed replica groups with WAL shipping.

The pieces (see ``docs/replication.md`` for the full story):

* :class:`~raft_tpu.replica.group.ReplicaGroup` — N engine-backed
  copies of every registered index behind the single-engine futures
  API, with circuit-breaker health routing and failover that
  **re-queues** in-flight work instead of erroring it;
* :class:`~raft_tpu.replica.router.Router` — least-queue-depth
  admission over breaker-closed, staleness-bounded, non-draining
  replicas;
* :mod:`~raft_tpu.replica.shipping` — leader WAL seal → CRC-verified
  segment shipping → follower replay, with bounded-staleness
  accounting and per-hop fencing tokens (:class:`Replication`,
  :class:`Shipper`, :class:`Follower`, :class:`ShipRejected`,
  :class:`FencedError`);
* :mod:`~raft_tpu.replica.control` — the control plane: file-CAS
  lease with epoch counter (:class:`LeaseStore`), highest-cursor
  leader election with fenced promotion (:class:`ControlPlane`), and
  SLO-driven fleet sizing (:class:`Autoscaler`,
  :class:`AutoscalePolicy`);
* :mod:`~raft_tpu.replica.transport` — the real wire: a length-framed
  TCP segment server plus the retrying, breaker-gated transport
  callable (:class:`SegmentServer`, :class:`SocketTransport`,
  :class:`TransportError`).
"""
from raft_tpu.replica.control import (
    Autoscaler,
    AutoscalePolicy,
    ControlPlane,
    Lease,
    LeaseStore,
)
from raft_tpu.replica.group import ReplicaGroup
from raft_tpu.replica.router import Router
from raft_tpu.replica.shipping import (
    DEFAULT_CHUNK_BYTES,
    FencedError,
    Follower,
    FollowerPosition,
    Replication,
    Shipper,
    ShipRejected,
)
from raft_tpu.replica.transport import SegmentServer, SocketTransport, TransportError

__all__ = [
    "DEFAULT_CHUNK_BYTES",
    "Autoscaler",
    "AutoscalePolicy",
    "ControlPlane",
    "FencedError",
    "Follower",
    "FollowerPosition",
    "Lease",
    "LeaseStore",
    "ReplicaGroup",
    "Replication",
    "Router",
    "SegmentServer",
    "ShipRejected",
    "Shipper",
    "SocketTransport",
    "TransportError",
]
