"""Replicated serving: health-routed replica groups with WAL shipping.

The pieces (see ``docs/replication.md`` for the full story):

* :class:`~raft_tpu.replica.group.ReplicaGroup` — N engine-backed
  copies of every registered index behind the single-engine futures
  API, with circuit-breaker health routing and failover that
  **re-queues** in-flight work instead of erroring it;
* :class:`~raft_tpu.replica.router.Router` — least-queue-depth
  admission over breaker-closed, staleness-bounded replicas;
* :mod:`~raft_tpu.replica.shipping` — leader WAL seal → CRC-verified
  segment shipping → follower replay, with bounded-staleness
  accounting (:class:`Replication`, :class:`Shipper`,
  :class:`Follower`, :class:`ShipRejected`).
"""
from raft_tpu.replica.group import ReplicaGroup
from raft_tpu.replica.router import Router
from raft_tpu.replica.shipping import (
    DEFAULT_CHUNK_BYTES,
    Follower,
    FollowerPosition,
    Replication,
    Shipper,
    ShipRejected,
)

__all__ = [
    "DEFAULT_CHUNK_BYTES",
    "Follower",
    "FollowerPosition",
    "ReplicaGroup",
    "Replication",
    "Router",
    "ShipRejected",
    "Shipper",
]
