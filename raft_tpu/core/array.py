"""Array ingestion and validation helpers.

The reference's ``mdspan``/``mdarray`` machinery (``core/mdarray.hpp``,
``core/host_device_accessor.hpp``) exists to give C++ a shape/layout-checked,
memory-space-aware view type; in JAX that role is played by ``jax.Array``
itself. What remains is the *ingestion* contract from pylibraft
(``cai_wrapper`` accepting any ``__cuda_array_interface__`` object): here any
``__array__``/dlpack-capable object — numpy, JAX, torch(cpu) — is accepted
and validated. ``memory_type_dispatcher`` (host-vs-device routing,
``util/memory_type_dispatcher.cuh:48-118``) reduces to ``jax.device_put``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.errors import expects


def as_array(x, dtype=None, ndim: Optional[int] = None, name: str = "array") -> jax.Array:
    """Convert ``x`` (numpy / jax / torch / dlpack / buffer) to a jax.Array.

    Validation analog of the pylibraft wrappers' dtype/shape checks
    (``neighbors/ivf_pq/ivf_pq.pyx:359-375``).
    """
    if isinstance(x, jax.Array):
        arr = x
    elif hasattr(x, "__dlpack__") and not isinstance(x, np.ndarray):
        try:
            arr = jnp.from_dlpack(x)
        except Exception:
            arr = jnp.asarray(np.asarray(x))
    else:
        arr = jnp.asarray(x)
    if dtype is not None:
        arr = arr.astype(dtype)
    if ndim is not None:
        expects(arr.ndim == ndim, "%s must be %d-dimensional, got %d", name, ndim, arr.ndim)
    return arr


def check_matching_dims(a: jax.Array, b: jax.Array, axis_a: int, axis_b: int, what: str) -> None:
    expects(
        a.shape[axis_a] == b.shape[axis_b],
        "%s: dimension mismatch (%d vs %d)",
        what,
        a.shape[axis_a],
        b.shape[axis_b],
    )


def check_dtype_one_of(arr: jax.Array, dtypes: Sequence, name: str = "array") -> None:
    expects(
        any(arr.dtype == jnp.dtype(d) for d in dtypes),
        "%s: unsupported dtype %s (expected one of %s)",
        name,
        arr.dtype,
        [jnp.dtype(d).name for d in dtypes],
    )
