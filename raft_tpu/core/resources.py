"""Execution-context container: the TPU-native analog of ``raft::resources``.

The reference threads a ``raft::resources const& handle`` through every API
(``cpp/include/raft/core/resources.hpp:49``): a type-indexed registry holding
the CUDA stream, BLAS handles, workspace allocator and communicator
(``core/resource/resource_types.hpp:29-51``). On TPU/JAX nearly all of those
slots dissolve — XLA owns streams and fusion, and there are no BLAS handles —
but three responsibilities survive and live here:

* device / mesh placement (the COMMUNICATOR / SUB_COMMUNICATOR slots,
  ``core/resource/resource_types.hpp:38-39``, map to `jax.sharding.Mesh` axes),
* a counter-based RNG key stream (the ``rng_state`` the reference passes
  explicitly),
* a workspace byte budget used by batching heuristics (the analog of
  ``workspace_resource_factory::default_workspace_size``,
  ``core/resource/device_memory_resource.hpp:106``).

Like the reference's handle, ``Resources`` is cheap to copy, lazily
initialized, and optional: every public API accepts ``res=None`` and falls
back to a process-global default (mirroring pylibraft's ``auto_sync_handle``).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

import jax
import numpy as np

from raft_tpu.utils import lockcheck


def _default_device() -> jax.Device:
    return jax.devices()[0]


@dataclasses.dataclass
class Resources:
    """Per-call execution context.

    Parameters
    ----------
    device:
        The JAX device new arrays should be placed on. Defaults to
        ``jax.devices()[0]``.
    mesh:
        Optional `jax.sharding.Mesh` for multi-chip execution. Set by
        :func:`raft_tpu.parallel.comms.init_comms`; algorithms fetch it via
        :meth:`get_mesh` (the analog of ``resource::get_comms(handle)``).
    seed:
        Seed for the resource-owned RNG key stream.
    workspace_bytes:
        Byte budget batching heuristics may assume for temporaries. Mirrors
        the reference's limited workspace resource (default there: 1/4 of
        free memory; here: a conservative 1 GiB of HBM).
    """

    device: Optional[jax.Device] = None
    mesh: Optional[jax.sharding.Mesh] = None
    seed: int = 0
    workspace_bytes: int = 1 << 30

    def __post_init__(self):
        if self.device is None:
            self.device = _default_device()
        self._key = jax.random.key(self.seed)
        self._lock = lockcheck.tracked(threading.Lock(), "core.resources")
        self._registry: dict[str, Any] = {}

    # -- RNG key stream ----------------------------------------------------
    def next_key(self, n: Optional[int] = None):
        """Split off fresh PRNG key(s) from the resource-owned stream."""
        with self._lock:
            if n is None:
                self._key, sub = jax.random.split(self._key)
                return sub
            keys = jax.random.split(self._key, n + 1)
            self._key = keys[0]
            return keys[1:]

    # -- mesh / comms ------------------------------------------------------
    def get_mesh(self) -> jax.sharding.Mesh:
        if self.mesh is None:
            raise ValueError(
                "No mesh set on Resources; call raft_tpu.parallel.init_comms() "
                "or pass mesh= explicitly (analog of resource::get_comms on a "
                "handle without a communicator)."
            )
        return self.mesh

    def has_mesh(self) -> bool:
        return self.mesh is not None

    # -- generic registry (analog of custom resources) ---------------------
    def set_resource(self, name: str, value: Any) -> None:
        with self._lock:
            self._registry[name] = value

    def get_resource(self, name: str, factory=None) -> Any:
        """Lazily fetch a named resource, creating it with ``factory``."""
        with self._lock:
            if name not in self._registry:
                if factory is None:
                    raise KeyError(name)
                self._registry[name] = factory()
            return self._registry[name]

    def sync(self) -> None:
        """Block until all queued work on this device is complete.

        Analog of ``resource::sync_stream``; JAX is async-dispatch so this
        just fences with a trivial transfer.
        """
        jax.block_until_ready(jax.device_put(np.zeros(()), self.device))


_default_resources: Optional[Resources] = None
_default_lock = lockcheck.tracked(threading.Lock(), "core.resources_default")


def default_resources() -> Resources:
    """Process-global default handle (lazy; analog of pylibraft's implicit
    ``DeviceResources`` injected by ``auto_sync_handle``)."""
    global _default_resources
    with _default_lock:
        if _default_resources is None:
            _default_resources = Resources()
        return _default_resources


def ensure_resources(res: Optional[Resources]) -> Resources:
    return res if res is not None else default_resources()
