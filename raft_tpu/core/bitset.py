"""Packed bitset / bitmap used as ANN search prefilters.

Analog of ``core/bitset.hpp:39,119`` (``bitset_view`` / ``bitset``) and
``core/bitmap.hpp:43`` in the reference, where bitsets mark deleted/filtered
dataset rows and are tested inside IVF/CAGRA/brute-force kernels. Here a
bitset is a flat ``uint32`` JAX array (a pytree), and all operations are pure
functions usable under ``jit`` — tests map onto VPU bitwise ops.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.utils.math import cdiv

_BITS = 32


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Bitset:
    """A fixed-size set of bits over ``[0, size)``; bit=1 means "keep".

    Mirrors ``raft::core::bitset``: created either empty (all set / all unset)
    or from a list of indices to *unset* (the deleted-rows use case,
    ``bitset.hpp`` ctor with ``mask_index``).
    """

    bits: jax.Array  # uint32[ceil(size/32)]
    size: int

    def tree_flatten(self):
        return (self.bits,), (self.size,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(bits=children[0], size=aux[0])

    # -- constructors ------------------------------------------------------
    @staticmethod
    def create(size: int, default: bool = True) -> "Bitset":
        n_words = cdiv(size, _BITS)
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        bits = jnp.full((n_words,), fill, dtype=jnp.uint32)
        if default and size % _BITS:
            # Mask tail bits beyond `size` so count() is exact.
            tail = jnp.uint32((1 << (size % _BITS)) - 1)
            bits = bits.at[-1].set(tail)
        return Bitset(bits=bits, size=size)

    @staticmethod
    def from_mask(mask: jax.Array) -> "Bitset":
        """Pack a boolean vector (True = keep) into a bitset."""
        size = mask.shape[0]
        n_words = cdiv(size, _BITS)
        pad = n_words * _BITS - size
        m = jnp.pad(mask.astype(jnp.uint32), (0, pad)).reshape(n_words, _BITS)
        weights = (jnp.uint32(1) << jnp.arange(_BITS, dtype=jnp.uint32))[None, :]
        return Bitset(bits=(m * weights).sum(axis=1).astype(jnp.uint32), size=size)

    @staticmethod
    def from_unset_indices(size: int, indices: jax.Array) -> "Bitset":
        """All-set bitset with ``indices`` cleared (deleted-rows ctor)."""
        return Bitset.create(size, default=True).unset(indices)

    # -- element ops -------------------------------------------------------
    def test(self, indices: jax.Array) -> jax.Array:
        """Gather bit values at ``indices`` -> bool array."""
        word = self.bits[indices // _BITS]
        return ((word >> (indices % _BITS).astype(jnp.uint32)) & 1).astype(bool)

    def set(self, indices: jax.Array) -> "Bitset":
        # Scattered OR: apply one index at a time so duplicates within a word
        # fold correctly (jnp scatter .set would keep only one of them).
        sel = jnp.uint32(1) << (indices % _BITS).astype(jnp.uint32)

        def body(bits, iw):
            i, w = iw
            return bits.at[i].set(bits[i] | w), None

        bits, _ = jax.lax.scan(body, self.bits, (indices // _BITS, sel))
        return Bitset(bits=bits, size=self.size)

    def unset(self, indices: jax.Array) -> "Bitset":
        # Scattered AND-NOT, same per-index fold as set().
        sel = ~(jnp.uint32(1) << (indices % _BITS).astype(jnp.uint32))

        def body(bits, iw):
            i, w = iw
            return bits.at[i].set(bits[i] & w), None

        bits, _ = jax.lax.scan(body, self.bits, (indices // _BITS, sel))
        return Bitset(bits=bits, size=self.size)

    def flip(self) -> "Bitset":
        bits = ~self.bits
        if self.size % _BITS:
            tail = jnp.uint32((1 << (self.size % _BITS)) - 1)
            bits = bits.at[-1].set(bits[-1] & tail)
        return Bitset(bits=bits, size=self.size)

    def count(self) -> jax.Array:
        """Number of set bits (analog of ``bitset::count``)."""
        return jnp.sum(popcount32(self.bits))

    def to_mask(self) -> jax.Array:
        """Unpack into a bool[size] vector (for masking distance tiles)."""
        shifts = jnp.arange(_BITS, dtype=jnp.uint32)[None, :]
        unpacked = ((self.bits[:, None] >> shifts) & 1).astype(bool)
        return unpacked.reshape(-1)[: self.size]


# Bitmap = 2D bitset view (rows x cols), used for per-query filters
# (core/bitmap.hpp). Represent as a Bitset over row-major flattened indices.
@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Bitmap:
    bitset: Bitset
    rows: int
    cols: int

    def tree_flatten(self):
        return (self.bitset,), (self.rows, self.cols)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(bitset=children[0], rows=aux[0], cols=aux[1])

    @staticmethod
    def from_mask(mask2d: jax.Array) -> "Bitmap":
        rows, cols = mask2d.shape
        return Bitmap(Bitset.from_mask(mask2d.reshape(-1)), rows, cols)

    def test(self, row: jax.Array, col: jax.Array) -> jax.Array:
        return self.bitset.test(row * self.cols + col)

    def to_mask(self) -> jax.Array:
        return self.bitset.to_mask().reshape(self.rows, self.cols)


def popcount32(x: jax.Array) -> jax.Array:
    """Per-element population count of a uint32 array (SWAR)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)
