"""Profiler annotation scopes: the TPU-native analog of the reference's NVTX
RAII ranges (``core/nvtx.hpp:26-93``) that mark every nontrivial entry point.

On TPU the profiler is XPlane/Perfetto via ``jax.profiler``; a
``TraceAnnotation`` shows up on the host timeline and a ``named_scope``
attaches names to compiled HLO. Like the reference (compile-time NVTX gate,
``cpp/CMakeLists.txt:261``) tracing is toggleable and zero-cost when off.
"""
from __future__ import annotations

import contextlib
import functools
import os

import jax

_enabled = os.environ.get("RAFT_TPU_TRACING", "1") != "0"


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def push_range(name: str):
    """Host-side timeline range (analog of ``nvtx::push_range/pop_range``)."""
    if not _enabled:
        yield
        return
    with jax.profiler.TraceAnnotation(name):
        yield


# The RAII alias used throughout the reference: raft::common::nvtx::range.
range = push_range


def annotate(name: str | None = None):
    """Decorator tracing a function (analog of the per-function NVTX ranges
    at e.g. ``cluster/detail/kmeans.cuh:371``)."""

    def deco(fn):
        label = name or f"raft_tpu::{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def named_scope(name: str):
    """In-graph scope: names survive into compiled HLO/XPlane."""
    if not _enabled:
        return contextlib.nullcontext()
    return jax.named_scope(name)
