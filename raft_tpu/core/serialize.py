"""Array/scalar (de)serialization in NumPy ``.npy`` format.

Analog of ``core/serialize.hpp:36-126`` / ``core/detail/numpy_serializer.hpp``
in the reference: mdspans are written to iostreams in the npy format so
indexes serialized by one implementation can be inspected (or loaded) by
numpy. Index-level serializers (brute-force / IVF-Flat / IVF-PQ / CAGRA) are
built from these primitives plus a versioned header, mirroring
``neighbors/*_serialize.cuh``.
"""
from __future__ import annotations

import io
import os
import struct
import zlib
from typing import BinaryIO, Callable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.errors import CorruptIndexError

# Serialization format version tag written by dump_header; bump on breaking
# layout changes (the reference keeps a per-index `serialization_version`).
# v4 is the checksummed envelope (save_stream/load_stream): the v<=3
# preamble, then index-format version + payload length + CRC32 + payload.
# v<=3 streams (bare preamble + body) still load, unchecked.
SERIALIZATION_VERSION = 4
_MAGIC = b"RAFT_TPU"


# Dtypes npy cannot represent, stored via a bit-identical view. The dtype
# name is tagged ahead of the npy payload so deserialize restores it.
_VIEW_AS = {"bfloat16": np.uint16}


def serialize_array(stream: BinaryIO, arr) -> None:
    """Write an array: a dtype-name tag followed by an ``.npy`` payload.

    Analog of ``serialize_mdspan`` (``core/serialize.hpp:99``). The npy
    payload stays numpy-loadable; bfloat16 (not representable in npy) is
    stored as a uint16 bit view and restored from the tag.
    """
    host = np.asarray(jax.device_get(arr))
    name = host.dtype.name
    serialize_string(stream, name)
    if name in _VIEW_AS:
        host = host.view(_VIEW_AS[name])
    np.save(stream, host, allow_pickle=False)


def deserialize_array(stream: BinaryIO, device=None) -> jax.Array:
    """Read one tagged array and place it on ``device``.

    Analog of ``deserialize_mdspan`` (``core/serialize.hpp:110``).
    """
    name = deserialize_string(stream)
    host = np.load(stream, allow_pickle=False)
    if name in _VIEW_AS:
        host = host.view(jnp.dtype(name))
    return jax.device_put(host, device)


_SCALAR_FMT = {
    "int32": "<i4",
    "int64": "<i8",
    "uint32": "<u4",
    "uint64": "<u8",
    "float32": "<f4",
    "float64": "<f8",
    "bool": "?",
}


def serialize_scalar(stream: BinaryIO, value: Union[int, float, bool, np.generic], dtype: str) -> None:
    """Write one fixed-width scalar (analog of ``serialize_scalar``,
    ``core/serialize.hpp:36``)."""
    stream.write(np.asarray(value, dtype=_SCALAR_FMT[dtype]).tobytes())


def deserialize_scalar(stream: BinaryIO, dtype: str):
    dt = np.dtype(_SCALAR_FMT[dtype])
    buf = stream.read(dt.itemsize)
    if len(buf) != dt.itemsize:
        raise EOFError("truncated stream while reading scalar")
    return np.frombuffer(buf, dtype=dt)[0].item()


def serialize_string(stream: BinaryIO, s: str) -> None:
    data = s.encode("utf-8")
    serialize_scalar(stream, len(data), "uint32")
    stream.write(data)


def deserialize_string(stream: BinaryIO) -> str:
    n = deserialize_scalar(stream, "uint32")
    return stream.read(n).decode("utf-8")


def dump_header(stream: BinaryIO, kind: str, version: int = SERIALIZATION_VERSION) -> None:
    """Write the magic + index-kind + version preamble used by all index
    serializers (analog of the version tag checks in
    ``neighbors/ivf_pq_serialize.cuh``)."""
    stream.write(_MAGIC)
    serialize_string(stream, kind)
    serialize_scalar(stream, version, "uint32")


def check_header(stream: BinaryIO, kind: str) -> int:
    magic = stream.read(len(_MAGIC))
    if magic != _MAGIC:
        raise ValueError(f"not a raft_tpu serialized object (bad magic {magic!r})")
    found = deserialize_string(stream)
    if found != kind:
        raise ValueError(f"expected serialized {kind!r}, found {found!r}")
    version = deserialize_scalar(stream, "uint32")
    if version > SERIALIZATION_VERSION:
        raise ValueError(f"serialization version {version} is newer than supported {SERIALIZATION_VERSION}")
    return version


# ---------------------------------------------------------------------------
# v4 checksummed envelope + atomic file helpers
# ---------------------------------------------------------------------------


def save_stream(stream: BinaryIO, kind: str, version: int, body: bytes) -> None:
    """Write an index snapshot in the v4 checksummed envelope.

    Layout: the v<=3 preamble (magic + kind + envelope version 4), then the
    index-format ``version`` (u32, what per-index ``load`` branches on),
    payload length (u64), CRC32 of the payload (u32), payload bytes.
    The CRC covers the payload only — header corruption already fails the
    magic/kind/version checks."""
    dump_header(stream, kind, SERIALIZATION_VERSION)
    serialize_scalar(stream, version, "uint32")
    serialize_scalar(stream, len(body), "uint64")
    serialize_scalar(stream, zlib.crc32(body) & 0xFFFFFFFF, "uint32")
    stream.write(body)


def load_stream(stream: BinaryIO, kind: str) -> Tuple[int, BinaryIO]:
    """Open an index snapshot: returns ``(index_version, payload_stream)``.

    v4 envelopes are length- and CRC-verified (raising
    :class:`CorruptIndexError` on truncation or bit damage) and the
    payload is returned as an in-memory stream; v<=3 legacy streams are
    returned as-is, unchecked, with the preamble version standing in for
    the index version (exactly what pre-v4 ``load`` consumed)."""
    version = check_header(stream, kind)
    # chaos seam: storage-layer faults (CorruptIndexError, injected
    # latency) fire after the header parse, before payload verification
    from raft_tpu.robust import faults

    faults.fire("serialize.load", kind=kind)
    if version < 4:
        return version, stream
    index_version = int(deserialize_scalar(stream, "uint32"))
    length = int(deserialize_scalar(stream, "uint64"))
    crc = int(deserialize_scalar(stream, "uint32"))
    payload_offset = stream.tell() if stream.seekable() else None
    payload = stream.read(length)
    if len(payload) != length:
        raise CorruptIndexError(
            f"truncated {kind} snapshot: payload is {len(payload)} of {length} bytes",
            offset=payload_offset,
        )
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    if actual != crc:
        raise CorruptIndexError(
            f"{kind} snapshot failed its CRC32 check",
            offset=payload_offset, expected_crc=crc, actual_crc=actual,
        )
    return index_version, io.BytesIO(payload)


def open_payload(path: str, kind: str, *, verify_crc: bool = True) -> Tuple[int, int, int]:
    """Locate the v4 payload inside the file at ``path`` without holding
    it in memory: returns ``(index_version, payload_offset, length)``.

    The lazy complement of :func:`load_stream` for memory-mapped loading
    (:func:`mmap_array_at`): the header is parsed, the CRC is verified by
    streaming the payload in 4 MiB chunks (skippable with
    ``verify_crc=False`` when the caller amortizes integrity elsewhere),
    and the file is closed again — the mapped array re-opens it on
    demand. v<=3 streams have no framed payload and are rejected."""
    with open(path, "rb") as f:
        version = check_header(f, kind)
        from raft_tpu.robust import faults

        faults.fire("serialize.load", kind=kind)
        if version < 4:
            raise ValueError(
                f"mmap loading needs a v4 envelope; {path!r} is v{version}"
            )
        index_version = int(deserialize_scalar(f, "uint32"))
        length = int(deserialize_scalar(f, "uint64"))
        crc = int(deserialize_scalar(f, "uint32"))
        offset = f.tell()
        if verify_crc:
            actual = 0
            remaining = length
            while remaining:
                chunk = f.read(min(remaining, 4 << 20))
                if not chunk:
                    raise CorruptIndexError(
                        f"truncated {kind} snapshot: payload is "
                        f"{length - remaining} of {length} bytes",
                        offset=offset,
                    )
                actual = zlib.crc32(chunk, actual)
                remaining -= len(chunk)
            if actual & 0xFFFFFFFF != crc:
                raise CorruptIndexError(
                    f"{kind} snapshot failed its CRC32 check",
                    offset=offset, expected_crc=crc,
                    actual_crc=actual & 0xFFFFFFFF,
                )
        return index_version, offset, length


def mmap_array_at(path: str, offset: int) -> Tuple[np.ndarray, int]:
    """Map the :func:`serialize_array` frame at ``offset`` in ``path``
    without copying it into RAM: returns ``(array, next_offset)``.

    The array is a read-only ``np.memmap`` view over the npy data bytes
    — the OS pages rows in as the host-tier gather touches them, which
    is what lets a tiered corpus exceed both HBM *and* resident host
    memory. bfloat16 frames are restored from the tagged uint16 view
    like :func:`deserialize_array`."""
    with open(path, "rb") as f:
        f.seek(offset)
        name = deserialize_string(f)
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
        else:
            shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
        if fortran:
            raise ValueError("mmap loading supports C-order arrays only")
        data_offset = f.tell()
    arr = np.memmap(path, dtype=dtype, mode="r", offset=data_offset, shape=shape)
    if name in _VIEW_AS:
        arr = arr.view(jnp.dtype(name))
    next_offset = data_offset + int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return arr, next_offset


def atomic_write(path: str, writer: Callable[[BinaryIO], None]) -> str:
    """Run ``writer`` against a temp file, fsync, then rename onto
    ``path`` — a torn write can never be observed at ``path``."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path
