"""Array/scalar (de)serialization in NumPy ``.npy`` format.

Analog of ``core/serialize.hpp:36-126`` / ``core/detail/numpy_serializer.hpp``
in the reference: mdspans are written to iostreams in the npy format so
indexes serialized by one implementation can be inspected (or loaded) by
numpy. Index-level serializers (brute-force / IVF-Flat / IVF-PQ / CAGRA) are
built from these primitives plus a versioned header, mirroring
``neighbors/*_serialize.cuh``.
"""
from __future__ import annotations

import io
import struct
from typing import BinaryIO, Union

import jax
import jax.numpy as jnp
import numpy as np

# Serialization format version tag written by dump_header; bump on breaking
# layout changes (the reference keeps a per-index `serialization_version`).
SERIALIZATION_VERSION = 3
_MAGIC = b"RAFT_TPU"


# Dtypes npy cannot represent, stored via a bit-identical view. The dtype
# name is tagged ahead of the npy payload so deserialize restores it.
_VIEW_AS = {"bfloat16": np.uint16}


def serialize_array(stream: BinaryIO, arr) -> None:
    """Write an array: a dtype-name tag followed by an ``.npy`` payload.

    Analog of ``serialize_mdspan`` (``core/serialize.hpp:99``). The npy
    payload stays numpy-loadable; bfloat16 (not representable in npy) is
    stored as a uint16 bit view and restored from the tag.
    """
    host = np.asarray(jax.device_get(arr))
    name = host.dtype.name
    serialize_string(stream, name)
    if name in _VIEW_AS:
        host = host.view(_VIEW_AS[name])
    np.save(stream, host, allow_pickle=False)


def deserialize_array(stream: BinaryIO, device=None) -> jax.Array:
    """Read one tagged array and place it on ``device``.

    Analog of ``deserialize_mdspan`` (``core/serialize.hpp:110``).
    """
    name = deserialize_string(stream)
    host = np.load(stream, allow_pickle=False)
    if name in _VIEW_AS:
        host = host.view(jnp.dtype(name))
    return jax.device_put(host, device)


_SCALAR_FMT = {
    "int32": "<i4",
    "int64": "<i8",
    "uint32": "<u4",
    "uint64": "<u8",
    "float32": "<f4",
    "float64": "<f8",
    "bool": "?",
}


def serialize_scalar(stream: BinaryIO, value: Union[int, float, bool, np.generic], dtype: str) -> None:
    """Write one fixed-width scalar (analog of ``serialize_scalar``,
    ``core/serialize.hpp:36``)."""
    stream.write(np.asarray(value, dtype=_SCALAR_FMT[dtype]).tobytes())


def deserialize_scalar(stream: BinaryIO, dtype: str):
    dt = np.dtype(_SCALAR_FMT[dtype])
    buf = stream.read(dt.itemsize)
    if len(buf) != dt.itemsize:
        raise EOFError("truncated stream while reading scalar")
    return np.frombuffer(buf, dtype=dt)[0].item()


def serialize_string(stream: BinaryIO, s: str) -> None:
    data = s.encode("utf-8")
    serialize_scalar(stream, len(data), "uint32")
    stream.write(data)


def deserialize_string(stream: BinaryIO) -> str:
    n = deserialize_scalar(stream, "uint32")
    return stream.read(n).decode("utf-8")


def dump_header(stream: BinaryIO, kind: str, version: int = SERIALIZATION_VERSION) -> None:
    """Write the magic + index-kind + version preamble used by all index
    serializers (analog of the version tag checks in
    ``neighbors/ivf_pq_serialize.cuh``)."""
    stream.write(_MAGIC)
    serialize_string(stream, kind)
    serialize_scalar(stream, version, "uint32")


def check_header(stream: BinaryIO, kind: str) -> int:
    magic = stream.read(len(_MAGIC))
    if magic != _MAGIC:
        raise ValueError(f"not a raft_tpu serialized object (bad magic {magic!r})")
    found = deserialize_string(stream)
    if found != kind:
        raise ValueError(f"expected serialized {kind!r}, found {found!r}")
    version = deserialize_scalar(stream, "uint32")
    if version > SERIALIZATION_VERSION:
        raise ValueError(f"serialization version {version} is newer than supported {SERIALIZATION_VERSION}")
    return version
