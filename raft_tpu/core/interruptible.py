"""Cooperative cancellation of long-running host-side loops.

Analog of ``core/interruptible.hpp:73-170``: the reference's spin-wait stream
sync polls a per-thread token so another thread can cancel in-flight GPU work.
On TPU, device work inside one jitted computation is not interruptible (XLA
runs the whole program), but the library's long-running *host* loops — batched
index builds, NN-descent rounds, benchmark sweeps — poll ``synchronize()``
between device calls, giving equivalent cancellation granularity to the
reference's between-kernel checks. Exposed to users exactly like the pylibraft
wrapper (``pylibraft/common/interruptible.pyx``).
"""
from __future__ import annotations

import threading
from typing import Dict

from raft_tpu.core.errors import RaftError
from raft_tpu.utils import lockcheck


class InterruptedException(RaftError):
    """Raised inside a cancelled thread at its next synchronize() point."""


_tokens: Dict[int, threading.Event] = {}
_lock = lockcheck.tracked(threading.Lock(), "core.interruptible")


def _token(tid: int | None = None) -> threading.Event:
    tid = threading.get_ident() if tid is None else tid
    with _lock:
        ev = _tokens.get(tid)
        if ev is None:
            ev = threading.Event()
            _tokens[tid] = ev
        return ev


def cancel(thread_id: int) -> None:
    """Request cancellation of another thread (``interruptible::cancel``)."""
    _token(thread_id).set()


def yield_() -> None:
    """Check-and-clear the current thread's token, raising if cancelled
    (``interruptible::yield``)."""
    ev = _token()
    if ev.is_set():
        ev.clear()
        raise InterruptedException("raft_tpu: computation interrupted")


def yield_no_throw() -> bool:
    """Check-and-clear; returns True if a cancellation was pending."""
    ev = _token()
    if ev.is_set():
        ev.clear()
        return True
    return False


def synchronize(value=None):
    """Cancellation-aware sync point: block on ``value`` (if given) and poll
    the token (analog of ``interruptible::synchronize(stream)``)."""
    yield_()
    if value is not None:
        import jax

        jax.block_until_ready(value)
        yield_()
    return value
