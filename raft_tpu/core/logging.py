"""Library logger: analog of the reference's spdlog-backed ``raft::logger``
(``core/logger-inl.hpp:72-140``) with settable level, pattern and a callback
sink, and the ``RAFT_LOG_{TRACE..CRITICAL}`` macros
(``core/logger-macros.hpp:81-102``).

Built on the stdlib ``logging`` module; one named logger ``"raft_tpu"`` with
convenience level constants matching the reference's numbering and an
optional callback sink (used by bindings to re-route messages).
"""
from __future__ import annotations

import logging as _logging
from typing import Callable, Optional

# Reference level numbering (core/logger-macros.hpp): OFF=0 .. TRACE=6.
LEVEL_OFF = 0
LEVEL_CRITICAL = 1
LEVEL_ERROR = 2
LEVEL_WARN = 3
LEVEL_INFO = 4
LEVEL_DEBUG = 5
LEVEL_TRACE = 6

_TO_PY = {
    LEVEL_OFF: _logging.CRITICAL + 10,
    LEVEL_CRITICAL: _logging.CRITICAL,
    LEVEL_ERROR: _logging.ERROR,
    LEVEL_WARN: _logging.WARNING,
    LEVEL_INFO: _logging.INFO,
    LEVEL_DEBUG: _logging.DEBUG,
    LEVEL_TRACE: 5,
}

logger = _logging.getLogger("raft_tpu")
logger.addHandler(_logging.NullHandler())

_callback: Optional[Callable[[int, str], None]] = None


class _CallbackHandler(_logging.Handler):
    def emit(self, record):
        if _callback is not None:
            _callback(record.levelno, self.format(record))


_cb_handler = _CallbackHandler()


def set_level(level: int) -> None:
    """Set verbosity using the reference's 0..6 numbering."""
    logger.setLevel(_TO_PY.get(level, _logging.INFO))


def get_level() -> int:
    eff = logger.getEffectiveLevel()
    for k, v in _TO_PY.items():
        if v == eff:
            return k
    return LEVEL_INFO


def set_callback(cb: Optional[Callable[[int, str], None]]) -> None:
    """Install a callback sink (analog of ``logger::set_callback``)."""
    global _callback
    _callback = cb
    if cb is not None and _cb_handler not in logger.handlers:
        logger.addHandler(_cb_handler)
    if cb is None and _cb_handler in logger.handlers:
        logger.removeHandler(_cb_handler)


def set_pattern(fmt: str) -> None:
    """Set the sink format string (analog of ``logger::set_pattern``)."""
    _cb_handler.setFormatter(_logging.Formatter(fmt))


# RAFT_LOG_* macro analogs
trace = lambda msg, *a: logger.log(5, msg, *a)
debug = logger.debug
info = logger.info
warn = logger.warning
error = logger.error
critical = logger.critical
