"""Core runtime layer (L1 analog): resources, errors, logging, tracing,
serialization, bitsets, interruptible cancellation, array ingestion.

See ``SURVEY.md`` §2.1 for the reference component map
(``/root/reference/cpp/include/raft/core``).
"""
from raft_tpu.core.array import as_array, check_dtype_one_of, check_matching_dims
from raft_tpu.core.bitset import Bitmap, Bitset, popcount32
from raft_tpu.core.errors import LogicError, RaftError, expects, fail
from raft_tpu.core.resources import Resources, default_resources, ensure_resources
from raft_tpu.core import interruptible, logging, serialize, tracing

__all__ = [
    "as_array",
    "check_dtype_one_of",
    "check_matching_dims",
    "Bitmap",
    "Bitset",
    "popcount32",
    "LogicError",
    "RaftError",
    "expects",
    "fail",
    "Resources",
    "default_resources",
    "ensure_resources",
    "interruptible",
    "logging",
    "serialize",
    "tracing",
]
