"""Exception types and check macros.

Analog of ``core/error.hpp:48,229,245``: ``raft::exception`` (with backtrace —
Python gives us that for free), ``RAFT_EXPECTS`` and ``RAFT_FAIL``.
"""
from __future__ import annotations


class RaftError(RuntimeError):
    """Base library exception (analog of ``raft::exception``)."""


class LogicError(RaftError):
    """Analog of ``raft::logic_error`` raised by ``RAFT_EXPECTS``."""


class ShardFailure(RaftError):
    """One shard of a distributed operation failed (lost device, failed
    collective participant). Degraded-mode search catches this and
    continues over the surviving shards (:mod:`raft_tpu.robust.degrade`)."""

    def __init__(self, msg: str = "shard failure", shard: int = -1):
        super().__init__(msg)
        self.shard = shard


class KernelFailure(RaftError):
    """A fused accelerator kernel failed to lower/compile/execute.
    ``mode="auto"`` dispatch catches this and falls back to the XLA path
    (:mod:`raft_tpu.robust`)."""


class CorruptIndexError(RaftError):
    """A serialized index snapshot failed its integrity check (bad CRC,
    truncated payload). Raised by :func:`raft_tpu.core.serialize.load_stream`.

    Carries the forensic detail an operator needs to locate the damage:
    ``offset`` is the stream position of the failing frame's payload,
    and ``expected_crc`` / ``actual_crc`` are set on checksum mismatch
    (both None on truncation)."""

    def __init__(
        self,
        msg: str,
        *,
        offset: int | None = None,
        expected_crc: int | None = None,
        actual_crc: int | None = None,
    ):
        detail = []
        if offset is not None:
            detail.append(f"offset={offset}")
        if expected_crc is not None:
            detail.append(f"expected_crc=0x{expected_crc:08x}")
        if actual_crc is not None:
            detail.append(f"actual_crc=0x{actual_crc:08x}")
        super().__init__(f"{msg} [{', '.join(detail)}]" if detail else msg)
        self.offset = offset
        self.expected_crc = expected_crc
        self.actual_crc = actual_crc


class HostFetchError(RaftError):
    """The host-tier vector fetch behind a tiered search failed after
    exhausting its retries (see :mod:`raft_tpu.tiered`). Carries the
    batch shape so an operator can correlate with ``tiered.fetch.*``
    metrics and the ``host.fetch`` fault seam."""

    def __init__(self, msg: str, *, rows: int | None = None, attempts: int | None = None):
        detail = []
        if rows is not None:
            detail.append(f"rows={rows}")
        if attempts is not None:
            detail.append(f"attempts={attempts}")
        super().__init__(f"{msg} [{', '.join(detail)}]" if detail else msg)
        self.rows = rows
        self.attempts = attempts


def expects(cond: bool, msg: str, *args) -> None:
    """Runtime check macro analog of ``RAFT_EXPECTS(cond, fmt, ...)``."""
    if not cond:
        raise LogicError(msg % args if args else msg)


def fail(msg: str, *args) -> None:
    """Unconditional failure (``RAFT_FAIL``)."""
    raise LogicError(msg % args if args else msg)
