"""Exception types and check macros.

Analog of ``core/error.hpp:48,229,245``: ``raft::exception`` (with backtrace —
Python gives us that for free), ``RAFT_EXPECTS`` and ``RAFT_FAIL``.
"""
from __future__ import annotations


class RaftError(RuntimeError):
    """Base library exception (analog of ``raft::exception``)."""


class LogicError(RaftError):
    """Analog of ``raft::logic_error`` raised by ``RAFT_EXPECTS``."""


def expects(cond: bool, msg: str, *args) -> None:
    """Runtime check macro analog of ``RAFT_EXPECTS(cond, fmt, ...)``."""
    if not cond:
        raise LogicError(msg % args if args else msg)


def fail(msg: str, *args) -> None:
    """Unconditional failure (``RAFT_FAIL``)."""
    raise LogicError(msg % args if args else msg)
