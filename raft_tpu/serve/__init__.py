"""raft_tpu.serve — the online query-serving engine.

Sits above every index type (and the sharded paths) and turns a stream
of small, arrival-timed requests into the large fixed-shape batches the
fused kernels want, without compiling an unbounded program population:

* :mod:`raft_tpu.serve.bucketing` — power-of-two shape buckets with
  pad/unpad and an LRU :class:`ProgramCache` of compiled programs
  (warmup/precompile API included);
* :mod:`raft_tpu.serve.batcher` — bounded request queue with dynamic
  micro-batching (flush on ``max_batch`` rows or ``max_wait_ms``),
  per-request deadlines, and deadline-aware admission control (typed
  :class:`QueueFull` / :class:`DeadlineExceeded` rejections);
* :mod:`raft_tpu.serve.engine` — :class:`ServingEngine` futures API
  plus a synchronous loop driver, routed through the
  :mod:`raft_tpu.robust` fallback/degrade machinery and instrumented
  with :mod:`raft_tpu.obs`.

See ``docs/serving.md``.
"""
from raft_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    QueueFull,
    Request,
    ServeFuture,
)
from raft_tpu.serve.bucketing import (
    CacheStats,
    ProgramCache,
    ProgramKey,
    bucket_for,
    bucket_sizes,
    pad_rows,
    params_key,
    unpad_rows,
)
from raft_tpu.serve.engine import ServeResult, ServingEngine

__all__ = [
    "CacheStats",
    "DeadlineExceeded",
    "MicroBatcher",
    "ProgramCache",
    "ProgramKey",
    "QueueFull",
    "Request",
    "ServeFuture",
    "ServeResult",
    "ServingEngine",
    "bucket_for",
    "bucket_sizes",
    "pad_rows",
    "params_key",
    "unpad_rows",
]
