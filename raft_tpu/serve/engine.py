"""Online query-serving engine over the raft_tpu index family.

:class:`ServingEngine` turns a stream of small, arrival-timed search
requests into the large fixed-shape micro-batches the fused Pallas
kernels were built for:

* requests enter through a futures API (:meth:`ServingEngine.submit` /
  :meth:`submit_many`) into a bounded :class:`~raft_tpu.serve.batcher.
  MicroBatcher` (typed ``QueueFull`` / ``DeadlineExceeded`` rejection,
  never unbounded latency);
* micro-batches are padded to the closed power-of-two shape vocabulary
  of :mod:`raft_tpu.serve.bucketing` and dispatched through an LRU
  :class:`~raft_tpu.serve.bucketing.ProgramCache`, so the engine only
  ever compiles ``log2(max_batch)+1`` programs per configuration;
* dispatch routes through the existing robustness machinery — fused
  kernels degrade to XLA inside ``mode="auto"`` search (see
  :mod:`raft_tpu.robust.fallback`), sharded indexes route through
  :func:`raft_tpu.robust.degrade.sharded_search_degraded` with a timed
  per-shard health probe, so a failed or *slow* shard yields a
  degraded response carrying ``coverage < 1.0`` instead of a timeout;
* the whole path is instrumented with :mod:`raft_tpu.obs`
  (``serve.queue_depth`` gauge, ``serve.time_in_queue_ms`` /
  ``serve.batch_fill`` histograms, ``serve.rejections`` counter,
  ``serve.dispatch`` spans) and chaos-testable at the
  ``serve.dispatch`` fault seam (:mod:`raft_tpu.robust.faults`).

The engine is **synchronous by design**: :meth:`step` processes at most
one micro-batch on the caller's thread and :meth:`run_until_idle`
drains the queue, so tests and single-threaded load generators drive
it deterministically; a deployment wraps :meth:`step` in its own
thread/event loop. With obs, faults, and the serve seam all disabled,
results are bit-identical to calling ``search()`` directly with the
same parameters (``tests/test_serve.py`` gate-parity).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.errors import ShardFailure, expects
from raft_tpu.robust import faults
from raft_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    QueueFull,
    Request,
    ServeFuture,
)
from raft_tpu.serve.bucketing import (
    ProgramCache,
    ProgramKey,
    bucket_for,
    bucket_sizes,
    pad_rows,
    params_key,
)

#: algo name -> default dispatch mode at registration
_DEFAULT_MODES = {
    "brute_force": "exact",
    "ivf_flat": "auto",
    "ivf_pq": "auto",
    "cagra": "auto",
    "sharded_ivf_flat": "sharded",
    "sharded_ivf_pq_lists": "sharded",
    # pre-built TieredIndex: device scan + host-tier refine gather
    "tiered": "auto",
    # pre-built (or auto-degraded) TieredShardedIndex: per-shard HBM
    # codes behind the ring merge, per-shard host tiers for the re-rank
    "tiered_sharded": "sharded",
}

#: algos the HBM placement planner knows how to model (and whose refine
#: dataset can degrade to the host tier)
_TIERABLE_ALGOS = ("ivf_pq", "ivf_flat", "brute_force")

#: sharded algos whose refine dataset can degrade to per-shard host
#: tiers (the registration converts to algo="tiered_sharded")
_SHARDED_TIERABLE = {
    "sharded_ivf_flat": ("ivf_flat", "ivf_flat"),
    "sharded_ivf_pq_lists": ("ivf_pq", "ivf_pq_lists"),
}


@dataclasses.dataclass(frozen=True)
class ServeResult:
    """One request's response: results plus the serving telemetry and
    the health picture they were computed under."""

    distances: np.ndarray  # [m, k]
    indices: np.ndarray  # [m, k]
    #: fraction of the index that answered (1.0 on non-sharded paths)
    coverage: float = 1.0
    degraded: bool = False
    failed_shards: Tuple[int, ...] = ()
    time_in_queue_ms: float = 0.0
    bucket: int = 0
    batch_rows: int = 0
    #: mutable-index generation the answer was computed against (0 for
    #: immutable registrations) — lets clients reason about freshness
    generation: int = 0
    #: obs request trace ID ("" with the gate off) — resolves to the
    #: request's spans/flow track in the Perfetto export and to its
    #: histogram exemplars (docs/observability.md "Request traces")
    trace_id: str = ""

    def __iter__(self):  # unpack like a plain (distances, indices)
        return iter((self.distances, self.indices))


@dataclasses.dataclass
class _Registration:
    index_id: str
    algo: str
    index: object
    params: object
    mode: str
    dataset: object = None
    mesh: object = None
    axis: str = "data"
    min_coverage: float = 0.0
    #: cross-shard exchange engine for the sharded algos
    #: ("auto" | "ring" | "gather"; see parallel/sharded_ann.py)
    merge_mode: str = "auto"
    search_kwargs: Dict[str, object] = dataclasses.field(default_factory=dict)
    #: background maintenance worker for mutable registrations (None
    #: when auto-compaction is not armed)
    compactor: object = None
    #: generation of the last dispatched batch (-1 before the first) —
    #: crossing a flip bumps the ``serve.generation_flips`` counter
    last_generation: int = -1
    #: active :class:`raft_tpu.plan.RegistrationPlan` (None with the
    #: planner gate off); swapped atomically by the re-plan tick
    plan: object = None
    #: live batch-size histogram since the last plan (bucket -> batches)
    bucket_counts: Dict[int, int] = dataclasses.field(default_factory=dict)
    #: dispatched-rows/s EWMA — the traffic model's arrival-rate input
    ewma_rows_per_s: float = 0.0
    last_dispatch_t: float = -1.0
    #: k of the most recent dispatch — what a plan flip precompiles for
    last_k: int = 10


class ServingEngine:
    """Dynamic micro-batching serving engine over registered indexes.

    >>> eng = ServingEngine(max_batch=64, max_wait_ms=2.0)
    >>> eng.register("wiki", "cagra", index)
    >>> fut = eng.submit("wiki", query_rows, k=10, deadline_ms=50)
    >>> eng.run_until_idle()
    >>> res = fut.result()          # ServeResult
    """

    def __init__(
        self,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        queue_capacity: int = 1024,
        cache_capacity: int = 64,
        clock: Optional[Callable[[], float]] = None,
        slow_shard_s: Optional[float] = 0.25,
        maintenance_interval_ms: float = 10.0,
        hbm_budget_bytes: Optional[int] = None,
        host_budget_bytes: Optional[int] = None,
    ):
        self.max_batch = int(max_batch)
        #: device-HBM budget for the placement planner (None = unplanned:
        #: every registration keeps its dataset wherever the caller put it)
        self.hbm_budget_bytes = hbm_budget_bytes
        #: per-shard host-RAM budget for the sharded three-level planner
        #: (None = unconstrained: spilled slabs stay in host RAM, never
        #: planned to disk)
        self.host_budget_bytes = host_budget_bytes
        self._residencies: Dict[str, object] = {}
        #: the planner's last verdict (an hbm_model.Placement), for
        #: introspection/tests after registrations
        self.placement = None
        #: per-registration sharded verdicts (hbm_model.ShardedPlacement),
        #: keyed by index_id — sharded registrations plan per shard and
        #: do not join the single-device fleet plan above
        self.sharded_placements: Dict[str, object] = {}
        self.batcher = MicroBatcher(
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            capacity=queue_capacity,
            clock=clock,
        )
        self.cache = ProgramCache(capacity=cache_capacity)
        #: a health probe slower than this marks the shard unhealthy —
        #: serve degraded coverage now rather than a timeout later
        self.slow_shard_s = slow_shard_s
        #: floor between maintenance ticks driven from :meth:`step`
        self.maintenance_interval_ms = float(maintenance_interval_ms)
        self._last_maint = -float("inf")
        self._indexes: Dict[str, _Registration] = {}
        #: per-index SLO trackers (see :meth:`set_slo` / :meth:`health`)
        self._slos: Dict[str, obs.SloTracker] = {}

    # -- registration ------------------------------------------------------

    def register(
        self,
        index_id: str,
        algo: str,
        index,
        *,
        params=None,
        mode: Optional[str] = None,
        dataset=None,
        mesh=None,
        axis: str = "data",
        min_coverage: float = 0.0,
        merge_mode: str = "auto",
        **search_kwargs,
    ) -> None:
        """Register ``index`` under ``index_id``.

        ``algo`` is one of ``brute_force`` / ``ivf_flat`` / ``ivf_pq`` /
        ``cagra`` / ``sharded_ivf_flat`` / ``sharded_ivf_pq_lists``.
        ``params``/``mode``/``search_kwargs`` are pinned at registration
        and become part of every program key; ``dataset`` enables
        IVF-PQ exact re-ranking; ``mesh`` is required for the sharded
        algos, ``min_coverage`` is their floor (below it the request
        fails with :class:`~raft_tpu.core.errors.ShardFailure` rather
        than return near-empty results), and ``merge_mode`` pins their
        cross-shard exchange engine (``"auto"`` | ``"ring"`` |
        ``"gather"``).

        ``algo="tiered"`` registers a pre-built
        :class:`raft_tpu.tiered.TieredIndex` (its store, refine ratio and
        params travel with the object). With the engine's
        ``hbm_budget_bytes`` set, a ``dataset`` that the
        :mod:`~raft_tpu.ops.pallas.hbm_model` planner cannot fit next to
        the already-registered indexes is transparently rewrapped in a
        :class:`~raft_tpu.tiered.HostVectorStore` — registration degrades
        to tiered serving instead of OOMing at first dispatch.

        ``algo="tiered_sharded"`` registers a pre-built
        :class:`raft_tpu.tiered.TieredShardedIndex` (``mesh``/``axis``
        default to the index's own). A *sharded* registration with a
        ``dataset`` and the budget set runs the per-shard three-level
        planner instead: a refine slab that cannot stay device-resident
        per shard converts the registration to ``tiered_sharded`` over
        per-shard :class:`~raft_tpu.tiered.ShardedHostTier` stores —
        ring-merged winners re-rank from each shard's host.
        """
        expects(algo in _DEFAULT_MODES, "unknown serving algo %r (want one of %s)",
                algo, ", ".join(sorted(_DEFAULT_MODES)))
        if algo == "tiered_sharded" and mesh is None:
            mesh = index.mesh
            axis = index.axis
        if algo.startswith("sharded_") or algo == "tiered_sharded":
            expects(mesh is not None, "sharded algo %r needs mesh=", algo)
        if algo in _SHARDED_TIERABLE:
            algo, index, dataset = self._plan_tier_sharded(
                index_id, algo, index, dataset, mesh=mesh, axis=axis,
                merge_mode=merge_mode, params=params, search_kwargs=search_kwargs,
            )
        else:
            dataset = self._plan_tier(index_id, algo, index, dataset)
        reg = _Registration(
            index_id=index_id,
            algo=algo,
            index=index,
            params=params,
            mode=mode if mode is not None else _DEFAULT_MODES[algo],
            dataset=dataset,
            mesh=mesh,
            axis=axis,
            min_coverage=min_coverage,
            merge_mode=merge_mode,
            search_kwargs=dict(search_kwargs),
        )
        reg.plan = self._plan_registration(reg)
        self._indexes[index_id] = reg

    def _plan_tier(self, index_id: str, algo: str, index, dataset):
        """Consult the HBM placement planner for this registration.

        With no budget, or an algo the model does not cover, the dataset
        passes through untouched. Otherwise the index's measured
        residency joins the fleet plan: required (scan) components must
        fit — an infeasible plan is a typed registration error — and a
        refine dataset the plan spills is rewrapped as a
        :class:`~raft_tpu.tiered.HostVectorStore`, so dispatch gathers
        winners from host RAM instead of holding the raw f32 slab in HBM.
        """
        if self.hbm_budget_bytes is None or algo not in _TIERABLE_ALGOS:
            return dataset
        from raft_tpu.neighbors.refine import is_host_dataset
        from raft_tpu.ops.pallas.hbm_model import plan_placement, residency_for_index

        refine_rows = 0
        if dataset is not None and not is_host_dataset(dataset):
            refine_rows = int(np.shape(dataset)[0])
        res = residency_for_index(index_id, algo, index, refine_rows=refine_rows)
        fleet = [r for iid, r in self._residencies.items() if iid != index_id]
        placement = plan_placement(fleet + [res], hbm_budget=self.hbm_budget_bytes)
        expects(
            placement.feasible,
            "registering %r needs %d B of scan-resident HBM against a budget "
            "of %d B — required components cannot tier to the host; shard or "
            "shrink the index",
            index_id, sum(r.required_bytes for r in fleet) + res.required_bytes,
            self.hbm_budget_bytes,
        )
        self._residencies[index_id] = res
        self.placement = placement
        if refine_rows and placement.tier(index_id, "raw_vectors") == "host":
            from raft_tpu.tiered import HostVectorStore

            dataset = HostVectorStore(np.asarray(dataset))
            obs.inc("serve.tiered_degrades", index_id=index_id, algo=algo)
        return dataset

    def _plan_tier_sharded(
        self, index_id: str, algo: str, index, dataset, *,
        mesh, axis, merge_mode, params, search_kwargs,
    ):
        """Per-shard three-level placement for a lists-sharded
        registration. Returns the (possibly converted) ``(algo, index,
        dataset)`` triple.

        With no budget or no refine dataset (or a caller-prepared host
        store) the registration passes through untouched. Otherwise the
        index's measured residency runs through
        :func:`~raft_tpu.ops.pallas.hbm_model.plan_placement_sharded`:
        required components must fit each shard's device cap — an
        infeasible plan is a typed registration error — and a spilled
        refine slab converts the registration to a
        :class:`~raft_tpu.tiered.TieredShardedIndex` whose per-shard
        :class:`~raft_tpu.tiered.ShardedHostTier` follows the lists-
        sharded row ownership, so each candidate re-ranks from the host
        of the shard that scanned it."""
        if self.hbm_budget_bytes is None or dataset is None:
            return algo, index, dataset
        from raft_tpu.neighbors.refine import is_host_dataset

        if is_host_dataset(dataset):
            return algo, index, dataset
        from raft_tpu.ops.pallas.hbm_model import (
            plan_placement_sharded,
            residency_for_index,
        )

        res_algo, scan_algo = _SHARDED_TIERABLE[algo]
        n_shards = mesh.shape[axis]
        res = residency_for_index(
            index_id, res_algo, index, refine_rows=int(np.shape(dataset)[0])
        )
        placement = plan_placement_sharded(
            [res], n_shards,
            hbm_budget_per_shard=self.hbm_budget_bytes,
            host_budget_per_shard=self.host_budget_bytes,
        )
        expects(
            placement.feasible,
            "registering %r needs %d B/shard of scan-resident HBM over %d "
            "shards against a per-shard budget of %d B — required components "
            "cannot tier to the host; add shards or shrink the index",
            index_id, placement.device_bytes_per_shard - placement.staging_device_bytes,
            n_shards, self.hbm_budget_bytes,
        )
        self.sharded_placements[index_id] = placement
        if placement.tier(index_id, "raw_vectors") == "device":
            return algo, index, dataset
        from raft_tpu.tiered import ShardedHostTier, TieredShardedIndex

        tier_kw = {
            key: search_kwargs.pop(key)
            for key in ("refine_ratio", "micro_batch", "metric_arg")
            if key in search_kwargs
        }
        tier = ShardedHostTier.from_lists(
            index, np.asarray(dataset), n_shards,
            fetch_depth_rows=search_kwargs.pop("fetch_depth_rows", None),
        )
        tiered = TieredShardedIndex(
            mesh, scan_algo, index, tier, axis=axis,
            search_params=params, merge_mode=merge_mode, **tier_kw,
        )
        obs.inc("serve.tiered_degrades", index_id=index_id, algo=algo)
        return "tiered_sharded", tiered, None

    def register_mutable(
        self,
        index_id: str,
        mutable,
        *,
        params=None,
        policy=None,
        compactor=None,
        **search_kwargs,
    ) -> None:
        """Register a :class:`raft_tpu.mutable.MutableIndex`.

        Each micro-batch is dispatched against one immutable
        :meth:`~raft_tpu.mutable.segments.MutableIndex.snapshot` taken
        at dispatch time, so concurrent insert/delete/upsert (and
        compaction's generation flips) are atomic with respect to
        serving — a batch sees the whole mutation or none of it. The
        snapshot's generation joins the :class:`ProgramKey`, retiring
        stale programs through the LRU and bounding distinct programs
        to ``generations × (log2(max_batch)+1)`` per configuration.

        ``policy`` (a :class:`raft_tpu.mutable.CompactionPolicy`) arms
        auto-compaction: the engine starts a background
        :class:`~raft_tpu.mutable.Compactor` for the index and drives
        its watchdog/trigger tick from :meth:`step`, so a churning
        index rebuilds itself off-thread while this engine keeps
        serving snapshots. Pass a pre-built ``compactor`` instead to
        control retry policy, seed, or resources; :meth:`shutdown`
        stops engine-owned workers either way.
        """
        old = self._indexes.get(index_id)
        if old is not None and old.compactor is not None:
            old.compactor.stop()
        if compactor is None and policy is not None:
            from raft_tpu.mutable.maintenance import Compactor

            compactor = Compactor(mutable, policy=policy, name=index_id)
        if compactor is not None:
            compactor.start()
        reg = _Registration(
            index_id=index_id,
            algo="mutable",
            index=mutable,
            params=params,
            mode="snapshot",
            search_kwargs=dict(search_kwargs),
            compactor=compactor,
        )
        # no engine pick for snapshot dispatch, but the plan still
        # carries the corpus/traffic anchors the re-plan tick tracks
        reg.plan = self._plan_registration(reg)
        self._indexes[index_id] = reg

    def registered(self) -> List[str]:
        return list(self._indexes)

    # -- submission --------------------------------------------------------

    def submit(
        self,
        index_id: str,
        queries,
        k: int,
        deadline_ms: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> ServeFuture:
        """Enqueue one request (``queries`` [m, dim] or a single [dim]
        row) and return its future. Raises :class:`QueueFull` /
        :class:`DeadlineExceeded` at admission — rejected work never
        occupies the queue. ``trace_id`` adopts an existing obs trace
        instead of minting one — how a replica group keeps one identity
        on a request across failover re-submissions
        (``docs/replication.md``)."""
        reg = self._reg(index_id)
        q = np.asarray(queries)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2, "queries must be [m, dim] (or one [dim] row)")
        expects(
            q.shape[0] <= self.max_batch,
            "request has %d rows > max_batch %d — use submit_many to split",
            q.shape[0], self.max_batch,
        )
        now = self.batcher.now()
        req = Request(
            queries=q,
            k=int(k),
            group=(index_id, int(k)),
            t_arrival=now,
            deadline_s=(now + deadline_ms / 1e3) if deadline_ms is not None else None,
        )
        if obs.is_enabled():
            # trace identity is minted at admission: the synthetic
            # serve.queue span starts here, and every span recorded under
            # this request's dispatch carries the ID (obs/request.py)
            req.trace_id = trace_id or obs.new_trace_id()
            req.t_submit_us = obs.registry().now_us()
        try:
            self.batcher.offer(req)
        except QueueFull:
            obs.inc("serve.rejections", reason="queue_full", index_id=index_id)
            raise
        except DeadlineExceeded:
            obs.inc("serve.rejections", reason="deadline_admission", index_id=index_id)
            raise
        if obs.is_enabled():
            obs.inc("serve.requests", index_id=index_id, algo=reg.algo)
            obs.set_gauge("serve.queue_depth", self.batcher.depth_rows())
        return req.future

    def submit_many(
        self,
        index_id: str,
        queries,
        k: int,
        deadline_ms: Optional[float] = None,
        request_rows: int = 1,
    ) -> List[ServeFuture]:
        """Split ``queries`` [n, dim] into requests of ``request_rows``
        rows each and submit them all; returns one future per request."""
        q = np.asarray(queries)
        expects(q.ndim == 2, "queries must be [n, dim]")
        expects(1 <= request_rows <= self.max_batch,
                "request_rows must be in [1, max_batch]")
        return [
            self.submit(index_id, q[s : s + request_rows], k, deadline_ms=deadline_ms)
            for s in range(0, q.shape[0], request_rows)
        ]

    # -- the synchronous loop driver ---------------------------------------

    def step(self, force: bool = False) -> int:
        """Process at most one micro-batch on the calling thread.

        Flushes when the batcher says so (full bucket or aged past
        ``max_wait_ms``) or unconditionally with ``force=True``.
        Returns the number of requests completed (including deadline
        rejections)."""
        now = self.batcher.now()
        if now - self._last_maint >= self.maintenance_interval_ms / 1e3:
            self._last_maint = now
            self.maintenance_tick()
        if not self.batcher.ready(now) and not (force and self.batcher.depth_requests()):
            return 0
        batch, expired = self.batcher.next_batch(now)
        for r in expired:
            obs.inc("serve.rejections", reason="deadline_expired",
                    index_id=r.group[0])
            tracker = self._slos.get(r.group[0])
            if tracker is not None:
                tracker.record(ok=False)  # shed work burns the budget
        done = len(expired)
        if batch:
            self._dispatch(batch, now)
            done += len(batch)
        if obs.is_enabled():
            obs.set_gauge("serve.queue_depth", self.batcher.depth_rows())
        return done

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive :meth:`step` until the queue is empty; returns requests
        completed. The safety valve ``max_steps`` bounds pathological
        loops (it is not a rate limit)."""
        total = 0
        for _ in range(max_steps):
            if not self.batcher.depth_requests():
                break
            total += self.step(force=True)
        return total

    def queue_depth(self) -> int:
        return self.batcher.depth_rows()

    def evict_queued(self) -> List[Request]:
        """Evacuate every queued request without completing its future
        (see :meth:`~raft_tpu.serve.batcher.MicroBatcher.drain_requests`).
        The replica layer calls this when this engine's replica is
        declared dead, then re-queues the evicted work on a healthy
        replica — the queue must not keep rows a failed engine will
        never serve."""
        out = self.batcher.drain_requests()
        if obs.is_enabled():
            obs.set_gauge("serve.queue_depth", self.batcher.depth_rows())
        return out

    # -- SLOs and health ---------------------------------------------------

    def set_slo(
        self,
        index_id: str,
        *,
        latency_ms: Optional[float] = None,
        target: float = 0.999,
        window_s: float = 3600.0,
        fast_window_s: float = 60.0,
        slow_window_s: float = 300.0,
        burn_threshold: float = 10.0,
    ) -> obs.SloTracker:
        """Declare a latency/availability objective for a registered
        index. Every completed request records against it (a request is
        *bad* when it errors, is shed past its deadline, or — with
        ``latency_ms`` set — finishes slower than the threshold,
        measured arrival→completion on the engine clock). The tracker
        shares the engine's injectable clock, so virtual-time tests
        drive burn-rate windows deterministically. Returns the tracker;
        :meth:`health` surfaces its :meth:`~raft_tpu.obs.SloTracker.
        evaluate` snapshot."""
        self._reg(index_id)  # must be registered
        tracker = obs.SloTracker(
            obs.SLO(
                index_id=index_id,
                latency_ms=latency_ms,
                target=target,
                window_s=window_s,
                fast_window_s=fast_window_s,
                slow_window_s=slow_window_s,
                burn_threshold=burn_threshold,
            ),
            clock=self.batcher.now,
        )
        self._slos[index_id] = tracker
        return tracker

    def slo_burn(self, index_id: str) -> Optional[float]:
        """The index's fast-window SLO burn rate right now (None when
        no SLO is declared) — the scalar the replica autoscaler feeds
        its scale-up threshold."""
        tracker = self._slos.get(index_id)
        if tracker is None:
            return None
        return tracker.evaluate().burn_fast

    def health(self) -> Dict[str, object]:
        """Structured health snapshot: queue + cache pressure, span-drop
        signal, and per-index registration state with SLO budget/burn
        status (``docs/serving.md``; the substrate the replicated-serving
        and SLA-adaptive roadmap items read)."""
        cache_stats = self.cache.stats()
        out: Dict[str, object] = {
            "queue": {
                "depth_rows": self.batcher.depth_rows(),
                "depth_requests": self.batcher.depth_requests(),
                "capacity": self.batcher.capacity,
            },
            "cache": {
                "hits": cache_stats.hits,
                "misses": cache_stats.misses,
                "evictions": cache_stats.evictions,
                "size": cache_stats.size,
            },
            "obs": {
                "enabled": obs.is_enabled(),
                "spans_dropped": obs.registry().spans_dropped,
            },
            "indexes": {},
        }
        for index_id, reg in self._indexes.items():
            entry: Dict[str, object] = {
                "algo": reg.algo,
                "mode": reg.mode,
                "generation": max(reg.last_generation, 0),
            }
            tracker = self._slos.get(index_id)
            entry["slo"] = tracker.evaluate().as_dict() if tracker else None
            out["indexes"][index_id] = entry
        return out

    # -- maintenance -------------------------------------------------------

    def maintenance_tick(self) -> None:
        """One watchdog + auto-compaction pass over every registration
        that carries a :class:`~raft_tpu.mutable.Compactor`, followed by
        the planner's drift check (:meth:`_replan_tick`). Driven
        from :meth:`step` (rate-limited by ``maintenance_interval_ms``)
        so serving loops get background maintenance for free; callable
        directly by deployments with their own schedulers."""
        for reg in list(self._indexes.values()):
            if reg.compactor is not None:
                reg.compactor.tick()
        self._replan_tick()
        # flight-recorder sampler tick: retains the serving time series
        # and drains any fault-latched dump; no-op unless a recorder is
        # installed AND obs is enabled
        obs.recorder.tick()

    def shutdown(self, wait: bool = True) -> None:
        """Stop every engine-owned background compactor. Queued
        requests stay queued — this only halts maintenance threads."""
        for reg in self._indexes.values():
            if reg.compactor is not None:
                reg.compactor.stop(wait=wait)

    # -- precompile --------------------------------------------------------

    def warmup(self, index_id: str, k: int, run: bool = True) -> List[ProgramKey]:
        """Build (and with ``run=True`` execute on zero queries, forcing
        the XLA compile) every bucket's program for ``(index_id, k)`` —
        the deploy-time precompile API. Returns the keys warmed."""
        reg = self._reg(index_id)
        snap = reg.index.snapshot() if reg.algo == "mutable" else None
        generation = snap.generation if snap is not None else 0
        keys = [
            ProgramKey(index_id, reg.algo, b, int(k),
                       self._program_params(reg, b), generation)
            for b in bucket_sizes(self.max_batch)
        ]
        built = self.cache.warmup(
            keys, lambda key: (lambda: self._build_program(reg, key.bucket, key.k))
        )
        if run:
            dim = self._index_dim(reg)
            for key in keys:
                prog = self.cache.get(
                    key, lambda: self._build_program(reg, key.bucket, key.k)
                )
                zeros = np.zeros((key.bucket, dim), np.float32)
                out = tuple(prog(zeros, snap) if snap is not None else prog(zeros))
                np.asarray(out[0])  # block until the compile+run completes  # graft-lint: ignore[sync-transfer-in-loop] — warmup exists to block on each compile
        return built

    # -- internals ---------------------------------------------------------

    def _reg(self, index_id: str) -> _Registration:
        expects(index_id in self._indexes, "no index registered as %r", index_id)
        return self._indexes[index_id]

    @staticmethod
    def _index_dim(reg: _Registration) -> int:
        idx = reg.index
        if hasattr(idx, "dim"):
            return int(idx.dim)
        return int(np.asarray(idx.dataset).shape[1])

    def _probe_health_timed(self, reg: _Registration) -> Tuple[bool, ...]:
        """Per-shard health through the ``sharded_ann.shard_scan`` fault
        point, with a latency budget: a probe slower than
        ``slow_shard_s`` marks the shard unhealthy so the query degrades
        coverage instead of waiting out a slow shard (the FusionANNS
        tail-tolerance policy)."""
        mesh, axis, algo = reg.mesh, reg.axis, reg.algo.replace("sharded_", "")
        n_shards = mesh.shape[axis]
        health = []
        for s in range(n_shards):
            t0 = time.perf_counter()
            try:
                faults.fire("sharded_ann.shard_scan", shard=s, algo=algo, axis=axis)
                ok = True
            except ShardFailure:
                obs.inc("robust.shard_failures", algo=algo, shard=str(s))
                ok = False
            if ok and self.slow_shard_s is not None:
                if time.perf_counter() - t0 > self.slow_shard_s:
                    obs.inc("serve.slow_shards", index_id=reg.index_id, shard=str(s))
                    ok = False
            health.append(ok)
        return tuple(health)

    # -- query planning ----------------------------------------------------

    def _tier_label(self, reg: _Registration) -> str:
        """Placement verdict recorded on the plan ("" = unplanned)."""
        if reg.algo in ("tiered", "tiered_sharded"):
            return reg.algo
        if reg.dataset is not None:
            from raft_tpu.neighbors.refine import is_host_dataset

            if is_host_dataset(reg.dataset):
                return "tiered"
        if reg.index_id in self._residencies or reg.index_id in self.sharded_placements:
            return "resident"
        return ""

    @staticmethod
    def _corpus_rows(reg: _Registration) -> int:
        try:
            return int(getattr(reg.index, "size", 0) or 0)
        except (TypeError, ValueError):
            return 0

    def _plan_registration(self, reg: _Registration, k: Optional[int] = None,
                           traffic=None, epoch: int = 0):
        """Cost this registration's full decision set (None = gate off).

        ``fused_ok`` is passed optimistically: a planned ``fused``
        dispatches as ``"auto"`` (see :meth:`_planned_mode`), so the
        search's own kernel-feasibility check remains authoritative and
        the fused→scan/xla degrade contract is preserved."""
        from raft_tpu import plan as _plan

        if not _plan.is_enabled():
            return None
        import jax

        n_shards = reg.mesh.shape[reg.axis] if reg.mesh is not None else 0
        with obs.span("plan.build", index_id=reg.index_id, algo=reg.algo,
                      epoch=epoch):
            return _plan.plan_registration(
                reg.index_id,
                reg.algo,
                buckets=bucket_sizes(self.max_batch),
                corpus_rows=self._corpus_rows(reg),
                on_tpu=jax.default_backend() == "tpu",
                fused_ok=True,
                n_shards=n_shards,
                k=int(k if k is not None else reg.last_k),
                tier=self._tier_label(reg),
                mode_pinned=reg.mode != "auto",
                merge_pinned=reg.merge_mode != "auto",
                traffic=traffic,
                epoch=epoch,
            )

    def _planned_mode(self, reg: _Registration, bucket: int,
                      plan=None) -> Optional[str]:
        """The plan's resolved engine for this bucket (None = dispatch
        on ``reg.mode`` unchanged). A planned ``fused`` is dispatched as
        ``"auto"``: the search re-resolves to fused by the same
        calibration when the kernel is actually feasible, and keeps the
        documented auto-degrade path when it is not."""
        plan = plan if plan is not None else reg.plan
        if plan is None or reg.mode != "auto":
            return None
        m = plan.mode_for(bucket, "")
        if not m:
            return None
        return "auto" if m == "fused" else m

    def _program_params(self, reg: _Registration, bucket: int,
                        plan=None) -> Tuple:
        """Params tuple for the ProgramKey: the registration params plus
        the planned engine when one applies, so a plan flip that changes
        a bucket's engine compiles a distinct program (bounded by
        engines × buckets) and one that does not reuses the cache."""
        pk = params_key(reg.params)
        m = self._planned_mode(reg, bucket, plan=plan)
        if m is not None:
            pk = pk + (("planned_mode", m),)
        return pk

    def plan_explain(self, index_id: str) -> Optional[str]:
        """The active plan's full cost breakdown (None = planner off)."""
        reg = self._reg(index_id)
        return reg.plan.explain() if reg.plan is not None else None

    def _warm_plan(self, reg: _Registration, new_plan) -> List[ProgramKey]:
        """Precompile the new plan's warm buckets BEFORE the swap, so a
        flip never pays an XLA compile on the serving path."""
        if reg.mode != "auto" or not new_plan.bucket_modes:
            return []
        keys = [
            ProgramKey(reg.index_id, reg.algo, b, int(reg.last_k),
                       self._program_params(reg, b, plan=new_plan), 0)
            for b in new_plan.warm_buckets
            if new_plan.mode_for(b, "")
        ]
        if not keys:
            return []
        dim = self._index_dim(reg)
        for key in keys:
            prog = self.cache.get(
                key, lambda: self._build_program(reg, key.bucket, key.k,
                                                 plan=new_plan)
            )
            zeros = np.zeros((key.bucket, dim), np.float32)
            out = tuple(prog(zeros))
            np.asarray(out[0])  # block until the compile+run completes  # graft-lint: ignore[sync-transfer-in-loop] — flip warmup exists to block on each compile
        return keys

    def _replan_tick(self) -> None:
        """Re-cost every planned registration whose corpus/traffic has
        drifted past the hysteresis thresholds; swap the plan atomically
        when a decision changed (``serve.plan_flips``), refresh the
        anchors when not (``serve.plan.recosts``)."""
        from raft_tpu import plan as _plan

        if not _plan.is_enabled():
            return
        for reg in list(self._indexes.values()):
            rp = reg.plan
            if rp is None:
                continue
            traffic = _plan.traffic_from_counts(
                reg.bucket_counts, reg.ewma_rows_per_s)
            rows = self._corpus_rows(reg)
            if not _plan.needs_replan(rp, rows, traffic):
                continue
            new = self._plan_registration(
                reg, k=reg.last_k, traffic=traffic, epoch=rp.epoch + 1)
            if new is None:
                continue
            if rp.same_decisions(new):
                # drift acknowledged, decisions unchanged: re-anchor
                # without burning an epoch (or a compile)
                reg.plan = dataclasses.replace(new, epoch=rp.epoch)
                obs.inc("serve.plan.recosts", index_id=reg.index_id)
                continue
            with obs.span("plan.flip", index_id=reg.index_id,
                          epoch=new.epoch, algo=reg.algo):
                self._warm_plan(reg, new)
                # one assignment: a concurrent dispatch reads the old
                # plan or the new one, never a mix
                reg.plan = new
            reg.bucket_counts = {}
            obs.inc("serve.plan_flips", index_id=reg.index_id)
            obs.set_gauge("serve.plan.epoch", float(new.epoch),
                          index_id=reg.index_id)
            # flight-recorder trigger: the swap is complete and no
            # engine lock is held here
            obs.recorder.note_plan_flip(reg.index_id, int(new.epoch))

    def _build_program(self, reg: _Registration, bucket: int, k: int,
                       plan=None) -> Callable:
        """One dispatchable closure for ``(reg, bucket, k)``; its jitted
        inner search is XLA-cached by the bucket's fixed shape."""
        from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

        kw = reg.search_kwargs
        # the planner's resolved engine for this bucket ("auto" for a
        # planned fused — the search's own feasibility check decides)
        mode = self._planned_mode(reg, bucket, plan=plan) or reg.mode
        if reg.algo == "mutable":
            # the snapshot is NOT baked into the closure — it arrives per
            # dispatch, so a cached program can never serve a stale view
            return lambda q, snap: snap.search(q, k, params=reg.params, **kw)
        if reg.algo == "tiered":
            # "auto" defers to the TieredIndex's own per-family default
            t_mode = None if reg.mode == "auto" else reg.mode
            return lambda q: reg.index.search(q, k, mode=t_mode, **kw)
        if reg.algo == "brute_force":
            return lambda q: brute_force.search(
                reg.index, q, k, query_batch=bucket, mode=reg.mode,
                dataset=reg.dataset, **kw
            )
        if reg.algo == "ivf_flat":
            return lambda q: ivf_flat.search(
                reg.index, q, k, reg.params, query_batch=bucket, mode=mode,
                dataset=reg.dataset, **kw
            )
        if reg.algo == "ivf_pq":
            return lambda q: ivf_pq.search(
                reg.index, q, k, reg.params, query_batch=bucket, mode=mode,
                dataset=reg.dataset, **kw
            )
        if reg.algo == "cagra":
            return lambda q: cagra.search(
                reg.index, q, k, reg.params, query_batch=bucket, mode=mode, **kw
            )
        if reg.algo == "tiered_sharded":
            # the composition path: timed health probe feeds the scan-side
            # mask, tier-side failures are detected in-line by the gather;
            # the index returns a DegradedResult with combined coverage
            def tiered_sharded_prog(q):
                health = self._probe_health_timed(reg)
                return reg.index.search(
                    q, k, health=health, min_coverage=reg.min_coverage,
                    merge_mode=None if reg.merge_mode == "auto" else reg.merge_mode,
                    **kw,
                )

            return tiered_sharded_prog
        # sharded paths ride the degraded-search machinery: per-dispatch
        # timed health probe, failed/slow shards excluded, coverage out
        from raft_tpu.robust.degrade import sharded_search_degraded

        algo = reg.algo.replace("sharded_", "")

        def sharded_prog(q):
            health = self._probe_health_timed(reg)
            return sharded_search_degraded(
                reg.mesh, reg.index, q, k,
                algo=algo, params=reg.params, axis=reg.axis,
                health=health, min_coverage=reg.min_coverage,
                merge_mode=reg.merge_mode, **kw,
            )

        return sharded_prog

    def _dispatch(self, batch: Sequence[Request], now: float) -> None:
        """Pad the batch to its bucket, fetch the compiled program, run
        it, and complete every request's future. A dispatch failure
        fails this batch's futures — typed and visible — and the engine
        keeps serving."""
        reg = self._reg(batch[0].group[0])
        k = batch[0].group[1]
        rows = np.concatenate([r.queries for r in batch], axis=0)
        n = rows.shape[0]
        bucket = bucket_for(n, self.max_batch)
        padded = pad_rows(rows, bucket)
        # one snapshot per micro-batch: every request in the batch sees
        # the same immutable view, and writers never race the dispatch
        snap = reg.index.snapshot() if reg.algo == "mutable" else None
        generation = snap.generation if snap is not None else 0
        if snap is not None:
            # a batch that crosses a background flip lands wholly on one
            # side of it (this snapshot); count the crossing
            if reg.last_generation >= 0 and generation != reg.last_generation:
                obs.inc("serve.generation_flips", index_id=reg.index_id)
            reg.last_generation = generation
        # traffic model inputs: the batch-size histogram and arrival-rate
        # EWMA the re-plan tick measures drift against
        reg.bucket_counts[bucket] = reg.bucket_counts.get(bucket, 0) + 1
        reg.last_k = k
        if reg.last_dispatch_t >= 0.0:
            rate = n / max(now - reg.last_dispatch_t, 1e-6)
            reg.ewma_rows_per_s = 0.25 * rate + 0.75 * reg.ewma_rows_per_s
        reg.last_dispatch_t = now
        key = ProgramKey(
            reg.index_id, reg.algo, bucket, k,
            self._program_params(reg, bucket), generation
        )
        tracker = self._slos.get(reg.index_id)
        # the batch's trace identities ride the dispatch thread: every
        # span recorded below (dispatch, degrade, tiered fetch/refine)
        # is tagged with them; NULL_SCOPE keeps the disabled path free of
        # per-dispatch allocation
        scope = (
            obs.trace_scope(tuple(r.trace_id for r in batch))
            if obs.is_enabled() else obs.NULL_SCOPE
        )
        try:
            program = self.cache.get(
                key, lambda: self._build_program(reg, bucket, k)
            )
            # the chaos seam: one host-level hook before the device work
            faults.fire(
                "serve.dispatch",
                index_id=reg.index_id, algo=reg.algo, bucket=bucket, rows=n,
            )
            t0 = time.perf_counter()
            with scope, obs.span(
                "serve.dispatch", algo=reg.algo, bucket=bucket, rows=n, k=k
            ) as sp:
                out = program(padded, snap) if snap is not None else program(padded)
                sp.sync(tuple(out))
            coverage, degraded, failed = 1.0, False, ()
            if hasattr(out, "coverage"):  # DegradedResult from sharded paths
                coverage, degraded, failed = out.coverage, out.degraded, out.failed_shards
            d_np = np.asarray(out.distances if hasattr(out, "distances") else out[0])
            i_np = np.asarray(out.indices if hasattr(out, "indices") else out[1])
            self.batcher.note_service_time(time.perf_counter() - t0)
        except Exception as e:
            obs.inc("serve.dispatch_errors", index_id=reg.index_id,
                    kind=type(e).__name__)
            for r in batch:
                r.future.set_exception(e)
                if tracker is not None:
                    tracker.record(ok=False)
            return
        if obs.is_enabled():
            obs.inc("serve.batches", index_id=reg.index_id, algo=reg.algo)
            obs.observe("serve.batch_fill", n / bucket)
            obs.observe("serve.batch_rows", float(n))
            # per-index result coverage (1.0 unless a sharded path
            # degraded) — the coverage-drop drift detector's input
            obs.set_gauge("serve.coverage", float(coverage),
                          index_id=reg.index_id)
            if snap is not None:
                obs.set_gauge("serve.generation", float(generation),
                              index_id=reg.index_id)
        t_done = self.batcher.now() if tracker is not None else now
        off = 0
        for r in batch:
            m = r.n_rows
            tiq_ms = (now - r.t_arrival) * 1e3
            if obs.is_enabled():
                obs.observe("serve.time_in_queue_ms", tiq_ms,
                            trace_id=r.trace_id or None)
                if r.trace_id:
                    # synthetic per-request queue span on its own track
                    # (tid derived from req_id): the first hop of the
                    # request's flow chain in the Perfetto export
                    obs.registry().record_span(
                        "serve.queue", r.t_submit_us, max(tiq_ms, 0.0) * 1e3,
                        0x40000000 + (r.req_id % 0x3FFFFFFF), 0,
                        {"index_id": reg.index_id, "rows": m},
                        trace=(r.trace_id,),
                    )
            r.future.set_result(
                ServeResult(
                    distances=d_np[off : off + m],
                    indices=i_np[off : off + m],
                    coverage=float(coverage),
                    degraded=bool(degraded),
                    failed_shards=tuple(failed),
                    time_in_queue_ms=tiq_ms,
                    bucket=bucket,
                    batch_rows=n,
                    generation=generation,
                    trace_id=r.trace_id,
                )
            )
            if tracker is not None:
                tracker.record(latency_ms=(t_done - r.t_arrival) * 1e3)
            off += m
