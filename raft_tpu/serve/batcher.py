"""Bounded request queue with dynamic micro-batching and deadlines.

The batching policy is the classic dynamic-batching tradeoff (Clipper;
TF-Serving's batching layer; FusionANNS' cooperative batching): hold
arrivals until either ``max_batch`` query rows are waiting (the fused
kernels' throughput shape) or the oldest request has waited
``max_wait_ms`` (the latency bound), then flush one micro-batch.

Overload is handled by *typed rejection*, never unbounded latency:

* the queue is **bounded** (``capacity`` query rows) — a full queue
  rejects new work with :class:`QueueFull` at submit time
  (backpressure the caller can act on);
* every request may carry a **deadline**; a request whose deadline is
  already unmeetable at submit time (expired, or provably behind the
  estimated queue drain) is rejected with :class:`DeadlineExceeded`
  up front (admission control — don't queue work you'll throw away);
* a request whose deadline expires while queued is *rejected* at
  batch-formation time — its future completes with
  :class:`DeadlineExceeded`; nothing is ever silently dropped.

The batcher is synchronous and clock-injectable: tests drive it with a
virtual clock, the engine drives it with ``time.monotonic``. No
background thread is required (or started) here.
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.core.errors import RaftError, expects
from raft_tpu.utils import lockcheck


class QueueFull(RaftError):
    """The serving queue is at capacity — backpressure: retry later or
    shed load upstream."""


class DeadlineExceeded(RaftError):
    """The request's deadline cannot be (or was not) met; the request
    was rejected, not silently dropped."""


class ServeFuture:
    """Minimal thread-safe future for one serving request.

    The engine completes it from its (synchronous or threaded) loop;
    callers ``result()``/``exception()`` after driving the loop, or
    block with a timeout when a background driver owns the engine.
    """

    __slots__ = ("_event", "_result", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, result) -> None:
        self._result = result
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise TimeoutError("serve future not completed")
        return self._exc

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve future not completed")
        if self._exc is not None:
            raise self._exc
        return self._result


_req_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One enqueued search request: ``queries`` [m, dim] rows against a
    registered index, due by ``deadline_s`` (absolute clock time, None =
    no deadline)."""

    queries: np.ndarray
    k: int
    group: Tuple  # requests batch together only within one group key
    t_arrival: float
    deadline_s: Optional[float] = None
    req_id: int = dataclasses.field(default_factory=lambda: next(_req_ids))
    future: ServeFuture = dataclasses.field(default_factory=ServeFuture)
    #: obs request trace ("" when the gate is off — nothing else is
    #: allocated on the disabled path); t_submit_us is the registry
    #: trace-clock stamp the synthetic ``serve.queue`` span starts at
    trace_id: str = ""
    t_submit_us: float = 0.0

    @property
    def n_rows(self) -> int:
        return int(self.queries.shape[0])

    def expired(self, now: float) -> bool:
        return self.deadline_s is not None and now > self.deadline_s


@lockcheck.guarded_fields
class MicroBatcher:
    """Bounded FIFO of :class:`Request` s with flush-on-size /
    flush-on-age batching and deadline-aware admission.

    ``capacity`` bounds total queued *query rows* (the resource that
    costs memory and compute), not request count. The service-time
    EWMA (fed by the engine via :meth:`note_service_time`) powers the
    admission estimate: a request whose deadline falls before
    ``now + queued_batches_ahead * ewma_service_s`` is rejected up
    front rather than queued to die.
    """

    def __init__(
        self,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        capacity: int = 1024,
        clock: Callable[[], float] = None,
    ):
        expects(max_batch >= 1, "max_batch must be >= 1")
        expects(capacity >= max_batch, "capacity %d < max_batch %d", capacity, max_batch)
        expects(max_wait_ms >= 0.0, "max_wait_ms must be >= 0")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.capacity = int(capacity)
        import time as _time

        self._clock = clock if clock is not None else _time.monotonic
        self._lock = lockcheck.tracked(threading.RLock(), "serve.batcher")
        # bound documents itself; offer() rejects before append so the
        # maxlen silent-drop semantics can never engage
        self._queue: "deque[Request]" = deque(maxlen=self.capacity)
        self._rows = 0
        self._ewma_service_s = 0.0

    # -- admission ---------------------------------------------------------

    def now(self) -> float:
        return self._clock()

    def depth_rows(self) -> int:
        with self._lock:
            return self._rows

    def depth_requests(self) -> int:
        with self._lock:
            return len(self._queue)

    def note_service_time(self, seconds: float, alpha: float = 0.25) -> None:
        """Feed one observed batch service time into the admission EWMA."""
        with self._lock:
            if self._ewma_service_s == 0.0:
                self._ewma_service_s = float(seconds)
            else:
                self._ewma_service_s += alpha * (float(seconds) - self._ewma_service_s)

    def estimated_wait_s(self) -> float:
        """Pessimistic time for a new arrival to clear the current
        queue: batches ahead of it times the service-time EWMA. Zero
        until the engine has reported at least one service time."""
        with self._lock:
            if self._ewma_service_s == 0.0:
                return 0.0
            batches_ahead = 1 + self._rows // self.max_batch
            return batches_ahead * self._ewma_service_s

    def offer(self, req: Request) -> None:
        """Admit ``req`` or raise a typed rejection.

        :class:`QueueFull` when the row bound is hit;
        :class:`DeadlineExceeded` when the deadline is already past or
        provably behind the estimated queue drain.
        """
        now = self.now()
        if req.expired(now):
            raise DeadlineExceeded(
                f"request {req.req_id} dead on arrival "
                f"(deadline {req.deadline_s:.4f} < now {now:.4f})"
            )
        if req.deadline_s is not None:
            est = self.estimated_wait_s()
            if est > 0.0 and now + est > req.deadline_s:
                raise DeadlineExceeded(
                    f"request {req.req_id} unmeetable: estimated queue wait "
                    f"{est * 1e3:.2f} ms overruns the deadline"
                )
        with self._lock:
            if self._rows + req.n_rows > self.capacity:
                raise QueueFull(
                    f"serving queue at capacity ({self._rows}/{self.capacity} "
                    f"query rows); request {req.req_id} rejected"
                )
            self._queue.append(req)
            self._rows += req.n_rows

    # -- batch formation ---------------------------------------------------

    def ready(self, now: Optional[float] = None) -> bool:
        """True when a micro-batch should flush: a full ``max_batch``
        rows are queued for some group, or the oldest request has aged
        past ``max_wait_ms`` (expired requests age instantly)."""
        if now is None:
            now = self.now()
        with self._lock:
            if not self._queue:
                return False
            oldest = self._queue[0]
            if now - oldest.t_arrival >= self.max_wait_s or oldest.expired(now):
                return True
            rows_by_group: Dict[Tuple, int] = {}
            for r in self._queue:
                rows_by_group[r.group] = rows_by_group.get(r.group, 0) + r.n_rows
                if rows_by_group[r.group] >= self.max_batch:
                    return True
            return False

    def next_batch(
        self, now: Optional[float] = None
    ) -> Tuple[List[Request], List[Request]]:
        """Form the next micro-batch.

        Returns ``(batch, expired)``: ``batch`` is the oldest-first run
        of same-group requests totalling at most ``max_batch`` rows;
        ``expired`` are requests whose deadline passed while queued —
        already failed with :class:`DeadlineExceeded` on their futures,
        returned so the caller can count the rejections. Both lists are
        empty only when the queue is empty.
        """
        if now is None:
            now = self.now()
        expired: List[Request] = []
        batch: List[Request] = []
        with self._lock:
            # reject the dead first so they can't poison batch formation
            alive: "deque[Request]" = deque(maxlen=self.capacity)
            for r in self._queue:
                if r.expired(now):
                    expired.append(r)
                    self._rows -= r.n_rows
                else:
                    alive.append(r)
            self._queue = alive
            if self._queue:
                group = self._queue[0].group
                rows = 0
                keep: "deque[Request]" = deque(maxlen=self.capacity)
                for r in self._queue:
                    if r.group == group and rows + r.n_rows <= self.max_batch:
                        batch.append(r)
                        rows += r.n_rows
                    else:
                        keep.append(r)
                self._queue = keep
                self._rows -= rows
        for r in expired:
            r.future.set_exception(
                DeadlineExceeded(
                    f"request {r.req_id} expired in queue "
                    f"(waited {(now - r.t_arrival) * 1e3:.2f} ms)"
                )
            )
        return batch, expired

    def drain_requests(self) -> List[Request]:
        """Remove and return every queued request WITHOUT completing
        their futures. Replica failover (:mod:`raft_tpu.replica`) uses
        this to evacuate a dead replica's queue: the requests are
        re-submitted on a healthy engine and their *group*-level futures
        complete there — the engine-level futures drained here are
        intentionally abandoned."""
        with self._lock:
            out = list(self._queue)
            self._queue = deque(maxlen=self.capacity)
            self._rows = 0
        return out

    def drain_expired(self, now: Optional[float] = None) -> List[Request]:
        """Reject (only) the expired requests without forming a batch."""
        if now is None:
            now = self.now()
        expired: List[Request] = []
        with self._lock:
            alive: "deque[Request]" = deque(maxlen=self.capacity)
            for r in self._queue:
                if r.expired(now):
                    expired.append(r)
                    self._rows -= r.n_rows
                else:
                    alive.append(r)
            self._queue = alive
        for r in expired:
            r.future.set_exception(
                DeadlineExceeded(
                    f"request {r.req_id} expired in queue "
                    f"(waited {(now - r.t_arrival) * 1e3:.2f} ms)"
                )
            )
        return expired
