"""Shape-bucket policy + compiled-program cache for online serving.

Every distinct query-batch shape dispatched to XLA is a distinct
compiled program; a serving layer that forwards arrival-sized batches
verbatim compiles an unbounded program population and pays a multi-
second XLA compile on every new size — the latency cliff FusionANNS
avoids on GPUs by cooperative batching and that TPUs make strictly
worse (recompiles are remote and tens of seconds on real pods).

The fix is a *closed* shape vocabulary: query counts are rounded up to
power-of-two **buckets** (1, 2, 4, ..., ``max_batch``), requests are
padded to the bucket and un-padded on the way out, so the engine only
ever dispatches ``log2(max_batch) + 1`` shapes per
``(index, algo, k, params)`` configuration. :class:`ProgramCache` is
the LRU cache of those dispatchable programs keyed by
:class:`ProgramKey`; its stats are the serving layer's compile-storm
alarm (``tests/test_serve.py`` pins ``misses <= len(bucket_sizes)``
under a randomized arrival stream) and its :meth:`ProgramCache.warmup`
hook is how deployments pre-compile the whole vocabulary before taking
traffic.
"""
from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.core.errors import expects
from raft_tpu.utils import lockcheck


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """The closed set of dispatchable query counts: powers of two up to
    (and including) ``max_batch``, which is rounded up if needed."""
    expects(max_batch >= 1, "max_batch must be >= 1, got %d", max_batch)
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b <<= 1
    out.append(b)
    return tuple(out)


def bucket_for(n_queries: int, max_batch: int) -> int:
    """Smallest bucket holding ``n_queries`` rows (<= ``max_batch``)."""
    expects(n_queries >= 1, "n_queries must be >= 1, got %d", n_queries)
    expects(
        n_queries <= max_batch,
        "n_queries %d exceeds max_batch %d — split the batch first",
        n_queries, max_batch,
    )
    b = 1
    while b < n_queries:
        b <<= 1
    return b


def pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``arr`` [n, ...] to ``bucket`` rows (no-op when full)."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    expects(n < bucket, "rows %d exceed bucket %d", n, bucket)
    pad = [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def unpad_rows(arr, n: int):
    """Strip bucket padding back to the ``n`` real rows."""
    return arr[:n]


def params_key(params) -> Tuple:
    """A hashable identity for a search-params dataclass (or None).

    Field order is the dataclass's own; values that aren't hashable
    (e.g. dtype objects) are keyed by ``str()``. Two params with equal
    keys compile to the same program for a given shape.
    """
    if params is None:
        return ()
    if dataclasses.is_dataclass(params):
        items = []
        for f in dataclasses.fields(params):
            v = getattr(params, f.name)
            try:
                hash(v)
            except TypeError:
                v = str(v)
            items.append((f.name, v))
        return (type(params).__name__,) + tuple(items)
    return (str(params),)


@dataclasses.dataclass(frozen=True)
class ProgramKey:
    """Identity of one compiled serving program: which index, which
    engine, which padded shape, which k, which knobs."""

    index_id: str
    algo: str
    bucket: int
    k: int
    params: Tuple = ()
    #: mutable-index generation the program was compiled against; 0 for
    #: immutable registrations. Bumping it on compaction retires stale
    #: programs via LRU instead of serving against a dead snapshot, and
    #: bounds distinct programs to generations × buckets per config.
    generation: int = 0


@dataclasses.dataclass(frozen=True)
class CacheStats:
    """Snapshot of :class:`ProgramCache` counters."""

    hits: int
    misses: int
    evictions: int
    size: int

    @property
    def distinct_programs(self) -> int:
        """Programs built over the cache's lifetime (== compile count
        when every builder compiles exactly one program)."""
        return self.misses


@lockcheck.guarded_fields
class ProgramCache:
    """LRU cache of dispatchable search programs keyed by
    :class:`ProgramKey`.

    A "program" is whatever the builder returns — here, a host callable
    closed over one ``(index, algo, bucket, k, params)`` configuration
    whose jitted inner function XLA caches by the bucket's fixed shape.
    The LRU bound caps host-side closure count; evicting does NOT evict
    XLA's own compile cache, so a re-miss on an evicted key re-builds
    the closure cheaply and re-uses the compiled executable.
    """

    def __init__(self, capacity: int = 64):
        expects(capacity >= 1, "capacity must be >= 1, got %d", capacity)
        self.capacity = capacity
        self._lock = lockcheck.tracked(
            threading.RLock(), "serve.program_cache"
        )
        self._programs: "OrderedDict[ProgramKey, Callable]" = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key: ProgramKey, builder: Callable[[], Callable]) -> Callable:
        """Return the cached program for ``key``, building (and counting
        a miss) on first use; refreshes LRU recency on hits."""
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._hits += 1
                self._programs.move_to_end(key)
                return prog
            self._misses += 1
        # build outside the lock: builders may trigger long XLA compiles
        prog = builder()
        with self._lock:
            self._programs[key] = prog
            self._programs.move_to_end(key)
            while len(self._programs) > self.capacity:
                self._programs.popitem(last=False)
                self._evictions += 1
        return prog

    def warmup(
        self,
        keys: Sequence[ProgramKey],
        builder_for: Callable[[ProgramKey], Callable[[], Callable]],
    ) -> List[ProgramKey]:
        """Pre-populate programs for ``keys`` (the precompile API);
        returns the keys that were actually built (not already cached)."""
        built = []
        for key in keys:
            with self._lock:
                cached = key in self._programs
            if not cached:
                built.append(key)
            self.get(key, builder_for(key))
        return built

    def __contains__(self, key: ProgramKey) -> bool:
        with self._lock:
            return key in self._programs

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def keys(self) -> List[ProgramKey]:
        with self._lock:
            return list(self._programs.keys())

    def stats(self) -> CacheStats:
        with self._lock:
            return CacheStats(
                hits=self._hits,
                misses=self._misses,
                evictions=self._evictions,
                size=len(self._programs),
            )

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()
