"""ANN recall metric — analog of ``raft::stats::neighborhood_recall``
(``stats/neighborhood_recall.cuh:35-62``).

Recall = fraction of (query, rank) pairs whose returned index appears in the
query's ground-truth top-k (order-insensitive), optionally also accepting
distance ties within ``eps`` (the reference's distance-match fallback for
equal-distance neighbors).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def neighborhood_recall(
    indices,
    ref_indices,
    distances: Optional[jax.Array] = None,
    ref_distances: Optional[jax.Array] = None,
    eps: float = 1e-3,
) -> jax.Array:
    """Compute recall of ``indices`` [n_queries, k] against ``ref_indices``.

    When distances are supplied, a non-matching id still counts if its
    distance matches any ground-truth distance within ``eps`` (handles
    equal-distance permutations, mirroring the reference's check).
    Returns a scalar f32 in [0, 1].
    """
    indices = jnp.asarray(indices)
    ref_indices = jnp.asarray(ref_indices)
    assert indices.shape == ref_indices.shape, "indices/ref shape mismatch"
    id_match = (indices[:, :, None] == ref_indices[:, None, :]).any(axis=2)
    if distances is not None and ref_distances is not None:
        distances = jnp.asarray(distances)
        ref_distances = jnp.asarray(ref_distances)
        dist_match = (
            jnp.abs(distances[:, :, None] - ref_distances[:, None, :]) < eps
        ).any(axis=2)
        id_match = id_match | dist_match
    return jnp.mean(id_match.astype(jnp.float32))
