"""Summary statistics — analog of ``raft/stats/{mean,stddev,sum,cov,
minmax,histogram,meanvar,weighted_mean,mean_center}.cuh``.

Thin, shape-checked jnp compositions: on TPU these are single fused VPU
reductions; the value added over raw jnp is the reference's API surface
(row/col orientation flags, sample vs population semantics) and jit-safety.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.errors import expects


def _axis(along_rows: bool) -> int:
    # along_rows=True reduces over the row axis (per-column stats), matching
    # the reference's rowMajor/alongRows conventions.
    return 0 if along_rows else 1


def mean(x, along_rows: bool = True) -> jax.Array:
    """``raft::stats::mean`` (``stats/mean.cuh``)."""
    return jnp.mean(jnp.asarray(x, jnp.float32), axis=_axis(along_rows))


def sum_(x, along_rows: bool = True) -> jax.Array:
    """``raft::stats::sum`` (``stats/sum.cuh``)."""
    return jnp.sum(jnp.asarray(x, jnp.float32), axis=_axis(along_rows))


def stddev(x, sample: bool = False, along_rows: bool = True) -> jax.Array:
    """``raft::stats::stddev`` (``stats/stddev.cuh``); ``sample`` selects
    the n-1 denominator."""
    x = jnp.asarray(x, jnp.float32)
    return jnp.std(x, axis=_axis(along_rows), ddof=1 if sample else 0)


def meanvar(x, sample: bool = False, along_rows: bool = True) -> Tuple[jax.Array, jax.Array]:
    """``raft::stats::meanvar`` (``stats/meanvar.cuh``)."""
    x = jnp.asarray(x, jnp.float32)
    ax = _axis(along_rows)
    return jnp.mean(x, axis=ax), jnp.var(x, axis=ax, ddof=1 if sample else 0)


def mean_center(x, mu: Optional[jax.Array] = None, along_rows: bool = True) -> jax.Array:
    """``raft::stats::mean_center`` (``stats/mean_center.cuh``)."""
    x = jnp.asarray(x, jnp.float32)
    if mu is None:
        mu = mean(x, along_rows)
    return x - (mu[None, :] if along_rows else mu[:, None])


def mean_add(x, mu: jax.Array, along_rows: bool = True) -> jax.Array:
    """``raft::stats::mean_add`` (``stats/mean_center.cuh``)."""
    x = jnp.asarray(x, jnp.float32)
    return x + (mu[None, :] if along_rows else mu[:, None])


def cov(x, mu: Optional[jax.Array] = None, sample: bool = True, stable: bool = True) -> jax.Array:
    """Covariance of columns (``raft::stats::cov``, ``stats/cov.cuh``):
    [d, d] from [n, d] data. ``sample`` selects the n-1 denominator;
    ``stable=False`` uses the reference's single-pass
    ``E[xxᵀ] - n·μμᵀ`` form (one fewer pass, more cancellation error)."""
    x = jnp.asarray(x, jnp.float32)
    expects(x.ndim == 2, "cov expects [n, d]")
    n = x.shape[0]
    if mu is None:
        mu = jnp.mean(x, axis=0)
    denom = max(n - 1, 1) if sample else n
    if stable:
        xc = x - mu[None, :]
        return (xc.T @ xc) / denom
    return (x.T @ x - n * jnp.outer(mu, mu)) / denom


def weighted_mean(x, weights, along_rows: bool = True) -> jax.Array:
    """``raft::stats::weighted_mean`` (``stats/weighted_mean.cuh``)."""
    x = jnp.asarray(x, jnp.float32)
    w = jnp.asarray(weights, jnp.float32)
    ax = _axis(along_rows)
    wb = w[:, None] if ax == 0 else w[None, :]
    return jnp.sum(x * wb, axis=ax) / jnp.maximum(jnp.sum(w), 1e-30)


def minmax(x, along_rows: bool = True) -> Tuple[jax.Array, jax.Array]:
    """``raft::stats::minmax`` (``stats/minmax.cuh``)."""
    x = jnp.asarray(x)
    ax = _axis(along_rows)
    return jnp.min(x, axis=ax), jnp.max(x, axis=ax)


def histogram(x, n_bins: int, lower: float, upper: float) -> jax.Array:
    """Fixed-width histogram per column (``raft::stats::histogram``,
    ``stats/histogram.cuh`` HistTypeAuto semantics): [n_bins, d] counts."""
    x = jnp.asarray(x, jnp.float32)
    expects(x.ndim == 2, "histogram expects [n, d]")
    expects(upper > lower, "upper must exceed lower")
    d = x.shape[1]
    width = (upper - lower) / n_bins
    bins = jnp.clip(((x - lower) / width).astype(jnp.int32), 0, n_bins - 1)
    inside = (x >= lower) & (x < upper)
    # scatter-add into d*n_bins segments — O(n*d) work, no dense one-hot
    flat = (bins + jnp.arange(d, dtype=jnp.int32)[None, :] * n_bins).reshape(-1)
    counts = jax.ops.segment_sum(
        inside.reshape(-1).astype(jnp.int32), flat, num_segments=d * n_bins
    )
    return counts.reshape(d, n_bins).T  # [n_bins, d]
