"""Model metrics — analog of ``raft/stats/{accuracy,r2_score,
regression_metrics,contingency_matrix,adjusted_rand_index,rand_index,
entropy,mutual_info_score,homogeneity_score,completeness_score,v_measure,
kl_divergence,silhouette_score,dispersion,information_criterion,
trustworthiness_score}.cuh``.

Label-pair metrics route through one contingency matrix built as a
segment-sum scatter (``stats/detail/contingencyMatrix.cuh`` builds the same
table with atomics); everything downstream is a handful of VPU reductions.
"""
from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, pairwise_distance


def accuracy(predictions, ref_predictions) -> jax.Array:
    """``raft::stats::accuracy`` (``stats/accuracy.cuh``)."""
    p = jnp.asarray(predictions)
    r = jnp.asarray(ref_predictions)
    expects(p.shape == r.shape, "shape mismatch")
    return jnp.mean((p == r).astype(jnp.float32))


def r2_score(y, y_hat) -> jax.Array:
    """``raft::stats::r2_score`` (``stats/r2_score.cuh``)."""
    y = jnp.asarray(y, jnp.float32)
    y_hat = jnp.asarray(y_hat, jnp.float32)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / ss_tot


def regression_metrics(predictions, ref) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(mean_abs_error, mean_squared_error, median_abs_error)
    (``stats/regression_metrics.cuh``)."""
    p = jnp.asarray(predictions, jnp.float32)
    r = jnp.asarray(ref, jnp.float32)
    err = jnp.abs(p - r)
    return jnp.mean(err), jnp.mean(err * err), jnp.median(err)


def contingency_matrix(y_true, y_pred, n_classes: Optional[int] = None) -> jax.Array:
    """[n_classes, n_classes] label co-occurrence counts
    (``stats/contingency_matrix.cuh``). Labels must be in [0, n_classes)."""
    t = jnp.asarray(y_true, jnp.int32)
    p = jnp.asarray(y_pred, jnp.int32)
    expects(t.shape == p.shape and t.ndim == 1, "labels must be matching 1-D")
    if n_classes is None:
        n_classes = int(jnp.maximum(jnp.max(t), jnp.max(p))) + 1
    flat = t * n_classes + p
    counts = jax.ops.segment_sum(
        jnp.ones_like(flat, jnp.float32), flat, num_segments=n_classes * n_classes
    )
    return counts.reshape(n_classes, n_classes)


def rand_index(y_true, y_pred) -> jax.Array:
    """``raft::stats::rand_index`` (``stats/rand_index.cuh``)."""
    c = contingency_matrix(y_true, y_pred)
    n = jnp.sum(c)
    sum_comb_c = jnp.sum(c * (c - 1)) / 2.0
    a = jnp.sum(c, axis=1)
    b = jnp.sum(c, axis=0)
    sum_comb_a = jnp.sum(a * (a - 1)) / 2.0
    sum_comb_b = jnp.sum(b * (b - 1)) / 2.0
    total = n * (n - 1) / 2.0
    agree = sum_comb_c + (total - sum_comb_a - sum_comb_b + sum_comb_c)
    return agree / total


def adjusted_rand_index(y_true, y_pred) -> jax.Array:
    """``raft::stats::adjusted_rand_index``
    (``stats/adjusted_rand_index.cuh``)."""
    c = contingency_matrix(y_true, y_pred)
    n = jnp.sum(c)
    sum_comb = jnp.sum(c * (c - 1)) / 2.0
    a = jnp.sum(c, axis=1)
    b = jnp.sum(c, axis=0)
    comb_a = jnp.sum(a * (a - 1)) / 2.0
    comb_b = jnp.sum(b * (b - 1)) / 2.0
    total = n * (n - 1) / 2.0
    expected = comb_a * comb_b / jnp.maximum(total, 1.0)
    max_index = 0.5 * (comb_a + comb_b)
    return (sum_comb - expected) / jnp.maximum(max_index - expected, 1e-30)


def entropy(labels, n_classes: Optional[int] = None) -> jax.Array:
    """Shannon entropy of a label vector in nats
    (``stats/entropy.cuh``)."""
    y = jnp.asarray(labels, jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.max(y)) + 1
    counts = jax.ops.segment_sum(jnp.ones_like(y, jnp.float32), y, num_segments=n_classes)
    p = counts / jnp.maximum(jnp.sum(counts), 1.0)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.where(p > 0, p, 1.0)), 0.0))


def mutual_info_score(y_true, y_pred, n_classes: Optional[int] = None) -> jax.Array:
    """``raft::stats::mutual_info_score`` (``stats/mutual_info_score.cuh``)."""
    c = contingency_matrix(y_true, y_pred, n_classes)
    n = jnp.maximum(jnp.sum(c), 1.0)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    denom = pi * pj
    ratio = jnp.where((pij > 0) & (denom > 0), pij / jnp.where(denom > 0, denom, 1.0), 1.0)
    return jnp.sum(jnp.where(pij > 0, pij * jnp.log(ratio), 0.0))


def homogeneity_score(y_true, y_pred, n_classes: Optional[int] = None) -> jax.Array:
    """``raft::stats::homogeneity_score``
    (``stats/homogeneity_score.cuh``): MI / H(true)."""
    mi = mutual_info_score(y_true, y_pred, n_classes)
    h = entropy(y_true, n_classes)
    return jnp.where(h == 0, 1.0, mi / jnp.where(h == 0, 1.0, h))


def completeness_score(y_true, y_pred, n_classes: Optional[int] = None) -> jax.Array:
    """``raft::stats::completeness_score``
    (``stats/completeness_score.cuh``): MI / H(pred)."""
    mi = mutual_info_score(y_true, y_pred, n_classes)
    h = entropy(y_pred, n_classes)
    return jnp.where(h == 0, 1.0, mi / jnp.where(h == 0, 1.0, h))


def v_measure(y_true, y_pred, n_classes: Optional[int] = None, beta: float = 1.0) -> jax.Array:
    """``raft::stats::v_measure`` (``stats/v_measure.cuh``)."""
    h = homogeneity_score(y_true, y_pred, n_classes)
    c = completeness_score(y_true, y_pred, n_classes)
    denom = beta * h + c
    return jnp.where(denom == 0, 0.0, (1.0 + beta) * h * c / jnp.where(denom == 0, 1.0, denom))


def kl_divergence(p, q) -> jax.Array:
    """``raft::stats::kl_divergence`` (``stats/kl_divergence.cuh``)."""
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    ratio = jnp.where((p > 0) & (q > 0), p / jnp.where(q > 0, q, 1.0), 1.0)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(ratio), 0.0))


def silhouette_score(X, labels, n_clusters: Optional[int] = None, chunk: int = 2048) -> jax.Array:
    """Mean silhouette coefficient (``stats/silhouette_score.cuh``; the
    batched variant mirrors ``batched_silhouette_score``): per-sample
    (b - a) / max(a, b) using mean intra/inter-cluster distances, computed
    from chunked pairwise distances + a cluster-sum matmul."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(labels, jnp.int32)
    n = X.shape[0]
    if n_clusters is None:
        n_clusters = int(jnp.max(y)) + 1
    onehot = jax.nn.one_hot(y, n_clusters, dtype=jnp.float32)  # [n, k]
    counts = jnp.sum(onehot, axis=0)  # [k]

    scores = []
    for s in range(0, n, chunk):
        xc = X[s : s + chunk]
        yc = y[s : s + chunk]
        d = pairwise_distance(xc, X, DistanceType.L2SqrtExpanded)  # [c, n]
        sums = d @ onehot  # [c, k] total distance to each cluster
        own = counts[yc]  # [c]
        row = jnp.arange(xc.shape[0])
        a = sums[row, yc] / jnp.maximum(own - 1.0, 1.0)
        mean_other = sums / jnp.maximum(counts[None, :], 1.0)
        mean_other = mean_other.at[row, yc].set(jnp.inf)
        b = jnp.min(mean_other, axis=1)
        sil = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
        scores.append(sil)
    return jnp.mean(jnp.concatenate(scores))


def dispersion(centroids, cluster_sizes, global_centroid=None) -> jax.Array:
    """Between-cluster dispersion (``stats/dispersion.cuh``): sqrt of the
    size-weighted squared distances of centroids to the global centroid."""
    c = jnp.asarray(centroids, jnp.float32)
    sizes = jnp.asarray(cluster_sizes, jnp.float32)
    if global_centroid is None:
        global_centroid = jnp.sum(c * sizes[:, None], axis=0) / jnp.maximum(jnp.sum(sizes), 1.0)
    d2 = jnp.sum((c - global_centroid[None, :]) ** 2, axis=1)
    return jnp.sqrt(jnp.sum(sizes * d2))


class CriterionType(enum.IntEnum):
    """``batched::linalg::detail::ic_type`` analog
    (``stats/information_criterion.cuh``)."""

    AIC = 0
    AICc = 1
    BIC = 2


def information_criterion(
    log_likelihood, criterion: CriterionType, n_params: int, n_samples: int
) -> jax.Array:
    """``raft::stats::information_criterion_batched``
    (``stats/information_criterion.cuh``)."""
    ll = jnp.asarray(log_likelihood, jnp.float32)
    base = -2.0 * ll
    if criterion == CriterionType.AIC:
        return base + 2.0 * n_params
    if criterion == CriterionType.AICc:
        corr = 2.0 * n_params * (n_params + 1) / max(n_samples - n_params - 1, 1)
        return base + 2.0 * n_params + corr
    return base + n_params * jnp.log(jnp.float32(n_samples))


def trustworthiness_score(X, X_embedded, n_neighbors: int = 5, chunk: int = 2048) -> jax.Array:
    """Embedding trustworthiness (``stats/trustworthiness_score.cuh``):
    penalizes embedded-space neighbors that are far in the original space."""
    from raft_tpu.ops.select_k import select_k

    X = jnp.asarray(X, jnp.float32)
    E = jnp.asarray(X_embedded, jnp.float32)
    n = X.shape[0]
    k = n_neighbors
    expects(k < n, "n_neighbors must be < n_samples")

    penalties = []
    for s in range(0, n, chunk):
        d_orig = pairwise_distance(X[s : s + chunk], X, DistanceType.L2Expanded)
        d_emb = pairwise_distance(E[s : s + chunk], E, DistanceType.L2Expanded)
        row = jnp.arange(d_orig.shape[0])
        # rank of every sample in original space (0 = self)
        orig_order = jnp.argsort(d_orig, axis=1)
        ranks = jnp.zeros_like(orig_order).at[row[:, None], orig_order].set(
            jnp.broadcast_to(jnp.arange(n), orig_order.shape)
        )
        d_emb = d_emb.at[row, s + row].set(jnp.inf)  # exclude self
        _, nbrs = select_k(d_emb, k, select_min=True)
        r = jnp.take_along_axis(ranks, nbrs, axis=1)  # original-space ranks
        penalties.append(jnp.sum(jnp.maximum(r - k, 0).astype(jnp.float32)))
    t = jnp.sum(jnp.stack(penalties))
    norm = 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0))
    return 1.0 - norm * t
