"""Statistics layer (L4 analog): summary stats + model/ANN metrics.

See ``SURVEY.md`` §2.3 (``/root/reference/cpp/include/raft/stats``).
"""
from raft_tpu.stats.recall import neighborhood_recall

__all__ = ["neighborhood_recall"]
