"""CLI entry — the ``raft-ann-bench`` run orchestration
(``python/raft-ann-bench/src/raft_ann_bench/run/__main__.py:141`` analog).

Examples::

    python -m raft_tpu.bench --dataset smoke-10k --algos raft_ivf_flat --group smoke
    python -m raft_tpu.bench --dataset sift-128-euclidean --algos raft_ivf_flat,raft_cagra \
        --k 10 --batch 1024 --out results.json
"""
from __future__ import annotations

import argparse
import json

from raft_tpu.bench import configs, datasets, harness


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("raft_tpu.bench")
    ap.add_argument("--dataset", default="smoke-10k")
    ap.add_argument("--algos", default="raft_brute_force,raft_ivf_flat,raft_ivf_pq,raft_cagra")
    ap.add_argument("--group", default="base", choices=sorted(configs.GROUPS))
    ap.add_argument("-k", "--k", type=int, default=10)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--min-recall", type=float, default=0.95)
    ap.add_argument("--min-search-time", type=float, default=2.0)
    ap.add_argument("--out", default=None, help="write gbench-style JSON report here")
    ap.add_argument("--csv-out", default=None, help="also export results as CSV (data_export)")
    ap.add_argument("--plot-out", default=None, help="also render the recall-QPS plot (PNG)")
    args = ap.parse_args(argv)

    ds = datasets.get_dataset(args.dataset)
    print(f"# dataset {ds.name}: n={ds.n} dim={ds.dim} nq={ds.queries.shape[0]} metric={ds.metric}")

    all_results = []
    for algo in args.algos.split(","):
        algo = algo.strip()
        grids = configs.GROUPS[args.group][algo]
        res = harness.sweep(
            ds,
            algo,
            grids["build"],
            grids["search"],
            k=args.k,
            batch=args.batch,
            min_search_time=args.min_search_time,
            constraint=configs.constraint(algo),
        )
        all_results.extend(res)
        op = harness.operating_point(res, args.min_recall)
        if op:
            print(
                f"## {algo} @ recall>={args.min_recall}: {op.qps:,.0f} qps "
                f"(recall={op.recall:.4f}, {harness._fmt(op.search_params)})"
            )
        else:
            print(f"## {algo}: no config reached recall {args.min_recall}")

    if args.out:
        harness.save_report(all_results, args.out)
        print(f"# wrote {args.out}")
    else:
        print(json.dumps([r.to_json() for r in harness.pareto_frontier(all_results)], indent=2))
    if args.csv_out:
        from raft_tpu.bench.data_export import export_results_csv

        print(f"# wrote {export_results_csv(all_results, args.csv_out)}")
    if args.plot_out:
        from raft_tpu.bench.plot import plot_results

        print(f"# wrote {plot_results(all_results, args.plot_out)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
