"""ANN benchmark harness — the L8 layer (SURVEY.md §2.8).

Re-implements the reference's algorithm-agnostic bench in Python/JAX:

* abstract build/search adapter per algorithm — the ``ANN<T>`` interface
  (``cpp/bench/ann/src/common/ann_types.hpp:74,116``),
* timed build and search loops with warmup, recall computed **in-harness**
  against cached exact ground truth, QPS/latency counters
  (``cpp/bench/ann/src/common/benchmark.hpp:120,175,379``),
* the gbench-compatible JSON result schema (``items_per_second``,
  ``Recall``, ``Latency``, ``end_to_end``, ``total_queries`` —
  ``benchmark.hpp:330-385``) so the reference's data_export/plot tooling
  ports directly,
* param-grid sweeps + recall-constrained operating-point selection — the
  orchestration of ``python/raft-ann-bench/src/raft_ann_bench/run/__main__.py:141``
  with the ``run/conf/algos/*.yaml`` grid semantics.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.bench.datasets import Dataset
from raft_tpu.core import serialize

# ---------------------------------------------------------------------------
# algorithm adapters (ann_types.hpp:74 ANN<T>::build / ::search analog)
# ---------------------------------------------------------------------------


def _metric_of(ds: Dataset):
    from raft_tpu.ops.distance import DistanceType

    return DistanceType.InnerProduct if ds.metric == "inner_product" else DistanceType.L2Expanded


def _build_brute_force(ds: Dataset, p: Dict[str, Any]):
    from raft_tpu.neighbors import brute_force

    return brute_force.build(ds.base, metric=_metric_of(ds))


def _search_brute_force(index, queries, k: int, p: Dict[str, Any], batch: int):
    from raft_tpu.neighbors import brute_force

    return brute_force.search(
        index,
        queries,
        k,
        query_batch=batch,
        mode=p.get("mode", "exact"),
        recall_target=p.get("recall_target", 0.99),
    )


def _build_ivf_flat(ds: Dataset, p: Dict[str, Any]):
    import dataclasses

    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_flat

    index = ivf_flat.build(
        ds.base,
        ivf_flat.IvfFlatIndexParams(
            n_lists=p.get("nlist", 1024),
            metric=_metric_of(ds),
            kmeans_n_iters=p.get("niter", 20),
            kmeans_trainset_fraction=1.0 / p.get("ratio", 2),
        ),
    )
    if p.get("list_dtype") == "half":
        # bf16 lists halve fused-scan DMA bytes (see docs/tpu_design.md);
        # the reference's half-precision list analog
        index = dataclasses.replace(index, list_data=index.list_data.astype(jnp.bfloat16))
    return index


def _search_ivf_flat(index, queries, k: int, p: Dict[str, Any], batch: int):
    from raft_tpu.neighbors import ivf_flat

    return ivf_flat.search(
        index,
        queries,
        k,
        ivf_flat.IvfFlatSearchParams(
            n_probes=p.get("nprobe", 20),
            fused_qt=p.get("fused_qt", 64),
            fused_probe_factor=p.get("fused_pf", 4),
            fused_group=p.get("fused_group", 1),
            fused_merge=p.get("fused_merge", "seg"),
            fused_precision=p.get("fused_precision", "highest"),
        ),
        query_batch=batch,
        mode=p.get("mode", "auto"),
    )


def _build_ivf_pq(ds: Dataset, p: Dict[str, Any]):
    from raft_tpu.neighbors import ivf_pq

    return ivf_pq.build(
        ds.base,
        ivf_pq.IvfPqIndexParams(
            n_lists=p.get("nlist", 1024),
            metric=_metric_of(ds),
            pq_dim=p.get("pq_dim", 0),
            pq_bits=p.get("pq_bits", 8),
            kmeans_n_iters=p.get("niter", 20),
            kmeans_trainset_fraction=1.0 / p.get("ratio", 10),
        ),
    )


def _search_ivf_pq(index, queries, k: int, p: Dict[str, Any], batch: int):
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_pq, refine as refine_mod

    # only an EXPLICIT smemLutDtype is a precision demand; absent = auto
    # (None), which lets mode="auto" keep the fused bf16-LUT fast path
    lut_map = {"float": jnp.float32, "half": jnp.bfloat16, "bf16": jnp.bfloat16, "fp8": jnp.bfloat16}
    lut = lut_map[p["smemLutDtype"]] if "smemLutDtype" in p else None
    rr = p.get("refine_ratio", 1)
    kk = k * rr
    d, i = ivf_pq.search(
        index,
        queries,
        kk,
        ivf_pq.IvfPqSearchParams(n_probes=p.get("nprobe", 20), lut_dtype=lut),
        query_batch=batch,
    )
    if rr > 1:
        ds = p["_dataset"]  # injected by the runner for refine re-rank
        d, i = refine_mod.refine(ds.base, queries, i, k, metric=_metric_of(ds))
    return d, i


def _build_cagra(ds: Dataset, p: Dict[str, Any]):
    from raft_tpu.neighbors import cagra

    return cagra.build(
        ds.base,
        cagra.CagraIndexParams(
            intermediate_graph_degree=p.get("intermediate_graph_degree", 64),
            graph_degree=p.get("graph_degree", 32),
            build_algo=p.get("graph_build_algo", "NN_DESCENT"),
            metric=_metric_of(ds),
        ),
    )


def _search_cagra(index, queries, k: int, p: Dict[str, Any], batch: int):
    from raft_tpu.neighbors import cagra

    return cagra.search(
        index,
        queries,
        k,
        cagra.CagraSearchParams(
            itopk_size=p.get("itopk", 64),
            search_width=p.get("search_width", 1),
            max_iterations=p.get("max_iterations", 0),
        ),
        query_batch=batch,
    )


ALGOS: Dict[str, Tuple[Callable, Callable]] = {
    "raft_brute_force": (_build_brute_force, _search_brute_force),
    "raft_ivf_flat": (_build_ivf_flat, _search_ivf_flat),
    "raft_ivf_pq": (_build_ivf_pq, _search_ivf_pq),
    "raft_cagra": (_build_cagra, _search_cagra),
}


# ---------------------------------------------------------------------------
# result record (benchmark.hpp:330-385 counter schema)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BenchResult:
    name: str
    algo: str
    dataset: str
    k: int
    batch: int
    build_params: Dict[str, Any]
    search_params: Dict[str, Any]
    build_time: float
    end_to_end: float  # total timed search seconds
    iterations: int  # timed sweeps over the query set
    total_queries: int
    qps: float  # items_per_second
    latency: float  # avg seconds per batch
    recall: float

    def to_json(self) -> Dict[str, Any]:
        """One gbench-style benchmark entry (``benchmark.hpp:330-385``)."""
        return {
            "name": self.name,
            "run_type": "iteration",
            "iterations": self.iterations,
            "real_time": self.end_to_end / max(self.iterations, 1),
            "time_unit": "s",
            "items_per_second": self.qps,
            "Recall": self.recall,
            "Latency": self.latency,
            "end_to_end": self.end_to_end,
            "total_queries": self.total_queries,
            "build_time": self.build_time,
            "k": self.k,
            "n_queries": self.batch,
            "algo": self.algo,
            "dataset": self.dataset,
            "build_params": self.build_params,
            "search_params": self.search_params,
        }


def recall_at_k(found: np.ndarray, gt: np.ndarray, k: int) -> float:
    """Set-overlap recall, the harness metric (``benchmark.hpp:346-379``)."""
    found = found[:, :k]
    gt = gt[:, :k]
    hits = 0
    for row_f, row_g in zip(found, gt):
        hits += len(np.intersect1d(row_f, row_g, assume_unique=False))
    return hits / float(gt.shape[0] * k)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def _grid(space: Dict[str, Sequence[Any]]) -> Iterable[Dict[str, Any]]:
    """Cartesian product of a {param: [values...]} grid (run/__main__.py:141)."""
    if not space:
        yield {}
        return
    keys = list(space)
    for combo in itertools.product(*(space[key] for key in keys)):
        yield dict(zip(keys, combo))


def _fmt(params: Dict[str, Any]) -> str:
    return ",".join(f"{k}={v}" for k, v in params.items() if not k.startswith("_")) or "default"


def run_case(
    ds: Dataset,
    algo: str,
    build_params: Dict[str, Any],
    search_params_list: Sequence[Dict[str, Any]],
    k: int = 10,
    batch: int = 1024,
    min_search_time: float = 2.0,
    max_iterations: int = 20,
    constraint: Optional[Callable[[Dict[str, Any], Dict[str, Any]], bool]] = None,
    verbose: bool = True,
) -> List[BenchResult]:
    """Build once, then time every search-param point (the reference's
    build/search phase split, ``benchmark.hpp:120,175``)."""
    import jax

    build_fn, search_fn = ALGOS[algo]
    gt = ds.ground_truth(k)

    t0 = time.perf_counter()
    index = build_fn(ds, build_params)
    jax.block_until_ready(index)  # whole pytree: include pack/encode work
    build_time = time.perf_counter() - t0
    if verbose:
        print(f"# {algo} [{_fmt(build_params)}] built in {build_time:.1f}s", flush=True)

    queries = ds.queries
    nq = queries.shape[0]
    # trim to whole batches: a trailing partial batch has a fresh jit shape
    # whose compile would land inside the timed region
    if nq > batch:
        nq = (nq // batch) * batch
        queries = queries[:nq]
        gt = gt[:nq]
    results = []
    for sp in search_params_list:
        if constraint is not None and not constraint(build_params, sp):
            continue
        sp = dict(sp)
        sp["_dataset"] = ds
        # warmup / compile
        d, i = search_fn(index, queries[:batch] if nq >= batch else queries, k, sp, batch)
        jax.block_until_ready((d, i))

        # timed: sweep the query set repeatedly until min_search_time
        iters = 0
        total_q = 0
        found = None
        t0 = time.perf_counter()
        while True:
            outs = []
            for s in range(0, nq, batch):
                outs.append(search_fn(index, queries[s : s + batch], k, sp, batch))
            jax.block_until_ready(outs[-1])
            iters += 1
            total_q += nq
            if found is None:
                found = np.concatenate([np.asarray(o[1]) for o in outs], axis=0)
            if time.perf_counter() - t0 >= min_search_time or iters >= max_iterations:
                break
        end_to_end = time.perf_counter() - t0

        rec = recall_at_k(found, gt, k)
        n_batches = iters * -(-nq // batch)
        res = BenchResult(
            name=f"{algo}.{_fmt(build_params)}/{_fmt(sp)}/k={k}/batch={batch}",
            algo=algo,
            dataset=ds.name,
            k=k,
            batch=batch,
            build_params=dict(build_params),
            search_params={key: v for key, v in sp.items() if not key.startswith("_")},
            build_time=build_time,
            end_to_end=end_to_end,
            iterations=iters,
            total_queries=total_q,
            qps=total_q / end_to_end,
            latency=end_to_end / n_batches,
            recall=rec,
        )
        results.append(res)
        if verbose:
            print(
                f"  {_fmt(res.search_params):<40s} qps={res.qps:>12,.0f}  "
                f"recall@{k}={rec:.4f}  lat={res.latency*1e3:.2f}ms",
                flush=True,
            )
    return results


def sweep(
    ds: Dataset,
    algo: str,
    build_grid: Dict[str, Sequence[Any]],
    search_grid: Dict[str, Sequence[Any]],
    **kw,
) -> List[BenchResult]:
    """Full build-grid × search-grid sweep for one algorithm."""
    out: List[BenchResult] = []
    for bp in _grid(build_grid):
        out.extend(run_case(ds, algo, bp, list(_grid(search_grid)), **kw))
    return out


# ---------------------------------------------------------------------------
# analysis (data_export / plot analogs)
# ---------------------------------------------------------------------------


def pareto_frontier(results: Sequence[BenchResult]) -> List[BenchResult]:
    """Recall-QPS Pareto frontier (``raft_ann_bench/plot/__main__.py``)."""
    pts = sorted(results, key=lambda r: (-r.recall, -r.qps))
    front: List[BenchResult] = []
    best_qps = -1.0
    for r in pts:
        if r.qps > best_qps:
            front.append(r)
            best_qps = r.qps
    return list(reversed(front))


def operating_point(results: Sequence[BenchResult], min_recall: float = 0.95) -> Optional[BenchResult]:
    """Max-QPS configuration with recall >= threshold — the BASELINE.md
    "QPS @ recall@10 = 0.95" operating point."""
    ok = [r for r in results if r.recall >= min_recall]
    return max(ok, key=lambda r: r.qps) if ok else None


def to_report(results: Sequence[BenchResult], context: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """gbench-shaped JSON document {context, benchmarks}."""
    import jax

    ctx = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "executable": "raft_tpu.bench",
        "device": str(jax.devices()[0]),
        "num_devices": len(jax.devices()),
    }
    ctx.update(context or {})
    return {"context": ctx, "benchmarks": [r.to_json() for r in results]}


def save_report(results: Sequence[BenchResult], path: str, context: Optional[Dict[str, Any]] = None) -> None:
    payload = json.dumps(to_report(results, context), indent=2).encode("utf-8")
    serialize.atomic_write(path, lambda f: f.write(payload))
