"""JSON -> CSV export of benchmark results — analog of
``python/raft-ann-bench/src/raft_ann_bench/data_export/__main__.py``.

The reference walks gbench JSON result files and emits one CSV per
(dataset, algo) with the throughput/latency/recall columns the plot tool
consumes; this does the same for :func:`raft_tpu.bench.harness.to_report`
documents (the schemas match on the fields that matter).
"""
from __future__ import annotations

import csv
import io
import json
import os
from typing import Dict, Iterable, List, Sequence, Union

from raft_tpu.core import serialize

# the reference's throughput-mode column set (data_export/__main__.py
# write_frame_* / skip_driver_cols)
_COLUMNS = [
    "name",
    "algo",
    "dataset",
    "k",
    "n_queries",
    "recall",
    "qps",
    "latency",
    "end_to_end",
    "build_time",
    "build_params",
    "search_params",
]


def _rows_of(report: Dict) -> List[Dict]:
    rows = []
    for b in report.get("benchmarks", []):
        rows.append(
            {
                "name": b.get("name", ""),
                "algo": b.get("algo", ""),
                "dataset": b.get("dataset", ""),
                "k": b.get("k", ""),
                "n_queries": b.get("n_queries", ""),
                "recall": b.get("Recall", ""),
                "qps": b.get("items_per_second", ""),
                "latency": b.get("Latency", ""),
                "end_to_end": b.get("end_to_end", ""),
                "build_time": b.get("build_time", ""),
                "build_params": json.dumps(b.get("build_params", {}), sort_keys=True),
                "search_params": json.dumps(b.get("search_params", {}), sort_keys=True),
            }
        )
    return rows


def export_csv(report: Union[Dict, str], out_path: str) -> str:
    """Write one CSV for a gbench-style report (dict or path to JSON).
    Returns ``out_path``."""
    if isinstance(report, str):
        with open(report) as f:
            report = json.load(f)
    rows = _rows_of(report)
    buf = io.StringIO(newline="")
    w = csv.DictWriter(buf, fieldnames=_COLUMNS)
    w.writeheader()
    for r in rows:
        w.writerow(r)
    payload = buf.getvalue().encode("utf-8")
    return serialize.atomic_write(out_path, lambda f: f.write(payload))


def export_results_csv(results: Sequence, out_path: str) -> str:
    """Convenience: export a list of :class:`BenchResult` directly."""
    from raft_tpu.bench.harness import to_report

    return export_csv(to_report(results), out_path)


def main(argv: Iterable[str] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser("raft_tpu.bench.data_export")
    ap.add_argument("report", help="gbench-style JSON report file")
    ap.add_argument("--out", default=None, help="CSV path (default: report stem + .csv)")
    args = ap.parse_args(argv)
    out = args.out or os.path.splitext(args.report)[0] + ".csv"
    print(export_csv(args.report, out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
