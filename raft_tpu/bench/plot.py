"""Recall/QPS Pareto-frontier plots — analog of
``python/raft-ann-bench/src/raft_ann_bench/plot/__main__.py``.

One throughput plot per dataset: x = recall@k, y = QPS (log scale), one
line per algorithm tracing its Pareto frontier, markers for the dominated
points — the same figure the reference publishes
(``docs/source/raft_ann_benchmarks.md:255``, img/raft-vector-search-*.png).
"""
from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Dict, Iterable, List, Sequence, Tuple, Union


def _frontier(points: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Upper-right Pareto frontier of (recall, qps) points."""
    pts = sorted(points, key=lambda p: (-p[0], -p[1]))
    out = []
    best_qps = -1.0
    for r, q in pts:
        if q > best_qps:
            out.append((r, q))
            best_qps = q
    return out[::-1]  # ascending recall


def plot_report(report: Union[Dict, str], out_path: str, title: str = "") -> str:
    """Render the recall-QPS plot for a gbench-style report. Returns
    ``out_path`` (PNG)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if isinstance(report, str):
        with open(report) as f:
            report = json.load(f)

    by_algo: Dict[str, List[Tuple[float, float]]] = defaultdict(list)
    k = None
    dataset = ""
    for b in report.get("benchmarks", []):
        r, q = b.get("Recall"), b.get("items_per_second")
        if r is None or q is None:
            continue
        by_algo[b.get("algo", "?")].append((float(r), float(q)))
        k = b.get("k", k)
        dataset = b.get("dataset", dataset)

    fig, ax = plt.subplots(figsize=(8, 5.5))
    for algo, pts in sorted(by_algo.items()):
        fr = _frontier(pts)
        ax.plot(*zip(*fr), marker="o", label=algo)
        dominated = [p for p in pts if p not in fr]
        if dominated:
            ax.scatter(*zip(*dominated), s=12, alpha=0.35)
    ax.set_xlabel(f"recall@{k if k is not None else 'k'}")
    ax.set_ylabel("QPS")
    ax.set_yscale("log")
    ax.grid(True, which="both", alpha=0.3)
    ax.legend()
    ax.set_title(title or f"{dataset}: recall vs throughput")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return out_path


def plot_results(results: Sequence, out_path: str, title: str = "") -> str:
    """Convenience: plot a list of :class:`BenchResult` directly."""
    from raft_tpu.bench.harness import to_report

    return plot_report(to_report(results), out_path, title)


def main(argv: Iterable[str] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser("raft_tpu.bench.plot")
    ap.add_argument("report", help="gbench-style JSON report file")
    ap.add_argument("--out", default=None, help="PNG path (default: report stem + .png)")
    ap.add_argument("--title", default="")
    args = ap.parse_args(argv)
    out = args.out or os.path.splitext(args.report)[0] + ".png"
    print(plot_report(args.report, out, args.title))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
