"""Benchmark parameter grids — the ``run/conf/algos/*.yaml`` groups
(``python/raft-ann-bench/src/raft_ann_bench/run/conf/algos/raft_ivf_pq.yaml:1-17``,
``raft_cagra.yaml``, ``raft_ivf_flat.yaml``, ``raft_brute_force.yaml``)
expressed as Python dicts, plus the per-algo constraint hooks
(``raft_ann_bench/constraints/__init__.py``).

Grids are intentionally smaller than the reference's full sweeps (the
reference grid-searches hundreds of points per dataset on a GPU farm);
``base`` covers the reference's competitive region, ``smoke`` is a
seconds-scale sanity sweep.
"""
from __future__ import annotations

from typing import Any, Dict, Sequence

# group -> algo -> {"build": grid, "search": grid}
GROUPS: Dict[str, Dict[str, Dict[str, Dict[str, Sequence[Any]]]]] = {
    "base": {
        "raft_brute_force": {
            "build": {},
            "search": {"mode": ["approx"]},
        },
        "raft_ivf_flat": {
            # raft_ivf_flat.yaml: nlist [1024,2048,4096], ratio, niter;
            # list_dtype half + the fused-scan knobs are TPU additions
            "build": {"nlist": [1024, 2048], "ratio": [4], "niter": [20], "list_dtype": ["float", "half"]},
            "search": {
                "nprobe": [5, 10, 20, 50, 100],
                "fused_group": [8],
                "fused_qt": [128],
                "fused_pf": [16, 32],
                "fused_precision": ["default"],
            },
        },
        "raft_ivf_pq": {
            # raft_ivf_pq.yaml:1-17
            "build": {"nlist": [1024], "pq_dim": [64, 32], "pq_bits": [8], "ratio": [10], "niter": [20]},
            "search": {
                "nprobe": [5, 10, 20, 50],
                "smemLutDtype": ["float", "half"],
                "refine_ratio": [1, 2],
            },
        },
        "raft_cagra": {
            # raft_cagra.yaml
            "build": {"graph_degree": [32, 64], "intermediate_graph_degree": [64], "graph_build_algo": ["NN_DESCENT"]},
            "search": {"itopk": [32, 64, 128], "search_width": [1, 2, 4]},
        },
    },
    "smoke": {
        "raft_brute_force": {"build": {}, "search": {"mode": ["approx"]}},
        "raft_ivf_flat": {"build": {"nlist": [64]}, "search": {"nprobe": [5, 10]}},
        "raft_ivf_pq": {"build": {"nlist": [64], "pq_dim": [16]}, "search": {"nprobe": [5, 10]}},
        "raft_cagra": {"build": {"graph_degree": [16], "intermediate_graph_degree": [32]}, "search": {"itopk": [32]}},
    },
}


def constraint(algo: str):
    """Per-algo (build_params, search_params) validity hook
    (``raft_ann_bench/constraints/__init__.py`` analog)."""

    def ivf_pq(bp: Dict[str, Any], sp: Dict[str, Any]) -> bool:
        # raft_ivf_pq_search_constraints: nprobe <= nlist
        return sp.get("nprobe", 1) <= bp.get("nlist", 1024)

    def ivf_flat(bp: Dict[str, Any], sp: Dict[str, Any]) -> bool:
        return sp.get("nprobe", 1) <= bp.get("nlist", 1024)

    def cagra(bp: Dict[str, Any], sp: Dict[str, Any]) -> bool:
        # raft_cagra_search_constraints: itopk >= k handled at run time;
        # search_width*graph_degree bounded to keep candidate sets sane
        return sp.get("itopk", 64) <= 512

    return {"raft_ivf_pq": ivf_pq, "raft_ivf_flat": ivf_flat, "raft_cagra": cagra}.get(algo)
