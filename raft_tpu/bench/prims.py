"""Primitive microbenchmarks — the ``cpp/bench/prims`` analog
(``cpp/bench/prims/common/benchmark.hpp`` fixtures for
``matrix/select_k.cu``, ``distance/fused_l2_nn.cu``,
``cluster/kmeans_balanced.cu``, ``neighbors/*``).

Each case times one primitive at a few representative shapes with the
same pipelined-sync discipline as the L8 harness (dispatches are async;
sync via a scalar fetch) and reports gbench-style entries.

Run: ``python -m raft_tpu.bench.prims [--filter distance]``
"""
from __future__ import annotations

import argparse
import json
import time
from typing import Callable, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _timed(fn: Callable[[], object], inner: int = 8, reps: int = 2) -> float:
    out = fn()
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn()
        float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def _cases() -> List[Tuple[str, Callable[[], Tuple[Callable, Dict]]]]:
    key = jax.random.PRNGKey(0)

    def pairwise_distance():
        from raft_tpu.ops.distance import DistanceType, pairwise_distance

        m, n, d = 2048, 16384, 128
        x = jax.random.normal(key, (m, d), jnp.float32)
        y = jax.random.normal(key, (n, d), jnp.float32)
        fn = jax.jit(lambda: pairwise_distance(x, y, DistanceType.L2Expanded))
        return fn, {"items": m * n, "flop": 2 * m * n * d}

    def fused_l2_nn():
        from raft_tpu.ops.fused_1nn import fused_l2_nn as f

        m, n, d = 65536, 1024, 128
        x = jax.random.normal(key, (m, d), jnp.float32)
        y = jax.random.normal(key, (n, d), jnp.float32)
        fn = jax.jit(lambda: f(x, y))
        return fn, {"items": m, "flop": 2 * m * n * d}

    def masked_l2_nn():
        from raft_tpu.ops.masked_nn import masked_l2_nn as f

        m, n, d, ng = 16384, 16384, 64, 32
        x = jax.random.normal(key, (m, d), jnp.float32)
        y = jax.random.normal(key, (n, d), jnp.float32)
        adj = jax.random.uniform(key, (m, ng)) < 0.5
        gi = jnp.arange(1, ng + 1, dtype=jnp.int32) * (n // ng)
        fn = lambda: f(x, y, adj, gi)
        return fn, {"items": m}

    def select_k_exact():
        from raft_tpu.ops.select_k import select_k

        b, n, k = 512, 65536, 64
        v = jax.random.normal(key, (b, n), jnp.float32)
        fn = jax.jit(lambda: select_k(v, k))
        return fn, {"items": b * n}

    def select_k_approx():
        from raft_tpu.ops.select_k import approx_select_k

        b, n, k = 512, 65536, 64
        v = jax.random.normal(key, (b, n), jnp.float32)
        fn = jax.jit(lambda: approx_select_k(v, k))
        return fn, {"items": b * n}

    def kmeans_balanced_fit():
        from raft_tpu.cluster import kmeans_balanced
        from raft_tpu.cluster.kmeans_balanced import BalancedKMeansParams

        n, d, k = 65536, 64, 256
        x = jax.random.normal(key, (n, d), jnp.float32)
        fn = lambda: kmeans_balanced.fit(x, BalancedKMeansParams(n_clusters=k, n_iters=5))
        return fn, {"items": n}

    def rng_normal():
        fn = jax.jit(lambda: jax.random.normal(key, (1 << 24,), jnp.float32))
        return fn, {"items": 1 << 24}

    def gram_rbf():
        from raft_tpu.ops.kernels import rbf_kernel

        m, n, d = 4096, 4096, 128
        x = jax.random.normal(key, (m, d), jnp.float32)
        y = jax.random.normal(key, (n, d), jnp.float32)
        fn = jax.jit(lambda: rbf_kernel(x, y, gamma=0.1))
        return fn, {"items": m * n, "flop": 2 * m * n * d}

    return [
        ("distance/pairwise_l2", pairwise_distance),
        ("distance/fused_l2_nn", fused_l2_nn),
        ("distance/masked_l2_nn", masked_l2_nn),
        ("matrix/select_k_exact", select_k_exact),
        ("matrix/select_k_approx", select_k_approx),
        ("cluster/kmeans_balanced", kmeans_balanced_fit),
        ("random/normal_16M", rng_normal),
        ("distance/gram_rbf", gram_rbf),
    ]


def run(filter_substr: str = "", inner: int = 8) -> List[Dict]:
    results = []
    for name, make in _cases():
        if filter_substr and filter_substr not in name:
            continue
        fn, meta = make()
        dt = _timed(fn, inner=inner)
        row = {
            "name": name,
            "real_time": dt,
            "time_unit": "s",
            "items_per_second": meta.get("items", 0) / dt,
        }
        if "flop" in meta:
            row["tflops"] = round(meta["flop"] / dt / 1e12, 2)
        results.append(row)
        extra = f"  {row['tflops']} TFLOP/s" if "tflops" in row else ""
        print(f"# {name:28s} {dt*1e3:10.2f} ms  {row['items_per_second']:>16,.0f} items/s{extra}", flush=True)
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("raft_tpu.bench.prims")
    ap.add_argument("--filter", default="", help="substring filter on case names")
    ap.add_argument("--inner", type=int, default=8)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    results = run(args.filter, args.inner)
    if args.out:
        # CLI scratch output rerun on demand, not a served artifact
        with open(args.out, "w") as f:  # graft-lint: ignore[non-atomic-write]
            json.dump({"benchmarks": results}, f, indent=2)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
