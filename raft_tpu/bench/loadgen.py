"""Load generator for the serving engine: open-loop (Poisson) and
closed-loop drivers with latency-percentile reporting.

Open-loop is the honest serving benchmark (the "how NOT to measure
latency" lesson): arrivals follow a seeded Poisson process whose rate
does **not** slow down when the system falls behind, so queueing delay
shows up in the percentiles instead of being hidden by coordinated
omission — latency is measured from the *intended* arrival time, not
from when the driver got around to submitting. Closed-loop keeps a
fixed number of requests in flight and measures the classic
throughput-at-concurrency operating point.

Both drivers run the engine's synchronous loop on the calling thread
(no background threads, deterministic under test) and report
throughput plus p50/p95/p99 latency; ``bench.py`` wires them in as the
``serve_*`` rows.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.serve.batcher import DeadlineExceeded, QueueFull


def percentile(samples, q: float) -> float:
    """p``q`` of ``samples`` (nearest-rank on the sorted list; 0 when
    empty) — tiny, dependency-free, and stable run-to-run."""
    if len(samples) == 0:
        return 0.0
    s = sorted(samples)
    rank = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return float(s[rank])


def poisson_arrivals(rate_qps: float, n: int, seed: int = 0) -> np.ndarray:
    """``n`` arrival offsets (seconds from start) of a Poisson process
    with mean rate ``rate_qps`` requests/s, seeded for reproducibility."""
    expects(rate_qps > 0, "rate_qps must be > 0")
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / rate_qps, size=n))


@dataclasses.dataclass
class LoadReport:
    """One load-generation run's scorecard."""

    mode: str  # "open" | "closed"
    n_requests: int
    completed: int
    #: rejection reason -> count (queue_full / deadline_* / dispatch errors)
    rejected: Dict[str, int]
    duration_s: float
    #: completed query rows per second of wall clock
    throughput_qps: float
    latency_ms_mean: float
    latency_ms_p50: float
    latency_ms_p95: float
    latency_ms_p99: float
    latency_ms_max: float
    #: raw per-request latencies (ms), completion order
    latencies_ms: List[float] = dataclasses.field(repr=False, default_factory=list)
    #: trace IDs aligned with ``latencies_ms`` ("" with obs off) — what
    #: lets the p99 sample resolve to a concrete request trace
    trace_ids: List[str] = dataclasses.field(repr=False, default_factory=list)

    def worst_trace(self) -> str:
        """Trace ID of the slowest completed request ("" when untraced)."""
        if not self.latencies_ms:
            return ""
        return self.trace_ids[self.latencies_ms.index(self.latency_ms_max)]

    def row(self) -> Dict[str, float]:
        """The bench-row projection (what lands in results.json)."""
        return {
            "qps": round(self.throughput_qps, 1),
            "completed": self.completed,
            "rejected": int(sum(self.rejected.values())),
            "p50_ms": round(self.latency_ms_p50, 3),
            "p95_ms": round(self.latency_ms_p95, 3),
            "p99_ms": round(self.latency_ms_p99, 3),
        }


def _report(mode, n_requests, completed, rejected, duration_s, rows_done,
            lats_ms, trace_ids=None):
    trace_ids = trace_ids if trace_ids is not None else [""] * len(lats_ms)
    report = LoadReport(
        mode=mode,
        n_requests=n_requests,
        completed=completed,
        rejected=rejected,
        duration_s=duration_s,
        throughput_qps=rows_done / duration_s if duration_s > 0 else 0.0,
        latency_ms_mean=float(np.mean(lats_ms)) if lats_ms else 0.0,
        latency_ms_p50=percentile(lats_ms, 50),
        latency_ms_p95=percentile(lats_ms, 95),
        latency_ms_p99=percentile(lats_ms, 99),
        latency_ms_max=max(lats_ms) if lats_ms else 0.0,
        latencies_ms=lats_ms,
        trace_ids=trace_ids,
    )
    if obs.is_enabled():
        obs.set_gauge("loadgen.throughput_qps", report.throughput_qps, mode=mode)
        obs.set_gauge("loadgen.p50_ms", report.latency_ms_p50, mode=mode)
        obs.set_gauge("loadgen.p99_ms", report.latency_ms_p99, mode=mode)
        for v, t in zip(lats_ms, trace_ids):
            # exemplar-enabled: the tail bucket keeps the worst request's
            # trace, so "what made p99" is answerable after the run
            obs.observe("loadgen.latency_ms", v, trace_id=t or None, mode=mode)
    return report


def run_open_loop(
    engine,
    index_id: str,
    query_pool: np.ndarray,
    k: int,
    *,
    rate_qps: float,
    n_requests: int,
    request_rows: int = 1,
    deadline_ms: Optional[float] = None,
    seed: int = 0,
    collect: bool = False,
) -> Tuple[LoadReport, List[Tuple[np.ndarray, np.ndarray]]]:
    """Open-loop run: submit ``n_requests`` requests of ``request_rows``
    query rows each (drawn round-robin from ``query_pool``) at seeded
    Poisson arrival times, driving ``engine.step()`` between arrivals.

    Latency is intended-arrival → completion (coordinated-omission
    safe). With ``collect=True`` the returned list holds
    ``(pool_row_ids, result_indices)`` per completed request so callers
    can score recall.
    """
    expects(query_pool.ndim == 2, "query_pool must be [n, dim]")
    offsets = poisson_arrivals(rate_qps, n_requests, seed)
    pool_n = query_pool.shape[0]

    pending: List[Tuple[float, object, np.ndarray]] = []  # (t_arrival, future, row_ids)
    rejected: Dict[str, int] = {}
    lats_ms: List[float] = []
    trace_ids: List[str] = []
    results: List[Tuple[np.ndarray, np.ndarray]] = []
    rows_done = 0
    completed = 0

    t0 = time.perf_counter()
    submitted = 0
    while submitted < n_requests or pending:
        now = time.perf_counter() - t0
        # release every arrival that is due (open loop: never waits for
        # the system — lateness becomes queueing latency)
        while submitted < n_requests and offsets[submitted] <= now:
            start = (submitted * request_rows) % pool_n
            ids = (np.arange(request_rows) + start) % pool_n
            q = query_pool[ids]
            try:
                fut = engine.submit(index_id, q, k, deadline_ms=deadline_ms)
                pending.append((float(offsets[submitted]), fut, ids))
            except (QueueFull, DeadlineExceeded) as e:
                rejected[type(e).__name__] = rejected.get(type(e).__name__, 0) + 1
            submitted += 1
        engine.step()
        if submitted >= n_requests:
            engine.run_until_idle()
        done_at = time.perf_counter() - t0
        still = []
        for t_arr, fut, ids in pending:
            if not fut.done():
                still.append((t_arr, fut, ids))
                continue
            exc = fut.exception()
            if exc is not None:
                rejected[type(exc).__name__] = rejected.get(type(exc).__name__, 0) + 1
                continue
            res = fut.result()
            lats_ms.append((done_at - t_arr) * 1e3)
            trace_ids.append(res.trace_id)
            rows_done += res.indices.shape[0]
            completed += 1
            if collect:
                results.append((ids, res.indices))
        pending = still
    duration = time.perf_counter() - t0
    return _report("open", n_requests, completed, rejected, duration, rows_done,
                   lats_ms, trace_ids), results


def run_closed_loop(
    engine,
    index_id: str,
    query_pool: np.ndarray,
    k: int,
    *,
    concurrency: int,
    n_requests: int,
    request_rows: int = 1,
    deadline_ms: Optional[float] = None,
    collect: bool = False,
) -> Tuple[LoadReport, List[Tuple[np.ndarray, np.ndarray]]]:
    """Closed-loop run: keep ``concurrency`` requests outstanding until
    ``n_requests`` have been issued; classic throughput-at-concurrency.
    Latency is submit → completion."""
    expects(query_pool.ndim == 2, "query_pool must be [n, dim]")
    expects(concurrency >= 1, "concurrency must be >= 1")
    pool_n = query_pool.shape[0]

    pending: List[Tuple[float, object, np.ndarray]] = []
    rejected: Dict[str, int] = {}
    lats_ms: List[float] = []
    trace_ids: List[str] = []
    results: List[Tuple[np.ndarray, np.ndarray]] = []
    rows_done = 0
    completed = 0
    submitted = 0

    t0 = time.perf_counter()
    while submitted < n_requests or pending:
        while submitted < n_requests and len(pending) < concurrency:
            start = (submitted * request_rows) % pool_n
            ids = (np.arange(request_rows) + start) % pool_n
            try:
                fut = engine.submit(index_id, query_pool[ids], k, deadline_ms=deadline_ms)
                pending.append((time.perf_counter(), fut, ids))
            except (QueueFull, DeadlineExceeded) as e:
                rejected[type(e).__name__] = rejected.get(type(e).__name__, 0) + 1
            submitted += 1
        # a full window cannot grow — force the flush instead of waiting
        # out max_wait_ms with nothing to do
        engine.step(force=len(pending) >= concurrency or submitted >= n_requests)
        t_done = time.perf_counter()
        still = []
        for t_sub, fut, ids in pending:
            if not fut.done():
                still.append((t_sub, fut, ids))
                continue
            exc = fut.exception()
            if exc is not None:
                rejected[type(exc).__name__] = rejected.get(type(exc).__name__, 0) + 1
                continue
            res = fut.result()
            lats_ms.append((t_done - t_sub) * 1e3)
            trace_ids.append(res.trace_id)
            rows_done += res.indices.shape[0]
            completed += 1
            if collect:
                results.append((ids, res.indices))
        pending = still
    duration = time.perf_counter() - t0
    return _report("closed", n_requests, completed, rejected, duration, rows_done,
                   lats_ms, trace_ids), results
