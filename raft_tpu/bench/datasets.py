"""Benchmark dataset handling — analog of ``python/raft-ann-bench``'s
``get_dataset`` + ``generate_groundtruth`` stages
(``python/raft-ann-bench/src/raft_ann_bench/get_dataset/__main__.py``,
``generate_groundtruth/__main__.py``) and the harness-side dataset object
(``cpp/bench/ann/src/common/dataset.hpp``).

The reference downloads ann-benchmarks HDF5 files and converts them to
``.fbin``; this environment has zero egress, so the registry provides

* **synthetic generators** shaped like the standard datasets (SIFT-1M-like
  clustered float32, DEEP-like, plus uniform worst-case), deterministic by
  seed, and
* **``.fbin`` / ``.npy`` loaders** for datasets already on disk (bit-format
  per ``cpp/bench/ann/src/common/dataset.hpp:37-94``: int32 [n_rows, dim]
  header then row-major data).

Ground truth is computed in-harness with the exact brute-force index (the
reference generates it with pylibraft brute force) and cached on disk next
to the dataset.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Optional, Tuple

import numpy as np

from raft_tpu.core import serialize

DATA_DIR = os.environ.get("RAFT_TPU_BENCH_DATA", os.path.join(os.path.dirname(__file__), "..", "..", ".bench_cache"))


@dataclasses.dataclass
class Dataset:
    """Base + query vectors with lazily computed/cached ground truth."""

    name: str
    base: np.ndarray  # [n, d]
    queries: np.ndarray  # [nq, d]
    metric: str = "euclidean"  # "euclidean" | "inner_product"
    _gt: Optional[np.ndarray] = None

    @property
    def n(self) -> int:
        return self.base.shape[0]

    @property
    def dim(self) -> int:
        return self.base.shape[1]

    def ground_truth(self, k: int, batch: int = 512) -> np.ndarray:
        """Exact top-k ids [nq, k] via brute force; disk-cached."""
        if self._gt is not None and self._gt.shape[1] >= k:
            return self._gt[:, :k]
        cache = _gt_cache_path(self)
        if cache and os.path.exists(cache):
            gt = np.load(cache)
            if gt.shape[0] == self.queries.shape[0] and gt.shape[1] >= k:
                self._gt = gt
                return gt[:, :k]
        gt = _exact_knn(self.base, self.queries, max(k, 100), self.metric, batch)
        if cache:
            os.makedirs(os.path.dirname(cache), exist_ok=True)
            np.save(cache, gt)
        self._gt = gt
        return gt[:, :k]


def download_file(
    url: str,
    dest: str,
    policy: Optional["RetryPolicy"] = None,
    timeout: float = 60.0,
    chunk: int = 1 << 20,
) -> str:
    """Fetch ``url`` to ``dest`` with retry + atomic temp-then-rename.

    The analog of ``get_dataset/__main__.py``'s wget stage, hardened the
    way the robustness layer hardens everything idempotent: transient
    network errors are retried per ``policy``
    (:class:`raft_tpu.robust.retry.RetryPolicy`, default 3 attempts with
    backoff), and a partially-fetched file can never be observed at
    ``dest`` — bytes land in ``dest + ".tmp<pid>"`` and are renamed only
    after a complete read. Returns ``dest``. (This environment has zero
    egress, so tests exercise it against ``file://`` URLs.)
    """
    import urllib.error
    import urllib.request

    from raft_tpu.robust.retry import RetryPolicy, retry_call

    if policy is None:
        policy = RetryPolicy(
            max_attempts=3, base_delay_s=0.5, max_delay_s=10.0,
            retryable=(urllib.error.URLError, ConnectionError, TimeoutError, OSError),
        )

    def _fetch() -> str:
        os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
        tmp = dest + f".tmp{os.getpid()}"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, open(tmp, "wb") as f:
                while True:
                    buf = r.read(chunk)
                    if not buf:
                        break
                    f.write(buf)
            os.replace(tmp, dest)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return dest

    return retry_call(_fetch, policy=policy, op="datasets.download")


def _fingerprint(ds: Dataset) -> str:
    h = hashlib.sha1()
    h.update(f"{ds.name}:{ds.base.shape}:{ds.queries.shape}:{ds.metric}".encode())
    # sample a few rows so regenerated-with-different-seed data doesn't hit
    h.update(np.ascontiguousarray(ds.base[:: max(1, ds.n // 64)][:64]).tobytes())
    h.update(np.ascontiguousarray(ds.queries[:16]).tobytes())
    return h.hexdigest()[:16]


def _gt_cache_path(ds: Dataset) -> Optional[str]:
    try:
        return os.path.join(os.path.abspath(DATA_DIR), "gt", f"{ds.name}-{_fingerprint(ds)}.npy")
    except Exception:
        return None


def _exact_knn(base: np.ndarray, queries: np.ndarray, k: int, metric: str, batch: int) -> np.ndarray:
    """Ground truth via the library's own exact index (reference uses
    pylibraft brute force, ``generate_groundtruth/__main__.py:58``)."""
    import jax

    from raft_tpu.neighbors import brute_force
    from raft_tpu.ops.distance import DistanceType

    m = DistanceType.InnerProduct if metric == "inner_product" else DistanceType.L2Expanded
    index = brute_force.build(base, metric=m)
    outs = []
    for s in range(0, queries.shape[0], batch):
        _, i = brute_force.search(index, queries[s : s + batch], k)
        outs.append(np.asarray(i))  # graft-lint: ignore[sync-transfer-in-loop] — per-batch host copy bounds GT memory; a one-off, not a serving path
    jax.block_until_ready(outs[-1])
    return np.concatenate(outs, axis=0)


# ---------------------------------------------------------------------------
# synthetic generators (registry)
# ---------------------------------------------------------------------------


def make_clustered(
    name: str,
    n: int,
    dim: int,
    n_queries: int,
    n_centers: Optional[int] = None,
    cluster_std: float = 0.5,
    metric: str = "euclidean",
    seed: int = 1234,
) -> Dataset:
    """Clustered float32 data — the realistic ANN regime (real embedding
    datasets are strongly clustered; uniform gaussians make every IVF/graph
    method look artificially bad)."""
    rng = np.random.default_rng(seed)
    nc = n_centers or max(64, int(np.sqrt(n)))
    centers = rng.standard_normal((nc, dim)).astype(np.float32)
    base = centers[rng.integers(0, nc, n)] + cluster_std * rng.standard_normal((n, dim)).astype(np.float32)
    queries = centers[rng.integers(0, nc, n_queries)] + cluster_std * rng.standard_normal(
        (n_queries, dim)
    ).astype(np.float32)
    return Dataset(name, base.astype(np.float32), queries.astype(np.float32), metric)


def make_uniform(name: str, n: int, dim: int, n_queries: int, metric: str = "euclidean", seed: int = 1234) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset(
        name,
        rng.standard_normal((n, dim)).astype(np.float32),
        rng.standard_normal((n_queries, dim)).astype(np.float32),
        metric,
    )


def read_fbin(path: str, dtype=np.float32) -> np.ndarray:
    """``.fbin``/``.ibin`` reader (``cpp/bench/ann/src/common/dataset.hpp:37``:
    two int32 [n_rows, dim] then row-major data)."""
    with open(path, "rb") as f:
        n, d = np.fromfile(f, np.int32, 2)
        return np.fromfile(f, dtype, int(n) * int(d)).reshape(int(n), int(d))


def write_fbin(path: str, arr: np.ndarray) -> None:
    def _write(f):
        np.asarray(arr.shape, np.int32).tofile(f)
        np.ascontiguousarray(arr).tofile(f)

    serialize.atomic_write(path, _write)


def load_fbin_dataset(name: str, base_path: str, query_path: str, metric: str = "euclidean", dtype=np.float32) -> Dataset:
    return Dataset(name, read_fbin(base_path, dtype), read_fbin(query_path, dtype), metric)


# Named registry mirroring run/conf/datasets.yaml shapes (synthetic stand-ins).
_REGISTRY = {
    # name: (n, dim, n_queries, metric)
    "sift-128-euclidean": (1_000_000, 128, 1_000, "euclidean"),
    "sift-128-euclidean-100k": (100_000, 128, 1_000, "euclidean"),
    "deep-image-96-angular-1M": (1_000_000, 96, 1_000, "inner_product"),
    "glove-100-angular-1M": (1_100_000, 100, 1_000, "inner_product"),
    "nytimes-256-angular": (290_000, 256, 1_000, "inner_product"),
    "smoke-10k": (10_000, 64, 200, "euclidean"),
}


def get_dataset(name: str, seed: int = 1234) -> Dataset:
    """Fetch a registered synthetic dataset, or load ``name`` as an on-disk
    pair ``<DATA_DIR>/<name>/base.fbin`` + ``query.fbin`` if present."""
    disk_base = os.path.join(DATA_DIR, name, "base.fbin")
    if os.path.exists(disk_base):
        metric = _REGISTRY[name][3] if name in _REGISTRY else "euclidean"
        return load_fbin_dataset(
            name, disk_base, os.path.join(DATA_DIR, name, "query.fbin"), metric=metric
        )
    if name not in _REGISTRY:
        raise KeyError(f"unknown dataset {name!r}; known: {sorted(_REGISTRY)}")
    n, dim, nq, metric = _REGISTRY[name]
    return make_clustered(name, n, dim, nq, metric=metric, seed=seed)
