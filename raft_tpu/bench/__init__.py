"""ANN benchmark harness (L8) — Python re-implementation of
``cpp/bench/ann`` + ``python/raft-ann-bench`` (SURVEY.md §2.8).

* :mod:`raft_tpu.bench.datasets` — dataset registry + ground-truth cache
* :mod:`raft_tpu.bench.harness` — build/search timing, in-harness recall,
  gbench-schema results, sweeps, Pareto / operating-point analysis
* :mod:`raft_tpu.bench.configs` — per-algo parameter grids + constraints
* :mod:`raft_tpu.bench.loadgen` — open/closed-loop load generation for
  the :mod:`raft_tpu.serve` engine (the ``serve_*`` bench rows)
* ``python -m raft_tpu.bench`` — CLI orchestration
"""
from raft_tpu.bench.datasets import Dataset, get_dataset, make_clustered, make_uniform, read_fbin, write_fbin
from raft_tpu.bench.loadgen import (
    LoadReport,
    poisson_arrivals,
    run_closed_loop,
    run_open_loop,
)
from raft_tpu.bench.harness import (
    ALGOS,
    BenchResult,
    operating_point,
    pareto_frontier,
    recall_at_k,
    run_case,
    save_report,
    sweep,
    to_report,
)

__all__ = [
    "ALGOS",
    "BenchResult",
    "Dataset",
    "LoadReport",
    "get_dataset",
    "make_clustered",
    "make_uniform",
    "operating_point",
    "pareto_frontier",
    "poisson_arrivals",
    "read_fbin",
    "recall_at_k",
    "run_case",
    "run_closed_loop",
    "run_open_loop",
    "save_report",
    "sweep",
    "to_report",
    "write_fbin",
]
