"""On-demand compilation + ctypes loading of the native C components.

One ``cc -O3 -shared -fPIC`` per source, cached under
``~/.cache/raft_tpu_native`` keyed by source hash — the moral equivalent
of the reference's precompiled ``libraft.so`` (``cpp/CMakeLists.txt:584``)
at the scale this framework needs native host code. Thread-safe,
fallback-friendly: callers treat a ``None`` return as "use the Python
path".
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig
import threading
from typing import Optional

from raft_tpu.robust.retry import RetryError, RetryPolicy, retry_call
from raft_tpu.utils import lockcheck

_CACHE_DIR = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")), "raft_tpu_native"
)
_LOCK = lockcheck.tracked(threading.Lock(), "native.build")
_LOADED: dict = {}

#: fs/toolchain hiccups (NFS races, OOM-killed cc) are transient; a failed
#: compile only costs the Python fallback, so keep the retry budget small
_COMPILE_RETRY = RetryPolicy(
    max_attempts=2, base_delay_s=0.2,
    retryable=(subprocess.SubprocessError, OSError),
)


def _compiler() -> Optional[str]:
    for cand in (os.environ.get("CC"), sysconfig.get_config_var("CC"), "cc", "gcc", "clang"):
        if not cand:
            continue
        exe = cand.split()[0]
        from shutil import which

        if which(exe):
            return cand
    return None


def load_native(name: str) -> Optional[ctypes.CDLL]:
    """Compile (once) and load ``raft_tpu/native/<name>.c``; ``None`` if no
    compiler is available or compilation fails.

    ``_LOCK`` covers only the ``_LOADED`` cache, never the compile: the
    retry loop emits obs metrics (which take the registry lock) and the
    compile itself blocks for seconds, so both run lock-free. Two
    threads racing on a cold cache may both compile — each writes a
    pid-suffixed temp and ``os.replace`` s it into place atomically, so
    the duplicates are identical and harmless; first publisher wins the
    cache slot."""
    with _LOCK:
        if name in _LOADED:
            return _LOADED[name]
    lib = _build_and_load(name)
    with _LOCK:
        return _LOADED.setdefault(name, lib)


def _build_and_load(name: str) -> Optional[ctypes.CDLL]:
    src = os.path.join(os.path.dirname(__file__), f"{name}.c")
    try:
        with open(src, "rb") as f:
            code = f.read()
    except OSError:
        return None
    tag = hashlib.sha256(code).hexdigest()[:16]
    out = os.path.join(_CACHE_DIR, f"{name}-{tag}.so")
    if not os.path.exists(out):
        cc = _compiler()
        if cc is None:
            return None
        os.makedirs(_CACHE_DIR, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        cmd = cc.split() + ["-O3", "-shared", "-fPIC", "-o", tmp, src]

        def _compile():
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)

        try:
            retry_call(_compile, policy=_COMPILE_RETRY, op="native.compile")
        except RetryError:
            return None
    try:
        return ctypes.CDLL(out)
    except OSError:
        return None
