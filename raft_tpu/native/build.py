"""On-demand compilation + ctypes loading of the native C components.

One ``cc -O3 -shared -fPIC`` per source, cached under
``~/.cache/raft_tpu_native`` keyed by source hash — the moral equivalent
of the reference's precompiled ``libraft.so`` (``cpp/CMakeLists.txt:584``)
at the scale this framework needs native host code. Thread-safe,
fallback-friendly: callers treat a ``None`` return as "use the Python
path".
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig
import threading
from typing import Optional

from raft_tpu.robust.retry import RetryError, RetryPolicy, retry_call

_CACHE_DIR = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")), "raft_tpu_native"
)
_LOCK = threading.Lock()
_LOADED: dict = {}

#: fs/toolchain hiccups (NFS races, OOM-killed cc) are transient; a failed
#: compile only costs the Python fallback, so keep the retry budget small
_COMPILE_RETRY = RetryPolicy(
    max_attempts=2, base_delay_s=0.2,
    retryable=(subprocess.SubprocessError, OSError),
)


def _compiler() -> Optional[str]:
    for cand in (os.environ.get("CC"), sysconfig.get_config_var("CC"), "cc", "gcc", "clang"):
        if not cand:
            continue
        exe = cand.split()[0]
        from shutil import which

        if which(exe):
            return cand
    return None


def load_native(name: str) -> Optional[ctypes.CDLL]:
    """Compile (once) and load ``raft_tpu/native/<name>.c``; ``None`` if no
    compiler is available or compilation fails."""
    with _LOCK:
        if name in _LOADED:
            return _LOADED[name]
        src = os.path.join(os.path.dirname(__file__), f"{name}.c")
        try:
            with open(src, "rb") as f:
                code = f.read()
        except OSError:
            _LOADED[name] = None
            return None
        tag = hashlib.sha256(code).hexdigest()[:16]
        out = os.path.join(_CACHE_DIR, f"{name}-{tag}.so")
        if not os.path.exists(out):
            cc = _compiler()
            if cc is None:
                _LOADED[name] = None
                return None
            os.makedirs(_CACHE_DIR, exist_ok=True)
            tmp = out + f".tmp{os.getpid()}"
            cmd = cc.split() + ["-O3", "-shared", "-fPIC", "-o", tmp, src]

            def _compile():
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(tmp, out)

            try:
                retry_call(_compile, policy=_COMPILE_RETRY, op="native.compile")
            except RetryError:
                _LOADED[name] = None
                return None
        try:
            _LOADED[name] = ctypes.CDLL(out)
        except OSError:
            _LOADED[name] = None
        return _LOADED[name]
