/* Jonker-Volgenant shortest-augmenting-path LAP solver.
 *
 * Native analog of raft::solver::LinearAssignmentProblem
 * (solver/linear_assignment.cuh, the Date-Nagi GPU Hungarian variant):
 * the reference runs the frontier expansion on CUDA; on a TPU system the
 * assignment problems its consumers solve (cluster matching, tracking)
 * are host-side O(n^3) work, so the native component is a C solver bound
 * through ctypes (compiled on first use, cached; see lap_native.py).
 *
 * Input: n x n row-major cost matrix. Output: p[j] = row assigned to
 * column j (0-based). Returns 0 on success.
 */
#include <stdlib.h>

int lap_jv(const double *c, long n, long *p_out) {
    /* 1-indexed arrays, potentials u (rows) / v (cols). */
    double *u = (double *)calloc((size_t)(n + 1), sizeof(double));
    double *v = (double *)calloc((size_t)(n + 1), sizeof(double));
    double *minv = (double *)malloc((size_t)(n + 1) * sizeof(double));
    long *p = (long *)calloc((size_t)(n + 1), sizeof(long)); /* col -> row */
    long *way = (long *)calloc((size_t)(n + 1), sizeof(long));
    char *used = (char *)malloc((size_t)(n + 1));
    if (!u || !v || !minv || !p || !way || !used) {
        free(u); free(v); free(minv); free(p); free(way); free(used);
        return -1;
    }
    const double INF = 1e300;

    for (long i = 1; i <= n; ++i) {
        p[0] = i;
        long j0 = 0;
        for (long j = 0; j <= n; ++j) { minv[j] = INF; used[j] = 0; }
        do {
            used[j0] = 1;
            long i0 = p[j0];
            double delta = INF;
            long j1 = 0;
            const double *row = c + (i0 - 1) * n;
            double ui0 = u[i0];
            for (long j = 1; j <= n; ++j) {
                if (used[j]) continue;
                double cur = row[j - 1] - ui0 - v[j];
                if (cur < minv[j]) { minv[j] = cur; way[j] = j0; }
                if (minv[j] < delta) { delta = minv[j]; j1 = j; }
            }
            for (long j = 0; j <= n; ++j) {
                if (used[j]) { u[p[j]] += delta; v[j] -= delta; }
                else { minv[j] -= delta; }
            }
            j0 = j1;
        } while (p[j0] != 0);
        /* augment along the alternating path */
        do {
            long j1 = way[j0];
            p[j0] = p[j1];
            j0 = j1;
        } while (j0);
    }

    for (long j = 1; j <= n; ++j) p_out[j - 1] = p[j] - 1;
    free(u); free(v); free(minv); free(p); free(way); free(used);
    return 0;
}
