"""Native (C) runtime components, compiled on demand and bound via ctypes.

The reference ships its runtime layer as C++/CUDA; here the TPU compute
path is JAX/Pallas and the host-side native pieces live in this package:
small C sources compiled once with the system compiler into a per-user
cache (no pybind11 — plain ``ctypes`` over a C ABI), with pure-Python
fallbacks when no compiler is available.
"""
from raft_tpu.native.build import load_native  # noqa: F401
