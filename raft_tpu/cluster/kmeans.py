"""K-means (Lloyd) with k-means++ init — analog of ``raft::cluster::kmeans``.

Reference: ``cluster/kmeans.cuh:89`` (``kmeans::fit``), params struct
``cluster/kmeans_types.hpp:38-70``, EM loop ``cluster/detail/kmeans.cuh:362``
(``kmeans_fit_main``), ``kmeansPlusPlus`` (``:91``), ``update_centroids``
(``:288``).

TPU design notes:

* The EM loop runs entirely on-device in ``lax.while_loop`` — the reference
  pays a device→host sync per iteration for its convergence check
  (``kmeans.cuh:440-455``); here the inertia/shift test is part of the loop
  carry, so there is no per-iteration ping-pong.
* The E step is the fused distance+argmin scan
  (:func:`raft_tpu.ops.fused_1nn.min_cluster_and_distance`) — [n, k]
  distances are never materialized.
* The M step is a ``segment_sum`` (XLA scatter-add), the
  ``reduce_rows_by_key`` analog.
* k-means++ seeding draws one center per ``fori_loop`` step via the
  categorical-from-min-distance trick, all on-device.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric, row_norms
from raft_tpu.ops.fused_1nn import min_cluster_and_distance
from raft_tpu.random.rng import as_key
from raft_tpu.utils.math import cdiv


@dataclasses.dataclass
class KMeansParams:
    """``cluster/kmeans_types.hpp:38-70`` analog."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: str = "kmeans++"  # "kmeans++" | "random" | "array"
    n_init: int = 1
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0
    oversampling_factor: float = 2.0  # kept for param parity; unused by Lloyd
    batch_samples: int = 1 << 15  # kept for param parity; the E step is
    #   already memory-bounded by the fused argmin scan, so no batching knob
    algorithm: str = "lloyd"  # "lloyd" | "flash" (Flash-KMeans exact E step)


@dataclasses.dataclass
class KMeansOutput:
    centroids: jax.Array  # [k, d] f32
    labels: jax.Array  # [n] i32
    inertia: jax.Array  # scalar f32
    n_iter: jax.Array  # scalar i32


def kmeans_plus_plus(key, X: jax.Array, k: int, sample_weights=None) -> jax.Array:
    """k-means++ seeding (``cluster/detail/kmeans.cuh:91`` kmeansPlusPlus):
    first center uniform, then each next center sampled with probability
    proportional to (weighted) squared distance to the nearest chosen
    center."""
    n, d = X.shape
    w = jnp.ones((n,), jnp.float32) if sample_weights is None else jnp.asarray(sample_weights, jnp.float32)
    k0, kloop = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centers = jnp.zeros((k, d), jnp.float32).at[0].set(X[first])
    min_d2 = jnp.sum((X - X[first]) ** 2, axis=1)

    def body(i, carry):
        centers, min_d2, kk = carry
        kk, ksel = jax.random.split(kk)
        # Sample proportional to w * min_d2 (log-categorical; zero-safe).
        logits = jnp.log(jnp.maximum(w * min_d2, 1e-30))
        idx = jax.random.categorical(ksel, logits)
        c = X[idx]
        centers = centers.at[i].set(c)
        min_d2 = jnp.minimum(min_d2, jnp.sum((X - c) ** 2, axis=1))
        return centers, min_d2, kk

    centers, _, _ = lax.fori_loop(1, k, body, (centers, min_d2, kloop))
    return centers


def _update_centroids(X, labels, k: int, old_centroids, weights):
    """M step (``cluster/detail/kmeans.cuh:288`` update_centroids): weighted
    mean of assigned points; empty clusters keep their previous centroid (the
    reference copies the old center for weight-0 clusters)."""
    sums = jax.ops.segment_sum(X * weights[:, None], labels, num_segments=k)
    counts = jax.ops.segment_sum(weights, labels, num_segments=k)
    means = sums / jnp.maximum(counts[:, None], 1e-9)
    return jnp.where(counts[:, None] > 0, means, old_centroids), counts


# -- Flash-KMeans exact E step ----------------------------------------------
# "Flash-KMeans: Fast and Memory-Efficient Exact K-Means" (PAPERS.md): three
# changes to the assignment step, none of which alter a single bit of the
# result relative to :func:`min_cluster_and_distance`:
#
# 1. **norm caching** — ``||x||^2`` (and for cosine the unit rows) are
#    computed once per fit and reused every EM iteration; the fused scan
#    recomputes them inside the ``while_loop`` body each time.
# 2. **blocked assignment** — rows are processed in MXU-sized blocks against
#    center tiles, one ``[block, tile]`` matmul per step.
# 3. **norm-difference bounds** — ``d(x, c) >= | ||x|| - ||c|| |`` lets a
#    whole center tile be skipped via ``lax.cond`` (the matmul truly does
#    not run) when no row in the block can improve on its running best.
#
# The bound is deflated by a worst-case f32 rounding margin so it only
# suppresses tiles whose *computed* distances provably cannot win, and
# replacement stays strict-(</>) with first-seen ties — so labels,
# distances, and the convergence trajectory are bit-identical to the
# default path ("bit-compatible convergence").

_F32_EPS = float(np.finfo(np.float32).eps)


@functools.partial(jax.jit, static_argnames=("tile", "sqrt"))
def _flash_assign_l2(Xb, xnb, sxb, ct, cnt, sct, *, tile: int, sqrt: bool):
    """Blocked bound-skipping L2 assignment over pre-tiled inputs.

    ``Xb [nb, block, d]``, ``xnb/sxb [nb, block]`` (squared norms / norms,
    zero on row padding); ``ct [nt, tile, d]``, ``cnt/sct [nt, tile]`` with
    ``inf`` norms marking center padding. Returns ``(labels, dists)`` each
    ``[nb * block]``, matching :func:`fused_l2_nn` bit-for-bit."""
    n_tiles, _, d = ct.shape
    # |computed d2 - true d2| <= eps * O(d) * (||x|| + ||c||)^2 covers both
    # the dot's length-d accumulation and the xn + cn - 2dot cancellation.
    margin_scale = jnp.float32(_F32_EPS * (d + 8.0))

    def per_block(blk):
        xb, xn, sx = blk

        def body(carry, inputs):
            t, yt, ynt, syt = inputs
            bv0, _ = carry
            pad = ynt == jnp.inf
            lb = (sx[:, None] - syt[None, :]) ** 2
            lb = lb - margin_scale * (sx[:, None] + syt[None, :]) ** 2
            lb = jnp.where(pad[None, :], jnp.inf, lb)
            can_skip = jnp.all(jnp.min(lb, axis=1) >= bv0)

            def compute(c):
                bv, bi = c
                dot = lax.dot_general(
                    xb, yt, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
                d2 = xn[:, None] + ynt[None, :] - 2.0 * dot
                d2 = jnp.maximum(d2, 0.0)
                d2 = jnp.where(pad[None, :], jnp.inf, d2)
                tile_val = jnp.min(d2, axis=1)
                tile_arg = jnp.argmin(d2, axis=1).astype(jnp.int32) + t * tile
                take_new = tile_val < bv
                return (
                    jnp.where(take_new, tile_val, bv),
                    jnp.where(take_new, tile_arg, bi),
                )

            carry = lax.cond(can_skip, lambda c: c, compute, carry)
            return carry, None

        init = (
            jnp.full(xb.shape[:1], jnp.inf, jnp.float32),
            jnp.zeros(xb.shape[:1], jnp.int32),
        )
        (bv, bi), _ = lax.scan(body, init, (jnp.arange(n_tiles), ct, cnt, sct))
        return bv, bi

    vals, idxs = lax.map(per_block, (Xb, xnb, sxb))
    vals = vals.reshape(-1)
    if sqrt:
        vals = jnp.sqrt(vals)
    return idxs.reshape(-1), vals


@functools.partial(jax.jit, static_argnames=("tile",))
def _flash_assign_ip(Xb, sxb, ct, sct, vt, *, tile: int):
    """Blocked max-inner-product assignment with a Cauchy-Schwarz skip:
    ``dot(x, c) <= ||x|| * ||c||`` (inflated by the rounding margin), so a
    tile whose upper bound cannot beat the running best never runs its
    matmul. Matches :func:`_fused_ip_nn_impl` bit-for-bit."""
    n_tiles, _, d = ct.shape
    margin_scale = jnp.float32(_F32_EPS * (d + 8.0))

    def per_block(blk):
        xb, sx = blk

        def body(carry, inputs):
            t, yt, syt, vtt = inputs
            bv0, _ = carry
            ub = sx[:, None] * syt[None, :]
            ub = jnp.where(vtt[None, :], ub + margin_scale * ub, -jnp.inf)
            can_skip = jnp.all(jnp.max(ub, axis=1) <= bv0)

            def compute(c):
                bv, bi = c
                dot = lax.dot_general(
                    xb, yt, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
                dot = jnp.where(vtt[None, :], dot, -jnp.inf)
                tile_val = jnp.max(dot, axis=1)
                tile_arg = jnp.argmax(dot, axis=1).astype(jnp.int32) + t * tile
                take_new = tile_val > bv
                return (
                    jnp.where(take_new, tile_val, bv),
                    jnp.where(take_new, tile_arg, bi),
                )

            carry = lax.cond(can_skip, lambda c: c, compute, carry)
            return carry, None

        init = (
            jnp.full(xb.shape[:1], -jnp.inf, jnp.float32),
            jnp.zeros(xb.shape[:1], jnp.int32),
        )
        (bv, bi), _ = lax.scan(body, init, (jnp.arange(n_tiles), ct, sct, vt))
        return bv, bi

    vals, idxs = lax.map(per_block, (Xb, sxb))
    return idxs.reshape(-1), vals.reshape(-1)


def flash_norm_cache(X, metric=DistanceType.L2Expanded):
    """Precompute the per-dataset arrays the flash E step reuses across EM
    iterations: for cosine the unit rows (plus their norms), otherwise the
    squared norms and norms of ``X``. Pass the result to
    :func:`flash_min_cluster_and_distance` as ``cache=``."""
    metric = resolve_metric(metric)
    X = jnp.asarray(X, jnp.float32)
    if metric == DistanceType.CosineExpanded:
        xu = X / jnp.maximum(jnp.linalg.norm(X, axis=1, keepdims=True), 1e-12)
        xn = row_norms(xu)
        return (xu, xn, jnp.sqrt(xn))
    xn = row_norms(X)
    return (X, xn, jnp.sqrt(xn))


def flash_min_cluster_and_distance(
    X,
    centroids,
    metric=DistanceType.L2Expanded,
    cache=None,
    row_block: int = 1024,
    center_tile: int = 512,
):
    """Drop-in, bit-identical replacement for
    :func:`min_cluster_and_distance` built on the flash blocked/bounded
    assignment. ``cache`` (from :func:`flash_norm_cache`) amortizes the
    sample-side norms across repeated calls on the same ``X``."""
    metric = resolve_metric(metric)
    if cache is None:
        cache = flash_norm_cache(X, metric)
    Xc, xn, sx = cache
    n, d = Xc.shape
    c = jnp.asarray(centroids, jnp.float32)
    k = c.shape[0]

    block = int(min(row_block, max(8, n)))
    nb = cdiv(n, block)
    rpad = nb * block - n
    if rpad:
        Xc = jnp.pad(Xc, ((0, rpad), (0, 0)))
        xn = jnp.pad(xn, (0, rpad))
        sx = jnp.pad(sx, (0, rpad))
    Xb = Xc.reshape(nb, block, d)
    xnb = xn.reshape(nb, block)
    sxb = sx.reshape(nb, block)

    tile = int(min(center_tile, max(128, k)))
    nt = cdiv(k, tile)
    cpad = nt * tile - k
    cp = jnp.pad(c, ((0, cpad), (0, 0))) if cpad else c
    ct = cp.reshape(nt, tile, d)

    if metric == DistanceType.InnerProduct:
        sct = jnp.sqrt(row_norms(cp)).reshape(nt, tile)
        valid = (jnp.arange(nt * tile) < k).reshape(nt, tile)
        labels, vals = _flash_assign_ip(Xb, sxb, ct, sct, valid, tile=tile)
        return labels[:n], vals[:n]

    if metric == DistanceType.CosineExpanded:
        cu = cp / jnp.maximum(jnp.linalg.norm(cp, axis=1, keepdims=True), 1e-12)
        cn = row_norms(cu)
        cn = jnp.where(jnp.arange(nt * tile) < k, cn, jnp.inf)
        labels, vals = _flash_assign_l2(
            Xb, xnb, sxb, cu.reshape(nt, tile, d), cn.reshape(nt, tile),
            jnp.sqrt(cn).reshape(nt, tile), tile=tile, sqrt=False,
        )
        return labels[:n], 0.5 * vals[:n]  # ||x̂-ĉ||²/2 == 1 - cos

    cn = row_norms(cp)
    cn = jnp.where(jnp.arange(nt * tile) < k, cn, jnp.inf)
    sqrt = metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded)
    labels, vals = _flash_assign_l2(
        Xb, xnb, sxb, ct, cn.reshape(nt, tile), jnp.sqrt(cn).reshape(nt, tile),
        tile=tile, sqrt=sqrt,
    )
    return labels[:n], vals[:n]


def _flash_lloyd(X, init_centers, k: int, metric, max_iter: int, tol: float, weights) -> KMeansOutput:
    """Flash-KMeans Lloyd: same ``while_loop`` cond/body semantics as
    :func:`_lloyd` with the E step swapped for the cached/blocked/bounded
    assignment — bit-compatible convergence, less work per iteration."""
    n = X.shape[0]
    tol2 = jnp.float32(tol * tol)
    cache = flash_norm_cache(X, metric)  # hoisted out of the EM loop

    def assign(centers):
        return flash_min_cluster_and_distance(X, centers, metric=metric, cache=cache)

    def cond(carry):
        _, _, it, shift2, _ = carry
        return (it < max_iter) & (shift2 > tol2)

    def body(carry):
        centers, _, it, _, _ = carry
        labels, dists = assign(centers)
        new_centers, _ = _update_centroids(X, labels, k, centers, weights)
        shift2 = jnp.sum((new_centers - centers) ** 2)
        inertia = jnp.sum(weights * dists)
        return new_centers, labels, it + 1, shift2, inertia

    init = (
        init_centers,
        jnp.zeros((n,), jnp.int32),
        jnp.int32(0),
        jnp.float32(jnp.inf),
        jnp.float32(jnp.inf),
    )
    centers, labels, n_iter, _, _ = lax.while_loop(cond, body, init)
    labels, dists = assign(centers)
    return KMeansOutput(
        centroids=centers, labels=labels, inertia=jnp.sum(weights * dists), n_iter=n_iter
    )


def fit(
    X,
    params: Optional[KMeansParams] = None,
    centroids: Optional[jax.Array] = None,
    sample_weights: Optional[jax.Array] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> KMeansOutput:
    """Lloyd EM (``kmeans::fit``, ``cluster/kmeans.cuh:89``).

    ``kwargs`` are convenience overrides for :class:`KMeansParams` fields
    (e.g. ``fit(X, n_clusters=16)``).
    """
    res = ensure_resources(res)
    if params is None:
        params = KMeansParams(**kwargs)
    metric = resolve_metric(params.metric)
    X = jnp.asarray(X, jnp.float32)
    expects(X.ndim == 2, "X must be [n_samples, n_features]")
    n, d = X.shape
    k = params.n_clusters
    expects(0 < k <= n, "n_clusters=%d out of range for %d samples", k, n)

    expects(
        params.init != "array" or centroids is not None,
        "init='array' requires an explicit centroids argument",
    )
    expects(
        params.algorithm in ("lloyd", "flash"),
        "algorithm must be 'lloyd' or 'flash', got %s",
        params.algorithm,
    )
    lloyd_fn = _flash_lloyd if params.algorithm == "flash" else _lloyd
    weights = (
        jnp.ones((n,), jnp.float32)
        if sample_weights is None
        else jnp.asarray(sample_weights, jnp.float32)
    )
    expects(weights.shape == (n,), "sample_weights must be [n_samples]")

    # Whether a smaller "inertia" is better depends on the metric direction
    # (InnerProduct assignment scores are similarities, larger = better).
    from raft_tpu.ops.distance import is_min_close

    min_close = is_min_close(metric)

    if obs.is_enabled():
        obs.inc("kmeans.fit.calls", init=str(params.init if centroids is None else "array"))
        obs.inc("kmeans.fit.samples", float(n))

    key = as_key(params.seed)
    best = None
    for trial in range(max(1, params.n_init)):
        key, kinit = jax.random.split(key)
        with obs.span("kmeans.fit.init", k=k, n=n, trial=trial) as sp:
            if centroids is not None:
                init_centers = jnp.asarray(centroids, jnp.float32)
                expects(init_centers.shape == (k, d), "explicit centroids shape mismatch")
            elif params.init == "random":
                idx = jax.random.permutation(kinit, n)[:k]
                init_centers = X[idx]
            else:
                init_centers = kmeans_plus_plus(kinit, X, k, sample_weights)
            sp.sync(init_centers)

        with obs.span(
            "kmeans.fit.lloyd", k=k, n=n, trial=trial, algorithm=params.algorithm
        ) as sp:
            out = sp.sync(
                lloyd_fn(X, init_centers, k, metric, params.max_iter, params.tol, weights)
            )
        if obs.is_enabled():
            obs.observe("kmeans.fit.n_iter", float(out.n_iter))
        better = best is None or (
            float(out.inertia) < float(best.inertia)
            if min_close
            else float(out.inertia) > float(best.inertia)
        )
        if better:
            best = out
        if centroids is not None:
            break
    return best


def _lloyd(X, init_centers, k: int, metric, max_iter: int, tol: float, weights) -> KMeansOutput:
    n = X.shape[0]
    tol2 = jnp.float32(tol * tol)

    def cond(carry):
        _, _, it, shift2, _ = carry
        return (it < max_iter) & (shift2 > tol2)

    def body(carry):
        centers, _, it, _, _ = carry
        labels, dists = min_cluster_and_distance(X, centers, metric=metric)
        new_centers, _ = _update_centroids(X, labels, k, centers, weights)
        shift2 = jnp.sum((new_centers - centers) ** 2)
        inertia = jnp.sum(weights * dists)
        return new_centers, labels, it + 1, shift2, inertia

    init = (
        init_centers,
        jnp.zeros((n,), jnp.int32),
        jnp.int32(0),
        jnp.float32(jnp.inf),
        jnp.float32(jnp.inf),
    )
    centers, labels, n_iter, _, _ = lax.while_loop(cond, body, init)
    # Final E step so labels/inertia match the returned centroids.
    labels, dists = min_cluster_and_distance(X, centers, metric=metric)
    return KMeansOutput(
        centroids=centers, labels=labels, inertia=jnp.sum(weights * dists), n_iter=n_iter
    )


def predict(X, centroids, metric=DistanceType.L2Expanded) -> Tuple[jax.Array, jax.Array]:
    """Assign samples to nearest centroids (``kmeans::predict``). Returns
    (labels, distances)."""
    labels, dists = min_cluster_and_distance(jnp.asarray(X, jnp.float32), centroids, metric=metric)
    return labels, dists


def fit_predict(X, params: Optional[KMeansParams] = None, **kwargs) -> Tuple[KMeansOutput, jax.Array]:
    out = fit(X, params, **kwargs)
    return out, out.labels


def transform(X, centroids, metric=DistanceType.L2Expanded) -> jax.Array:
    """Distances to every centroid (``kmeans::transform``) — [n, k]."""
    from raft_tpu.ops.distance import pairwise_distance

    return pairwise_distance(jnp.asarray(X, jnp.float32), centroids, metric=metric)


def inertia(X, centroids, metric=DistanceType.L2Expanded) -> jax.Array:
    _, dists = predict(X, centroids, metric)
    return jnp.sum(dists)


def cluster_dispersion(centroids, cluster_sizes) -> jax.Array:
    """Cluster dispersion metric (``stats/dispersion.cuh:85``): sqrt of the
    weighted sum of squared distances between centroids and the global
    (size-weighted) centroid."""
    c = jnp.asarray(centroids, jnp.float32)
    w = jnp.asarray(cluster_sizes, jnp.float32)
    total = jnp.maximum(jnp.sum(w), 1.0)
    g = jnp.sum(c * w[:, None], axis=0) / total
    return jnp.sqrt(jnp.sum(w * jnp.sum((c - g) ** 2, axis=1)))


def find_k(
    X,
    kmax: int,
    kmin: int = 1,
    max_iter: int = 100,
    tol: float = 1e-2,
    seed: int = 0,
) -> Tuple[int, jax.Array, jax.Array]:
    """Auto-select k — ``kmeans::find_k`` (``cluster/kmeans.cuh:291-308``,
    ``detail/kmeans_auto_find_k.cuh:67``).

    Binary search over k maximizing the Calinski-Harabasz-style objective
    ``(n - k) / (k - 1) * dispersion(k) / inertia(k)`` exactly as the
    reference's bisection does (slope test on the objective at
    left/mid/right). Returns ``(best_k, inertia, n_iter)``.
    """
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    expects(1 <= kmin <= kmax <= n, "need 1 <= kmin <= kmax <= n")
    params = lambda k: KMeansParams(n_clusters=k, max_iter=max_iter, tol=tol, seed=seed)

    cache = {}

    def objective(k):
        if k not in cache:
            out = fit(X, params(k))
            sizes = jnp.zeros((k,), jnp.int32).at[out.labels].add(1)
            disp = cluster_dispersion(out.centroids, sizes)
            inert = jnp.maximum(out.inertia, 1e-20)
            obj = (n - k) / max(k - 1, 1) * float(disp) / float(inert)
            cache[k] = (obj, out)
        return cache[k]

    left = max(2, kmin)
    right = kmax
    if left >= right:
        _, out = objective(right)
        return right, out.inertia, out.n_iter
    if right - left <= 24:
        # small range: evaluate exhaustively (each fit is cached; the
        # reference's slope-sign bisection walks the wrong way when the
        # objective is monotone, e.g. true k at kmin)
        best = max(range(left, right + 1), key=lambda k: objective(k)[0])
        _, out = objective(best)
        return best, out.inertia, out.n_iter
    while right - left > 2:
        m1 = left + (right - left) // 3
        m2 = right - (right - left) // 3
        if objective(m1)[0] < objective(m2)[0]:
            left = m1 + 1
        else:
            right = m2 - 1
    best = max(range(left, right + 1), key=lambda k: objective(k)[0])
    _, out = objective(best)
    return best, out.inertia, out.n_iter


def fit_minibatch(
    X,
    params: Optional[KMeansParams] = None,
    n_epochs: int = 10,
    res: Optional[Resources] = None,
    **kwargs,
) -> KMeansOutput:
    """Mini-batch Lloyd — the ``batch_samples`` tiling of
    ``kmeans_types.hpp:102-106`` taken to its stochastic conclusion: each
    step assigns one ``batch_samples``-sized sample and moves its centers
    by the running-count learning rate (centers never see a full [n, k]
    anything; peak memory is O(batch * d + batch * k_tile)).

    Use for n >> HBM; plain :func:`fit` already tiles its E step and is
    preferred when the data fits."""
    res = ensure_resources(res)
    if params is None:
        params = KMeansParams(**kwargs)
    metric = resolve_metric(params.metric)
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    k = params.n_clusters
    b = int(min(params.batch_samples, n))
    expects(0 < k <= b, "n_clusters=%d must be <= batch_samples=%d", k, b)

    key = as_key(params.seed)
    key, kinit = jax.random.split(key)
    init_idx = jax.random.permutation(kinit, n)[:b]
    centers = kmeans_plus_plus(kinit, X[init_idx], k)

    steps = max(1, n_epochs * (n // b))

    @functools.partial(jax.jit, static_argnames=())
    def step(carry, kk):
        centers, counts = carry
        idx = jax.random.randint(kk, (b,), 0, n)
        batch = X[idx]
        labels, _ = min_cluster_and_distance(batch, centers, metric=metric)
        bsum = jax.ops.segment_sum(batch, labels, num_segments=k)
        bcnt = jax.ops.segment_sum(jnp.ones((b,), jnp.float32), labels, num_segments=k)
        new_counts = counts + bcnt
        # per-center learning rate = batch count / total count (sklearn's
        # MiniBatchKMeans update; equivalent to a running weighted mean)
        lr = jnp.where(new_counts > 0, bcnt / jnp.maximum(new_counts, 1.0), 0.0)
        bmean = bsum / jnp.maximum(bcnt[:, None], 1e-9)
        centers = jnp.where(
            (bcnt > 0)[:, None], centers + lr[:, None] * (bmean - centers), centers
        )
        return (centers, new_counts), None

    keys = jax.random.split(key, steps)
    (centers, _), _ = lax.scan(step, (centers, jnp.zeros((k,), jnp.float32)), keys)

    labels, dists = min_cluster_and_distance(X, centers, metric=metric)
    return KMeansOutput(
        centroids=centers,
        labels=labels,
        inertia=jnp.sum(dists),
        n_iter=jnp.int32(steps),
    )
