"""Single-linkage agglomerative clustering — analog of
``raft::cluster::single_linkage`` (``cluster/single_linkage.cuh``,
``cluster/detail/{connectivities,mst,agglomerative}.cuh``).

Pipeline (same as the reference): kNN-graph connectivities → MST (with
cross-component connection fix-up when the kNN graph is disconnected) →
dendrogram by merging MST edges in weight order → flat labels by cutting
the dendrogram at ``n_clusters``.

The MST runs on device (vectorized Borůvka, :mod:`raft_tpu.sparse.solver`);
the dendrogram build is an inherently sequential union-find over n-1 edges
and runs on host at build time (the reference does the same,
``agglomerative.cuh`` builds the dendrogram on host).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.errors import expects
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.sparse.neighbors import cross_component_nn, knn_graph
from raft_tpu.sparse.solver import mst
from raft_tpu.sparse.types import COO


@dataclasses.dataclass
class SingleLinkageOutput:
    """``linkage_output`` analog (``cluster/single_linkage_types.hpp``)."""

    labels: np.ndarray  # [n] flat cluster labels
    children: np.ndarray  # [n-1, 2] merged node ids (scipy linkage style)
    deltas: np.ndarray  # [n-1] merge distances
    sizes: np.ndarray  # [n-1] merged cluster sizes
    n_clusters: int


class _UnionFind:
    def __init__(self, n):
        self.parent = np.arange(n)

    def find(self, x):
        root = x
        while self.parent[root] != root:
            root = self.parent[root]
        while self.parent[x] != root:
            self.parent[x], x = root, self.parent[x]
        return root

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return False
        self.parent[rb] = ra
        return True


def _components(n, src, dst):
    uf = _UnionFind(n)
    for a, b in zip(src, dst):
        uf.union(int(a), int(b))
    roots = np.array([uf.find(i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels, len(np.unique(roots))


def single_linkage(
    X,
    n_clusters: int = 2,
    c: int = 15,
    metric=DistanceType.L2SqrtExpanded,
) -> SingleLinkageOutput:
    """Fit single-linkage clustering (``single_linkage.cuh:60``); ``c``
    controls kNN-graph connectivity (k = min(c, n-1), the reference's
    ``c`` knob)."""
    metric = resolve_metric(metric)
    X = jnp.asarray(X)
    n = X.shape[0]
    expects(1 <= n_clusters <= n, "n_clusters out of range")
    k = min(max(c, 2), n - 1)

    g = knn_graph(X, k, metric=metric)
    res = mst(g)
    src, dst, w = res.src, res.dst, res.weights

    # connect components until spanning (connect_components +
    # cross_component_nn fix-up, detail/connectivities.cuh)
    for _ in range(64):
        labels, n_comp = _components(n, src, dst)
        if n_comp == 1:
            break
        cs, cd, cw = cross_component_nn(X, labels, n_comp, metric=metric)
        extra = COO(
            jnp.asarray(np.concatenate([src, cs]), jnp.int32),
            jnp.asarray(np.concatenate([dst, cd]), jnp.int32),
            jnp.asarray(np.concatenate([w, cw]), jnp.float32),
            (n, n),
        )
        res = mst(extra)
        src, dst, w = res.src, res.dst, res.weights

    expects(len(w) == n - 1, "failed to build spanning tree")

    # -- dendrogram: merge edges in weight order (agglomerative.cuh) --------
    order = np.argsort(w, kind="stable")
    src_o, dst_o, w_o = src[order], dst[order], w[order]
    uf = _UnionFind(2 * n - 1)
    cluster_of = np.arange(n)  # current dendrogram node of each root
    sizes_acc = np.ones(2 * n - 1, np.int64)
    children = np.empty((n - 1, 2), np.int64)
    deltas = np.empty(n - 1, np.float64)
    sizes = np.empty(n - 1, np.int64)
    nxt = n
    for i in range(n - 1):
        ra, rb = uf.find(int(src_o[i])), uf.find(int(dst_o[i]))
        ca, cb = cluster_of[ra], cluster_of[rb]
        children[i] = (ca, cb)
        deltas[i] = w_o[i]
        sizes[i] = sizes_acc[ca] + sizes_acc[cb]
        sizes_acc[nxt] = sizes[i]
        uf.union(ra, rb)
        cluster_of[uf.find(ra)] = nxt
        nxt += 1

    # -- flat labels: cut the last (n_clusters - 1) merges ------------------
    uf2 = _UnionFind(n)
    for i in range(n - 1 - (n_clusters - 1)):
        uf2.union(int(src_o[i]), int(dst_o[i]))
    roots = np.array([uf2.find(i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)

    return SingleLinkageOutput(
        labels=labels.astype(np.int32),
        children=children,
        deltas=deltas,
        sizes=sizes,
        n_clusters=n_clusters,
    )
