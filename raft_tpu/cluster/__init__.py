"""Clustering layer (L5 analog): k-means (Lloyd + ++), balanced hierarchical
k-means, single-linkage.

See ``SURVEY.md`` §2.4 (``/root/reference/cpp/include/raft/cluster``).
"""
from raft_tpu.cluster import kmeans, kmeans_balanced
from raft_tpu.cluster.kmeans import KMeansOutput, KMeansParams
from raft_tpu.cluster.kmeans_balanced import BalancedKMeansParams
from raft_tpu.cluster.single_linkage import SingleLinkageOutput, single_linkage

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "KMeansOutput",
    "KMeansParams",
    "BalancedKMeansParams",
    "SingleLinkageOutput",
    "single_linkage",
]
