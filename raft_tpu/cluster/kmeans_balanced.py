"""Hierarchical balanced k-means — analog of ``raft::cluster::kmeans_balanced``.

This is the trainer behind every IVF index: it must produce ``k`` centroids
whose cluster populations are *balanced* (no giant or empty inverted lists).
Reference: ``cluster/kmeans_balanced.cuh:77`` (``fit``),
``cluster/detail/kmeans_balanced.cuh:952`` (``build_hierarchical``),
``:839`` (``build_fine_clusters``), ``:615`` (``balancing_em_iters``),
``:98`` (``adjust_centers``).

TPU design: the same three phases as the reference —

1. **Mesocluster pass**: plain Lloyd with ``≈√k`` mesoclusters on a
   trainset subsample.
2. **Fine clusters**: per mesocluster, a *weighted* Lloyd run (all points
   participate with 0/1 weights — static shapes, no ragged partitions) with
   a proportional share of ``k``.
3. **Balancing EM**: full-data EM iterations where, after each assignment,
   under-populated clusters (count < avg/ratio) are re-seeded onto data
   points drawn from crowded clusters (``adjust_centers``), pulling list
   sizes toward the mean.

The mesocluster size bookkeeping runs on host (build-time only, matching the
reference's host-side loop at ``kmeans_balanced.cuh:988-1028``).
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.ops.fused_1nn import min_cluster_and_distance
from raft_tpu.random.rng import as_key

# Reference constant kAdjustCentersWeight (kmeans_balanced.cuh:78).
_ADJUST_WEIGHT = 7.0


@dataclasses.dataclass
class BalancedKMeansParams:
    """``kmeans_balanced_params`` analog (``cluster/kmeans_types.hpp:80``)."""

    n_clusters: int = 8
    n_iters: int = 20  # balancing EM iterations
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0
    max_train_points_per_cluster: int = 256  # trainset subsample budget
    balancing_threshold: float = 0.25  # re-seed clusters below avg*threshold


@functools.partial(jax.jit, static_argnames=("k", "n_iters", "metric"))
def _weighted_lloyd(X, weights, init_centers, *, k: int, metric, n_iters: int):
    """Lloyd restricted to ``weights``-selected points (0/1 weights keep all
    shapes static — the TPU alternative to the reference's gather into a
    per-mesocluster buffer at ``build_fine_clusters``).

    Jitted with a static ``k``: callers must pad every run to one shared
    ``k`` (see ``fit``) so the whole fine-cluster phase compiles ONCE —
    per-mesocluster shapes would otherwise retrace/recompile for each of
    the ~√k mesoclusters (~10 min of compile at 1M-scale builds).

    The E step is the Flash-KMeans cached/blocked assignment (bit-identical
    to ``min_cluster_and_distance``) with the sample norms hoisted out of
    the iteration loop."""
    from raft_tpu.cluster.kmeans import flash_min_cluster_and_distance, flash_norm_cache

    cache = flash_norm_cache(X, metric)

    def body(_, centers):
        labels, _ = flash_min_cluster_and_distance(X, centers, metric=metric, cache=cache)
        w = weights
        sums = jax.ops.segment_sum(X * w[:, None], labels, num_segments=k)
        counts = jax.ops.segment_sum(w, labels, num_segments=k)
        means = sums / jnp.maximum(counts[:, None], 1e-9)
        return jnp.where(counts[:, None] > 0, means, centers)

    return lax.fori_loop(0, n_iters, body, init_centers)


def _adjust_centers(key, X, centers, labels, counts, threshold: float):
    """Re-seed under-populated clusters onto random data points, biased
    toward points in crowded clusters (``adjust_centers``,
    ``kmeans_balanced.cuh:98-180``)."""
    k = centers.shape[0]
    n = X.shape[0]
    avg = n / k
    small = counts < (avg * threshold)
    # One candidate point per cluster, drawn with probability proportional to
    # the population of the cluster the point currently belongs to (the
    # reference's scan accepts points from crowded clusters).
    logits = jnp.log(jnp.maximum(counts[labels], 1e-9))
    idx = jax.random.categorical(key, logits, shape=(k,))
    candidates = X[idx]
    # Average-weighted blend (W = 7, kAdjustCentersWeight): the old center
    # keeps most of its position, nudged toward the candidate point.
    w = _ADJUST_WEIGHT
    blended = (centers * w + candidates) / (w + 1.0)
    return jnp.where(small[:, None], blended, centers), small.sum()


def _em_iters(key, X, centers, k: int, metric, n_iters: int, threshold: float):
    """Balancing EM (``balancing_em_iters``, ``kmeans_balanced.cuh:615``):
    assignment + mean update + center adjustment, fully on-device. The
    assignment is the Flash-KMeans cached/blocked E step (bit-identical to
    ``min_cluster_and_distance``) with the full-dataset norms computed once
    for all ``n_iters`` EM passes — this is the build-time hot loop of
    every IVF coarse training run."""
    from raft_tpu.cluster.kmeans import flash_min_cluster_and_distance, flash_norm_cache

    cache = flash_norm_cache(X, metric)

    def assign(c):
        return flash_min_cluster_and_distance(X, c, metric=metric, cache=cache)

    def body(i, carry):
        centers, kk = carry
        kk, kadj = jax.random.split(kk)
        labels, _ = assign(centers)
        sums = jax.ops.segment_sum(X, labels, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((X.shape[0],), jnp.float32), labels, num_segments=k)
        means = sums / jnp.maximum(counts[:, None], 1.0)
        centers = jnp.where(counts[:, None] > 0, means, centers)
        centers, _ = _adjust_centers(kadj, X, centers, labels, counts, threshold)
        return centers, kk

    centers, _ = lax.fori_loop(0, n_iters, body, (centers, key))
    # Final pure-mean pass (no adjustment) so returned centers are the means
    # of their final assignments.
    labels, _ = assign(centers)
    sums = jax.ops.segment_sum(X, labels, num_segments=k)
    counts = jax.ops.segment_sum(jnp.ones((X.shape[0],), jnp.float32), labels, num_segments=k)
    means = sums / jnp.maximum(counts[:, None], 1.0)
    return jnp.where(counts[:, None] > 0, means, centers)


def fit(
    X,
    params: Optional[BalancedKMeansParams] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> jax.Array:
    """Train balanced cluster centers; returns ``centroids [k, d] f32``.

    Mirrors ``kmeans_balanced::fit`` → ``build_hierarchical``
    (``kmeans_balanced.cuh:952``).
    """
    res = ensure_resources(res)
    if params is None:
        params = BalancedKMeansParams(**kwargs)
    metric = resolve_metric(params.metric)
    X = jnp.asarray(X, jnp.float32)
    expects(X.ndim == 2, "X must be 2-D")
    n, d = X.shape
    k = params.n_clusters
    expects(0 < k <= n, "n_clusters=%d out of range for n=%d", k, n)

    key = as_key(params.seed)
    k_sub, k_meso, k_fine, k_em = jax.random.split(key, 4)

    # -- trainset subsample (build_hierarchical's trainset fraction) --------
    max_train = min(n, k * params.max_train_points_per_cluster)
    if max_train < n:
        sub_idx = jax.random.permutation(k_sub, n)[:max_train]
        Xt = X[sub_idx]
    else:
        Xt = X
    nt = Xt.shape[0]

    # -- phase 1: mesoclusters ---------------------------------------------
    n_meso = int(min(max(1, round(math.sqrt(k))), k))
    if n_meso <= 1 or k <= 8:
        # Small k: single-level balanced EM with k-means++ seeding (random
        # seeding merges natural clusters too often at tiny k).
        from raft_tpu.cluster.kmeans import kmeans_plus_plus

        init = kmeans_plus_plus(k_meso, Xt, k)
        centers = _em_iters(k_em, X, init, k, metric, params.n_iters, params.balancing_threshold)
        return centers

    from raft_tpu.cluster.kmeans import KMeansParams, fit as kmeans_fit

    meso = kmeans_fit(
        Xt,
        KMeansParams(n_clusters=n_meso, max_iter=20, metric=params.metric, seed=params.seed, init="random"),
    )
    meso_labels, _ = min_cluster_and_distance(Xt, meso.centroids, metric=metric)

    # -- phase 2: proportional fine clusters (host-side allocation) ---------
    counts = np.asarray(jax.ops.segment_sum(jnp.ones((nt,), jnp.float32), meso_labels, num_segments=n_meso))
    # Allocate k across mesoclusters proportionally to population
    # (build_fine_clusters' mesocluster_size_max bookkeeping).
    raw = counts / max(counts.sum(), 1.0) * k
    alloc = np.maximum(np.floor(raw).astype(int), 1)
    while alloc.sum() > k:
        alloc[np.argmax(alloc)] -= 1
    while alloc.sum() < k:
        alloc[np.argmax(raw - alloc)] += 1

    # For L2 metrics, all mesoclusters train at ONE padded k (k_pad = max
    # allocation) so the jitted weighted-Lloyd compiles once; padding rows
    # are parked at a far sentinel no point ever assigns to, so the kept
    # centers converge exactly as an alloc[m]-sized run would — without
    # per-mesocluster recompiles (which cost ~10 min at 1M-scale builds).
    # No such sentinel exists for InnerProduct/Cosine assignment, so those
    # metrics keep the per-mesocluster shapes (rare path; IVF builds train
    # with L2).
    l2_family = metric in (
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.L2Unexpanded,
        DistanceType.L2SqrtUnexpanded,
    )
    k_pad = int(alloc.max())
    fine_centers = []
    w_all = jax.nn.one_hot(meso_labels, n_meso, dtype=jnp.float32)  # [nt, n_meso]
    for m in range(n_meso):
        km = k_pad if l2_family else int(alloc[m])
        kf, k_fine = jax.random.split(k_fine)
        weights = w_all[:, m]
        # Seed from points in this mesocluster: weighted sample via gumbel.
        g = jax.random.gumbel(kf, (nt,))
        seed_idx = lax.top_k(jnp.log(jnp.maximum(weights, 1e-30)) + g, km)[1]
        init = Xt[seed_idx]
        if l2_family:
            live = (jnp.arange(km) < int(alloc[m]))[:, None]
            init = jnp.where(live, init, jnp.float32(1e30))
        out = _weighted_lloyd(Xt, weights, init, k=km, metric=metric, n_iters=8)
        fine_centers.append(out[: int(alloc[m])])
    centers = jnp.concatenate(fine_centers, axis=0)

    # -- phase 3: balancing EM over the full dataset ------------------------
    centers = _em_iters(k_em, X, centers, k, metric, params.n_iters, params.balancing_threshold)
    return centers


def predict(X, centroids, metric=DistanceType.L2Expanded) -> Tuple[jax.Array, jax.Array]:
    """Nearest-centroid assignment (``kmeans_balanced::predict``)."""
    return min_cluster_and_distance(jnp.asarray(X, jnp.float32), centroids, metric=metric)


def fit_predict(X, params: Optional[BalancedKMeansParams] = None, **kwargs):
    centers = fit(X, params, **kwargs)
    metric = params.metric if params is not None else kwargs.get("metric", DistanceType.L2Expanded)
    labels, _ = predict(X, centers, metric=metric)
    return centers, labels
