"""Cost-model query planner: one dispatcher over the vmem / HBM /
wire / traffic models.

Every ``"auto"`` dispatch decision in raft_tpu — IVF search engine,
CAGRA beam engine, cross-shard merge engine, distributed-build comm
mode, mutable delta engine, PQ code family, sparse pairwise engine,
and the serving engine's per-registration plan — resolves here instead
of through scattered local heuristics. Each resolver enumerates the
eligible candidates, prices them from the repo's existing cost models
(:mod:`raft_tpu.plan.cost`), and returns a typed, explainable
:class:`Plan`.

Gate: set ``RAFT_TPU_PLAN=0`` (or ``false``/``off``) to disable the
planner — every call site then runs its original inline heuristic,
bit-identically. With the gate on, the calibrated cost constants make
the planner reproduce the legacy choices across the legacy decision
envelope (pinned by ``tests/test_plan.py``), so results stay
bit-identical there too.
"""
from __future__ import annotations

import os

from raft_tpu.plan.cost import CostTerm
from raft_tpu.plan.planner import (
    Candidate,
    Plan,
    plan_cagra_mode,
    plan_comm_mode,
    plan_delta_mode,
    plan_merge_mode,
    plan_pq_kind,
    plan_search_mode,
    plan_sparse_mode,
)
from raft_tpu.plan.registration import (
    GROWTH_REPLAN_FACTOR,
    TRAFFIC_MIN_SAMPLES,
    WARM_BUCKETS,
    RegistrationPlan,
    TrafficSnapshot,
    needs_replan,
    plan_registration,
    traffic_from_counts,
)

_OFF = ("0", "false", "off", "no")


def is_enabled() -> bool:
    """Planner gate: on by default; ``RAFT_TPU_PLAN=0`` restores every
    call site's original inline heuristic."""
    return os.environ.get("RAFT_TPU_PLAN", "1").strip().lower() not in _OFF


__all__ = [
    "Candidate",
    "CostTerm",
    "GROWTH_REPLAN_FACTOR",
    "Plan",
    "RegistrationPlan",
    "TRAFFIC_MIN_SAMPLES",
    "TrafficSnapshot",
    "WARM_BUCKETS",
    "is_enabled",
    "needs_replan",
    "plan_cagra_mode",
    "plan_comm_mode",
    "plan_delta_mode",
    "plan_merge_mode",
    "plan_pq_kind",
    "plan_registration",
    "plan_search_mode",
    "plan_sparse_mode",
    "traffic_from_counts",
]
