"""Candidate enumeration + costed selection for every dispatch decision.

Each ``plan_*`` resolver enumerates the legal candidate configurations
for one decision, prices each from :mod:`raft_tpu.plan.cost`, and
returns a :class:`Plan` — a typed, explainable record of the choice
with the per-term breakdown of every candidate (including the ones that
lost, and the ones that were ineligible and why). The winning ``choice``
is what the call site dispatches on; the rest is the audit trail
``plan.explain`` dumps into the obs report.

Selection is deterministic: candidates are priced in a fixed
enumeration order and the first strictly-cheapest eligible candidate
wins, so a cost tie resolves to the earlier (more conservative)
engine — the same discipline the wire model's ring/gather parity uses.

Parity contract: with the gate off (``RAFT_TPU_PLAN=0``) every call
site runs its original inline heuristic; with it on, the calibrated
crossovers in :mod:`raft_tpu.plan.cost` make each resolver select the
same configuration the heuristic did across the legacy decision
envelope (swept in ``tests/test_plan.py``), and an identical resolved
configuration drives byte-identical downstream code — so gates-off
results are bit-identical either way. Where the cost models see farther
than the old one-liners (e.g. a CA exchange whose row cap cannot
undercut the full exchange), the planner deviates *toward the models*;
those deviations are enumerated in ``docs/planner.md``.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

from raft_tpu import obs
from raft_tpu.plan import cost as _cost
from raft_tpu.plan.cost import CostTerm


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One enumerated configuration for a decision, with its price."""

    name: str
    terms: Tuple[CostTerm, ...] = ()
    eligible: bool = True
    reason: str = ""  # why ineligible (shown in explain)

    @property
    def cost(self) -> float:
        if not self.eligible:
            return math.inf
        return sum(t.value for t in self.terms)

    def render(self) -> str:
        if not self.eligible:
            return f"x {self.name:<12} ineligible: {self.reason}"
        breakdown = " + ".join(t.render() for t in self.terms)
        return f"- {self.name:<12} {self.cost:10.2f} cu  [{breakdown}]"


@dataclasses.dataclass(frozen=True)
class Plan:
    """A resolved dispatch decision: the choice, every candidate's cost
    breakdown, and the inputs the costing read."""

    decision: str
    choice: str
    candidates: Tuple[Candidate, ...]
    inputs: Tuple[Tuple[str, object], ...] = ()

    @property
    def cost(self) -> float:
        for c in self.candidates:
            if c.name == self.choice:
                return c.cost
        return math.inf

    def candidate(self, name: str) -> Optional[Candidate]:
        for c in self.candidates:
            if c.name == name:
                return c
        return None

    def explain(self) -> str:
        lines = [f"plan {self.decision}: {self.choice}  ({self.cost:.2f} cu)"]
        if self.inputs:
            lines.append("  inputs: " + " ".join(f"{k}={v}" for k, v in self.inputs))
        for c in sorted(self.candidates, key=lambda c: c.cost):
            lines.append("  " + c.render())
        return "\n".join(lines)


def _decide(decision: str, candidates, inputs) -> Plan:
    """First strictly-cheapest eligible candidate wins (stable ties)."""
    cands = tuple(candidates)
    best = None
    for c in cands:
        if c.eligible and (best is None or c.cost < best.cost):
            best = c
    if best is None:  # caller enumerated no eligible engine — a bug
        raise ValueError(f"plan {decision}: no eligible candidate")
    if obs.is_enabled():
        obs.inc("plan.decisions", decision=decision, choice=best.name)
    return Plan(decision=decision, choice=best.name, candidates=cands,
                inputs=tuple(inputs))


# ---------------------------------------------------------------------------
# per-decision resolvers
# ---------------------------------------------------------------------------


def plan_search_mode(algo: str, nq: int, *, on_tpu: bool, fused_ok: bool,
                     wants_f32_lut: bool = False) -> Plan:
    """IVF engine pick (``probe`` | ``scan`` | ``fused``) for one batch
    of ``nq`` queries. ``fused_ok`` is the call site's kernel-eligibility
    verdict (metric/codebook support and the vmem_model feasibility
    check); ``wants_f32_lut`` is the explicit-precision demand the bf16
    fused LUT cannot honor."""
    reasons = []
    if not on_tpu:
        reasons.append("backend is not tpu")
    if not fused_ok:
        reasons.append("kernel infeasible (metric/codebook/vmem window)")
    if wants_f32_lut:
        reasons.append("explicit f32 LUT demand (bf16 kernel LUT)")
    return _decide(
        f"{algo}.search_mode",
        [
            Candidate("probe", _cost.search_mode_terms("probe", nq)),
            Candidate("scan", _cost.search_mode_terms("scan", nq)),
            Candidate("fused", _cost.search_mode_terms("fused", nq),
                      eligible=not reasons, reason="; ".join(reasons)),
        ],
        [("nq", nq), ("on_tpu", on_tpu), ("fused_ok", fused_ok)],
    )


def plan_cagra_mode(nq: int, *, on_tpu: bool, fused_ok: bool) -> Plan:
    """CAGRA beam engine pick (``xla`` | ``fused``) for ``nq`` queries."""
    reasons = []
    if not on_tpu:
        reasons.append("backend is not tpu")
    if not fused_ok:
        reasons.append("needs raw dataset, init_sample>0, dedup='post', "
                       "no prefilter, graph_degree<=dim")
    return _decide(
        "cagra.search_mode",
        [
            Candidate("xla", _cost.cagra_mode_terms("xla", nq)),
            Candidate("fused", _cost.cagra_mode_terms("fused", nq),
                      eligible=not reasons, reason="; ".join(reasons)),
        ],
        [("nq", nq), ("on_tpu", on_tpu), ("fused_ok", fused_ok)],
    )


def plan_merge_mode(n_shards: int, k: Optional[int] = None,
                    tile_width: Optional[int] = None) -> Plan:
    """Cross-shard merge engine pick (``gather`` | ``ring`` |
    ``fused_ring``). ``tile_width`` is the per-shard candidate width
    entering the merge (defaults to ``k`` — the classic call sites,
    where the scan has already folded to k)."""
    k = int(k) if k else 10  # nominal: the winner is k-independent
    width = int(tile_width) if tile_width else k
    single = n_shards <= 1
    return _decide(
        "merge_mode",
        [
            Candidate("gather", _cost.merge_mode_terms("gather", n_shards, k, width)),
            Candidate("ring", _cost.merge_mode_terms("ring", n_shards, k, width),
                      eligible=not single, reason="single shard: nothing to exchange"),
            Candidate("fused_ring",
                      _cost.merge_mode_terms("fused_ring", n_shards, k, width),
                      eligible=not single, reason="single shard: nothing to exchange"),
        ],
        [("n_shards", n_shards), ("k", k), ("tile_width", width)],
    )


def plan_comm_mode(n_rows: int, d: int, n_shards: int, ca_cap=None) -> Plan:
    """Distributed-build accumulator exchange pick (``full`` | ``ca``)
    over ``[n_rows, d+1]`` f32 accumulator rows per iteration."""
    return _decide(
        "comm_mode",
        [
            Candidate("full", _cost.comm_mode_terms("full", n_rows, d, n_shards)),
            Candidate("ca", _cost.comm_mode_terms("ca", n_rows, d, n_shards,
                                                  ca_cap=ca_cap)),
        ],
        [("n_rows", n_rows), ("d", d), ("n_shards", n_shards)],
    )


def plan_delta_mode(*, eligible: bool, on_tpu: bool) -> Plan:
    """Mutable delta-scan engine pick (``exact`` | ``fused``).
    ``eligible`` is ``segments._delta_fused_eligible``'s verdict (metric
    window, banked row cap, k width)."""
    reasons = []
    if not eligible:
        reasons.append("metric/cap/k outside the lossless banked window")
    if not on_tpu:
        reasons.append("backend is not tpu")
    return _decide(
        "delta_mode",
        [
            Candidate("exact", _cost.delta_mode_terms("exact")),
            Candidate("fused", _cost.delta_mode_terms("fused"),
                      eligible=not reasons, reason="; ".join(reasons)),
        ],
        [("eligible", eligible), ("on_tpu", on_tpu)],
    )


def plan_pq_kind(pq_bits: int, per_subspace: bool, pq_dim: int = 16) -> Plan:
    """PQ code-family pick (``rabitq`` | ``nibble`` | ``kmeans``) at
    build time. Representability is eligibility; among representable
    families the decode-throughput terms decide."""
    pq_dim = max(1, int(pq_dim))
    return _decide(
        "pq_kind",
        [
            Candidate("rabitq", _cost.pq_kind_terms("rabitq", pq_dim, 1),
                      eligible=pq_bits == 1,
                      reason="1 bit/dim only (pq_bits != 1)"),
            Candidate("nibble", _cost.pq_kind_terms("nibble", pq_dim, pq_bits),
                      eligible=pq_bits == 8 and per_subspace,
                      reason="needs pq_bits=8 and per_subspace codebooks"),
            # kmeans is the fallback family: it stays eligible for
            # out-of-range pq_bits so the call site's own validation
            # raises the canonical error, not the planner
            Candidate("kmeans", _cost.pq_kind_terms("kmeans", pq_dim, pq_bits),
                      eligible=pq_bits != 1,
                      reason="1 bit/dim is rabitq's encoding"),
        ],
        [("pq_bits", pq_bits), ("per_subspace", per_subspace)],
    )


def plan_sparse_mode(n_cols: int, *, native_ok: bool) -> Plan:
    """Sparse pairwise engine pick (``densify`` | ``native``) at feature
    width ``n_cols``."""
    return _decide(
        "sparse_mode",
        [
            Candidate("densify", _cost.sparse_mode_terms("densify", n_cols)),
            Candidate("native", _cost.sparse_mode_terms("native", n_cols),
                      eligible=native_ok,
                      reason="metric has no sort-merge gram path"),
        ],
        [("n_cols", n_cols), ("native_ok", native_ok)],
    )
