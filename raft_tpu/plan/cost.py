"""Cost terms for the query planner — one price list over the repo's
calibrated resource models.

Every candidate configuration the planner enumerates is priced as a sum
of :class:`CostTerm` entries in **cost units** (``cu``): a relative
device-time scale whose per-mode coefficients are calibrated so each
decision's cost crossover lands exactly where the measured bench Pareto
frontier (and the per-call-site heuristics it validated) put it — the
batch-128 probe/scan crossover on the kernel engines, the
ring-vs-gather merge crossover from the wire model, the CA-vs-full
build crossover from the per-iteration byte models. Wire terms convert
bytes to cu at :data:`CU_PER_WIRE_BYTE` so fabric traffic and compute
land on one axis.

The sources feeding these terms are the four existing models:

* :mod:`raft_tpu.ops.pallas.vmem_model` — kernel VMEM residency
  (consumed as *eligibility*: a fused candidate whose decode window
  cannot fit VMEM is dropped, not priced; the call site passes the
  verdict in as ``fused_ok``, exactly the feasibility bit the legacy
  dispatch consulted);
* :mod:`raft_tpu.ops.pallas.hbm_model` — three-level placement
  residencies (the registration plan's tier terms);
* :mod:`raft_tpu.parallel.wire_model` — per-verb collective bytes, the
  ring/gather merge bytes, the distributed-build per-iteration bytes;
* live traffic stats — batcher EWMA service time, the engine's
  per-bucket batch-size counts, corpus shape (the registration plan's
  re-planning inputs).

Calibration contract: ``tests/test_plan.py`` sweeps every decision
against the legacy heuristics across the operating envelope; a
coefficient change that moves a crossover fails those sweeps, so the
numbers below are pinned the same way the wire-model byte values are.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

from raft_tpu.parallel.wire_model import (
    AG_ENTRY_BYTES,
    RS_ENTRY_BYTES,
    codebook_wire_bytes_per_iter,
    lloyd_wire_bytes_per_iter,
    wire_bytes_per_query,
)


@dataclasses.dataclass(frozen=True)
class CostTerm:
    """One additive component of a candidate's cost."""

    name: str
    value: float  # cu
    note: str = ""

    def render(self) -> str:
        return f"{self.name} {self.value:.2f}" + (f" ({self.note})" if self.note else "")


#: cu per fabric byte — puts wire terms on the compute axis (one cu
#: ~ one merged candidate entry; an 8-byte (val, id) entry costs 1 cu
#: to ship, matching the merge cost of consuming it).
CU_PER_WIRE_BYTE = 1.0 / 8.0

# -- search-mode engine coefficients (ivf_flat / ivf_pq, incl. rabitq) --
#
# probe = per-query gather dispatch (the latency path: per-probe
# dynamic-slice gathers defeat batching); scan = one dense masked scan
# launch amortized over the batch; fused = the Pallas probed-list DMA
# kernel — cheaper per query than scan (only probed lists move), dearer
# to launch. Calibrated to the measured batch-128 crossover: probe wins
# through nq=127, scan/fused from nq=128, fused beats scan whenever the
# kernel is eligible (and loses to probe below the crossover, keeping
# the latency path on small batches).
PROBE_CU_PER_QUERY = 2.0
SCAN_LAUNCH_CU = 127.5
SCAN_CU_PER_QUERY = 1.0
FUSED_LAUNCH_CU = 159.0
FUSED_CU_PER_QUERY = 0.75

# -- cagra engine coefficients --
#
# The beam state is VMEM-resident in the fused kernel and every parent
# expansion is one DMA'd packed-neighbor row; the XLA loop re-gathers
# from HBM each iteration. Fused wins at every batch size whenever
# eligible (the legacy rule), so the coefficients only need ordering.
CAGRA_XLA_LAUNCH_CU = 64.0
CAGRA_XLA_CU_PER_QUERY = 1.0
CAGRA_FUSED_LAUNCH_CU = 32.0
CAGRA_FUSED_CU_PER_QUERY = 0.5

# -- merge-engine coefficients --
#
# gather materialises the full n·k candidate set on every shard and
# k-way merges it there (1 cu per merged entry); the rings fold k-wide
# (1 cu per folded entry per hop window) and ship fewer bytes for
# n > 2. scan-fold fusion saves the [nq, width] candidate tile's HBM
# round-trip when the scan emits wider-than-k tiles; at width == k it
# is the plain ring plus kernel-dispatch overhead.
MERGE_CU_PER_ENTRY = 1.0
RING_FOLD_CU_PER_ENTRY = 1.0
FUSED_RING_SETUP_CU = 0.5
HBM_ROUNDTRIP_CU_PER_ENTRY = 1.0

# -- delta-scan coefficients (mutable delta path) --
#
# exact = a separate XLA delta scan + merge against the main segment's
# winners (two launches and a candidate round-trip); fused = the banked
# probed-list kernel folding the delta in one pass. Within the
# eligibility window fused is bit-identical and strictly cheaper.
DELTA_EXACT_CU = 3.0
DELTA_FUSED_CU = 1.0

# -- CA-exchange selection overhead (distributed builds) --
#
# the changed-row top-k select + accumulator patch each iteration;
# breaks the tie toward the reference full exchange when the byte
# models price equal (single shard) and keeps CA from winning on
# noise when the cap cannot undercut the full exchange.
CA_SELECT_CU = 1.0

# -- sparse pairwise coefficients --
#
# densify streams [block, n_cols] dense tiles (cost tracks the feature
# width); native computes the sort-merge gram without densifying —
# a fixed overhead calibrated at the 2^18-column densification-sanity
# bound the legacy dispatch used.
DENSIFY_CU_PER_COL = 1.0
NATIVE_GRAM_CU = float(1 << 18)


def search_mode_terms(mode: str, nq: int) -> Tuple[CostTerm, ...]:
    """Per-batch cost of one IVF search engine at batch size ``nq``."""
    if mode == "probe":
        return (CostTerm("gather", PROBE_CU_PER_QUERY * nq,
                         f"{PROBE_CU_PER_QUERY:g} cu/query per-probe gather"),)
    if mode == "scan":
        return (
            CostTerm("launch", SCAN_LAUNCH_CU, "dense masked scan launch"),
            CostTerm("stream", SCAN_CU_PER_QUERY * nq,
                     f"{SCAN_CU_PER_QUERY:g} cu/query list streaming"),
        )
    # fused
    return (
        CostTerm("launch", FUSED_LAUNCH_CU, "Pallas kernel dispatch"),
        CostTerm("stream", FUSED_CU_PER_QUERY * nq,
                 f"{FUSED_CU_PER_QUERY:g} cu/query probed-list DMA"),
    )


def cagra_mode_terms(mode: str, nq: int) -> Tuple[CostTerm, ...]:
    """Per-batch cost of one CAGRA beam engine at batch size ``nq``."""
    if mode == "xla":
        return (
            CostTerm("launch", CAGRA_XLA_LAUNCH_CU, "per-iteration gather loop"),
            CostTerm("beam", CAGRA_XLA_CU_PER_QUERY * nq, "HBM re-gather per hop"),
        )
    return (
        CostTerm("launch", CAGRA_FUSED_LAUNCH_CU, "Pallas kernel dispatch"),
        CostTerm("beam", CAGRA_FUSED_CU_PER_QUERY * nq, "VMEM-resident beam state"),
    )


def merge_mode_terms(mode: str, n_shards: int, k: int,
                     tile_width: int) -> Tuple[CostTerm, ...]:
    """Per-query cost of one cross-shard merge engine.

    ``tile_width`` is the per-shard candidate width entering the merge
    (``k`` at the classic call sites; ``k·refine_ratio`` when the scan's
    tile feeds the fused ring directly)."""
    wire = wire_bytes_per_query(n_shards, k, "gather" if mode == "gather" else "ring")
    terms = [CostTerm("wire", wire * CU_PER_WIRE_BYTE,
                      f"{wire:.0f} B/query over {n_shards} shards")]
    if mode == "gather":
        terms.append(CostTerm("merge", MERGE_CU_PER_ENTRY * n_shards * k,
                              f"k-way merge over n·k={n_shards * k} on every shard"))
        if tile_width > k:
            terms.append(CostTerm("prefold", RING_FOLD_CU_PER_ENTRY * tile_width,
                                  "fold scan tile to k before the exchange"))
            terms.append(CostTerm("hbm_roundtrip",
                                  HBM_ROUNDTRIP_CU_PER_ENTRY * (tile_width - k),
                                  "[nq, width] tile through HBM"))
        return tuple(terms)
    if mode == "ring":
        terms.append(CostTerm("fold", RING_FOLD_CU_PER_ENTRY * k, "k-wide hop fold"))
        if tile_width > k:
            terms.append(CostTerm("prefold", RING_FOLD_CU_PER_ENTRY * tile_width,
                                  "fold scan tile to k before the ring"))
            terms.append(CostTerm("hbm_roundtrip",
                                  HBM_ROUNDTRIP_CU_PER_ENTRY * (tile_width - k),
                                  "[nq, width] tile through HBM"))
        return tuple(terms)
    # fused_ring: the scan's tile folds inside the ring engine — the
    # tile never round-trips HBM, the ring's hop fold consumes it raw
    terms.append(CostTerm("fold", RING_FOLD_CU_PER_ENTRY * tile_width,
                          "in-engine scan-tile fold"))
    terms.append(CostTerm("setup", FUSED_RING_SETUP_CU, "scan-to-ring kernel handoff"))
    return tuple(terms)


def comm_mode_terms(mode: str, n_rows: int, d: int, n_shards: int,
                    ca_cap=None) -> Tuple[CostTerm, ...]:
    """Per-iteration cost of one distributed-build accumulator exchange
    over ``[n_rows, d+1]`` f32 accumulator rows."""
    wire = lloyd_wire_bytes_per_iter(n_rows, d, n_shards, comm_mode=mode,
                                     ca_cap=ca_cap)
    terms = [CostTerm("wire", wire * CU_PER_WIRE_BYTE,
                      f"{wire:.0f} B/iter over {n_shards} shards")]
    if mode == "ca":
        terms.append(CostTerm("select", CA_SELECT_CU,
                              "changed-row top-k select + patch"))
    return tuple(terms)


def delta_mode_terms(mode: str) -> Tuple[CostTerm, ...]:
    """Per-batch cost of one mutable delta-scan engine."""
    if mode == "exact":
        return (CostTerm("scan_merge", DELTA_EXACT_CU,
                         "XLA delta scan + main-segment merge"),)
    return (CostTerm("banked_scan", DELTA_FUSED_CU,
                     "one banked probed-list kernel pass"),)


def pq_kind_terms(kind: str, pq_dim: int, pq_bits: int) -> Tuple[CostTerm, ...]:
    """Per-row decode/footprint cost of one PQ code family."""
    code_bytes = pq_dim * pq_bits / 8.0
    if kind == "rabitq":
        return (CostTerm("codes", code_bytes, "1 sign bit per rotated dim"),
                CostTerm("decode", 0.25 * pq_dim, "popcount estimator"))
    if kind == "nibble":
        return (CostTerm("codes", code_bytes, "additive nibble books"),
                CostTerm("decode", 0.5 * pq_dim, "one multi-hot decode pass"))
    return (CostTerm("codes", code_bytes, "k-means codebooks"),
            CostTerm("decode", 1.0 * pq_dim, "per-subspace LUT gather"))


def sparse_mode_terms(mode: str, n_cols: int) -> Tuple[CostTerm, ...]:
    """Per-block cost of one sparse pairwise engine at feature width
    ``n_cols``."""
    if mode == "densify":
        return (CostTerm("densify", DENSIFY_CU_PER_COL * n_cols,
                         f"[block, {n_cols}] dense tiles"),)
    return (CostTerm("gram", NATIVE_GRAM_CU, "sort-merge gram, no densify"),)


__all__ = [
    "AG_ENTRY_BYTES",
    "RS_ENTRY_BYTES",
    "CU_PER_WIRE_BYTE",
    "CostTerm",
    "cagra_mode_terms",
    "codebook_wire_bytes_per_iter",
    "comm_mode_terms",
    "delta_mode_terms",
    "lloyd_wire_bytes_per_iter",
    "merge_mode_terms",
    "pq_kind_terms",
    "search_mode_terms",
    "sparse_mode_terms",
    "wire_bytes_per_query",
]
